"""Typed configuration for the HTM anomaly pipeline.

The reference (a NuPIC application — SURVEY.md L2/L3) configures models via
nested `modelParams` dicts copied from NAB's tuned parameter JSONs
(SURVEY.md §5 "Config / flag system"). We replace those with frozen
dataclasses plus two blessed presets:

- :func:`nab_preset` — NuPIC/NAB-scale model (2048 columns, 32 cells/col),
  used for detection-quality runs on NAB-format corpora (benchmark configs
  1-2 in BASELINE.md).
- :func:`cluster_preset` — a small-footprint model for massive stream counts
  (benchmark configs 3 and 5: 1k-100k concurrent streams on one chip), where
  per-stream HBM budget is the binding constraint (SURVEY.md §7 hard part 4).

All sizes are static so every kernel compiles to fixed shapes (XLA
requirement); segment/synapse pools are bounded capacity by design, mirroring
NuPIC's maxSegmentsPerCell / maxSynapsesPerSegment bounds.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any


# RDSE bucket indices are clamped to this magnitude on BOTH backends before
# integer conversion. The device kernel runs int32 (no x64 on TPU); without a
# shared clamp, a wild value (overflowed counter, sensor garbage) >= 2^31
# buckets from the offset would wrap on device but not on host, silently and
# permanently diverging the SDR stream. 2^30 is exactly representable in f32
# and leaves headroom for the +active_bits hash-key offsets.
RDSE_BUCKET_CLAMP = 1 << 30


@dataclass(frozen=True)
class RDSEConfig:
    """Random Distributed Scalar Encoder (SURVEY.md C1).

    Scalar -> sparse binary SDR. A value maps to bucket
    ``b = round((value - offset) / resolution)``; bucket ``b`` activates bits
    ``{hash(seed, b + k) % size : k in 0..active_bits-1}``. Adjacent buckets
    share ``active_bits - 1`` hash keys, so SDR overlap decays linearly with
    bucket distance — the defining RDSE property. Hash collisions within one
    bucket are tolerated (the SDR then has active_bits-1 on bits), the same
    deterministic-union approach used by the public htm.core RDSE; this keeps
    the encoder table-free and device-computable.

    ``offset`` is bound to the first value a stream sees (NuPIC behavior),
    stored in per-stream state.
    """

    size: int = 400
    active_bits: int = 21
    resolution: float = 0.9
    seed: int = 42


@dataclass(frozen=True)
class ScalarEncoderConfig:
    """Classic bucketed ScalarEncoder (SURVEY.md C2, NuPIC `scalar.py`):
    a fixed [min_val, max_val] range mapped onto ``size`` bits with a
    ``width``-bit contiguous run; bucket = round((v - min) * (size - width)
    / (max - min)), input clipped into range (NuPIC clipInput=True).

    Unlike the RDSE it needs the value range up front and wastes resolution
    outside it — the detector presets keep the RDSE; this exists for parity
    with the reference's encoder family and for fields with known ranges
    (e.g. percentages). Selected per model via ``ModelConfig.scalar``.
    """

    size: int = 400
    width: int = 21
    min_val: float = 0.0
    max_val: float = 100.0


#: Valid per-field encoder kinds of a composite multi-field encoder
#: ("Encoding Data for HTM Systems", PAPERS.md 1602.05925):
#:   rdse        — the RDSE over the field's raw value (the default family)
#:   delta       — RDSE over the FIRST DIFFERENCE of the value (NuPIC
#:                 DeltaEncoder semantics: rate-of-change is the signal;
#:                 the first sample, having no predecessor, encodes as
#:                 missing). Needs per-stream prev-value state (enc_prev).
#:   categorical — hash-bucketed enum: category id c activates bits
#:                 {hash(seed, c*w + k) % size : k < w}. DISJOINT key
#:                 ranges per category, so distinct categories share no
#:                 hash keys and their SDRs overlap only by chance — the
#:                 defining categorical property (no false similarity
#:                 between adjacent ids), vs the RDSE's deliberate
#:                 linear-decay overlap. Log-template ids (the drain-style
#:                 miner in rtap_tpu/ingest/templates.py) ride this kind.
FIELD_KINDS = ("rdse", "delta", "categorical")


@dataclass(frozen=True)
class FieldSpec:
    """One field of a :class:`CompositeEncoderConfig` (name + kind + its
    own encoder geometry). ``resolution`` applies to rdse/delta kinds;
    categorical buckets are the (rounded) ids themselves."""

    name: str
    kind: str = "rdse"
    size: int = 128
    active_bits: int = 11
    resolution: float = 0.5
    seed: int = 42

    def categorical_clamp(self) -> int:
        """Category-id magnitude bound: ids clamp here on BOTH backends so
        the device's int32 key arithmetic (c * active_bits + k) can never
        wrap where the host's int64 would not (same contract as
        RDSE_BUCKET_CLAMP)."""
        return RDSE_BUCKET_CLAMP // max(self.active_bits, 1)


@dataclass(frozen=True)
class CompositeEncoderConfig:
    """Composite multi-field encoder: fuse heterogeneous fields — e.g.
    {value, delta, event-class} (+ the DateConfig hour-of-day ring, which
    stays a ModelConfig-level field) — into ONE SDR per stream.

    Each field owns a disjoint bit range (the per-field layout table,
    ``ModelConfig.field_layout``), so SDR union semantics (PAPERS.md
    1503.07469) carry the joint code and the RDSE key-space attribution
    decode (service/attribution.py) can name which FIELD spiked. Wire
    records stay [n_fields] f32 rows; categorical fields carry the
    category id as a float (template ids from the log miner included).
    """

    fields: tuple[FieldSpec, ...] = ()

    def __post_init__(self) -> None:
        if not self.fields:
            raise ValueError("CompositeEncoderConfig needs >= 1 field")
        # dict/JSON round-trips hand tuples back as lists; normalize so
        # frozen-config hashing (the jit static key) stays stable
        object.__setattr__(self, "fields", tuple(
            f if isinstance(f, FieldSpec) else FieldSpec(**f)
            for f in self.fields))
        names = [f.name for f in self.fields]
        if len(set(names)) != len(names) or any(not n for n in names):
            raise ValueError(
                f"composite field names must be non-empty and unique; got "
                f"{names} (attribution reports fields BY NAME)")
        for f in self.fields:
            if f.kind not in FIELD_KINDS:
                raise ValueError(
                    f"field {f.name!r}: kind must be one of {FIELD_KINDS}; "
                    f"got {f.kind!r}")
            if not 0 < f.active_bits < f.size:
                raise ValueError(
                    f"field {f.name!r}: needs 0 < active_bits < size; got "
                    f"w={f.active_bits}, n={f.size}")
            if f.kind in ("rdse", "delta") and not f.resolution > 0:
                raise ValueError(
                    f"field {f.name!r}: resolution must be > 0; got "
                    f"{f.resolution}")

    @property
    def size(self) -> int:
        return sum(f.size for f in self.fields)

    @property
    def has_delta(self) -> bool:
        return any(f.kind == "delta" for f in self.fields)


@dataclass(frozen=True)
class DateConfig:
    """Date/time encoder (SURVEY.md C2): periodic time-of-day + weekend bits.

    ``time_of_day_width`` bits win a contiguous (wrapping) run on a periodic
    ring of ``time_of_day_size`` bits covering 24h. ``weekend_width`` bits are
    all-on during Sat/Sun, all-off otherwise. Width 0 disables a field.
    """

    time_of_day_width: int = 21
    time_of_day_size: int = 54  # ring size; NuPIC n = w * period/radius ~ 21*24/9.49
    weekend_width: int = 0

    @property
    def size(self) -> int:
        return (self.time_of_day_size if self.time_of_day_width else 0) + self.weekend_width


@dataclass(frozen=True)
class SPConfig:
    """Spatial Pooler (SURVEY.md C3) — global inhibition variant.

    Semantics follow the public NuPIC SpatialPooler (overlap = count of
    connected synapses on active inputs; boost; global top-k inhibition;
    Hebbian permanence learning), re-laid-out as dense per-column arrays:
    a fixed potential mask [columns, input_size] and a dense permanence
    matrix masked by it. Tie-breaks in the top-k are deterministic by lower
    column index (score = overlap * columns + (columns-1-c)), identical in
    the numpy oracle and the TPU kernel.
    """

    columns: int = 2048
    potential_pct: float = 0.8
    syn_perm_connected: float = 0.2
    syn_perm_active_inc: float = 0.003
    syn_perm_inactive_dec: float = 0.0005
    stimulus_threshold: int = 0
    num_active_columns: int = 40  # k winners (global inhibition)
    boost_strength: float = 0.0
    duty_cycle_period: int = 1000
    min_pct_overlap_duty_cycle: float = 0.001
    syn_perm_below_stimulus_inc: float = 0.01  # bump for starved columns
    seed: int = 1956
    # Permanence storage: 0 = f32 (reference semantics), 16/8 = fixed-point
    # quanta on 1/(2^bits - 1) with exact integer arithmetic on both backends
    # (models/perm.py). Quantization is the per-stream HBM lever (SURVEY.md
    # §7 hard part 4): SP perm is the second-largest state tensor.
    perm_bits: int = 0
    # Structurally sparse pool storage (ISSUE 18): True replaces the dense
    # `potential` bool [C, n_in] mask + `perm` [C, n_in] plane with a
    # member-index table `members` [C, P] (P potential inputs per column,
    # -1 = empty slot) + `perm` [C, P] over the members only. Overlap and
    # learning become gathers over the member table (ops/sp_tpu.py); bytes
    # and the per-tick sweep shrink from C*n_in to C*P. SDR theory says
    # sparsity, not pool width, carries capacity (PAPERS.md 1503.07469).
    # False (default) keeps the dense layout — every pre-existing config,
    # checkpoint, and golden is byte-identical.
    sparse_pool: bool = False
    # Members per column in the sparse layout: 0 derives
    # P = round(potential_pct * input_size) (the structural twin of the
    # dense mask's expected density); > 0 pins P explicitly — the
    # dense->sparse checkpoint migration needs an exact P that covers the
    # widest migrated column (models/migrate.py). Ignored when dense.
    pool_members: int = 0


@dataclass(frozen=True)
class TMConfig:
    """Temporal Memory (SURVEY.md C4/C5) — vanilla TM with bounded dense pools.

    NuPIC's pointer-graph `Connections` store becomes pre-allocated pools
    (SURVEY.md §7 design stance): per cell, ``max_segments_per_cell`` segment
    slots x ``max_synapses_per_segment`` synapse slots, each synapse a
    (presynaptic cell id, permanence) pair; id < 0 marks an empty slot.
    Segment allocation uses free slots first, then evicts the least recently
    used segment (NuPIC's eviction rule). Winner-cell and best-segment
    tie-breaks are deterministic by lowest index.
    """

    cells_per_column: int = 32
    activation_threshold: int = 13
    min_threshold: int = 10
    initial_permanence: float = 0.21
    connected_permanence: float = 0.5
    permanence_increment: float = 0.1
    permanence_decrement: float = 0.1
    predicted_segment_decrement: float = 0.001
    max_segments_per_cell: int = 16
    max_synapses_per_segment: int = 32
    new_synapse_count: int = 20
    seed: int = 1960
    # Static-shape capacities for the device kernel's column-compact learning
    # pass (SURVEY.md §7 hard part 1): at most `learn_cap` segments learn per
    # step (>= active columns; predicted columns can contribute several).
    # Overflow is counted in state["tm_overflow"]; tests assert it stays zero
    # at the configured sizes.
    learn_cap: int = 128
    # Permanence storage for the TM synapse pools — the single largest state
    # tensor (see SPConfig.perm_bits; models/perm.py). At 8 bits the coarse
    # quantum makes predicted_segment_decrement 1/255 ≈ 0.0039 (floored at one
    # quantum); the detection-quality impact per domain is measured in
    # eval/fault_eval, not assumed.
    perm_bits: int = 0
    # Max simultaneously-active columns per step (>= SPConfig.num_active_columns,
    # validated in ModelConfig). The device kernel's membership tests and its
    # learning workspace are column-compact: active cells can only live in
    # active columns, so comparing against <= col_cap column ids + a packed
    # K-bit per-column cell mask replaces comparing against a flat active-cell
    # id list (8-32x fewer VPU ops at preset sizes).
    col_cap: int = 40
    # Static capacity of the RTAP_TM_SWEEP=compact punish/death pass (ops/
    # tm_tpu.py): at most `punish_cap` matching segments in non-active columns
    # are punished per step; overflow is counted in state["tm_overflow"].
    # Dense-sweep mode (the round-3 semantics) ignores it.
    punish_cap: int = 256
    # Forward-index fanout capacity F (RTAP_TM_DENDRITE=forward, ops/
    # fwd_index.py): max synapse slots per presynaptic cell tracked by
    # fwd_slots [num_cells, F]. A cell exceeding F drops appends — counted in
    # state["fwd_of"] (a dropped entry corrupts dendrite counts, so tests
    # assert the counter stays zero). Memory when the index is enabled:
    # num_cells * F * 4 B (+1-2 B/synapse slot for fwd_pos). Size F to the
    # fanout TAIL: hot winner cells concentrate synapses (measured on the
    # cluster preset's diurnal feed: max fanout 231-382 after 12k ticks and
    # still rising — docs/FORWARD_INDEX_DESIGN.md round-4 measurement), so
    # production forward-mode runs need F >= ~512 at that workload. The
    # default stays small because the index is opt-in and tests own their F.
    fanout_cap: int = 64


@dataclass(frozen=True)
class ClassifierConfig:
    """SDR classifier (SURVEY.md C10) — decodes TM cell state to a predicted
    value distribution, the "prediction" half of the reference's name.

    Semantics follow the public NuPIC SDRClassifier (softmax regression from
    active-cell patterns to encoder buckets, one-step-ahead): at record t the
    pattern from t-1 is trained toward the bucket of the value at t
    (error = onehot - softmax, SGD with rate ``alpha``); inference applies
    the pattern at t to predict t+1. Per-bucket actual values are tracked
    with an EMA (``act_value_alpha``) and the predicted value is the actual
    value of the argmax bucket.

    TPU-native layout: weights are a dense [num_cells, buckets] matrix per
    stream; the pattern->logits matvec and the outer-product update both run
    on the MXU. Buckets are the RDSE bucket index shifted by ``buckets // 2``
    and clamped to [0, buckets) — offset binding centers the first value, and
    NAB-style resolutions span the value range in ~130 buckets.

    Memory note (state_nbytes includes it when enabled): ``cls_w`` is
    [num_cells, buckets] f32 per stream — +1.06 MB/stream on the cluster
    preset (2048 cells x 130, roughly DOUBLING its state) and +34 MB/stream
    on the NAB preset. That is why it is off by default and should stay off
    for massive-stream-count deployments unless predictions are required.
    """

    enabled: bool = False
    buckets: int = 130
    alpha: float = 0.01
    act_value_alpha: float = 0.3


@dataclass(frozen=True)
class LikelihoodConfig:
    """Anomaly likelihood post-process (SURVEY.md C8) — stays on host.

    Faithful to the public NuPIC `anomaly_likelihood.py`: keep a rolling
    window of raw scores, periodically fit a Gaussian to the *moving-averaged*
    scores, and report ``1 - Q((shortTermMean - mu)/sigma)``, log-scaled.

    ``mode="window"`` keeps the exact rolling window (quality runs);
    ``mode="streaming"`` replaces it with exponential moving moments so that
    100k streams do not need a [streams, window] buffer on host
    (SURVEY.md §7 hard part 5).
    """

    learning_period: int = 288
    estimation_samples: int = 100
    historic_window_size: int = 8640
    reestimation_period: int = 100
    averaging_window: int = 10
    mode: str = "window"  # "window" | "streaming"
    streaming_decay: float = 0.999  # EMA decay for streaming mode

    @property
    def probationary_period(self) -> int:
        return self.learning_period + self.estimation_samples

    def safe_inject_frac(self, length: int, margin: int = 100, cap: float = 0.6) -> float:
        """Earliest fault-injection point (fraction of a `length`-tick
        stream) that clears the probation plus a settling margin — a fault
        injected while the likelihood is pinned at 0.5 is undetectable by
        construction, and scoring it corrupts recall with a measurement
        artifact. Shared by the fault eval and the report script so the two
        can never drift. Raises when the stream is too short to evaluate."""
        frac = (self.probationary_period + margin) / length
        if frac > cap:
            raise ValueError(
                f"stream length {length} too short to evaluate: probation "
                f"{self.probationary_period} + margin {margin} is {frac:.0%} "
                f"of it (cap {cap:.0%}); lengthen the streams or shorten the "
                "likelihood learning period"
            )
        return frac


@dataclass(frozen=True)
class ModelConfig:
    """Bundle: one HTM anomaly model (per stream or per stream group)."""

    rdse: RDSEConfig = field(default_factory=RDSEConfig)
    date: DateConfig = field(default_factory=DateConfig)
    sp: SPConfig = field(default_factory=SPConfig)
    tm: TMConfig = field(default_factory=TMConfig)
    likelihood: LikelihoodConfig = field(default_factory=LikelihoodConfig)
    classifier: ClassifierConfig = field(default_factory=ClassifierConfig)
    n_fields: int = 1  # multivariate: number of scalar fields fused into one SDR
    # When set, value fields use the classic ScalarEncoder instead of the
    # RDSE (same layout position; date bits unchanged). None = RDSE default.
    scalar: ScalarEncoderConfig | None = None
    # Composite multi-field encoder (ISSUE 9): when set, each of the
    # n_fields wire fields encodes by ITS OWN FieldSpec (rdse / delta /
    # categorical, per-field sizes) instead of the uniform RDSE/scalar
    # family; date bits are unchanged. None = the uniform default — every
    # pre-existing config/checkpoint/artifact is byte-identical.
    composite: CompositeEncoderConfig | None = None
    # Learning cadence: learn on ticks where tm_iter % learn_every == 0 (or
    # tm_iter < learn_full_until — the maturity window learns every tick).
    # 1 = NuPIC-faithful continuous learning (default). The silicon A/B
    # (SCALING.md round-4) measured the learning pass as ~85% of the fused
    # step with inference-only at ~155k metrics/s/chip, so thinning mature
    # streams' learning to every k-th tick is the single-chip throughput
    # lever; its detection-quality cost is measured, not assumed
    # (eval/fault_eval.py --learn-every).
    learn_every: int = 1
    learn_full_until: int = 0
    # Burst shape of the thinned cadence: learn `learn_burst` CONSECUTIVE
    # ticks out of every `learn_every * learn_burst` (same 1/learn_every
    # average rate and device cost, same scalar clock). burst=1 is the
    # spread schedule (every k-th tick) — which breaks the temporal
    # adjacency TM sequence learning feeds on (synapses grow toward the
    # PREVIOUS tick's winner cells, so isolated learn ticks mostly learn
    # k-step-apart pairs). Bursts preserve adjacency inside each burst;
    # quality measured in eval/fault_eval.py --learn-burst.
    learn_burst: int = 1
    # Cadence phase offset: group i of a many-group deployment learns on
    # ticks where (it - learn_phase) % learn_every == 0. With every group
    # at phase 0, ALL groups learn on the same ticks — the per-tick device
    # compute spikes to the full-fleet learning cost on learn ticks and
    # idles on the rest, and at 100k streams the spike alone exceeds the
    # 1 s cadence. Staggering phases (registry stagger_learn) spreads the
    # fleet's learning load evenly across ticks; per-group semantics are
    # identical up to a <learn_every-tick shift of its schedule.
    learn_phase: int = 0

    def learns_on(self, it):
        """The cadence predicate, shared by the device schedule
        (ops/step.py:_tick, traced jnp scalar) and the host twin
        (HTMModel.run, python int) so the two can never diverge:
        learn when `it` (completed steps) is inside the full-rate maturity
        window or on the cadence (burst=1: every k-th tick shifted by
        learn_phase; burst=B: the first B ticks of every k*B-tick cycle,
        phased so a burst begins the tick the maturity window ends —
        absolute phasing would freeze learning for up to (k-1)*B ticks
        right as scoring starts — then shifted by learn_phase)."""
        if self.learn_burst == 1:
            # the original spread schedule (measured semantics; unchanged
            # by the burst/phase features at phase 0)
            return (it < self.learn_full_until) | (
                (it - self.learn_phase) % self.learn_every == 0)
        rel = it - self.learn_full_until - self.learn_phase
        # negative inside the window, where the first clause already grants
        # learning (python/jnp % both give non-negative results, so the
        # second clause stays well-defined)
        return (it < self.learn_full_until) | (
            rel % (self.learn_every * self.learn_burst) < self.learn_burst
        )

    @property
    def cadence_active(self) -> bool:
        """True when the schedule can ever skip a learn tick — the single
        gate shared by the device path (ops/step.py) and the host twins
        (HTMModel.run, registry CPU path), so 'is a cadence configured'
        can never be answered differently on different paths."""
        return self.learn_every > 1

    def with_learn_every(self, k: int, full_until: int | None = None,
                         burst: int = 1) -> "ModelConfig":
        """Cadence config with the standard maturity alignment: full-rate
        learning for the likelihood learning_period (or an explicit
        `full_until`; note this is the Gaussian-fit window, NOT the full
        probation — probation additionally spans estimation_samples ticks
        during which the likelihood is still pinned at 0.5 but learning
        already thins; the measured cadence curve in SCALING.md used
        exactly this boundary). The single policy shared by the operator CLI and
        the fault eval so quality numbers always describe the config the
        service runs. Invalid k (< 1) fails loudly via validation."""
        if k == 1 and full_until is None and burst == 1:
            return self
        return dataclasses.replace(
            self, learn_every=k, learn_burst=burst,
            learn_full_until=(self.likelihood.learning_period
                              if full_until is None else full_until),
        )

    def with_learning_period(self, learning_period: int) -> "ModelConfig":
        """Likelihood probation override (the measured precision lever:
        lp600 is +3 f1 points on the quality study, cost = +5 min warm-up
        over the preset's 300 at 1 s cadence, 10 min total). Apply BEFORE
        `with_learn_every`: the cadence's default full-rate window is the
        learning_period, so the other order silently pins full_until to
        the old probation — this helper and the CLI both enforce the safe
        ordering so callers cannot compose them wrong. Re-deriving
        learn_full_until here keeps an already-cadenced config aligned."""
        if learning_period < 1:
            raise ValueError(f"learning_period must be >= 1; got {learning_period}")
        cfg = dataclasses.replace(self, likelihood=dataclasses.replace(
            self.likelihood, learning_period=learning_period))
        if cfg.cadence_active and self.learn_full_until == \
                self.likelihood.learning_period:
            # the cadence was using the default maturity boundary: keep it
            # tied to the (new) probation rather than the stale value
            cfg = dataclasses.replace(cfg, learn_full_until=learning_period)
        return cfg

    def __post_init__(self) -> None:
        # A col_cap below the SP winner count would silently truncate the
        # kernel's column-compact active set and corrupt dendrite counts (the
        # tm_overflow counter is the only symptom). Fail loudly at construction.
        if self.tm.col_cap < self.sp.num_active_columns:
            raise ValueError(
                f"TMConfig.col_cap={self.tm.col_cap} is below "
                f"SPConfig.num_active_columns={self.sp.num_active_columns}; raise it"
            )
        if self.tm.cells_per_column > 32:
            raise ValueError(
                "cells_per_column > 32 is unsupported: the device kernel packs a "
                "column's cell activity into one int32 bit mask"
            )
        for name, bits in (("sp", self.sp.perm_bits), ("tm", self.tm.perm_bits)):
            if bits not in (0, 8, 16):
                raise ValueError(f"{name}.perm_bits must be 0 (f32), 8, or 16; got {bits}")
        if self.tm.punish_cap < 1:
            raise ValueError(f"TMConfig.punish_cap must be >= 1; got {self.tm.punish_cap}")
        if not 1 <= self.tm.fanout_cap <= (1 << 15) - 1:
            raise ValueError(
                f"TMConfig.fanout_cap must be in [1, 32767] (fwd_pos is int16 at "
                f"widest); got {self.tm.fanout_cap}"
            )
        if self.composite is not None:
            if self.scalar is not None:
                raise ValueError(
                    "composite and scalar encoder configs are exclusive "
                    "(each field of a composite picks its own kind)")
            if len(self.composite.fields) != self.n_fields:
                raise ValueError(
                    f"composite declares {len(self.composite.fields)} "
                    f"field(s) but n_fields={self.n_fields}; the wire row "
                    "and the layout table must agree")
            if self.classifier.enabled:
                raise ValueError(
                    "the SDR classifier decodes the uniform RDSE bucket "
                    "space of field 0 and is unsupported with a composite "
                    "encoder (predict on a scalar-config model instead)")
        if self.scalar is not None:
            # An invalid scalar range corrupts SDRs silently (negative buckets
            # wrap on host but drop on device — parity breaks) — fail loudly.
            if self.scalar.width >= self.scalar.size:
                raise ValueError(
                    f"ScalarEncoderConfig.width={self.scalar.width} must be "
                    f"< size={self.scalar.size}"
                )
            if not self.scalar.min_val < self.scalar.max_val:
                raise ValueError(
                    f"ScalarEncoderConfig needs min_val < max_val; got "
                    f"[{self.scalar.min_val}, {self.scalar.max_val}]"
                )
        if self.learn_every < 1:
            raise ValueError(f"learn_every must be >= 1; got {self.learn_every}")
        if self.learn_burst < 1:
            raise ValueError(f"learn_burst must be >= 1; got {self.learn_burst}")
        if self.learn_burst > 1 and self.learn_every == 1:
            # it % (1*B) < B is always true: the operator asked for a burst
            # cadence that can never thin anything — same loud-failure
            # policy as an invalid k (a saved config claiming learn_burst=8
            # at full rate would misrepresent what actually ran)
            raise ValueError(
                f"learn_burst={self.learn_burst} requires learn_every > 1 "
                "(with learn_every=1 the burst schedule never thins learning)"
            )
        if self.learn_full_until < 0:
            raise ValueError(
                f"learn_full_until must be >= 0; got {self.learn_full_until}"
            )
        cycle = self.learn_every * self.learn_burst
        if not 0 <= self.learn_phase < cycle:
            # a phase outside the cadence cycle silently aliases; demand
            # the canonical value so saved configs read unambiguously.
            # The cycle is k ticks for the spread schedule and k*B for
            # bursts (a burst-mode stagger offsets whole B-tick bursts)
            raise ValueError(
                f"learn_phase must be in [0, learn_every*learn_burst="
                f"{cycle}); got {self.learn_phase}"
            )
        if self.sp.pool_members < 0:
            raise ValueError(
                f"SPConfig.pool_members must be >= 0; got {self.sp.pool_members}"
            )
        if self.sp.sparse_pool:
            p = self.sp_members
            if not 1 <= p <= self.input_size:
                raise ValueError(
                    f"sparse SP pool needs 1 <= members <= input_size="
                    f"{self.input_size}; potential_pct={self.sp.potential_pct} "
                    f"/ pool_members={self.sp.pool_members} derive P={p}"
                )
        if self.sp.columns * self.tm.cells_per_column >= 1 << 24:
            # The kernel round-trips presynaptic cell ids through f32 one-hot
            # matmuls; ids >= 2^24 would lose bits silently.
            raise ValueError(
                "columns * cells_per_column must stay below 2^24 (cell ids are "
                "routed through f32 matmuls in the device kernel)"
            )

    @property
    def field_size(self) -> int:
        """Bits one value field occupies in the SDR (RDSE or classic
        scalar). Composite fields size individually — use
        :meth:`field_layout` there (this property serves the uniform
        family only and refuses to guess)."""
        if self.composite is not None:
            raise ValueError(
                "composite fields have per-field sizes; use field_layout()")
        return self.scalar.size if self.scalar is not None else self.rdse.size

    @property
    def input_size(self) -> int:
        if self.composite is not None:
            return self.composite.size + self.date.size
        return self.field_size * self.n_fields + self.date.size

    def field_resolutions(self) -> tuple[float, ...]:
        """Per-field encoder resolution, wire order — what the per-stream
        ``enc_resolution`` state row initializes from. Uniform configs
        repeat the family resolution; composite rdse/delta fields carry
        their FieldSpec's, and categorical fields use 1.0 (bucket ==
        rounded category id — one shared bucket formula serves all
        kinds)."""
        if self.composite is not None:
            return tuple(
                f.resolution if f.kind in ("rdse", "delta") else 1.0
                for f in self.composite.fields)
        # uniform families share one resolution (the scalar family ignores
        # enc_resolution entirely but the state row has always carried the
        # rdse default — preserved bit-for-bit)
        return (self.rdse.resolution,) * self.n_fields

    def field_layout(self) -> list[tuple[str, str, int, int]]:
        """The per-field SDR layout table: one (name, kind, offset, size)
        row per value field, in wire order — the single source of truth
        for encoder twins, attribution decode, and docs/WORKLOADS.md.
        Uniform configs report kind 'scalar'/'rdse' with synthetic names
        f0..fN-1; composite configs report the declared FieldSpec names."""
        rows: list[tuple[str, str, int, int]] = []
        off = 0
        if self.composite is not None:
            for f in self.composite.fields:
                rows.append((f.name, f.kind, off, f.size))
                off += f.size
            return rows
        kind = "scalar" if self.scalar is not None else "rdse"
        for i in range(self.n_fields):
            rows.append((f"f{i}", kind, off, self.field_size))
            off += self.field_size
        return rows

    @property
    def num_cells(self) -> int:
        return self.sp.columns * self.tm.cells_per_column

    @property
    def sp_members(self) -> int:
        """Members per column P of the sparse SP pool layout (0 for the
        dense layout): an explicit ``pool_members`` wins (the migration
        path pins it to the widest migrated column); otherwise P derives
        from the dense mask's expected density, round-half-up — the same
        arithmetic the scaling-math analyzer re-derives statically
        (analysis/scalingmath.py), so the two can never disagree."""
        if not self.sp.sparse_pool:
            return 0
        if self.sp.pool_members:
            return self.sp.pool_members
        return int(self.sp.potential_pct * self.input_size + 0.5)

    # ---- serialization (JSON round-trip for config files) ----
    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2, sort_keys=True)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "ModelConfig":
        def known(cfg_cls, sub: dict) -> dict:
            # Serialized configs may carry fields from other framework
            # versions (e.g. the retired active_cap/winner_cap capacity
            # bounds): accept and drop them so old checkpoints stay loadable.
            names = {f.name for f in dataclasses.fields(cfg_cls)}
            return {k: v for k, v in sub.items() if k in names}

        sp = SPConfig(**known(SPConfig, d.get("sp", {})))
        tm = TMConfig(**known(TMConfig, d.get("tm", {})))
        # Migration: configs serialized before col_cap existed default to 40;
        # clamp up to the SP winner count (col_cap is a transient kernel
        # workspace bound, not part of saved state shapes, so raising it on
        # resume is semantics-preserving) rather than failing validation.
        if tm.col_cap < sp.num_active_columns:
            import logging

            logging.getLogger(__name__).warning(
                "stored TMConfig.col_cap=%d below num_active_columns=%d; clamping up",
                tm.col_cap, sp.num_active_columns,
            )
            tm = dataclasses.replace(tm, col_cap=sp.num_active_columns)
        return cls(
            rdse=RDSEConfig(**known(RDSEConfig, d.get("rdse", {}))),
            date=DateConfig(**known(DateConfig, d.get("date", {}))),
            sp=sp,
            tm=tm,
            likelihood=LikelihoodConfig(**known(LikelihoodConfig, d.get("likelihood", {}))),
            classifier=ClassifierConfig(**known(ClassifierConfig, d.get("classifier", {}))),
            n_fields=d.get("n_fields", 1),
            scalar=(
                ScalarEncoderConfig(**known(ScalarEncoderConfig, d["scalar"]))
                if d.get("scalar") is not None
                else None
            ),
            composite=(
                CompositeEncoderConfig(
                    fields=tuple(FieldSpec(**known(FieldSpec, f))
                                 for f in d["composite"]["fields"]))
                if d.get("composite") is not None
                else None
            ),
            # pre-cadence checkpoints default to full-rate learning
            learn_every=d.get("learn_every", 1),
            learn_full_until=d.get("learn_full_until", 0),
            learn_burst=d.get("learn_burst", 1),
            learn_phase=d.get("learn_phase", 0),
        )

    @classmethod
    def from_json(cls, s: str) -> "ModelConfig":
        return cls.from_dict(json.loads(s))


def rdse_resolution(min_val: float, max_val: float, buckets: int = 130) -> float:
    """NAB's encoder-resolution rule: the expected value range spans ~130
    buckets (SURVEY.md §5 key defaults). Single source of truth — the preset
    and the per-file rescale in nab/runner.py both use it."""
    return max(0.001, (max_val - min_val) / float(buckets))


def nab_preset(min_val: float = 0.0, max_val: float = 100.0) -> ModelConfig:
    """NuPIC/NAB-scale model for detection-quality runs.

    Mirrors the NAB Numenta-detector parameter family (SURVEY.md §5 key
    defaults): RDSE n=400/w=21 with resolution (max-min)/130, SP 2048
    columns / 40 winners, TM 32 cells per column. Segment pools are bounded
    at 16x32 (vs NuPIC's loose 128-segment cap) — dense-pool capacity
    actually reached by single-metric streams is far below the cap.
    """
    resolution = rdse_resolution(min_val, max_val)
    return ModelConfig(
        rdse=RDSEConfig(size=400, active_bits=21, resolution=resolution),
        date=DateConfig(time_of_day_width=21, time_of_day_size=54, weekend_width=0),
        sp=SPConfig(columns=2048, num_active_columns=40),
        tm=TMConfig(cells_per_column=32, max_segments_per_cell=16,
                    max_synapses_per_segment=32, col_cap=40),
        likelihood=LikelihoodConfig(mode="window"),
    )


def _round_half_up(x: float) -> int:
    """Shared by the scaled presets: banker's rounding once produced a
    degenerate perfect-match segment geometry (see scaled_cluster_preset);
    both width-scaling paths must round the same way."""
    return int(x + 0.5)


def _guard_segment_capacity(name: str, columns: int, ns: int, cap: int) -> None:
    if ns > cap:
        raise ValueError(
            f"{name}({columns}) needs new_synapse_count={ns} > "
            f"max_synapses_per_segment={cap}: upscaling past the preset's "
            "segment capacity silently truncates growth; widen the TM pools "
            "explicitly instead"
        )


def scaled_nab_preset(columns: int, min_val: float = 0.0,
                      max_val: float = 100.0) -> ModelConfig:
    """NAB preset rescaled to `columns` SP width at the preset's ~2%
    activation sparsity, segment geometry tracking the winner count at the
    NuPIC Numenta-detector ratios (sample half the winners per learned
    segment, activate on ~0.65 of the samples, match on ~half — the
    2048/40/20/13/10 family scaled down, round-half-up like
    scaled_cluster_preset so small widths keep non-degenerate thresholds).

    Purpose: the model-width study (SCALING.md, scripts/model_size_eval.py)
    measured the CLUSTER preset heavily oversized on node-metric streams;
    this preset asks the same question of the NAB-family model on the
    diverse-profile stand-in corpus (scripts/nab_standin_report.py
    --columns), where the full-size 2048-column model is the 10.5 s/tick
    CPU-infeasible config. Cells per column stay at the preset's 32 — width
    is the measured axis; the cells axis is deliberately unexplored here.
    """
    base = nab_preset(min_val, max_val)
    k = max(4, _round_half_up(columns * base.sp.num_active_columns
                              / base.sp.columns))
    ns = max(3, _round_half_up(k * base.tm.new_synapse_count
                               / base.sp.num_active_columns))
    _guard_segment_capacity("scaled_nab_preset", columns, ns,
                            base.tm.max_synapses_per_segment)
    act = max(2, _round_half_up(ns * base.tm.activation_threshold
                                / base.tm.new_synapse_count))
    mn = max(1, min(act, _round_half_up(ns * base.tm.min_threshold
                                        / base.tm.new_synapse_count)))
    return dataclasses.replace(
        base,
        sp=dataclasses.replace(base.sp, columns=columns, num_active_columns=k),
        tm=dataclasses.replace(base.tm, activation_threshold=act,
                               min_threshold=mn, new_synapse_count=ns,
                               col_cap=k),
    )


def node_preset(n_metrics: int = 3, perm_bits: int = 16) -> ModelConfig:
    """Multivariate per-node model (SURVEY.md §6 benchmark config 4:
    'multivariate per-node cpu/mem/net fused RDSE').

    One HTM model per NODE, fusing its `n_metrics` scalar fields into a
    single SDR (`ModelConfig.n_fields`; each field gets its own RDSE bit
    range and per-field offset binding — models/oracle/encoders.py). The SP
    learns cross-metric structure, so a fault visible in any one field (or a
    correlated node-level fault across all of them) perturbs the shared
    column code. Built on the DENSE cluster geometry
    (:func:`dense_cluster_preset` — the pre-ISSUE-18 cluster_preset), NOT
    the sparse member-index preset: the ISSUE 18 quality evidence
    (reports/sparse_quality.json) covers single-metric streams only, and
    the fused multi-field bars in tests/integration/
    test_multivariate_node.py measurably regress at the sparse P=0.5*n_in
    width (learned-quiet p99 raw 0.10 -> 0.30; sweeping P recovers one bar
    only at the cost of leaving the weakest single-field window response
    at the alertability threshold). Sparse-migrating the multivariate
    config needs its own occupancy/quality study — until then it keeps
    the measured dense geometry, and only the SP pool tables grow with
    input_size.
    """
    base = dense_cluster_preset(perm_bits=perm_bits)
    return dataclasses.replace(base, n_fields=n_metrics)


def composite_preset(perm_bits: int = 16, value_resolution: float = 0.5,
                     n_event_classes_hint: int = 256) -> ModelConfig:
    """Composite workload model (ISSUE 9; ROADMAP item 4): one stream fuses
    {value, delta, event-class} + the hour-of-day ring into a single SDR.

    Built on the cluster_preset footprint (only the SP potential/permanence
    matrices grow with input_size; the TM pools — the dominant state — are
    unchanged, same as node_preset). Field geometry keeps the preset's
    ~8.6% per-field bit density (11/128):

    - ``value``  — RDSE over the raw metric (the scalar component; its
      encoding arithmetic is IDENTICAL to the scalar path's field 0, so
      composite F1 on scalar faults is an apples comparison).
    - ``delta``  — RDSE over the first difference (NuPIC DeltaEncoder):
      rate-of-change anomalies (a slope flip inside the normal band) that
      the absolute value hides.
    - ``event_class`` — hash-bucketed categorical over event/template ids
      (log-template ids from rtap_tpu/ingest/templates.py ride here).
      ``n_event_classes_hint`` documents the expected id cardinality; the
      encoder itself is table-free and unbounded.
    - hour-of-day — the DateConfig ring at REDUCED weight (7 of the
      54-bucket NAB ring, vs the NAB family's 21): date bits are context,
      not signal, and at sub-hour horizons they are near-constant. At the
      NAB width they are 21 of 54 active bits, so a full value-field
      novelty flips only ~1/3 of the SP's input overlap and the anomaly
      contrast of a scalar fault collapses (measured: composite F1 0.72
      vs scalar 0.97 on eval/workload_eval.py's regression gate). At 7
      bits the ring still gives the TM its seasonality context while the
      {value, delta} pair dominates the code — the gate holds with F1
      above the scalar baseline (reports/workloads_r09.json). This is the
      paper's composite-encoder weighting rule: bits are allocated by
      field importance, not uniformly.
    """
    base = cluster_preset(perm_bits=perm_bits)
    del n_event_classes_hint  # documentation-only: the encoder is table-free
    return dataclasses.replace(
        base,
        n_fields=3,
        composite=CompositeEncoderConfig(fields=(
            FieldSpec(name="value", kind="rdse", size=128, active_bits=11,
                      resolution=value_resolution),
            FieldSpec(name="delta", kind="delta", size=128, active_bits=11,
                      resolution=value_resolution),
            FieldSpec(name="event_class", kind="categorical", size=128,
                      active_bits=11),
        )),
        date=DateConfig(time_of_day_width=7, time_of_day_size=54,
                        weekend_width=0),
    )


def categorical_preset(perm_bits: int = 16) -> ModelConfig:
    """Single-field categorical model (event-class / log-template streams):
    the cluster_preset footprint with the one value field encoded as a
    hash-bucketed categorical — the eval config for the categorical and
    log-template NAB-style modalities (eval/workload_eval.py)."""
    base = cluster_preset(perm_bits=perm_bits)
    return dataclasses.replace(
        base,
        composite=CompositeEncoderConfig(fields=(
            FieldSpec(name="event_class", kind="categorical", size=128,
                      active_bits=11),
        )),
    )


def cluster_preset(perm_bits: int = 16) -> ModelConfig:
    """Small-footprint model for 1k-100k concurrent streams on one chip.

    Per-stream HBM budget dominates at 100k streams (16 GB HBM / 100k ~=
    160 KB per stream — SURVEY.md §7 hard part 4). Honest footprint (measure
    with models/state.state_nbytes, which sums the actual arrays — a round-2
    comment here claimed ~112 KB/stream by counting only SP perms and
    misreading the TM pool product; the round-2 layout's real figure was
    ~1015 KB/stream).

    ISSUE 18 (structurally sparse synapse pools) re-lays the preset on the
    memory frontier: the SP pool is the sparse member-index layout
    (``sparse_pool``; P = 64 of 128 inputs per column — SDR capacity rides
    sparsity, not pool width, PAPERS.md 1503.07469) and the TM segment pool
    is right-sized from live occupancy evidence (obs/health occupancy
    histograms + reports/sparse_quality.json: single-metric streams leave
    most of the old 4-segment lanes empty) to 2 segments/cell with LRU
    eviction unchanged. Current measured state_nbytes totals — presyn
    narrows to int16 and seg_pot to int16 automatically (num_cells = 2048
    here), independent of perm_bits:

    - perm_bits=0  (f32 perms):  433,173 B/stream (was 826 KB dense)
    - perm_bits=16 (u16 quanta): 302,101 B/stream (was 564,245 B: -46%)
    - perm_bits=8  (u8 quanta):  236,565 B/stream (was 433,173 B)

    The pre-ISSUE-18 dense geometry survives as :func:`dense_cluster_preset`
    (checkpoint migration source, quality A/B baseline, frozen golden).
    SCALING.md records the measured HBM frontier per domain on hardware.
    """
    return ModelConfig(
        rdse=RDSEConfig(size=128, active_bits=11, resolution=0.5),
        date=DateConfig(time_of_day_width=0, time_of_day_size=0, weekend_width=0),
        sp=SPConfig(columns=256, potential_pct=0.5, sparse_pool=True,
                    num_active_columns=10,
                    syn_perm_active_inc=0.01, syn_perm_inactive_dec=0.002,
                    perm_bits=perm_bits),
        # activation_threshold/new_synapse_count ratio 5/10: a learned segment
        # samples one winner cell from each of the 10 active columns, and
        # activates on half of them recurring — measured on the fault-injection
        # eval, the old brittle 7/8 ratio left steady-state raw ~0.23 (p90 =
        # 0.9, i.e. frequent full bursts) vs 0.06 (p90 = 0.2) here, and f1
        # 0.44 -> 0.61 (eval/fault_eval.py, 40 streams x 1000 s).
        # learn_cap 64: the round-4 replay drive caught learn_cap=32
        # truncating learning bursts on the default synthetic workload
        # (tm_overflow_total=2 at magnitude 6; 48 clears it — kept at 64 for
        # headroom, the [learn_cap, M] workspace is tiny next to the pools)
        # max_segments_per_cell 2 (was 4): the compact right-sizing half of
        # ISSUE 18 — a knob-only change (no format change); the occupancy
        # evidence and the F1 A/B vs the dense baseline are committed in
        # reports/sparse_quality.json
        tm=TMConfig(cells_per_column=8, activation_threshold=5, min_threshold=4,
                    max_segments_per_cell=2, max_synapses_per_segment=12,
                    new_synapse_count=10, learn_cap=64, col_cap=10,
                    perm_bits=perm_bits),
        # probation 400: false-alert episodes cluster in ticks 150-400 with
        # the short round-2 probation (the tiny model is still maturing when
        # the likelihood starts firing) — measured 56 of 75 false episodes
        # landed there.
        likelihood=LikelihoodConfig(mode="streaming", historic_window_size=512,
                                    learning_period=300, estimation_samples=100),
    )


def dense_cluster_preset(perm_bits: int = 16) -> ModelConfig:
    """The pre-ISSUE-18 cluster preset: dense SP pool (potential mask at
    pct 0.8) and 4-segment TM lanes — 564,245 B/stream at u16.

    Kept verbatim because committed artifacts stand on it: the frozen
    quantized golden (tests/golden), the dense-layout checkpoint fixture
    the migration test restores (docs/MIGRATION.md), and the quality A/B
    baseline the sparse preset is measured against
    (reports/sparse_quality.json). New deployments should use
    :func:`cluster_preset`; dense checkpoints upgrade via
    ``load_group(..., sparsify=True)`` (service/checkpoint.py)."""
    base = cluster_preset(perm_bits=perm_bits)
    return dataclasses.replace(
        base,
        sp=dataclasses.replace(base.sp, potential_pct=0.8, sparse_pool=False),
        tm=dataclasses.replace(base.tm, max_segments_per_cell=4),
    )


def scaled_cluster_preset(columns: int, perm_bits: int = 16) -> ModelConfig:
    """Cluster preset rescaled to `columns` SP width at the preset's ~3.9%
    activation sparsity, the learned-segment geometry tracking the winner
    count (one sampled winner per active column; activation on half
    recurring — the preset's measured ratio, see cluster_preset's TMConfig
    comment).

    Measured at production scale (scripts/model_size_eval.py,
    reports/model_size_quality.json, 120 x 1500 fault eval): the
    256-column preset is heavily over-parameterized for node-metric
    streams — 128 cols scores f1 0.804, 64 cols 0.771, and 32 cols
    (70.5 KB/stream, 1/8 the state, analytic ~220k streams/chip) 0.813,
    the best of all measured configs, vs the preset's 0.789. Size
    reduction preserves quality far better than cadence thinning (the
    staleness study, SCALING.md). Caveat: synthetic node-metric workload;
    richer signals may need the width. Silicon throughput: bench
    BENCH_COLUMNS rungs / profile_half harvest steps."""
    base = cluster_preset(perm_bits=perm_bits)
    # round-half-up (not banker's): 64 cols must give k=3, preserving ~the
    # preset's sparsity; and the activation ratio stays ~half of k — at
    # banker's k=2 the geometry degenerated to a 2-of-2 perfect-match
    # requirement, which confounded the first quarter-model measurement
    k = max(3, _round_half_up(columns * base.sp.num_active_columns
                              / base.sp.columns))
    _guard_segment_capacity("scaled_cluster_preset", columns, k,
                            base.tm.max_synapses_per_segment)
    return dataclasses.replace(
        base,
        sp=dataclasses.replace(base.sp, columns=columns, num_active_columns=k),
        tm=dataclasses.replace(base.tm,
                               activation_threshold=max(2, k // 2),
                               min_threshold=max(1, k // 2 - 1),
                               new_synapse_count=k, col_cap=k),
    )
