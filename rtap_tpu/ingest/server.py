"""BinaryBatchSource: the production wire-speed ingest front end.

The live_loop source contract (``source(tick) -> (values [G] f32, ts)``)
fed by the ``RB1`` binary batch protocol instead of per-record JSON:

- **Persistent sockets.** A threaded listener accepts any number of
  producer connections; each connection's bytes run through one
  :class:`~rtap_tpu.ingest.protocol.FrameWalker` (native C scanner when
  the toolchain allows, pure Python otherwise) and every validated DATA
  frame decodes with one ``np.frombuffer`` + one fancy-index scatter
  into the per-(group, slot) dispatch buffer — zero per-record Python.
- **Shared-memory ring** (:mod:`rtap_tpu.ingest.shm`): co-located
  exporters hand the same frames over shm; the ring is drained once per
  tick through the same walker + admission path.
- **Timestamp alignment / backfill** (``backfill_horizon=H`` SECONDS
  of row timestamp): rows are bucketed by their wire timestamp (unix
  seconds) and emission trails the newest observed timestamp by H, so
  a row arriving up to H seconds late lands in the slot its timestamp
  names instead of overwriting the newest value (the JSONL listener's
  clamp). At the standard 1 s cadence a second is a tick. ``H=0``
  (default) keeps the JSONL source's exact latest-wins/drain
  semantics — the live_loop equivalence test pins bit-identical alert
  streams on that mode.
- **Admission control.** Per-tenant row quotas per tick
  (``quota_rows``; a frame's tenant header names the payer), drop-
  oldest backpressure on the backfill buckets, and ``rtap_obs_ingest_*``
  counters/gauges riding the normal snapshot path (docs/TELEMETRY.md).

Membership follows the registry's SLOT MAP (``set_slot_map`` — the
(shard, group, slot) addressing of ROADMAP-1), and the auto-register
protocol is shared with the JSONL listener: producers announce unknown
stream ids in NAMES frames, ``drain_unknown`` feeds serve
--auto-register, and connecting producers receive the current id->code
MAP frame (re-requestable with an empty MAP frame).

The write-ahead journal integration (``take_tick_frames``): the raw
DATA frames that composed a tick's emission are handed to the journal
verbatim (cheaper write-ahead than re-encoding the full-width vector);
ticks whose emission is NOT a pure frame replay (backfill merges,
quota-truncated frames) synthesize one compact frame from the emitted
vector instead, so journal replay is bit-identical either way.
"""

from __future__ import annotations

import json
import socket as socket_module
import socketserver
import threading
import time

import numpy as np

from rtap_tpu.ingest.dispatch import DispatchTable
from rtap_tpu.ingest.protocol import (
    KIND_DATA,
    KIND_MAP,
    KIND_NAMES,
    FrameWalker,
    build_frame,
    data_frame,
)
from rtap_tpu.obs import get_registry


class BinaryBatchSource:
    """See module docstring. Construct with the registry's slot map
    (``StreamGroupRegistry.slot_map()``), then ``start()`` / ``close()``
    (or use as a context manager)."""

    #: bound on remembered unknown-id NAMES (same threat model as
    #: TcpJsonlSource.MAX_UNKNOWN_TRACKED)
    MAX_UNKNOWN_TRACKED = 4096
    #: distinct tenants tracked per quota window; overflow tenants share
    #: one fold-over bucket (an id-spraying producer must not grow host
    #: memory through tenant labels either)
    TENANT_TRACK_CAP = 1024
    #: raw frames retained per tick for the journal; a tick exceeding
    #: this synthesizes one compact frame instead (bounded memory)
    MAX_TICK_FRAME_ROWS = 1 << 20

    def __init__(self, slot_map: dict, host: str = "127.0.0.1",
                 port: int | None = 0, shm=None, shm_bytes: int = 8 << 20,
                 quota_rows: int = 0, backfill_horizon: int = 0,
                 track_unknown: bool = False, native: bool | None = None,
                 max_pending_buckets: int | None = None):
        if quota_rows < 0:
            raise ValueError(f"quota_rows must be >= 0; got {quota_rows}")
        if backfill_horizon < 0:
            raise ValueError(
                f"backfill_horizon must be >= 0; got {backfill_horizon}")
        self._table = DispatchTable(slot_map)
        self._lock = threading.Lock()
        self._native = native
        self.quota_rows = int(quota_rows)
        self.horizon = int(backfill_horizon)  # seconds of row timestamp
        self.max_pending = int(max_pending_buckets) if max_pending_buckets \
            else max(2 * self.horizon + 8, 16)
        self._track_unknown = bool(track_unknown)
        self._unknown_seen: set[str] = set()
        # hot-path state (all guarded by _lock)
        self._latest = np.full(self._table.n, np.nan, np.float32)
        self._latest_ts = 0
        self._max_row_ts = 0
        self._emit_floor = None  # newest bucket ts already emitted (H>0)
        self._buckets: dict[int, list] = {}  # ts -> [vec f32, n_rows]
        self._tenant_used: dict[str, int] = {}
        self._tick_frames: list[bytes] = []
        self._tick_frame_rows = 0
        self._tick_pure = True  # emission == replay of _tick_frames
        self._last_tick_frames = None
        # detection-latency stage surfaces (ISSUE 11, obs/latency.py):
        # the latest DATA frame's wire-transit lag (arrival wall clock
        # minus its freshest row ts) and, in backfill mode, the hold the
        # horizon imposed on the last emitted tick. The LatencyTracker
        # getattr-probes once per tick WITHOUT the lock, so the
        # (wall, ts) pair lives in ONE tuple rebound atomically — two
        # separate attributes could tear between a handler's write and
        # the loop's read and report a lag computed from mismatched
        # halves (rtap-lint race-audit fix, docs/ANALYSIS.md).
        self._arrival: tuple[float, int] | None = None  # (wall, row ts)
        self._release_hold: float | None = None
        # map epoch 1..65535 (0 is reserved for epoch-unaware
        # producers): bumped on every membership change so a producer
        # still sending with a cached map goes loudly deaf instead of
        # feeding a re-claimed slot's NEW stream (docs/INGEST.md)
        self._map_epoch = 1
        #: failover redirect (ISSUE 8): when this serve loses leadership
        #: the MAP gains "__leader__": "host:port" naming who producers
        #: should reconnect to (announce_leader); None = we are it
        self._leader_addr: str | None = None
        self._map_blob = self._render_map()
        # accounting (ints, mirrored into the registry instruments below)
        self.rows_applied = 0
        self.frames_applied = 0
        self.rows_unknown = 0
        self.rows_stale_epoch = 0
        self.rows_quota_dropped = 0
        self.rows_late_dropped = 0
        self.rows_backfilled = 0
        self.rows_backpressure_dropped = 0
        obs = get_registry()
        # rows share the JSONL listener's record counter on purpose:
        # "successfully ingested records" must mean the same thing
        # across transports (satellite: and across parser backends)
        self._obs_rows = obs.counter(
            "rtap_obs_ingest_records_total",
            "successfully parsed ingest records (JSONL records and "
            "binary batch rows, both parser backends)")
        self._obs_frames = obs.counter(
            "rtap_obs_ingest_frames_total",
            "validated RB1 frames applied (DATA/NAMES/MAP)")
        self._obs_bad_frames = obs.counter(
            "rtap_obs_ingest_bad_frames_total",
            "RB1 frames rejected by the walker (CRC mismatch)")
        self._obs_garbage = obs.counter(
            "rtap_obs_ingest_garbage_bytes_total",
            "stream bytes skipped while resyncing to the next frame "
            "magic (torn producers, line noise)")
        self._obs_version_skew = obs.counter(
            "rtap_obs_ingest_version_skew_total",
            "well-framed RB1 frames skipped for an unknown protocol "
            "version or frame kind (forward compatibility, counted)")
        self._obs_unknown = obs.counter(
            "rtap_obs_ingest_unknown_ids_total",
            "records for unregistered stream ids (claim candidates under "
            "--auto-register, otherwise dropped)")
        self._obs_stale = obs.counter(
            "rtap_obs_ingest_stale_epoch_total",
            "rows dropped whole-frame because the producer's map epoch "
            "predates a membership change (slot codes may have been "
            "re-claimed by different streams — refuse, never misroute)")
        self._obs_quota = obs.counter(
            "rtap_obs_ingest_quota_dropped_total",
            "rows dropped by per-tenant admission quotas "
            "(--ingest-quota rows/tenant/tick)")
        self._obs_late = obs.counter(
            "rtap_obs_ingest_late_dropped_total",
            "rows older than the backfill horizon (their tick slot was "
            "already emitted) — dropped, never mis-clocked")
        self._obs_backfilled = obs.counter(
            "rtap_obs_ingest_backfilled_rows_total",
            "late rows the backfill horizon landed in their correct "
            "(earlier) tick slot")
        self._obs_backpressure = obs.counter(
            "rtap_obs_ingest_backpressure_dropped_total",
            "rows dropped by drop-oldest backpressure (pending backfill "
            "buckets exceeded the bound)")
        self._obs_buffered = obs.gauge(
            "rtap_obs_ingest_buffered_rows",
            "rows currently buffered in backfill buckets awaiting their "
            "emission tick")
        self._obs_tenants = obs.gauge(
            "rtap_obs_ingest_tenants",
            "distinct tenants seen in the current quota window")
        # a probe walker decides native availability once (and loudly if
        # native=True); per-connection walkers inherit the choice
        self._walker_native = FrameWalker(native=native).native_active \
            if native is not False else None
        if self._walker_native is None:
            self._walker_native = False
        self._walkers: list[FrameWalker] = []  # live conns, for counter sums
        # shm + feed_frames path (NOT in _walkers: summed separately)
        self._local_walker = FrameWalker(native=bool(self._walker_native))
        # shared-memory ring (created here; co-located exporters attach)
        self._ring = None
        if shm is not None:
            from rtap_tpu.ingest.shm import ShmRing

            self._ring = shm if isinstance(shm, ShmRing) \
                else ShmRing.create(shm, shm_bytes)
        # TCP listener (port=None: shm/local-only source, no socket)
        self._server = None
        self._thread = None
        self.address = None
        self._conns: set = set()  # live producer sockets, for MAP pushes
        # serializes ALL server->client control writes (handler map
        # replies vs membership pushes share sockets across threads; an
        # interleaved sendall would tear frames on the wire)
        self._send_lock = threading.Lock()
        #: live handler threads, for the deterministic close() join —
        #: socketserver's own daemon_threads bookkeeping does not track
        #: daemon handlers, and a handler blocked in recv() would
        #: otherwise outlive close() nondeterministically (the conftest
        #: no-leaked-thread fixture's flake mode under repeated
        #: open/close in tests)
        self._handler_threads: set = set()
        self._closing = False  # close() raises it BEFORE joining: even a
        # handler that connected in the shutdown race (registered after
        # the join snapshot, socket never woken) exits within one recv
        # timeout instead of blocking forever
        if port is not None:
            outer = self

            class Handler(socketserver.BaseRequestHandler):
                def handle(self):
                    with outer._lock:
                        outer._handler_threads.add(threading.current_thread())
                    walker = None
                    # ONE finally owns the bookkeeping for every exit
                    # path, including a hello that fails before the
                    # loop (a connect-then-die producer must not leak
                    # its thread entry forever)
                    try:
                        # hello: the current id -> slot-code map, so the
                        # producer can encode without out-of-band config
                        try:
                            outer._send_map(self.request)
                        except OSError:
                            return
                        with outer._lock:
                            outer._conns.add(self.request)
                        walker = outer._new_walker()
                        self.request.settimeout(0.5)
                        while True:
                            try:
                                data = self.request.recv(1 << 20)
                            except socket_module.timeout:
                                if outer._closing:
                                    break
                                continue  # idle producer: keep waiting
                            if not data:
                                break
                            frames = walker.feed(data)
                            # MAP re-requests answer OUTSIDE the hot
                            # lock (a slow client's send must not stall
                            # every producer's apply)
                            for fr in frames:
                                if fr.kind == KIND_MAP and fr.count == 0:
                                    outer._send_map(self.request)
                            with outer._lock:
                                for fr in frames:
                                    outer._apply(fr)
                    except OSError:  # rtap: allow[except-silent] —
                        # connection death is a producer's normal end;
                        # the finally below books the disconnect
                        pass
                    finally:
                        with outer._lock:
                            outer._conns.discard(self.request)
                            outer._handler_threads.discard(
                                threading.current_thread())
                        if walker is not None:
                            outer._drop_walker(walker)

            class Server(socketserver.ThreadingTCPServer):
                allow_reuse_address = True
                daemon_threads = True

            self._server = Server((host, port), Handler)
            self.address = self._server.server_address
            self._thread = threading.Thread(
                target=self._server.serve_forever,
                name="rtap-ingest-accept", daemon=True)

    # ---- lifecycle ---------------------------------------------------
    def start(self) -> "BinaryBatchSource":
        if self._thread is not None:
            self._thread.start()
        return self

    def close(self) -> None:
        """Deterministic shutdown: stop accepting, WAKE every handler
        (socket shutdown makes its blocking recv return b"" — the
        wakeup), then join the accept thread and every handler thread
        with a bounded wait. Repeated open/close in one process (the
        test suite's pattern) leaves no thread behind to trip the
        conftest no-leaked-thread fixture; threads stay daemonized so a
        truly wedged one still cannot hang interpreter exit."""
        if self._server is not None:
            self._closing = True
            if self._thread is not None and self._thread.is_alive():
                self._server.shutdown()  # unblocks serve_forever
            with self._lock:
                conns = list(self._conns)
                handlers = list(self._handler_threads)
            for sock in conns:
                try:
                    sock.shutdown(socket_module.SHUT_RDWR)
                except OSError:
                    pass
            if self._thread is not None and self._thread.is_alive():
                self._thread.join(timeout=5.0)
            for t in handlers:
                t.join(timeout=5.0)
            self._server.server_close()
        if self._ring is not None:
            self._ring.close()

    def __enter__(self) -> "BinaryBatchSource":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    @property
    def ring_name(self) -> str | None:
        return self._ring.name if self._ring is not None else None

    # ---- walker bookkeeping ------------------------------------------
    def _new_walker(self) -> FrameWalker:
        # the probe in __init__ already decided (and failed loudly for
        # native=True); per-connection walkers just follow it
        w = FrameWalker(native=bool(self._walker_native))
        with self._lock:
            self._walkers.append(w)
        return w

    def _drop_walker(self, w: FrameWalker) -> None:
        with self._lock:
            # fold the dead connection's walker tallies into durable sums
            self._dead_garbage = getattr(self, "_dead_garbage", 0) \
                + w.garbage_bytes
            self._dead_bad_crc = getattr(self, "_dead_bad_crc", 0) + w.bad_crc
            self._dead_skew = getattr(self, "_dead_skew", 0) + w.version_skew
            try:
                self._walkers.remove(w)
            except ValueError:  # rtap: allow[except-silent] — a
                # double-drop in the close() race; tallies above
                # already folded once
                pass

    def _walker_sum(self, attr: str, dead: str) -> int:
        return getattr(self, dead, 0) + sum(
            getattr(w, attr) for w in self._walkers)

    # ---- membership (the registry slot-map protocol) -----------------
    def _render_map(self) -> bytes:
        return json.dumps({"__epoch__": self._map_epoch,
                           **({"__leader__": self._leader_addr}
                              if self._leader_addr else {}),
                           **self._table.code_of},
                          separators=(",", ":")).encode("utf-8")

    def announce_leader(self, addr: str) -> None:
        """Failover re-point (ISSUE 8): a FENCED old leader pushes a MAP
        naming the new leader's ingest address and bumping the epoch, so
        every connected RB1 producer both goes loudly deaf here (stale
        epoch) and learns where to reconnect
        (BinaryFeedConnection.leader_hint; send_binary follows the
        redirect). Best-effort: producers whose connection already died
        learn the same thing from their reconnect failing."""
        with self._lock:
            self._leader_addr = str(addr)
            self._map_epoch = self._map_epoch % 0xFFFF + 1
            self._map_blob = self._render_map()
            conns = list(self._conns)
            blob = self._map_blob
        frame = build_frame(KIND_MAP, blob)
        with self._send_lock:
            for sock in conns:
                try:
                    sock.sendall(frame)
                except OSError:
                    pass

    def _send_map(self, sock) -> None:
        with self._lock:
            blob = self._map_blob
        with self._send_lock:
            sock.sendall(build_frame(KIND_MAP, blob))

    def set_slot_map(self, slot_map: dict) -> None:
        """Adopt the registry's new slot map (membership changed).

        Latest values and pending buckets carry over BY ID — a retained
        stream must not lose the sample that arrived this tick; new ids
        start NaN. New connections (and MAP re-requests) see the new
        map immediately; rows addressed at released slots start
        counting as unknown."""
        table = DispatchTable(slot_map)
        with self._lock:
            old = self._table
            remap = np.full(table.n, -1, np.int64)
            old_pos = {sid: i for i, sid in enumerate(old.ids)}
            for j, sid in enumerate(table.ids):
                i = old_pos.get(sid)
                if i is not None:
                    remap[j] = i

            def carry(vec):
                out = np.full(table.n, np.nan, np.float32)
                m = remap >= 0
                out[m] = vec[remap[m]]
                return out

            self._latest = carry(self._latest)
            for ts in list(self._buckets):
                vec, nrows = self._buckets[ts]
                self._buckets[ts] = [carry(vec), nrows]
            self._table = table
            # bump the epoch (1..65535, skipping the epoch-unaware 0):
            # frames stamped with the old epoch are stale from here on
            self._map_epoch = self._map_epoch % 0xFFFF + 1
            self._map_blob = self._render_map()
            # a membership change invalidates raw-frame journaling for
            # the in-progress tick (old codes): synthesize at snapshot
            self._tick_pure = False
            conns = list(self._conns)
            blob = self._map_blob
        # PUSH the fresh map to every connected producer (outside the
        # hot lock; best-effort — a dead socket's handler cleans up):
        # without this, a producer whose NAMES were not the trigger
        # (e.g. an auto-release elsewhere in the fleet) would keep
        # stamping the old epoch and go deaf until it happened to
        # re-request. Producers drain pushes via
        # BinaryFeedConnection.poll_map() before sending.
        frame = build_frame(KIND_MAP, blob)
        with self._send_lock:
            for sock in conns:
                try:
                    sock.sendall(frame)
                except OSError:
                    pass

    def drain_unknown(self) -> list[str]:
        """Pop unknown-id names announced in NAMES frames since the last
        drain (sorted; empty unless track_unknown)."""
        if not self._track_unknown:
            return []
        with self._lock:
            seen = sorted(self._unknown_seen)
            self._unknown_seen.clear()
        return seen

    # ---- frame application (lock held) -------------------------------
    def _apply(self, fr) -> None:
        # (DATA frames count below, AFTER the stale-epoch gate — a
        # refused frame must not read as "applied" in the triage pair
        # frames-applied vs rows-applied)
        if fr.kind == KIND_NAMES:
            self.frames_applied += 1
            self._obs_frames.inc()
            if self._track_unknown:
                for name in bytes(fr.payload).decode(
                        "utf-8", "ignore").split("\n"):
                    if name and len(self._unknown_seen) \
                            < self.MAX_UNKNOWN_TRACKED:
                        self._unknown_seen.add(name)
            return
        if fr.kind == KIND_MAP:
            self.frames_applied += 1
            self._obs_frames.inc()
            return  # map requests are answered by the handler thread
        # ---- DATA ----
        rows = fr.rows()
        n = len(rows)
        if n == 0:
            self.frames_applied += 1
            self._obs_frames.inc()
            return
        if fr.epoch and fr.epoch != self._map_epoch:
            # the producer's map predates a membership change: its slot
            # codes may now address DIFFERENT streams (released +
            # re-claimed). Refuse the whole frame, loudly — misrouting
            # a stranger's model is the one failure worse than deafness
            self.rows_stale_epoch += n
            self._obs_stale.inc(n)
            return
        self.frames_applied += 1
        self._obs_frames.inc()
        kept = n
        if self.quota_rows:
            tenant = fr.tenant
            if tenant not in self._tenant_used \
                    and len(self._tenant_used) >= self.TENANT_TRACK_CAP:
                tenant = "__other__"
            used = self._tenant_used.get(tenant, 0)
            kept = max(0, min(n, self.quota_rows - used))
            self._tenant_used[tenant] = used + kept
            if kept < n:
                self.rows_quota_dropped += n - kept
                self._obs_quota.inc(n - kept)
                self._tick_pure = False  # raw frame != admitted rows
                if kept == 0:
                    return
                rows = rows[:kept]
        pos = self._table.lookup(rows["slot"])
        valid = pos >= 0
        n_unknown = int((~valid).sum())
        if n_unknown:
            self.rows_unknown += n_unknown
            self._obs_unknown.inc(n_unknown)
        ts_rows = fr.base_ts + rows["dt"].astype(np.int64)
        # the backfill comparison point is the clock BEFORE this frame:
        # a frame's own timestamp spread must not count as late rows
        prev_max = self._max_row_ts
        if ts_rows.size:
            self._max_row_ts = max(self._max_row_ts, int(ts_rows.max()))
            # stage surface: when THIS frame's freshest row arrived,
            # in wall time (one clock read per frame, not per row);
            # one tuple rebind — the unlocked reader sees a coherent pair
            self._arrival = (time.time(), int(ts_rows.max()))
        applied = int(valid.sum())
        if applied:
            if self.horizon == 0:
                # latest-wins in arrival order (numpy fancy-assign keeps
                # the last duplicate) — the JSONL listener's semantics
                self._latest[pos[valid]] = rows["value"][valid]
                self._latest_ts = max(self._latest_ts,
                                      int(ts_rows[valid].max()))
            else:
                # only rows that actually LANDED in a bucket count as
                # ingested (late drops are drops, not successes; rows a
                # later backpressure eviction removes were genuinely
                # accepted and ride the backpressure counter instead)
                applied = self._bucket_rows(
                    pos[valid], rows["value"][valid], ts_rows[valid],
                    prev_max)
            self.rows_applied += applied
            self._obs_rows.inc(applied)
        # journal capture: the raw frame reproduces this application
        # exactly iff nothing was truncated (unknown rows are dropped
        # identically at replay, so they don't break purity)
        if self.horizon == 0 and self._tick_pure:
            if self._tick_frame_rows + n <= self.MAX_TICK_FRAME_ROWS:
                self._tick_frames.append(fr.raw)
                self._tick_frame_rows += n
            else:
                self._tick_pure = False

    def _bucket_rows(self, pos, values, ts_rows, prev_max: int) -> int:
        """Scatter rows into their per-timestamp buckets -> rows landed."""
        floor = self._emit_floor
        if floor is not None:
            late = ts_rows <= floor
            n_late = int(late.sum())
            if n_late:
                # beyond the horizon: that tick slot was already emitted
                self.rows_late_dropped += n_late
                self._obs_late.inc(n_late)
                keep = ~late
                pos, values, ts_rows = pos[keep], values[keep], ts_rows[keep]
                if not len(pos):
                    return 0
        # late relative to data seen BEFORE this frame (an on-time
        # frame whose rows span several seconds is not backfill)
        backfilled = int((ts_rows < prev_max).sum())
        if backfilled:
            self.rows_backfilled += backfilled
            self._obs_backfilled.inc(backfilled)
        for ts in np.unique(ts_rows):
            m = ts_rows == ts
            b = self._buckets.get(int(ts))
            if b is None:
                b = self._buckets[int(ts)] = [
                    np.full(self._table.n, np.nan, np.float32), 0]
            b[0][pos[m]] = values[m]
            b[1] += int(m.sum())
        # drop-oldest backpressure: pending buckets are bounded; the
        # freshest data wins (a stalled consumer must not grow host
        # memory, and real-time serving prefers now over then)
        while len(self._buckets) > self.max_pending:
            oldest = min(self._buckets)
            _vec, nrows = self._buckets.pop(oldest)
            self.rows_backpressure_dropped += nrows
            self._obs_backpressure.inc(nrows)
            self._emit_floor = max(self._emit_floor or 0, oldest)
        return len(pos)

    # ---- local/shm ingestion -----------------------------------------
    def feed_frames(self, blobs) -> None:
        """Apply raw frame bytes in-process (co-located producers and
        the deterministic soak feeders; same validation/admission path
        as the socket)."""
        for blob in blobs:
            frames = self._local_walker.feed(blob)
            with self._lock:
                for fr in frames:
                    self._apply(fr)

    def _drain_ring(self) -> None:
        if self._ring is None:
            return
        while True:
            data = self._ring.drain()
            if not data:
                return
            frames = self._local_walker.feed(data)
            with self._lock:
                for fr in frames:
                    self._apply(fr)

    # ---- the live_loop source contract -------------------------------
    def __call__(self, tick: int):
        """Snapshot AND DRAIN (horizon 0) or emit the due backfill
        bucket(s) (horizon H): see module docstring."""
        self._drain_ring()
        with self._lock:
            if self.horizon == 0:
                values = self._latest.copy()
                self._latest[:] = np.nan
                ts = self._latest_ts or int(time.time())
                if self._tick_pure and self._tick_frames:
                    self._last_tick_frames = self._tick_frames
                else:
                    # synthesis is LAZY (take_tick_frames): a serve
                    # without a journal must not pay a pack + crc pass
                    # per tick for frames nothing will ever read
                    self._last_tick_frames = ("synth", values, ts)
            else:
                values, ts = self._emit_due()
                self._last_tick_frames = ("synth", values, ts)
            self._tick_frames = []
            self._tick_frame_rows = 0
            self._tick_pure = True
            self._obs_tenants.set(len(self._tenant_used))
            self._tenant_used.clear()
            self._obs_buffered.set(
                sum(b[1] for b in self._buckets.values()))
        self.sync_obs()
        return values, ts

    def _emit_due(self):
        """Merge + pop every bucket at/below the watermark (newest row
        ts minus the horizon); ascending ts, newer wins per stream."""
        watermark = self._max_row_ts - self.horizon
        due = sorted(t for t in self._buckets if t <= watermark)
        if not due:
            ts = self._emit_floor or self._latest_ts or int(time.time())
            return np.full(self._table.n, np.nan, np.float32), ts
        merged = np.full(self._table.n, np.nan, np.float32)
        for t in due:
            vec, _n = self._buckets.pop(t)
            # presence = not-NaN, NOT isfinite: a producer may push inf
            # (legal f32) and it must survive to scoring and replay
            m = ~np.isnan(vec)
            merged[m] = vec[m]
        self._emit_floor = due[-1]
        self._latest_ts = max(self._latest_ts, due[-1])
        # stage surface: the hold the horizon imposed on this emission
        # (newest data seen minus the tick just released, ~= horizon)
        self._release_hold = float(max(0, self._max_row_ts - due[-1]))
        return merged, due[-1]

    def _synth_frames(self, values, ts) -> list[bytes]:
        """One compact DATA frame reproducing an emitted vector exactly
        (used when raw passthrough would not: backfill merges, quota
        truncation, membership changes, overflow)."""
        # not-NaN, NOT isfinite: an emitted inf must replay as inf or
        # the journal's bit-exactness contract breaks on that tick
        m = ~np.isnan(values)
        if not m.any():
            return []
        return [data_frame(self._table.codes[m], values[m], int(ts))]

    def take_tick_frames(self) -> list[bytes]:
        """The raw DATA frames whose replay reproduces the LAST emitted
        tick bit-identically — the journal's cheap write-ahead payload
        (service/loop.py calls this right after the source poll).
        Ticks whose emission was not a pure frame replay synthesize one
        compact frame here, lazily — only journal users pay for it."""
        out = self._last_tick_frames
        self._last_tick_frames = None
        if isinstance(out, tuple):
            _tag, values, ts = out
            return self._synth_frames(values, ts)
        return out or []

    # ---- detection-latency stage surfaces (obs/latency.py probes) ----
    @property
    def last_arrival_lag_s(self) -> float | None:
        """Wire-transit lag of the freshest DATA frame (arrival wall
        clock minus its newest row's source ts, clamped >= 0); None
        before any data arrived. Lock-free: the (wall, ts) pair is one
        atomically-rebound tuple, so a concurrent handler write can at
        worst make this one frame stale, never mismatched."""
        pair = self._arrival
        if pair is None:
            return None
        wall, ts = pair
        return max(0.0, wall - ts)

    @property
    def last_release_hold_s(self) -> float | None:
        """Backfill hold of the last emitted tick (newest row ts seen
        minus the released tick's ts); None in latest-wins mode."""
        return self._release_hold

    # ---- health surface (serve stats line parity with TcpJsonlSource)
    @property
    def records_parsed(self) -> int:
        return self.rows_applied

    @property
    def parse_errors(self) -> int:
        with self._lock:
            bad = self._walker_sum("bad_crc", "_dead_bad_crc") \
                + self._local_walker.bad_crc
            skew = self._walker_sum("version_skew", "_dead_skew") \
                + self._local_walker.version_skew
        # mirrored into the registry lazily (walker tallies live on the
        # per-connection objects; this property is the per-tick surface)
        return bad + skew

    @property
    def garbage_bytes(self) -> int:
        with self._lock:
            return self._walker_sum("garbage_bytes", "_dead_garbage") \
                + self._local_walker.garbage_bytes

    @property
    def unknown_ids(self) -> int:
        return self.rows_unknown

    @property
    def native_active(self) -> bool:
        return bool(self._walker_native)

    def sync_obs(self) -> None:
        """Once-per-tick delta sync of walker-level tallies (bad CRC,
        version skew, garbage bytes) into the registry counters — the
        walkers tally on per-connection objects for hot-path cheapness,
        like the JSONL listener's C counters."""
        synced = getattr(self, "_obs_synced",
                         {"bad": 0, "skew": 0, "garbage": 0})
        with self._lock:
            bad = self._walker_sum("bad_crc", "_dead_bad_crc") \
                + self._local_walker.bad_crc
            skew = self._walker_sum("version_skew", "_dead_skew") \
                + self._local_walker.version_skew
            garbage = self._walker_sum("garbage_bytes", "_dead_garbage") \
                + self._local_walker.garbage_bytes
        self._obs_bad_frames.inc(max(0, bad - synced["bad"]))
        self._obs_version_skew.inc(max(0, skew - synced["skew"]))
        self._obs_garbage.inc(max(0, garbage - synced["garbage"]))
        self._obs_synced = {"bad": bad, "skew": skew, "garbage": garbage}
