"""Host-side drain-style log-template miner (ISSUE 9 encoder family).

The log-template encoder of "Encoding Data for HTM Systems" needs a
stable line -> template-id map: the HTM sees the TEMPLATE (the fixed
part of a log line) as a categorical field, while the variable parts
(ids, counts, addresses) are masked out. This is the Drain algorithm's
fixed-depth parse tree, compacted for the ingest boundary:

1. tokenize on whitespace; tokens containing digits mask to ``<*>``
   up front (Drain's preprocessing — variables are overwhelmingly
   numeric-ish);
2. group by token COUNT, then descend a fixed-depth prefix tree keyed
   by the first ``depth`` masked tokens (wildcards collapse);
3. inside a leaf, match against existing templates by token-equality
   similarity; >= ``sim_threshold`` merges (differing tokens become
   ``<*>``), below it mints a new template id.

Ids are dense ints in FIRST-SEEN order, so a replayed line sequence
reproduces the same ids — the determinism the journal/crash story
needs. The miner is bounded: beyond ``max_templates`` new structures
fold into the OVERFLOW id (counted, never dropped silently), keeping a
hostile/log4j-ish firehose from growing host memory without bound.

The miner runs at the ingest boundary (lines in, template-id floats
out via :meth:`encode_values`); everything downstream — journal,
scoring, replay — sees only the numeric id stream, so the wire/replay
bit-exactness contracts are untouched by construction.
"""

from __future__ import annotations

from dataclasses import dataclass, field

__all__ = ["TemplateMiner", "WILDCARD"]

WILDCARD = "<*>"


def _mask(token: str) -> str:
    """Drain preprocessing: any token carrying a digit is a variable."""
    return WILDCARD if any(ch.isdigit() for ch in token) else token


@dataclass
class _Template:
    tid: int
    tokens: list[str]
    count: int = 0


@dataclass
class TemplateMiner:
    """Stable log-line -> template-id mapping (see module docstring).

    ``observe(line)`` returns the line's template id (minting one for a
    new structure); ``template(tid)`` renders the learned template
    string. ``encode_values`` is the ingest-boundary adapter: lines in,
    float ids out, ready to feed a categorical composite field.
    """

    depth: int = 4
    sim_threshold: float = 0.5
    max_templates: int = 4096

    _templates: list[_Template] = field(default_factory=list)
    #: prefix-tree: (token_count, tok0..tokD) -> list of template indices
    _tree: dict[tuple, list[int]] = field(default_factory=dict)
    #: lines that fell into the overflow bucket (capacity exhausted)
    overflow: int = 0
    lines_seen: int = 0

    def __post_init__(self) -> None:
        if self.depth < 1:
            raise ValueError(f"depth must be >= 1; got {self.depth}")
        if not 0.0 < self.sim_threshold <= 1.0:
            raise ValueError(
                f"sim_threshold must be in (0, 1]; got {self.sim_threshold}")
        if self.max_templates < 2:
            raise ValueError(
                f"max_templates must be >= 2 (one id is the overflow "
                f"bucket); got {self.max_templates}")

    # ---- core ----
    @property
    def overflow_id(self) -> int:
        """The id every beyond-capacity structure folds into."""
        return self.max_templates - 1

    def n_templates(self) -> int:
        return len(self._templates)

    def observe(self, line: str) -> int:
        """Mine one line -> its (possibly fresh) template id."""
        self.lines_seen += 1
        tokens = [_mask(t) for t in line.split()]
        if not tokens:
            tokens = [WILDCARD]
        key = (len(tokens),
               *(tokens[i] if i < len(tokens) else "" for i in range(self.depth)))
        leaf = self._tree.get(key)
        if leaf is None:
            leaf = self._tree[key] = []
        best, best_sim = None, -1.0
        for ti in leaf:
            t = self._templates[ti]
            same = sum(1 for a, b in zip(t.tokens, tokens) if a == b)
            sim = same / len(tokens)
            if sim > best_sim:
                best, best_sim = t, sim
        if best is not None and best_sim >= self.sim_threshold:
            # merge: positions that disagree become wildcards (the
            # template generalizes as variable positions reveal themselves)
            best.tokens = [a if a == b else WILDCARD
                           for a, b in zip(best.tokens, tokens)]
            best.count += 1
            return best.tid
        if len(self._templates) >= self.max_templates - 1:
            # capacity: fold into the overflow bucket, loudly countable —
            # an unbounded template population is an attack shape, not a
            # workload (docs/WORKLOADS.md sizing note)
            self.overflow += 1
            return self.overflow_id
        t = _Template(tid=len(self._templates), tokens=list(tokens), count=1)
        self._templates.append(t)
        leaf.append(t.tid)
        return t.tid

    def template(self, tid: int) -> str:
        """Render a learned template (the overflow id renders as such)."""
        if tid == self.overflow_id and tid >= len(self._templates):
            return "<overflow>"
        return " ".join(self._templates[tid].tokens)

    def encode_values(self, lines: list[str]) -> list[float]:
        """Ingest-boundary adapter: log lines -> template-id floats, ready
        to feed a categorical composite field (resolution 1.0: the id IS
        the bucket)."""
        return [float(self.observe(ln)) for ln in lines]

    def stats(self) -> dict:
        return {
            "templates": len(self._templates),
            "lines_seen": self.lines_seen,
            "overflow": self.overflow,
            "top": sorted(
                ({"tid": t.tid, "count": t.count,
                  "template": " ".join(t.tokens)}
                 for t in self._templates),
                key=lambda d: -d["count"])[:10],
        }
