"""Wire-speed ingest (ROADMAP item 5, ISSUE 7): the production front end
that replaces per-record JSON with a zero-copy binary batch protocol.

Layers (each its own module, host-only — no accelerator dependency):

- :mod:`rtap_tpu.ingest.protocol` — the versioned ``RB1`` length-prefixed
  CRC-framed batch format (packed ``(slot_u32, value_f32, ts_delta_u16)``
  rows), the (shard, group, slot) slot-code packing, and the frame
  walker (native C fast path, pure-Python fallback).
- :mod:`rtap_tpu.ingest.dispatch` — the registry slot map rendered as a
  vectorized code -> dispatch-position table (``np.frombuffer`` rows
  scatter straight into per-(group, slot) dispatch buffers with zero
  per-record Python).
- :mod:`rtap_tpu.ingest.shm` — the shared-memory frame ring for
  co-located exporters (same frames, no socket).
- :mod:`rtap_tpu.ingest.server` — :class:`BinaryBatchSource`, the
  live_loop source: persistent-socket listener + optional shm drain,
  ingest-side timestamp alignment/backfill, and admission control
  (per-tenant quotas, drop-oldest backpressure) wired into
  ``rtap_obs_ingest_*`` telemetry.
- :mod:`rtap_tpu.ingest.templates` — the drain-style log-template miner
  (ISSUE 9): log lines -> stable template ids at the ingest boundary,
  feeding the categorical/log-template composite encoder fields.
- :mod:`rtap_tpu.ingest.emit` — producer-side helpers
  (:func:`send_binary`, :class:`BinaryFeedConnection`), the
  ``send_jsonl`` twin the soak feeders use.

docs/INGEST.md is the operator runbook (frame layout, endianness,
versioning rules, backfill semantics, quota/backpressure).
"""

from rtap_tpu.ingest.dispatch import DispatchTable
from rtap_tpu.ingest.emit import BinaryFeedConnection, send_binary
from rtap_tpu.ingest.protocol import (
    KIND_DATA,
    KIND_MAP,
    KIND_NAMES,
    PROTOCOL_VERSION,
    FrameWalker,
    build_frame,
    decode_slot,
    encode_slot,
    pack_rows,
)
from rtap_tpu.ingest.server import BinaryBatchSource
from rtap_tpu.ingest.shm import ShmRing
from rtap_tpu.ingest.templates import TemplateMiner

__all__ = [
    "BinaryBatchSource",
    "TemplateMiner",
    "BinaryFeedConnection",
    "DispatchTable",
    "FrameWalker",
    "KIND_DATA",
    "KIND_MAP",
    "KIND_NAMES",
    "PROTOCOL_VERSION",
    "ShmRing",
    "build_frame",
    "decode_slot",
    "encode_slot",
    "pack_rows",
    "send_binary",
]
