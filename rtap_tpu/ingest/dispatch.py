"""Registry slot map -> vectorized wire-code dispatch (ROADMAP-1 shape).

The registry hands sources a SLOT MAP — ``{stream_id: SlotAddress(shard,
group, slot)}`` (service/registry.py) — instead of a flat id list: the
addressing every pod-scale design needs (a shard owns groups, a group
owns slots; a flat id registry cannot express placement). This module
renders that map as dense numpy lookup tables so a frame's packed rows
route to their (group, slot) dispatch positions with two fancy-index
operations and zero per-record Python.
"""

from __future__ import annotations

import numpy as np

from rtap_tpu.ingest.protocol import (
    MAX_GROUPS,
    MAX_SHARDS,
    MAX_SLOTS,
    SLOT_BITS,
    encode_slot,
)


class DispatchTable:
    """Bidirectional (shard, group, slot) <-> dispatch-position tables.

    ``ids``/``codes`` follow the registry's dispatch order (the value-
    vector order live_loop routes by); ``lookup`` maps wire slot codes to
    dispatch positions (-1 for codes that address no live stream —
    pads, released slots, or garbage), vectorized over whole frames.
    """

    def __init__(self, slot_map: dict):
        # dispatch order = (group, slot) ascending, matching
        # StreamGroupRegistry.dispatch_ids() (live slots per group in
        # slot order) — pinned by tests/unit/test_ingest_protocol.py
        items = sorted(slot_map.items(),
                       key=lambda kv: (kv[1].group, kv[1].slot))
        self.ids: list[str] = [sid for sid, _ in items]
        self.codes = np.array(
            [encode_slot(a.shard, a.group, a.slot) for _, a in items],
            np.uint32)
        self.code_of = {sid: int(c) for sid, c in zip(self.ids, self.codes)}
        self.n = len(self.ids)
        # dense [n_groups, max_slot+1] -> dispatch position (or -1):
        # group/slot extents come from the map, so the table is sized to
        # the fleet, not to the 14-bit code space
        n_groups = 1 + max((a.group for _, a in items), default=0)
        n_slots = 1 + max((a.slot for _, a in items), default=0)
        self._dense = np.full((n_groups, n_slots), -1, np.int64)
        for pos, (_sid, a) in enumerate(items):
            self._dense[a.group, a.slot] = pos
        self._gmask = np.uint32(MAX_GROUPS - 1)
        self._smask = np.uint32(MAX_SLOTS - 1)

    def lookup(self, codes: np.ndarray) -> np.ndarray:
        """Wire codes [N] u32 -> dispatch positions [N] i64 (-1 = no
        live stream at that address). Shard bits are part of the
        address: a code whose (group, slot) exists but whose shard
        disagrees with the map is rejected too."""
        codes = np.asarray(codes, np.uint32)
        g = (codes >> np.uint32(SLOT_BITS)) & self._gmask
        s = codes & self._smask
        ok = (g < self._dense.shape[0]) & (s < self._dense.shape[1])
        pos = np.full(codes.shape, -1, np.int64)
        idx = self._dense[g[ok], s[ok]]
        # full-code check catches wrong-shard (and any future reserved-
        # bit) addressing without a separate per-row comparison pass
        # when everything matches
        valid = idx >= 0
        sel = idx[valid]
        valid[valid] = self.codes[sel] == codes[ok][valid]
        out = np.full(int(ok.sum()), -1, np.int64)
        out[valid] = idx[valid]
        pos[ok] = out
        return pos

    @classmethod
    def from_registry(cls, reg) -> "DispatchTable":
        return cls(reg.slot_map())


def decode_frames_to_row(blobs, width: int, table: DispatchTable) -> np.ndarray:
    """Journal-replay decode: apply raw DATA frame bytes in order onto
    a NaN row of ``width`` — exactly the ingest-time scatter, re-run,
    so a journaled binary tick replays bit-identically
    (resilience/journal.py FRAME records; service/loop.py calls this).

    Raises ValueError on a width mismatch (membership changed without a
    checkpoint boundary — the caller skips the row, counted)."""
    from rtap_tpu.ingest.protocol import KIND_DATA, FrameWalker

    if width != table.n:
        raise ValueError(
            f"journaled frame width {width} != dispatch width {table.n}")
    values = np.full(width, np.nan, np.float32)
    walker = FrameWalker(native=False)  # replay is cold-path
    for blob in blobs:
        for fr in walker.feed(blob):
            if fr.kind != KIND_DATA:
                continue
            rows = fr.rows()
            pos = table.lookup(rows["slot"])
            valid = pos >= 0
            values[pos[valid]] = rows["value"][valid]
    return values
