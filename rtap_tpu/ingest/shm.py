"""Shared-memory frame ring: the co-located exporter transport.

An exporter on the SAME host should not pay the socket stack to hand
frames to serve. The ring is a single-producer single-consumer byte
queue in POSIX shared memory carrying the exact same ``RB1`` frames as
the socket path (protocol.py) — the consumer drains it straight into
the same frame walker, so every validation/admission rule is shared.

Layout (little-endian, 64-byte header)::

    0   8   magic    b"RBSHRING"
    8   8   capacity data-region bytes
    16  8   head     producer write cursor (monotonic byte count)
    24  8   tail     consumer read cursor (monotonic byte count)
    32  32  reserved
    64  ..  data     ring bytes (frames wrap byte-wise)

``head``/``tail`` are monotonic u64s; ``head - tail`` is the unread
byte count. The producer refuses (returns False) when a frame does not
fit — drop-newest at the transport, counted by the producer; the
consumer's admission control owns the drop-oldest policy above this.

Memory-ordering contract: cursor updates are 8-byte aligned stores
issued AFTER the data bytes, which is safe cross-process on
total-store-order hosts (x86/x86-64 — every deployment target today).
On weakly-ordered architectures (ARM64) a consumer could observe a
cursor before the bytes it covers; the CRC framing DETECTS that (the
walker counts the torn read as garbage/bad-CRC, never accepts it) but
the affected frame is lost — co-located exporters on such hosts should
use the socket transport until a fenced ring lands (docs/INGEST.md).
One producer and one consumer per ring; multi-producer setups run one
ring per producer.
"""

from __future__ import annotations

import struct
from multiprocessing import shared_memory

_MAGIC = b"RBSHRING"
_HDR = 64
_U64 = struct.Struct("<Q")


class ShmRing:
    """Create or attach one frame ring. The creator owns unlink()."""

    def __init__(self, shm: shared_memory.SharedMemory, owner: bool):
        self._shm = shm
        self._owner = owner
        buf = shm.buf
        if bytes(buf[:8]) != _MAGIC:
            raise ValueError(
                f"shm segment {shm.name!r} is not an RB ring (bad magic)")
        (self.capacity,) = _U64.unpack_from(buf, 8)
        if _HDR + self.capacity > len(buf):
            raise ValueError(f"shm segment {shm.name!r} truncated")
        self.pushed = 0
        self.push_rejected = 0  # frames that did not fit (producer side)

    # ---- lifecycle ---------------------------------------------------
    @classmethod
    def create(cls, name: str, capacity: int = 8 << 20) -> "ShmRing":
        if capacity < 4096:
            raise ValueError(f"ring capacity must be >= 4096; got {capacity}")
        shm = shared_memory.SharedMemory(
            name=name, create=True, size=_HDR + capacity)
        shm.buf[:_HDR] = bytes(_HDR)
        shm.buf[:8] = _MAGIC
        _U64.pack_into(shm.buf, 8, capacity)
        return cls(shm, owner=True)

    @classmethod
    def attach(cls, name: str) -> "ShmRing":
        shm = shared_memory.SharedMemory(name=name)
        # CPython < 3.13 registers EVERY SharedMemory with the process's
        # resource_tracker, owner or not — an attaching exporter exiting
        # would unlink the ring out from under serve (and every future
        # attacher). Only the creator may own the name's lifetime.
        try:
            from multiprocessing import resource_tracker

            resource_tracker.unregister(shm._name, "shared_memory")
        except Exception:  # rtap: allow[except-silent] — tracker API
            # moved (3.13+ track=False) or absent; unregister is a
            # CPython-version workaround, never load-bearing
            pass
        return cls(shm, owner=False)

    @property
    def name(self) -> str:
        return self._shm.name

    def close(self) -> None:
        try:
            self._shm.close()
            if self._owner:
                self._shm.unlink()
        except (OSError, FileNotFoundError):  # rtap: allow[except-silent]
            pass  # teardown of an already-vanished ring (peer unlinked)

    # ---- cursors -----------------------------------------------------
    def _head(self) -> int:
        return _U64.unpack_from(self._shm.buf, 16)[0]

    def _tail(self) -> int:
        return _U64.unpack_from(self._shm.buf, 24)[0]

    @property
    def unread_bytes(self) -> int:
        return self._head() - self._tail()

    # ---- producer ----------------------------------------------------
    def push(self, frame: bytes) -> bool:
        """Append one frame's bytes; False (counted) when it does not
        fit — the producer decides whether to retry next tick."""
        n = len(frame)
        head, tail = self._head(), self._tail()
        if n > self.capacity - (head - tail):
            self.push_rejected += 1
            return False
        pos = head % self.capacity
        first = min(n, self.capacity - pos)
        buf = self._shm.buf
        buf[_HDR + pos:_HDR + pos + first] = frame[:first]
        if first < n:
            buf[_HDR:_HDR + n - first] = frame[first:]
        # cursor store strictly after the data: the consumer never
        # observes a head covering bytes it cannot read
        _U64.pack_into(buf, 16, head + n)
        self.pushed += 1
        return True

    # ---- consumer ----------------------------------------------------
    def drain(self, max_bytes: int = 1 << 22) -> bytes:
        """Pop up to max_bytes of unread ring bytes (possibly mid-frame;
        the frame walker owns reassembly)."""
        head, tail = self._head(), self._tail()
        n = min(head - tail, max_bytes)
        if n <= 0:
            return b""
        pos = tail % self.capacity
        first = min(n, self.capacity - pos)
        buf = self._shm.buf
        out = bytes(buf[_HDR + pos:_HDR + pos + first])
        if first < n:
            out += bytes(buf[_HDR:_HDR + n - first])
        _U64.pack_into(buf, 24, tail + n)
        return out
