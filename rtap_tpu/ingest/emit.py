"""Producer-side binary ingest helpers — ``send_jsonl``'s wire-speed twin.

:func:`send_binary` keeps send_jsonl's exact calling convention (records
as ``{"id", "value", "ts"}`` dicts, returns the delivered count, bounded
retry) so the soak feeders and tests can switch transports with a flag;
:class:`BinaryFeedConnection` is the persistent-connection form the
paced live_soak feeder uses (connect once, push one vectorized frame
per tick — no per-record Python on the producer either).

Both learn the id -> slot-code map from the listener itself: a
:class:`~rtap_tpu.ingest.server.BinaryBatchSource` greets every
connection with a MAP frame, and an empty MAP frame re-requests it
(after serve --auto-register claims announced NAMES).
"""

from __future__ import annotations

import json
import socket

import numpy as np

from rtap_tpu.ingest.protocol import (
    KIND_MAP,
    KIND_NAMES,
    FrameWalker,
    build_frame,
    data_frame,
)

#: rows per DATA frame — bounds what one mid-stream connection drop can
#: leave in doubt, like send_jsonl's _SEND_BATCH
_SEND_BATCH = 4096


class BinaryFeedConnection:
    """One persistent producer connection: MAP handshake, vectorized
    DATA frames, NAMES announcements, MAP refresh."""

    def __init__(self, address, timeout_s: float = 5.0, tenant: str = ""):
        self.tenant = tenant
        self._sock = socket.create_connection(address, timeout=timeout_s)
        self._walker = FrameWalker(native=False)  # map frames are rare
        self.code_of: dict[str, int] = {}
        self.epoch = 0  # the map's epoch; stamped into every DATA frame
        # so the listener can refuse frames built from a stale map
        self.leader_hint: str | None = None  # "host:port" of the NEW
        # leader, when the listener we are talking to lost a failover
        # (ISSUE 8 __leader__ MAP field); send_binary follows it
        self._read_map()

    def _adopt_map(self, fr) -> None:
        blob = json.loads(bytes(fr.payload))
        self.epoch = int(blob.pop("__epoch__", 0))
        hint = blob.pop("__leader__", None)
        if hint:
            self.leader_hint = str(hint)
        self.code_of = {k: int(v) for k, v in blob.items()}

    def _read_map(self) -> None:
        # the constructor's timeout governs every wait on this socket —
        # map reads must not shorten a caller's stall tolerance
        while True:
            data = self._sock.recv(1 << 16)
            if not data:
                raise ConnectionError("listener closed before MAP frame")
            for fr in self._walker.feed(data):
                if fr.kind == KIND_MAP and fr.count:
                    self._adopt_map(fr)
                    return

    def refresh_map(self) -> None:
        """Re-request the map (e.g. after NAMES announcements were
        claimed by serve --auto-register)."""
        self._sock.sendall(build_frame(KIND_MAP, b""))
        self._read_map()

    def poll_map(self) -> bool:
        """Drain any MAP frames the listener PUSHED (it pushes on every
        membership change, so epochs propagate without a request) ->
        True if the map changed. Non-blocking; call before each send so
        a fleet-wide epoch bump elsewhere never leaves this producer
        stamping a stale epoch."""
        changed = False
        prev_timeout = self._sock.gettimeout()
        self._sock.setblocking(False)
        try:
            while True:
                try:
                    data = self._sock.recv(1 << 16)
                except (BlockingIOError, InterruptedError):
                    break
                if not data:
                    raise ConnectionError("listener closed")
                for fr in self._walker.feed(data):
                    if fr.kind == KIND_MAP and fr.count:
                        self._adopt_map(fr)
                        changed = True
        finally:
            self._sock.settimeout(prev_timeout)
        return changed

    def send_names(self, ids) -> None:
        """Announce unknown stream ids (the auto-register protocol)."""
        blob = "\n".join(ids).encode("utf-8")
        self._sock.sendall(build_frame(KIND_NAMES, blob, tenant=self.tenant))

    def send_rows(self, ids, values, ts: int, deltas=0) -> int:
        """Push one frame of aligned (ids, values) at base timestamp
        ``ts``; unknown ids are skipped (returned count = rows sent)."""
        codes = np.array([self.code_of.get(s, -1) for s in ids], np.int64)
        known = codes >= 0
        n = int(known.sum())
        if n:
            self._sock.sendall(data_frame(
                codes[known].astype(np.uint32),
                np.asarray(values, np.float32)[known], ts,
                deltas=np.broadcast_to(
                    np.asarray(deltas, np.uint16), codes.shape)[known],
                tenant=self.tenant, epoch=self.epoch))
        return n

    def send_frame(self, frame: bytes) -> None:
        self._sock.sendall(frame)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self) -> "BinaryFeedConnection":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _split_by_ts_span(batch) -> list[tuple[list, int]]:
    """Cut a record batch into (sub-batch, base_ts) runs whose
    timestamps fit the u16 row delta — a backfill batch spanning more
    than ~18 h must be delivered with exact timestamps across several
    frames, never clamped hours wrong. Order is preserved (latest-wins
    routing depends on it). Records without a ts adopt the running
    sub-batch's base (one ts-less record must not drag a batch's base
    to 0 and wreck every real timestamp)."""
    out: list[tuple[list, int]] = []
    cur: list = []
    lo = hi = None
    for r in batch:
        ts = int(r["ts"]) if "ts" in r else None
        if ts is None:
            cur.append(r)
            continue
        nlo = ts if lo is None else min(lo, ts)
        nhi = ts if hi is None else max(hi, ts)
        if nhi - nlo > 65535 and cur:
            out.append((cur, lo if lo is not None else 0))
            cur, lo, hi = [], ts, ts
        else:
            lo, hi = nlo, nhi
        cur.append(r)
    if cur:
        out.append((cur, lo if lo is not None else 0))
    return out


def send_binary(address, records, retry=None, tenant: str = "") -> int:
    """send_jsonl's binary twin: push ``{"id", "value", "ts"}`` records
    to a BinaryBatchSource listener -> count handed to the kernel.

    Ids absent from the listener's map are announced in a NAMES frame
    (claim candidates under --auto-register) and do NOT count as
    delivered — the caller retries them next call, by which time the
    fresh connection's MAP reflects any claims. Connection failures get
    bounded exponential backoff like send_jsonl; delivery is
    at-least-once across retries (harmless against latest-wins rows).
    """
    from rtap_tpu.resilience.policies import Retry

    if retry is None:
        retry = Retry(attempts=4, base_delay_s=0.05, max_delay_s=0.5,
                      op="send_binary")
    delivered = 0
    sent_names = False
    next_batch = 0
    redirected = False
    batches = [records[i:i + _SEND_BATCH]
               for i in range(0, len(records), _SEND_BATCH)]
    attempt = 0
    while attempt < retry.attempts:
        try:
            with BinaryFeedConnection(address, tenant=tenant) as conn:
                if conn.leader_hint and not redirected:
                    # the listener lost a failover and named its
                    # successor (ISSUE 8): re-point ONCE — the hinted
                    # leader's own map is authoritative from here on.
                    # A successful control exchange, NOT a failure: it
                    # must not burn a retry attempt (a hint on the last
                    # attempt still gets its shot at the new leader)
                    host, _sep, port = conn.leader_hint.rpartition(":")
                    if host and port.isdigit():
                        address = (host, int(port))
                        redirected = True
                        continue
                if not sent_names:
                    unknown = sorted({str(r["id"]) for r in records
                                      if r["id"] not in conn.code_of})
                    if unknown:
                        conn.send_names(unknown)
                        sent_names = True
                while next_batch < len(batches):
                    batch = batches[next_batch]
                    sent = 0
                    for sub, ts0 in _split_by_ts_span(batch):
                        sent += conn.send_rows(
                            [r["id"] for r in sub],
                            [r["value"] for r in sub], ts0,
                            deltas=[int(r.get("ts", ts0)) - ts0
                                    for r in sub])
                    # counted only once the WHOLE batch went out: a
                    # drop mid-batch resends it whole (at-least-once,
                    # harmless vs latest-wins) without double-counting
                    delivered += sent
                    next_batch += 1
            return delivered
        except OSError:
            attempt += 1
            if attempt >= retry.attempts:
                return delivered
            retry.backoff(attempt)
    return delivered
