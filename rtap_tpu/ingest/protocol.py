"""The ``RB1`` binary batch frame: format, slot-code packing, frame walker.

Per-record JSON saturates the host ingest edge near ~100k metrics/s
(reports/ingest_bench.json) — far below what one chip can score. This
protocol moves the per-record cost to the producer: a frame carries a
whole batch of packed 10-byte rows that the consumer decodes with ONE
``np.frombuffer`` call and scatters with ONE fancy-index assignment;
nothing on the hot path touches a Python object per record.

Frame layout (little-endian throughout; docs/INGEST.md is the operator
reference)::

    offset size  field
    0      3     magic      b"RB1"
    3      1     version    PROTOCOL_VERSION (1)
    4      1     kind       1=DATA  2=NAMES  3=MAP
    5      1     tenant_len bytes of tenant id following the header
    6      2     epoch      map epoch the frame's slot codes came from
                            (0 = epoch-unaware producer, always admitted)
    8      4     count      DATA: row count; NAMES/MAP: payload bytes
    12     8     base_ts    unix seconds rows are relative to (DATA)
    20     T     tenant     tenant_len bytes, UTF-8
    20+T   P     payload    DATA: count * 10 bytes of packed rows
                            NAMES: newline-joined UTF-8 stream ids
                            MAP:   JSON {"__epoch__": N, stream_id: code}
    20+T+P 4     crc32      zlib.crc32 over bytes [3, 20+T+P)

The map EPOCH closes the stale-code wormhole: slot codes are positional,
so a slot released and re-claimed by a NEW stream reuses the old code —
a producer still sending with a cached map would silently feed the new
stream's model (the string-id JSONL path cannot mis-deliver this way).
Every MAP hello carries the current epoch; producers stamp it into their
DATA frames; the consumer drops whole frames whose nonzero epoch
disagrees with the current map (counted,
``rtap_obs_ingest_stale_epoch_total``) — a stale producer goes loudly
deaf instead of silently corrupting a stranger's model.

A DATA row is ``slot_code u32 | value f32 | ts_delta u16`` (10 bytes,
packed). ``row ts = base_ts + ts_delta`` — a frame spans at most ~18 h
of timestamps, far beyond any backfill horizon. The slot code packs the
registry's (shard, group, slot) address (:func:`encode_slot`):
8 shard bits | 12 group bits | 12 slot bits.

Versioning rules (docs/INGEST.md): the FRAMING fields — magic, version,
kind, tenant_len, count, base_ts positions and the trailing crc32 — are
frozen for the life of the ``RB1`` magic, so any parser can delimit and
CRC-check a frame whose version or kind it does not understand; such
frames are skipped whole and counted (``version_skew``), never treated
as garbage. Layout-incompatible changes must bump the magic (``RB2``).

The frame walker (stream -> validated frames) has a native C fast path
(rtap_tpu/native/frame_walker.c, same build/fallback discipline as the
JSONL parser) and a pure-Python fallback with identical semantics —
torn tails wait for more bytes, bad magic resyncs to the next magic
(counted as garbage bytes), CRC mismatches skip the frame.
"""

from __future__ import annotations

import struct
import zlib
from dataclasses import dataclass

import numpy as np

PROTOCOL_VERSION = 1
MAGIC = b"RB1"

KIND_DATA = 1
KIND_NAMES = 2
KIND_MAP = 3
_KINDS = (KIND_DATA, KIND_NAMES, KIND_MAP)

HEADER = struct.Struct("<3sBBBHIq")  # magic, version, kind, tenant_len,
# epoch, count, base_ts
CRC = struct.Struct("<I")
ROW_DTYPE = np.dtype(
    [("slot", "<u4"), ("value", "<f4"), ("dt", "<u2")])  # 10 B packed
ROW_SIZE = ROW_DTYPE.itemsize
assert ROW_SIZE == 10

#: framing sanity bounds — a flipped count byte must not make the walker
#: wait forever for (or allocate) gigabytes
MAX_DATA_ROWS = 1 << 22       # 4M rows = 40 MiB payload
MAX_BLOB_BYTES = 16 << 20     # NAMES/MAP payloads

# ---- (shard, group, slot) slot-code packing --------------------------
# 8 | 12 | 12: up to 256 mesh shards (a full v5e pod slice — ROADMAP-1's
# target topology), 4096 groups, 4096 slots/group (throughput peaks at
# SMALL G — SCALING.md — so the slot budget is the loosest bound)
SHARD_BITS = 8
GROUP_BITS = 12
SLOT_BITS = 12
MAX_SHARDS = 1 << SHARD_BITS
MAX_GROUPS = 1 << GROUP_BITS
MAX_SLOTS = 1 << SLOT_BITS


def encode_slot(shard: int, group: int, slot: int) -> int:
    """Pack a registry (shard, group, slot) address into the wire u32."""
    if not (0 <= shard < MAX_SHARDS and 0 <= group < MAX_GROUPS
            and 0 <= slot < MAX_SLOTS):
        raise ValueError(
            f"slot address out of range: shard={shard} (<{MAX_SHARDS}), "
            f"group={group} (<{MAX_GROUPS}), slot={slot} (<{MAX_SLOTS})")
    return (shard << (GROUP_BITS + SLOT_BITS)) | (group << SLOT_BITS) | slot


def decode_slot(code):
    """Unpack wire code(s) -> (shard, group, slot); vectorized over
    ndarray inputs (the zero-per-record decode path)."""
    code = np.asarray(code, np.uint32)
    slot = code & (MAX_SLOTS - 1)
    group = (code >> SLOT_BITS) & (MAX_GROUPS - 1)
    shard = code >> (GROUP_BITS + SLOT_BITS)
    return shard, group, slot


# ---- frame construction (producer side) ------------------------------


def pack_rows(codes, values, deltas=0) -> bytes:
    """Vectorized row packing: aligned u32/f32/u16 arrays -> payload
    bytes. ``deltas`` broadcasts (0 = every row at base_ts)."""
    codes = np.asarray(codes, np.uint32)
    rows = np.empty(codes.shape[0], ROW_DTYPE)
    rows["slot"] = codes
    rows["value"] = np.asarray(values, np.float32)
    rows["dt"] = np.asarray(deltas, np.uint16)
    return rows.tobytes()


def build_frame(kind: int, payload: bytes, base_ts: int = 0,
                tenant: str = "", count: int | None = None,
                epoch: int = 0) -> bytes:
    """Assemble one wire frame. For DATA, ``payload`` is packed rows and
    ``count`` defaults to ``len(payload) // ROW_SIZE``; for NAMES/MAP the
    count IS the payload byte length. ``epoch`` is the map epoch the
    slot codes came from (0 = epoch-unaware, always admitted)."""
    if kind not in _KINDS:
        raise ValueError(f"unknown frame kind {kind}")
    if not (0 <= epoch <= 0xFFFF):
        raise ValueError(f"epoch must fit u16; got {epoch}")
    tb = tenant.encode("utf-8")
    if len(tb) > 255:
        raise ValueError(f"tenant id exceeds 255 UTF-8 bytes: {tenant!r}")
    if kind == KIND_DATA:
        if len(payload) % ROW_SIZE:
            raise ValueError(
                f"DATA payload not a whole number of {ROW_SIZE}-byte rows")
        n = len(payload) // ROW_SIZE if count is None else count
        if n * ROW_SIZE != len(payload):
            raise ValueError("count does not match payload length")
        if n > MAX_DATA_ROWS:
            raise ValueError(f"frame exceeds MAX_DATA_ROWS ({MAX_DATA_ROWS})")
    else:
        n = len(payload)
        if n > MAX_BLOB_BYTES:
            raise ValueError(f"blob exceeds MAX_BLOB_BYTES ({MAX_BLOB_BYTES})")
    head = HEADER.pack(MAGIC, PROTOCOL_VERSION, kind, len(tb), epoch, n,
                       int(base_ts))
    body = head + tb + payload
    return body + CRC.pack(zlib.crc32(body[3:]))


def data_frame(codes, values, base_ts: int, deltas=0,
               tenant: str = "", epoch: int = 0) -> bytes:
    """One-call DATA frame from aligned arrays (the emitter hot path)."""
    return build_frame(KIND_DATA, pack_rows(codes, values, deltas),
                       base_ts=base_ts, tenant=tenant, epoch=epoch)


# ---- frame walker (consumer side) ------------------------------------


@dataclass
class Frame:
    """One validated frame. ``raw`` is the ONE copy made per frame (the
    walker's internal buffer is consumed after the scan); ``payload``
    is a zero-copy view into it. ``raw`` is what the write-ahead
    journal appends verbatim."""

    kind: int
    tenant: str
    count: int
    base_ts: int
    raw: bytes
    _poff: int
    epoch: int = 0

    @property
    def payload(self) -> memoryview:
        plen = self.count * ROW_SIZE if self.kind == KIND_DATA \
            else self.count
        return memoryview(self.raw)[self._poff:self._poff + plen]

    def rows(self) -> np.ndarray:
        """DATA payload as a structured [count] array (one frombuffer,
        zero per-record work)."""
        return np.frombuffer(self.payload, ROW_DTYPE, count=self.count)


def _frame_len(kind: int, tenant_len: int, count: int) -> int:
    payload = count * ROW_SIZE if kind == KIND_DATA else count
    return HEADER.size + tenant_len + payload + CRC.size


def scan_frames_py(buf) -> tuple[list[tuple], int, dict]:
    """Pure-Python walker: scan ``buf`` for complete frames.

    Returns ``(metas, consumed, stats)`` where each meta is
    ``(kind, version, epoch, tenant_off, tenant_len, count, base_ts,
    payload_off)``, ``consumed`` is how many leading bytes are fully
    scanned (valid frames, skipped frames, and garbage — never a
    trailing partial frame), and ``stats`` counts
    ``{garbage_bytes, bad_crc, version_skew}``. Semantics are pinned
    against the native walker by tests/unit/test_ingest_protocol.py.
    """
    # ONE copy up front: .find() for resync must not re-copy the tail
    # per step (garbage-dense input would go quadratic in the fallback)
    data = buf if isinstance(buf, bytes) else bytes(buf)
    n = len(data)
    metas: list[tuple] = []
    off = 0
    stats = {"garbage_bytes": 0, "bad_crc": 0, "version_skew": 0}

    def _resync(pos: int) -> int:
        """Skip to the next possible magic at/after pos+1 (counted)."""
        nxt = data.find(MAGIC, pos + 1)
        skip_to = nxt if nxt != -1 else max(pos + 1, n - (len(MAGIC) - 1))
        stats["garbage_bytes"] += skip_to - pos
        return skip_to

    while off + HEADER.size <= n:
        magic, version, kind, tlen, epoch, count, base_ts = \
            HEADER.unpack_from(data, off)
        sane = (magic == MAGIC
                and (count <= MAX_DATA_ROWS if kind == KIND_DATA
                     else count <= MAX_BLOB_BYTES))
        if not sane:
            off = _resync(off)
            continue
        end = off + _frame_len(kind, tlen, count)
        if end > n:
            break  # torn tail: wait for more bytes
        (crc,) = CRC.unpack_from(data, end - CRC.size)
        if crc != zlib.crc32(data[off + 3:end - CRC.size]):
            stats["bad_crc"] += 1
            off = _resync(off)
            continue
        if version != PROTOCOL_VERSION or kind not in _KINDS:
            # framing fields are frozen across versions: skip the whole
            # frame, counted — forward compatibility, not corruption
            stats["version_skew"] += 1
            off = end
            continue
        metas.append((kind, version, epoch, off + HEADER.size, tlen, count,
                      base_ts, off + HEADER.size + tlen))
        off = end
    return metas, off, stats


def _native_scan():
    """The C walker's scan callable, or None (no toolchain — callers
    fall back to :func:`scan_frames_py`)."""
    try:
        from rtap_tpu.native import frame_walker_scan

        return frame_walker_scan
    except Exception:
        return None


class FrameWalker:
    """Incremental stream -> frames: feed() recv chunks, get validated
    :class:`Frame` objects out. Owns one connection's remainder buffer
    (bounded — an unterminated garbage stream is dropped and counted,
    never an unbounded buffer).

    ``native=None`` auto-detects the C scanner (missing toolchain falls
    back to Python); ``True`` requires it; ``False`` forces Python.
    """

    #: remainder bound: the largest legal frame plus slack; beyond it
    #: the buffer cannot possibly complete into a valid frame we accept
    MAX_BUFFER = HEADER.size + 255 + MAX_DATA_ROWS * ROW_SIZE + CRC.size

    def __init__(self, native: bool | None = None):
        self._buf = bytearray()
        self.frames = 0
        self.garbage_bytes = 0
        self.bad_crc = 0
        self.version_skew = 0
        self._scan = None
        if native is not False:
            self._scan = _native_scan()
            if native and self._scan is None:
                raise RuntimeError("native frame walker unavailable")

    @property
    def native_active(self) -> bool:
        return self._scan is not None

    def feed(self, data: bytes) -> list[Frame]:
        # fast path: no remainder pending -> scan the recv chunk in
        # place (zero copy); only the torn tail is carried over
        if self._buf:
            self._buf += data
            view = memoryview(self._buf)
            buffered = True
        else:
            view = memoryview(data)
            buffered = False
        if self._scan is not None:
            metas, consumed, stats = self._scan(view)
        else:
            metas, consumed, stats = scan_frames_py(view)
        out = []
        for kind, _ver, epoch, toff, tlen, count, base_ts, poff in metas:
            plen = count * ROW_SIZE if kind == KIND_DATA else count
            start = toff - HEADER.size
            if tlen:
                try:
                    tenant = bytes(view[toff:toff + tlen]).decode("utf-8")
                except UnicodeDecodeError:
                    tenant = ""  # tenant is accounting, not routing
            else:
                tenant = ""
            out.append(Frame(kind, tenant, count, base_ts,
                             bytes(view[start:poff + plen + CRC.size]),
                             poff - start, epoch))
        self.frames += len(out)
        self.garbage_bytes += stats["garbage_bytes"]
        self.bad_crc += stats["bad_crc"]
        self.version_skew += stats["version_skew"]
        if buffered:
            del view
            del self._buf[:consumed]
        elif consumed < len(data):
            self._buf += view[consumed:]
        if len(self._buf) > self.MAX_BUFFER:
            # cannot complete into an acceptable frame: drop + resync
            self.garbage_bytes += len(self._buf)
            self._buf.clear()
        return out
