"""Deterministic 32-bit hashing shared by host oracle and device kernels.

The reference's RDSE builds its bucket->bits map imperatively with NuPIC's
portable RNG (SURVEY.md C1/C15). Here the map is a pure hash function so the
encoder is table-free and computable on-device with no host state. The host
(numpy) and device (jax, in ops/) implementations are bit-identical — this is
what makes oracle-vs-TPU parity tests exact (SURVEY.md §4 item 2).

The mixer is MurmurHash3's 32-bit finalizer (public domain), keyed by seed.
TPU note: uses only uint32 ops (JAX x64 stays disabled).
"""

from __future__ import annotations

import numpy as np

_C1 = np.uint32(0x85EBCA6B)
_C2 = np.uint32(0xC2B2AE35)
_GOLDEN = np.uint32(0x9E3779B9)


def fmix32_np(x: np.ndarray) -> np.ndarray:
    """MurmurHash3 fmix32 finalizer over uint32 arrays (vectorized)."""
    h = x.astype(np.uint32)
    with np.errstate(over="ignore"):
        h ^= h >> np.uint32(16)
        h *= _C1
        h ^= h >> np.uint32(13)
        h *= _C2
        h ^= h >> np.uint32(16)
    return h


def hash_u32_np(key: np.ndarray, seed: int) -> np.ndarray:
    """hash(seed, key) -> uint32. key may be any integer array (cast mod 2^32)."""
    k = np.asarray(key).astype(np.int64).astype(np.uint32)
    with np.errstate(over="ignore"):
        mixed = k * _GOLDEN + np.uint32(seed)
    return fmix32_np(mixed)


def hash_bits_np(keys: np.ndarray, seed: int, n: int) -> np.ndarray:
    """Map integer keys to bit indices in [0, n). Used by the RDSE."""
    return (hash_u32_np(keys, seed) % np.uint32(n)).astype(np.int32)
