"""Platform selection helpers.

This environment's axon sitecustomize pre-imports jax and sets
``jax_platforms="axon,cpu"`` through jax.config at interpreter start, which
OVERRIDES the ``JAX_PLATFORMS`` environment variable. Consequences:

- ``JAX_PLATFORMS=cpu python script.py`` does NOT force CPU — the axon
  backend still initializes first (and hangs the process whenever the TPU
  tunnel is wedged rather than failing fast).
- The only reliable way to force CPU is ``jax.config.update`` in-process,
  BEFORE first backend use (what tests/conftest.py does for pytest).

Scripts call :func:`maybe_force_cpu` at entry so ``RTAP_FORCE_CPU=1``
gives a deterministic CPU run regardless of tunnel health.
"""

from __future__ import annotations

import os


def maybe_force_cpu(env_var: str = "RTAP_FORCE_CPU") -> bool:
    """If ``$RTAP_FORCE_CPU`` is truthy, pin jax to the CPU platform (must be
    called before any jax backend use). Returns whether CPU was forced."""
    if os.environ.get(env_var, "") not in ("", "0"):
        import jax

        jax.config.update("jax_platforms", "cpu")
        return True
    return False
