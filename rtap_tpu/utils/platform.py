"""Platform selection helpers.

This environment's axon sitecustomize pre-imports jax and sets
``jax_platforms="axon,cpu"`` through jax.config at interpreter start, which
OVERRIDES the ``JAX_PLATFORMS`` environment variable. Consequences:

- ``JAX_PLATFORMS=cpu python script.py`` does NOT force CPU — the axon
  backend still initializes first (and hangs the process whenever the TPU
  tunnel is wedged rather than failing fast).
- The only reliable way to force CPU is ``jax.config.update`` in-process,
  BEFORE first backend use (what tests/conftest.py does for pytest).

Scripts call :func:`maybe_force_cpu` at entry so ``RTAP_FORCE_CPU=1``
gives a deterministic CPU run regardless of tunnel health.
"""

from __future__ import annotations

import os


def enable_compile_cache(repo_root: str) -> None:
    """Turn on JAX's persistent compilation cache at `<repo_root>/.jax_cache`
    (cache everything — min sizes/times zeroed). Shared by bench.py and the
    scripts so retries and later rounds skip recompilation."""
    import jax

    jax.config.update("jax_compilation_cache_dir", os.path.join(repo_root, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)


def force_virtual_devices(n: int) -> None:
    """Give this process n virtual CPU devices (must run before first jax
    backend use): sets --xla_force_host_platform_device_count and pins the
    CPU platform (the axon sitecustomize would otherwise init the TPU).
    An existing flag with a DIFFERENT count is an error — silently keeping
    it would make the later mesh construction fail far from the cause."""
    import re

    import jax

    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    if m is None:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    elif int(m.group(1)) < n:
        raise ValueError(
            f"XLA_FLAGS already forces {m.group(1)} host devices but {n} "
            "were requested; unset the flag or raise its value"
        )
    jax.config.update("jax_platforms", "cpu")


INIT_WATCHDOG_EXIT = 113  # distinctive: rc=2 would collide with argparse
# usage errors and CLI validation returns, and supervisors (scripts/
# hw_watch.py) key tunnel-down retry semantics off this exact code


def init_backend_or_die(timeout_s: float = 120.0) -> None:
    """Initialize the jax backend with a hard deadline.

    The axon TPU tunnel oscillates: backend init either completes in ~1 s or
    blocks indefinitely inside the PJRT client (observed: >10 min hangs, also
    hit by the round-2 judge). A hung init can't be interrupted in-process —
    the watchdog hard-exits (os._exit(INIT_WATCHDOG_EXIT)) so callers
    (scripts, bench attempt subprocesses) fail fast instead of silently
    eating their wall budget, and supervisors can tell "tunnel down" from a
    step's own usage/validation errors. No-op cost when the tunnel is
    healthy: one timer thread.
    """
    import threading

    done = threading.Event()

    def watchdog():
        if not done.wait(timeout_s):
            print(
                f"backend init exceeded {timeout_s:.0f}s (TPU tunnel wedged); aborting",
                file=__import__("sys").stderr, flush=True,
            )
            os._exit(INIT_WATCHDOG_EXIT)

    t = threading.Thread(target=watchdog, name="rtap-platform-watchdog",
                         daemon=True)
    t.start()
    import jax

    jax.devices()  # forces PJRT client creation — the part that hangs
    done.set()


def force_cpu_requested(env_var: str = "RTAP_FORCE_CPU") -> bool:
    """One parser for the force-CPU env convention (""/"0" falsy, anything
    else truthy). Artifact writers (e.g. the live-soak `forced_cpu` field)
    must agree with :func:`maybe_force_cpu` about what counts as forced."""
    return os.environ.get(env_var, "") not in ("", "0")


def maybe_force_cpu(env_var: str = "RTAP_FORCE_CPU") -> bool:
    """If ``$RTAP_FORCE_CPU`` is truthy, pin jax to the CPU platform (must be
    called before any jax backend use). Returns whether CPU was forced."""
    if force_cpu_requested(env_var):
        import jax

        jax.config.update("jax_platforms", "cpu")
        return True
    return False
