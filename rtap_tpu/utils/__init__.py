from rtap_tpu.utils.hashing import fmix32_np, hash_bits_np  # noqa: F401
