"""Shared benchmark-measurement building blocks.

bench.py (the headline number), scripts/scaling_law.py (the G-sweep), and
__graft_entry__ (the multi-chip dry run) all drive the same workload shape:
a synthetic diurnal cluster feed through the depth-2 pipelined chunk replay.
One implementation here, so a change to the feed or the measurement window
can never make the bench and the scaling sweep measure different things.
"""

from __future__ import annotations

import time

import numpy as np


def make_sine_feed(
    G: int, chunk_ticks: int, key: tuple[int, int], t0: int = 0,
    phase: np.ndarray | None = None,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Diurnal sine + Gaussian noise for G streams over one chunk.

    -> (values [T, G] f32, ts [T, G] i64, phase [G]) — pass `phase` back in
    to generate consecutive chunks of the same streams.
    """
    rng = np.random.Generator(np.random.Philox(key=key))
    if phase is None:
        phase = rng.integers(0, 86400, G)
    t_idx = t0 + np.arange(chunk_ticks)[:, None]
    base = 35.0 + 20.0 * np.sin(2 * np.pi * (t_idx + phase[None, :]) / 86400.0)
    vals = (base + rng.normal(0, 3.0, (chunk_ticks, G))).astype(np.float32)
    ts = (1_700_000_000 + t_idx + np.zeros((1, G))).astype(np.int64)
    return vals, ts, phase


def measure_pipelined(
    grp, vals: np.ndarray, ts: np.ndarray, measure_chunks: int = 3,
    novel: tuple[tuple[int, int], np.ndarray] | None = None,
):
    """Steady-state scored-metrics/s over `measure_chunks` chunk dispatches,
    overlapped depth-2 (dispatch chunk i+1 before collecting chunk i —
    SURVEY.md §7 hard part 3). The group must already be warmed up (compiled).

    `novel=(key, phase)`: each measured chunk carries FRESH values continuing
    `vals`' streams via the phase-advancing feed (per-chunk noise key), so
    steady state includes genuine novelty and the learning path's real cost —
    re-dispatching one chunk lets the TM fully learn a T-tick loop and
    flatters throughput (round-3 verdict, weak #8). Chunks are pre-generated
    OUTSIDE the timed window (the live service overlaps ingest with device
    compute; host rng is not the thing under measurement). Default (None)
    keeps the old re-dispatch behavior for A/B comparability.
    """
    chunk_ticks, G = vals.shape[:2]
    if novel is not None:
        key, phase = novel
        chunks = []
        for i in range(measure_chunks):
            v, t, _ = make_sine_feed(
                G, chunk_ticks, key=(key[0], key[1] + 1 + i),
                t0=(i + 1) * chunk_ticks, phase=phase,
            )
            chunks.append((v, t))
    else:
        chunks = [(vals, ts + (i + 1) * chunk_ticks) for i in range(measure_chunks)]
    t0 = time.perf_counter()
    pending = grp.dispatch_chunk(*chunks[0])
    for i in range(1, measure_chunks):
        nxt = grp.dispatch_chunk(*chunks[i])
        grp.collect_chunk(pending)
        pending = nxt
    grp.collect_chunk(pending)
    dt = time.perf_counter() - t0
    return measure_chunks * chunk_ticks * G / dt, dt
