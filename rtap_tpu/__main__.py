"""Operator CLI: ``python -m rtap_tpu <command>``.

The reference is an application, not just a library — its operators launch
the collector/service loop, replay corpora, and evaluate detection from the
command line (SURVEY.md L4/L5, §3.3-3.5). This is that surface, thin glue
over the library:

    serve    live scoring loop at a fixed cadence, fed by a TCP JSONL push
             listener or an HTTP poll endpoint (service/sources.py, C18)
    replay   synthetic cluster replay through stream groups at full speed,
             JSONL alerts + throughput/occupancy stats (service/loop.py)
    eval     fault-injection evaluation -> JSON report (eval/fault_eval.py)
    report   matplotlib overlays from a replay/eval (scripts/report.py)

``bench``/``scaling``/``profile`` remain repo-root scripts (bench.py,
scripts/) since they are driver/measurement surfaces, not operator ones.

Every command honors ``RTAP_FORCE_CPU=1`` (tunnel-independent runs) and the
kernel strategy env knobs (RTAP_TM_SCATTER / RTAP_TM_LAYOUT / RTAP_TM_SWEEP
/ RTAP_TM_DENDRITE — docs/KERNELS.md catalogs them).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from rtap_tpu.utils.platform import maybe_force_cpu


def _apply_cadence(cfg, args: argparse.Namespace):
    """ModelConfig.learn_every from the operator flag (SCALING.md
    "Learning-cadence operating curve"). Delegates to
    ModelConfig.with_learn_every — the shared policy — so an invalid k
    (0, negative) fails loudly instead of silently running full-rate."""
    return cfg.with_learn_every(getattr(args, "learn_every", 1),
                                full_until=getattr(args, "learn_full_until",
                                                   None),
                                burst=getattr(args, "learn_burst", 1))


def _sized_cluster(args: argparse.Namespace):
    """cluster_preset, optionally width-scaled (--columns: SCALING.md model-
    width study — per-workload deployment choice; validation lives in
    scaled_cluster_preset, which rejects degenerate geometries loudly)."""
    from rtap_tpu.config import cluster_preset, scaled_cluster_preset

    cols = getattr(args, "columns", None)
    return cluster_preset() if cols is None else scaled_cluster_preset(cols)


def _cmd_serve(args: argparse.Namespace) -> int:
    # control plane (ISSUE 20, docs/RESILIENCE.md "Control plane"): owns
    # one fencing lease per shard + membership + the shard map, epochs
    # journaled write-ahead. Started before any service import so a
    # --control-only process never touches the accelerator stack.
    control_plane = None
    if args.control_listen is not None:
        from rtap_tpu.fleet.control import ControlPlane

        try:
            control_plane = ControlPlane(
                args.control_journal, port=args.control_listen,
                lease_timeout_s=args.lease_timeout).start()
        except (OSError, ValueError) as e:
            print(f"serve: control plane failed to start: {e}",
                  file=sys.stderr)
            return 2
        chost, cport = control_plane.address
        print(f"serve: control plane on {chost}:{cport} (journal "
              f"{args.control_journal}, {control_plane.recovered_shards} "
              "shard lease(s) recovered)", file=sys.stderr)
        if args.control_only:
            import signal
            import threading

            cstop = threading.Event()
            for _sig in (signal.SIGTERM, signal.SIGINT):
                signal.signal(_sig, lambda *_: cstop.set())
            cstop.wait()
            stats = control_plane.stats()
            control_plane.close()
            print(json.dumps({"control": stats, "stopped": True}))
            return 0

    from rtap_tpu.config import nab_preset
    from rtap_tpu.service.loop import live_loop
    from rtap_tpu.service.registry import StreamGroupRegistry
    from rtap_tpu.service.shardpath import shard_scoped_path
    from rtap_tpu.service.sources import HttpPollSource, TcpJsonlSource

    # Shard-scope every operator resource path up front (ISSUE 15, the
    # shard-resource gate): one serve process = one mesh shard, and its
    # journal dir, checkpoint claims, lease file, and alert sink (plus
    # the .corr/.epoch sidecars derived from it downstream) must be
    # distinct per shard. --shard is the index ROADMAP-1's mesh launcher
    # (and the control-plane shard map) lands here; the single-shard
    # default is shard 0, where shard_scoped_path returns every path
    # byte-identical.
    serve_shard = int(getattr(args, "shard", 0) or 0)
    for _attr in ("journal_dir", "checkpoint_dir", "lease_file", "alerts"):
        if getattr(args, _attr, None):
            setattr(args, _attr,
                    shard_scoped_path(getattr(args, _attr), serve_shard))

    if args.streams.startswith("@"):
        # @file form: one stream id per line — a 16k-stream fleet's comma
        # list exceeds the kernel's single-argv limit (MAX_ARG_STRLEN,
        # observed at the live_soak_16k harvest step)
        try:
            with open(args.streams[1:]) as f:
                ids = [s.strip() for s in f if s.strip()]
        except OSError as e:
            print(f"serve: cannot read stream-id file {args.streams[1:]}: {e}",
                  file=sys.stderr)
            return 2
    else:
        ids = [s.strip() for s in args.streams.split(",") if s.strip()]
    if not ids:
        print("serve: --streams must name at least one stream id", file=sys.stderr)
        return 2
    if args.group_size < 1:
        print("serve: --group-size must be >= 1", file=sys.stderr)
        return 2
    # resilience wiring (docs/RESILIENCE.md): scripted fault injection and
    # the load-shedding ladder are operator opt-ins; quarantine itself is
    # always on (a faulted group must never take down the fleet). Parsed
    # BEFORE any source/registry construction: a bad spec is a usage
    # error, not a half-started serve with a listener to clean up.
    chaos = None
    if args.chaos_spec:
        from rtap_tpu.resilience import ChaosEngine, ChaosSpec

        try:
            chaos = ChaosEngine(ChaosSpec.from_file(args.chaos_spec))
        except (OSError, ValueError, KeyError, TypeError) as e:
            print(f"serve: bad --chaos-spec {args.chaos_spec}: {e}",
                  file=sys.stderr)
            return 2
        print(f"serve: chaos spec loaded ({len(chaos.spec.faults)} faults, "
              f"digest {chaos.spec.digest()})", file=sys.stderr)
    # topology-aware incident correlation (ISSUE 9, rtap_tpu/correlate/,
    # docs/WORKLOADS.md): parsed before any source/registry construction
    # — a bad spec is a usage error, not a half-started serve
    correlator = None
    if args.topology:
        from rtap_tpu.correlate import IncidentCorrelator, TopologyMap

        try:
            topo = TopologyMap.infer() if args.topology == "infer" \
                else TopologyMap.from_spec(args.topology)
            # only user-set knobs become kwargs — the class defaults
            # (window 30s, min 3 streams) have ONE owner
            knobs = {k: v for k, v in (
                ("window_s", args.correlate_window),
                ("min_streams", args.correlate_min_streams))
                if v is not None}
            correlator = IncidentCorrelator(topo, **knobs)
        except (OSError, ValueError, KeyError, TypeError) as e:
            print(f"serve: bad --topology {args.topology}: {e}",
                  file=sys.stderr)
            return 2
        print(f"serve: incident correlation armed ({'inferred' if args.topology == 'infer' else args.topology}; "
              f"window {correlator.window_s}s, min {correlator.min_streams} "
              "streams)", file=sys.stderr)
    # detection-latency observability + SLOs (ISSUE 11, obs/latency.py,
    # obs/slo.py, docs/SLO.md): specs parse BEFORE any source/registry
    # construction — a malformed --slo is a usage error, not a
    # half-started serve with a listener to clean up
    slo_specs = []
    if args.slo:
        from rtap_tpu.obs import parse_slo

        try:
            slo_specs = [parse_slo(s) for s in args.slo]
        except ValueError as e:
            print(f"serve: bad --slo: {e}", file=sys.stderr)
            return 2
    latency = None
    slo_tracker = None
    if args.latency:
        from rtap_tpu.obs import LatencyTracker

        try:
            latency = LatencyTracker(
                window_ticks=args.latency_window
                if args.latency_window is not None else 120,
                cadence_s=args.cadence)
        except ValueError as e:
            print(f"serve: bad --latency-window: {e}", file=sys.stderr)
            return 2
        print("serve: detection-latency tracking armed (window "
              f"{latency.window_ticks} ticks; GET /latency with "
              "--obs-port)", file=sys.stderr)
    if slo_specs:
        from rtap_tpu.obs import SloTracker

        try:
            slo_tracker = SloTracker(
                slo_specs, cadence_s=args.cadence,
                fast_window=args.slo_fast_window
                if args.slo_fast_window is not None else 60,
                slow_window=args.slo_slow_window
                if args.slo_slow_window is not None else 600,
                quantile_source=latency.quantile)
        except ValueError as e:
            print(f"serve: bad --slo/--slo-*-window: {e}",
                  file=sys.stderr)
            return 2
        print("serve: SLOs armed: "
              + ", ".join(s.label() for s in slo_specs)
              + f" (burn windows {slo_tracker.fast_window}/"
              f"{slo_tracker.slow_window} ticks)", file=sys.stderr)
    degradation = None
    if args.degrade:
        from rtap_tpu.resilience import DegradationController

        try:
            degradation = DegradationController(
                degrade_after=args.degrade_after,
                recover_after=args.degrade_recover_after)
        except ValueError as e:
            print(f"serve: bad --degrade parameters: {e}", file=sys.stderr)
            return 2
    # durability (docs/RESILIENCE.md, ISSUE 5): the per-tick write-ahead
    # journal. Constructing it performs recovery (torn tails truncated,
    # rows loaded for replay); with a journal, --ticks is the run's TOTAL
    # tick budget across restarts — a resumed serve catches up through
    # the journal and then runs only the remainder.
    journal = None
    n_ticks_eff = args.ticks
    if args.journal_dir:
        from rtap_tpu.resilience.journal import TickJournal, parse_fsync

        try:
            fsync_policy, fsync_every = parse_fsync(args.journal_fsync)
            journal = TickJournal(
                args.journal_dir,
                segment_bytes=args.journal_segment_bytes,
                max_segments=args.journal_max_segments,
                fsync=fsync_policy, fsync_every=fsync_every)
        except (OSError, ValueError) as e:
            print(f"serve: bad --journal-dir/--journal-fsync: {e}",
                  file=sys.stderr)
            return 2
        base = journal.next_tick
        if args.checkpoint_dir:
            from rtap_tpu.service.checkpoint import peek_resume_ticks

            base = max(base, peek_resume_ticks(args.checkpoint_dir))
        n_ticks_eff = max(0, args.ticks - base)
        if base:
            print(f"serve: resuming at tick {base} "
                  f"({len(journal.recovered_ticks)} journaled rows "
                  f"recovered; --ticks {args.ticks} is the total budget "
                  f"-> {n_ticks_eff} new ticks)", file=sys.stderr)
            if chaos is not None:
                # under a journal the chaos schedule is GLOBAL-tick
                # -indexed: a restarted serve shifts it onto its local
                # clock and fired faults (in particular the proc_exit
                # that killed the previous incarnation) drop out instead
                # of re-firing every restart
                from rtap_tpu.resilience import ChaosEngine as _CE

                chaos = _CE(chaos.spec.shifted(base))
                print(f"serve: chaos schedule shifted to resume base "
                      f"{base} ({len(chaos.spec.faults)} faults remain)",
                      file=sys.stderr)
        if journal.truncations or journal.dropped_segments:
            print(f"serve: journal tail truncated on recovery "
                  f"({journal.truncations} truncation(s), "
                  f"{journal.truncated_bytes} bytes, "
                  f"{journal.dropped_segments} dropped segment(s)) — "
                  "continuing from the last valid record", file=sys.stderr)
    # hot-standby replication + leadership lease (ISSUE 8,
    # docs/RESILIENCE.md failover runbook). The lease is constructed
    # here; a LEADER acquires it now (refusing to start split-brained),
    # a STANDBY only watches it until promotion.
    lease = None
    if args.lease_file:
        from rtap_tpu.resilience.replicate import Lease

        lease = Lease(args.lease_file,
                      owner=f"{os.uname().nodename}:{os.getpid()}",
                      timeout_s=args.lease_timeout)
        if not args.standby:
            if not lease.try_acquire():
                print(f"serve: lease {args.lease_file} is held by "
                      f"{lease.holder()!r} and fresh — refusing to serve "
                      "split-brained (start this process with --standby, "
                      "or wait out the lease timeout)", file=sys.stderr)
                return 2
            # liveness = process alive, not tick-loop fast: the
            # heartbeat keeps the lease fresh through multi-second
            # synchronous work (checkpoint rounds)
            lease.start_heartbeat()
    elif args.control_join:
        # same FencingLease surface, control-plane backend (ISSUE 20):
        # the loop, alert fence, follower and heartbeat cannot tell the
        # two apart — only the acquire/degrade semantics differ
        from rtap_tpu.fleet.control import ControlLease, parse_control_addr

        lease = ControlLease(
            parse_control_addr(args.control_join),
            owner=f"{os.uname().nodename}:{os.getpid()}",
            shard=serve_shard, timeout_s=args.lease_timeout,
            degraded_grace_s=args.control_grace)
        lease.hello("standby" if args.standby else "leader")
        if not args.standby:
            if not lease.try_acquire():
                print(f"serve: control plane {args.control_join} refused "
                      f"the shard {serve_shard} lease (held by "
                      f"{lease.holder()!r}, in its restart grace, or "
                      "unreachable) — start with --standby, or wait out "
                      "the lease timeout", file=sys.stderr)
                return 2
            lease.start_heartbeat()
    # (--columns + non-cluster presets rejected in main() before backend init)
    if args.preset == "nab":
        cfg = nab_preset()
    elif args.preset == "composite":
        from rtap_tpu.config import composite_preset

        cfg = composite_preset()
    elif args.preset == "categorical":
        from rtap_tpu.config import categorical_preset

        cfg = categorical_preset()
    else:
        cfg = _sized_cluster(args)
    cfg = _apply_cadence(cfg, args)
    # many groups per chip is the at-scale serving shape (throughput peaks
    # at small G — SCALING.md); capping at len(ids) keeps small serves in
    # one exactly-sized group with no pad slots
    gsize = min(args.group_size, len(ids))
    # --auto-register without reserved capacity can only claim group-size
    # rounding pads; make the elastic intent explicit by default
    reserve = args.reserve if args.reserve is not None \
        else (gsize if args.auto_register else 0)
    # predictive horizon (ISSUE 16): a non-zero k makes every group carry
    # the pred_* ring leaves and the fused reducer from tick 0 — the
    # horizon is structural (it sizes device state), so it is fixed at
    # registry construction, not toggled later
    predict_k = (args.predict_horizon if args.predict_horizon is not None
                 else 8) if args.predict else 0
    grp = StreamGroupRegistry(cfg, group_size=gsize,
                              backend=args.backend, threshold=args.threshold,
                              debounce=args.debounce,
                              stagger_learn=args.stagger_learn,
                              health=args.health,
                              predict=predict_k)
    for sid in ids:
        grp.add_stream(sid)
    grp.finalize(reserve=reserve)
    # orderly shutdown: SIGTERM/SIGINT finish the current tick (or end a
    # standby's follow loop), save final state, and still print stats —
    # installed BEFORE the standby block so a follow loop is stoppable
    import signal
    import threading

    stop = threading.Event()
    prev = {}

    def _on_signal(*_):
        stop.set()
        # restore the previous handlers so a SECOND signal force-exits —
        # a tick wedged on the device must not make the process
        # unkillable except by SIGKILL
        for s, h in prev.items():
            signal.signal(s, h)

    for sig in (signal.SIGTERM, signal.SIGINT):
        prev[sig] = signal.signal(sig, _on_signal)
    if lease is not None and hasattr(lease, "on_drain"):
        # a control-plane drain mark becomes an orderly between-tick
        # exit: same path as SIGTERM, so final state is saved and the
        # stats line printed — the rolling-upgrade primitive
        lease.on_drain = stop.set
    # fleet observability plane, member side (ISSUE 19, rtap_tpu/fleet/,
    # docs/FLEET.md): started BEFORE the standby block so the aggregator
    # watches the whole standby phase — the follow loop, the promotion
    # role change, and the served remainder are one member timeline
    fleet_pub = None
    if args.fleet_join:
        from rtap_tpu.fleet import FleetPublisher

        fhost, _fsep, fport_s = args.fleet_join.rpartition(":")
        frole = "standby" if args.standby else "leader"
        fleet_pub = FleetPublisher(
            (fhost or "127.0.0.1", int(fport_s)),
            f"{frole}-{os.getpid()}", role=frole,
            lease_epoch=lease.epoch if lease is not None else 0,
            push_interval_s=args.fleet_push_interval
            if args.fleet_push_interval is not None else 1.0).start()
        print(f"serve: fleet member {fleet_pub.member!r} pushing to "
              f"{args.fleet_join} every {fleet_pub.push_interval_s}s",
              file=sys.stderr)
    resume_sup = None
    follower = None
    if args.standby:
        # hot standby (ISSUE 8): mirror the leader's journal stream,
        # keep model state warm at the live edge, promote on lease
        # loss — then fall through into normal (leader) serving below
        from rtap_tpu.resilience.replicate import StandbyFollower

        follower = StandbyFollower(
            grp, journal, lease=lease, port=args.replicate_listen,
            alert_path=args.alerts, checkpoint_dir=args.checkpoint_dir,
            learn=not args.freeze, cadence_s=args.cadence,
            stop_event=stop)
        print(f"serve: standby following on port "
              f"{args.replicate_listen} (lease "
              f"{args.lease_file or f'control:{args.control_join}'}, "
              f"timeout {args.lease_timeout}s)", file=sys.stderr)
        outcome = follower.run()
        if outcome == "stopped":
            for sig, handler in prev.items():
                signal.signal(sig, handler)
            journal.close()
            if fleet_pub is not None:
                fleet_pub.close()  # orderly BYE: the fleet sees "left"
            print(json.dumps({"standby": follower.stats(),
                              "stopped": True}))
            return 0
        # promoted: the follower checkpointed the warm fleet and
        # spliced the alert stream; serve the REMAINING budget as the
        # leader (the resume machinery below picks it all up)
        base = max(journal.next_tick, 0)
        if args.checkpoint_dir:
            from rtap_tpu.service.checkpoint import peek_resume_ticks

            base = max(base, peek_resume_ticks(args.checkpoint_dir))
        n_ticks_eff = max(0, args.ticks - base)
        resume_sup = follower.resume_suppression
        lease.start_heartbeat()
        if fleet_pub is not None:
            # the promotion IS a fleet event: same member, new role, the
            # successor lease epoch — failover_soak asserts this exact
            # role_changed sequence against the lease/journal truth
            fleet_pub.set_role("leader", lease_epoch=lease.epoch)
        print(f"serve: standby PROMOTED to leader at tick {base} "
              f"(lease epoch {lease.epoch}, detected in "
              f"{follower.promote_detect_s:.3f}s; {n_ticks_eff} ticks "
              "remain)", file=sys.stderr)
    sender = None
    if args.replicate_to:
        from rtap_tpu.resilience.replicate import ReplicationSender

        host, _sep, port_s = args.replicate_to.rpartition(":")
        sender = ReplicationSender(
            (host or "127.0.0.1", int(port_s)), journal,
            checkpoint_dir=args.checkpoint_dir, chaos=chaos).start()
        journal.tee = sender.tee
        journal.compact_floor = sender.compact_floor
        print(f"serve: replicating journal appends to "
              f"{args.replicate_to} (bounded buffer, drop-oldest)",
              file=sys.stderr)
    if args.http:
        source = HttpPollSource(args.http, ids,
                                track_unknown=args.auto_register)
        close = lambda: None  # noqa: E731
    elif args.ingest_port is not None or args.ingest_shm:
        # wire-speed binary ingest (ISSUE 7, docs/INGEST.md): the RB1
        # batch protocol over persistent sockets and/or a shared-memory
        # ring, addressed by the registry's (shard, group, slot) slot
        # map. Quotas/backfill are admission-control knobs of this path.
        from rtap_tpu.ingest import BinaryBatchSource

        bsrc = BinaryBatchSource(
            grp.slot_map(),
            port=args.ingest_port,
            shm=args.ingest_shm or None,
            quota_rows=args.ingest_quota,
            backfill_horizon=args.ingest_backfill_horizon,
            track_unknown=args.auto_register).start()
        if bsrc.address is not None:
            bhost, bport = bsrc.address
            print(f"serve: listening for binary batch frames on "
                  f"{bhost}:{bport}", file=sys.stderr)
        if bsrc.ring_name is not None:
            print(f"serve: binary ingest shm ring {bsrc.ring_name!r} "
                  "created (co-located exporters attach by name)",
                  file=sys.stderr)
        source, close = bsrc, bsrc.close
    else:
        tcp = TcpJsonlSource(ids, port=args.port,
                             track_unknown=args.auto_register).start()
        host, port = tcp.address
        print(f"serve: listening for JSONL records on {host}:{port}", file=sys.stderr)
        source, close = tcp, tcp.close
    # telemetry exposition (rtap_tpu.obs): a localhost /metrics endpoint for
    # scrapers, and/or a JSONL snapshot file for the no-network hw sessions
    # (--obs-snapshot; $RTAP_OBS_SNAPSHOT is the session runner's default)
    from rtap_tpu.obs import ExpositionServer, default_snapshot_path, write_snapshot

    # per-tick tracing + black-box flight recorder (obs/trace.py,
    # obs/flight.py, docs/POSTMORTEM.md). The span ring also backs the
    # obs server's /trace route, so --obs-port alone enables it.
    trace = None
    flight = None
    if args.trace_out or args.postmortem_dir or args.obs_port is not None:
        from rtap_tpu.obs import TraceRecorder

        # real process identity on the timeline: fleet_trace.py stitches
        # multi-process traces by pid and labels tracks by this name
        trace = TraceRecorder(
            capacity=args.trace_ring,
            process_name=fleet_pub.member if fleet_pub is not None
            else f"rtap-serve-{os.getpid()}")
    if args.postmortem_dir:
        from rtap_tpu.obs import FlightRecorder

        os.makedirs(args.postmortem_dir, exist_ok=True)
        flight = FlightRecorder(
            trace=trace, n_ticks=args.flight_ticks,
            out_dir=args.postmortem_dir,
            info={"command": "serve", "streams": len(ids),
                  "group_size": gsize, "cadence_s": args.cadence,
                  "ticks": args.ticks, "backend": args.backend,
                  "preset": args.preset, "micro_chunk": args.micro_chunk,
                  "pipeline_depth": args.pipeline_depth,
                  "freeze": bool(args.freeze)})
        print(f"serve: flight recorder armed (last {args.flight_ticks} "
              f"ticks -> {args.postmortem_dir})", file=sys.stderr)
    attributor = None
    if args.alert_attribution:
        from rtap_tpu.service.attribution import AlertAttributor

        attributor = AlertAttributor(cfg)
    # model-health observability (obs/health.py, ISSUE 6): the groups
    # above were built with health=args.health, so every chunk already
    # carries the fused on-device aggregates; the tracker folds them
    # into scorecards (GET /health), detects score drift, and raises
    # health incidents onto the alert stream + flight recorder
    health = None
    if args.health:
        from rtap_tpu.obs import HealthTracker

        try:
            health = HealthTracker(
                cfg,
                occupancy_threshold=args.health_occupancy_threshold,
                sparsity_min_frac=args.health_sparsity_min_frac,
                drift_threshold=args.health_drift_threshold,
                drift_min_ticks=args.health_drift_min_ticks)
        except ValueError as e:
            print(f"serve: bad --health parameters: {e}", file=sys.stderr)
            return 2
        print("serve: model-health reducers armed "
              f"(drift tvd>={args.health_drift_threshold} after "
              f"{args.health_drift_min_ticks} ticks, pool occupancy>="
              f"{args.health_occupancy_threshold})", file=sys.stderr)
    # predictive horizon (rtap_tpu/predict/, ISSUE 16): the groups above
    # were built with predict=k, so every chunk already carries the fused
    # predictive-divergence leaf; the tracker folds it into precursor
    # events with a predicted lead time, and with --topology the fuser
    # collapses precursors into one predicted_incident with a predicted
    # blast radius (the correlator's TopologyMap is reused — one parse,
    # one owner)
    predictor = None
    if args.predict:
        from rtap_tpu.predict import BlastFuser, PredictTracker

        try:
            predictor = PredictTracker(
                horizon=predict_k,
                threshold=args.predict_threshold
                if args.predict_threshold is not None else 0.35,
                min_ticks=args.predict_min_ticks
                if args.predict_min_ticks is not None else 12,
                blast=BlastFuser(correlator.topology, seed_streams=ids)
                if correlator is not None else None)
        except ValueError as e:
            print(f"serve: bad --predict parameters: {e}", file=sys.stderr)
            return 2
        print("serve: predictive horizon armed "
              f"(k={predict_k} ticks, miss ewma>={predictor.threshold} "
              f"for {predictor.min_ticks} ticks"
              + (", blast fusion on" if predictor.blast is not None
                 else "") + ")", file=sys.stderr)
    # restart continuity (ISSUE 6 satellite): the run epoch persists
    # beside the incident stream and the gauge survives into every
    # snapshot, so a supervised child's counter resets are attributable
    from rtap_tpu.obs import bump_run_epoch, set_build_info

    run_epoch = bump_run_epoch(args.alerts)
    # always-on identity gauge (ISSUE 19 satellite): every snapshot,
    # scrape, and fleet push says who this process is — a serve reaching
    # this point serves as the leader (a standby already promoted above)
    set_build_info(role="leader", shard=serve_shard, run_epoch=run_epoch,
                   config=cfg)
    if fleet_pub is not None:
        fleet_pub.set_role("leader", run_epoch=run_epoch)
        fleet_pub.attach(health=health, latency=latency, slo=slo_tracker,
                         correlator=correlator, trace=trace)
    if latency is not None:
        # first-class lag gauges (ISSUE 11): polled once per tick into
        # rtap_obs_latency_lag{lag=...} — replication-ack lag while a
        # standby is attached, incident-close lag while correlating
        if sender is not None:
            latency.lag_providers["repl_ack_ticks"] = \
                lambda _t, _ts: sender.ack_lag_ticks()
        if correlator is not None:
            latency.lag_providers["incident_close_s"] = \
                lambda _t, ts: correlator.oldest_open_age_s(ts)
    # fleet observability plane, aggregator side (ISSUE 19): the merged
    # one-pane-of-glass views ride the obs HTTP server (/fleet/*), so
    # --fleet-listen requires --obs-port (enforced in main())
    fleet_agg = None
    if args.fleet_listen is not None:
        from rtap_tpu.fleet import FleetAggregator

        fleet_agg = FleetAggregator(port=args.fleet_listen).start()
        print(f"serve: fleet aggregator on "
              f"{fleet_agg.host}:{fleet_agg.port} (merged views at the "
              "obs server's GET /fleet/* routes)", file=sys.stderr)
    obs_server = None
    if args.obs_port is not None:
        obs_server = ExpositionServer(
            port=args.obs_port, trace=trace,
            flight=flight, health=health,
            correlator=correlator, latency=latency, slo=slo_tracker,
            predict=predictor, fleet=fleet_agg,
            healthz_stale_after_s=max(30.0, 10 * args.cadence)).start()
        ohost, oport = obs_server.address
        print(f"serve: obs telemetry on http://{ohost}:{oport}/metrics",
              file=sys.stderr)
    obs_snapshot = args.obs_snapshot or default_snapshot_path()
    if lease is not None and hasattr(source, "announce_leader") \
            and getattr(source, "address", None) is not None:
        # the lease advertises this leader's RB1 ingest address so a
        # fenced predecessor can re-point its producers (the MAP
        # __leader__ push — docs/INGEST.md)
        lhost, lport = source.address
        lease.set_meta(ingest=f"{lhost}:{lport}")
    jax_tracing = False
    if args.jax_trace:
        # device-side XLA trace paired with the host span timeline: the
        # hw_session device-trace step loads both into Perfetto
        import jax

        jax.profiler.start_trace(args.jax_trace)
        jax_tracing = True
        print(f"serve: jax profiler tracing to {args.jax_trace}",
              file=sys.stderr)
    try:
        try:
            stats = live_loop(source, grp, n_ticks=n_ticks_eff, cadence_s=args.cadence,
                              alert_path=args.alerts,
                              checkpoint_dir=args.checkpoint_dir,
                              checkpoint_every=args.checkpoint_every,
                              stop_event=stop,
                              pipeline_depth=args.pipeline_depth,
                              dispatch_threads=args.dispatch_threads,
                              learn=not args.freeze,
                              auto_register=args.auto_register,
                              auto_release_after=args.auto_release_after,
                              micro_chunk=args.micro_chunk,
                              chunk_stagger=args.chunk_stagger,
                              chaos=chaos,
                              degradation=degradation,
                              quarantine_restore_after=args.quarantine_restore_after,
                              alert_flush_every=args.alert_flush_every,
                              aot_warmup=args.aot_warmup,
                              trace=trace, flight=flight,
                              attributor=attributor,
                              journal=journal,
                              health=health,
                              lease=lease,
                              resume_suppression=resume_sup,
                              correlator=correlator,
                              latency=latency,
                              slo=slo_tracker,
                              predictor=predictor,
                              fleet=fleet_pub)
        except BaseException as e:  # noqa: BLE001 — dump, then re-raise
            # crash black-box: an exception escaping serve dumps a
            # postmortem bundle BEFORE the traceback, so a dead soak
            # leaves its last N ticks of evidence behind. (Worker-thread
            # faults already surface here: the loop joins its pool and
            # re-raises captured exceptions in the loop thread.)
            if flight is not None:
                flight.record_event({
                    "event": "unhandled_exception",
                    "error": f"{type(e).__name__}: {e}"})
                flight.dump("unhandled_exception")
            raise
        if stats.get("fenced") and lease is not None:
            # fenced out by a promoted standby: re-point any connected
            # RB1 producers at the new leader BEFORE the source closes
            hint = lease.holder_meta().get("ingest")
            if hint and hasattr(source, "announce_leader"):
                source.announce_leader(hint)
                print(f"serve: pushed MAP re-point to new leader {hint}",
                      file=sys.stderr)
        if lease is not None and hasattr(lease, "degraded"):
            stats["control_lease"] = lease.stats()
        if lease is not None and getattr(lease, "draining", False):
            # drained by the control plane: an ORDERLY handoff — release
            # the lease (epoch floor retained server-side) so the standby
            # promotes immediately instead of waiting out staleness
            lease.stop_heartbeat()
            rel = getattr(lease, "release", None)
            if rel is not None:
                rel()
            stats["drained"] = True
            print(f"serve: shard {serve_shard} drained — lease released, "
                  "the standby takes over", file=sys.stderr)
    finally:
        if jax_tracing:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception as e:  # noqa: BLE001 — diagnostics only
                print(f"serve: jax profiler stop failed: {e}",
                      file=sys.stderr)
        for sig, handler in prev.items():
            signal.signal(sig, handler)
        close()
        if sender is not None:
            sender.close()
        if lease is not None:
            lease.stop_heartbeat()
        if journal is not None:
            journal.tee = None
            journal.close()
        if fleet_pub is not None:
            # joined push-thread exit with a best-effort BYE; an abrupt
            # death instead goes stale and the aggregator marks it DOWN.
            # A drain exit says so in the BYE — fleet_report must not
            # read a rolling upgrade as an outage.
            fleet_pub.close(
                reason="drain" if (lease is not None
                                   and getattr(lease, "draining", False))
                else None)
        if obs_server is not None:
            obs_server.close()
        if fleet_agg is not None:
            # after the obs server: no /fleet/* route may race a closed
            # aggregator
            fleet_agg.close()
        if control_plane is not None:
            control_plane.close()
        if args.trace_out and trace is not None:
            # Perfetto-loadable Chrome trace JSON, atomically (tmp +
            # replace): written even on an error path — the timeline of
            # a dying serve is exactly what the postmortem needs. Best
            # effort: must not mask the loop's own exception.
            try:
                tmp = args.trace_out + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(trace.chrome_trace(), f)
                os.replace(tmp, args.trace_out)
                print(f"serve: host trace written to {args.trace_out} "
                      f"({trace.total} records, {trace.dropped} dropped)",
                      file=sys.stderr)
            except OSError as e:
                print(f"serve: trace write failed: {e}", file=sys.stderr)
        if obs_snapshot:
            # final registry snapshot even on an error path: a soak that
            # died mid-run must still leave its telemetry on disk. Best
            # effort — an unwritable path must not mask the loop's own
            # exception (or fail an otherwise-complete run).
            try:
                write_snapshot(obs_snapshot)
            except OSError as e:
                print(f"serve: obs snapshot write failed: {e}",
                      file=sys.stderr)
    # ingest health belongs in the service artifact: a zero-missed-deadline
    # line is only evidence if data was flowing and parsing cleanly
    for attr in ("records_parsed", "parse_errors", "unknown_ids",
                 "native_active", "poll_failures", "polls_short_circuited",
                 "frames_applied", "garbage_bytes", "rows_quota_dropped",
                 "rows_late_dropped", "rows_backfilled",
                 "rows_backpressure_dropped", "rows_stale_epoch"):
        v = getattr(source, attr, None)
        if v is not None:
            stats[attr] = v
    if sender is not None:
        stats["replication"] = sender.stats()
    if args.standby:
        stats["promoted_from_standby"] = True
        stats["promote_detect_s"] = round(follower.promote_detect_s, 3)
        stats["standby"] = follower.stats()
    if stats.get("fenced"):
        from rtap_tpu.resilience.replicate import FENCED_RC

        print(f"serve: FENCED by {lease.holder()!r} at epoch "
              f"{lease.holder_meta().get('epoch')} — exiting rc "
              f"{FENCED_RC}", file=sys.stderr)
        print(json.dumps(stats))
        return FENCED_RC
    print(json.dumps(stats))
    return 0


def _cmd_replay(args: argparse.Namespace) -> int:
    from rtap_tpu.data.synthetic import SyntheticStreamConfig, generate_cluster
    from rtap_tpu.service.loop import replay_streams

    # the generator needs room for post-probation injections
    # (inject_after_frac * length .. length - 50 must be non-empty)
    min_len = 80
    if args.length < min_len:
        print(f"replay: --length must be >= {min_len} (fault injections land "
              "past the probation region)", file=sys.stderr)
        return 2
    scfg = SyntheticStreamConfig(length=args.length, cadence_s=1.0,
                                 anomaly_magnitude=args.magnitude,
                                 noise_phi=0.97, noise_scale=0.5)
    streams = generate_cluster(args.nodes, cfg=scfg, seed=args.seed)
    res = replay_streams(streams, _apply_cadence(_sized_cluster(args), args),
                         backend=args.backend,
                         group_size=args.group_size, chunk_ticks=args.chunk_ticks,
                         threshold=args.threshold, alert_path=args.alerts,
                         checkpoint_dir=args.checkpoint_dir,
                         checkpoint_every=args.checkpoint_every,
                         debounce=args.debounce, learn=not args.freeze)
    print(json.dumps({"streams": len(res.stream_ids), "ticks": len(res.timestamps),
                      **res.throughput}))
    return 0


def _with_argv(argv: list[str], fn) -> int:
    """Run `fn` under a temporary sys.argv (the wrapped mains parse it);
    always restore — a programmatic main(['eval', ...]) call must not leave
    stale args behind for the caller's own argparse users. Propagates the
    wrapped main's int return code (ADVICE.md r3: a failing eval/report must
    not exit 0)."""
    saved = sys.argv
    sys.argv = [saved[0], *argv]
    try:
        return int(fn() or 0)
    finally:
        sys.argv = saved


def _cmd_eval(args: argparse.Namespace) -> int:
    from rtap_tpu.eval import fault_eval

    argv = ["--streams", str(args.streams), "--length", str(args.length),
            "--magnitude", str(args.magnitude), "--backend", args.backend,
            "--debounce", str(args.debounce), "--likelihood", args.likelihood]
    if args.learning_period is not None:
        argv += ["--learning-period", str(args.learning_period)]
    if args.learn_every != 1:
        argv += ["--learn-every", str(args.learn_every)]
    if getattr(args, "learn_burst", 1) != 1:
        argv += ["--learn-burst", str(args.learn_burst)]
    if args.all_kinds:
        argv.append("--all-kinds")
    if args.out:
        argv += ["--out", args.out]
    return _with_argv(argv, fault_eval.main)


def _cmd_nab(args: argparse.Namespace) -> int:
    """BASELINE configs 1-2 as one mechanical command (SURVEY.md §6): load a
    NAB-layout corpus, run the detector family over every file, sweep the
    threshold exhaustively, report normalized per-profile scores."""
    import json as _json

    from rtap_tpu.data.nab_corpus import NAB_CORPUS_ENV, NabFile, load_corpus
    from rtap_tpu.nab.runner import run_corpus

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    root = args.corpus or os.environ.get(NAB_CORPUS_ENV) \
        or os.path.join(repo, "data", "nab")
    if not os.path.isfile(os.path.join(root, "labels", "combined_windows.json")):
        print(f"nab: no corpus at {root} (need data/**/*.csv + labels/"
              "combined_windows.json). Pass --corpus, set "
              f"${NAB_CORPUS_ENV}, or regenerate the stand-in: "
              "python -c 'from rtap_tpu.data.nab_corpus import "
              "ensure_standin_corpus; ensure_standin_corpus(\"data/nab\")'",
              file=sys.stderr)
        return 2
    files = load_corpus(root, subset=args.subset)
    if not files:
        print(f"nab: corpus at {root} matched no files "
              f"(subset={args.subset!r})", file=sys.stderr)
        return 2
    if args.rows:
        files = [NabFile(f.name, f.timestamps[: args.rows],
                         f.values[: args.rows], f.windows) for f in files]
    cfg = None
    if args.columns:
        from rtap_tpu.config import scaled_nab_preset

        cfg = scaled_nab_preset(args.columns)
    t0 = time.time()
    res = run_corpus(files, cfg=cfg, backend=args.backend)
    wall = time.time() - t0
    scores = {prof: {"threshold": round(thr, 4), "score": round(score, 2)}
              for prof, (thr, score) in res.scores.items()}
    report = {
        "corpus_root": os.path.abspath(root),
        "backend": args.backend,
        "files": [f.name for f in files],
        "records": int(sum(len(f.values) for f in files)),
        "wall_s": round(wall, 1),
        "scores": scores,
    }
    if args.out:
        os.makedirs(os.path.dirname(os.path.abspath(args.out)), exist_ok=True)
        with open(args.out, "w") as f:
            _json.dump(report, f, indent=2)
    print(_json.dumps(scores))
    return 0


def _cmd_report(args: argparse.Namespace) -> int:
    import os
    import runpy

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    argv = ["--out-dir", args.out_dir, "--streams", str(args.streams),
            "--length", str(args.length)]
    if args.eval_report:
        argv += ["--eval-report", args.eval_report]
    return _with_argv(
        argv,
        lambda: runpy.run_path(os.path.join(repo, "scripts", "report.py"),
                               run_name="__main__"),
    )


def main(argv: list[str] | None = None) -> int:
    maybe_force_cpu()
    ap = argparse.ArgumentParser(prog="python -m rtap_tpu", description=__doc__)
    sub = ap.add_subparsers(dest="command", required=True)

    p = sub.add_parser("serve", help="live scoring loop fed by TCP push or HTTP poll")
    p.add_argument("--streams", default=None,
                   help="comma-separated stream ids to register, or "
                        "@/path/to/file with one id per line (argv has a "
                        "~128 KB single-argument limit; fleets above a few "
                        "thousand streams need the file form)")
    p.add_argument("--http", default=None,
                   help="poll this metrics endpoint each tick (default: TCP listener)")
    p.add_argument("--port", type=int, default=0, help="TCP listen port (0 = ephemeral)")
    p.add_argument("--ingest-port", type=int, default=None,
                   help="listen for the RB1 binary batch protocol on this "
                        "port (0 = ephemeral) instead of per-record JSONL: "
                        "length-prefixed CRC-framed frames of packed "
                        "(slot, value, ts_delta) rows addressed by the "
                        "registry's (shard, group, slot) slot map, decoded "
                        "with zero per-record Python — the wire-speed "
                        "ingest front end (docs/INGEST.md; "
                        "scripts/ingest_bench.py measures it)")
    p.add_argument("--ingest-shm", default=None,
                   help="also create a shared-memory frame ring under this "
                        "name for co-located exporters (same RB1 frames, "
                        "no socket; combine with --ingest-port or use "
                        "alone). The ring is drained once per tick")
    p.add_argument("--ingest-quota", type=int, default=0,
                   help="admission control: max binary-ingest rows per "
                        "tenant per tick (frames carry a tenant header); "
                        "rows beyond the quota are dropped + counted "
                        "(rtap_obs_ingest_quota_dropped_total). 0 = off")
    p.add_argument("--ingest-backfill-horizon", type=int, default=0,
                   help="binary-ingest timestamp alignment: hold emission "
                        "this many SECONDS (of row timestamp) behind the "
                        "newest row seen, so late rows land in the slot "
                        "their timestamp names instead of overwriting "
                        "the latest value; older-than-horizon rows drop "
                        "(counted). At the standard 1 s cadence a second "
                        "is a tick. 0 = latest-wins (JSONL-equivalent "
                        "semantics, the default)")
    p.add_argument("--ticks", type=int, default=60)
    p.add_argument("--cadence", type=float, default=1.0)
    p.add_argument("--preset", choices=("cluster", "nab", "composite",
                                        "categorical"), default="cluster",
                   help="model family: cluster (scalar RDSE, the "
                        "default), nab (NAB-scale), composite (the "
                        "ISSUE 9 multi-field encoder preset — each wire "
                        "record is [value, delta, event-class] fused "
                        "with the hour-of-day ring into one SDR), or "
                        "categorical (single event-class/log-template "
                        "field; docs/WORKLOADS.md encoder family)")
    p.add_argument("--backend", default="tpu")
    p.add_argument("--group-size", type=int, default=1024,
                   help="streams per device group; len(streams) above this "
                        "serves as multiple interleaved groups per chip "
                        "(SCALING.md: throughput peaks at small G)")
    p.add_argument("--threshold", type=float, default=0.5)
    p.add_argument("--debounce", type=int, default=2,
                   help="alert only after this many consecutive ticks at/"
                        "above threshold (reports/quality_study.json)")
    p.add_argument("--alerts", default=None, help="JSONL alert sink path")
    p.add_argument("--checkpoint-dir", default=None,
                   help="atomic per-group resume checkpoints; restarting "
                        "serve with the same dir resumes every group from "
                        "its recorded tick (service restart survival)")
    p.add_argument("--checkpoint-every", type=int, default=0,
                   help="checkpoint cadence in ticks (0 = save only on "
                        "exit/shutdown; with --checkpoint-dir, resume-on-"
                        "start always applies)")
    p.add_argument("--journal-dir", default=None,
                   help="per-tick write-ahead journal: every ingested tick "
                        "row is appended (CRC-framed, segment-rotated) "
                        "before scoring, and a restarted serve replays the "
                        "journaled ticks past its checkpoint through the "
                        "normal scoring path — bit-identical catch-up with "
                        "exactly-once alerts across a crash. With a "
                        "journal, --ticks is the run's TOTAL tick budget "
                        "across restarts (docs/RESILIENCE.md durability)")
    p.add_argument("--journal-fsync", default="os",
                   help="journal durability policy: 'os' (page cache; "
                        "survives kill -9, not power loss — default), "
                        "'every-tick' (fsync per tick), or 'every-N' "
                        "(fsync once per N ticks, e.g. every-64)")
    p.add_argument("--journal-segment-bytes", type=int, default=4 << 20,
                   help="journal segment rotation size (bytes)")
    p.add_argument("--journal-max-segments", type=int, default=256,
                   help="hard bound on journal segments on disk (oldest "
                        "evicted + counted; checkpoint compaction normally "
                        "keeps the journal far below this)")
    p.add_argument("--supervise", action="store_true",
                   help="run serve as a supervised child process: abnormal "
                        "deaths (crash, OOM kill, kill -9) restart it with "
                        "exponential backoff under a restart budget, and "
                        "each death lands on the incident stream (needs "
                        "--checkpoint-dir; pair with --journal-dir for "
                        "tick-exact catch-up — scripts/crash_soak.py is "
                        "the acceptance soak)")
    p.add_argument("--supervise-restarts", type=int, default=10,
                   help="supervisor restart budget: abnormal deaths beyond "
                        "this exit 3 instead of restarting")
    p.add_argument("--supervise-backoff", type=float, default=0.5,
                   help="supervisor restart backoff base seconds (doubles "
                        "per consecutive fast death, capped at 30 s; a "
                        "child that stayed up >= 60 s resets the exponent)")
    p.add_argument("--replicate-to", default=None, metavar="HOST:PORT",
                   help="hot-standby replication (docs/RESILIENCE.md "
                        "failover runbook): tee every journal append — "
                        "the exact CRC-framed record bytes — to a "
                        "standby serve listening there, through a "
                        "bounded drop-oldest buffer (a slow standby "
                        "never stalls the tick; rtap_obs_repl_* sizes "
                        "the lag). Needs --journal-dir; journal "
                        "compaction pauses at the standby's ack while "
                        "one is connected")
    p.add_argument("--standby", action="store_true",
                   help="run as the hot standby: listen for a leader's "
                        "replication stream (--replicate-listen), apply "
                        "every shipped tick through the normal scoring "
                        "path (bit-identical warm state), emit nothing, "
                        "and PROMOTE to leader when the lease goes "
                        "stale — splicing the alert stream exactly-once "
                        "and serving the remaining --ticks budget. "
                        "Needs --replicate-listen, --journal-dir, "
                        "--checkpoint-dir, and --lease-file")
    p.add_argument("--replicate-listen", type=int, default=None,
                   help="standby replication listen port (0 = ephemeral)")
    p.add_argument("--lease-file", default=None,
                   help="leadership lease file (shared storage): the "
                        "leader's heartbeat thread refreshes it at "
                        "timeout/3; a standby "
                        "promotes when it goes stale, bumping the "
                        "monotonic fencing epoch — a paused old leader "
                        "that wakes up is fenced out of the alert sink "
                        "and exits rc 7 (docs/RESILIENCE.md)")
    p.add_argument("--lease-timeout", type=float, default=5.0,
                   help="seconds without a lease refresh before a "
                        "standby declares the leader dead and promotes "
                        "(staleness must persist an extra timeout/2 — "
                        "single starved heartbeat reads never false-"
                        "promote; detection ~= 1.5x timeout, so keep "
                        "the timeout <= ~5 cadences for a 10-tick "
                        "takeover budget)")
    p.add_argument("--shard", type=int, default=0,
                   help="this serve's mesh shard index: scopes the "
                        "journal/checkpoint/lease/alert paths per shard "
                        "(the ISSUE 15 shard-resource gate) and names "
                        "the control-plane lease this process claims "
                        "under --control-join")
    p.add_argument("--control-listen", type=int, default=None,
                   metavar="PORT",
                   help="host the fleet CONTROL PLANE on localhost PORT "
                        "(0 = ephemeral): one fencing lease per shard, "
                        "membership/claims, and the shard map. Needs "
                        "--control-journal — every epoch grant is "
                        "journaled write-ahead (RJ framing, fsync before "
                        "the reply), so a kill-9'd control plane "
                        "restarts with epochs strictly monotonic "
                        "(docs/RESILIENCE.md control plane)")
    p.add_argument("--control-journal", default=None, metavar="DIR",
                   help="the control plane's write-ahead journal dir — "
                        "the epoch-durability root for --control-listen")
    p.add_argument("--control-only", action="store_true",
                   help="run ONLY the control plane (no data plane): "
                        "serve leases/membership until SIGTERM, then "
                        "print a stats line — the process "
                        "scripts/fleet_chaos.py kills and restarts. "
                        "Needs --control-listen")
    p.add_argument("--control-join", default=None, metavar="HOST:PORT",
                   help="hold this shard's lease THROUGH the control "
                        "plane at HOST:PORT instead of a --lease-file: "
                        "acquire/heartbeat/fence over control RPCs. An "
                        "unreachable plane degrades — the loop keeps "
                        "ticking on the cached lease for a bounded, "
                        "counted window (--control-grace), then "
                        "self-fences; a standby never promotes on "
                        "control-plane silence (docs/RESILIENCE.md)")
    p.add_argument("--control-grace", type=float, default=None,
                   metavar="SECONDS",
                   help="the bounded cached-lease window under "
                        "--control-join (default max(10x lease timeout, "
                        "30s)): a control plane unreachable past this "
                        "self-fences the holder — fail-safe, never "
                        "split-brain")
    p.add_argument("--learn-every", type=int, default=1,
                   help="learning cadence: learn every k-th tick once the "
                        "likelihood learning_period has passed (SCALING.md "
                        "operating curve; k=1 = full-rate default)")
    p.add_argument("--learn-burst", type=int, default=1,
                   help="burst shape of the thinned cadence: B consecutive "
                        "learn ticks per k*B cycle (same device cost as "
                        "--learn-every alone; preserves TM sequence "
                        "adjacency — SCALING.md burst study)")
    p.add_argument("--learn-full-until", type=int, default=None,
                   help="ticks of full-rate learning before the cadence "
                        "thins (default: the likelihood learning_period — "
                        "the quality-correct bring-up window). 0 measures "
                        "the mature steady state (profile/bench semantics); "
                        "production fleets onboarding gradually never pay "
                        "the whole window at once")
    p.add_argument("--chunk-stagger", action="store_true",
                   help="with --micro-chunk M: rotate chunk boundaries "
                        "across groups (group i flushes at ticks == i mod "
                        "M) so each tick dispatches ~1/M of the fleet "
                        "instead of spiking the whole fleet's chunk work "
                        "onto every M-th tick. Elastic membership and "
                        "periodic checkpoints force a one-tick boundary "
                        "realignment when they fire")
    p.add_argument("--stagger-learn", action="store_true",
                   help="stagger the learning-cadence phase across groups "
                        "(group i learns on ticks == i mod k): spreads the "
                        "fleet's learning load evenly over ticks instead of "
                        "spiking every k-th tick — the 100k-streams-per-chip "
                        "serving shape (SCALING.md)")
    p.add_argument("--pipeline-depth", type=int, default=1,
                   help="2 = collect tick k after dispatching k+1: hides the "
                        "per-group device round trip (remote-chip dispatch "
                        "latency) behind the cadence sleep; alerts lag one "
                        "cadence (reports/live_soak.json measured the cost "
                        "of depth 1 at 16 groups)")
    p.add_argument("--micro-chunk", type=int, default=1,
                   help="batch M consecutive ticks into one device dispatch "
                        "per group: divides the per-program invocation floor "
                        "(~12 ms on the tunnel runtime — the 100k-soak "
                        "binder) by M, at <= (depth*M - 1) ticks of alert "
                        "staleness. The 100k-streams-per-chip cadence lever "
                        "(SCALING.md round 5)")
    p.add_argument("--dispatch-threads", type=int, default=1,
                   help="issue per-group dispatch/collect calls from N "
                        "threads: on links where each dispatch is itself a "
                        "blocking RPC (remote-chip tunnel, ~65 ms/group), "
                        "depth-2 pipelining alone cannot help — the round "
                        "trips must overlap each other "
                        "(reports/live_soak_pipelined.json measured depth 2 "
                        "at 16 groups unchanged, p50 1.07 s); output is "
                        "bit-identical to serial dispatch")
    p.add_argument("--columns", type=int, default=None,
                   help="width-scale the cluster preset's SP to N columns "
                        "(scaled_cluster_preset: ratio-preserving k-winners/"
                        "thresholds). The measured density levers: 32 col = "
                        "best f1 on the node-metric family at 1/8 state and "
                        "2.26x throughput; with --learn-every 2 it is the "
                        "135.8k/chip bench headline (SCALING.md model-width "
                        "study). Default: the conservative 256-col preset")
    p.add_argument("--auto-register", action="store_true",
                   help="lazily create a model for every NEW stream id "
                        "seen on the wire — TCP records with unknown ids, "
                        "or unregistered metric KEYS in the HTTP poll "
                        "payload (the reference's per-metric lazy model "
                        "creation / exporter discovery): each claims a "
                        "free pad slot with a fresh model + its own "
                        "likelihood probation, no recompile. Capacity = "
                        "pad slots (--reserve; default one extra group's "
                        "worth)")
    p.add_argument("--reserve", type=int, default=None,
                   help="extra claimable pad-slot capacity for post-start "
                        "registration (rounded up to whole groups; default "
                        "0, or one group's worth with --auto-register)")
    p.add_argument("--auto-release-after", type=int, default=0,
                   help="release a stream's slot after N consecutive silent "
                        "(no-record) ticks — elastic shrink for churning "
                        "clusters; the slot becomes claimable again and a "
                        "returning stream re-registers as a new model. "
                        "Pick N well above ordinary outages: NaN semantics "
                        "keep scoring through gaps, release discards the "
                        "learned context. 0 = never (default)")
    p.add_argument("--chaos-spec", default=None,
                   help="JSON fault-injection schedule (rtap_tpu.resilience."
                        "chaos: {'seed': S, 'faults': [...]} or {'seed': S, "
                        "'generate': {'n_ticks': T, 'n_groups': G, 'rate': "
                        "R}}): scripted source timeouts, dispatch "
                        "exceptions, alert-sink OSErrors, checkpoint write "
                        "failures etc. injected at exactly the scheduled "
                        "ticks — deterministic per seed (docs/RESILIENCE.md)")
    p.add_argument("--degrade", action="store_true",
                   help="shed load under sustained deadline misses, down "
                        "the declared ladder: learn_thin -> score_only -> "
                        "tick_widen, with hysteresis; emits degraded/"
                        "recovered events and the rtap_obs_degradation_"
                        "level gauge (docs/RESILIENCE.md)")
    p.add_argument("--degrade-after", type=int, default=3,
                   help="misses within the 10-tick window that escalate "
                        "the ladder one level (with --degrade)")
    p.add_argument("--degrade-recover-after", type=int, default=15,
                   help="consecutive clean ticks that de-escalate one "
                        "level (with --degrade)")
    p.add_argument("--quarantine-restore-after", type=int, default=0,
                   help="re-load a quarantined group from its last "
                        "checkpoint after this many ticks of cooldown "
                        "(needs --checkpoint-dir; 0 = quarantine is "
                        "permanent for the run). The group loses the ticks "
                        "since its last save; every other group's cadence "
                        "is untouched either way")
    p.add_argument("--aot-warmup", action=argparse.BooleanOptionalAction,
                   default=True,
                   help="compile every knowable (chunk length, config, "
                        "learn-phase) program before tick 0 (service/aot.py) "
                        "so no XLA compile lands inside a scored tick — the "
                        "1h 100k soak's 9 missed deadlines were all warm-up "
                        "compiles; --no-aot-warmup restores lazy compilation")
    p.add_argument("--alert-flush-every", type=int, default=1,
                   help="flush the alert JSONL sink once per N batches "
                        "instead of per batch (1 = per batch, the crash-"
                        "safe default; higher trades at most N batches of "
                        "alert loss on a crash for less write overhead)")
    p.add_argument("--obs-port", type=int, default=None,
                   help="serve the telemetry registry over localhost HTTP "
                        "(GET /metrics = Prometheus v0 text, GET /snapshot "
                        "= JSON); 0 binds an ephemeral port, default: no "
                        "endpoint")
    p.add_argument("--obs-snapshot", default=None,
                   help="append one JSONL telemetry snapshot line to this "
                        "file on exit (default: $RTAP_OBS_SNAPSHOT if set "
                        "— the no-network hw-session surface)")
    p.add_argument("--fleet-join", default=None, metavar="HOST:PORT",
                   help="join the fleet observability plane: push this "
                        "process's full telemetry (registry snapshot, "
                        "health rollup, latency sketch states, SLO "
                        "windows, open-incident digest) to the fleet "
                        "aggregator at HOST:PORT once per "
                        "--fleet-push-interval, off the tick path "
                        "(docs/FLEET.md)")
    p.add_argument("--fleet-listen", type=int, default=None, metavar="PORT",
                   help="host the fleet aggregator: accept member pushes "
                        "on localhost PORT (0 = ephemeral) and serve the "
                        "merged one-pane-of-glass views on the obs "
                        "server's GET /fleet/* routes (requires "
                        "--obs-port; docs/FLEET.md)")
    p.add_argument("--fleet-push-interval", type=float, default=None,
                   metavar="SECONDS",
                   help="fleet telemetry push cadence (default 1.0; needs "
                        "--fleet-join). The member declares 3 missed "
                        "pushes as its DOWN staleness horizon")
    p.add_argument("--trace-out", default=None,
                   help="write the per-tick host span timeline as Chrome "
                        "trace-event JSON to this file on exit (load it in "
                        "ui.perfetto.dev; docs/POSTMORTEM.md). Tracing is "
                        "a bounded in-memory ring, near-zero overhead — "
                        "also served live at GET /trace?last=N with "
                        "--obs-port")
    p.add_argument("--trace-ring", type=int, default=65536,
                   help="span-ring capacity in records PER WRITER THREAD "
                        "(~33 B each); older records are overwritten and "
                        "counted in rtap_obs_trace_dropped")
    p.add_argument("--postmortem-dir", default=None,
                   help="arm the black-box flight recorder: the last "
                        "--flight-ticks ticks of spans/events/metric "
                        "deltas auto-dump here as an atomic postmortem "
                        "bundle on group quarantine, degradation-level "
                        "change, missed-tick burst, or a crash "
                        "(scripts/postmortem.py pretty-prints one; "
                        "docs/POSTMORTEM.md is the runbook)")
    p.add_argument("--flight-ticks", type=int, default=240,
                   help="flight-recorder window: how many recent ticks a "
                        "postmortem bundle covers (bounded ring; memory "
                        "is O(flight_ticks * n_groups))")
    p.add_argument("--health", action="store_true",
                   help="model-health observability (docs/TELEMETRY.md "
                        "health section): fused on-device reducers add "
                        "segment-pool occupancy, permanence sketch, SDR "
                        "sparsity, hit rate and score histograms (~200 B/"
                        "group/tick, pure reads — scores and state are "
                        "bit-identical) to every chunk; a HealthTracker "
                        "folds them into per-group scorecards served at "
                        "GET /health, detects score drift by EWMA, and "
                        "raises pool_saturated / sparsity_collapsed / "
                        "score_drift incidents that auto-dump postmortem "
                        "bundles like a quarantine does")
    p.add_argument("--health-occupancy-threshold", type=float, default=0.9,
                   help="segment-pool mean occupancy fraction at/above "
                        "which a group raises pool_saturated (with "
                        "--health; ROADMAP-3 right-sizing signal)")
    p.add_argument("--health-sparsity-min-frac", type=float, default=0.5,
                   help="fraction of the expected active-column density "
                        "(k/C) below which a live group raises "
                        "sparsity_collapsed (with --health)")
    p.add_argument("--health-drift-threshold", type=float, default=0.25,
                   help="total-variation distance between the fast and "
                        "slow EWMA score distributions at/above which a "
                        "group raises score_drift (with --health)")
    p.add_argument("--health-drift-min-ticks", type=int, default=120,
                   help="scored ticks a group must fold before the drift "
                        "detector may fire (the slow EWMA baseline needs "
                        "weight before a distance to it means anything)")
    p.add_argument("--predict", action="store_true",
                   help="predictive horizon (docs/PREDICT.md): a fused "
                        "on-device reducer scores the TM's own "
                        "predictions against the input that actually "
                        "arrives k ticks later (~13 B/stream/tick, pure "
                        "reads — scores and state are bit-identical) and "
                        "a PredictTracker turns sustained predictive "
                        "divergence into precursor events with a "
                        "predicted lead time, BEFORE the anomaly score "
                        "crosses the alert threshold; with --topology, "
                        "precursors fuse into a single "
                        "predicted_incident with a predicted blast "
                        "radius at the FIRST node (GET /predict with "
                        "--obs-port)")
    p.add_argument("--predict-horizon", type=int, default=None,
                   help="prediction lead k in ticks: each tick's "
                        "predicted-active columns are scored against the "
                        "input k ticks later, so precursors carry a "
                        "~k-tick predicted lead (default 8, with "
                        "--predict)")
    p.add_argument("--predict-threshold", type=float, default=None,
                   help="predictive-miss EWMA level at/above which a "
                        "stream counts as diverging (default 0.35, with "
                        "--predict)")
    p.add_argument("--predict-min-ticks", type=int, default=None,
                   help="consecutive diverging scored ticks before a "
                        "precursor fires — edge-triggered hysteresis, "
                        "one event per excursion (default 12, with "
                        "--predict)")
    p.add_argument("--topology", default=None,
                   help="arm topology-aware incident correlation "
                        "(rtap_tpu/correlate/, docs/WORKLOADS.md): a JSON "
                        "topology spec path ({'services': {...}, 'links': "
                        "[...]}) or the literal 'infer' to derive node/"
                        "service adjacency from stream-name prefixes. "
                        "Per-stream alerts on adjacent nodes fold into "
                        "cluster-level 'incident' events on the alert "
                        "stream (member alert_ids, blast-radius node set, "
                        "onset tick, attributed fields), served live at "
                        "GET /incidents. Needs --alerts (incidents ride "
                        "the alert stream)")
    p.add_argument("--correlate-window", type=int, default=None,
                   help="incident correlation quiescence window in "
                        "SECONDS of source timestamp (== ticks at the "
                        "standard 1 s cadence): a cluster's window closes "
                        "after this long without a new member alert — "
                        "re-bursts inside it extend the same incident "
                        "(hysteresis). Size it above the pipeline's alert "
                        "staleness (pipeline_depth * micro_chunk ticks). "
                        "Default 30; needs --topology")
    p.add_argument("--correlate-min-streams", type=int, default=None,
                   help="distinct alerting streams a closed window needs "
                        "to emit an incident; below it the window expires "
                        "silently (the per-stream alert lines already "
                        "told that story). Default 3; needs --topology")
    p.add_argument("--latency", action="store_true",
                   help="arm detection-latency observability (docs/SLO.md; "
                        "docs/TELEMETRY.md latency section): per-tick "
                        "stage waterfalls (source ts -> ingest arrival/"
                        "backfill release -> dispatch -> collect -> "
                        "alert-sink flush) folded into bounded windowed "
                        "quantile sketches, a per-alert end-to-end detect "
                        "sketch observed at sink-write time, and first-"
                        "class replication-ack / incident-close lag "
                        "gauges. Host wall clocks only — zero extra "
                        "device fetches; alert stream and model state are "
                        "byte/bit-identical with the flag off")
    p.add_argument("--latency-window", type=int, default=None,
                   help="quantile-sketch window in ticks (default 120): "
                        "GET /latency reports p50/p95/p99/p99.9 over the "
                        "last one-to-two windows, next to lifetime "
                        "totals. Needs --latency")
    p.add_argument("--slo", action="append", default=None,
                   metavar="NAME=TARGET@pQ",
                   help="declare a latency SLO (repeatable), e.g. "
                        "detect=2s@p99 ('99%% of alerts within 2s of "
                        "their row's source timestamp') or tick=500ms@p95. "
                        "Stages: detect, tick, ingest, dispatch, collect, "
                        "emit. Evaluated with fast/slow multi-window "
                        "burn rates; edge-triggered slo_burn/"
                        "slo_recovered/slo_budget_exhausted events ride "
                        "the alert stream, a fast burn dumps a postmortem "
                        "bundle, and the run's verdict lands in the stats "
                        "line + GET /slo (docs/SLO.md). Needs --latency")
    p.add_argument("--slo-fast-window", type=int, default=None,
                   help="fast burn-rate window in ticks (default 60; "
                        "1 min at 1 s cadence). Needs --slo")
    p.add_argument("--slo-slow-window", type=int, default=None,
                   help="slow burn-rate window in ticks (default 600; "
                        "10 min at 1 s cadence; must be >= the fast "
                        "window). Needs --slo")
    p.add_argument("--alert-attribution", action="store_true",
                   help="per-alert provenance: alert JSONL lines gain a "
                        "top_fields block naming the encoder fields whose "
                        "representation moved most (SDR bucket-overlap "
                        "decode — service/attribution.py); meaningful for "
                        "multivariate models, cheap either way")
    p.add_argument("--jax-trace", default=None,
                   help="wrap the serve window in jax.profiler.trace "
                        "writing the XLA device trace to this directory "
                        "(pairs with --trace-out: host + device timelines "
                        "of the same ticks — the hw_session device-trace "
                        "step)")
    p.add_argument("--freeze", action="store_true",
                   help="inference-only serving (NuPIC disableLearning "
                        "parity): SP/TM/classifier state is bit-frozen, raw "
                        "scores and alerts still flow, and the anomaly "
                        "likelihood keeps adapting (it is the score "
                        "normalizer, not model state). Skips the learning "
                        "pass — ~85%% of the fused step on silicon "
                        "(SCALING.md); pair with --checkpoint-dir to serve "
                        "a trained model frozen (the dir becomes strictly "
                        "read-only: frozen serving resumes from it but "
                        "never writes, so replicas can share it)")
    p.set_defaults(fn=_cmd_serve)

    p = sub.add_parser("replay", help="synthetic cluster replay at full speed")
    p.add_argument("--nodes", type=int, default=32, help="nodes x 3 metrics = streams")
    p.add_argument("--length", type=int, default=1500)
    p.add_argument("--magnitude", type=float, default=6.0)
    p.add_argument("--group-size", type=int, default=None)
    p.add_argument("--chunk-ticks", type=int, default=64)
    p.add_argument("--backend", default="tpu")
    p.add_argument("--threshold", type=float, default=0.5)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--alerts", default=None)
    p.add_argument("--checkpoint-dir", default=None,
                   help="atomic per-group resume checkpoints; a rerun with "
                        "the same dir resumes each group from its last "
                        "checkpointed tick (crash recovery)")
    p.add_argument("--checkpoint-every", type=int, default=4,
                   help="checkpoint cadence in collected chunks (with "
                        "--checkpoint-dir)")
    p.add_argument("--debounce", type=int, default=2,
                   help="alert only after this many consecutive ticks at/"
                        "above threshold")
    p.add_argument("--learn-every", type=int, default=1,
                   help="learning cadence: learn every k-th tick once the "
                        "likelihood learning_period has passed (SCALING.md "
                        "operating curve; k=1 = full-rate default)")
    p.add_argument("--learn-burst", type=int, default=1,
                   help="burst shape of the thinned cadence: B consecutive "
                        "learn ticks per k*B cycle (same device cost as "
                        "--learn-every alone; preserves TM sequence "
                        "adjacency — SCALING.md burst study)")
    p.add_argument("--freeze", action="store_true",
                   help="inference-only replay (NuPIC disableLearning "
                        "parity): no SP/TM/classifier updates; likelihood "
                        "still adapts")
    p.add_argument("--columns", type=int, default=None,
                   help="width-scale the cluster preset (see serve --columns)")
    p.set_defaults(fn=_cmd_replay)

    p = sub.add_parser("eval", help="fault-injection evaluation -> JSON report")
    p.add_argument("--streams", type=int, default=120)
    p.add_argument("--length", type=int, default=1500)
    p.add_argument("--magnitude", type=float, default=6.0)
    p.add_argument("--all-kinds", action="store_true")
    p.add_argument("--backend", default="tpu")
    p.add_argument("--debounce", type=int, default=2)
    p.add_argument("--likelihood", choices=("window", "streaming"),
                   default="streaming",
                   help="likelihood mode; streaming is the production config "
                        "behind the headline artifact (reports/"
                        "fault_eval.json), window the comparison study")
    p.add_argument("--learning-period", type=int, default=None,
                   help="override the likelihood probation length in ticks")
    p.add_argument("--learn-every", type=int, default=1,
                   help="learning cadence: learn every k-th tick once the "
                        "likelihood learning_period has passed (SCALING.md "
                        "operating curve; k=1 = full-rate default)")
    p.add_argument("--learn-burst", type=int, default=1,
                   help="burst shape of the thinned cadence: B consecutive "
                        "learn ticks per k*B cycle (same device cost as "
                        "--learn-every alone; preserves TM sequence "
                        "adjacency — SCALING.md burst study)")
    p.add_argument("--out", default=None)
    p.set_defaults(fn=_cmd_eval)

    p = sub.add_parser(
        "nab",
        help="NAB corpus run: detect -> threshold sweep -> normalized score")
    p.add_argument("--corpus", default=None,
                   help="NAB-layout corpus root (data/**/*.csv + labels/"
                        "combined_windows.json). Default: $RTAP_NAB_CORPUS, "
                        "else the committed stand-in at <repo>/data/nab. "
                        "Point this at the real NAB checkout the moment one "
                        "is available — the run is mechanical (SURVEY.md §6 "
                        "blocker drill)")
    p.add_argument("--subset", default=None,
                   help="relative-path prefix filter, e.g. realAWSCloudwatch")
    p.add_argument("--backend", default="tpu", choices=("tpu", "cpu"),
                   help="tpu = all files as one vmapped device group; cpu = "
                        "per-file oracle (slow at full width)")
    p.add_argument("--columns", type=int, default=None,
                   help="width-scaled NAB model (scaled_nab_preset) instead "
                        "of the 2048-column preset")
    p.add_argument("--rows", type=int, default=None,
                   help="truncate files to this many rows (cheap drives)")
    p.add_argument("--out", default=None, help="report JSON path (default: "
                                               "print scores only)")
    p.set_defaults(fn=_cmd_nab)

    p = sub.add_parser("report", help="matplotlib overlays (metric/likelihood/alerts)")
    p.add_argument("--out-dir", default="reports")
    p.add_argument("--streams", type=int, default=6)
    p.add_argument("--length", type=int, default=900)
    p.add_argument("--eval-report", default=None)
    p.set_defaults(fn=_cmd_report)

    args = ap.parse_args(argv)
    # cheap flag-consistency checks BEFORE backend init: a usage error must
    # surface instantly, not after a 120 s wedged-tunnel watchdog
    if getattr(args, "preset", "cluster") != "cluster" and \
            getattr(args, "columns", None) is not None:
        print("serve: --columns applies to the cluster preset only "
              "(the NAB family scales via scaled_nab_preset; the "
              "composite/categorical presets fix their field geometry)",
              file=sys.stderr)
        return 2
    if (getattr(args, "correlate_window", None) is not None
            or getattr(args, "correlate_min_streams", None) is not None) \
            and not getattr(args, "topology", None):
        print("serve: --correlate-window/--correlate-min-streams are "
              "incident-correlation knobs; add --topology (a spec path "
              "or 'infer')", file=sys.stderr)
        return 2
    if getattr(args, "topology", None) and not getattr(args, "alerts", None):
        print("serve: --topology needs --alerts — incidents are emitted "
              "on (and resume-recovered from) the alert stream",
              file=sys.stderr)
        return 2
    if (getattr(args, "correlate_window", None) is not None
            and args.correlate_window < 1):
        print("serve: --correlate-window must be >= 1", file=sys.stderr)
        return 2
    if (getattr(args, "correlate_min_streams", None) is not None
            and args.correlate_min_streams < 2):
        print("serve: --correlate-min-streams must be >= 2 (one stream "
              "is a per-stream alert, not an incident)", file=sys.stderr)
        return 2
    if (getattr(args, "predict_horizon", None) is not None
            or getattr(args, "predict_threshold", None) is not None
            or getattr(args, "predict_min_ticks", None) is not None) \
            and not getattr(args, "predict", False):
        print("serve: --predict-horizon/--predict-threshold/"
              "--predict-min-ticks are predictive-horizon knobs; add "
              "--predict", file=sys.stderr)
        return 2
    if getattr(args, "predict_horizon", None) is not None \
            and args.predict_horizon < 1:
        print("serve: --predict-horizon must be >= 1 (the reducer scores "
              "each tick's prediction against the input that many ticks "
              "later)", file=sys.stderr)
        return 2
    if getattr(args, "predict_min_ticks", None) is not None \
            and args.predict_min_ticks < 1:
        print("serve: --predict-min-ticks must be >= 1", file=sys.stderr)
        return 2
    if getattr(args, "slo", None) and not getattr(args, "latency", False):
        print("serve: --slo declares an objective over the latency "
              "tracker's measurements; add --latency", file=sys.stderr)
        return 2
    if getattr(args, "latency_window", None) is not None \
            and not getattr(args, "latency", False):
        print("serve: --latency-window sizes the quantile-sketch window; "
              "add --latency", file=sys.stderr)
        return 2
    if (getattr(args, "slo_fast_window", None) is not None
            or getattr(args, "slo_slow_window", None) is not None) \
            and not getattr(args, "slo", None):
        print("serve: --slo-fast-window/--slo-slow-window are burn-rate "
              "knobs; add --slo NAME=TARGET@pQ", file=sys.stderr)
        return 2
    if getattr(args, "latency_window", None) is not None \
            and args.latency_window < 1:
        print("serve: --latency-window must be >= 1", file=sys.stderr)
        return 2
    if getattr(args, "http", None) and (
            getattr(args, "ingest_port", None) is not None
            or getattr(args, "ingest_shm", None)):
        print("serve: --http and --ingest-port/--ingest-shm are exclusive "
              "(one source feeds the loop)", file=sys.stderr)
        return 2
    if getattr(args, "port", 0) and (
            getattr(args, "ingest_port", None) is not None
            or getattr(args, "ingest_shm", None)):
        print("serve: --port (JSONL listener) and --ingest-port/--ingest-shm "
              "are exclusive — the binary source replaces the JSONL one; "
              "a JSONL producer pointed at --port would get connection "
              "refused while serve reports healthy", file=sys.stderr)
        return 2
    if (getattr(args, "ingest_quota", 0)
            or getattr(args, "ingest_backfill_horizon", 0)) \
            and getattr(args, "ingest_port", None) is None \
            and not getattr(args, "ingest_shm", None):
        print("serve: --ingest-quota/--ingest-backfill-horizon are binary-"
              "ingest admission knobs; add --ingest-port or --ingest-shm",
              file=sys.stderr)
        return 2
    if getattr(args, "replicate_to", None) and not getattr(args, "journal_dir", None):
        print("serve: --replicate-to ships the write-ahead journal — add "
              "--journal-dir", file=sys.stderr)
        return 2
    if getattr(args, "replicate_to", None) \
            and not getattr(args, "lease_file", None) \
            and not getattr(args, "control_join", None) \
            and not getattr(args, "standby", False):
        print("serve: --replicate-to needs --lease-file (or "
              "--control-join) — a leader without the lease cannot be "
              "fenced, and its standby (which requires the lease) would "
              "find it absent and promote immediately: two live leaders "
              "on one alert sink", file=sys.stderr)
        return 2
    if getattr(args, "replicate_to", None) \
            and not getattr(args, "checkpoint_dir", None):
        print("serve: --replicate-to needs --checkpoint-dir — the "
              "shared checkpoint dir is the reconnect-after-gap "
              "fallback (a standby whose position was compacted or "
              "evicted out of the journal resyncs from it) and the "
              "promotion target", file=sys.stderr)
        return 2
    if getattr(args, "standby", False):
        missing = [f for f, v in (
            ("--replicate-listen", args.replicate_listen is not None),
            ("--journal-dir", bool(args.journal_dir)),
            ("--checkpoint-dir", bool(args.checkpoint_dir)),
            ("--lease-file or --control-join",
             bool(args.lease_file) or bool(getattr(args, "control_join",
                                                   None))),
        ) if not v]
        if missing:
            print(f"serve: --standby needs {', '.join(missing)} (the "
                  "standby mirrors the journal, promotes from the shared "
                  "checkpoint dir, and watches the lease)", file=sys.stderr)
            return 2
        if args.supervise:
            print("serve: --standby under --supervise is unsupported — "
                  "supervise the PAIR from scripts/failover_soak.py "
                  "instead (roles swap across restarts)", file=sys.stderr)
            return 2
    if (getattr(args, "standby", False)
            or getattr(args, "replicate_to", None)) and (
            getattr(args, "auto_register", False)
            or getattr(args, "auto_release_after", 0)):
        print("serve: replication requires a FIXED fleet — "
              "--auto-register/--auto-release-after change membership "
              "mid-stream and the standby's slot addressing would "
              "diverge (elastic membership under replication is future "
              "work)", file=sys.stderr)
        return 2
    if (getattr(args, "standby", False)
            or getattr(args, "replicate_to", None)) \
            and getattr(args, "topology", None):
        print("serve: --topology under replication is unsupported — the "
              "standby buffers would-be alert lines without correlation "
              "state, so a post-failover incident stream could not stay "
              "identical to the leader's (correlation under replication "
              "is future work)", file=sys.stderr)
        return 2
    if (getattr(args, "standby", False)
            or getattr(args, "replicate_to", None)) \
            and getattr(args, "alert_attribution", False):
        print("serve: --alert-attribution under replication is "
              "unsupported — the standby buffers would-be alert lines "
              "WITHOUT the attributor's routing history, so a "
              "post-failover splice could not stay byte-identical to "
              "the leader's stream (attribution under replication is "
              "future work)", file=sys.stderr)
        return 2
    if (getattr(args, "standby", False)
            or getattr(args, "replicate_to", None)) \
            and getattr(args, "predict", False):
        print("serve: --predict under replication is unsupported — the "
              "standby buffers would-be alert lines WITHOUT the "
              "tracker's hysteresis state, so a post-failover precursor "
              "stream could not stay identical to the leader's "
              "(predictive horizon under replication is future work)",
              file=sys.stderr)
        return 2
    if getattr(args, "replicate_listen", None) is not None \
            and not getattr(args, "standby", False):
        print("serve: --replicate-listen is the standby's listen port — "
              "add --standby (the leader side uses --replicate-to)",
              file=sys.stderr)
        return 2
    if getattr(args, "fleet_join", None):
        fhost, fsep, fport_s = args.fleet_join.rpartition(":")
        try:
            fport = int(fport_s)
        except ValueError:
            fport = -1
        if not fsep or not (0 < fport < 65536):
            print(f"serve: bad --fleet-join {args.fleet_join!r} — expected "
                  "HOST:PORT (the fleet aggregator's listen address; an "
                  "empty HOST means 127.0.0.1)", file=sys.stderr)
            return 2
    if getattr(args, "fleet_listen", None) is not None:
        if not (0 <= args.fleet_listen < 65536):
            print("serve: --fleet-listen must be a TCP port "
                  "(0 = ephemeral)", file=sys.stderr)
            return 2
        if getattr(args, "obs_port", None) is None:
            print("serve: --fleet-listen serves the merged fleet views "
                  "on the obs HTTP server's /fleet/* routes; add "
                  "--obs-port", file=sys.stderr)
            return 2
    if getattr(args, "fleet_push_interval", None) is not None:
        if not getattr(args, "fleet_join", None):
            print("serve: --fleet-push-interval paces the fleet "
                  "telemetry push; add --fleet-join HOST:PORT",
                  file=sys.stderr)
            return 2
        if args.fleet_push_interval <= 0:
            print("serve: --fleet-push-interval must be > 0",
                  file=sys.stderr)
            return 2
    if getattr(args, "control_listen", None) is not None:
        if not (0 <= args.control_listen < 65536):
            print("serve: --control-listen must be a TCP port "
                  "(0 = ephemeral)", file=sys.stderr)
            return 2
        if not getattr(args, "control_journal", None):
            print("serve: --control-listen needs --control-journal — the "
                  "write-ahead epoch journal is what keeps fencing "
                  "monotonic across a control-plane crash",
                  file=sys.stderr)
            return 2
    if getattr(args, "control_journal", None) \
            and getattr(args, "control_listen", None) is None:
        print("serve: --control-journal is the control plane's journal "
              "dir; add --control-listen PORT", file=sys.stderr)
        return 2
    if getattr(args, "control_only", False) \
            and getattr(args, "control_listen", None) is None:
        print("serve: --control-only runs just the control plane; add "
              "--control-listen PORT (and --control-journal)",
              file=sys.stderr)
        return 2
    if args.command == "serve" and args.streams is None \
            and not getattr(args, "control_only", False):
        # --streams is only optional for the pure control-plane process
        # (it scores nothing); every data-plane serve must name its fleet
        print("serve: --streams is required (only --control-only runs "
              "without a stream fleet)", file=sys.stderr)
        return 2
    if getattr(args, "control_join", None):
        if getattr(args, "lease_file", None):
            print("serve: --control-join and --lease-file are exclusive "
                  "— one lease authority per process (under a control "
                  "plane, IT owns the shard lease)", file=sys.stderr)
            return 2
        chost, csep, cport_s = args.control_join.rpartition(":")
        try:
            cport = int(cport_s)
        except ValueError:
            cport = -1
        if not csep or not (0 < cport < 65536):
            print(f"serve: bad --control-join {args.control_join!r} — "
                  "expected HOST:PORT (the control plane's listen "
                  "address; an empty HOST means 127.0.0.1)",
                  file=sys.stderr)
            return 2
    if getattr(args, "control_grace", None) is not None:
        if not getattr(args, "control_join", None):
            print("serve: --control-grace bounds the cached-lease window "
                  "under --control-join; add --control-join HOST:PORT",
                  file=sys.stderr)
            return 2
        if args.control_grace <= 0:
            print("serve: --control-grace must be > 0", file=sys.stderr)
            return 2
    if getattr(args, "shard", 0) < 0:
        print("serve: --shard must be >= 0 (the mesh shard index)",
              file=sys.stderr)
        return 2
    if getattr(args, "freeze", False) and getattr(args, "auto_register", False):
        print("serve: --freeze with --auto-register would claim fresh "
              "models that can never learn — a lazily registered stream "
              "would score garbage forever. Register streams in a "
              "learning serve, then freeze; or serve frozen with a fixed "
              "fleet", file=sys.stderr)
        return 2
    if getattr(args, "supervise", False):
        # supervision wraps the WHOLE child serve (including backend
        # init): handle it before this process touches the backend — the
        # parent must never hold the chip its child needs
        if not args.checkpoint_dir:
            print("serve: --supervise needs --checkpoint-dir (a restarted "
                  "child must resume its fleet, not rescore from scratch); "
                  "add --journal-dir for tick-exact catch-up",
                  file=sys.stderr)
            return 2
        if not args.journal_dir:
            print("serve: --supervise without --journal-dir will lose the "
                  "ticks since the last checkpoint on every restart "
                  "(continuity yes, bit-exact catch-up no)", file=sys.stderr)
        from rtap_tpu.resilience.supervisor import (
            Supervisor,
            strip_supervise_flags,
        )

        raw = list(argv) if argv is not None else sys.argv[1:]
        child_cmd = [sys.executable, "-m", "rtap_tpu",
                     *strip_supervise_flags(raw)]
        sup = Supervisor(
            child_cmd, restart_budget=args.supervise_restarts,
            backoff_base_s=args.supervise_backoff,
            backoff_max_s=max(30.0, args.supervise_backoff),
            event_path=args.alerts, postmortem_dir=args.postmortem_dir,
            log=lambda m: print(m, file=sys.stderr))
        print(f"serve: supervising {' '.join(child_cmd[3:])} "
              f"(restart budget {args.supervise_restarts})", file=sys.stderr)
        sup_pub = None
        if getattr(args, "fleet_join", None):
            # the supervisor is a fleet member too: its restart-budget
            # counters and liveness ride the same plane as its child
            # (which inherits --fleet-join and registers separately)
            from rtap_tpu.fleet import FleetPublisher

            shost, _ssep, sport_s = args.fleet_join.rpartition(":")
            sup_pub = FleetPublisher(
                (shost or "127.0.0.1", int(sport_s)),
                f"supervisor-{os.getpid()}", role="supervisor",
                push_interval_s=args.fleet_push_interval
                if args.fleet_push_interval is not None else 1.0).start()
        try:
            return sup.run()
        finally:
            if sup_pub is not None:
                sup_pub.close()
    if getattr(args, "backend", None) == "tpu":
        # fail in 120s on a wedged tunnel instead of hanging the operator's
        # terminal, and reuse compiled programs across service restarts
        from rtap_tpu.utils.platform import enable_compile_cache, init_backend_or_die

        init_backend_or_die()
        enable_compile_cache(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
