"""Spatial Pooler — device kernel (functional twin of oracle/spatial_pooler.py).

The reference's SP hot loop is SpatialPooler.cpp's sparse matvec + inhibition
(SURVEY.md C3, §3.2). Two TPU-native pool layouts (SPConfig.sparse_pool):

* dense (default): the connected-synapse mask is a dense bool [C, n_in];
  overlap is a 0/1 matmul that XLA tiles onto the MXU (counts < 2^24, so f32
  accumulation is exact).
* sparse (ISSUE 18): the pool is a member-index table [C, P] of input
  indices (-1 = empty slot) + perm [C, P]; overlap gathers the SDR at the
  member indices and reduces over the P lane — an O(C*P) VPU
  gather-and-count instead of the O(C*n_in) matmul, and the learning pass
  sweeps C*P instead of C*n_in permanence slots. On a memory-bound step the
  byte traffic, not the flop count, is the cost (docs/KERNELS.md roofline
  section), so shrinking the swept plane is both the HBM and the
  throughput lever. Counts stay exact integers on both layouts.

Inhibition is `lax.top_k` over an integer score that encodes the low-index
tie-break, making winner selection bit-identical to the oracle's argsort on
either layout.

State dict keys/layout are shared with the oracle (models/state.py); this
module never mutates — it returns the updated SP slice of the state dict.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from rtap_tpu.config import SPConfig
from rtap_tpu.models.perm import sp_domain


def _gather_sdr(pool: jnp.ndarray, sdr: jnp.ndarray) -> jnp.ndarray:
    """SDR bits at each member slot: bool [C, P]. Empty slots (-1) gather
    index 0 and are masked out by every caller via ``pool >= 0`` — the
    clamp keeps the gather in-bounds so the backend never sees the
    sentinel (out-of-bounds gather semantics are backend-defined)."""
    return sdr[jnp.maximum(pool, 0).astype(jnp.int32)]


# rtap: twin[sp_overlap] — explicit-tensor calling convention vs the
# oracle's state-dict one; same math, parity in test_twin_registry.py
def sp_overlap(perm: jnp.ndarray, pool: jnp.ndarray, sdr: jnp.ndarray, cfg: SPConfig) -> jnp.ndarray:
    """Overlap per column = |connected potential synapses ∩ active inputs|.

    `pool` is the layout-defining tensor: dense bool potential mask
    [C, n_in], or the sparse member-index table [C, P]. Exact integer
    counts either way (dense: 0/1 f32 matmul -> MXU; sparse: gather +
    masked popcount on the VPU)."""
    thr = sp_domain(cfg).threshold(cfg.syn_perm_connected)
    if cfg.sparse_pool:
        connected = (perm >= thr) & (pool >= 0)
        hit = _gather_sdr(pool, sdr)
        return jnp.sum((connected & hit).astype(jnp.int32), axis=1)
    connected = ((perm >= thr) & pool).astype(jnp.float32)
    return jnp.dot(connected, sdr.astype(jnp.float32), precision=jax.lax.Precision.HIGHEST).astype(jnp.int32)


def sp_inhibit(overlap: jnp.ndarray, boost: jnp.ndarray, cfg: SPConfig) -> jnp.ndarray:
    """Global k-winner inhibition -> bool[C]. Score = overlap*C + (C-1-c)
    (quantized to 1/256 under boosting) is unique per column, so top_k has no
    ties and matches the oracle's descending argsort exactly when
    boost_strength == 0 (the NAB preset). Under boosting, a 1-ulp host/device
    exp() difference on an exact .5 rounding boundary of q can still flip a
    winner — statistically negligible, and tolerated by the boost parity test.
    """
    C = overlap.shape[0]
    col_rev = (C - 1 - jnp.arange(C, dtype=jnp.int32))
    if cfg.boost_strength > 0.0:
        # q*C + col_rev must stay < 2^31: the device computes the score
        # in i32 while the host oracle widens to i64, so an unclamped q
        # (pathological boost × overlap > ~8M/C) would WRAP here and
        # invert winners on TPU only. Both twins clamp IN F32, BEFORE
        # the int cast — an out-of-range f32→i32 convert is backend-
        # defined, so clamping after it would rest on exactly the
        # nonportability this guards against. The extra min(·, 2^24)
        # keeps qmax f32-EXACT for every C: for C < 128 the raw bound
        # exceeds 2^24 and float32() would round it UP (C=64 →
        # 33554431 → 2^25), re-opening the wrap; capped at 2^24 the
        # compare and casts are exact and q*C ≤ 2^24·C < 2^31
        # whenever the raw bound was the larger one. Twins stay
        # bit-identical in every regime (the ISSUE 14 dtype-domain
        # gate's i32-wrap rule pins this shape).
        qmax = jnp.float32(min((2**31 - C) // C, 2**24))
        qf = jnp.round(overlap.astype(jnp.float32) * boost * 256.0)
        q = jnp.clip(qf, 0.0, qmax).astype(jnp.int32)
        score = q * C + col_rev
    else:
        score = overlap * C + col_rev
    _, winners = jax.lax.top_k(score, cfg.num_active_columns)
    active = jnp.zeros(C, bool).at[winners].set(True, unique_indices=True)
    return active & (overlap >= cfg.stimulus_threshold)


def sp_learn(
    state: dict, sdr: jnp.ndarray, overlap: jnp.ndarray, active: jnp.ndarray, cfg: SPConfig
) -> dict:
    """Hebbian update on winners + duty cycles + boost + weak-column bump.
    Same op order as the oracle (hebbian -> clip -> duty -> boost -> bump ->
    clip); inc/dec masks are disjoint so the fused expression is bit-equal to
    the oracle's sequential += / -=. Quantized domains compute in int32
    (bit-equal to the oracle's int32 by construction). Sparse layout: the
    per-slot SDR bit comes from the member-index gather and the valid mask
    (members >= 0) plays the dense potential mask's role in every term."""
    dom = sp_domain(cfg)
    if cfg.sparse_pool:
        pool = state["members"]
        valid = pool >= 0
        hit = _gather_sdr(pool, sdr)
        inc_mask = active[:, None] & valid & hit
        dec_mask = active[:, None] & valid & ~hit
        bump_pool = valid
    else:
        pool = state["potential"]
        inc_mask = active[:, None] & pool & sdr[None, :]
        dec_mask = active[:, None] & pool & ~sdr[None, :]
        bump_pool = pool
    perm = state["perm"].astype(dom.compute_dtype)
    perm = perm + dom.rate(cfg.syn_perm_active_inc) * inc_mask - dom.rate(cfg.syn_perm_inactive_dec) * dec_mask
    perm = jnp.clip(perm, dom.zero, dom.one)

    it = state["sp_iter"] + 1
    period = jnp.minimum(cfg.duty_cycle_period, it).astype(jnp.float32)
    overlap_now = (overlap > 0).astype(jnp.float32)
    # d += (x-d)/p form (not (d*(p-1)+x)/p): sub/div/add has no multiply-add
    # for XLA to FMA-contract, keeping device duty bit-identical to the numpy
    # oracle (an optimization_barrier does NOT stop the contraction; observed).
    overlap_duty = state["overlap_duty"] + (overlap_now - state["overlap_duty"]) / period
    active_duty = state["active_duty"] + (active.astype(jnp.float32) - state["active_duty"]) / period

    boost = state["boost"]
    if cfg.boost_strength > 0.0:
        target = cfg.num_active_columns / perm.shape[0]
        boost = jnp.exp((target - active_duty) * cfg.boost_strength).astype(jnp.float32)

    min_duty = cfg.min_pct_overlap_duty_cycle * overlap_duty.max()
    weak = overlap_duty < min_duty
    perm = jnp.clip(
        perm + dom.rate(cfg.syn_perm_below_stimulus_inc) * (weak[:, None] & bump_pool),
        dom.zero, dom.one,
    )

    return {
        **state,
        "perm": perm.astype(dom.dtype),
        "boost": boost,
        "overlap_duty": overlap_duty,
        "active_duty": active_duty,
        "sp_iter": it.astype(jnp.int32),
    }


# rtap: twin[sp_compute] — the oracle names the full SP step sp_compute
@partial(jax.jit, static_argnames=("cfg", "learn"))
def sp_step(state: dict, sdr: jnp.ndarray, cfg: SPConfig, learn: bool = True):
    """One SP step -> (new_state, bool[C] active columns). Pure."""
    pool = state["members"] if cfg.sparse_pool else state["potential"]
    overlap = sp_overlap(state["perm"], pool, sdr, cfg)
    active = sp_inhibit(overlap, state["boost"], cfg)
    if learn:
        state = sp_learn(state, sdr, overlap, active, cfg)
    return state, active
