"""SDR classifier — device kernel (functional twin of oracle/classifier.py).

The reference's SDRClassifier.cpp is a sparse-pattern softmax regression
(SURVEY.md C10). TPU-native layout: per stream a dense weight matrix
[num_cells, buckets]; the pattern->logits contraction and the outer-product
SGD update are MXU matmuls over the 0/1 pattern vector, fused into the
per-record step (ops/step.py) so prediction costs no extra dispatch.

State keys (models/state.py, present only when cfg.classifier.enabled):
    cls_w     f32 [num_cells, buckets]
    cls_val   f32 [buckets]   per-bucket actual-value EMA
    cls_cnt   i32 [buckets]   per-bucket observation count
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from rtap_tpu.config import ModelConfig


def classifier_bucket_device(
    value: jnp.ndarray, offset: jnp.ndarray, resolution: jnp.ndarray, n_buckets: int
) -> jnp.ndarray:
    """Classifier bucket (scalar i32) — same f32 arithmetic, overflow
    clamping, and non-finite handling as the oracle's classifier_bucket."""
    from rtap_tpu.config import RDSE_BUCKET_CLAMP

    b = jnp.round((value - offset) / resolution)
    # overflowed-but-finite-value divisions clamp to the edge (RDSE rule);
    # non-finite values (NaN propagates through clip) map to relative 0
    b = jnp.clip(b, -RDSE_BUCKET_CLAMP, RDSE_BUCKET_CLAMP)
    b = jnp.where(jnp.isfinite(value) & jnp.isfinite(b), b, 0.0)
    return jnp.clip(b + n_buckets // 2, 0, n_buckets - 1).astype(jnp.int32)


# rtap: twin[SDRClassifierOracle] — the oracle classifier is stateful
# (oracle/classifier.py .compute); parity in test_twin_registry.py
def classifier_step(
    state: dict,
    pattern_prev: jnp.ndarray,  # bool [C, K] — active cells at t-1
    pattern_now: jnp.ndarray,  # bool [C, K] — active cells at t
    value: jnp.ndarray,  # scalar f32, the predicted field's value at t
    cfg: ModelConfig,
    learn: bool,
):
    """-> (new_state, predicted value for t+1 (f32), argmax-bucket prob)."""
    ccfg = cfg.classifier
    B = ccfg.buckets
    w = state["cls_w"]
    act_value = state["cls_val"]
    act_count = state["cls_cnt"]

    bucket = classifier_bucket_device(
        value, state["enc_offset"][0], state["enc_resolution"][0], B
    )
    oh = jnp.arange(B, dtype=jnp.int32) == bucket  # [B]
    finite = jnp.isfinite(value)

    if learn:
        # actual-value EMA for the observed bucket (first touch sets it)
        a = jnp.float32(ccfg.act_value_alpha)
        # one-hot count probe, not a scalar gather (vmapped gathers serialize)
        first = jnp.where(oh, act_count, 0).sum() == 0
        upd = jnp.where(first, value, (1.0 - a) * act_value + a * value)
        act_value = jnp.where(oh & finite, upd, act_value)
        act_count = act_count + (oh & finite)

        pat = pattern_prev.reshape(-1).astype(jnp.float32)  # [N]
        z = jax.lax.dot(pat, w, precision=jax.lax.Precision.HIGHEST)  # [B]
        z = z - z.max()
        e = jnp.exp(z)
        p = e / e.sum()
        err = oh.astype(jnp.float32) - p
        do_learn = finite & pattern_prev.any()
        w = w + jnp.where(
            do_learn, jnp.float32(ccfg.alpha), 0.0
        ) * pat[:, None] * err[None, :]

    pat_now = pattern_now.reshape(-1).astype(jnp.float32)
    z2 = jax.lax.dot(pat_now, w, precision=jax.lax.Precision.HIGHEST)
    z2 = z2 - z2.max()
    e2 = jnp.exp(z2)
    p2 = e2 / e2.sum()
    best = jnp.argmax(p2)  # first max, matching the oracle
    best_oh = jnp.arange(B, dtype=jnp.int32) == best
    pred = jnp.where(best_oh, act_value, 0.0).sum()
    conf = jnp.where(best_oh, p2, 0.0).sum()

    new_state = {**state, "cls_w": w, "cls_val": act_value, "cls_cnt": act_count}
    return new_state, pred, conf
