"""Pallas TPU megakernel: the WHOLE TM learning pass fused in VMEM.

Round-4 measured the dendrite-only Pallas kernel LOSING to XLA (-13%,
SCALING.md silicon A/B): hand-scheduling ONE already-cheap pass just added
dispatch edges around it. The round-6 profile (reports/profile_r06.json,
scripts/profile_step.py --report) places ~99% of a learn tick inside the TM
learning pass while the chip is ~90% idle (roofline latency_bound_factor
10.0) — the cost is op-dispatch/serialization BETWEEN the pass's XLA
regions, not arithmetic. This kernel therefore fuses the granularity the
round-4 attempt got wrong: dendrite activity + workspace movement +
reinforce/grow — the entire per-tick pool traversal — as ONE kernel whose
intermediates never leave VMEM:

    alloc     clear the burst-new segment's synapse slots
    reinforce +inc toward prev-active presynaptic cells, -dec elsewhere,
              on the learning segments
    grow      add winner-cell synapses ascending, evicting the weakest
              occupied slots when free slots run short
    punish    -pdec on matching segments in non-active columns
    death     presyn := -1 at permanence <= 0; per-segment synapse counts
    dendrite  packed-column activity + connected/potential counts for t+1

Key design choice vs the XLA formulation (ops/tm_tpu.py): NO column-compact
workspace. The gather→learn→scatter movement exists to avoid full-pool HBM
round trips; with the pool VMEM-resident the dense traversal is free of
exactly that cost, so the kernel runs per-segment lanes [n_seg, M] directly
and the learning decisions arrive as a per-segment metadata array. All
DECISION logic (column categorization, allocation targets, capacity
truncation) stays in XLA on the [C, K, S]-scale tensors — it is 32 KB-scale
work; the kernel owns the MB-scale pool traversal.

Semantics are bit-identical to the default XLA path (RTAP_TM_SCATTER=matmul
with dense sweeps): the workspace truncation (first col_cap active columns,
first learn_cap learning segments in ascending (c, k, s) order) is
reproduced exactly by `tm_learn_pallas`'s mask prep, and every arithmetic
expression mirrors tm_tpu.py's f32 forms (integer-valued in quantized
domains, exact below 2^24). Asserted by tests/parity/test_pallas_tm.py via
interpreter mode on CPU, across the perm domains and under vmap.

Strategy wiring: RTAP_TM_SCATTER=pallas (ops/tm_tpu.py mode table). OFF by
default — shipping an unmeasured kernel as the default would repeat the
round-1 mistake; scripts/hw_session.py carries the silicon A/B steps
(profile_mega*) and the measured winner becomes the default, same protocol
as the r4 flat/matmul flip. Incompatible with RTAP_TM_DENDRITE=forward
(the kernel computes dendrite counts itself) and RTAP_TM_SWEEP=compact
(it fuses the DENSE punish/death semantics); tm_step rejects both combos
loudly. Inference ticks (learn=False) keep the XLA dendrite path — the
learning pass is ~99% of the tick, the dendrite pass is already cheap.

Known v1 caveats for the silicon A/B (documented, not guessed around):
the [n_seg, M] layout leaves M (<= 32) lanes per row, which the TPU tiler
pads to 128 — VMEM cost ~128/M x the dense bytes (~43 MB-equivalent at the
cluster preset's M=12: still inside the guard only for sub-preset shapes;
measured viability on silicon decides whether v2 re-blocks lanes to
[C, K*S*M]). The winner-loop unrolls W = col_cap * cells_per_column times —
fine at the cluster preset (80), guarded off at NAB scale (1280).

Interpreter-mode caveat (same as the retired dendrite kernel): off-TPU the
kernel runs the Pallas interpreter, orders of magnitude slower than XLA —
fine for small parity tests, pathological beyond them; the guards refuse
large shapes instead of hanging.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# The whole per-stream pool plus temporaries must fit VMEM (no grid/blocking
# in this v1 kernel), with lane padding to 128 accounted: ~12 MiB budget.
_VMEM_BUDGET_BYTES = 12 * 1024 * 1024
# Interpreter mode (off-TPU) is for parity tests only; refuse big shapes
# instead of silently hanging for minutes.
_INTERPRET_MAX_SYNAPSES = 1 << 18
# The grow pass unrolls over the winner list twice; beyond this the trace
# (and the Mosaic schedule) blows up — the NAB preset (W=1280) is refused.
_MAX_WINNER_UNROLL = 512

_META_COLS = 5  # learn, alloc, grow, punish, n_grow


def _mega_kernel(K, M, N, W, Ac, consts,
                 presyn_ref, perm_ref, meta_ref,
                 pids_ref, pmasks_ref, wids_ref, aids_ref, amasks_ref,
                 presyn_out, perm_out, nsyn_out, conn_out, pot_out):
    """One stream's full TM learning pass on [n_seg, M] pools (see module
    docstring for the stage list). `consts` are the permanence-domain
    constants (trace-time floats); `pdec` None skips the punish stage."""
    p_inc, p_dec, p_init, p_one, p_zero, p_thr, pdec = consts
    presyn = presyn_ref[:]  # [n_seg, M] i32
    perm = perm_ref[:]  # [n_seg, M] f32 (domain values)
    meta = meta_ref[:]  # [n_seg, 5] i32
    learn = meta[:, 0:1] > 0
    alloc = meta[:, 1:2] > 0
    grow = meta[:, 2:3] > 0
    punish = meta[:, 3:4] > 0
    n_grow = meta[:, 4:5]

    # --- burst-new allocation: clear the allocated segment's slots ---
    presyn = jnp.where(alloc, -1, presyn)
    perm = jnp.where(alloc, 0.0, perm)

    def packed_act(pres, ids_ref, masks_ref):
        # packed-column membership (tm_tpu._presyn_active_packed, unrolled
        # over the tiny Ac like the r4 dendrite kernel)
        c_pre = pres // K  # -1 -> -1 (floor): never equals a valid col id
        k_pre = pres % K  # -1 -> K-1, masked by pres >= 0 below
        msk = jnp.zeros_like(pres)
        for i in range(Ac):
            msk = msk + jnp.where(c_pre == ids_ref[0, i], masks_ref[0, i], 0)
        return (pres >= 0) & (((msk >> k_pre) & 1) > 0)

    # --- reinforce learning segments toward prev-active cells ---
    act = packed_act(presyn, pids_ref, pmasks_ref)
    exists = presyn >= 0
    perm = jnp.where(
        learn,
        jnp.clip(perm + p_inc * act - p_dec * (exists & ~act), 0.0, p_one),
        perm,
    )

    # --- grow pass 1: eligible-winner count per segment (eligibility reads
    # the PRE-eviction pool, exactly like _grow_compact's membership) ---
    presyn_pre = presyn
    n_seg = presyn.shape[0]
    n_elig = jnp.zeros((n_seg, 1), jnp.int32)
    for w in range(W):
        wid = wids_ref[0, w]
        already = jnp.sum(
            (presyn_pre == wid).astype(jnp.int32), axis=1, keepdims=True) > 0
        n_elig = n_elig + ((wid < N) & ~already).astype(jnp.int32)
    n_new = jnp.minimum(n_elig, jnp.maximum(n_grow, 0))
    n_new = jnp.where(grow, n_new, 0)  # non-growing segments add nothing

    # --- evict weakest occupied synapses when free slots run short:
    # stable ascending rank by (permanence, slot), compare-count form ---
    occupied = presyn >= 0
    n_free = M - jnp.sum(occupied.astype(jnp.int32), axis=1, keepdims=True)
    short = n_new - n_free
    key = jnp.where(occupied, perm, jnp.float32(jnp.inf))
    slot = jax.lax.broadcasted_iota(jnp.int32, (1, M), 1)
    ranks = jnp.zeros((n_seg, M), jnp.int32)
    for mp in range(M):
        kmp = key[:, mp:mp + 1]
        ranks = ranks + ((kmp < key) | ((kmp == key) & (mp < slot))).astype(jnp.int32)
    evict = occupied & (ranks < short)
    presyn = jnp.where(evict, -1, presyn)
    perm = jnp.where(evict, 0.0, perm)

    # --- fill free slots ascending with chosen winners ascending ---
    free = presyn < 0
    frank_cols = []  # 0-based rank of each slot among free slots
    accf = jnp.zeros((n_seg, 1), jnp.int32)
    for m in range(M):
        frank_cols.append(accf)
        accf = accf + free[:, m:m + 1].astype(jnp.int32)
    frank = jnp.concatenate(frank_cols, axis=1)
    fill = jnp.zeros((n_seg, M), jnp.int32)
    accr = jnp.zeros((n_seg, 1), jnp.int32)
    for w in range(W):
        wid = wids_ref[0, w]
        already = jnp.sum(
            (presyn_pre == wid).astype(jnp.int32), axis=1, keepdims=True) > 0
        elig = (wid < N) & ~already
        rank_w = accr + elig.astype(jnp.int32)  # 1-based among eligible
        accr = rank_w
        chosen = elig & (rank_w <= n_grow)
        fill = jnp.where(chosen & (frank == rank_w - 1), wid, fill)
    assign = free & (frank < n_new) & grow
    presyn = jnp.where(assign, fill, presyn)
    perm = jnp.where(assign, p_init, perm)

    # --- punish matching segments in non-active columns (dense sweep
    # semantics; punished columns are disjoint from learning columns, so
    # the pre-grow membership `act` is still exact there) ---
    if pdec is not None:
        perm = jnp.where(punish & act, jnp.maximum(perm - pdec, p_zero), perm)

    # --- synapse death at permanence <= 0, per-segment occupancy ---
    dead = (presyn >= 0) & (perm <= p_zero)
    presyn = jnp.where(dead, -1, presyn)
    nsyn = jnp.sum((presyn >= 0).astype(jnp.int32), axis=1, keepdims=True)

    # --- dendrite activity for t+1 on the updated pools ---
    dact = packed_act(presyn, aids_ref, amasks_ref)
    pot = jnp.sum(dact.astype(jnp.int32), axis=1, keepdims=True)
    conn = jnp.sum((dact & (perm >= p_thr)).astype(jnp.int32),
                   axis=1, keepdims=True)

    presyn_out[:] = presyn
    perm_out[:] = perm
    nsyn_out[:] = nsyn
    conn_out[:] = conn
    pot_out[:] = pot


def _guard_shapes(C, K, S, M, W, interpret):
    n_syn = C * K * S * M
    if interpret and n_syn > _INTERPRET_MAX_SYNAPSES:
        raise ValueError(
            f"Pallas TM megakernel in INTERPRETER mode with {n_syn} synapses "
            f"(> {_INTERPRET_MAX_SYNAPSES}): this path exists for small "
            "parity tests; on CPU leave RTAP_TM_SCATTER at the default "
            "(the XLA formulation is the fast path there)"
        )
    if W > _MAX_WINNER_UNROLL:
        raise ValueError(
            f"Pallas TM megakernel with winner-list length {W} (> "
            f"{_MAX_WINNER_UNROLL}): the grow pass unrolls over it twice — "
            "this preset (col_cap * cells_per_column too large, e.g. the NAB "
            "preset) needs the XLA path"
        )
    # v1 has no grid/blocking: pools + temporaries must fit VMEM, with the
    # [n_seg, M] rows lane-padded to 128 on real hardware
    lanes = M if interpret else max(M, 128)
    block_bytes = C * K * S * (lanes * 4 * 6 + _META_COLS * 4 + 3 * 4)
    if block_bytes > _VMEM_BUDGET_BYTES:
        raise ValueError(
            f"Pallas TM megakernel needs ~{block_bytes >> 20} MiB VMEM for "
            f"[C={C}, K={K}, S={S}, M={M}] incl. lane padding (budget "
            f"~{_VMEM_BUDGET_BYTES >> 20} MiB): this preset is too large for "
            "the unblocked v1 kernel — keep RTAP_TM_SCATTER=matmul for it"
        )


# rtap: twin[TMOracle] — megakernel twin of the default TM learning path;
# bit-parity in interpreter mode: tests/parity/test_pallas_tm.py
def tm_learn_pallas(
    cfg,
    dom,
    presyn: jnp.ndarray,  # kernel-layout pool (any int dtype; -1 = empty)
    syn_perm: jnp.ndarray,  # kernel-layout pool (storage domain)
    seg_last: jnp.ndarray,  # kernel-layout [C, K*S] or [C, K, S] i32
    seg_pot4: jnp.ndarray,  # i32 [C, K, S] (prev step)
    matching_seg4: jnp.ndarray,  # bool [C, K, S] (prev step)
    learn_mask: jnp.ndarray,  # bool [C, K, S] (predicted + burst-match)
    alloc,  # (alloc_col [C], bn_k [C], bn_s [C]) from _segment_learning_mask
    active_cols: jnp.ndarray,  # bool [C]
    have_winners: jnp.ndarray,  # bool scalar
    it: jnp.ndarray,  # i32 scalar (this step's iteration stamp)
    pcol_ids: jnp.ndarray,  # [Ac] packed prev-active columns
    pcol_masks: jnp.ndarray,
    p_cols: jnp.ndarray,  # i32 scalar: TOTAL prev-active columns (overflow)
    winner_ids: jnp.ndarray,  # [Ac*K] prev winner cell ids (fills = N)
    acol_ids: jnp.ndarray,  # [Ac] packed CURRENT active cells (dendrite)
    acol_masks: jnp.ndarray,
    interpret: bool | None = None,
):
    """XLA-side harness for the megakernel: reproduce the workspace
    truncation as dense masks, call the kernel, apply the [C, K, S]-scale
    epilogue (seg_last stamping/death). Returns
    (presyn' i32 [n_seg, M], perm' f32 [n_seg, M], seg_last' i32 [n_seg],
    conn [n_seg], pot [n_seg], overflow bool scalar) — caller casts/reshapes
    back to the pool layout/domain.
    """
    C = active_cols.shape[0]
    K = cfg.cells_per_column
    S = cfg.max_segments_per_cell
    M = cfg.max_synapses_per_segment
    n_seg = C * K * S
    N = C * K
    L, Ac = cfg.learn_cap, cfg.col_cap
    W = winner_ids.shape[0]
    G = cfg.new_synapse_count
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    _guard_shapes(C, K, S, M, W, interpret)

    # --- the workspace truncation, as dense masks: the XLA path captures
    # the first Ac active columns ascending, then the first L learning
    # segments in ascending (c, k, s) order — identical selection here ---
    alloc_col, bn_k, bn_s = alloc
    burst_new = alloc_col < C
    captured = active_cols & (jnp.cumsum(active_cols.astype(jnp.int32)) <= Ac)
    kk = jnp.arange(K, dtype=jnp.int32)
    ss = jnp.arange(S, dtype=jnp.int32)
    alloc_seg = (
        (burst_new & captured)[:, None, None]
        & (kk[None, :, None] == bn_k[:, None, None])
        & (ss[None, None, :] == bn_s[:, None, None])
    )  # [C, K, S]
    ws_learn = ((learn_mask & captured[:, None, None]) | alloc_seg).reshape(-1)
    learn_trunc = ws_learn & (jnp.cumsum(ws_learn.astype(jnp.int32)) <= L)
    grow_seg = learn_trunc & have_winners
    n_grow = (G - jnp.where(alloc_seg, 0, seg_pot4).reshape(-1)).astype(jnp.int32)

    pdec = None
    if cfg.predicted_segment_decrement > 0.0:
        pdec = float(dom.rate(cfg.predicted_segment_decrement))
        punish_seg = (matching_seg4 & ~active_cols[:, None, None]).reshape(-1)
    else:
        punish_seg = jnp.zeros(n_seg, bool)

    meta = jnp.stack(
        [
            learn_trunc.astype(jnp.int32),
            alloc_seg.reshape(-1).astype(jnp.int32),
            grow_seg.astype(jnp.int32),
            punish_seg.astype(jnp.int32),
            n_grow,
        ],
        axis=1,
    )  # [n_seg, _META_COLS]

    consts = (
        float(dom.rate(cfg.permanence_increment)),
        float(dom.rate(cfg.permanence_decrement)),
        float(dom.rate(cfg.initial_permanence)),
        float(dom.one),
        float(dom.zero),
        float(dom.threshold(cfg.connected_permanence)),
        pdec,
    )
    kernel = functools.partial(_mega_kernel, K, M, N, W, Ac, consts)
    i32, f32 = jnp.int32, jnp.float32
    presyn_n, perm_n, nsyn, conn, pot = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((n_seg, M), i32),
            jax.ShapeDtypeStruct((n_seg, M), f32),
            jax.ShapeDtypeStruct((n_seg, 1), i32),
            jax.ShapeDtypeStruct((n_seg, 1), i32),
            jax.ShapeDtypeStruct((n_seg, 1), i32),
        ),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ),
        interpret=interpret,
    )(
        presyn.reshape(n_seg, M).astype(i32),
        syn_perm.reshape(n_seg, M).astype(f32),
        meta,
        pcol_ids.reshape(1, Ac).astype(i32),
        pcol_masks.reshape(1, Ac).astype(i32),
        winner_ids.reshape(1, W).astype(i32),
        acol_ids.reshape(1, Ac).astype(i32),
        acol_masks.reshape(1, Ac).astype(i32),
    )
    nsyn = nsyn.reshape(-1)

    # --- [C, K, S]-scale epilogue (identical to the XLA tail): stamp
    # alloc + learned segments, then empty-segment death post-sweep ---
    sl = seg_last.reshape(-1)
    sl = jnp.where(alloc_seg.reshape(-1) | learn_trunc, it, sl)
    sl = jnp.where((sl >= 0) & (nsyn == 0), -1, sl)

    # same capacity-overflow accounting as the workspace path: truncated
    # active set, truncated prev-active packing, or > learn_cap learners
    overflow = (
        (active_cols.sum() > Ac) | (p_cols > Ac) | (ws_learn.sum() > L)
    )
    return presyn_n, perm_n, sl, conn.reshape(-1), pot.reshape(-1), overflow
