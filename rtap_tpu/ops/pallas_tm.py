"""Pallas TPU kernel: fused TM dendrite-activity pass.

The dendrite pass — "for every synapse, is its presynaptic cell active, and
is it connected?" followed by per-segment counts — runs EVERY tick on the
full [C, K, S, M] pools (inference and learning alike; SURVEY.md §3.2 TM
hot loop). The XLA formulation in tm_tpu.py materializes several
pool-shaped intermediates ([..., Ac] compare, bit probe, two boolean
masks) between HBM round-trips; this kernel fuses the whole pass in VMEM:

    synapse activity:  msk = Σ_i where(presyn//K == col_ids[i], col_masks[i])
                       act = presyn >= 0  &  (msk >> (presyn % K)) & 1
    segment counts:    pot  = Σ_M act            (0/1 f32 matmul on the MXU
                       conn = Σ_M act & (perm >= thr)   with a block-diagonal
                                                        reduction matrix)

Layout: the pools flatten to [C, K*S*M] (rows = columns, lanes = synapses),
which keeps the VPU lanes dense for any preset; the Σ_M reduction is a
[C, K*S*M] x [K*S*M, K*S] matmul whose operand is a static 0/1
block-diagonal matrix — exact integer counts in f32 (counts <= M < 2^24).

Semantics are bit-identical to `tm_tpu._presyn_active_packed` + the count
reductions (asserted by tests/parity/test_pallas_tm.py, which runs the
kernel in interpreter mode on CPU). OFF by default: enable with
RTAP_TM_PALLAS=1 (or set USE_PALLAS) once profiled on silicon — shipping an
unmeasured kernel as the default would repeat the round-1 mistake of
hand-scheduling what XLA already does well.

Interpreter-mode caveat: off-TPU the kernel runs through the Pallas
interpreter, which is orders of magnitude slower to compile/run than the
XLA formulation — fine for the small parity tests, pathological for large
CPU replays (a G=256 x T=64 chunk fails to even compile within minutes).
Only enable the flag on real TPU hardware or in small tests.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# None = read RTAP_TM_PALLAS env (default off); tests set True/False directly.
USE_PALLAS: bool | None = None

# The whole per-stream pool must fit VMEM (no grid/blocking in this v1
# kernel): presyn i32 + perm f32 + reduce matrix + outputs, ~16 MiB budget.
_VMEM_BUDGET_BYTES = 12 * 1024 * 1024
# Interpreter mode (off-TPU) is for parity tests only; refuse big shapes
# instead of silently hanging for minutes.
_INTERPRET_MAX_SYNAPSES = 1 << 18


def use_pallas() -> bool:
    """Whether tm_step routes the dendrite pass through the Pallas kernel.

    NOTE: consulted at TRACE time — a compiled tm_step/group_step keeps
    whichever path it was traced with. Toggle via :func:`set_use_pallas`
    (which drops jit caches) rather than mutating the env mid-process.
    """
    if USE_PALLAS is not None:
        return USE_PALLAS
    return os.environ.get("RTAP_TM_PALLAS", "0") not in ("", "0")


def set_use_pallas(on: bool | None) -> None:
    """Set the kernel flag AND clear jit caches so already-traced step
    functions re-trace with the new path (the flag is a trace-time constant,
    not a jit cache key)."""
    global USE_PALLAS
    USE_PALLAS = on
    jax.clear_caches()


@functools.lru_cache(maxsize=None)
def _reduce_matrix(ks: int, m: int) -> np.ndarray:
    """Block-diagonal 0/1 [ks*m, ks] f32: column s sums synapse lanes
    [s*m, (s+1)*m) — the Σ_M reduction as one MXU matmul."""
    r = np.zeros((ks * m, ks), np.float32)
    for s in range(ks):
        r[s * m : (s + 1) * m, s] = 1.0
    return r


def _kernel(K: int, thr: float, Ac: int,
            presyn_ref, perm_ref, ids_ref, masks_ref, red_ref,
            conn_ref, pot_ref):
    presyn = presyn_ref[:]  # [C, K*S*M] i32
    c_pre = presyn // K  # -1 -> -1 (floor): never equals a valid col id
    k_pre = presyn % K  # -1 -> K-1, masked by presyn >= 0 below
    msk = jnp.zeros_like(presyn)
    for i in range(Ac):  # static unroll: Ac = col_cap is tiny (10-40)
        msk = msk + jnp.where(c_pre == ids_ref[0, i], masks_ref[0, i], 0)
    syn_act = (presyn >= 0) & (((msk >> k_pre) & 1) > 0)
    pot_f = syn_act.astype(jnp.float32)
    conn_f = jnp.where(perm_ref[:] >= thr, pot_f, 0.0)
    red = red_ref[:]
    conn_ref[:] = jnp.round(
        jnp.dot(conn_f, red, preferred_element_type=jnp.float32)
    ).astype(jnp.int32)
    pot_ref[:] = jnp.round(
        jnp.dot(pot_f, red, preferred_element_type=jnp.float32)
    ).astype(jnp.int32)


def dendrite_activity_pallas(
    presyn: jnp.ndarray,  # [C, K, S, M] int (any width; -1 = empty)
    syn_perm: jnp.ndarray,  # [C, K, S, M] storage domain
    col_ids: jnp.ndarray,  # [Ac] i32 active column ids (C fills)
    col_masks: jnp.ndarray,  # [Ac] i32 packed K-bit cell masks
    connected_thr,  # python scalar in the storage domain
    interpret: bool | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """-> (conn_count [C, K, S] i32, pot_count [C, K, S] i32).

    `interpret` defaults to True off-TPU (CPU tests run the interpreter);
    pass False only on real TPU.
    """
    C, K, S, M = presyn.shape
    Ac = col_ids.shape[0]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n_syn = C * K * S * M
    if interpret and n_syn > _INTERPRET_MAX_SYNAPSES:
        raise ValueError(
            f"Pallas dendrite kernel in INTERPRETER mode with {n_syn} synapses "
            f"(> {_INTERPRET_MAX_SYNAPSES}): this path exists for small parity "
            "tests; on CPU leave RTAP_TM_PALLAS off (the XLA formulation is "
            "the fast path there)"
        )
    # v1 kernel has no grid/blocking: the whole per-stream pool must fit VMEM
    block_bytes = n_syn * (4 + 4) + (K * S * M) * (K * S) * 4 + C * K * S * 2 * 4
    if block_bytes > _VMEM_BUDGET_BYTES:
        raise ValueError(
            f"Pallas dendrite kernel needs ~{block_bytes >> 20} MiB VMEM for "
            f"[C={C}, K={K}, S={S}, M={M}] (budget ~{_VMEM_BUDGET_BYTES >> 20} "
            "MiB): this preset is too large for the unblocked v1 kernel — "
            "leave RTAP_TM_PALLAS off for it"
        )
    kernel = functools.partial(_kernel, K, float(connected_thr), Ac)
    conn, pot = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((C, K * S), jnp.int32),
            jax.ShapeDtypeStruct((C, K * S), jnp.int32),
        ),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ),
        interpret=interpret,
    )(
        presyn.reshape(C, K * S * M).astype(jnp.int32),
        syn_perm.reshape(C, K * S * M).astype(jnp.float32),
        col_ids.reshape(1, Ac).astype(jnp.int32),
        col_masks.reshape(1, Ac).astype(jnp.int32),
        jnp.asarray(_reduce_matrix(K * S, M)),
    )
    return conn.reshape(C, K, S), pot.reshape(C, K, S)
