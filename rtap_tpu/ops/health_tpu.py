"""Fused on-device model-health reducers (ISSUE 6 tentpole).

The serve stack can see its own latency (obs/trace.py) and durability
(resilience/journal.py) but the MODEL is a black box while serving:
nothing reports segment-pool occupancy, permanence distributions, SDR
sparsity, or prediction accuracy. SDR theory (PAPERS.md, 1503.07469)
says capacity and robustness live in exactly those quantities — a
collapsed active-column sparsity or a saturated segment pool is a
detector-quality incident even when every tick hits its deadline — and
ROADMAP item 3 (segment-pool right-sizing from live fleet occupancy)
needs the numbers this module produces.

:func:`health_reduce` runs INSIDE the fused step program (ops/step.py
`_tick`, behind the static ``health`` flag): it reads the post-step
state the scan already holds on device and reduces it to one small
per-group leaf (~200 bytes — a handful of scalars plus three fixed-bin
histograms), returned alongside the scores. Properties the tests pin:

- **Pure reads.** The model state, scores, and alert stream are
  bit-identical with health on vs off
  (tests/integration/test_health_serve.py).
- **No extra device<->host state fetch.** The leaf rides the existing
  chunk output; the host never pulls pool tensors.
- **Bounded size.** Histogram bin counts are module constants, so the
  leaf is a few hundred bytes per group regardless of G or model width.

Aggregation semantics: per-stream fractions are averaged over the LIVE
streams of the tick (streams whose polled values had at least one
finite field) — pad slots and silent streams must not dilute a
half-full group's occupancy story. Pool-wide quantities are reduced as
per-stream fractions (mean over live streams), never as raw counts: a
group-level synapse count at 100k-stream scale overflows int32 and f32
alike, a mean fraction never does.

:func:`health_reduce_host` is the bit-twin on numpy/public-layout
state — the CPU-oracle backend's health path and the parity oracle for
the device reducer (tests/unit/test_health.py).
"""

from __future__ import annotations

import numpy as np

from rtap_tpu.config import ModelConfig
from rtap_tpu.models.perm import tm_domain

__all__ = [
    "HEALTH_KEYS",
    "OCC_BINS",
    "PERM_BINS",
    "SCORE_BINS",
    "health_nbytes",
    "health_reduce",
    "health_reduce_host",
    "health_from_states",
]

#: per-stream segment-pool occupancy fraction histogram bins (streams
#: are counted into bins of used-segment fraction — the right-sizing
#: evidence: a fleet living in the top bin needs a bigger pool, one in
#: the bottom bins is paying HBM for nothing)
OCC_BINS = 8

#: permanence-distribution sketch bins over the [0, 1] domain (counts
#: are per-stream-normalized then averaged, so the sketch is a
#: probability vector once any synapse exists)
PERM_BINS = 8

#: streaming anomaly-score histogram bins over [0, 1] — the host-side
#: EWMA drift detector (obs/health.py) folds these per tick
SCORE_BINS = 16

#: the leaf's key set, in a fixed order (schema contract for the host
#: tracker, the /health route, and the drift gate tests)
HEALTH_KEYS = (
    "occ_hist",        # i32 [OCC_BINS]  live streams per occupancy bin
    "seg_occ_frac",    # f32 []  mean used-segment fraction (live streams)
    "syn_frac",        # f32 []  mean non-empty synapse-slot fraction
    "perm_hist",       # f32 [PERM_BINS] mean normalized permanence sketch
    "perm_conn_frac",  # f32 []  mean connected fraction among non-empty
    "act_col_frac",    # f32 []  mean active-column fraction (of C)
    "pred_cell_frac",  # f32 []  mean predictive-cell fraction (of C*K)
    "hit_num",         # f32 []  sum of (1 - raw) * active_cols (scored)
    "hit_den",         # f32 []  sum of active_cols (scored streams)
    "score_hist",      # i32 [SCORE_BINS] scored streams per raw-score bin
    "scored",          # i32 []  streams scored this tick (live, finite raw)
)


def health_nbytes() -> int:
    """Bytes per (group, tick) health leaf — the "few hundred bytes"
    bound the module docstring claims, computed from the schema."""
    return 4 * (OCC_BINS + PERM_BINS + SCORE_BINS
                + len(HEALTH_KEYS) - 3)


def health_reduce(state: dict, raw, values, cfg: ModelConfig) -> dict:
    """Per-group health aggregates from POST-STEP group state (device).

    `state` is the kernel-layout group state ([G, ...] leaves — flat or
    aos, the reductions are layout-invariant), `raw` the [G] raw anomaly
    scores of the tick, `values` the [G, n_fields] polled inputs (the
    live-stream mask source). Pure: reads only, returns a fresh dict of
    small arrays (see :data:`HEALTH_KEYS`). Traced inside the fused step
    program — keep everything shape-static and reduction-only.
    """
    import jax.numpy as jnp

    tm = cfg.tm
    C, K, S = cfg.sp.columns, tm.cells_per_column, tm.max_segments_per_cell
    G = state["seg_last"].shape[0]

    liv = jnp.isfinite(values).any(-1)  # [G] streams with data this tick
    livf = liv.astype(jnp.float32)
    n_live = jnp.maximum(livf.sum(), 1.0)

    # -- segment-pool occupancy (ROADMAP-3 right-sizing evidence) --
    seg_axes = tuple(range(1, state["seg_last"].ndim))
    seg_used = (state["seg_last"] >= 0).sum(seg_axes)  # [G] i32
    seg_cap = float(np.prod(state["seg_last"].shape[1:]))
    occ = seg_used.astype(jnp.float32) / seg_cap  # [G]
    occ_bin = jnp.clip((occ * OCC_BINS).astype(jnp.int32), 0, OCC_BINS - 1)
    occ_hist = ((occ_bin[:, None] == jnp.arange(OCC_BINS)[None, :])
                & liv[:, None]).sum(0).astype(jnp.int32)
    seg_occ_frac = (occ * livf).sum() / n_live

    # -- synapse pool + permanence sketch --
    pool_axes = tuple(range(1, state["presyn"].ndim))
    used_syn = state["presyn"] >= 0
    syn_used = used_syn.sum(pool_axes).astype(jnp.float32)  # [G]
    pool_cap = float(np.prod(state["presyn"].shape[1:]))
    syn_frac = (syn_used / pool_cap * livf).sum() / n_live
    dom = tm_domain(tm)
    perm_f = state["syn_perm"].astype(jnp.float32)
    pbin = jnp.clip((perm_f / jnp.float32(dom.one)
                     * PERM_BINS).astype(jnp.int32), 0, PERM_BINS - 1)
    denom = jnp.maximum(syn_used, 1.0)
    per_bin = jnp.stack(
        [((pbin == b) & used_syn).sum(pool_axes).astype(jnp.float32)
         for b in range(PERM_BINS)], axis=-1)  # [G, PERM_BINS]
    perm_hist = (per_bin / denom[:, None] * livf[:, None]).sum(0) / n_live
    conn_thr = jnp.float32(dom.threshold(tm.connected_permanence))
    conn = ((perm_f >= conn_thr) & used_syn).sum(pool_axes).astype(jnp.float32)
    perm_conn_frac = (conn / denom * livf).sum() / n_live

    # -- SDR sparsity (post-step prev_active = THIS tick's active cells;
    #    post-step active_seg = the dendrites predicting t+1) --
    ac = state["prev_active"].any(-1).sum(-1).astype(jnp.float32)  # [G]
    act_col_frac = (ac / float(C) * livf).sum() / n_live
    aseg = state["active_seg"].reshape(G, C, K, S)
    pred_cells = aseg.any(-1).sum((-1, -2)).astype(jnp.float32)  # [G]
    pred_cell_frac = (pred_cells / float(C * K) * livf).sum() / n_live

    # -- predicted->active hit rate + streaming score histogram --
    rawc = jnp.clip(jnp.nan_to_num(raw, nan=0.0), 0.0, 1.0)
    rfin = jnp.isfinite(raw) & liv
    rfinf = rfin.astype(jnp.float32)
    hit_num = (rfinf * (1.0 - rawc) * ac).sum()
    hit_den = (rfinf * ac).sum()
    sbin = jnp.clip((rawc * SCORE_BINS).astype(jnp.int32), 0, SCORE_BINS - 1)
    score_hist = ((sbin[:, None] == jnp.arange(SCORE_BINS)[None, :])
                  & rfin[:, None]).sum(0).astype(jnp.int32)

    return {
        "occ_hist": occ_hist,
        "seg_occ_frac": seg_occ_frac,
        "syn_frac": syn_frac,
        "perm_hist": perm_hist,
        "perm_conn_frac": perm_conn_frac,
        "act_col_frac": act_col_frac,
        "pred_cell_frac": pred_cell_frac,
        "hit_num": hit_num,
        "hit_den": hit_den,
        "score_hist": score_hist,
        "scored": rfin.sum().astype(jnp.int32),
    }


def health_reduce_host(state: dict, raw: np.ndarray, values: np.ndarray,
                       cfg: ModelConfig) -> dict:
    """Numpy twin of :func:`health_reduce` on PUBLIC-layout group state
    ([G, C, K, S, M] pools — what ``grp.state`` holds between chunks).
    Same schema, same semantics; the parity test pins the two against
    each other and the CPU-oracle backend emits health through it."""
    tm = cfg.tm
    C, K, S = cfg.sp.columns, tm.cells_per_column, tm.max_segments_per_cell
    G = np.shape(state["seg_last"])[0]
    values = np.asarray(values, np.float32)
    if values.ndim == 1:
        values = values[:, None]
    raw = np.asarray(raw, np.float32)

    liv = np.isfinite(values).any(-1)
    livf = liv.astype(np.float32)
    n_live = max(float(livf.sum()), 1.0)

    seg_last = np.asarray(state["seg_last"]).reshape(G, -1)
    seg_used = (seg_last >= 0).sum(-1)
    occ = seg_used.astype(np.float32) / float(seg_last.shape[1])
    occ_bin = np.clip((occ * OCC_BINS).astype(np.int32), 0, OCC_BINS - 1)
    occ_hist = ((occ_bin[:, None] == np.arange(OCC_BINS)[None, :])
                & liv[:, None]).sum(0).astype(np.int32)

    presyn = np.asarray(state["presyn"]).reshape(G, -1)
    used_syn = presyn >= 0
    syn_used = used_syn.sum(-1).astype(np.float32)
    syn_frac = float((syn_used / presyn.shape[1] * livf).sum() / n_live)
    dom = tm_domain(tm)
    perm_f = np.asarray(state["syn_perm"]).reshape(G, -1).astype(np.float32)
    pbin = np.clip((perm_f / np.float32(dom.one)
                    * PERM_BINS).astype(np.int32), 0, PERM_BINS - 1)
    denom = np.maximum(syn_used, 1.0)
    per_bin = np.stack(
        [((pbin == b) & used_syn).sum(-1).astype(np.float32)
         for b in range(PERM_BINS)], axis=-1)
    perm_hist = ((per_bin / denom[:, None] * livf[:, None]).sum(0)
                 / n_live).astype(np.float32)
    conn_thr = np.float32(dom.threshold(tm.connected_permanence))
    conn = ((perm_f >= conn_thr) & used_syn).sum(-1).astype(np.float32)
    perm_conn_frac = float((conn / denom * livf).sum() / n_live)

    ac = np.asarray(state["prev_active"]).any(-1).sum(-1).astype(np.float32)
    act_col_frac = float((ac / float(C) * livf).sum() / n_live)
    aseg = np.asarray(state["active_seg"]).reshape(G, C, K, S)
    pred_cells = aseg.any(-1).sum((-1, -2)).astype(np.float32)
    pred_cell_frac = float((pred_cells / float(C * K) * livf).sum() / n_live)

    rawc = np.clip(np.nan_to_num(raw, nan=0.0), 0.0, 1.0)
    rfin = np.isfinite(raw) & liv
    rfinf = rfin.astype(np.float32)
    sbin = np.clip((rawc * SCORE_BINS).astype(np.int32), 0, SCORE_BINS - 1)
    score_hist = ((sbin[:, None] == np.arange(SCORE_BINS)[None, :])
                  & rfin[:, None]).sum(0).astype(np.int32)

    return {
        "occ_hist": occ_hist,
        "seg_occ_frac": np.float32((occ * livf).sum() / n_live),
        "syn_frac": np.float32(syn_frac),
        "perm_hist": perm_hist,
        "perm_conn_frac": np.float32(perm_conn_frac),
        "act_col_frac": np.float32(act_col_frac),
        "pred_cell_frac": np.float32(pred_cell_frac),
        "hit_num": np.float32((rfinf * (1.0 - rawc) * ac).sum()),
        "hit_den": np.float32((rfinf * ac).sum()),
        "score_hist": score_hist,
        "scored": np.int32(rfin.sum()),
    }


def health_from_states(states: list[dict], raw: np.ndarray,
                       values: np.ndarray, cfg: ModelConfig) -> dict:
    """CPU-oracle backend adapter: stack per-stream oracle state dicts
    into a [G, ...] view and reduce through the host twin. Only the
    leaves the reducer reads are stacked (views where possible)."""
    grouped = {
        k: np.stack([np.asarray(s[k]) for s in states])
        for k in ("seg_last", "presyn", "syn_perm", "prev_active",
                  "active_seg")
    }
    return health_reduce_host(grouped, raw, values, cfg)
