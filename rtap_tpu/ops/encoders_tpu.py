"""Device-side record encoder: RDSE + date bits, table-free, vmappable.

Twin of models/oracle/encoders.py (SURVEY.md C1/C2). The RDSE is a pure hash
function (bucket b -> bits {hash(seed, b+k) % n}), so encoding runs on device
with no host-side bucket table: one record is (values[F] f32, ts i32) and the
output is a bool[input_size] SDR built by scatter. All arithmetic is f32/int32
and bit-identical to the host oracle (tests/parity/test_encoder_parity.py).

NaN/inf field values contribute no bits (NuPIC missing-sample behavior),
implemented branch-free via out-of-bounds scatter indices with mode="drop".
"""

from __future__ import annotations

import jax.numpy as jnp

from rtap_tpu.config import RDSE_BUCKET_CLAMP, ModelConfig
from rtap_tpu.ops.hashing_tpu import hash_bits

SECONDS_PER_DAY = 86400
_EPOCH_WEEKDAY_SHIFT = 3  # 1970-01-01 was a Thursday; weekday = (days+3) % 7


def _composite_indices(
    cfg: ModelConfig,
    values: jnp.ndarray,  # [F] f32
    enc_offset: jnp.ndarray,  # [F] f32
    enc_resolution: jnp.ndarray,  # [F] f32
    enc_prev: jnp.ndarray | None,  # [F] f32 (delta predecessor), or None
) -> jnp.ndarray:
    """Composite-family scatter indices (ISSUE 9), flattened across fields;
    missing samples point at n_in (dropped). Static python loop over the
    FieldSpec table — F is small and the per-field geometry (size, kind,
    seed, offset) is config-static, so this traces to straight-line code.
    Twin of the oracle's _composite_field_bits, bit-exact per field."""
    n_in = cfg.input_size
    parts = []
    for f, (spec, (_name, _kind, off, _sz)) in enumerate(
            zip(cfg.composite.fields, cfg.field_layout())):
        w = spec.active_bits
        vf = values[f]
        res = enc_resolution[f].astype(jnp.float32)
        finite = jnp.isfinite(vf)
        v = jnp.where(finite, vf, jnp.float32(0.0))
        if spec.kind == "delta":
            # first difference; a stream's first sample (prev NaN) has
            # none — same missing-sample drop as a NaN value
            pf = enc_prev[f] if enc_prev is not None else jnp.float32(jnp.nan)
            finite = finite & jnp.isfinite(pf)
            p = jnp.where(jnp.isfinite(pf), pf, jnp.float32(0.0))
            bucket = jnp.clip(jnp.round((v - p) / res),
                              -RDSE_BUCKET_CLAMP,
                              RDSE_BUCKET_CLAMP).astype(jnp.int32)
            keys = bucket + jnp.arange(w, dtype=jnp.int32)
        elif spec.kind == "categorical":
            # rounded id, clamped FIRST in the f32 bucket domain (shared
            # rdse_bucket arithmetic), then in the integer domain to the
            # per-field categorical bound so c*w + k cannot wrap int32 —
            # the same double clamp the host performs
            b = jnp.clip(jnp.round(v / res), -RDSE_BUCKET_CLAMP,
                         RDSE_BUCKET_CLAMP).astype(jnp.int32)
            cclamp = jnp.int32(spec.categorical_clamp())
            cat = jnp.clip(b, -cclamp, cclamp)
            keys = cat * jnp.int32(w) + jnp.arange(w, dtype=jnp.int32)
        else:  # rdse
            bucket = jnp.clip(jnp.round((v - enc_offset[f]) / res),
                              -RDSE_BUCKET_CLAMP,
                              RDSE_BUCKET_CLAMP).astype(jnp.int32)
            keys = bucket + jnp.arange(w, dtype=jnp.int32)
        bits = hash_bits(keys, jnp.uint32(spec.seed)
                         + jnp.uint32(0x1000) * jnp.uint32(f), spec.size)
        idx = bits + jnp.int32(off)
        parts.append(jnp.where(finite, idx, n_in))
    return jnp.concatenate(parts)


# rtap: twin[encode_record] — the host oracle encoder (oracle/encoders.py)
def encode_device(
    cfg: ModelConfig,
    values: jnp.ndarray,  # [F] f32
    ts_unix: jnp.ndarray,  # scalar i32
    enc_offset: jnp.ndarray,  # [F] f32
    enc_resolution: jnp.ndarray | None = None,  # [F] f32 (runtime, per stream)
    enc_prev: jnp.ndarray | None = None,  # [F] f32 (delta fields' predecessor)
) -> jnp.ndarray:
    """Encode one record -> bool[input_size]. Layout matches the oracle:
    [field0 | field1 | ... | time-of-day ring | weekend] per
    cfg.field_layout() (uniform RDSE/scalar, or the composite family's
    per-field kinds).

    `enc_resolution` defaults to the config's static resolution (rounded
    through f32, exactly like the state-carried per-stream array)."""
    F = cfg.n_fields
    n_in = cfg.input_size
    if cfg.composite is not None:
        if enc_resolution is None:
            enc_resolution = jnp.asarray(cfg.field_resolutions(), jnp.float32)
        idx = _composite_indices(cfg, values, enc_offset, enc_resolution,
                                 enc_prev)
        sdr = jnp.zeros(n_in, bool).at[idx].set(True, mode="drop")
        base = cfg.composite.size
        if cfg.date.time_of_day_width:
            center = (ts_unix % SECONDS_PER_DAY) * cfg.date.time_of_day_size \
                // SECONDS_PER_DAY
            tod = (
                center
                + jnp.arange(cfg.date.time_of_day_width, dtype=jnp.int32)
                - cfg.date.time_of_day_width // 2
            ) % cfg.date.time_of_day_size
            sdr = sdr.at[base + tod].set(True)
            base += cfg.date.time_of_day_size
        if cfg.date.weekend_width:
            weekend = ((ts_unix // SECONDS_PER_DAY + _EPOCH_WEEKDAY_SHIFT)
                       % 7) >= 5
            widx = jnp.where(
                weekend,
                base + jnp.arange(cfg.date.weekend_width, dtype=jnp.int32),
                n_in)
            sdr = sdr.at[widx].set(True, mode="drop")
        return sdr
    R = cfg.field_size
    finite = jnp.isfinite(values)
    v = jnp.where(finite, values, jnp.float32(0.0))

    if cfg.scalar is not None:
        # classic ScalarEncoder: clipped fixed-range bucket, contiguous run
        sc = cfg.scalar
        vc = jnp.clip(v, jnp.float32(sc.min_val), jnp.float32(sc.max_val))
        scale = jnp.float32(sc.size - sc.width) / (
            jnp.float32(sc.max_val) - jnp.float32(sc.min_val)
        )
        bucket = jnp.round((vc - jnp.float32(sc.min_val)) * scale).astype(jnp.int32)
        bits = bucket[:, None] + jnp.arange(sc.width, dtype=jnp.int32)[None, :]
    else:
        w = cfg.rdse.active_bits
        if enc_resolution is None:
            enc_resolution = jnp.full(F, jnp.float32(cfg.rdse.resolution))
        bucket = jnp.clip(
            jnp.round((v - enc_offset) / enc_resolution.astype(jnp.float32)),
            -RDSE_BUCKET_CLAMP,
            RDSE_BUCKET_CLAMP,
        ).astype(jnp.int32)
        keys = bucket[:, None] + jnp.arange(w, dtype=jnp.int32)[None, :]  # [F, w]
        # per-field hash stream: seed + 0x1000 * field (same keying as oracle)
        seeds = jnp.uint32(cfg.rdse.seed) + jnp.uint32(0x1000) * jnp.arange(F, dtype=jnp.uint32)
        bits = hash_bits(keys, seeds[:, None], R)  # [F, w]
    idx = bits + (jnp.arange(F, dtype=jnp.int32) * R)[:, None]
    idx = jnp.where(finite[:, None], idx, n_in)  # missing field -> dropped scatter

    sdr = jnp.zeros(n_in, bool).at[idx.reshape(-1)].set(True, mode="drop")

    base = F * R
    if cfg.date.time_of_day_width:
        # integer floor((s/86400) * ring_size); identical to the oracle
        center = (ts_unix % SECONDS_PER_DAY) * cfg.date.time_of_day_size // SECONDS_PER_DAY
        tod = (
            center
            + jnp.arange(cfg.date.time_of_day_width, dtype=jnp.int32)
            - cfg.date.time_of_day_width // 2
        ) % cfg.date.time_of_day_size
        sdr = sdr.at[base + tod].set(True)
        base += cfg.date.time_of_day_size
    if cfg.date.weekend_width:
        weekend = ((ts_unix // SECONDS_PER_DAY + _EPOCH_WEEKDAY_SHIFT) % 7) >= 5
        widx = jnp.where(weekend, base + jnp.arange(cfg.date.weekend_width, dtype=jnp.int32), n_in)
        sdr = sdr.at[widx].set(True, mode="drop")
    return sdr


# rtap: twin[oracle_record_step] — the oracle performs the first-finite
# bind inline (models/htm_model.py, the np.where on enc_offset)
def bind_offsets(
    values: jnp.ndarray, enc_offset: jnp.ndarray, enc_bound: jnp.ndarray
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Bind each field's RDSE offset at its first finite value (NuPIC binds
    buckets to the first sample; a leading NaN must not poison the stream).
    Returns (new_offset, new_bound); pure, runs inside the fused step."""
    bind = ~enc_bound & jnp.isfinite(values)
    return jnp.where(bind, values, enc_offset), enc_bound | bind
