"""Forward synapse index: dendrite work scaling with ACTIVE cells, not pool size.

The reference's `Connections.computeActivity` (SURVEY.md C5, §3.2 hot path)
never scans all synapses — it walks a presynaptic-cell -> synapse adjacency,
so per-record dendrite cost tracks the active-cell count (tens) instead of
pool capacity (1e5-3e7 synapses). The round-3 TPU kernel used a full-pool
scan instead, whose measured HBM floor (~40k metrics/s/chip, SCALING.md)
is the round-4 target to break (docs/FORWARD_INDEX_DESIGN.md).

This module is the TPU-native translation of that adjacency: a fixed-capacity
**forward index** carried as two dense tensors alongside the synapse pools,

    fwd_slots  i32 [N, F]     flat pool-slot ids where cell n is presynaptic
                              (-1 = free); F = cfg.tm.fanout_cap
    fwd_pos    i8/i16 [pool]  each slot's position within its presynaptic
                              cell's fwd row (-1 when empty) — the back
                              pointer that makes removal O(1)

plus an i32 overflow counter (a dropped append would silently corrupt
dendrite counts, so it is counted like tm_overflow and tests assert zero).

The index is DERIVED state: rebuilt from `presyn` on checkpoint load
(:func:`build_fwd_index` — checkpoints never store it, so the on-disk schema
is unchanged), maintained incrementally inside the learning step
(:func:`apply_removals` / :func:`apply_appends`), and consumed by
:func:`dendrite_counts` which gathers only the <= col_cap*K active cells'
rows (~KBs) instead of sweeping the MB-scale pools.

Two bit-identical accumulation strategies for the segment-count histogram
(RTAP_TM_FWD_IMPL, raced on silicon by scripts/hw_session.py):

- "scatter": jnp ``.at[seg].add`` — native scatter-add.
- "matmul": the factored one-hot contraction. Segment ids split into
  (hi, lo) digits; counts[hi, lo] = sum_e A[e, hi] * B[e, lo] with A/B 0/1
  indicator matrices -> ONE MXU matmul [hi, E] x [E, lo] producing the dense
  count grid. Counts <= max_synapses_per_segment << 2^24, so f32 accumulation
  at HIGHEST precision is exact.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

_HI = jax.lax.Precision.HIGHEST

# lo-digit width of the factored histogram: the TPU lane dimension. The hi
# digit then spans ceil(n_seg / 128) rows of the count grid.
_LO = 128


def pos_dtype(fanout_cap: int):
    """Narrowest signed dtype holding positions in [0, F) plus the -1 fill."""
    return jnp.int8 if fanout_cap <= 127 else jnp.int16


# rtap: twin[TMOracle] — the oracle walks presyn adjacency directly; the
# index is DERIVED state whose only contract is count parity with it
def build_fwd_index(presyn: jnp.ndarray, n_cells: int, fanout_cap: int):
    """Derive (fwd_slots [N, F], fwd_pos [pool], overflow i32) from a presyn
    pool (any shape; flattened row-major — slot id = flat index).

    Canonical layout: each cell's slots ascend. Jittable and vmappable (used
    per stream on checkpoint load); the incremental maintenance inside the
    step does NOT reproduce this canonical order — only count parity is a
    contract, the row layout is free.
    """
    F = fanout_cap
    pool = int(np.prod(presyn.shape))
    p = presyn.reshape(-1).astype(jnp.int32)
    slot = jnp.arange(pool, dtype=jnp.int32)
    sorted_p, sorted_slot = jax.lax.sort_key_val(p, slot)
    # rank within each equal-presyn run = position - first occurrence
    start = jnp.searchsorted(sorted_p, sorted_p, side="left").astype(jnp.int32)
    rank = jnp.arange(pool, dtype=jnp.int32) - start
    valid = sorted_p >= 0
    keep = valid & (rank < F)
    rows = jnp.where(keep, sorted_p, n_cells)  # n_cells = out of bounds -> dropped
    fwd_slots = (
        jnp.full((n_cells, F), -1, jnp.int32)
        .at[rows, jnp.clip(rank, 0, F - 1)]
        .set(sorted_slot, mode="drop")
    )
    pdt = pos_dtype(F)
    fwd_pos = (
        jnp.full(pool, -1, pdt)
        .at[jnp.where(keep, sorted_slot, pool)]
        .set(rank.astype(pdt), mode="drop")
    )
    overflow = (valid & (rank >= F)).sum().astype(jnp.int32)
    return fwd_slots, fwd_pos, overflow


# rtap: twin[TMOracle] — counts must equal the oracle's adjacency walk
def dendrite_counts(
    fwd_slots: jnp.ndarray,  # i32 [N, F]
    syn_perm_flat: jnp.ndarray,  # [pool] storage dtype
    act_ids: jnp.ndarray,  # i32 [A] active-cell flat ids, fills = N
    p_connected,  # domain threshold (f32 or int)
    n_seg: int,
    syn_per_seg: int,
    impl: str,
):
    """Per-segment (conn_count, pot_count) i32 [n_seg] from the forward index.

    Reads only the A = len(act_ids) active cells' fwd rows plus an [A, F]
    permanence gather — vs the full-pool sweep of the scan formulation.
    Bit-identical to the scan for a consistent index (tests/parity/
    test_fwd_index.py asserts per-step equality).
    """
    N, F = fwd_slots.shape
    pool = syn_perm_flat.shape[0]
    M = syn_per_seg
    rows = fwd_slots[jnp.clip(act_ids, 0, N - 1)]  # [A, F]
    valid = (act_ids < N)[:, None] & (rows >= 0)
    rowc = jnp.clip(rows, 0, pool - 1)
    perms = syn_perm_flat[rowc]  # [A, F]
    conn = valid & (perms >= p_connected)
    seg = rowc // M  # junk where ~valid; masked below

    if impl == "scatter":
        pot = (
            jnp.zeros(n_seg, jnp.int32)
            .at[jnp.where(valid, seg, n_seg)]
            .add(1, mode="drop")
        )
        connc = (
            jnp.zeros(n_seg, jnp.int32)
            .at[jnp.where(conn, seg, n_seg)]
            .add(1, mode="drop")
        )
        return connc, pot

    # factored one-hot MXU contraction (exact: 0/1 entries, counts <= M < 2^24)
    lo_n = min(_LO, n_seg)
    hi_n = -(-n_seg // lo_n)  # ceil
    seg_f = seg.reshape(-1)
    valid_f = valid.reshape(-1)
    conn_f = conn.reshape(-1)
    hi = seg_f // lo_n
    lo = seg_f % lo_n
    a = (
        (hi[:, None] == jnp.arange(hi_n, dtype=jnp.int32)) & valid_f[:, None]
    ).astype(jnp.float32)  # [E, hi_n]
    b = (lo[:, None] == jnp.arange(lo_n, dtype=jnp.int32)).astype(jnp.float32)  # [E, lo_n]
    pot = jnp.round(jax.lax.dot(a.T, b, precision=_HI)).astype(jnp.int32)
    ac = a * conn_f[:, None].astype(jnp.float32)
    connc = jnp.round(jax.lax.dot(ac.T, b, precision=_HI)).astype(jnp.int32)
    return connc.reshape(-1)[:n_seg], pot.reshape(-1)[:n_seg]


# rtap: twin[TMOracle] — incremental maintenance; rebuild-vs-incremental
# equivalence pinned in tests/parity/test_fwd_index.py
def apply_removals(
    fwd_slots: jnp.ndarray,
    fwd_pos: jnp.ndarray,
    slots: jnp.ndarray,  # i32 [E] flat pool-slot ids (may contain fills)
    old_presyn: jnp.ndarray,  # i32 [E] presyn id being removed from each slot
    remove: jnp.ndarray,  # bool [E]
):
    """Detach `slots` from their presynaptic cells' fwd rows (O(1) each via
    the fwd_pos back pointer). Slot ids must be distinct where `remove`."""
    N = fwd_slots.shape[0]
    pool = fwd_pos.shape[0]
    slotc = jnp.clip(slots, 0, pool - 1)
    pos = fwd_pos[slotc].astype(jnp.int32)  # [E]
    ok = remove & (old_presyn >= 0) & (pos >= 0)
    rows = jnp.where(ok, old_presyn, N)  # N -> dropped
    fwd_slots = fwd_slots.at[rows, jnp.clip(pos, 0, fwd_slots.shape[1] - 1)].set(
        -1, mode="drop"
    )
    fwd_pos = fwd_pos.at[jnp.where(ok, slotc, pool)].set(
        jnp.asarray(-1, fwd_pos.dtype), mode="drop"
    )
    return fwd_slots, fwd_pos


# rtap: twin[TMOracle] — incremental maintenance (see apply_removals)
def apply_appends(
    fwd_slots: jnp.ndarray,
    fwd_pos: jnp.ndarray,
    slots: jnp.ndarray,  # i32 [E] flat pool-slot ids
    new_presyn: jnp.ndarray,  # i32 [E] presyn id now occupying each slot
    append: jnp.ndarray,  # bool [E]
):
    """Attach `slots` to their (new) presynaptic cells' fwd rows, assigning
    distinct free positions to multiple same-cell appends in one step.
    Returns (fwd_slots, fwd_pos, n_dropped) — n_dropped counts appends that
    found no free position (fanout_cap overflow; corrupts counts, so the
    caller adds it to the stream's overflow counter)."""
    N, F = fwd_slots.shape
    pool = fwd_pos.shape[0]
    E = slots.shape[0]
    # rank among earlier same-target appends -> each needs its own free slot
    same = (
        (new_presyn[:, None] == new_presyn[None, :]) & append[:, None] & append[None, :]
    )
    ee = jnp.arange(E, dtype=jnp.int32)
    rank = (same & (ee[None, :] < ee[:, None])).sum(-1).astype(jnp.int32)  # [E]
    rowdata = fwd_slots[jnp.clip(new_presyn, 0, N - 1)]  # [E, F] (post-removal)
    free = rowdata < 0
    cum = jnp.cumsum(free, axis=-1)
    hit = free & (cum == (rank + 1)[:, None])  # the (rank+1)-th free slot
    pos = jnp.argmax(hit, axis=-1).astype(jnp.int32)
    ok = append & (new_presyn >= 0) & hit.any(-1)
    dropped = (append & (new_presyn >= 0) & ~hit.any(-1)).sum().astype(jnp.int32)
    rows = jnp.where(ok, new_presyn, N)
    fwd_slots = fwd_slots.at[rows, pos].set(slots, mode="drop")
    fwd_pos = fwd_pos.at[jnp.where(ok, jnp.clip(slots, 0, pool - 1), pool)].set(
        pos.astype(fwd_pos.dtype), mode="drop"
    )
    return fwd_slots, fwd_pos, dropped
