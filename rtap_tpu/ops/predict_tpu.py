"""Fused on-device predictive-horizon reducer (ISSUE 16 tentpole).

The pipeline detects anomalies 1-2 s AFTER onset (ROADMAP item 6); the
paper's title promises *prediction*. The TM already computes a forward
model every tick — its active segments name the columns it expects next
— and throws it away. :func:`predict_update` runs INSIDE the fused step
program (ops/step.py ``_tick``, behind the static ``predict`` flag,
beside ``health``): it keeps a k-deep ring of predicted-active column
sets in predictor-owned state leaves and reduces the horizon-old
prediction against the tick's actual active columns into a compact
per-stream leaf — overlap, a divergence EWMA (the trajectory the host
tracker in rtap_tpu/predict/ pages on), and predicted sparsity.

Properties the tests pin (the PR 6 health discipline):

- **Model state untouched.** The reducer reads the post-step TM state
  and writes ONLY the predictor-owned leaves (``pred_ring``,
  ``pred_miss_ewma``), which exist only when a horizon is configured —
  with ``--predict`` off the state tree, scores, and alert stream are
  byte-identical to a predict-less build
  (tests/integration/test_predict_serve.py).
- **No extra device<->host fetch.** The [G] leaf rides the existing
  chunk output beside the scores.
- **Bit-exact twin.** The numpy oracle twin lives in
  models/oracle/predict.py (``predict_update_host``) — same schema,
  same f32 arithmetic, power-of-two EWMA alpha;
  tests/parity/test_predict_parity.py pins device == oracle.

Semantics (full derivation in the twin module's docstring): at tick t
the ring slot ``t % k`` is read (the prediction captured at ``t - k``)
then overwritten with this tick's prediction; overlap vs the actual
active columns scores only streams that are live AND past their
per-stream warm-up (``t >= pred_tick0 + k`` — a claimed slot's zeroed
ring must not fake a divergence).
"""

from __future__ import annotations

import numpy as np

from rtap_tpu.config import ModelConfig
from rtap_tpu.models.oracle.predict import (
    PRED_ALPHA,
    PREDICT_KEYS,
    predict_horizon_of,
    predict_nbytes,
)

__all__ = [
    "PREDICT_KEYS",
    "PRED_ALPHA",
    "predict_horizon_of",
    "predict_nbytes",
    "predict_update",
]


# rtap: twin[predict_update_host] — numpy oracle twin on public-layout
# state (models/oracle/predict.py); parity: tests/parity/test_predict_parity.py
def predict_update(state: dict, values, cfg: ModelConfig) -> tuple[dict, dict]:
    """Fold one tick into the predictor state -> (state', leaf [G]).

    `state` is the kernel-layout POST-STEP group state (flat or aos —
    the reads reshape like the health reducer, layout-invariant),
    `values` the [G, n_fields] polled inputs (live-stream mask source).
    Traced inside the fused step program: shape-static, the only writes
    are the predictor-owned ring + EWMA leaves (donation-safe in-place
    updates). See :data:`PREDICT_KEYS` for the leaf schema.
    """
    import jax.numpy as jnp

    tm = cfg.tm
    C, K, S = cfg.sp.columns, tm.cells_per_column, tm.max_segments_per_cell
    ring = state["pred_ring"]
    G, k = ring.shape[0], ring.shape[1]

    liv = jnp.isfinite(values).any(-1)  # [G] streams with data this tick
    # tm_iter counts COMPLETED steps (lockstep scalar); the tick just
    # scored is t = tm_iter - 1
    t = state["tm_iter"].reshape(-1)[0].astype(jnp.int32) - jnp.int32(1)
    slot = jnp.mod(t, jnp.int32(k))

    act = state["prev_active"].reshape(G, C, K).any(-1)  # [G, C] this tick
    aseg = state["active_seg"].reshape(G, C, K, S)
    pred_new = aseg.any(-1).any(-1)  # [G, C] columns predicted for t+1

    old = jnp.take(ring, slot, axis=1)  # the set captured at tick t - k
    act_n = act.sum(-1).astype(jnp.float32)
    ov_n = (old & act).sum(-1).astype(jnp.float32)
    overlap = ov_n / jnp.maximum(act_n, jnp.float32(1.0))
    miss = jnp.float32(1.0) - overlap

    tick0 = state["pred_tick0"].reshape(G).astype(jnp.int32)
    scored = liv & (t >= tick0 + jnp.int32(k))

    ewma = state["pred_miss_ewma"].reshape(G).astype(jnp.float32)
    folded = jnp.where(jnp.isnan(ewma), miss,
                       ewma + PRED_ALPHA * (miss - ewma))
    new_ewma = jnp.where(scored, folded, ewma)

    state = dict(state)
    state["pred_ring"] = ring.at[:, slot, :].set(pred_new)
    state["pred_miss_ewma"] = new_ewma.reshape(
        np.shape(state["pred_miss_ewma"]))

    leaf = {
        "overlap": jnp.where(scored, overlap, jnp.float32(np.nan)),
        "miss_ewma": new_ewma,
        "pred_col_frac": (pred_new.sum(-1).astype(jnp.float32)
                          / jnp.float32(C)),
        "scored": scored,
    }
    return state, leaf
