"""Temporal Memory — device kernel (functional twin of oracle/temporal_memory.py).

The reference's TM is Cells4.cpp/TemporalMemory.cpp over the Connections
pointer graph (SURVEY.md C4/C5). TPU-native re-design (SURVEY.md §7 hard part
1): fixed-capacity dense pools [C, K, S, M] of (presyn id, permanence), and a
step composed of

  1. column categorization (predicted / burst-matching / burst-new) — dense,
  2. burst-new segment allocation (first-free slot else LRU-evict) — scatter,
  3. a *compact learning pass*: the <= learn_cap segments that learn this step
     are gathered to a [L, M] workspace, reinforced, grown toward previous
     winner cells (membership test + rank-select + weakest-synapse eviction,
     all static-shape), and scattered back,
  4. dense punishment of matching segments in non-active columns,
  5. dense synapse/segment death,
  6. dense dendrite activity (gather presyn -> segment popcounts) for t+1.

Tie-breaks are lowest-index everywhere, matching the oracle exactly; parity
is bit-for-bit (tests/parity/test_tm_parity.py).

Capacity bounds (learn_cap learning segments, winner_cap previous winners per
step) are static-shape requirements of XLA; overflow beyond the bounds is
counted in state["tm_overflow"] so tests can assert it never fires at the
configured sizes.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from rtap_tpu.config import TMConfig

INF = jnp.float32(jnp.inf)


# Strategy switch for ops whose natural formulation (gather / nonzero)
# serializes on the TPU scalar core: None = per-backend default (TPU-friendly
# reformulations on TPU, plain gather/nonzero elsewhere); tests flip it to
# cover both code paths on the CPU platform. Both paths are bit-identical.
FORCE_TPU_PATHS: bool | None = None

# Above this many [R, L] match elements (16M f32 = 64 MiB per stream) the
# one-hot write-back matmul costs more memory than it saves time; use the
# plain scatter instead (see the write-back branch in tm_step).
_MATCH_WRITEBACK_MAX = 16 * 1024 * 1024


def _tpu_paths() -> bool:
    if FORCE_TPU_PATHS is not None:
        return FORCE_TPU_PATHS
    return jax.default_backend() == "tpu"


def _compact_ids(mask: jnp.ndarray, size: int) -> jnp.ndarray:
    """Indices of the first `size` True entries of `mask` [n], ascending,
    filled with n -> i32 [size].

    Equivalent to jnp.nonzero(mask, size=size, fill_value=n)[0], but on TPU
    nonzero's cumsum+pack runs on the scalar core (~16 ms/tick across the four
    call sites at G=128 — profiled); top_k of (n - index) is the vector-unit
    formulation: descending top_k of distinct values = ascending indices.
    """
    n = mask.shape[0]
    if not _tpu_paths():
        return jnp.nonzero(mask, size=size, fill_value=n)[0].astype(jnp.int32)
    k = min(size, n)  # top_k rejects k > n; a cap larger than the domain
    iota = jnp.arange(n, dtype=jnp.int32)
    top = jax.lax.top_k(jnp.where(mask, n - iota, 0), k)[0]
    ids = jnp.where(top > 0, n - top, n).astype(jnp.int32)
    if k < size:
        ids = jnp.concatenate([ids, jnp.full(size - k, n, jnp.int32)])
    return ids


def _presyn_active(presyn: jnp.ndarray, flat: jnp.ndarray, ids: jnp.ndarray) -> jnp.ndarray:
    """Is each synapse's presynaptic cell active? -> bool, presyn's shape.

    `presyn` [..., M] i32 (-1 = empty); `flat` bool [N] dense activity;
    `ids` [A] i32 the same activity as a compact ascending id list (fill N).

    Two bit-identical implementations: on TPU, compare-any membership against
    `ids` — XLA lowers `flat[presyn]` gathers to a serialized scalar-core loop
    (~135 ms/tick at G=128, C=256 — profiled; it was the framework
    bottleneck), while eq+any is pure VPU work. On CPU the gather is the fast
    path (membership costs M*A compares per synapse). Empty slots (-1) and id
    fills (N) never match / are masked.
    """
    if _tpu_paths():
        return (presyn[..., None] == ids).any(-1)
    N = flat.shape[0]
    return (presyn >= 0) & flat[jnp.clip(presyn, 0, N - 1)]


def _segment_learning_mask(
    cfg: TMConfig,
    active_cols: jnp.ndarray,  # bool [C]
    active_seg: jnp.ndarray,  # bool [C, K, S] (prev step)
    matching_seg: jnp.ndarray,  # bool [C, K, S] (prev step)
    seg_pot: jnp.ndarray,  # i32 [C, K, S] (prev step)
    seg_last: jnp.ndarray,  # i32 [C, K, S]
    have_winners: jnp.ndarray,  # bool scalar (any prev winner cells)
):
    """Categorize columns and pick the per-column learning segments.

    Returns (predicted_cols, learn_mask, alloc [C,3] (col, cell, slot) for
    burst-new allocations with col==C when inactive, winner_cells_extra
    [C, K] winner contributions from burst columns).
    """
    C, K, S = active_seg.shape
    prev_predictive = active_seg.any(-1)  # [C, K]
    predicted_cols = prev_predictive.any(-1)  # [C]

    burst = active_cols & ~predicted_cols
    col_matching = matching_seg.any((-2, -1))  # [C]
    burst_match = burst & col_matching
    burst_new = burst & ~col_matching & have_winners

    # (a) predicted columns: every active segment of every predicted cell learns
    mask_pred = active_cols[:, None, None] & active_seg

    # (b) burst-matching: best matching segment (max seg_pot, lowest flat index)
    pot = jnp.where(matching_seg, seg_pot, -1).reshape(C, K * S)
    best_flat = jnp.argmax(pot, axis=-1)  # first max — same as np.argmax
    bm_k, bm_s = best_flat // S, best_flat % S
    bm_mask = (
        jnp.zeros((C, K, S), bool)
        .at[jnp.arange(C), bm_k, bm_s]
        .set(burst_match)
    )

    # (c) burst-new: cell with fewest segments; first free slot else LRU slot
    seg_counts = (seg_last >= 0).sum(-1)  # [C, K]
    bn_k = jnp.argmin(seg_counts, axis=-1)  # first min — matches oracle
    # one-hot select of row bn_k (a [C] gather serializes on TPU); exactly one
    # k matches per column, so the sum passes values (incl. -1) through.
    sel_k = jnp.arange(K, dtype=jnp.int32)[None, :] == bn_k[:, None]  # [C, K]
    row_last = jnp.where(sel_k[:, :, None], seg_last, 0).sum(1)  # [C, S]
    any_free = (row_last < 0).any(-1)
    first_free = jnp.argmax(row_last < 0, axis=-1)
    lru = jnp.argmin(row_last, axis=-1)
    bn_s = jnp.where(any_free, first_free, lru)

    # burst-column winner cells, one-hot (no scatter: a False write from one
    # branch must never clobber a True from the other)
    kk = jnp.arange(K, dtype=jnp.int32)[None, :]
    winner_extra = (burst_match[:, None] & (kk == bm_k[:, None])) | (
        (burst & ~col_matching)[:, None] & (kk == bn_k[:, None])  # winner even when no alloc
    )

    alloc_col = jnp.where(burst_new, jnp.arange(C), C)  # C == dropped
    return predicted_cols, mask_pred | bm_mask, (alloc_col, bn_k, bn_s), winner_extra, burst


def _grow_compact(
    cfg: TMConfig,
    presyn_l: jnp.ndarray,  # i32 [L, M] (post-reinforce)
    perm_l: jnp.ndarray,  # f32 [L, M]
    n_grow: jnp.ndarray,  # i32 [L]
    winner_ids: jnp.ndarray,  # i32 [W] ascending, padded with N
    n_cells: int,
):
    """Oracle _grow_synapses, vectorized: per segment, add the first
    min(n_grow, #eligible) winner cells (ascending id, not already
    presynaptic), evicting weakest synapses when free slots run short."""
    L, M = presyn_l.shape
    W = winner_ids.shape[0]
    G = cfg.new_synapse_count  # max grown per segment per step

    valid_w = winner_ids < n_cells
    # membership: winner already presynaptic on this segment?  [L, W]
    already = (presyn_l[:, None, :] == winner_ids[None, :, None]).any(-1)
    eligible = valid_w[None, :] & ~already
    rank = jnp.cumsum(eligible, axis=1)  # 1-based among eligible
    chosen = eligible & (rank <= n_grow[:, None])
    n_new = chosen.sum(-1).astype(jnp.int32)  # [L]

    # extract chosen winner positions ascending -> [L, G]
    wpos = jnp.where(chosen, jnp.arange(W, dtype=jnp.int32), W)
    wpos = jax.lax.sort(wpos, dimension=1)[:, :G]
    new_ids = jnp.where(wpos < W, winner_ids[jnp.clip(wpos, 0, W - 1)], n_cells)  # [L]

    # evict weakest occupied synapses if short of free slots (stable by slot)
    occupied = presyn_l >= 0
    n_free = M - occupied.sum(-1)
    short = n_new - n_free  # [L]
    key = jnp.where(occupied, perm_l, INF)
    ranks = jnp.argsort(jnp.argsort(key, axis=-1, stable=True), axis=-1, stable=True)
    evict = occupied & (ranks < short[:, None])
    presyn_l = jnp.where(evict, -1, presyn_l)
    perm_l = jnp.where(evict, 0.0, perm_l)

    # fill free slots ascending with new ids ascending
    free = presyn_l < 0
    frank = jnp.cumsum(free, axis=-1) - 1  # 0-based among free slots
    assign = free & (frank < n_new[:, None])
    fill = new_ids[jnp.arange(L)[:, None], jnp.clip(frank, 0, G - 1)]
    presyn_l = jnp.where(assign, fill, presyn_l)
    perm_l = jnp.where(assign, jnp.float32(cfg.initial_permanence), perm_l)
    return presyn_l, perm_l


@partial(jax.jit, static_argnames=("cfg", "learn"))
def tm_step(state: dict, active_cols: jnp.ndarray, cfg: TMConfig, learn: bool = True):
    """One TM step -> (new_state, raw anomaly score f32). Pure.

    `state` uses the models/state.py TM layout plus "tm_overflow" (i32
    overflow counter, device-only observability).
    """
    C, K, S, M = state["presyn"].shape
    N = C * K
    L, W = cfg.learn_cap, cfg.winner_cap

    presyn = state["presyn"]
    syn_perm = state["syn_perm"]
    seg_last = state["seg_last"]
    it = state["tm_iter"] + 1

    prev_predictive = state["active_seg"].any(-1)  # [C, K]
    prev_pred_cols = prev_predictive.any(-1)
    n_active = active_cols.sum()
    raw = jnp.where(
        n_active > 0,
        1.0 - (active_cols & prev_pred_cols).sum() / jnp.maximum(n_active, 1).astype(jnp.float32),
        0.0,
    )

    prev_active_flat = state["prev_active"].reshape(-1)  # bool [N]
    prev_winner_flat = state["prev_winner"].reshape(-1)
    n_winners = prev_winner_flat.sum()
    have_winners = n_winners > 0

    predicted_cols, learn_mask, alloc, winner_extra, burst = _segment_learning_mask(
        cfg, active_cols, state["active_seg"], state["matching_seg"], state["seg_pot"],
        seg_last, have_winners,
    )

    # cell activation / winner selection (pure function of prev state)
    active_cells = (
        jnp.where((active_cols & predicted_cols)[:, None], prev_predictive, False)
        | burst[:, None]
    )
    winner_cells = (
        jnp.where((active_cols & predicted_cols)[:, None], prev_predictive, False)
        | winner_extra
    )

    A = cfg.active_cap
    prev_ids = _compact_ids(prev_active_flat, A)

    if learn:
        alloc_col, bn_k, bn_s = alloc

        # --- burst-new allocation: clear slot (evict if LRU) + stamp ---
        # Dense one-hot writes, not scatters: XLA's TPU scatter on the [C,K,S,M]
        # pools serializes and drags transposed-layout copies along (~23 ms/tick
        # each at G=1024 — profiled).
        burst_new = alloc_col < C  # [C]
        sel_k_a = jnp.arange(K, dtype=bn_k.dtype)[None, :] == bn_k[:, None]  # [C, K]
        sel_s_a = jnp.arange(S, dtype=bn_s.dtype)[None, :] == bn_s[:, None]  # [C, S]
        alloc_mask = burst_new[:, None, None] & sel_k_a[:, :, None] & sel_s_a[:, None, :]
        presyn = jnp.where(alloc_mask[..., None], -1, presyn)
        syn_perm = jnp.where(alloc_mask[..., None], jnp.float32(0), syn_perm)
        seg_pot0 = jnp.where(alloc_mask, 0, state["seg_pot"])
        seg_last = jnp.where(alloc_mask, it, seg_last)
        lm = learn_mask | alloc_mask
        overflow = (lm.sum() > L) | (n_winners > W) | (prev_active_flat.sum() > A)

        # --- compact gather of learning segments ---
        idx = _compact_ids(lm.reshape(-1), L)
        valid_l = idx < C * K * S
        safe = jnp.clip(idx, 0, C * K * S - 1)
        presyn_l = presyn.reshape(-1, M)[safe]
        perm_l = syn_perm.reshape(-1, M)[safe]
        pot_l = seg_pot0.reshape(-1)[safe]

        # reinforce: +inc on synapses to prev-active cells, -dec on the rest
        exists = presyn_l >= 0
        act = _presyn_active(presyn_l, prev_active_flat, prev_ids)
        perm_l = jnp.clip(
            perm_l
            + cfg.permanence_increment * act
            - cfg.permanence_decrement * (exists & ~act),
            0.0,
            1.0,
        )

        # grow toward previous winner cells (ascending id)
        winner_ids = _compact_ids(prev_winner_flat, W)
        n_grow = (cfg.new_synapse_count - pot_l).astype(jnp.int32)
        grown_presyn, grown_perm = _grow_compact(cfg, presyn_l, perm_l, n_grow, winner_ids, N)
        grow_ok = have_winners & valid_l
        presyn_l = jnp.where(grow_ok[:, None], grown_presyn, presyn_l)
        perm_l = jnp.where(grow_ok[:, None], grown_perm, perm_l)

        if not _tpu_paths() or (C * K * S) * L > _MATCH_WRITEBACK_MAX:
            # Plain row scatter. On CPU it is the fast path. On TPU it
            # serializes per update row, but at large-model sizes (NAB preset:
            # R = 1M, L = 128) the scatter is only ~L rows while the match
            # matrix below would be R*L f32 = 512 MiB per stream — the scatter
            # wins. idx is ascending with OOB fills; applied rows are unique.
            hint = dict(mode="drop", unique_indices=True, indices_are_sorted=True)
            presyn = presyn.reshape(-1, M).at[idx].set(presyn_l, **hint).reshape(C, K, S, M)
            syn_perm = syn_perm.reshape(-1, M).at[idx].set(perm_l, **hint).reshape(C, K, S, M)
            seg_last = seg_last.reshape(-1).at[idx].set(it, **hint).reshape(C, K, S)
        else:
            # Write-back as a one-hot matmul (MXU): XLA's TPU scatter
            # serializes per update (~170 ms/tick at stream-group sizes) and
            # row gathers / select-reduces drag transposed-layout pool copies
            # along (~60 ms each — profiled). idx is unique, so inverting the
            # scatter is an [R, L] equality match; each output row has at most
            # one 1.0, so values pass through exactly (1.0*x accumulated with
            # 0.0s in f32; presyn ids < 2^24).
            rows = jnp.arange(C * K * S, dtype=idx.dtype)
            match = rows[:, None] == idx[None, :]  # [R, L]
            hit = match.any(-1)
            match_f = match.astype(jnp.float32)
            scat_presyn = jnp.round(
                jax.lax.dot(match_f, presyn_l.astype(jnp.float32),
                            precision=jax.lax.Precision.HIGHEST)
            ).astype(jnp.int32)
            scat_perm = jax.lax.dot(match_f, perm_l, precision=jax.lax.Precision.HIGHEST)
            presyn = jnp.where(hit[:, None], scat_presyn, presyn.reshape(-1, M)).reshape(C, K, S, M)
            syn_perm = jnp.where(hit[:, None], scat_perm, syn_perm.reshape(-1, M)).reshape(C, K, S, M)
            seg_last = jnp.where(hit, it, seg_last.reshape(-1)).reshape(C, K, S)

        # --- punish matching segments in columns that did not activate ---
        if cfg.predicted_segment_decrement > 0.0:
            pmask = state["matching_seg"] & ~active_cols[:, None, None]
            pact = _presyn_active(presyn, prev_active_flat, prev_ids)
            syn_perm = jnp.where(
                pmask[..., None] & pact,
                jnp.maximum(syn_perm - cfg.predicted_segment_decrement, 0.0),
                syn_perm,
            )

        # --- synapse death at permanence <= 0, then empty-segment death ---
        dead = (presyn >= 0) & (syn_perm <= 0.0)
        presyn = jnp.where(dead, -1, presyn)
        nsyn = (presyn >= 0).sum(-1)
        seg_last = jnp.where((seg_last >= 0) & (nsyn == 0), -1, seg_last)

        overflow_learn = overflow
    else:
        overflow_learn = jnp.bool_(False)

    # --- dendrite activity for t+1 over existing segments ---
    exists_seg = seg_last >= 0
    active_flat = active_cells.reshape(-1)
    act_ids = _compact_ids(active_flat, A)
    # the act_ids truncation applies under inference too — count it always
    tm_overflow = state["tm_overflow"] + (
        overflow_learn | (active_flat.sum() > A)
    ).astype(jnp.int32)
    syn_act = _presyn_active(presyn, active_flat, act_ids)
    conn_count = (syn_act & (syn_perm >= cfg.connected_permanence)).sum(-1)
    pot_count = syn_act.sum(-1)
    active_seg = exists_seg & (conn_count >= cfg.activation_threshold)
    matching_seg = exists_seg & (pot_count >= cfg.min_threshold)
    seg_pot = jnp.where(exists_seg, pot_count, 0).astype(jnp.int32)
    if learn:
        # LRU stamp for active segments (NuPIC stamps under learn only)
        seg_last = jnp.where(active_seg, it, seg_last)

    new_state = {
        **state,
        "presyn": presyn,
        "syn_perm": syn_perm,
        "seg_last": seg_last,
        "active_seg": active_seg,
        "matching_seg": matching_seg,
        "seg_pot": seg_pot,
        "prev_active": active_cells,
        "prev_winner": winner_cells,
        "tm_iter": it.astype(jnp.int32),  # oracle increments under inference too
        "tm_overflow": tm_overflow,
    }
    return new_state, raw
