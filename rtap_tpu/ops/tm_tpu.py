"""Temporal Memory — device kernel (functional twin of oracle/temporal_memory.py).

The reference's TM is Cells4.cpp/TemporalMemory.cpp over the Connections
pointer graph (SURVEY.md C4/C5). TPU-native re-design (SURVEY.md §7 hard part
1): fixed-capacity dense pools [C, K, S, M] of (presyn id, permanence), and a
step built around two column-compact structures (profiled on v5e: the flat
formulations in the first design cost 144 ms/tick at G=2048; these bring the
same semantics down by an order of magnitude):

1. **Packed-column membership.** "Is this synapse's presynaptic cell active?"
   Active cells can only live in active columns (<= col_cap of them, = SP's
   k winners), so the active set is (column ids [Ac], per-column K-bit cell
   masks [Ac]) instead of a flat cell-id list. Membership is an
   [..., Ac] compare + mask-select + bit probe — 8-32x fewer VPU ops than the
   flat cell-id compare at preset sizes, and no serialized gather.
2. **Column-compact learning workspace.** Every learning segment lives in an
   active column, so the learning pass gathers the <= col_cap active columns
   into a [Ac, K, S, M] workspace with one-hot MXU matmuls (XLA's TPU scatter
   and row-gather on the full pool serialize — profiled in round 1), does the
   compact reinforce/grow pass there (selecting <= learn_cap segments with a
   cheap top_k over Ac*K*S instead of C*K*S), and scatters the workspace back
   with the transposed one-hot matmul + column mask.

Step outline: dense column categorization (predicted / burst-matching /
burst-new) -> workspace learning (alloc, reinforce, grow toward previous
winner cells with weakest-synapse eviction) -> punishment of matching
segments in non-active columns -> synapse/segment death -> dendrite activity
for t+1. Tie-breaks are lowest-index everywhere, matching the oracle exactly;
parity is bit-for-bit (tests/parity/test_tm_parity.py). Punish/death run
either as dense full-pool sweeps or as the round-4 compact touched-rows pass
(RTAP_TM_SWEEP), and dendrite activity as a full-pool scan or through the
forward synapse index (RTAP_TM_DENDRITE; ops/fwd_index.py) — see the switch
table below; every combination is parity-pinned.

Round 6 (docs/KERNELS.md): the roofline pinned the step latency-bound
(10x over the HBM floor, MXU < 0.1%) — the binding cost is the number of
scheduled regions per scan iteration, not arithmetic. The workspace path
is therefore region-consolidated: presyn + perm (+ seg_pot / the forward
diff base) ride ONE one-hot MXU pass per gather/scatter stage instead of
one pass per tensor (bitwise identical per block — each output element
touches only its own operand columns), the dendrite conn/pot counts share
one block-diagonal reduction, and tick-invariant operands (the flat
layout's reduction matrix) hoist out of the chunk scan via
:func:`tm_invariants`. The escalation beyond what XLA will fuse is the
RTAP_TM_SCATTER=pallas megakernel (ops/pallas_tm.py): the whole learning
pass VMEM-resident with no workspace movement at all.

Capacity bounds (col_cap active columns, learn_cap learning segments per
step) are static-shape requirements of XLA; overflow beyond the bounds is
counted in state["tm_overflow"] so tests can assert it never fires at the
configured sizes.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp

from rtap_tpu.config import TMConfig
from rtap_tpu.models.perm import tm_domain

INF = jnp.float32(jnp.inf)
_HI = jax.lax.Precision.HIGHEST


# Strategy switch for _compact_ids, whose natural formulation (nonzero)
# serializes on the TPU scalar core: None = per-backend default (top_k
# reformulation on TPU, nonzero elsewhere); tests flip it to cover both code
# paths on the CPU platform. Both paths are bit-identical.
FORCE_TPU_PATHS: bool | None = None


def _tpu_paths() -> bool:
    if FORCE_TPU_PATHS is not None:
        return FORCE_TPU_PATHS
    return jax.default_backend() == "tpu"


# ---------------------------------------------------------------------------
# Kernel strategy switches. Each is a trace-time constant (NOT a jit cache
# key): the env var is read ONCE at import — mutating os.environ mid-process
# has no effect (set_*_mode() is the only supported runtime override, and it
# clears the jit caches so stale compiled kernels can never mix modes).
# All alternatives are bit-identical (tests/parity/); scripts/hw_session.py
# races them on silicon and the measured winners become defaults.
#
#   RTAP_TM_SCATTER  matmul|indexed|pallas
#                                     workspace row movement: one-hot MXU
#                                     matmuls (full-pool f32 round trips) vs
#                                     jnp.take/.at[].set of touched rows only
#                                     vs the Pallas TM-learning megakernel
#                                     (ops/pallas_tm.py: the whole learning
#                                     pass fused in VMEM, dense sweeps, no
#                                     workspace movement at all)
#   RTAP_TM_LAYOUT   aos|flat         pools [C,K,S,M] (TPU tiling pads the
#                                     tiny trailing dims up to ~20x) vs
#                                     [C, K*S*M] with block-diagonal-matmul
#                                     per-segment reductions
#   RTAP_TM_SWEEP    dense|compact    punish/death as full-pool sweeps vs
#                                     gather/update/scatter of the <=
#                                     punish_cap + learn_cap touched segment
#                                     rows (ops/tm_tpu.py round 4)
#   RTAP_TM_DENDRITE scan|forward     dendrite activity as a full-pool scan
#                                     vs the forward synapse index
#                                     (ops/fwd_index.py; state carries
#                                     fwd_slots/fwd_pos/fwd_of)
#   RTAP_TM_FWD_IMPL scatter|matmul   forward-index histogram accumulation:
#                                     native scatter-add vs factored one-hot
#                                     MXU contraction
# ---------------------------------------------------------------------------
import os as _os

_MODE_CHOICES = {
    "scatter": ("matmul", "indexed", "pallas"),
    "layout": ("aos", "flat"),
    "sweep": ("dense", "compact"),
    "dendrite": ("scan", "forward"),
    "fwd_impl": ("scatter", "matmul"),
}
_ENV_NAMES = {
    "scatter": "RTAP_TM_SCATTER",
    "layout": "RTAP_TM_LAYOUT",
    "sweep": "RTAP_TM_SWEEP",
    "dendrite": "RTAP_TM_DENDRITE",
    "fwd_impl": "RTAP_TM_FWD_IMPL",
}
# Defaults are the measured silicon winners (SCALING.md round-4 A/B,
# hw_results/ 2026-07-31): flat layout beat aos by 13% on the full
# learning step (31.9k vs 28.1k metrics/s at G=1024) and matmul scatter
# beat indexed by 1.55x — the reverse of the CPU-drive signal.
_MODE_DEFAULTS = {
    "scatter": "matmul",
    "layout": "flat",
    "sweep": "dense",
    "dendrite": "scan",
    "fwd_impl": "scatter",
}
# start-of-process env snapshot (read once; see block comment above)
_MODES: dict[str, str] = {
    k: _os.environ.get(env, _MODE_DEFAULTS[k]) for k, env in _ENV_NAMES.items()
}
for _k, _v in _MODES.items():
    if _v not in _MODE_CHOICES[_k]:
        raise ValueError(
            f"{_ENV_NAMES[_k]} must be one of {_MODE_CHOICES[_k]}, got {_v!r}"
        )
# runtime overrides (set_*_mode); None = keep the env snapshot value
_OVERRIDES: dict[str, str | None] = {k: None for k in _MODES}


def _mode(kind: str) -> str:
    ov = _OVERRIDES[kind]
    return _MODES[kind] if ov is None else ov


def _set_mode(kind: str, mode: str | None) -> None:
    if mode is not None and mode not in _MODE_CHOICES[kind]:
        raise ValueError(
            f"{kind} mode must be None or one of {_MODE_CHOICES[kind]}, got {mode!r}"
        )
    _OVERRIDES[kind] = mode
    jax.clear_caches()


def scatter_mode() -> str:
    return _mode("scatter")


def layout_mode() -> str:
    return _mode("layout")


def sweep_mode() -> str:
    return _mode("sweep")


def dendrite_mode() -> str:
    return _mode("dendrite")


def fwd_impl() -> str:
    return _mode("fwd_impl")


def set_scatter_mode(mode: str | None) -> None:
    """Override the workspace-movement strategy AND clear jit caches."""
    _set_mode("scatter", mode)


def set_layout_mode(mode: str | None) -> None:
    """Override the kernel tensor layout AND clear jit caches."""
    _set_mode("layout", mode)


def set_sweep_mode(mode: str | None) -> None:
    """Override the punish/death sweep strategy AND clear jit caches."""
    _set_mode("sweep", mode)


def set_dendrite_mode(mode: str | None) -> None:
    """Override the dendrite-activity strategy AND clear jit caches.

    "forward" requires state built with the forward index present
    (models/state.init_state reads this mode; checkpoint load rebuilds the
    index from `presyn` — service/checkpoint.py)."""
    _set_mode("dendrite", mode)


def set_fwd_impl(mode: str | None) -> None:
    """Override the forward-index histogram strategy AND clear jit caches."""
    _set_mode("fwd_impl", mode)


# TM state keys reshaped by the flat kernel layout: key -> how many trailing
# dims collapse into one (pools: K,S,M -> K*S*M; segment tensors: K,S -> K*S).
_FLAT_KEYS = {
    "presyn": 3, "syn_perm": 3,
    "seg_last": 2, "active_seg": 2, "matching_seg": 2, "seg_pot": 2,
}


def to_kernel_layout(state: dict) -> dict:
    """Public state layout -> kernel layout (no-op in "aos" mode). Shape
    change only — values are untouched, so checkpoints, the oracle, and the
    parity harness all keep the public [C, K, S, M] layout."""
    if layout_mode() != "flat":
        return state
    out = dict(state)
    for k, nd in _FLAT_KEYS.items():
        x = out[k]
        out[k] = x.reshape(*x.shape[: x.ndim - nd], -1)
    return out


def from_kernel_layout(state: dict, cfg: TMConfig) -> dict:
    """Kernel layout -> public state layout (no-op in "aos" mode)."""
    if layout_mode() != "flat":
        return state
    K, S, M = cfg.cells_per_column, cfg.max_segments_per_cell, cfg.max_synapses_per_segment
    tails = {3: (K, S, M), 2: (K, S)}
    out = dict(state)
    for k, nd in _FLAT_KEYS.items():
        x = out[k]
        out[k] = x.reshape(*x.shape[:-1], *tails[nd])
    return out


@lru_cache(maxsize=None)
def _reduce_matrix(ks: int, m: int):
    """Block-diagonal 0/1 [ks*m, ks] f32: column s sums synapse lanes
    [s*m, (s+1)*m) — the per-segment Σ_M reduction as one MXU matmul.
    (Moved here from the retired dendrite-only Pallas kernel: it is the
    flat layout's seg_sum operand, load-bearing independent of Pallas.)"""
    import numpy as np

    r = np.zeros((ks * m, ks), np.float32)
    for s in range(ks):
        r[s * m : (s + 1) * m, s] = 1.0
    return r


def tm_invariants(cfg: TMConfig) -> dict | None:  # rtap: allow[twin-parity] — trace-time constant builder (reduction matrix), not a semantic kernel; exercised through every tm_step parity run
    """Tick-invariant device operands of :func:`tm_step`, built ONCE so a
    caller scanning over ticks (ops/step.py:_scan_chunk) can hoist them
    out of the scan body explicitly — they stay HBM-resident across the
    whole T-tick chunk instead of rematerializing as per-iteration
    constants. None when the current layout needs none (aos reduces on the
    trailing dim directly)."""
    if layout_mode() != "flat":
        return None
    K, S, M = cfg.cells_per_column, cfg.max_segments_per_cell, cfg.max_synapses_per_segment
    return {"red": jnp.asarray(_reduce_matrix(K * S, M))}


def _compact_ids(mask: jnp.ndarray, size: int) -> jnp.ndarray:
    """Indices of the first `size` True entries of `mask` [n], ascending,
    filled with n -> i32 [size].

    Equivalent to jnp.nonzero(mask, size=size, fill_value=n)[0], but on TPU
    nonzero's cumsum+pack runs on the scalar core (profiled in round 1);
    top_k of (n - index) is the vector-unit formulation: descending top_k of
    distinct values = ascending indices.
    """
    n = mask.shape[0]
    if not _tpu_paths():
        return jnp.nonzero(mask, size=size, fill_value=n)[0].astype(jnp.int32)
    k = min(size, n)  # top_k rejects k > n; a cap larger than the domain
    iota = jnp.arange(n, dtype=jnp.int32)
    top = jax.lax.top_k(jnp.where(mask, n - iota, 0), k)[0]
    ids = jnp.where(top > 0, n - top, n).astype(jnp.int32)
    if k < size:
        ids = jnp.concatenate([ids, jnp.full(size - k, n, jnp.int32)])
    return ids


def _pack_active(cells_ck: jnp.ndarray, Ac: int):
    """Column-compact representation of a [C, K] cell set (K <= 32).

    Returns (col_ids [Ac] i32 ascending with C fills, col_masks [Ac] i32
    K-bit packed per-column cell masks, n_cols i32 total occupied columns —
    n_cols > Ac means the compact form is truncated, counted as overflow).
    """
    C, K = cells_ck.shape
    col_any = cells_ck.any(-1)
    col_ids = _compact_ids(col_any, Ac)
    packed = (cells_ck.astype(jnp.int32) << jnp.arange(K, dtype=jnp.int32)).sum(-1)  # [C]
    hit = col_ids[:, None] == jnp.arange(C, dtype=jnp.int32)  # [Ac, C]
    col_masks = jnp.where(hit, packed[None, :], 0).sum(-1)
    return col_ids, col_masks, col_any.sum()


def _presyn_active_packed(
    presyn: jnp.ndarray, col_ids: jnp.ndarray, col_masks: jnp.ndarray, K: int
) -> jnp.ndarray:
    """Is each synapse's presynaptic cell in the packed active set? -> bool,
    presyn's shape. `presyn` [..., M] i32 (-1 = empty, never matches)."""
    c_pre = presyn // K  # -1 -> -1 (floor), never equals a valid col id
    k_pre = presyn % K  # python modulo: -1 -> K-1, masked by presyn >= 0
    msk = jnp.where(c_pre[..., None] == col_ids, col_masks, 0).sum(-1)
    return (presyn >= 0) & (((msk >> k_pre) & 1) > 0)


def _winner_id_list(winner_ck: jnp.ndarray, Ac: int) -> jnp.ndarray:
    """Flat cell-id list of winner cells, ascending where valid, invalid
    entries = N -> i32 [Ac*K]. Winner cells live in <= Ac columns (they are a
    subset of that step's active columns), so a column-compact construction
    avoids a [N]-wide top_k."""
    C, K = winner_ck.shape
    N = C * K
    col_ids = _compact_ids(winner_ck.any(-1), Ac)  # [Ac]
    hit = col_ids[:, None] == jnp.arange(C, dtype=jnp.int32)  # [Ac, C]
    rows = (hit[:, :, None] & winner_ck[None, :, :]).any(1)  # [Ac, K]
    ids = col_ids[:, None] * K + jnp.arange(K, dtype=jnp.int32)[None, :]
    return jnp.where(rows & (col_ids[:, None] < C), ids, N).reshape(-1)


def _segment_learning_mask(
    cfg: TMConfig,
    active_cols: jnp.ndarray,  # bool [C]
    active_seg: jnp.ndarray,  # bool [C, K, S] (prev step)
    matching_seg: jnp.ndarray,  # bool [C, K, S] (prev step)
    seg_pot: jnp.ndarray,  # i32 [C, K, S] (prev step)
    seg_last: jnp.ndarray,  # i32 [C, K, S]
    have_winners: jnp.ndarray,  # bool scalar (any prev winner cells)
):
    """Categorize columns and pick the per-column learning segments.

    Returns (predicted_cols, learn_mask, alloc [C,3] (col, cell, slot) for
    burst-new allocations with col==C when inactive, winner_cells_extra
    [C, K] winner contributions from burst columns).
    """
    C, K, S = active_seg.shape
    prev_predictive = active_seg.any(-1)  # [C, K]
    predicted_cols = prev_predictive.any(-1)  # [C]

    burst = active_cols & ~predicted_cols
    col_matching = matching_seg.any((-2, -1))  # [C]
    burst_match = burst & col_matching
    burst_new = burst & ~col_matching & have_winners

    # (a) predicted columns: every active segment of every predicted cell learns
    mask_pred = active_cols[:, None, None] & active_seg

    # (b) burst-matching: best matching segment (max seg_pot, lowest flat index)
    pot = jnp.where(matching_seg, seg_pot, -1).reshape(C, K * S)
    best_flat = jnp.argmax(pot, axis=-1)  # first max — same as np.argmax
    bm_k, bm_s = best_flat // S, best_flat % S
    bm_mask = (
        jnp.zeros((C, K, S), bool)
        .at[jnp.arange(C), bm_k, bm_s]
        .set(burst_match)
    )

    # (c) burst-new: cell with fewest segments; first free slot else LRU slot
    seg_counts = (seg_last >= 0).sum(-1)  # [C, K]
    bn_k = jnp.argmin(seg_counts, axis=-1)  # first min — matches oracle
    # one-hot select of row bn_k (a [C] gather serializes on TPU); exactly one
    # k matches per column, so the sum passes values (incl. -1) through.
    sel_k = jnp.arange(K, dtype=jnp.int32)[None, :] == bn_k[:, None]  # [C, K]
    row_last = jnp.where(sel_k[:, :, None], seg_last, 0).sum(1)  # [C, S]
    any_free = (row_last < 0).any(-1)
    first_free = jnp.argmax(row_last < 0, axis=-1)
    lru = jnp.argmin(row_last, axis=-1)
    bn_s = jnp.where(any_free, first_free, lru)

    # burst-column winner cells, one-hot (no scatter: a False write from one
    # branch must never clobber a True from the other)
    kk = jnp.arange(K, dtype=jnp.int32)[None, :]
    winner_extra = (burst_match[:, None] & (kk == bm_k[:, None])) | (
        (burst & ~col_matching)[:, None] & (kk == bn_k[:, None])  # winner even when no alloc
    )

    alloc_col = jnp.where(burst_new, jnp.arange(C), C)  # C == dropped
    return predicted_cols, mask_pred | bm_mask, (alloc_col, bn_k, bn_s), winner_extra, burst


def _grow_compact(
    cfg: TMConfig,
    presyn_l: jnp.ndarray,  # i32 [L, M] (post-reinforce)
    perm_l: jnp.ndarray,  # f32 [L, M] (domain values: perms or quanta)
    n_grow: jnp.ndarray,  # i32 [L]
    winner_ids: jnp.ndarray,  # i32 [W] ascending where valid, fills = N
    n_cells: int,
    initial_perm: jnp.ndarray,  # f32 scalar, domain value of initial_permanence
):
    """Oracle _grow_synapses, vectorized: per segment, add the first
    min(n_grow, #eligible) winner cells (ascending id, not already
    presynaptic), evicting weakest synapses when free slots run short."""
    L, M = presyn_l.shape
    W = winner_ids.shape[0]
    G = cfg.new_synapse_count  # max grown per segment per step

    valid_w = winner_ids < n_cells
    # membership: winner already presynaptic on this segment?  [L, W]
    already = (presyn_l[:, None, :] == winner_ids[None, :, None]).any(-1)
    eligible = valid_w[None, :] & ~already
    rank = jnp.cumsum(eligible, axis=1)  # 1-based among eligible
    chosen = eligible & (rank <= n_grow[:, None])
    n_new = chosen.sum(-1).astype(jnp.int32)  # [L]

    # extract chosen winner positions ascending -> [L, G]
    wpos = jnp.where(chosen, jnp.arange(W, dtype=jnp.int32), W)
    if _tpu_paths():
        # ascending distinct values via top_k (chosen positions are distinct;
        # fills map to 0 and come out last) — full lax.sort serializes worse
        # than top_k on the TPU vector unit for these tiny rows
        wpos = W - jax.lax.top_k(W - wpos, min(G, W))[0]
        if G > W:
            wpos = jnp.concatenate([wpos, jnp.full((L, G - W), W, jnp.int32)], axis=1)
    else:
        wpos = jax.lax.sort(wpos, dimension=1)[:, :G]
    new_ids = jnp.where(wpos < W, winner_ids[jnp.clip(wpos, 0, W - 1)], n_cells)  # [L]

    # evict weakest occupied synapses if short of free slots (stable by slot)
    occupied = presyn_l >= 0
    n_free = M - occupied.sum(-1)
    short = n_new - n_free  # [L]
    key = jnp.where(occupied, perm_l, INF)
    if _tpu_paths():
        # stable ascending rank by (key, slot) via compare-count: M is tiny
        # (<= 32), so the [L, M, M] compare grid is cheap, branch-free VPU
        # work — vs two serialized stable sorts
        kj, ki = key[:, :, None], key[:, None, :]  # [L, M(j), M(i)]
        jj = jnp.arange(M, dtype=jnp.int32)
        before = (kj < ki) | ((kj == ki) & (jj[None, :, None] < jj[None, None, :]))
        ranks = before.sum(1).astype(jnp.int32)  # [L, M]
    else:
        ranks = jnp.argsort(jnp.argsort(key, axis=-1, stable=True), axis=-1, stable=True)
    evict = occupied & (ranks < short[:, None])
    presyn_l = jnp.where(evict, -1, presyn_l)
    perm_l = jnp.where(evict, 0.0, perm_l)

    # fill free slots ascending with new ids ascending
    free = presyn_l < 0
    frank = jnp.cumsum(free, axis=-1) - 1  # 0-based among free slots
    assign = free & (frank < n_new[:, None])
    fill = new_ids[jnp.arange(L)[:, None], jnp.clip(frank, 0, G - 1)]
    presyn_l = jnp.where(assign, fill, presyn_l)
    perm_l = jnp.where(assign, initial_perm, perm_l)
    return presyn_l, perm_l


def _gather_rows_f32(x: jnp.ndarray, oh: jnp.ndarray) -> jnp.ndarray:
    """One-hot row gather as an MXU matmul: oh [R, C] f32 0/1 one-hot rows,
    x [C, F] f32 -> [R, F]. At most one 1.0 per output row, so values pass
    through exactly under HIGHEST precision (full-f32 passes)."""
    return jax.lax.dot(oh, x, precision=_HI)


def _gather_rows_i32(x: jnp.ndarray, oh_b: jnp.ndarray) -> jnp.ndarray:
    """One-hot row gather for i32 values of unbounded magnitude (e.g.
    iteration stamps > 2^24, where the f32 matmul would round): masked
    select + integer sum over the one-hot axis."""
    # oh_b [R, C] bool, x [C, F] i32 -> [R, F]
    return jnp.where(oh_b[:, :, None], x[None, :, :], 0).sum(1)


# rtap: twin[TMOracle] — the oracle TM is stateful (TMOracle.compute)
@partial(jax.jit, static_argnames=("cfg", "learn"))
def tm_step(state: dict, active_cols: jnp.ndarray, cfg: TMConfig, learn: bool = True,
            inv: dict | None = None):
    """One TM step -> (new_state, raw anomaly score f32). Pure.

    `state` uses the models/state.py TM layout plus "tm_overflow" (i32
    overflow counter, device-only observability). `inv` optionally carries
    the tick-invariant operands from :func:`tm_invariants` so a scanning
    caller hoists them out of its loop body; None rebuilds them as
    in-trace constants (single-dispatch callers).
    """
    flat = layout_mode() == "flat"
    if flat:
        K, S, M = cfg.cells_per_column, cfg.max_segments_per_cell, cfg.max_synapses_per_segment
        if state["presyn"].ndim != 2:
            raise ValueError(
                "RTAP_TM_LAYOUT=flat: tm_step expects kernel-layout state "
                "([C, K*S*M] pools — ops/step.py applies to_kernel_layout); "
                f"got presyn shape {state['presyn'].shape}"
            )
        C = state["presyn"].shape[0]
    else:
        C, K, S, M = state["presyn"].shape
    N = C * K
    L, Ac = cfg.learn_cap, cfg.col_cap
    if K > 32:
        raise ValueError("cells_per_column > 32 unsupported (packed cell masks)")

    pool_shape = (C, K * S * M) if flat else (C, K, S, M)
    seg_shape = (C, K * S) if flat else (C, K, S)

    def _red():
        if inv is not None:
            return inv["red"]
        return jnp.asarray(_reduce_matrix(K * S, M))

    def seg_sum(x):
        """Per-segment count over synapse lanes -> i32 [*seg_shape]. Flat
        layout reduces via the block-diagonal 0/1 MXU matmul (counts <= M <<
        2^24: f32-exact) instead of a minor-dim sum the tiler pads."""
        if not flat:
            return x.sum(-1)
        return jnp.round(
            jax.lax.dot(x.astype(jnp.float32), _red(), precision=_HI)
        ).astype(jnp.int32)

    def seg_sum2(a, b):
        """TWO per-segment counts in ONE reduction: the operands stack on
        the row axis so the flat layout pays a single [2C, K*S*M] MXU pass
        instead of two (fused-region consolidation; bitwise identical per
        block — each output element touches only its own operand rows)."""
        if not flat:
            return a.sum(-1), b.sum(-1)
        both = jnp.round(
            jax.lax.dot(
                jnp.concatenate([a, b], 0).astype(jnp.float32), _red(),
                precision=_HI,
            )
        ).astype(jnp.int32)
        return both[:C], both[C:]

    def seg_expand(x):
        """Broadcast a per-segment value onto its synapse lanes."""
        return jnp.repeat(x, M, axis=-1) if flat else x[..., None]

    # Permanence-domain constants (models/perm.py). The learning workspace
    # computes on integer-VALUED f32 in quantized domains (quanta <= 65535
    # < 2^24 are exact in f32, and the one-hot MXU gathers are f32 anyway),
    # which agrees bit-for-bit with the oracle's int32 arithmetic.
    dom = tm_domain(cfg)
    p_dt = state["syn_perm"].dtype
    p_one = jnp.float32(dom.one)
    p_inc = jnp.float32(dom.rate(cfg.permanence_increment))
    p_dec = jnp.float32(dom.rate(cfg.permanence_decrement))
    p_init = jnp.float32(dom.rate(cfg.initial_permanence))
    p_connected = dom.threshold(cfg.connected_permanence)
    presyn_dt = state["presyn"].dtype

    presyn = state["presyn"]
    syn_perm = state["syn_perm"]
    seg_last = state["seg_last"]
    it = state["tm_iter"] + 1

    # 4-D views of the SMALL segment tensors for the categorization logic
    # (32 KB each — cheap to repack; the MB-scale pools never leave flat)
    active_seg4 = state["active_seg"].reshape(C, K, S)
    matching_seg4 = state["matching_seg"].reshape(C, K, S)
    seg_pot4 = state["seg_pot"].reshape(C, K, S)
    seg_last4 = seg_last.reshape(C, K, S)

    prev_predictive = active_seg4.any(-1)  # [C, K]
    prev_pred_cols = prev_predictive.any(-1)
    n_active = active_cols.sum()
    raw = jnp.where(
        n_active > 0,
        1.0 - (active_cols & prev_pred_cols).sum() / jnp.maximum(n_active, 1).astype(jnp.float32),
        0.0,
    )

    have_winners = state["prev_winner"].any()

    predicted_cols, learn_mask, alloc, winner_extra, burst = _segment_learning_mask(
        cfg, active_cols, active_seg4, matching_seg4, seg_pot4,
        seg_last4, have_winners,
    )

    # cell activation / winner selection (pure function of prev state)
    active_cells = (
        jnp.where((active_cols & predicted_cols)[:, None], prev_predictive, False)
        | burst[:, None]
    )
    winner_cells = (
        jnp.where((active_cols & predicted_cols)[:, None], prev_predictive, False)
        | winner_extra
    )

    # Strategy resolution for this trace. The forward index cannot survive a
    # dense death sweep (presyn mutates without index updates), so forward
    # dendrite mode forces the compact sweep under learning.
    forward = dendrite_mode() == "forward"
    compact_sweep = forward or sweep_mode() == "compact"
    if forward and "fwd_slots" not in state:
        raise ValueError(
            "RTAP_TM_DENDRITE=forward: state lacks the forward index "
            "(fwd_slots/fwd_pos/fwd_of) — build it via models/state.init_state "
            "under forward mode, or rebuild from presyn with "
            "ops.fwd_index.build_fwd_index (checkpoint loads do this)"
        )
    if not forward and learn and "fwd_slots" in state:
        # learning under scan mode mutates presyn WITHOUT index maintenance;
        # a later switch to forward mode would then read a silently-stale
        # index. Refuse now instead: rebuild state under the target mode
        # (A/B runs construct one state per mode; checkpoints are
        # mode-agnostic and rebuild on load).
        raise ValueError(
            "state carries a forward index but RTAP_TM_DENDRITE=scan would "
            "learn without maintaining it (silent index corruption); "
            "re-init the state under scan mode or run with forward dendrite"
        )
    fwd_slots = state.get("fwd_slots")
    fwd_pos = state.get("fwd_pos")
    fwd_of = state.get("fwd_of")
    n_seg = C * K * S

    pallas_learn = learn and scatter_mode() == "pallas"
    if scatter_mode() == "pallas":
        if forward:
            raise ValueError(
                "RTAP_TM_SCATTER=pallas is incompatible with "
                "RTAP_TM_DENDRITE=forward: the megakernel computes dendrite "
                "counts itself and maintains no forward index"
            )
        if sweep_mode() == "compact":
            raise ValueError(
                "RTAP_TM_SCATTER=pallas is incompatible with "
                "RTAP_TM_SWEEP=compact: the megakernel fuses the DENSE "
                "punish/death sweeps in VMEM"
            )

    overflow_learn = jnp.bool_(False)
    conn_count = pot_count = tm_overflow = None
    if pallas_learn:
        # --- the whole learning pass as ONE Pallas kernel, VMEM-resident
        # (ops/pallas_tm.py): decisions stay here on [C, K, S]-scale
        # tensors; the kernel owns every pool traversal including the
        # dendrite counts for t+1 ---
        from rtap_tpu.ops.pallas_tm import tm_learn_pallas

        pcol_ids, pcol_masks, p_cols = _pack_active(state["prev_active"], Ac)
        winner_ids = _winner_id_list(state["prev_winner"], Ac)  # [Ac*K]
        acol_ids, acol_masks, a_cols = _pack_active(active_cells, Ac)
        presyn_n, perm_n, sl, conn_f, pot_f, overflow_learn = tm_learn_pallas(
            cfg, dom, presyn, syn_perm, seg_last,
            seg_pot4, matching_seg4, learn_mask, alloc,
            active_cols, have_winners, it,
            pcol_ids, pcol_masks, p_cols, winner_ids,
            acol_ids, acol_masks,
        )
        presyn = presyn_n.astype(presyn_dt).reshape(*pool_shape)
        perm_w = jnp.round(perm_n) if dom.bits else perm_n  # exact already
        syn_perm = perm_w.astype(p_dt).reshape(*pool_shape)
        seg_last = sl.reshape(*seg_shape)
        conn_count = conn_f.reshape(*seg_shape)
        pot_count = pot_f.reshape(*seg_shape)
        tm_overflow = state["tm_overflow"] + (
            overflow_learn | (a_cols > Ac)
        ).astype(jnp.int32)
    if learn and not pallas_learn:
        alloc_col, bn_k, bn_s = alloc
        burst_new = alloc_col < C  # [C]

        # --- gather the active columns into the [Ac, ...] workspace ---
        indexed = scatter_mode() == "indexed"
        col_ids = _compact_ids(active_cols, Ac)  # [Ac], fills = C
        col_oh_b = col_ids[:, None] == jnp.arange(C, dtype=jnp.int32)  # [Ac, C]
        col_oh = col_oh_b.astype(jnp.float32)
        hit_cols = col_oh_b.any(0)  # [C] columns actually captured (== active_cols sans overflow)

        if indexed:
            # move only the <= Ac touched rows; fill slots (id C) clamp to a
            # junk copy of row C-1 that is masked out of learning (ws_learn /
            # ws_alloc are False there) and dropped at scatter-back
            idx_c = jnp.clip(col_ids, 0, C - 1)
            ws_presyn = presyn.reshape(C, -1)[idx_c].astype(jnp.int32)
            ws_perm = syn_perm.reshape(C, -1)[idx_c].astype(jnp.float32)
            ws_last = seg_last.reshape(C, -1)[idx_c].reshape(Ac, K, S)
            ws_pot = state["seg_pot"].reshape(C, -1)[idx_c].astype(jnp.int32).reshape(Ac, K, S)
            ws_learn = (
                learn_mask.reshape(C, -1)[idx_c] & (col_ids < C)[:, None]
            ).reshape(Ac, K, S)
        else:
            # ONE one-hot MXU pass gathers presyn + perm + seg_pot together
            # (fused-region consolidation: each output element of the
            # concatenated matmul touches only its own operand block, so
            # the values are bitwise those of the three separate gathers;
            # seg_pot <= M << 2^24 and cell ids < 2^24 are f32-exact)
            KSM = K * S * M
            cat = jnp.concatenate(
                [
                    presyn.reshape(C, -1).astype(jnp.float32),
                    syn_perm.reshape(C, -1).astype(jnp.float32),
                    state["seg_pot"].reshape(C, -1).astype(jnp.float32),
                ],
                axis=1,
            )  # [C, 2*KSM + K*S]
            g = _gather_rows_f32(cat, col_oh)  # [Ac, 2*KSM + K*S]
            ws_presyn = jnp.round(g[:, :KSM]).astype(jnp.int32)  # [Ac, K*S*M]
            ws_perm = g[:, KSM:2 * KSM]  # [Ac, K*S*M]
            ws_pot = jnp.round(g[:, 2 * KSM:]).astype(jnp.int32).reshape(Ac, K, S)
            # seg_last carries unbounded iteration stamps (> 2^24 possible):
            # it keeps the exact integer gather
            ws_last = _gather_rows_i32(seg_last.reshape(C, -1), col_oh_b).reshape(Ac, K, S)
            ws_learn = (
                (col_oh_b[:, :, None] & learn_mask.reshape(C, -1)[None]).any(1).reshape(Ac, K, S)
            )

        # original pool content of the workspace (pre alloc-clear): the
        # forward-index maintenance diffs learned rows against it
        ws_presyn0_r = ws_presyn.reshape(Ac * K * S, M) if forward else None

        # --- burst-new allocation inside the workspace: clear slot + stamp ---
        ws_bn = (col_oh_b & burst_new[None, :]).any(-1)  # [Ac]
        ws_bnk = jnp.where(col_oh_b, bn_k[None, :], 0).sum(-1)  # [Ac]
        ws_bns = jnp.where(col_oh_b, bn_s[None, :], 0).sum(-1)
        sel_k = jnp.arange(K, dtype=jnp.int32)[None, :] == ws_bnk[:, None]  # [Ac, K]
        sel_s = jnp.arange(S, dtype=jnp.int32)[None, :] == ws_bns[:, None]  # [Ac, S]
        ws_alloc = ws_bn[:, None, None] & sel_k[:, :, None] & sel_s[:, None, :]  # [Ac, K, S]
        alloc_lanes = jnp.repeat(ws_alloc.reshape(Ac, K * S), M, axis=-1)  # [Ac, K*S*M]
        ws_presyn = jnp.where(alloc_lanes, -1, ws_presyn)
        ws_perm = jnp.where(alloc_lanes, 0.0, ws_perm)
        ws_pot = jnp.where(ws_alloc, 0, ws_pot)
        ws_last = jnp.where(ws_alloc, it, ws_last)
        ws_learn = ws_learn | ws_alloc

        # --- compact the <= learn_cap learning segments within the workspace ---
        R2 = Ac * K * S
        idx = _compact_ids(ws_learn.reshape(-1), L)  # [L], fills = R2
        valid_l = idx < R2
        ws_presyn_r = ws_presyn.reshape(R2, M)
        ws_perm_r = ws_perm.reshape(R2, M)
        presyn_l0 = None
        if indexed:
            idx_r = jnp.clip(idx, 0, R2 - 1)
            presyn_l = ws_presyn_r[idx_r]  # [L, M]; fill rows junk, see below
            perm_l = ws_perm_r[idx_r]
            pot_l = jnp.where(valid_l, ws_pot.reshape(-1)[idx_r], 0)  # [L]
            if forward:
                presyn_l0 = ws_presyn0_r[idx_r]
        else:
            row_oh_b = idx[:, None] == jnp.arange(R2, dtype=jnp.int32)  # [L, R2]
            row_oh = row_oh_b.astype(jnp.float32)
            # presyn + perm (+ the forward diff base) compact in ONE
            # [L, R2] MXU pass — same consolidation as the column gather
            parts = [ws_presyn_r.astype(jnp.float32), ws_perm_r]
            if forward:
                parts.append(ws_presyn0_r.astype(jnp.float32))
            gl = _gather_rows_f32(jnp.concatenate(parts, axis=1), row_oh)  # [L, 2-3M]
            presyn_l = jnp.round(gl[:, :M]).astype(jnp.int32)  # [L, M]
            perm_l = gl[:, M:2 * M]  # [L, M]
            pot_l = jnp.where(row_oh_b, ws_pot.reshape(-1)[None, :], 0).sum(-1)  # [L]
            if forward:
                presyn_l0 = jnp.round(gl[:, 2 * M:]).astype(jnp.int32)

        # prev-step active cells, column-compact (shared by reinforce + punish)
        pcol_ids, pcol_masks, p_cols = _pack_active(state["prev_active"], Ac)

        # reinforce: +inc on synapses to prev-active cells, -dec on the rest
        exists = presyn_l >= 0
        act = _presyn_active_packed(presyn_l, pcol_ids, pcol_masks, K)
        perm_l = jnp.clip(
            perm_l + p_inc * act - p_dec * (exists & ~act),
            0.0,
            p_one,
        )

        # grow toward previous winner cells (ascending id)
        winner_ids = _winner_id_list(state["prev_winner"], Ac)  # [Ac*K]
        n_grow = (cfg.new_synapse_count - pot_l).astype(jnp.int32)
        grown_presyn, grown_perm = _grow_compact(
            cfg, presyn_l, perm_l, n_grow, winner_ids, N, p_init
        )
        grow_ok = have_winners & valid_l
        presyn_l = jnp.where(grow_ok[:, None], grown_presyn, presyn_l)
        perm_l = jnp.where(grow_ok[:, None], grown_perm, perm_l)

        last_l = jnp.full((L,), 1, jnp.int32) * it  # [L] seg_last of learned rows
        if compact_sweep:
            # Synapse death (perm <= 0 after reinforce) and empty-segment
            # death applied IN the workspace: learned rows are the only
            # active-column rows whose perms moved this step, so handling
            # them here (and punished rows below) makes the dense full-pool
            # death sweep redundant — that equivalence is the compact-sweep
            # contract (tests/parity/test_sweep_parity.py).
            dead_l = (presyn_l >= 0) & (perm_l <= jnp.float32(dom.zero))
            presyn_l = jnp.where(dead_l, -1, presyn_l)
            last_l = jnp.where((presyn_l >= 0).sum(-1) == 0, -1, last_l)

        # --- scatter learned rows back into the workspace ---
        if indexed:
            hit_rows = jnp.zeros(R2, bool).at[idx].set(True, mode="drop")
            ws_presyn_r = ws_presyn_r.at[idx].set(presyn_l, mode="drop")
            ws_perm_r = ws_perm_r.at[idx].set(perm_l, mode="drop")
        else:
            hit_rows = row_oh_b.any(0)  # [R2]
            # presyn + perm scatter back in ONE transposed one-hot MXU pass
            scat = jax.lax.dot(
                row_oh.T,
                jnp.concatenate([presyn_l.astype(jnp.float32), perm_l], axis=1),
                precision=_HI,
            )  # [R2, 2M]
            scat_presyn = jnp.round(scat[:, :M]).astype(jnp.int32)
            scat_perm = scat[:, M:]
            ws_presyn_r = jnp.where(hit_rows[:, None], scat_presyn, ws_presyn_r)
            ws_perm_r = jnp.where(hit_rows[:, None], scat_perm, ws_perm_r)
        if indexed:
            ws_last = (
                ws_last.reshape(R2).at[idx].set(last_l, mode="drop").reshape(Ac, K, S)
            )
        else:
            last_scat = jnp.where(row_oh_b, last_l[:, None], 0).sum(0)  # [R2]
            ws_last = jnp.where(
                hit_rows.reshape(Ac, K, S), last_scat.reshape(Ac, K, S), ws_last
            )

        # --- scatter the workspace back to the pools ---
        if indexed:
            # only the <= Ac touched rows are written; fill ids (C) drop
            presyn = (
                presyn.reshape(C, -1)
                .at[col_ids]
                .set(ws_presyn_r.reshape(Ac, -1).astype(presyn_dt), mode="drop")
                .reshape(*pool_shape)
            )
            ws_perm_w = ws_perm_r.reshape(Ac, -1)
            if dom.bits:
                ws_perm_w = jnp.round(ws_perm_w)  # exact already; belt+braces
            syn_perm = (
                syn_perm.reshape(C, -1)
                .at[col_ids]
                .set(ws_perm_w.astype(p_dt), mode="drop")
                .reshape(*pool_shape)
            )
            seg_last = (
                seg_last.reshape(C, -1)
                .at[col_ids]
                .set(ws_last.reshape(Ac, -1), mode="drop")
                .reshape(*seg_shape)
            )
        else:
            hit_pool = hit_cols.reshape(C, *([1] * (len(pool_shape) - 1)))
            hit_seg = hit_cols.reshape(C, *([1] * (len(seg_shape) - 1)))
            # presyn + perm pools restored in ONE [C, Ac] x [Ac, 2*KSM] pass
            KSM = K * S * M
            pools = jax.lax.dot(
                col_oh.T,
                jnp.concatenate(
                    [
                        ws_presyn_r.reshape(Ac, -1).astype(jnp.float32),
                        ws_perm_r.reshape(Ac, -1),
                    ],
                    axis=1,
                ),
                precision=_HI,
            )  # [C, 2*KSM]
            pool_presyn = jnp.round(pools[:, :KSM]).astype(presyn_dt).reshape(*pool_shape)
            pool_perm_f = pools[:, KSM:]
            if dom.bits:
                pool_perm_f = jnp.round(pool_perm_f)  # exact already; belt+braces
            pool_perm = pool_perm_f.astype(p_dt).reshape(*pool_shape)
            pool_last = jnp.where(
                col_oh_b[:, :, None], ws_last.reshape(Ac, 1, -1), 0
            ).sum(0).reshape(*seg_shape)
            presyn = jnp.where(hit_pool, pool_presyn, presyn)
            syn_perm = jnp.where(hit_pool, pool_perm, syn_perm)
            seg_last = jnp.where(hit_seg, pool_last, seg_last)

        overflow_learn = (
            (n_active > Ac) | (p_cols > Ac) | (ws_learn.sum() > L)
        )

        slots_p = old_p = rem_p = None
        if compact_sweep:
            # --- compact punish/death (RTAP_TM_SWEEP=compact): gather the
            # <= punish_cap matching segments in non-active columns, punish
            # + kill them there, scatter back. Together with the in-workspace
            # death above this covers every synapse whose permanence moved
            # this step (learned rows and punished rows are disjoint by
            # column), so the full-pool punish/death sweeps are skipped
            # entirely — the dense sweeps re-derive death for ALL synapses,
            # but an untouched synapse can never newly satisfy perm <= 0
            # (death ran last learn step; inference leaves perms alone). ---
            if cfg.predicted_segment_decrement > 0.0:
                pdec = dom.rate(cfg.predicted_segment_decrement)
                P = min(cfg.punish_cap, n_seg)
                pmask_seg = (matching_seg4 & ~active_cols[:, None, None]).reshape(-1)
                pids = _compact_ids(pmask_seg, P)  # [P], fills = n_seg
                valid_p = pids < n_seg
                pidc = jnp.clip(pids, 0, n_seg - 1)
                pres_p = presyn.reshape(n_seg, M)[pidc].astype(jnp.int32)  # [P, M]
                perm_p = syn_perm.reshape(n_seg, M)[pidc]
                pact_p = _presyn_active_packed(pres_p, pcol_ids, pcol_masks, K)
                sp_c = perm_p.astype(dom.compute_dtype)
                perm_pn = jnp.where(pact_p, jnp.maximum(sp_c - pdec, dom.zero), sp_c)
                dead_p = (pres_p >= 0) & (perm_pn <= dom.zero)
                pres_pn = jnp.where(dead_p, -1, pres_p)
                sl_p = seg_last.reshape(-1)[pidc]
                sl_pn = jnp.where((sl_p >= 0) & ((pres_pn >= 0).sum(-1) == 0), -1, sl_p)
                drop_ids = jnp.where(valid_p, pids, n_seg)  # fills -> dropped
                syn_perm = (
                    syn_perm.reshape(n_seg, M)
                    .at[drop_ids]
                    .set(perm_pn.astype(p_dt), mode="drop")
                    .reshape(*pool_shape)
                )
                presyn = (
                    presyn.reshape(n_seg, M)
                    .at[drop_ids]
                    .set(pres_pn.astype(presyn_dt), mode="drop")
                    .reshape(*pool_shape)
                )
                seg_last = (
                    seg_last.reshape(-1)
                    .at[drop_ids]
                    .set(sl_pn, mode="drop")
                    .reshape(*seg_shape)
                )
                overflow_learn = overflow_learn | (pmask_seg.sum() > P)
                if forward:
                    slots_p = pidc[:, None] * M + jnp.arange(M, dtype=jnp.int32)
                    old_p = pres_p
                    rem_p = valid_p[:, None] & dead_p
        else:
            # --- dense punish: matching segments in columns that did not
            # activate, over the full pool ---
            if cfg.predicted_segment_decrement > 0.0:
                pdec = dom.rate(cfg.predicted_segment_decrement)
                acols_seg = active_cols.reshape(C, *([1] * (len(seg_shape) - 1)))
                pmask = state["matching_seg"] & ~acols_seg  # [*seg_shape]
                pact = _presyn_active_packed(presyn, pcol_ids, pcol_masks, K)
                sp_c = syn_perm.astype(dom.compute_dtype)
                syn_perm = jnp.where(
                    seg_expand(pmask) & pact,
                    jnp.maximum(sp_c - pdec, dom.zero),
                    sp_c,
                ).astype(p_dt)

            # --- synapse death at permanence <= 0, then empty-segment death ---
            dead = (presyn >= 0) & (syn_perm <= dom.zero)
            presyn = jnp.where(dead, -1, presyn)
            nsyn = seg_sum(presyn >= 0)
            seg_last = jnp.where((seg_last >= 0) & (nsyn == 0), -1, seg_last)

        if forward:
            # --- forward-index maintenance: diff the touched rows against
            # their original pool content and apply removals, then appends
            # (ops/fwd_index.py). Touched rows = the L learned workspace rows
            # (evictions, alloc-clears, growth, reinforce-death) + the P
            # punished rows (death only). ---
            from rtap_tpu.ops.fwd_index import apply_appends, apply_removals

            a_i = idx // (K * S)
            gcol = jnp.where(valid_l, col_ids[jnp.clip(a_i, 0, Ac - 1)], C)
            vs_l = valid_l & (gcol < C)  # [L]
            seg_flat_l = jnp.where(vs_l, gcol * (K * S) + (idx % (K * S)), n_seg)
            slots_l = seg_flat_l[:, None] * M + jnp.arange(M, dtype=jnp.int32)  # [L, M]
            changed = presyn_l0 != presyn_l
            rem_l = vs_l[:, None] & changed & (presyn_l0 >= 0)
            add_l = vs_l[:, None] & changed & (presyn_l >= 0)
            if slots_p is not None:
                slots_all = jnp.concatenate([slots_l.reshape(-1), slots_p.reshape(-1)])
                old_all = jnp.concatenate([presyn_l0.reshape(-1), old_p.reshape(-1)])
                rem_all = jnp.concatenate([rem_l.reshape(-1), rem_p.reshape(-1)])
            else:
                slots_all = slots_l.reshape(-1)
                old_all = presyn_l0.reshape(-1)
                rem_all = rem_l.reshape(-1)
            fwd_slots, fwd_pos = apply_removals(
                fwd_slots, fwd_pos, slots_all, old_all, rem_all
            )
            fwd_slots, fwd_pos, ndrop = apply_appends(
                fwd_slots, fwd_pos, slots_l.reshape(-1),
                presyn_l.reshape(-1), add_l.reshape(-1),
            )
            fwd_of = fwd_of + ndrop

    # --- dendrite activity for t+1 over existing segments ---
    exists_seg = seg_last >= 0
    if pallas_learn:
        pass  # the megakernel already produced conn/pot counts + overflow
    elif forward:
        # forward index: gather only the <= Ac*K active cells' fanout rows
        # (ops/fwd_index.py) instead of sweeping the pools
        from rtap_tpu.ops.fwd_index import dendrite_counts

        a_cols = active_cells.any(-1).sum()
        tm_overflow = state["tm_overflow"] + (
            overflow_learn | (a_cols > Ac)
        ).astype(jnp.int32)
        act_ids = _winner_id_list(active_cells, Ac)  # [Ac*K], fills = N
        conn_c, pot_c = dendrite_counts(
            fwd_slots, syn_perm.reshape(-1), act_ids, p_connected,
            n_seg, M, fwd_impl(),
        )
        conn_count = conn_c.reshape(*seg_shape)
        pot_count = pot_c.reshape(*seg_shape)
    else:
        acol_ids, acol_masks, a_cols = _pack_active(active_cells, Ac)
        # the packed-column truncation applies under inference too — count it always
        tm_overflow = state["tm_overflow"] + (
            overflow_learn | (a_cols > Ac)
        ).astype(jnp.int32)
        syn_act = _presyn_active_packed(presyn, acol_ids, acol_masks, K)
        conn_count, pot_count = seg_sum2(
            syn_act & (syn_perm >= p_connected), syn_act
        )
    active_seg = exists_seg & (conn_count >= cfg.activation_threshold)
    matching_seg = exists_seg & (pot_count >= cfg.min_threshold)
    seg_pot = jnp.where(exists_seg, pot_count, 0).astype(jnp.int16)
    if learn:
        # LRU stamp for active segments (NuPIC stamps under learn only)
        seg_last = jnp.where(active_seg, it, seg_last)

    new_state = {
        **state,
        "presyn": presyn,
        "syn_perm": syn_perm,
        "seg_last": seg_last,
        "active_seg": active_seg,
        "matching_seg": matching_seg,
        "seg_pot": seg_pot,
        "prev_active": active_cells,
        "prev_winner": winner_cells,
        "tm_iter": it.astype(jnp.int32),  # oracle increments under inference too
        "tm_overflow": tm_overflow,
    }
    if forward:
        new_state["fwd_slots"] = fwd_slots
        new_state["fwd_pos"] = fwd_pos
        new_state["fwd_of"] = fwd_of
    return new_state, raw
