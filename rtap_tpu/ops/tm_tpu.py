"""Temporal Memory — device kernel (functional twin of oracle/temporal_memory.py).

The reference's TM is Cells4.cpp/TemporalMemory.cpp over the Connections
pointer graph (SURVEY.md C4/C5). TPU-native re-design (SURVEY.md §7 hard part
1): fixed-capacity dense pools [C, K, S, M] of (presyn id, permanence), and a
step composed of

  1. column categorization (predicted / burst-matching / burst-new) — dense,
  2. burst-new segment allocation (first-free slot else LRU-evict) — scatter,
  3. a *compact learning pass*: the <= learn_cap segments that learn this step
     are gathered to a [L, M] workspace, reinforced, grown toward previous
     winner cells (membership test + rank-select + weakest-synapse eviction,
     all static-shape), and scattered back,
  4. dense punishment of matching segments in non-active columns,
  5. dense synapse/segment death,
  6. dense dendrite activity (gather presyn -> segment popcounts) for t+1.

Tie-breaks are lowest-index everywhere, matching the oracle exactly; parity
is bit-for-bit (tests/parity/test_tm_parity.py).

Capacity bounds (learn_cap learning segments, winner_cap previous winners per
step) are static-shape requirements of XLA; overflow beyond the bounds is
counted in state["tm_overflow"] so tests can assert it never fires at the
configured sizes.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from rtap_tpu.config import TMConfig

INF = jnp.float32(jnp.inf)


def _segment_learning_mask(
    cfg: TMConfig,
    active_cols: jnp.ndarray,  # bool [C]
    active_seg: jnp.ndarray,  # bool [C, K, S] (prev step)
    matching_seg: jnp.ndarray,  # bool [C, K, S] (prev step)
    seg_pot: jnp.ndarray,  # i32 [C, K, S] (prev step)
    seg_last: jnp.ndarray,  # i32 [C, K, S]
    have_winners: jnp.ndarray,  # bool scalar (any prev winner cells)
):
    """Categorize columns and pick the per-column learning segments.

    Returns (predicted_cols, learn_mask, alloc [C,3] (col, cell, slot) for
    burst-new allocations with col==C when inactive, winner_cells_extra
    [C, K] winner contributions from burst columns).
    """
    C, K, S = active_seg.shape
    prev_predictive = active_seg.any(-1)  # [C, K]
    predicted_cols = prev_predictive.any(-1)  # [C]

    burst = active_cols & ~predicted_cols
    col_matching = matching_seg.any((-2, -1))  # [C]
    burst_match = burst & col_matching
    burst_new = burst & ~col_matching & have_winners

    # (a) predicted columns: every active segment of every predicted cell learns
    mask_pred = active_cols[:, None, None] & active_seg

    # (b) burst-matching: best matching segment (max seg_pot, lowest flat index)
    pot = jnp.where(matching_seg, seg_pot, -1).reshape(C, K * S)
    best_flat = jnp.argmax(pot, axis=-1)  # first max — same as np.argmax
    bm_k, bm_s = best_flat // S, best_flat % S
    bm_mask = (
        jnp.zeros((C, K, S), bool)
        .at[jnp.arange(C), bm_k, bm_s]
        .set(burst_match)
    )

    # (c) burst-new: cell with fewest segments; first free slot else LRU slot
    seg_counts = (seg_last >= 0).sum(-1)  # [C, K]
    bn_k = jnp.argmin(seg_counts, axis=-1)  # first min — matches oracle
    row_last = seg_last[jnp.arange(C), bn_k]  # [C, S]
    any_free = (row_last < 0).any(-1)
    first_free = jnp.argmax(row_last < 0, axis=-1)
    lru = jnp.argmin(row_last, axis=-1)
    bn_s = jnp.where(any_free, first_free, lru)

    # burst-column winner cells, one-hot (no scatter: a False write from one
    # branch must never clobber a True from the other)
    kk = jnp.arange(K, dtype=jnp.int32)[None, :]
    winner_extra = (burst_match[:, None] & (kk == bm_k[:, None])) | (
        (burst & ~col_matching)[:, None] & (kk == bn_k[:, None])  # winner even when no alloc
    )

    alloc_col = jnp.where(burst_new, jnp.arange(C), C)  # C == dropped
    return predicted_cols, mask_pred | bm_mask, (alloc_col, bn_k, bn_s), winner_extra, burst


def _grow_compact(
    cfg: TMConfig,
    presyn_l: jnp.ndarray,  # i32 [L, M] (post-reinforce)
    perm_l: jnp.ndarray,  # f32 [L, M]
    n_grow: jnp.ndarray,  # i32 [L]
    winner_ids: jnp.ndarray,  # i32 [W] ascending, padded with N
    n_cells: int,
):
    """Oracle _grow_synapses, vectorized: per segment, add the first
    min(n_grow, #eligible) winner cells (ascending id, not already
    presynaptic), evicting weakest synapses when free slots run short."""
    L, M = presyn_l.shape
    W = winner_ids.shape[0]
    G = cfg.new_synapse_count  # max grown per segment per step

    valid_w = winner_ids < n_cells
    # membership: winner already presynaptic on this segment?  [L, W]
    already = (presyn_l[:, None, :] == winner_ids[None, :, None]).any(-1)
    eligible = valid_w[None, :] & ~already
    rank = jnp.cumsum(eligible, axis=1)  # 1-based among eligible
    chosen = eligible & (rank <= n_grow[:, None])
    n_new = chosen.sum(-1).astype(jnp.int32)  # [L]

    # extract chosen winner positions ascending -> [L, G]
    wpos = jnp.where(chosen, jnp.arange(W, dtype=jnp.int32), W)
    wpos = jax.lax.sort(wpos, dimension=1)[:, :G]
    new_ids = jnp.where(wpos < W, winner_ids[jnp.clip(wpos, 0, W - 1)], n_cells)  # [L]

    # evict weakest occupied synapses if short of free slots (stable by slot)
    occupied = presyn_l >= 0
    n_free = M - occupied.sum(-1)
    short = n_new - n_free  # [L]
    key = jnp.where(occupied, perm_l, INF)
    ranks = jnp.argsort(jnp.argsort(key, axis=-1, stable=True), axis=-1, stable=True)
    evict = occupied & (ranks < short[:, None])
    presyn_l = jnp.where(evict, -1, presyn_l)
    perm_l = jnp.where(evict, 0.0, perm_l)

    # fill free slots ascending with new ids ascending
    free = presyn_l < 0
    frank = jnp.cumsum(free, axis=-1) - 1  # 0-based among free slots
    assign = free & (frank < n_new[:, None])
    fill = new_ids[jnp.arange(L)[:, None], jnp.clip(frank, 0, G - 1)]
    presyn_l = jnp.where(assign, fill, presyn_l)
    perm_l = jnp.where(assign, jnp.float32(cfg.initial_permanence), perm_l)
    return presyn_l, perm_l


@partial(jax.jit, static_argnames=("cfg", "learn"))
def tm_step(state: dict, active_cols: jnp.ndarray, cfg: TMConfig, learn: bool = True):
    """One TM step -> (new_state, raw anomaly score f32). Pure.

    `state` uses the models/state.py TM layout plus "tm_overflow" (i32
    overflow counter, device-only observability).
    """
    C, K, S, M = state["presyn"].shape
    N = C * K
    L, W = cfg.learn_cap, cfg.winner_cap

    presyn = state["presyn"]
    syn_perm = state["syn_perm"]
    seg_last = state["seg_last"]
    it = state["tm_iter"] + 1

    prev_predictive = state["active_seg"].any(-1)  # [C, K]
    prev_pred_cols = prev_predictive.any(-1)
    n_active = active_cols.sum()
    raw = jnp.where(
        n_active > 0,
        1.0 - (active_cols & prev_pred_cols).sum() / jnp.maximum(n_active, 1).astype(jnp.float32),
        0.0,
    )

    prev_active_flat = state["prev_active"].reshape(-1)  # bool [N]
    prev_winner_flat = state["prev_winner"].reshape(-1)
    n_winners = prev_winner_flat.sum()
    have_winners = n_winners > 0

    predicted_cols, learn_mask, alloc, winner_extra, burst = _segment_learning_mask(
        cfg, active_cols, state["active_seg"], state["matching_seg"], state["seg_pot"],
        seg_last, have_winners,
    )

    # cell activation / winner selection (pure function of prev state)
    active_cells = (
        jnp.where((active_cols & predicted_cols)[:, None], prev_predictive, False)
        | burst[:, None]
    )
    winner_cells = (
        jnp.where((active_cols & predicted_cols)[:, None], prev_predictive, False)
        | winner_extra
    )

    if learn:
        alloc_col, bn_k, bn_s = alloc

        # --- burst-new allocation: clear slot (evict if LRU) + stamp ---
        presyn = presyn.at[alloc_col, bn_k, bn_s].set(-1, mode="drop")
        syn_perm = syn_perm.at[alloc_col, bn_k, bn_s].set(0.0, mode="drop")
        seg_pot0 = state["seg_pot"].at[alloc_col, bn_k, bn_s].set(0, mode="drop")
        seg_last = seg_last.at[alloc_col, bn_k, bn_s].set(it, mode="drop")
        alloc_mask = (
            jnp.zeros((C, K, S), bool).at[alloc_col, bn_k, bn_s].set(True, mode="drop")
        )
        lm = learn_mask | alloc_mask
        overflow = (lm.sum() > L) | (n_winners > W)

        # --- compact gather of learning segments ---
        idx = jnp.nonzero(lm.reshape(-1), size=L, fill_value=C * K * S)[0]
        valid_l = idx < C * K * S
        safe = jnp.clip(idx, 0, C * K * S - 1)
        presyn_l = presyn.reshape(-1, M)[safe]
        perm_l = syn_perm.reshape(-1, M)[safe]
        pot_l = seg_pot0.reshape(-1)[safe]

        # reinforce: +inc on synapses to prev-active cells, -dec on the rest
        exists = presyn_l >= 0
        act = exists & prev_active_flat[jnp.clip(presyn_l, 0, N - 1)]
        perm_l = jnp.clip(
            perm_l
            + cfg.permanence_increment * act
            - cfg.permanence_decrement * (exists & ~act),
            0.0,
            1.0,
        )

        # grow toward previous winner cells (ascending id)
        winner_ids = jnp.nonzero(prev_winner_flat, size=W, fill_value=N)[0].astype(jnp.int32)
        n_grow = (cfg.new_synapse_count - pot_l).astype(jnp.int32)
        grown_presyn, grown_perm = _grow_compact(cfg, presyn_l, perm_l, n_grow, winner_ids, N)
        grow_ok = have_winners & valid_l
        presyn_l = jnp.where(grow_ok[:, None], grown_presyn, presyn_l)
        perm_l = jnp.where(grow_ok[:, None], grown_perm, perm_l)

        # scatter back (invalid rows dropped via OOB index)
        presyn = presyn.reshape(-1, M).at[idx].set(presyn_l, mode="drop").reshape(C, K, S, M)
        syn_perm = syn_perm.reshape(-1, M).at[idx].set(perm_l, mode="drop").reshape(C, K, S, M)
        seg_last = seg_last.reshape(-1).at[idx].set(it, mode="drop").reshape(C, K, S)

        # --- punish matching segments in columns that did not activate ---
        if cfg.predicted_segment_decrement > 0.0:
            pmask = state["matching_seg"] & ~active_cols[:, None, None]
            pact = (presyn >= 0) & prev_active_flat[jnp.clip(presyn, 0, N - 1)]
            syn_perm = jnp.where(
                pmask[..., None] & pact,
                jnp.maximum(syn_perm - cfg.predicted_segment_decrement, 0.0),
                syn_perm,
            )

        # --- synapse death at permanence <= 0, then empty-segment death ---
        dead = (presyn >= 0) & (syn_perm <= 0.0)
        presyn = jnp.where(dead, -1, presyn)
        nsyn = (presyn >= 0).sum(-1)
        seg_last = jnp.where((seg_last >= 0) & (nsyn == 0), -1, seg_last)

        tm_overflow = state["tm_overflow"] + overflow.astype(jnp.int32)
    else:
        tm_overflow = state["tm_overflow"]

    # --- dendrite activity for t+1 over existing segments ---
    exists_seg = seg_last >= 0
    syn_act = (presyn >= 0) & active_cells.reshape(-1)[jnp.clip(presyn, 0, N - 1)]
    conn_count = (syn_act & (syn_perm >= cfg.connected_permanence)).sum(-1)
    pot_count = syn_act.sum(-1)
    active_seg = exists_seg & (conn_count >= cfg.activation_threshold)
    matching_seg = exists_seg & (pot_count >= cfg.min_threshold)
    seg_pot = jnp.where(exists_seg, pot_count, 0).astype(jnp.int32)
    if learn:
        # LRU stamp for active segments (NuPIC stamps under learn only)
        seg_last = jnp.where(active_seg, it, seg_last)

    new_state = {
        **state,
        "presyn": presyn,
        "syn_perm": syn_perm,
        "seg_last": seg_last,
        "active_seg": active_seg,
        "matching_seg": matching_seg,
        "seg_pot": seg_pot,
        "prev_active": active_cells,
        "prev_winner": winner_cells,
        "tm_iter": it.astype(jnp.int32),  # oracle increments under inference too
        "tm_overflow": tm_overflow,
    }
    return new_state, raw
