"""Device twin of utils/hashing.py — MurmurHash3 fmix32 over uint32 lanes.

Bit-identical to the numpy version (tests/parity/test_encoder_parity.py):
uint32 multiply/xor/shift wrap the same way in XLA as in numpy, and JAX x64
stays disabled so everything is 32-bit on TPU (VPU-friendly integer ops).
"""

from __future__ import annotations

import jax.numpy as jnp

_C1 = jnp.uint32(0x85EBCA6B)
_C2 = jnp.uint32(0xC2B2AE35)
_GOLDEN = jnp.uint32(0x9E3779B9)


def fmix32(x: jnp.ndarray) -> jnp.ndarray:
    """MurmurHash3 fmix32 finalizer over uint32 arrays."""
    h = x.astype(jnp.uint32)
    h = h ^ (h >> 16)
    h = h * _C1
    h = h ^ (h >> 13)
    h = h * _C2
    h = h ^ (h >> 16)
    return h


def hash_u32(key: jnp.ndarray, seed: jnp.ndarray | int) -> jnp.ndarray:
    """hash(seed, key) -> uint32; key any integer array (cast mod 2^32)."""
    k = key.astype(jnp.uint32)
    return fmix32(k * _GOLDEN + jnp.asarray(seed, jnp.uint32))


def hash_bits(keys: jnp.ndarray, seed: jnp.ndarray | int, n: int) -> jnp.ndarray:
    """Map integer keys to bit indices in [0, n). RDSE device path."""
    return (hash_u32(keys, seed) % jnp.uint32(n)).astype(jnp.int32)
