"""Device (TPU) kernels — the XLA-compiled analog of the reference's C++ core.

The reference's hot loop lives in nupic.core C++ (SpatialPooler.cpp,
Cells4.cpp/TemporalMemory.cpp, Connections.cpp — SURVEY.md §1 L0). Here the
same semantics are pure JAX functions over fixed-shape pytrees, jitted and
vmapped over stream groups (SURVEY.md §7 design stance). Every kernel has a
numpy oracle twin in models/oracle/ and bit-exact parity tests in
tests/parity/.
"""
