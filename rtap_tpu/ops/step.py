"""Fused per-record device step: encode -> SP -> TM -> raw anomaly score.

This is the TPU-native analog of the reference's per-record hot path
(SURVEY.md §3.2: `model.run` -> encoders -> SpatialPooler.cpp ->
Cells4/TemporalMemory.cpp -> raw score), collapsed into ONE jitted XLA
program so a record costs a single device dispatch. The host boundary is
exactly the one BASELINE.json prescribes: values/timestamps in, raw scores
out; anomaly likelihood stays on host (models/oracle/likelihood.py,
service/likelihood_batch.py).

Three entry points:

- :func:`fused_step` — single stream, used by `HTMModel(backend="tpu")`.
- :func:`group_step` — vmapped over a leading stream-group axis G: one
  dispatch scores G streams in lockstep (SURVEY.md §2.3 "DP over streams").
- :class:`TpuStepRunner` — stateful convenience wrapper holding device state.

All three are bit-identical to the CPU oracle per step
(tests/parity/test_e2e_parity.py).
"""

from __future__ import annotations

import functools as _functools
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from rtap_tpu.config import ModelConfig
from rtap_tpu.ops.encoders_tpu import bind_offsets, encode_device
from rtap_tpu.ops.sp_tpu import sp_step
from rtap_tpu.ops.tm_tpu import tm_step


def _step_impl(state: dict, values: jnp.ndarray, ts_unix: jnp.ndarray, cfg: ModelConfig, learn: bool,
              inv: dict | None = None):
    """One fused record step -> (new_state, out). Pure/traceable.

    `values` is [n_fields] f32 (NaN = missing sample), `ts_unix` scalar i32.
    `out` is the raw anomaly score (f32 scalar), or the tuple
    (raw, predicted_value, prediction_prob) when the SDR classifier is
    enabled (cfg.classifier.enabled — a static property, so call sites can
    unpack unconditionally for a given config). `inv` carries tm_step's
    tick-invariant operands (ops/tm_tpu.tm_invariants) when the caller
    hoists them out of a scan; None rebuilds them in-trace.
    """
    enc_offset, enc_bound = bind_offsets(values, state["enc_offset"], state["enc_bound"])
    state = {**state, "enc_offset": enc_offset, "enc_bound": enc_bound}
    enc_prev = state.get("enc_prev")  # composite delta fields only
    sdr = encode_device(cfg, values, ts_unix, enc_offset,
                        state["enc_resolution"], enc_prev)
    if enc_prev is not None:
        # the delta predecessor advances to the last FINITE value AFTER
        # encoding (this tick encoded against the pre-tick predecessor);
        # NaN gaps keep the pre-gap baseline, mirroring offset binding
        state["enc_prev"] = jnp.where(jnp.isfinite(values), values, enc_prev)
    pattern_prev = state["prev_active"]  # TM active cells at t-1
    state, active = sp_step(state, sdr, cfg.sp, learn)
    state, raw = tm_step(state, active, cfg.tm, learn, inv=inv)
    if cfg.classifier.enabled:
        from rtap_tpu.ops.classifier_tpu import classifier_step

        state, pred, conf = classifier_step(
            state, pattern_prev, state["prev_active"], values[0], cfg, learn
        )
        return state, (raw, pred, conf)
    return state, raw


# rtap: twin[oracle_record_step] — the oracle chains bind/encode/SP/TM
# per record (models/htm_model.py); parity: tests/parity/test_e2e_parity.py
@partial(jax.jit, static_argnames=("cfg", "learn"))
def fused_step(state: dict, values: jnp.ndarray, ts_unix: jnp.ndarray, cfg: ModelConfig, learn: bool = True):
    """Single-stream fused step (see :func:`_step_impl`)."""
    from rtap_tpu.ops.tm_tpu import from_kernel_layout, to_kernel_layout

    state, out = _step_impl(to_kernel_layout(state), values, ts_unix, cfg, learn)
    return from_kernel_layout(state, cfg.tm), out


def _tick(s: dict, values: jnp.ndarray, ts_unix: jnp.ndarray, cfg: ModelConfig, learn: bool,
          inv: dict | None = None, health: bool = False, predict: bool = False):
    """One group tick on KERNEL-layout state, honoring cfg.learn_every.

    With a learning cadence (cfg.learn_every > 1 and learn=True) the
    learn/infer choice is a `lax.cond` on a SCALAR schedule flag derived
    from the group's lockstep tick counter (`tm_iter`, which advances under
    inference too) — the cond must sit OUTSIDE the vmap: a per-stream
    predicate would lower to select and execute BOTH branches, paying the
    learning pass it exists to skip. Groups tick in lockstep (registry
    invariant), so one flag serves all G streams.

    `inv` (tm_invariants) is closed over, NOT vmapped: one shared
    HBM-resident copy serves all G streams.

    `health=True` (static) additionally reduces the POST-STEP state to
    one small per-group health leaf (ops/health_tpu.py) and returns
    (state, (out, health_leaf)). Pure reads on the tensors the step just
    produced — the model state and scores are bit-identical either way
    (tests/integration/test_health_serve.py pins it), and the leaf adds
    ~200 bytes to the chunk output instead of a device->host state fetch.

    `predict=True` (static, ISSUE 16) additionally folds the predictive-
    horizon reducer (ops/predict_tpu.py) — it updates ONLY the
    predictor-owned ring/EWMA leaves and wraps the per-stream leaf
    OUTERMOST: (state, (inner, predict_leaf)) where `inner` is whatever
    the health flag produced, so existing unpack sites are untouched.
    Requires the predictor leaves in the state tree (the registry builds
    them via init_state(predict_horizon=...)).
    """

    def step_all(lrn):
        return lambda ss: jax.vmap(
            lambda s1, vv, tt: _step_impl(s1, vv, tt, cfg, lrn, inv)
        )(ss, values, ts_unix)

    if not (learn and cfg.cadence_active):
        s, out = step_all(learn)(s)
    else:
        tick = s["tm_iter"].reshape(-1)[0]  # completed steps so far (lockstep)
        s, out = jax.lax.cond(
            cfg.learns_on(tick), step_all(True), step_all(False), s)
    if predict:
        from rtap_tpu.ops.predict_tpu import predict_update

        s, pleaf = predict_update(s, values, cfg)
    if health:
        from rtap_tpu.ops.health_tpu import health_reduce

        raw = out[0] if cfg.classifier.enabled else out
        out = (out, health_reduce(s, raw, values, cfg))
    if predict:
        out = (out, pleaf)
    return s, out


# rtap: twin[oracle_record_step] — vmapped form of the same oracle chain
@partial(jax.jit, static_argnames=("cfg", "learn", "health", "predict"), donate_argnums=(0,))
def group_step(state: dict, values: jnp.ndarray, ts_unix: jnp.ndarray, cfg: ModelConfig, learn: bool = True,
               health: bool = False, predict: bool = False):
    """Stream-group fused step: every state leaf carries a leading G axis;
    `values` is [G, n_fields] f32, `ts_unix` [G] i32 -> (state, raw [G] f32).

    State buffers are donated: at 100k streams the TM pools dominate HBM and
    the update must happen in place (SURVEY.md §7 hard part 4).
    With `health=True` the out leaf becomes (out, health_leaf); with
    `predict=True` the predictive-horizon leaf wraps outermost — see
    :func:`_tick` / ops/health_tpu.py / ops/predict_tpu.py.
    """
    from rtap_tpu.ops.tm_tpu import from_kernel_layout, to_kernel_layout

    state, out = _tick(to_kernel_layout(state), values, ts_unix, cfg, learn,
                       health=health, predict=predict)
    return from_kernel_layout(state, cfg.tm), out


def _scan_chunk(state: dict, values: jnp.ndarray, ts_unix: jnp.ndarray, cfg: ModelConfig, learn: bool,
                health: bool = False, predict: bool = False):
    """Shared hot-loop body: scan the vmapped fused step over the time axis.
    Used identically by the single-device and shard_map entry points, so the
    two can never diverge semantically.

    The kernel-layout adapters sit OUTSIDE the scan: under RTAP_TM_LAYOUT=
    flat the carry holds flat pools for all T ticks and the public [C,K,S,M]
    layout is restored once per chunk (shape-only reshapes — checkpoints,
    oracle parity, and the service API never see kernel layout). Likewise
    the tick-invariant kernel operands (the flat layout's per-segment
    reduction matrix) are built ONCE here and closed over by the body, so
    they are hoisted out of the scan by construction and stay HBM-resident
    across the whole T-tick chunk."""
    from rtap_tpu.ops.tm_tpu import from_kernel_layout, tm_invariants, to_kernel_layout

    inv = tm_invariants(cfg.tm)

    def body(s, inp):
        v, t = inp
        return _tick(s, v, t, cfg, learn, inv, health=health,
                     predict=predict)

    state, out = jax.lax.scan(body, to_kernel_layout(state), (values, ts_unix))
    return from_kernel_layout(state, cfg.tm), out


# rtap: twin[oracle_record_step] — time-scanned form of the oracle chain
@partial(jax.jit, static_argnames=("cfg", "learn", "health", "predict"), donate_argnums=(0,))
def chunk_step(state: dict, values: jnp.ndarray, ts_unix: jnp.ndarray, cfg: ModelConfig, learn: bool = True,
               health: bool = False, predict: bool = False):
    """Multi-tick stream-group step: scan :func:`group_step`'s body over a
    leading time axis so T ticks cost ONE device dispatch.

    `values` is [T, G, n_fields] f32, `ts_unix` [T, G] i32 ->
    (state, raw [T, G] f32). This is the replay/bench fast path (SURVEY.md §7
    hard part 3: amortize per-tick dispatch latency by batching ticks when
    replaying faster than real time); the live 1s-cadence service uses
    :func:`group_step` per tick instead. With `health=True` (static) the
    out leaf becomes (out, health_leaf) and every health-leaf array gains
    the leading T axis — one ~200 B record per tick, scanned alongside the
    scores (ops/health_tpu.py). With `predict=True` the predictive-horizon
    leaf rides the same way, wrapped outermost ([T, G] per-stream vectors
    beside the scores — ops/predict_tpu.py).
    """
    return _scan_chunk(state, values, ts_unix, cfg, learn, health=health,
                       predict=predict)


@_functools.lru_cache(maxsize=None)
def _sharded_chunk_fn(cfg: ModelConfig, mesh, learn: bool, state_ranks: tuple):
    """Build (and cache) the jitted shard_map program for one (config, mesh)."""
    from jax.sharding import PartitionSpec as P

    state_specs = {k: P("streams", *([None] * (r - 1))) for k, r in state_ranks}

    @partial(jax.jit, donate_argnums=(0,))
    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(state_specs, P(None, "streams", None), P(None, "streams")),
        out_specs=(state_specs, P(None, "streams")),
    )
    def run(state, values, ts_unix):
        return _scan_chunk(state, values, ts_unix, cfg, learn)

    return run


def sharded_chunk_step(state: dict, values: jnp.ndarray, ts_unix: jnp.ndarray,
                       cfg: ModelConfig, mesh, learn: bool = True):
    """:func:`chunk_step` under explicit SPMD (`jax.shard_map`) over the
    1-D ("streams",) mesh.

    Streams are independent, so each device steps its own shard with zero
    collectives — guaranteed by construction here, whereas plain jit +
    sharded inputs lets the partitioner all-gather around ops it won't
    partition (observed: the [G, C] TopK in SP inhibition gets its batch
    gathered to every chip). tests/scale/test_sharded.py pins the compiled
    program collective-free.
    """
    state_ranks = tuple(sorted((k, max(np.ndim(v), 1)) for k, v in state.items()))
    return _sharded_chunk_fn(cfg, mesh, learn, state_ranks)(state, values, ts_unix)


def replicate_state(state: dict, group_size: int) -> dict:
    """Tile a single-stream state dict into a [G, ...] group state (host side).

    Every stream starts from the same deterministic init (models/state.py);
    per-stream divergence comes entirely from the data, mirroring the
    reference's one-independent-model-per-stream registry (SURVEY.md C19).
    """
    return {
        k: np.broadcast_to(np.asarray(v)[None, ...], (group_size, *np.shape(v))).copy()
        for k, v in state.items()
    }


@partial(jax.jit, static_argnames=("group_size",))
def _broadcast_state(state: dict, group_size: int) -> dict:
    return {
        k: jnp.broadcast_to(v[None, ...], (group_size, *v.shape)) for k, v in state.items()
    }


def replicate_state_device(state: dict, group_size: int) -> dict:
    """Device-side :func:`replicate_state`: transfer ONE stream's state
    (~0.5 MB) and broadcast to [G, ...] on the chip.

    The host-side tiling + device_put costs minutes at the HBM frontier
    (measured 208 s at G=24576 on the tunneled v5e — 13.9 GB staged on host
    and pushed through the wire for what is a broadcast of identical rows);
    this makes group construction O(one stream) on the wire regardless of G.
    """
    single = {k: jnp.asarray(v) for k, v in state.items()}
    return _broadcast_state(single, group_size)


@partial(jax.jit, donate_argnums=(0,))
def _set_row_jit(state: dict, fresh: dict, slot: jnp.ndarray) -> dict:
    return jax.tree_util.tree_map(
        lambda s, f: s.at[slot].set(f.astype(s.dtype)), state, fresh)


def set_state_row(state: dict, fresh: dict, slot: int) -> dict:  # rtap: allow[twin-parity] — host twin is a one-line numpy row assignment; claim/release semantics pinned by tests/unit/test_dynamic_streams.py and the registry tests
    """Overwrite ONE stream's row of grouped [G, ...] state with a fresh
    single-stream state (dynamic slot claim — registry.claim_slot). The
    slot index is a traced argument so claiming different slots reuses one
    compiled program; the group buffer is donated (no [G, ...] copy)."""
    return _set_row_jit(state, {k: jnp.asarray(v) for k, v in fresh.items()},
                        jnp.asarray(slot, jnp.int32))


class TpuStepRunner:
    """Holds one stream's device state and steps it record by record.

    Used by `HTMModel(backend="tpu")` — the single-stream convenience path.
    High-throughput multi-stream execution goes through service/registry.py
    stream groups and :func:`group_step` instead.
    """

    def __init__(self, cfg: ModelConfig, state: dict):
        self.cfg = cfg
        self.state = jax.device_put(state)

    def step(self, values: np.ndarray, ts_unix: int, learn: bool = True):
        """-> raw score (float), or (raw, prediction, prob) floats when the
        SDR classifier is enabled (static per config)."""
        v = jnp.asarray(np.atleast_1d(values), jnp.float32)
        self.state, out = fused_step(self.state, v, jnp.int32(ts_unix), self.cfg, learn)
        if self.cfg.classifier.enabled:
            return float(out[0]), float(out[1]), float(out[2])
        return float(out)
