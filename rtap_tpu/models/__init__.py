from rtap_tpu.models.htm_model import AnomalyDetector, HTMModel, ModelResult, create_model  # noqa: F401
