"""HTMModel / AnomalyDetector factory — the plugin boundary.

This is the analog of the reference's `ModelFactory.create(modelParams)` ->
`HTMPredictionModel.run(record)` -> `inferences["anomalyScore"]` surface
(SURVEY.md C9, §3.1-3.2), which BASELINE.json designates as the plugin seam:
the CPU path is the default backend and TPU is opt-in. `backend="cpu"` runs
the numpy oracle in this process; `backend="tpu"` routes the SDR hot loop
through the jitted device step (ops/), keeping likelihood on host.

Single-stream convenience API; high-throughput multi-stream execution goes
through service/registry.py stream groups instead.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from rtap_tpu.config import ModelConfig, nab_preset
from rtap_tpu.models.oracle.encoders import encode_record
from rtap_tpu.models.oracle.likelihood import AnomalyLikelihood
from rtap_tpu.models.oracle.spatial_pooler import sp_compute
from rtap_tpu.models.oracle.temporal_memory import TMOracle
from rtap_tpu.models.state import init_state

BACKENDS = ("cpu", "tpu")


def oracle_record_step(
    cfg: ModelConfig,
    state: dict,
    tm: TMOracle,
    values: np.ndarray,
    ts_unix: int,
    learn: bool = True,
    classifier=None,
) -> float | tuple[float, float, float]:
    """One oracle record through bind -> encode -> SP -> TM -> raw score.

    The single source of the CPU per-record composition, shared by
    HTMModel.run and the service layer's CPU stream groups; the device twin
    is ops/step._step_impl. With a `classifier` (SDRClassifierOracle), also
    decodes the predicted next value: returns (raw, prediction, prob).
    """
    bind = ~state["enc_bound"] & np.isfinite(values)
    if bind.any():
        # bind each field's offset at its first finite value (a leading NaN
        # must not poison the stream's bucket arithmetic forever)
        state["enc_offset"] = np.where(bind, values, state["enc_offset"]).astype(np.float32)
        state["enc_bound"] = state["enc_bound"] | bind
    enc_prev = state.get("enc_prev")  # composite delta fields only
    sdr = encode_record(cfg, values, int(ts_unix), state["enc_offset"],
                        state["enc_resolution"], enc_prev)
    if enc_prev is not None:
        # advance the delta predecessor AFTER encoding (device twin:
        # ops/step._step_impl); NaN gaps keep the pre-gap baseline
        state["enc_prev"] = np.where(
            np.isfinite(values), values, enc_prev).astype(np.float32)
    # TM active cells at t-1: TMOracle rebinds (not mutates) prev_active, so
    # the snapshot needs no copy; only taken when a classifier will read it
    pattern_prev = state["prev_active"].reshape(-1) if classifier is not None else None
    active = sp_compute(state, sdr, cfg.sp, learn)
    raw = tm.compute(active, learn)
    if classifier is None:
        return raw
    from rtap_tpu.models.oracle.classifier import classifier_bucket

    bucket = classifier_bucket(
        float(values[0]), float(state["enc_offset"][0]),
        float(state["enc_resolution"][0]), cfg.classifier.buckets,
    )
    pred, prob = classifier.compute(
        pattern_prev, state["prev_active"].reshape(-1), bucket, float(values[0]), learn
    )
    return raw, pred, prob


@dataclass
class ModelResult:
    """Per-record inference output (the reference's ModelResult.inferences)."""

    raw_score: float  # 1 - |active ∩ predicted| / |active|
    likelihood: float  # rolling-Gaussian tail probability complement
    log_likelihood: float  # NuPIC log-scaled likelihood (the detection score)
    prediction: float | None = None  # predicted next value (SDR classifier)
    prediction_prob: float | None = None  # probability of the argmax bucket


class HTMModel:
    """One HTM anomaly model over one (possibly multivariate) metric stream."""

    def __init__(self, cfg: ModelConfig, seed: int = 0, backend: str = "cpu",
                 _state: dict | None = None):
        if backend not in BACKENDS:
            raise ValueError(f"backend must be one of {BACKENDS}, got {backend!r}")
        self.cfg = cfg
        self.backend = backend
        self.seed = seed
        # _state: prebuilt state injection (HTMModel.load) — skips the RNG
        # init whose arrays would be immediately overwritten
        self.state = init_state(cfg, seed) if _state is None else _state
        self.likelihood = AnomalyLikelihood(cfg.likelihood)
        self._classifier = None
        if backend == "cpu":
            self._tm = TMOracle(self.state, cfg.tm)
            if cfg.classifier.enabled:
                from rtap_tpu.models.oracle.classifier import SDRClassifierOracle

                self._classifier = SDRClassifierOracle(self.state, cfg.classifier)
        else:
            from rtap_tpu.ops.step import TpuStepRunner  # deferred: jax import

            self._runner = TpuStepRunner(cfg, self.state)

    def run(self, timestamp: int, value: float | np.ndarray, learn: bool = True) -> ModelResult:
        """Process one record; returns scores. Mirrors model.run({...})."""
        values = np.atleast_1d(np.asarray(value, np.float32))

        if learn and self.cfg.cadence_active:
            # host-side twin of ops/step.py:_tick's schedule (same clock:
            # tm_iter = completed steps, checkpointed, advances under
            # inference; same predicate: cfg.learns_on) so single-stream
            # runs match grouped device runs record-for-record
            it = int(self.state["tm_iter"]) if self.backend == "cpu" else int(
                self._runner.state["tm_iter"]
            )
            learn = bool(self.cfg.learns_on(it))

        pred = prob = None
        if self.backend == "cpu":
            out = oracle_record_step(
                self.cfg, self.state, self._tm, values, int(timestamp), learn,
                classifier=self._classifier,
            )
            raw = out if self._classifier is None else out[0]
            if self._classifier is not None:
                pred, prob = out[1], out[2]
        else:
            # the tpu path performs the offset bind on device
            # (ops/encoders_tpu.bind_offsets) against its own state copy
            out = self._runner.step(values, int(timestamp), learn)
            raw = out if not self.cfg.classifier.enabled else out[0]
            if self.cfg.classifier.enabled:
                pred, prob = out[1], out[2]

        lik, loglik = self.likelihood.update(float(raw))
        return ModelResult(float(raw), lik, loglik, pred, prob)

    # ---- single-model persistence (SURVEY.md C16: the reference's
    # model.save() / ModelFactory.loadFromCheckpoint surface; group-scale
    # checkpoints use service/checkpoint.py's orbax path instead) ----

    def save(self, path: str) -> None:
        """Serialize the FULL model (SDR state, likelihood state machine,
        config, seed) to one .npz; `HTMModel.load` resumes bit-exactly.
        The write is atomic (temp sibling + rename, like the group
        checkpoint path): a crash mid-save can never corrupt an existing
        checkpoint at `path`."""
        import os

        if self.backend == "cpu":
            state = self.state
        else:
            import jax

            state = jax.device_get(self._runner.state)
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            np.savez_compressed(
                tmp,
                config_json=np.frombuffer(self.cfg.to_json().encode(), np.uint8),
                seed=np.asarray(self.seed, np.int64),
                **{f"lik_{k}": v for k, v in self.likelihood.state_dict().items()},
                # fwd_* is derived state (ops/fwd_index.py): load() rebuilds
                # it from presyn, so checkpoints are dendrite-mode-agnostic
                **{
                    f"s_{k}": np.asarray(v)
                    for k, v in state.items()
                    if not k.startswith("fwd_")
                },
            )
            # savez appends .npz when missing — mirror that for the temp name
            if not tmp.endswith(".npz") and os.path.exists(tmp + ".npz"):
                tmp += ".npz"
            os.replace(tmp, path)
        finally:
            # a failed savez may have left either spelling behind (numpy
            # appends .npz to suffix-less names before writing)
            for residue in (tmp, tmp if tmp.endswith(".npz") else tmp + ".npz"):
                if os.path.exists(residue) and os.path.abspath(residue) != os.path.abspath(path):
                    os.unlink(residue)

    @classmethod
    def load(cls, path: str, backend: str = "cpu") -> "HTMModel":
        """Rebuild a model from :meth:`save`. `backend` may differ from the
        saving side (cpu<->tpu resume; the state layout is shared)."""
        with np.load(path) as z:
            cfg = ModelConfig.from_json(bytes(z["config_json"]).decode())
            loaded = {
                k[2:]: z[k]
                for k in z.files
                if k.startswith("s_") and not k[2:].startswith("fwd_")
            }
            lik_state = {k[4:]: z[k] for k in z.files if k.startswith("lik_")}
            seed = int(z["seed"])
        from rtap_tpu.ops.tm_tpu import dendrite_mode

        if dendrite_mode() == "forward":
            # rebuild the derived forward index from the restored pools
            from rtap_tpu.ops.fwd_index import build_fwd_index

            slots, pos, of = build_fwd_index(
                np.asarray(loaded["presyn"]), cfg.num_cells, cfg.tm.fanout_cap
            )
            loaded["fwd_slots"] = np.asarray(slots)
            loaded["fwd_pos"] = np.asarray(pos)
            loaded["fwd_of"] = np.asarray(of)
        model = cls(cfg, seed=seed, backend=backend, _state=loaded)
        model.likelihood.load_state_dict(lik_state)
        return model


def create_model(
    cfg: ModelConfig | None = None,
    backend: str = "cpu",
    seed: int = 0,
    min_val: float = 0.0,
    max_val: float = 100.0,
) -> HTMModel:
    """ModelFactory.create analog. With no explicit config, builds the NAB
    preset sized to the stream's expected [min_val, max_val] range (NAB hands
    detectors the per-file input range the same way)."""
    return HTMModel(cfg or nab_preset(min_val, max_val), seed=seed, backend=backend)


class AnomalyDetector:
    """NAB-detector-shaped wrapper: feed records, get detection scores + alerts.

    The reference's service layer thresholds log-likelihood to raise early
    warnings (SURVEY.md C20, §3.3); `threshold` defaults to the NuPIC-common
    0.5 on the log scale.
    """

    def __init__(
        self,
        cfg: ModelConfig | None = None,
        backend: str = "cpu",
        seed: int = 0,
        min_val: float = 0.0,
        max_val: float = 100.0,
        threshold: float = 0.5,
    ):
        self.model = create_model(cfg, backend, seed, min_val, max_val)
        self.threshold = threshold

    def handle_record(self, timestamp: int, value: float | np.ndarray) -> tuple[float, bool]:
        """-> (detection score in [0,1] (log-likelihood), alert?)."""
        res = self.model.run(timestamp, value)
        return res.log_likelihood, res.log_likelihood >= self.threshold
