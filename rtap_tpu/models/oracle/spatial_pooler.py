"""Spatial Pooler — numpy oracle.

Semantics per SURVEY.md C3 / §3.2 (NuPIC `spatial_pooler.py` +
`SpatialPooler.cpp`): overlap = connected-synapse count on active inputs,
boosting, global k-winner inhibition, Hebbian permanence learning, duty
cycles with weak-column permanence bump.

Deviations from NuPIC, deliberate and shared with the TPU kernel so both
backends agree bit-for-bit:
- top-k tie-break is deterministic by lower column index (NuPIC breaks ties
  by internal ordering of its sort) — encoded as score = overlap*C + (C-1-c);
- the weak-column bump (raisePermanenceToThreshold) applies every step via
  duty-cycle comparison rather than NuPIC's every-50-step update period.
"""

from __future__ import annotations

import numpy as np

from rtap_tpu.config import SPConfig
from rtap_tpu.models.perm import sp_domain


def sp_overlap(state: dict, input_sdr: np.ndarray, cfg: SPConfig) -> np.ndarray:
    """Overlap per column: number of connected potential synapses whose
    presynaptic input bit is active.

    Dense layout: indexes the ~w active bits instead of building the full
    [C, n_in] connected mask (O(C*w) vs O(C*n_in)). Sparse layout
    (SPConfig.sparse_pool, ISSUE 18): gathers the SDR at the member-index
    table [C, P] and counts connected hits (O(C*P)); empty slots
    (members == -1) are masked out, clamp-gathered in-bounds exactly like
    the device kernel. Exact integer counts either way."""
    connected = sp_domain(cfg).threshold(cfg.syn_perm_connected)
    if cfg.sparse_pool:
        members = state["members"]
        hit = input_sdr[np.maximum(members, 0)]
        cols = (state["perm"] >= connected) & (members >= 0) & hit
        return cols.sum(1, dtype=np.int64)
    idx = np.nonzero(input_sdr)[0]
    if len(idx) == 0:
        return np.zeros(state["perm"].shape[0], np.int64)
    cols = (state["perm"][:, idx] >= connected) & state["potential"][:, idx]
    return cols.sum(1, dtype=np.int64)


def sp_inhibit(overlap: np.ndarray, boost: np.ndarray, cfg: SPConfig) -> np.ndarray:
    """Global k-winner inhibition -> bool[C] active columns.

    Winners are the top `num_active_columns` by boosted overlap with
    deterministic low-index tie-break; columns below stimulus_threshold
    (on raw overlap) never win.
    """
    C = overlap.shape[0]
    if cfg.boost_strength > 0.0:
        # Quantize boosted overlap to 1/256 so the low-index tie-break term
        # can never override a real (>= 1/256) difference. Note this makes
        # host/device winner parity overwhelmingly likely but not guaranteed:
        # a 1-ulp exp() difference can still flip q on an exact .5 boundary.
        # The NAB preset runs boost_strength=0, where parity is exact.
        # same f32 clamp as the device kernel, BEFORE the int cast: i64
        # cannot wrap here, but the DEVICE computes this score in i32
        # and clamps q (in f32 — an overflowing f32→i32 convert is
        # backend-defined) to keep q*C + tiebreak < 2^31; the min(·,
        # 2^24) keeps qmax f32-exact for C < 128 (see ops/sp_tpu.py).
        # The oracle mirrors the exact expression so the twins stay
        # bit-identical even under pathological boost (ISSUE 14).
        qmax = np.float32(min((2**31 - C) // C, 2**24))
        qf = np.round((overlap * boost).astype(np.float32) * 256.0)
        q = np.clip(qf, np.float32(0.0), qmax).astype(np.int64)
        score = q * C + (C - 1 - np.arange(C))
    else:
        score = overlap.astype(np.int64) * C + (C - 1 - np.arange(C))
    k = cfg.num_active_columns
    winners = np.argsort(score)[::-1][:k]
    active = np.zeros(C, bool)
    active[winners] = True
    active &= overlap >= cfg.stimulus_threshold
    return active


def sp_learn(
    state: dict, input_sdr: np.ndarray, overlap: np.ndarray, active: np.ndarray, cfg: SPConfig
) -> None:
    """Hebbian update on winners + duty cycles + boost + weak-column bump.

    `overlap` is this step's pre-learning overlap (duty cycles measure what
    the column saw, not what it would see after the update). Mutates `state`
    in place (the oracle is imperative; the TPU kernel is the functional twin).
    """
    dom = sp_domain(cfg)
    if cfg.sparse_pool:
        # sparse member-index pool: the valid mask (members >= 0) plays the
        # dense potential mask's role, and the per-slot SDR bit comes from
        # the member gather — same masks, same op order as the device twin
        members = state["members"]
        potential = members >= 0
        hit = input_sdr[np.maximum(members, 0)]
        inc_mask = active[:, None] & potential & hit
        dec_mask = active[:, None] & potential & ~hit
    else:
        potential = state["potential"]
        inc_mask = active[:, None] & potential & input_sdr[None, :]
        dec_mask = active[:, None] & potential & ~input_sdr[None, :]
    # Arithmetic runs in the domain's compute dtype. f32 domain: np.float32
    # constants (a python float * bool-mask would promote to f64 and
    # double-round on the store, drifting 1 ulp from the device f32 chain —
    # see temporal_memory._reinforce_and_grow). Quantized domain: int32, so
    # adds can't wrap the narrow storage type before the clip.
    perm = state["perm"].astype(dom.compute_dtype)
    perm += dom.rate(cfg.syn_perm_active_inc) * inc_mask
    perm -= dom.rate(cfg.syn_perm_inactive_dec) * dec_mask
    np.clip(perm, dom.zero, dom.one, out=perm)

    it = int(state["sp_iter"]) + 1
    state["sp_iter"] = np.int32(it)
    period = np.float32(min(cfg.duty_cycle_period, it))
    overlap_now = (overlap > 0).astype(np.float32)
    # Moving average in incremental form d += (x-d)/p, not (d*(p-1)+x)/p: the
    # latter's multiply-add gets FMA-contracted by XLA on device (1-ulp drift
    # vs numpy, observed); sub/div/add has no contractable pattern, so host
    # and device stay bit-identical.
    state["overlap_duty"] = state["overlap_duty"] + (overlap_now - state["overlap_duty"]) / period
    state["active_duty"] = state["active_duty"] + (
        active.astype(np.float32) - state["active_duty"]
    ) / period

    if cfg.boost_strength > 0.0:
        target = cfg.num_active_columns / perm.shape[0]
        state["boost"] = np.exp((target - state["active_duty"]) * cfg.boost_strength).astype(np.float32)

    # Bump starved columns: below min_pct of the max overlap duty cycle ->
    # raise all potential permanences (keeps dead columns recoverable).
    min_duty = cfg.min_pct_overlap_duty_cycle * state["overlap_duty"].max()
    weak = state["overlap_duty"] < min_duty
    if weak.any():
        perm += dom.rate(cfg.syn_perm_below_stimulus_inc) * (weak[:, None] & potential)
        np.clip(perm, dom.zero, dom.one, out=perm)
    state["perm"] = perm.astype(dom.dtype)


def sp_compute(state: dict, input_sdr: np.ndarray, cfg: SPConfig, learn: bool = True) -> np.ndarray:
    """One SP step -> bool[C] active columns. Mutates state if learn."""
    overlap = sp_overlap(state, input_sdr, cfg)
    active = sp_inhibit(overlap, state["boost"], cfg)
    if learn:
        sp_learn(state, input_sdr, overlap, active, cfg)
    return active
