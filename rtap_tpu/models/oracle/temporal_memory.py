"""Temporal Memory — numpy oracle over dense bounded segment pools.

Semantics per SURVEY.md C4/C5 / §3.2 (NuPIC `temporal_memory.py` +
`Connections.cpp`): per-cell distal segments; correctly-predicted cells
activate and learn; unpredicted active columns burst, pick a winner cell
(best matching segment, else fewest segments) and learn/grow; matching
segments in columns that failed to activate are punished; synapses die at
permanence <= 0; full cell pools evict the least-recently-used segment.

NuPIC's pointer-graph Connections store is replaced by fixed-capacity dense
pools [C, K, S, M] (SURVEY.md §7 design stance) — empty synapse slots hold
presyn = -1, free segment slots hold seg_last = -1. Deliberate deviations,
shared with the TPU kernel so backends agree exactly:
- all tie-breaks (winner cell, best segment, slot choice) are lowest-index,
  not RNG-driven;
- growth candidates are taken in ascending prev-winner cell order rather
  than random sample;
- when a full segment needs room to grow, its weakest synapses are evicted
  (NuPIC's destroyMinPermanenceSynapses, minus its random tie-break).
"""

from __future__ import annotations

import numpy as np

from rtap_tpu.config import TMConfig
from rtap_tpu.models.perm import tm_domain


def _grow_synapses(
    state: dict, c: int, k: int, s: int, candidates: np.ndarray, n: int, cfg: TMConfig
) -> None:
    """Add up to n synapses on segment (c,k,s) to candidate cells (ascending
    id) not already presynaptic; evict weakest synapses if slots run short."""
    if n <= 0 or len(candidates) == 0:
        return
    presyn = state["presyn"][c, k, s]
    perm = state["syn_perm"][c, k, s]
    existing = presyn[presyn >= 0]
    new_ids = candidates[~np.isin(candidates, existing)][:n]
    if len(new_ids) == 0:
        return
    free = np.nonzero(presyn < 0)[0]
    short = len(new_ids) - len(free)
    if short > 0:
        # evict weakest existing synapses to make room (bounded-pool rule)
        occupied = np.nonzero(presyn >= 0)[0]
        order = occupied[np.argsort(perm[occupied], kind="stable")]
        evict = order[:short]
        presyn[evict] = -1
        perm[evict] = 0.0
        free = np.nonzero(presyn < 0)[0]
    slots = free[: len(new_ids)]
    presyn[slots] = new_ids[: len(slots)]
    perm[slots] = tm_domain(cfg).rate(cfg.initial_permanence)


def _reinforce_and_grow(
    state: dict,
    c: int,
    k: int,
    s: int,
    prev_active_flat: np.ndarray,
    prev_winner_ids: np.ndarray,
    cfg: TMConfig,
    it: int,
) -> None:
    """Adapt one learning segment: +inc on synapses to previously-active
    cells, -dec on the rest, then grow toward prev winner cells until the
    segment has new_synapse_count active-potential synapses."""
    presyn = state["presyn"][c, k, s]
    exists = presyn >= 0
    act = exists & prev_active_flat[np.clip(presyn, 0, None)]
    # Domain compute dtype: f32 constants in the f32 domain (a python float *
    # bool-array promotes to f64 and the f64-compute-then-f32-store
    # double-rounds, diverging 1 ulp from the device's pure-f32 chain —
    # observed); int32 in quantized domains (no wrap before the clip).
    dom = tm_domain(cfg)
    state["syn_perm"][c, k, s] = np.clip(
        state["syn_perm"][c, k, s].astype(dom.compute_dtype)
        + dom.rate(cfg.permanence_increment) * act
        - dom.rate(cfg.permanence_decrement) * (exists & ~act),
        dom.zero,
        dom.one,
    ).astype(dom.dtype)
    state["seg_last"][c, k, s] = it
    n_grow = cfg.new_synapse_count - int(state["seg_pot"][c, k, s])
    _grow_synapses(state, c, k, s, prev_winner_ids, n_grow, cfg)


def _allocate_segment(state: dict, c: int, k: int, it: int) -> int:
    """Lowest free slot in cell (c,k)'s pool, else evict the LRU segment."""
    seg_last = state["seg_last"][c, k]
    free = np.nonzero(seg_last < 0)[0]
    if len(free):
        s = int(free[0])
    else:
        s = int(np.argmin(seg_last))
        state["presyn"][c, k, s] = -1
        state["syn_perm"][c, k, s] = 0.0
        state["active_seg"][c, k, s] = False
        state["matching_seg"][c, k, s] = False
        state["seg_pot"][c, k, s] = 0
    state["seg_last"][c, k, s] = it
    return s


class TMOracle:
    """Stateful wrapper: compute(active_cols, learn) -> raw anomaly score."""

    def __init__(self, state: dict, cfg: TMConfig):
        self.state = state
        self.cfg = cfg

    def compute(self, active_cols: np.ndarray, learn: bool = True) -> float:
        state, cfg = self.state, self.cfg
        C, K, S, M = state["presyn"].shape
        prev_predictive = state["active_seg"].any(-1)  # [C, K] cells predicted for t
        prev_pred_cols = prev_predictive.any(-1)  # [C]

        n_active = int(active_cols.sum())
        # f32 arithmetic: the device step emits raw as f32, and the score is
        # part of the cross-backend parity contract — round the same way here.
        raw_anomaly = (
            float(np.float32(1.0) - np.float32((active_cols & prev_pred_cols).sum()) / np.float32(n_active))
            if n_active
            else 0.0
        )

        active_cells = np.zeros((C, K), bool)
        winner_cells = np.zeros((C, K), bool)
        prev_active_flat = state["prev_active"].reshape(-1)
        prev_winner_ids = np.nonzero(state["prev_winner"].reshape(-1))[0]
        it = int(state["tm_iter"]) + 1

        for c in np.nonzero(active_cols)[0]:
            pred = np.nonzero(prev_predictive[c])[0]
            if len(pred):
                # correctly predicted column: predicted cells activate + learn
                active_cells[c, pred] = True
                winner_cells[c, pred] = True
                if learn:
                    for k in pred:
                        for s in np.nonzero(state["active_seg"][c, k])[0]:
                            _reinforce_and_grow(
                                state, c, int(k), int(s), prev_active_flat, prev_winner_ids, cfg, it
                            )
            else:
                # burst
                active_cells[c, :] = True
                matching = state["matching_seg"][c]  # [K, S]
                if matching.any():
                    pot = np.where(matching, state["seg_pot"][c], -1)
                    k, s = np.unravel_index(int(np.argmax(pot)), pot.shape)
                    winner_cells[c, k] = True
                    if learn:
                        _reinforce_and_grow(
                            state, c, int(k), int(s), prev_active_flat, prev_winner_ids, cfg, it
                        )
                else:
                    seg_counts = (state["seg_last"][c] >= 0).sum(-1)  # [K]
                    k = int(np.argmin(seg_counts))
                    winner_cells[c, k] = True
                    if learn and len(prev_winner_ids):
                        s = _allocate_segment(state, c, k, it)
                        _grow_synapses(
                            state, c, k, s, prev_winner_ids, cfg.new_synapse_count, cfg
                        )

        if learn and cfg.predicted_segment_decrement > 0.0:
            # punish matching segments in columns that did not activate
            seg_mask = state["matching_seg"] & ~active_cols[:, None, None]
            idx = np.nonzero(seg_mask)
            if len(idx[0]):
                dom = tm_domain(cfg)
                presyn = state["presyn"][idx]
                act = (presyn >= 0) & prev_active_flat[np.clip(presyn, 0, None)]
                state["syn_perm"][idx] = np.maximum(
                    state["syn_perm"][idx].astype(dom.compute_dtype)
                    - dom.rate(cfg.predicted_segment_decrement) * act,
                    dom.zero,
                ).astype(dom.dtype)

        if learn:
            # synapse death at permanence <= 0, then segment death at 0 synapses
            dead = (state["presyn"] >= 0) & (state["syn_perm"] <= 0.0)
            state["presyn"][dead] = -1
            nsyn = (state["presyn"] >= 0).sum(-1)
            empty = (state["seg_last"] >= 0) & (nsyn == 0)
            state["seg_last"][empty] = -1

        # dendrite activity for the next step, over existing segments only
        exist_idx = np.nonzero(state["seg_last"] >= 0)
        active_seg = np.zeros((C, K, S), bool)
        matching_seg = np.zeros((C, K, S), bool)
        seg_pot = np.zeros((C, K, S), np.int16)
        if len(exist_idx[0]):
            presyn = state["presyn"][exist_idx]  # [Nseg, M]
            syn_act = (presyn >= 0) & active_cells.reshape(-1)[np.clip(presyn, 0, None)]
            connected = tm_domain(cfg).threshold(cfg.connected_permanence)
            conn_count = (syn_act & (state["syn_perm"][exist_idx] >= connected)).sum(-1)
            pot_count = syn_act.sum(-1)
            active_seg[exist_idx] = conn_count >= cfg.activation_threshold
            matching_seg[exist_idx] = pot_count >= cfg.min_threshold
            seg_pot[exist_idx] = pot_count
            if learn:
                # LRU stamp only while learning (NuPIC records lastUsedIteration
                # under learn; inference must not perturb eviction order)
                state["seg_last"][active_seg] = it

        state["active_seg"] = active_seg
        state["matching_seg"] = matching_seg
        state["seg_pot"] = seg_pot
        state["prev_active"] = active_cells
        state["prev_winner"] = winner_cells
        state["tm_iter"] = np.int32(it)
        return raw_anomaly
