"""Host twin of the fused predictive-horizon reducer (ISSUE 16).

The TM's active segments are a one-step forward model the fused step
computes and discards every tick. The predict reducer
(ops/predict_tpu.py:predict_update) turns that state into a LEAD-TIME
signal: the predicted-active column set captured at tick ``t - k`` is
compared against the actual active columns at tick ``t`` — a stream in
a learned stable regime keeps high overlap across the horizon, while a
slow pre-fault drift erodes it ticks before the anomaly score spikes
(the precursor the host tracker in rtap_tpu/predict/ pages on).

This module is the oracle side of the pair, in numpy on PUBLIC-layout
state — :func:`predict_update_host` is the bit-twin the rtap-lint v3
``twin-parity`` pass resolves for the device kernel, and
:func:`predict_from_states` is the CPU-oracle backend's adapter (stacks
per-stream state dicts, folds the twin, scatters the updated predictor
leaves back). Everything schema-shaped lives here so the device module
imports it, never the reverse (models/ must not import ops/).

Predictor state (models/state.py, present only when a horizon is set):

    pred_ring      bool [k, C]  predicted-active column sets of the last
                                k ticks (slot ``t % k``)
    pred_miss_ewma f32  []      divergence trajectory: EWMA of the
                                predicted->actual miss rate (NaN until
                                the first scored tick — init-on-first)
    pred_tick0     i32  []      tick the stream's predictor state was
                                (re)initialized — claimed slots stay
                                unscored for a full horizon instead of
                                scoring against a zeroed ring

Update semantics, per tick t (post-step, group layout):

- ``act``  = this tick's active columns (post-step ``prev_active``);
- ``pred`` = columns with any active segment (the TM's prediction for
  t+1); written to ring slot ``t % k`` AFTER the slot is read;
- the slot's prior content is the set captured at ``t - k``; overlap =
  |old & act| / max(|act|, 1), miss = 1 - overlap;
- a stream scores iff it is live (finite input) AND ``t >= pred_tick0
  + k`` (the ring holds a real horizon-old prediction for it);
- the EWMA folds ``miss`` with :data:`PRED_ALPHA` on scored ticks only
  (first scored tick adopts ``miss`` outright).

All arithmetic is f32 with a power-of-two alpha so the device and host
twins agree bit for bit (tests/parity/test_predict_parity.py).
"""

from __future__ import annotations

import numpy as np

from rtap_tpu.config import ModelConfig

__all__ = [
    "PREDICT_KEYS",
    "PRED_ALPHA",
    "predict_horizon_of",
    "predict_nbytes",
    "predict_update_host",
    "predict_from_states",
]

#: divergence-EWMA step — a power of two, so the fold is bit-exact
#: across the device and numpy twins (no fused-multiply reassociation)
PRED_ALPHA = np.float32(0.125)

#: the leaf's key set, in a fixed order (schema contract for the host
#: tracker, the /predict route, and the parity tests). Unlike the
#: health leaf these are PER-STREAM vectors: the tracker needs each
#: stream's own divergence trajectory to page with a stable stream id.
PREDICT_KEYS = (
    "overlap",        # f32 [G] predicted(t-k) -> actual(t) column overlap
    #                           (NaN on unscored streams)
    "miss_ewma",      # f32 [G] post-update divergence EWMA (NaN until a
    #                           stream's first scored tick)
    "pred_col_frac",  # f32 [G] predicted-active column fraction (of C)
    "scored",         # bool [G] live AND past the per-stream horizon
)


def predict_horizon_of(state: dict) -> int:
    """Horizon k carried by a state tree (0 when the predictor leaves are
    absent — flags-off trees are byte-identical to pre-predict HEAD)."""
    ring = state.get("pred_ring")
    if ring is None:
        return 0
    # single-stream [k, C] or group [G, k, C]
    return int(np.shape(ring)[-2])


def predict_nbytes(group_size: int) -> int:
    """Bytes per (group, tick) predict leaf: three f32 vectors plus one
    bool mask per stream — 13 B/stream, riding the chunk output beside
    the [T, G] scores (never a separate device->host fetch)."""
    return group_size * (3 * 4 + 1)


def predict_update_host(state: dict, values: np.ndarray,
                        cfg: ModelConfig) -> tuple[dict, dict]:
    """Numpy twin of ``predict_update`` on PUBLIC-layout group state
    ([G, ...] leaves) -> (state', leaf). Only the predictor-owned leaves
    (``pred_ring``, ``pred_miss_ewma``) change; every model leaf passes
    through untouched — the flags-off bit-exactness contract is
    structural, not behavioral."""
    tm = cfg.tm
    C, K, S = cfg.sp.columns, tm.cells_per_column, tm.max_segments_per_cell
    ring = np.asarray(state["pred_ring"])
    G, k = ring.shape[0], ring.shape[1]
    values = np.asarray(values, np.float32)
    if values.ndim == 1:
        values = values[:, None]

    liv = np.isfinite(values).any(-1)  # [G]
    # tm_iter counts COMPLETED steps (lockstep scalar); the tick just
    # scored is t = tm_iter - 1
    t = np.int32(np.asarray(state["tm_iter"]).reshape(-1)[0]) - np.int32(1)
    slot = int(t % k)

    act = np.asarray(state["prev_active"]).reshape(G, C, K).any(-1)  # [G, C]
    aseg = np.asarray(state["active_seg"]).reshape(G, C, K, S)
    pred_new = aseg.any(-1).any(-1)  # [G, C] columns predicted for t+1

    old = ring[:, slot, :]  # the set captured at tick t - k
    act_n = act.sum(-1).astype(np.float32)
    ov_n = (old & act).sum(-1).astype(np.float32)
    overlap = ov_n / np.maximum(act_n, np.float32(1.0))
    miss = np.float32(1.0) - overlap

    tick0 = np.asarray(state["pred_tick0"], np.int32).reshape(G)
    scored = liv & (t >= tick0 + np.int32(k))

    ewma = np.asarray(state["pred_miss_ewma"], np.float32).reshape(G)
    folded = np.where(np.isnan(ewma), miss,
                      ewma + PRED_ALPHA * (miss - ewma)).astype(np.float32)
    new_ewma = np.where(scored, folded, ewma).astype(np.float32)

    new_ring = ring.copy()
    new_ring[:, slot, :] = pred_new

    nan_overlap = np.where(scored, overlap,
                           np.float32(np.nan)).astype(np.float32)
    col_frac = (pred_new.sum(-1).astype(np.float32) / np.float32(C))
    leaf = {
        "overlap": nan_overlap,  # rtap: partition[shard-streams]
        "miss_ewma": new_ewma,  # rtap: partition[shard-streams]
        "pred_col_frac": col_frac,  # rtap: partition[shard-streams]
        "scored": scored,  # rtap: partition[shard-streams]
    }
    state = dict(state)
    state["pred_ring"] = new_ring
    state["pred_miss_ewma"] = new_ewma
    return state, leaf


def predict_from_states(states: list[dict], values: np.ndarray,
                        cfg: ModelConfig) -> dict:
    """CPU-oracle backend adapter: stack the per-stream oracle dicts into
    a [G, ...] view, fold the host twin, and scatter the updated
    predictor leaves back into each stream's dict (the oracle owns its
    state in place). Only the leaves the reducer reads are stacked."""
    grouped = {
        key: np.stack([np.asarray(s[key]) for s in states])
        for key in ("prev_active", "active_seg", "tm_iter",
                    "pred_ring", "pred_miss_ewma", "pred_tick0")
    }
    grouped, leaf = predict_update_host(grouped, values, cfg)
    for g, s in enumerate(states):
        s["pred_ring"] = grouped["pred_ring"][g]
        s["pred_miss_ewma"] = np.float32(grouped["pred_miss_ewma"][g])
    return leaf
