"""Host-side encoders: RDSE + date (time-of-day / weekend) + multi-field.

Semantics per SURVEY.md C1/C2 (NuPIC `random_distributed_scalar.py`,
`date.py`, `multi.py`), redesigned table-free: RDSE bucket b activates bits
{hash(seed, b+k) % n : k < w}, so adjacent buckets share w-1 hash keys and
SDR overlap decays linearly with |Δbucket| — the defining RDSE property —
with no host-side bucket map to grow or serialize. Identical arithmetic runs
on-device in ops/encoders_tpu.py.
"""

from __future__ import annotations

import numpy as np

from rtap_tpu.config import (
    RDSE_BUCKET_CLAMP,
    DateConfig,
    FieldSpec,
    ModelConfig,
    RDSEConfig,
    ScalarEncoderConfig,
)
from rtap_tpu.utils.hashing import hash_bits_np

SECONDS_PER_DAY = 86400
# Unix epoch (1970-01-01) was a Thursday; weekday = (days + 3) % 7 (Mon=0).
_EPOCH_WEEKDAY_SHIFT = 3


def rdse_bucket(value: float | np.ndarray, offset: float | np.ndarray, resolution: float) -> np.ndarray:
    """Bucket index: round((value - offset) / resolution). NuPIC binds `offset`
    to the first value a stream sees so buckets stay centered on the data.

    Computed in float32 end-to-end: the device kernels have no f64 (JAX x64
    stays off on TPU), and host/device bucket arithmetic must be bit-identical
    for oracle-vs-TPU parity (SURVEY.md §4 item 2)."""
    v = np.asarray(value, np.float32)
    off = np.asarray(offset, np.float32)
    res = np.float32(resolution)
    # f32 divide may overflow to inf for wild values; that's fine — inf clamps
    # to the bound, same as on device (which warns for nothing).
    with np.errstate(over="ignore"):
        b = np.clip(np.round((v - off) / res), -RDSE_BUCKET_CLAMP, RDSE_BUCKET_CLAMP)
    return b.astype(np.int64)


def rdse_bits(cfg: RDSEConfig, bucket: int, field_index: int = 0) -> np.ndarray:
    """Active bit indices for one bucket (may contain duplicates — tolerated,
    see RDSEConfig docstring). Each field of a multivariate record gets its
    own hash stream via the seed."""
    keys = bucket + np.arange(cfg.active_bits, dtype=np.int64)
    return hash_bits_np(keys, cfg.seed + 0x1000 * field_index, cfg.size)


def scalar_bucket(value: float | np.ndarray, cfg: ScalarEncoderConfig) -> np.ndarray:
    """Classic ScalarEncoder bucket (SURVEY.md C2): clip into [min, max],
    then round((v - min) * (size - width) / range). All-f32 so the device
    twin is bit-identical (same contract as rdse_bucket)."""
    v = np.clip(np.asarray(value, np.float32), np.float32(cfg.min_val), np.float32(cfg.max_val))
    scale = np.float32(cfg.size - cfg.width) / (np.float32(cfg.max_val) - np.float32(cfg.min_val))
    return np.round((v - np.float32(cfg.min_val)) * scale).astype(np.int64)


def scalar_bits(cfg: ScalarEncoderConfig, bucket: int) -> np.ndarray:
    """Contiguous ``width``-bit run starting at the bucket index."""
    return bucket + np.arange(cfg.width)


def categorical_bits(spec: FieldSpec, category: int,
                     field_index: int = 0) -> np.ndarray:
    """Active bit indices for one category id (ISSUE 9 encoder family).

    Unlike the RDSE, distinct categories must NOT look similar: category
    ``c`` uses hash keys ``[c*w, c*w + w)`` — disjoint key ranges, so any
    SDR overlap between two ids is pure hash coincidence (the categorical
    property of "Encoding Data for HTM Systems"). Ids are clamped to
    ``spec.categorical_clamp()`` so the device's int32 ``c*w + k`` can
    never wrap where this host int64 path would not."""
    w = spec.active_bits
    clamp = spec.categorical_clamp()
    c = int(np.clip(category, -clamp, clamp))
    keys = c * w + np.arange(w, dtype=np.int64)
    return hash_bits_np(keys, spec.seed + 0x1000 * field_index, spec.size)


def _composite_field_bits(spec: FieldSpec, f: int, value: float, prev: float,
                          offset: float, resolution: float) -> np.ndarray | None:
    """One composite field's active bits (field base offset not yet
    applied), or None for a missing sample. The bucket arithmetic is the
    shared f32 rdse_bucket; what differs per kind is the encoded quantity
    (value vs first difference vs category id), the bucket center (bound
    offset for rdse; the natural 0 for delta/categorical), and the key
    derivation (overlapping runs vs disjoint categorical ranges)."""
    if not np.isfinite(value):
        return None
    if spec.kind == "delta":
        # NuPIC DeltaEncoder: the signal is the first difference; the
        # first sample of a stream (prev is NaN) has none -> missing
        if not np.isfinite(prev):
            return None
        d = float(np.float32(value) - np.float32(prev))
        b = int(rdse_bucket(d, 0.0, resolution))
        keys = b + np.arange(spec.active_bits, dtype=np.int64)
        return hash_bits_np(keys, spec.seed + 0x1000 * f, spec.size)
    if spec.kind == "categorical":
        cat = int(rdse_bucket(value, 0.0, resolution))  # res 1.0: round(id)
        return categorical_bits(spec, cat, f)
    # rdse: same arithmetic as the uniform family, per-field geometry;
    # the offset binds at the stream's first finite value like every RDSE
    b = int(rdse_bucket(value, offset, resolution))
    keys = b + np.arange(spec.active_bits, dtype=np.int64)
    return hash_bits_np(keys, spec.seed + 0x1000 * f, spec.size)


def time_of_day_bits(cfg: DateConfig, ts_unix: int) -> np.ndarray:
    """Periodic encoder over the 24h ring: w contiguous (wrapping) bits
    centered on the current time of day."""
    # Pure integer math (floor((s/86400) * size)) so host and device agree
    # exactly; float forms can differ by 1 ulp at bucket boundaries.
    center = (ts_unix % SECONDS_PER_DAY) * cfg.time_of_day_size // SECONDS_PER_DAY
    return (center + np.arange(cfg.time_of_day_width) - cfg.time_of_day_width // 2) % cfg.time_of_day_size


def is_weekend(ts_unix: int) -> bool:
    weekday = (ts_unix // SECONDS_PER_DAY + _EPOCH_WEEKDAY_SHIFT) % 7
    return weekday >= 5


def encode_record(
    cfg: ModelConfig,
    values: np.ndarray,
    ts_unix: int,
    enc_offset: np.ndarray,
    enc_resolution: np.ndarray | None = None,
    enc_prev: np.ndarray | None = None,
) -> np.ndarray:
    """Encode one record (n_fields scalars + timestamp) -> bool[input_size].

    Layout: [field0 | field1 | ... | time-of-day ring | weekend], each
    field's bit range per ``cfg.field_layout()`` (uniform RDSE/scalar
    runs, or the composite family's per-field kinds — ISSUE 9).
    ``enc_prev`` is the per-field previous finite value (delta fields
    only; None reads as "no predecessor yet" for every field).
    """
    sdr = np.zeros(cfg.input_size, bool)
    values = np.atleast_1d(np.asarray(values, np.float64))
    if len(values) != cfg.n_fields:
        raise ValueError(f"expected {cfg.n_fields} field value(s), got {len(values)}")
    if cfg.composite is not None:
        defaults = cfg.field_resolutions()
        for f, (spec, (_n, _k, off, _sz)) in enumerate(
                zip(cfg.composite.fields, cfg.field_layout())):
            res = float(np.float32(defaults[f])) if enc_resolution is None \
                else float(enc_resolution[f])
            prev = float(enc_prev[f]) if enc_prev is not None else float("nan")
            bits = _composite_field_bits(
                spec, f, float(values[f]), prev, float(enc_offset[f]), res)
            if bits is not None:
                sdr[off + bits] = True
        base = cfg.composite.size
        if cfg.date.time_of_day_width:
            sdr[base + time_of_day_bits(cfg.date, ts_unix)] = True
            base += cfg.date.time_of_day_size
        if cfg.date.weekend_width and is_weekend(ts_unix):
            sdr[base : base + cfg.date.weekend_width] = True
        return sdr
    for f in range(cfg.n_fields):
        if not np.isfinite(values[f]):
            continue  # missing/garbled sample -> no bits for this field (NuPIC behavior)
        if cfg.scalar is not None:
            b = int(scalar_bucket(values[f], cfg.scalar))
            sdr[f * cfg.field_size + scalar_bits(cfg.scalar, b)] = True
            continue
        # Always round the resolution through f32: the state-carried array is
        # f32, and the two entry points (explicit array vs config default)
        # must agree on bucket assignment at boundaries.
        res = float(np.float32(cfg.rdse.resolution)) if enc_resolution is None else float(enc_resolution[f])
        b = int(rdse_bucket(values[f], float(enc_offset[f]), res))
        sdr[f * cfg.field_size + rdse_bits(cfg.rdse, b, f)] = True
    base = cfg.n_fields * cfg.field_size
    if cfg.date.time_of_day_width:
        sdr[base + time_of_day_bits(cfg.date, ts_unix)] = True
        base += cfg.date.time_of_day_size
    if cfg.date.weekend_width:
        if is_weekend(ts_unix):
            sdr[base : base + cfg.date.weekend_width] = True
    return sdr
