"""Anomaly likelihood — rolling-Gaussian-tail post-process (host side).

Semantics per SURVEY.md C8 / §3.2 (NuPIC `anomaly_likelihood.py`): raw
anomaly scores are smoothed with a short moving average; a Gaussian is
periodically refit to the moving-averaged scores over a long historic
window; the reported likelihood is 1 - Q(shortTermAverage), log-scaled to
spread the top of the range. During the probationary period the output is a
noncommittal 0.5 (log score 0).

`mode="streaming"` replaces the historic window with exponentially-decayed
moments so 100k streams need O(1) host memory per stream (SURVEY.md §7 hard
part 5); the window mode is the NuPIC-faithful default.
"""

from __future__ import annotations

import math
from collections import deque

import numpy as np

from rtap_tpu.config import LikelihoodConfig

# NuPIC's log-scale constant: log(1.0000000001 - x) / log(1e-10)
_LOG_DENOM = math.log(1e-10)


def tail_probability(z: float) -> float:
    """Gaussian upper-tail Q(z) via erfc."""
    return 0.5 * math.erfc(z / math.sqrt(2.0))


def log_likelihood(likelihood: float) -> float:
    """NuPIC's log scale: 0.5 -> ~0.03, 0.9999 -> ~0.4, 1-1e-10 -> 1.0."""
    return math.log(1.0000000001 - likelihood) / _LOG_DENOM


class AnomalyLikelihood:
    """Per-stream likelihood state machine (single stream; the service layer
    vectorizes a batch variant in service/likelihood_batch.py)."""

    def __init__(self, cfg: LikelihoodConfig):
        self.cfg = cfg
        self.records = 0
        self.scores: deque[float] = deque(maxlen=cfg.historic_window_size)
        self.recent: deque[float] = deque(maxlen=cfg.averaging_window)
        self.mean = 0.0
        self.std = 1.0
        self.have_distribution = False
        # streaming-mode moments of the averaged score
        self._s0 = 0.0
        self._s1 = 0.0
        self._s2 = 0.0

    def _refit_window(self) -> None:
        scores = np.asarray(self.scores, np.float64)
        # NuPIC skips the model's learning-period records when fitting: early
        # scores are dominated by an untrained TM (raw ~1.0) and would inflate
        # sigma for the rest of the stream.
        still_buffered = max(0, self.cfg.learning_period - (self.records - len(scores)))
        if still_buffered:
            scores = scores[still_buffered:]
        if len(scores) < 2:
            return
        w = self.cfg.averaging_window
        kernel = np.ones(w) / w
        averaged = np.convolve(scores, kernel, mode="valid") if len(scores) >= w else scores
        self.mean = float(averaged.mean())
        self.std = max(float(averaged.std()), 1e-6)
        self.have_distribution = True

    def _update_streaming(self, avg: float) -> None:
        d = self.cfg.streaming_decay
        self._s0 = d * self._s0 + 1.0
        self._s1 = d * self._s1 + avg
        self._s2 = d * self._s2 + avg * avg
        self.mean = self._s1 / self._s0
        var = max(self._s2 / self._s0 - self.mean**2, 0.0)
        self.std = max(math.sqrt(var), 1e-6)
        self.have_distribution = self.records >= self.cfg.probationary_period

    # serialization seam, mirroring BatchAnomalyLikelihood.state_dict — the
    # single source of truth for what this state machine persists.
    # Partition rules (ISSUE 15): likelihood state is host-side post-
    # processing — it never lives in HBM, and under the mesh each shard
    # PROCESS owns the moments of exactly its own streams (host-only).
    def state_dict(self) -> dict:
        return {
            "records": np.asarray(self.records, np.int64),  # rtap: partition[host-only]
            "have_distribution": np.asarray(int(self.have_distribution), np.int64),  # rtap: partition[host-only]
            "scalars": np.array(  # rtap: partition[host-only]
                [self.mean, self.std, self._s0, self._s1, self._s2], np.float64
            ),
            "scores": np.asarray(self.scores, np.float64),  # rtap: partition[host-only]
            "recent": np.asarray(self.recent, np.float64),  # rtap: partition[host-only]
        }

    def load_state_dict(self, d: dict) -> None:
        self.records = int(d["records"])
        self.have_distribution = bool(d["have_distribution"])
        self.mean, self.std, self._s0, self._s1, self._s2 = (
            float(x) for x in d["scalars"]
        )
        self.scores = deque(d["scores"].tolist(), maxlen=self.cfg.historic_window_size)
        self.recent = deque(d["recent"].tolist(), maxlen=self.cfg.averaging_window)

    def update(self, raw_score: float) -> tuple[float, float]:
        """Feed one raw anomaly score -> (likelihood, log_likelihood)."""
        self.records += 1
        self.recent.append(raw_score)
        avg = sum(self.recent) / len(self.recent)

        if self.cfg.mode == "streaming":
            self._update_streaming(avg)
        else:
            self.scores.append(raw_score)
            if self.records % self.cfg.reestimation_period == 0 or not self.have_distribution:
                if self.records >= self.cfg.probationary_period:
                    self._refit_window()

        if self.records < self.cfg.probationary_period or not self.have_distribution:
            return 0.5, log_likelihood(0.5)
        lik = 1.0 - tail_probability((avg - self.mean) / self.std)
        return lik, log_likelihood(lik)
