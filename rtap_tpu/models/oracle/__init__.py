"""Pure-numpy CPU oracle — the semantic specification of the HTM pipeline.

Mirrors the role of NuPIC's pure-Python algorithm twins, which exist to pin
the C++ implementations via parity tests (SURVEY.md §1 L1->L0 note, §4 item
2). Here the oracle is additionally the default production backend for small
stream counts (the reference keeps CPU NuPIC as default, TPU opt-in — the
north star in BASELINE.json).
"""

from rtap_tpu.models.oracle.encoders import encode_record  # noqa: F401
from rtap_tpu.models.oracle.spatial_pooler import sp_compute  # noqa: F401
from rtap_tpu.models.oracle.temporal_memory import TMOracle  # noqa: F401
from rtap_tpu.models.oracle.likelihood import AnomalyLikelihood  # noqa: F401
