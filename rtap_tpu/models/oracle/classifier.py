"""SDR classifier — numpy oracle (SURVEY.md C10).

Semantics per the public NuPIC SDRClassifier (`sdr_classifier.py` /
`SDRClassifier.cpp`): a single-layer softmax regression from active-cell
patterns to encoder buckets, trained one-step-ahead — at record t the
pattern observed at t-1 is pushed toward the bucket of the value seen at t
(error = onehot(bucket) - softmax(logits), plain SGD), and inference applies
the pattern at t to produce the distribution for t+1. Per-bucket "actual
values" are tracked with an exponential moving average; the predicted value
is the actual value of the argmax bucket.

Deliberate deviations (shared with the device twin, ops/classifier_tpu.py):

- fixed bucket window [0, buckets) instead of NuPIC's growable bucket dict
  (static shapes; offset binding centers the stream's first value, and the
  NAB resolution rule spans the expected range in ~130 buckets, so clamping
  only triggers on out-of-range excursions);
- steps fixed at 1 (the reference's OPF models predict the next record);
- arithmetic in float32 to mirror the device kernel (parity is tested to
  float tolerance — softmax/exp may differ by ulps across backends).
"""

from __future__ import annotations

import numpy as np

from rtap_tpu.config import ClassifierConfig


def classifier_bucket(
    value: float, offset: float, resolution: float, n_buckets: int
) -> int:
    """Classifier bucket for one value: the RDSE bucket (same f32 arithmetic
    and overflow clamping — reuses encoders.rdse_bucket) shifted to center
    the window and clamped to [0, n_buckets). Non-finite values map to the
    center bucket (relative bucket 0)."""
    from rtap_tpu.models.oracle.encoders import rdse_bucket

    if not np.isfinite(value):
        return n_buckets // 2
    b = int(rdse_bucket(value, offset, resolution))
    return int(np.clip(b + n_buckets // 2, 0, n_buckets - 1))


class SDRClassifierOracle:
    """Per-record classifier compute over the shared state dict.

    Operates in place on the same ``cls_w`` / ``cls_val`` / ``cls_cnt``
    arrays that models/state.py allocates (and the device kernel carries),
    mirroring how TMOracle shares the TM pools — one state layout for both
    backends, one checkpoint path."""

    def __init__(self, state: dict, cfg: ClassifierConfig):
        self.state = state
        self.cfg = cfg

    def _softmax(self, pattern_flat: np.ndarray) -> np.ndarray:
        z = pattern_flat.astype(np.float32) @ self.state["cls_w"]  # [B]
        z = z - z.max()
        e = np.exp(z, dtype=np.float32)
        return e / e.sum(dtype=np.float32)

    def compute(
        self,
        pattern_prev: np.ndarray,  # bool [n_cells] — active cells at t-1
        pattern_now: np.ndarray,  # bool [n_cells] — active cells at t
        bucket: int,  # classifier bucket of the value at t
        value: float,  # the value at t
        learn: bool = True,
    ) -> tuple[float, float]:
        """-> (predicted value for t+1, probability of the argmax bucket)."""
        cfg = self.cfg
        act_value, act_count = self.state["cls_val"], self.state["cls_cnt"]
        if learn and np.isfinite(value):
            # actual-value EMA for the observed bucket (first touch sets it)
            if act_count[bucket] == 0:
                act_value[bucket] = np.float32(value)
            else:
                act_value[bucket] = np.float32(
                    (1.0 - np.float32(cfg.act_value_alpha)) * act_value[bucket]
                    + np.float32(cfg.act_value_alpha) * np.float32(value)
                )
            act_count[bucket] += 1
            if pattern_prev.any():
                p = self._softmax(pattern_prev)
                err = -p
                err[bucket] += 1.0
                self.state["cls_w"][pattern_prev] += np.float32(cfg.alpha) * err[None, :]

        probs = self._softmax(pattern_now)
        best = int(np.argmax(probs))  # first max, matching device argmax
        return float(act_value[best]), float(probs[best])
