"""Permanence arithmetic domains: f32 reference semantics or fixed-point.

Permanence tensors dominate per-stream HBM (the cluster preset's TM
`syn_perm` + SP `perm` are ~76% of state bytes — SURVEY.md §7 hard part 4),
so the storage dtype is the highest-leverage memory lever. `perm_bits` on
SPConfig/TMConfig selects the domain:

- ``0``  — f32 permanences in [0, 1], the NuPIC-faithful reference semantics.
- ``16`` — uint16 fixed-point quanta on the grid 1/(2^16 - 1). Every
  configured rate/threshold is converted once at trace/init time
  (``round(v * 65535)``, floored at 1 quantum so a configured-nonzero rate
  can never silently become a no-op); all updates are exact integer
  arithmetic. The deviation from f32 semantics is only the one-time rounding
  of the configured constants (worst case 1/131070 relative on a rate).
- ``8``  — uint8 quanta on 1/255, for maximum stream density. Coarse: e.g.
  a predicted_segment_decrement of 0.001 becomes 1/255 ≈ 0.0039 (4x). The
  quality impact is measured, not assumed — eval/fault_eval compares domains
  (SCALING.md).

Cross-backend parity stays bit-for-bit in every domain: the numpy oracle
computes in int32 and the device kernel in integer-valued f32 (quanta are
< 2^24, exactly representable), which agree exactly.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from rtap_tpu.config import SPConfig, TMConfig


@dataclass(frozen=True)
class PermDomain:
    """Resolved constants for one permanence tensor family.

    ``one`` is the clip ceiling (1.0 or 2^bits - 1); rates/thresholds are
    pre-converted to the domain so oracle and kernel share one expression
    shape. Types: f32 domain -> np.float32 scalars; quantized -> python ints
    (numpy weak promotion keeps int32 compute exact).
    """

    bits: int  # 0 = f32

    @property
    def scale(self) -> int:
        return (1 << self.bits) - 1

    @property
    def dtype(self):
        """Storage dtype of the permanence tensors."""
        return {0: np.float32, 8: np.uint8, 16: np.uint16}[self.bits]

    @property
    def compute_dtype(self):
        """Intermediate dtype for update arithmetic: f32, or int32 so a
        quantized add can never wrap before the clip. (The device TM kernel
        instead computes on integer-VALUED f32 — quanta < 2^24 are exact —
        which agrees bit-for-bit with int32.)"""
        return np.float32 if self.bits == 0 else np.int32

    @property
    def one(self):
        return np.float32(1.0) if self.bits == 0 else self.scale

    @property
    def zero(self):
        return np.float32(0.0) if self.bits == 0 else 0

    def threshold(self, v: float):
        """Comparison constant (connected permanence): plain round."""
        return np.float32(v) if self.bits == 0 else int(round(v * self.scale))

    def rate(self, v: float):
        """Additive constant (inc/dec/bump/initial): rounds, but a nonzero
        configured rate is floored at 1 quantum — quantization must never
        turn a learning rule off."""
        if self.bits == 0:
            return np.float32(v)
        return max(1, int(round(v * self.scale))) if v > 0.0 else 0

    def quantize_init(self, perm_f32: np.ndarray) -> np.ndarray:
        """Quantize a freshly-initialized f32 permanence array to storage."""
        if self.bits == 0:
            return perm_f32.astype(np.float32)
        return np.round(perm_f32 * self.scale).astype(self.dtype)


def sp_domain(cfg: SPConfig) -> PermDomain:
    return PermDomain(cfg.perm_bits)


def tm_domain(cfg: TMConfig) -> PermDomain:
    return PermDomain(cfg.perm_bits)
