"""Dense→sparse SP pool migration (ISSUE 18; docs/MIGRATION.md).

A dense-layout checkpoint stores the SP pool as `potential` bool [C, n_in]
+ `perm` [C, n_in]. The sparse layout stores the same pool as a
member-index table `members` [C, P] (+ `perm` [C, P]). The two are
informationally identical whenever P covers the widest column's potential
count — migration is a pure re-layout: every (column, input) synapse keeps
its exact permanence, columns with fewer than P members pad with the -1
empty-slot sentinel (permanence 0), and both kernels mask those slots out
of every overlap/learning term. Forward scores after migration are
therefore BIT-IDENTICAL to the dense run, forever: overlap is an
order-independent integer count over the same synapse set, and the
learning masks touch the same (column, input) pairs
(tests/parity/test_sparse_sp.py pins this; the committed-checkpoint
restore is tests/unit/test_checkpoint.py).

Group state trees carry a leading G axis; everything here is shape-
polymorphic over leading axes.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from rtap_tpu.config import ModelConfig


def sparse_pool_width(potential: np.ndarray, multiple: int = 8) -> int:
    """Smallest P (rounded up to `multiple` for lane alignment) that holds
    the widest column of `potential` bool [..., C, n_in]."""
    widest = int(np.asarray(potential).sum(-1).max()) if potential.size else 0
    widest = max(widest, 1)
    return -(-widest // multiple) * multiple


def sparsify_sp_state(state: dict, pool_members: int | None = None) -> dict:
    """Re-lay a dense state tree's SP pool as member-index sparse.

    `state` holds `potential` bool [..., C, n_in] and `perm` [..., C, n_in]
    (leading group axes allowed). Returns a new dict where those two become
    `members` [..., C, P] (ascending input indices, -1 padding) and `perm`
    [..., C, P]; every other leaf rides through unchanged. P defaults to
    :func:`sparse_pool_width` of the mask; an explicit `pool_members` must
    cover the widest column or the migration would silently DROP synapses —
    refused loudly."""
    potential = np.asarray(state["potential"])
    perm = np.asarray(state["perm"])
    n_in = potential.shape[-1]
    widest = int(potential.sum(-1).max()) if potential.size else 0
    P = sparse_pool_width(potential) if pool_members is None else int(pool_members)
    if P < widest:
        raise ValueError(
            f"pool_members={P} cannot hold the widest migrated column "
            f"({widest} potential synapses); a lossy migration would change "
            "scores silently — raise pool_members or let it default"
        )
    # stable argsort of (not potential) lists each row's True positions
    # first, in ascending input order, then the False positions — exactly
    # the ascending member table with the pad tail in one vectorized shot
    order = np.argsort(~potential, axis=-1, kind="stable")[..., :P]
    valid = np.take_along_axis(potential, order, axis=-1)
    members_dt = np.int16 if n_in <= (1 << 15) - 1 else np.int32
    members = np.where(valid, order, -1).astype(members_dt)
    sparse_perm = np.where(
        valid, np.take_along_axis(perm, order, axis=-1), np.zeros((), perm.dtype)
    ).astype(perm.dtype)
    out = {k: v for k, v in state.items() if k != "potential"}
    out["members"] = members
    out["perm"] = sparse_perm
    return out


def sparsify_config(cfg: ModelConfig, pool_members: int) -> ModelConfig:
    """The migrated state's config: same model, sparse pool layout with the
    migration's exact P pinned via `pool_members` (the derived
    potential_pct*input_size width only applies to fresh-init pools)."""
    return dataclasses.replace(
        cfg, sp=dataclasses.replace(cfg.sp, sparse_pool=True, pool_members=int(pool_members))
    )
