"""Model state initialization — shared by the CPU oracle and the TPU kernels.

The reference's state lives in C++ object graphs (SpatialPooler members,
Connections' segment/synapse lists — SURVEY.md C3/C5). Here all state is a
flat dict of fixed-shape numpy arrays, initialized once on host; the TPU
backend `device_put`s the very same arrays. Using one init for both backends
makes oracle-vs-TPU parity exact (SURVEY.md §4 item 2).

Layout (single stream; stream groups add a leading G axis):

SP state — two structurally different pool layouts (SPConfig.sparse_pool):

  dense (default; NuPIC-shaped):
    potential   bool [C, n_in]   fixed potential pool mask
    perm        P_sp [C, n_in]   permanences (0 outside potential)
  sparse (ISSUE 18; gather-addressed member-index pools):
    members     i16/i32 [C, P]   presynaptic INPUT indices of each column's
                                 P potential synapses, ascending; -1 = empty
                                 slot (only dense->sparse migration pads —
                                 models/migrate.py; i16 iff n_in fits)
    perm        P_sp [C, P]      permanences per member slot (0 in empty)
  shared:
    boost       f32  [C]         boost factors (1.0 when boost_strength == 0)
    overlap_duty f32 [C]         overlap duty cycles
    active_duty f32  [C]         activation duty cycles
    sp_iter     i32  []          records seen

TM state (dense bounded pools; C cols x K cells x S segments x M synapses):
    presyn      i16/i32 [C,K,S,M] presynaptic flat cell id, -1 = empty slot
                                 (i16 iff C*K <= 2^15 - 1)
    syn_perm    P_tm [C,K,S,M]   synapse permanences (0 in empty slots)
    seg_last    i32 [C,K,S]      last-used iteration, -1 = segment free (LRU key)
    active_seg  bool [C,K,S]     segments active at end of previous step
    matching_seg bool [C,K,S]    segments matching at end of previous step
    seg_pot     i16 [C,K,S]      active-potential synapse count at prev step
                                 (<= max_synapses_per_segment)
    prev_active bool [C,K]       active cells at previous step
    prev_winner bool [C,K]       winner cells at previous step
    tm_iter     i32  []

P_sp / P_tm are the permanence storage dtypes of the configured domains
(models/perm.py): f32 at perm_bits=0, uint16/uint8 fixed-point quanta
otherwise. The per-stream byte budget — the binding constraint at 100k
streams (SURVEY.md §7 hard part 4) — is computed honestly by
:func:`state_nbytes`, which sums the actual arrays.

Encoder state:
    enc_offset  f32 [n_fields]   RDSE offset, bound to first seen value
    enc_bound   bool []          whether offset has been bound
    enc_resolution f32 [n_fields] RDSE resolution (runtime, so one compiled
                                 program serves streams with different value
                                 ranges, e.g. a batched NAB corpus run)
"""

from __future__ import annotations

import numpy as np

from rtap_tpu.config import ModelConfig
from rtap_tpu.models.perm import sp_domain, tm_domain


def presyn_dtype(cfg: ModelConfig):
    """int16 whenever every cell id (< num_cells) fits, else int32. The -1
    empty-slot sentinel needs a signed type either way."""
    return np.int16 if cfg.num_cells <= (1 << 15) - 1 else np.int32


def members_dtype(cfg: ModelConfig):
    """Sparse SP member-index dtype: int16 whenever every input index
    (< input_size) fits, else int32 — same rule (and same -1 sentinel
    need) as presyn_dtype."""
    return np.int16 if cfg.input_size <= (1 << 15) - 1 else np.int32


def fwd_index_arrays(cfg: ModelConfig) -> dict[str, np.ndarray]:
    """Fresh (all-empty) forward-index arrays for an empty synapse pool
    (RTAP_TM_DENDRITE=forward — ops/fwd_index.py): fwd_slots [N, F] i32,
    fwd_pos [pool] i8/i16, fwd_of i32 overflow counter. Derived state —
    checkpoints drop them and loads rebuild from `presyn`."""
    tm = cfg.tm
    F = tm.fanout_cap
    pool = cfg.sp.columns * tm.cells_per_column * tm.max_segments_per_cell * tm.max_synapses_per_segment
    return {
        "fwd_slots": np.full((cfg.num_cells, F), -1, np.int32),  # rtap: partition[shard-streams]
        "fwd_pos": np.full(pool, -1, np.int8 if F <= 127 else np.int16),  # rtap: partition[shard-streams]
        "fwd_of": np.int32(0),  # rtap: partition[shard-streams]
    }


def init_state(
    cfg: ModelConfig, seed: int = 0, include_fwd: bool | None = None,
    predict_horizon: int = 0,
) -> dict[str, np.ndarray]:
    """Build the full per-stream state dict (see module docstring for layout).

    `include_fwd` adds the forward-index arrays (None = yes iff the kernel's
    dendrite mode is "forward", so callers stay mode-agnostic).

    `predict_horizon` > 0 adds the predictive-horizon leaves (ISSUE 16,
    ops/predict_tpu.py): a k-deep ring of predicted-active column sets, the
    divergence EWMA, and the per-stream warm-up epoch. 0 (the default) omits
    them entirely, so predict-less state trees — and their checkpoints — stay
    byte-identical to pre-predict builds (the flags-off bit-exactness pin)."""
    if include_fwd is None:
        from rtap_tpu.ops.tm_tpu import dendrite_mode

        include_fwd = dendrite_mode() == "forward"
    rng = np.random.Generator(np.random.Philox(key=(seed, 0xC0FFEE)))
    C, n_in = cfg.sp.columns, cfg.input_size
    K, S, M = cfg.tm.cells_per_column, cfg.tm.max_segments_per_cell, cfg.tm.max_synapses_per_segment

    if cfg.sp.sparse_pool:
        # Sparse member-index pool (ISSUE 18): exactly P distinct input
        # indices per column (a uniform P-subset via argsort of iid
        # uniforms), stored ascending. Every init slot is valid; -1 padding
        # only enters via dense->sparse migration (models/migrate.py).
        P = cfg.sp_members
        sel = np.argsort(rng.random((C, n_in)), axis=1, kind="stable")[:, :P]
        # Permanences seeded around the connected threshold so ~half the
        # pool starts connected (NuPIC's init strategy, SURVEY.md C3) —
        # the same formula as the dense branch, over member slots only.
        perm = np.clip(
            cfg.sp.syn_perm_connected + (rng.random((C, P)) - 0.5) * 0.1, 0.0, 1.0
        ).astype(np.float32)
        sp_pool = {
            "members": np.sort(sel, axis=1).astype(members_dtype(cfg)),  # rtap: partition[shard-streams]
            "perm": sp_domain(cfg.sp).quantize_init(perm),  # rtap: partition[shard-streams]
        }
    else:
        potential = rng.random((C, n_in)) < cfg.sp.potential_pct
        # Permanences seeded around the connected threshold so ~half the potential
        # pool starts connected (NuPIC's init strategy, SURVEY.md C3).
        perm = np.where(
            potential,
            np.clip(cfg.sp.syn_perm_connected + (rng.random((C, n_in)) - 0.5) * 0.1, 0.0, 1.0),
            0.0,
        ).astype(np.float32)
        sp_pool = {
            "potential": np.asarray(potential),  # rtap: partition[shard-streams]
            "perm": sp_domain(cfg.sp).quantize_init(perm),  # rtap: partition[shard-streams]
        }

    # Partition rules (ISSUE 15, rtap-lint partition-contract): every
    # leaf below is per-stream state whose group form carries a leading
    # G axis — shard-streams, the SDR-independence property ROADMAP-1's
    # mesh stands on. A future leaf that is NOT per-stream must declare
    # replicated/host-only or the analyzer refuses it.
    return {
        # SP pool (dense potential/perm or sparse members/perm — above)
        **sp_pool,
        "boost": np.ones(C, np.float32),  # rtap: partition[shard-streams]
        "overlap_duty": np.zeros(C, np.float32),  # rtap: partition[shard-streams]
        "active_duty": np.zeros(C, np.float32),  # rtap: partition[shard-streams]
        "sp_iter": np.int32(0),  # rtap: partition[shard-streams]
        # TM
        "presyn": np.full((C, K, S, M), -1, presyn_dtype(cfg)),  # rtap: partition[shard-streams]
        "syn_perm": np.zeros((C, K, S, M), tm_domain(cfg.tm).dtype),  # rtap: partition[shard-streams]
        "seg_last": np.full((C, K, S), -1, np.int32),  # rtap: partition[shard-streams]
        "active_seg": np.zeros((C, K, S), bool),  # rtap: partition[shard-streams]
        "matching_seg": np.zeros((C, K, S), bool),  # rtap: partition[shard-streams]
        "seg_pot": np.zeros((C, K, S), np.int16),  # rtap: partition[shard-streams]
        "prev_active": np.zeros((C, K), bool),  # rtap: partition[shard-streams]
        "prev_winner": np.zeros((C, K), bool),  # rtap: partition[shard-streams]
        "tm_iter": np.int32(0),  # rtap: partition[shard-streams]
        # device-kernel capacity overflow counter
        "tm_overflow": np.int32(0),  # rtap: partition[shard-streams]

        # encoder (offset binds per field at the first *finite* value seen;
        # resolutions are per field — uniform configs repeat the family
        # default bit-for-bit, composite fields carry their FieldSpec's)
        "enc_offset": np.zeros(cfg.n_fields, np.float32),  # rtap: partition[shard-streams]
        "enc_bound": np.zeros(cfg.n_fields, bool),  # rtap: partition[shard-streams]
        "enc_resolution": np.asarray(cfg.field_resolutions(), np.float32),  # rtap: partition[shard-streams]
        # delta-encoder predecessor (composite family only): last FINITE
        # value per field, NaN = no predecessor yet (the first sample of
        # a delta field encodes as missing — NuPIC DeltaEncoder). Absent
        # for every non-delta config, so pre-ISSUE-9 state trees (and
        # their checkpoints) are byte-identical.
        **({"enc_prev": np.full(cfg.n_fields, np.nan, np.float32)}  # rtap: partition[shard-streams]
           if cfg.composite is not None and cfg.composite.has_delta else {}),
        # predictive-horizon leaves (ISSUE 16, ops/predict_tpu.py): present
        # only when a horizon is armed — serve --predict off keeps the tree
        # byte-identical to HEAD. pred_ring slot t%k holds the predicted-
        # active column set captured at tick t; pred_miss_ewma is NaN until
        # the stream's first scored tick; pred_tick0 is the (re)init tick —
        # claimed slots stay unscored for a full horizon (registry sets it).
        **({
            "pred_ring": np.zeros((predict_horizon, cfg.sp.columns), bool),  # rtap: partition[shard-streams]
            "pred_miss_ewma": np.float32(np.nan),  # rtap: partition[shard-streams]
            "pred_tick0": np.int32(0),  # rtap: partition[shard-streams]
        } if predict_horizon else {}),
        # forward synapse index (derived; present only in forward dendrite mode)
        **(fwd_index_arrays(cfg) if include_fwd else {}),
        # SDR classifier (SURVEY.md C10), present only when enabled
        **(
            {
                "cls_w": np.zeros((C * K, cfg.classifier.buckets), np.float32),  # rtap: partition[shard-streams]
                "cls_val": np.zeros(cfg.classifier.buckets, np.float32),  # rtap: partition[shard-streams]
                "cls_cnt": np.zeros(cfg.classifier.buckets, np.int32),  # rtap: partition[shard-streams]
            }
            if cfg.classifier.enabled
            else {}
        ),
    }


def state_nbytes(cfg: ModelConfig, seed: int = 0) -> dict[str, int]:
    """Honest per-stream device-state byte budget: sums the actual arrays of
    one stream's state (the authoritative number for SCALING.md and the
    preset docstrings; a hand-derived figure in round 2 was off by 9x).

    Returns {"total": bytes, "<key>": bytes, ...} sorted descending by size.
    """
    st = init_state(cfg, seed)
    per = {k: int(np.asarray(v).nbytes) for k, v in st.items()}
    out = {"total": sum(per.values())}
    out.update(sorted(per.items(), key=lambda kv: -kv[1]))
    return out
