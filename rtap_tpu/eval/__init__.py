"""Detection-quality evaluation (SURVEY.md §3.5 + §6).

The reference's "real" eval injects faults into a live cluster and measures
whether the anomaly-likelihood alert fired around the fault onset (lead
time, precision). Here the monitored cluster is the synthetic generator
(rtap_tpu/data/synthetic.py) with kind-labeled fault events, and the
measurement is :mod:`rtap_tpu.eval.fault_eval`.
"""

from rtap_tpu.eval.fault_eval import FaultEvalReport, run_fault_eval

__all__ = ["FaultEvalReport", "run_fault_eval"]
