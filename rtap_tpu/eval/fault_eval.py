"""Fault-injection evaluation: lead time, latency, precision, recall.

The reference's experiment loop (SURVEY.md §3.5) injects a fault at t_f and
asks: did the log-likelihood alert fire inside [t_f - lead, t_f + window]?
This module is that measurement for the synthetic cluster: replay N
kind-labeled streams through the detector pipeline, threshold the
log-likelihood into alerts, match alerts to fault events, and report
per-kind and overall

- recall      — fraction of injected faults whose window contains >= 1 alert
- precision   — fraction of alerts that fall inside some labeled window
- latency     — first-alert time minus fault onset (negative = early warning
                from the pre-onset margin; the reference's "lead time" is
                window_end - first_alert, also reported)

Methodology follows NAB: the detection threshold is swept and metrics are
reported both at the F1-optimal threshold (the detector's quality) and at
the fixed service default (the deployed alerting behavior).

Run as a script for the report artifacts (note the likelihood mode — the
headline artifact is the PRODUCTION streaming config; the default window
mode is the NuPIC-faithful comparison config):

    python -m rtap_tpu.eval.fault_eval --streams 120 --likelihood streaming \
        --out reports/fault_eval.json
    python -m rtap_tpu.eval.fault_eval --streams 120 --out reports/fault_eval_window.json
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
from dataclasses import asdict, dataclass, field

import numpy as np

from rtap_tpu.config import ModelConfig, cluster_preset
from rtap_tpu.data.synthetic import ANOMALY_KINDS, LabeledStream, SyntheticStreamConfig, generate_stream


@dataclass
class KindStats:
    events: int = 0
    detected: int = 0
    latencies: list[float] = field(default_factory=list)  # sec, detected only
    leads: list[float] = field(default_factory=list)  # window_end - first alert

    @property
    def recall(self) -> float:
        return self.detected / self.events if self.events else 0.0

    def summary(self) -> dict:
        lat = np.asarray(self.latencies, np.float64)
        lead = np.asarray(self.leads, np.float64)
        return {
            "events": self.events,
            "detected": self.detected,
            "recall": round(self.recall, 4),
            "median_latency_s": float(np.median(lat)) if lat.size else None,
            "mean_latency_s": float(lat.mean()) if lat.size else None,
            "median_lead_s": float(np.median(lead)) if lead.size else None,
        }


@dataclass
class FaultEvalReport:
    n_streams: int
    n_ticks: int
    default_threshold: float
    best_threshold: float
    at_default: dict  # overall metrics at the service default threshold
    at_best: dict  # overall metrics at the F1-optimal (threshold, debounce)
    per_kind: dict[str, dict]  # per-kind stats at the best operating point
    throughput: dict
    default_debounce: int = 1
    best_debounce: int = 1
    # per-kind optimal operating points (kind f1 vs the global precision) —
    # the spread quantifies what one shared service threshold costs each kind
    kind_thresholds: dict[str, dict] = field(default_factory=dict)

    def to_json(self) -> str:
        return json.dumps(asdict(self), indent=2, sort_keys=True)


def _f1(precision: float, recall: float) -> float:
    return (2 * precision * recall / (precision + recall)) if (precision + recall) else 0.0


def debounce_mask(hits: np.ndarray, d: int) -> np.ndarray:
    """Apply the service's consecutive-tick debounce (StreamGroup._debounced)
    to a [T, N] hit mask: a stream alerts at t iff hits held for the last
    `d` ticks. Equivalent to the service's running counter, vectorized as an
    AND of d shifted slices (the sweep calls this ~190x per eval; a per-tick
    Python loop would add millions of interpreter iterations)."""
    if d <= 1:
        return hits
    out = hits.copy()
    for k in range(1, d):
        out[k:] &= hits[:-k]
        out[:k] = False
    return out


def _episodes(alert_ts: np.ndarray, cooldown_s: float) -> list[tuple[int, int]]:
    """Collapse alert ticks into episodes: a new episode starts when the gap
    since the previous alert exceeds `cooldown_s`. Returns (first, last)
    timestamp spans."""
    if len(alert_ts) == 0:
        return []
    splits = np.nonzero(np.diff(alert_ts) > cooldown_s)[0] + 1
    return [
        (int(seg[0]), int(seg[-1]))
        for seg in np.split(alert_ts, splits)
    ]


def match_alerts(
    streams: list[LabeledStream],
    alerts: np.ndarray,  # [T, N] bool
    timestamps: np.ndarray,  # [T] int64 (shared clock)
    cooldown_s: float = 10.0,
) -> tuple[dict[str, KindStats], dict]:
    """Match per-stream alerts to kind-labeled fault events.

    Precision is reported at two granularities:

    - tick level (`precision_ticks`): fraction of alert *ticks* inside some
      labeled window — harsh on persistent faults, where the likelihood tail
      after the window closes counts one false alert per tick;
    - episode level (`precision`, the headline): consecutive alert ticks
      (gaps <= cooldown) collapse into one alert episode, and an episode is
      true iff it intersects a labeled window. This matches the reference's
      event-granularity question (SURVEY.md §3.5: "did the alert fire in
      [t_f - lead, t_f + window]?") — an operator pages once per episode,
      not once per tick.
    """
    per_kind: dict[str, KindStats] = {k: KindStats() for k in ANOMALY_KINDS}
    total_alerts = 0
    true_alerts = 0
    total_episodes = 0
    true_episodes = 0
    for j, s in enumerate(streams):
        alert_ts = timestamps[alerts[:, j]]
        total_alerts += len(alert_ts)
        in_any = np.zeros(len(alert_ts), bool)
        for ev in s.events:
            ks = per_kind.setdefault(ev.kind, KindStats())
            ks.events += 1
            lo, hi = ev.window
            inside = (alert_ts >= lo) & (alert_ts <= hi)
            in_any |= inside
            if inside.any():
                first = int(alert_ts[inside][0])
                ks.detected += 1
                ks.latencies.append(float(first - ev.onset))
                ks.leads.append(float(hi - first))
        true_alerts += int(in_any.sum())
        eps = _episodes(alert_ts, cooldown_s)
        total_episodes += len(eps)
        true_episodes += sum(
            any(e0 <= hi and e1 >= lo for (lo, hi) in (ev.window for ev in s.events))
            for (e0, e1) in eps
        )

    all_events = sum(k.events for k in per_kind.values())
    all_detected = sum(k.detected for k in per_kind.values())
    all_lat = np.asarray(
        [x for k in per_kind.values() for x in k.latencies], np.float64
    )
    recall = all_detected / all_events if all_events else 0.0
    precision_ticks = true_alerts / total_alerts if total_alerts else 1.0
    precision = true_episodes / total_episodes if total_episodes else 1.0
    f1 = _f1(precision, recall)
    overall = {
        "events": all_events,
        "detected": all_detected,
        "recall": round(recall, 4),
        "alerts": total_alerts,
        "true_alerts": true_alerts,
        "precision_ticks": round(precision_ticks, 4),
        "episodes": total_episodes,
        "true_episodes": true_episodes,
        "precision": round(precision, 4),
        "f1": round(f1, 4),
        "median_latency_s": float(np.median(all_lat)) if all_lat.size else None,
    }
    return per_kind, overall


def score_lead_time(
    events: list[dict],
    onsets: dict[str, int],
    cascade_order: list[str],
    node_of=None,
) -> dict:
    """Score predictive-horizon events against cascade ground truth
    (ISSUE 16 acceptance; scripts/predict_eval.py is the driver).

    ``events`` are the predictor's emitted dicts (``precursor`` /
    ``predicted_incident``, docs/PREDICT.md schemas) with ticks on the
    eval's replay clock; ``onsets`` maps node -> fault-onset tick;
    ``cascade_order`` lists the faulted nodes origin-first. A *page* is
    the first precursor on any cascade node, or the first
    predicted_incident whose blast radius touches one — a false
    precursor on a healthy service must not count as the win. The
    headline is ``lead_ticks_vs_second``: positive means the operator
    was paged BEFORE the second node fell over, i.e. while the cascade
    was still preventable — the reference's lead-time question asked of
    the prediction stream instead of the score stream."""
    if node_of is None:
        def node_of(s):
            return s.rsplit(".", 1)[0] if "." in s else s
    cascade = set(cascade_order)
    first_by_node: dict[str, int] = {}
    false_precursors = 0
    for ev in events:
        if ev.get("event") != "precursor":
            continue
        node = node_of(str(ev.get("stream")))
        t = int(ev["tick"])
        if node in cascade:
            first_by_node[node] = min(t, first_by_node.get(node, t))
        else:
            false_precursors += 1
    incident = next(
        (ev for ev in events if ev.get("event") == "predicted_incident"
         and cascade & set(ev.get("blast_radius", ()))), None)
    page_ticks = list(first_by_node.values())
    if incident is not None:
        page_ticks.append(int(incident["tick"]))
    page_tick = min(page_ticks) if page_ticks else None
    origin = cascade_order[0]
    second_onset = onsets[cascade_order[1]] if len(cascade_order) > 1 \
        else None
    radius = set(incident.get("blast_radius", ())) \
        if incident is not None else set()
    blast_covered = incident is not None and cascade <= radius
    return {
        "paged": page_tick is not None,
        "page_tick": page_tick,
        "origin_onset": int(onsets[origin]),
        "second_onset": int(second_onset) if second_onset is not None
        else None,
        "lead_ticks_vs_origin": int(onsets[origin] - page_tick)
        if page_tick is not None else None,
        "lead_ticks_vs_second": int(second_onset - page_tick)
        if page_tick is not None and second_onset is not None else None,
        "first_precursor_by_node": {
            n: int(t) for n, t in sorted(first_by_node.items())},
        "false_precursors": false_precursors,
        "predicted_incident": None if incident is None else {
            "incident_id": incident.get("alert_id"),
            "tick": int(incident["tick"]),
            "first_node": incident.get("first_node"),
            "blast_radius": sorted(radius),
        },
        "blast_covered": blast_covered,
        "win": bool(page_tick is not None and second_onset is not None
                    and page_tick < second_onset and blast_covered),
    }


def run_fault_eval(
    n_streams: int = 120,
    length: int = 1500,
    kinds: tuple[str, ...] = ("spike", "level_shift", "dropout"),
    magnitude: float = 6.0,
    cfg: ModelConfig | None = None,
    backend: str = "tpu",
    default_threshold: float = 0.5,
    seed: int = 11,
    chunk_ticks: int = 256,
    default_debounce: int = 2,
    family: str = "diurnal",
) -> FaultEvalReport:
    """Generate a kind-labeled cluster, replay it, sweep the detection
    threshold (NAB methodology), and score the alerts.

    Defaults to the detectable point-anomaly kinds; pass
    ``kinds=ANOMALY_KINDS`` to include the hard gradual classes (drift,
    stuck) whose recall is reported per kind. The synthetic noise is AR(1)
    (real node metrics move smoothly tick to tick; white noise at 1s cadence
    would bury any detector of this family in per-tick bucket jitter).
    """
    from rtap_tpu.service.loop import replay_streams

    if cfg is None:
        base = cluster_preset()
        # quality runs use the faithful NuPIC window-mode likelihood
        cfg = dataclasses.replace(
            base, likelihood=dataclasses.replace(base.likelihood, mode="window")
        )
    metrics = ("cpu", "mem", "net", "disk_io", "latency_ms")
    # injections land after probation + settling margin (raises when the
    # streams are too short to evaluate honestly — see safe_inject_frac)
    frac = cfg.likelihood.safe_inject_frac(length)
    scfg = SyntheticStreamConfig(
        length=length, cadence_s=1.0, n_anomalies=2, kinds=kinds,
        anomaly_magnitude=magnitude, noise_phi=0.97, noise_scale=0.5,
        inject_after_frac=frac, family=family,
    )
    streams = [
        generate_stream(
            f"node{i:05d}.{metrics[i % len(metrics)]}",
            dataclasses.replace(scfg, metric=metrics[i % len(metrics)]),
            seed=seed,
        )
        for i in range(n_streams)
    ]
    res = replay_streams(streams, cfg, backend=backend, chunk_ticks=chunk_ticks,
                         threshold=default_threshold)

    # NAB-style sweep, jointly over threshold x debounce. The threshold grid
    # spans the full useful log-likelihood range (probation emits ~0.03;
    # 0.97 is the top of the log scale) — a narrow grid can miss the optimum
    # NAB's sweeper would find (round-2 verdict weak #4). Debounce (alert
    # only after d consecutive hit ticks — the service's StreamGroup
    # semantics) attacks episode precision: false episodes are dominated by
    # 1-2-tick likelihood flickers while injected faults persist. The
    # service operating point is always included so at_best can never be
    # worse than at_default.
    grid = np.union1d(np.arange(0.05, 0.96, 0.02), [default_threshold])
    debounces = sorted({1, 2, 3, 4, default_debounce})
    best = (None, -1.0, None, None, None)  # (thr, f1, per_kind, overall, d)
    # per-kind threshold study (r3 verdict item 4): for each fault kind, the
    # (threshold, debounce) maximizing the kind's f1 (kind recall against the
    # GLOBAL episode precision — false episodes carry no kind label). A
    # spread of per-kind optima quantifies what a single service threshold
    # costs each kind; the study is analysis-only (runtime can't know kinds).
    kind_best: dict[str, dict] = {}
    for d in debounces:
        for thr in grid:
            al = debounce_mask(res.log_likelihood >= thr, d)
            pk, ov = match_alerts(streams, al, res.timestamps)
            if ov["f1"] > best[1]:
                best = (float(thr), ov["f1"], pk, ov, d)
            for kind, ks in pk.items():
                if not ks.events:
                    continue
                p = ov["precision"]
                kf1 = _f1(p, ks.recall)
                cur = kind_best.get(kind)
                if cur is None or kf1 > cur["f1"]:
                    kind_best[kind] = {
                        "threshold": round(float(thr), 3), "debounce": d,
                        "f1": round(kf1, 4), "recall": round(ks.recall, 4),
                        "precision_global": round(p, 4),
                    }
    _, _, best_pk, best_overall, best_d = best
    _, default_overall = match_alerts(
        streams,
        debounce_mask(res.log_likelihood >= default_threshold, default_debounce),
        res.timestamps,
    )
    return FaultEvalReport(
        n_streams=n_streams,
        n_ticks=length,
        default_threshold=default_threshold,
        best_threshold=best[0],
        at_default=default_overall,
        at_best=best_overall,
        per_kind={k: v.summary() for k, v in best_pk.items() if v.events},
        throughput=res.throughput,
        default_debounce=default_debounce,
        best_debounce=best_d,
        kind_thresholds=kind_best,
    )


def main() -> None:
    from rtap_tpu.utils.platform import maybe_force_cpu

    maybe_force_cpu()
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--streams", type=int, default=120)
    ap.add_argument("--length", type=int, default=1500)
    ap.add_argument("--magnitude", type=float, default=6.0)
    ap.add_argument("--family", choices=("diurnal", "heldout"),
                    default="diurnal",
                    help="signal family: 'heldout' is the external-"
                         "validation world (heavy-tailed bursty noise, "
                         "trend, unlabeled regime switches) no config was "
                         "tuned on")
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--all-kinds", action="store_true",
                    help="include the hard gradual kinds (drift, stuck)")
    ap.add_argument("--backend", default="tpu")
    ap.add_argument("--threshold", type=float, default=0.5)
    ap.add_argument("--debounce", type=int, default=2,
                    help="service debounce (consecutive hit ticks) for the "
                         "at_default operating point")
    ap.add_argument("--perm-bits", type=int, default=None, choices=(0, 8, 16),
                    help="override the cluster preset's permanence domain "
                         "(compression quality comparison, models/perm.py)")
    ap.add_argument("--likelihood", choices=("window", "streaming"), default="window",
                    help="likelihood mode for the evaluated config: 'window' "
                         "= the faithful NuPIC rolling window (the default "
                         "quality-comparison config), 'streaming' = the "
                         "preset's at-scale EMA mode — measured BETTER on "
                         "episode precision (reports/quality_study.json)")
    ap.add_argument("--learning-period", type=int, default=None,
                    help="override likelihood probation length (the measured "
                         "precision lever: false episodes cluster in the "
                         "post-probation maturity window)")
    ap.add_argument("--learn-every", type=int, default=1,
                    help="learning cadence (ModelConfig.learn_every): learn "
                         "on every k-th tick after --learn-full-until. The "
                         "single-chip throughput lever (SCALING.md r4 "
                         "silicon A/B: learning = ~85%% of the step); this "
                         "flag measures its detection-quality price")
    ap.add_argument("--learn-full-until", type=int, default=None,
                    help="ticks of full-rate learning before the cadence "
                         "kicks in (default: the likelihood "
                         "learning_period, the Gaussian-fit window)")
    ap.add_argument("--learn-burst", type=int, default=1,
                    help="burst shape of the thinned cadence: learn B "
                         "CONSECUTIVE ticks of every k*B (same average "
                         "cost as --learn-every alone; preserves the "
                         "temporal adjacency TM sequence learning needs)")
    ap.add_argument("--out", default=None, help="write the JSON report here")
    args = ap.parse_args()

    base = cluster_preset(**({"perm_bits": args.perm_bits} if args.perm_bits is not None else {}))
    cfg = dataclasses.replace(base, likelihood=dataclasses.replace(
        base.likelihood, mode=args.likelihood))
    if args.learning_period is not None:
        # shared helper: keeps the cadence's full-rate window aligned and
        # enforces the replace-before-with_learn_every ordering
        cfg = cfg.with_learning_period(args.learning_period)
    if args.learn_every != 1 or args.learn_full_until is not None \
            or args.learn_burst != 1:
        # shared policy with the operator CLI (ModelConfig.with_learn_every):
        # invalid k fails loudly; default full-rate window = learning_period
        cfg = cfg.with_learn_every(args.learn_every, args.learn_full_until,
                                   burst=args.learn_burst)
    kinds = ANOMALY_KINDS if args.all_kinds else ("spike", "level_shift", "dropout")
    report = run_fault_eval(
        n_streams=args.streams, length=args.length, kinds=kinds,
        magnitude=args.magnitude, cfg=cfg, backend=args.backend,
        default_threshold=args.threshold, default_debounce=args.debounce,
        seed=args.seed, family=args.family,
    )
    print(report.to_json())
    if args.out:
        with open(args.out, "w") as f:
            f.write(report.to_json())
        print(f"report written to {args.out}", file=sys.stderr)


if __name__ == "__main__":
    main()
