"""NAB-style evaluation for the ISSUE 9 workload modalities.

The scalar path is quality-gated by eval/fault_eval.py; this module asks
the same question of the NEW encoder families so they ship measured, not
assumed:

- **categorical** — event-class streams (skewed steady distribution,
  anomalies = bursts of a NOVEL class) scored through the categorical
  encoder preset. A scalar RDSE sees a novel id as "one bucket further"
  (overlap decays linearly); the categorical encoder sees a disjoint
  representation — the modality this family exists for.
- **log_template** — seeded log-line streams through the drain-style
  template miner (rtap_tpu/ingest/templates.py) into template-id
  streams, scored the same way: the log-burst workload of ROADMAP 4.
- **composite_vs_scalar** — the regression gate: the composite
  multi-field preset ({value, delta, event-class} + hour-of-day) scored
  on SCALAR faults must reach an F1 no worse than the scalar-only
  baseline on the same faults (threshold/debounce swept per config, NAB
  methodology) — fusing extra fields must not cost the scalar component
  its detection quality.

Scoring reuses fault_eval's machinery verbatim (debounce_mask,
match_alerts, threshold x debounce sweep), so "F1" means the same thing
in every committed artifact. The committed artifact is
``reports/workloads_r09.json``:

    python -m rtap_tpu.eval.workload_eval --out reports/workloads_r09.json
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys

import numpy as np

from rtap_tpu.config import (
    CompositeEncoderConfig,
    FieldSpec,
    ModelConfig,
    categorical_preset,
    cluster_preset,
    composite_preset,
)
from rtap_tpu.data.synthetic import (
    LabeledStream,
    SyntheticStreamConfig,
    generate_categorical_stream,
    generate_log_stream,
    generate_stream,
)
from rtap_tpu.eval.fault_eval import _f1, debounce_mask, match_alerts


def _sweep(streams, loglik: np.ndarray, timestamps: np.ndarray,
           default_threshold: float = 0.5,
           default_debounce: int = 2) -> dict:
    """fault_eval's NAB sweep, compacted: joint threshold x debounce grid,
    reporting the F1-optimal and service-default operating points."""
    grid = np.union1d(np.arange(0.05, 0.96, 0.02), [default_threshold])
    best = {"f1": -1.0}
    for d in sorted({1, 2, 3, default_debounce}):
        for thr in grid:
            al = debounce_mask(loglik >= thr, d)
            _pk, ov = match_alerts(streams, al, timestamps)
            if ov["f1"] > best["f1"]:
                best = {"threshold": round(float(thr), 3), "debounce": d,
                        **ov}
    _pk, default_ov = match_alerts(
        streams,
        debounce_mask(loglik >= default_threshold, default_debounce),
        timestamps)
    return {"at_best": best,
            "at_default": {"threshold": default_threshold,
                           "debounce": default_debounce, **default_ov}}


def _short_probation(cfg: ModelConfig, learning_period: int,
                     estimation: int = 60) -> ModelConfig:
    return dataclasses.replace(cfg, likelihood=dataclasses.replace(
        cfg.likelihood, learning_period=learning_period,
        estimation_samples=estimation))


def run_categorical_eval(n_streams: int = 12, length: int = 900,
                         cfg: ModelConfig | None = None,
                         backend: str = "cpu", seed: int = 11,
                         chunk_ticks: int = 128) -> dict:
    """Categorical modality: novel-class bursts vs the categorical preset."""
    from rtap_tpu.service.loop import replay_streams

    cfg = cfg or _short_probation(categorical_preset(), 300, 100)
    frac = cfg.likelihood.safe_inject_frac(length)
    scfg = SyntheticStreamConfig(length=length, cadence_s=1.0,
                                 n_anomalies=2, inject_after_frac=frac)
    streams = [
        generate_categorical_stream(f"ev{i:04d}.class", scfg, seed=seed)
        for i in range(n_streams)
    ]
    res = replay_streams(streams, cfg, backend=backend,
                         chunk_ticks=chunk_ticks)
    return {"modality": "categorical", "n_streams": n_streams,
            "n_ticks": length,
            **_sweep(streams, res.log_likelihood, res.timestamps),
            "throughput": res.throughput}


def run_log_template_eval(n_streams: int = 12, length: int = 900,
                          cfg: ModelConfig | None = None,
                          backend: str = "cpu", seed: int = 11,
                          chunk_ticks: int = 128) -> dict:
    """Log-template modality: seeded line streams -> drain miner ->
    template-id streams -> the categorical preset. One miner PER STREAM
    (each node's log vocabulary is its own), mirroring the serve-side
    ingest-boundary deployment."""
    from rtap_tpu.ingest.templates import TemplateMiner
    from rtap_tpu.service.loop import replay_streams

    cfg = cfg or _short_probation(categorical_preset(), 300, 100)
    frac = cfg.likelihood.safe_inject_frac(length)
    scfg = SyntheticStreamConfig(length=length, cadence_s=1.0,
                                 n_anomalies=2, inject_after_frac=frac)
    miners = []
    streams = []
    for i in range(n_streams):
        log = generate_log_stream(f"node{i:04d}.log", scfg, seed=seed)
        miner = TemplateMiner()
        vals = np.asarray(miner.encode_values(log.lines), np.float32)
        miners.append(miner)
        streams.append(LabeledStream(log.stream_id, log.timestamps, vals,
                                     log.windows, log.events))
    res = replay_streams(streams, cfg, backend=backend,
                         chunk_ticks=chunk_ticks)
    return {"modality": "log_template", "n_streams": n_streams,
            "n_ticks": length,
            "miner": {
                "templates_max": max(m.n_templates() for m in miners),
                "overflow": sum(m.overflow for m in miners),
            },
            **_sweep(streams, res.log_likelihood, res.timestamps),
            "throughput": res.throughput}


def run_composite_vs_scalar(n_streams: int = 8, length: int = 900,
                            backend: str = "cpu", seed: int = 11,
                            chunk_ticks: int = 128,
                            scalar_cfg: ModelConfig | None = None,
                            composite_cfg: ModelConfig | None = None) -> dict:
    """The regression gate: identical scalar faults scored by (a) the
    scalar-only cluster family and (b) the composite preset with the
    value routed to its value+delta fields and a quiet event-class
    column — composite F1 on the scalar component must be no worse.

    Wire convention for delta fields (docs/WORKLOADS.md): the field
    carries the SAME wire value as its source field; the encoder
    differentiates internally against its per-stream ``enc_prev`` state.
    """
    from rtap_tpu.service.loop import replay_streams

    scalar_cfg = scalar_cfg or _short_probation(cluster_preset(), 300, 100)
    composite_cfg = composite_cfg or _short_probation(
        composite_preset(), 300, 100)
    frac = max(scalar_cfg.likelihood.safe_inject_frac(length),
               composite_cfg.likelihood.safe_inject_frac(length))
    scfg = SyntheticStreamConfig(
        length=length, cadence_s=1.0, n_anomalies=2,
        kinds=("spike", "level_shift", "dropout"),
        anomaly_magnitude=6.0, noise_phi=0.97, noise_scale=0.5,
        inject_after_frac=frac)
    scalar_streams = [
        generate_stream(f"node{i:04d}.cpu", scfg, seed=seed)
        for i in range(n_streams)
    ]
    res_scalar = replay_streams(scalar_streams, scalar_cfg, backend=backend,
                                chunk_ticks=chunk_ticks)
    scalar = _sweep(scalar_streams, res_scalar.log_likelihood,
                    res_scalar.timestamps)

    # the composite run scores the SAME faults: value + delta fields both
    # carry the scalar wire value; the event-class column is quiet
    # (steady class 0 with a rare benign class 1 — a status field's
    # realistic shape, and a precision hazard the gate must absorb)
    rng = np.random.default_rng(seed)
    comp_streams = []
    for s in scalar_streams:
        ev = (rng.random(length) < 0.02).astype(np.float32)
        comp_streams.append(LabeledStream(
            s.stream_id, s.timestamps,
            np.stack([s.values, s.values, ev], axis=1),
            s.windows, s.events))
    res_comp = replay_streams(comp_streams, composite_cfg, backend=backend,
                              chunk_ticks=chunk_ticks)
    comp = _sweep(comp_streams, res_comp.log_likelihood, res_comp.timestamps)
    gate = comp["at_best"]["f1"] >= scalar["at_best"]["f1"] - 1e-9
    return {"modality": "composite_vs_scalar", "n_streams": n_streams,
            "n_ticks": length,
            "scalar": scalar, "composite": comp,
            "scalar_f1": scalar["at_best"]["f1"],
            "composite_f1": comp["at_best"]["f1"],
            "gate_composite_no_worse": bool(gate)}


def tiny_eval_configs() -> tuple[ModelConfig, ModelConfig, ModelConfig]:
    """Miniature (categorical, scalar, composite) configs for the tier-1
    tests: same families, 32-column widths, short probation — seconds,
    not minutes, on the 1-core CI host."""
    from rtap_tpu.config import scaled_cluster_preset

    tiny = _short_probation(scaled_cluster_preset(32), 40, 20)
    cat = dataclasses.replace(
        tiny, composite=CompositeEncoderConfig(fields=(
            FieldSpec(name="event_class", kind="categorical", size=64,
                      active_bits=7),)))
    comp = dataclasses.replace(
        tiny, n_fields=3,
        composite=CompositeEncoderConfig(fields=(
            FieldSpec(name="value", kind="rdse", size=64, active_bits=7,
                      resolution=0.5),
            FieldSpec(name="delta", kind="delta", size=64, active_bits=7,
                      resolution=0.5),
            FieldSpec(name="event_class", kind="categorical", size=64,
                      active_bits=7),
        )))
    return cat, tiny, comp


def main() -> int:
    from rtap_tpu.utils.platform import maybe_force_cpu

    maybe_force_cpu()
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--streams", type=int, default=12)
    ap.add_argument("--length", type=int, default=900)
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--backend", default="cpu",
                    help="cpu = the oracle (no accelerator needed; the "
                         "committed artifact's config); tpu = the device "
                         "path (bit-identical by the parity suite)")
    ap.add_argument("--out", default=None, help="report JSON path")
    args = ap.parse_args()

    report = {
        "round": "r09",
        "seed": args.seed,
        "backend": args.backend,
        "categorical": run_categorical_eval(
            n_streams=args.streams, length=args.length,
            backend=args.backend, seed=args.seed),
        "log_template": run_log_template_eval(
            n_streams=args.streams, length=args.length,
            backend=args.backend, seed=args.seed),
        "composite_vs_scalar": run_composite_vs_scalar(
            n_streams=max(4, args.streams * 2 // 3), length=args.length,
            backend=args.backend, seed=args.seed),
    }
    ok = report["composite_vs_scalar"]["gate_composite_no_worse"]
    report["verified"] = bool(ok)
    print(json.dumps(report))
    if args.out:
        import os

        os.makedirs(os.path.dirname(os.path.abspath(args.out)), exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
        print(f"report written to {args.out}", file=sys.stderr)
    if not ok:
        print("FAIL: composite F1 below the scalar-only baseline on the "
              "scalar component", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
