"""rtap_tpu — TPU-native real-time anomaly prediction for distributed systems.

A ground-up JAX/XLA rebuild of the capabilities of
`atambol/Real-time-anomaly-prediction-in-distributed-systems` (an HTM-based
per-node-metric anomaly pipeline built on NuPIC — see SURVEY.md for the full
reconstruction): RDSE encoding -> Spatial Pooler -> Temporal Memory -> raw
anomaly score on device, rolling-Gaussian anomaly likelihood + alerting on
host, vmapped/sharded over thousands of concurrent metric streams.

Layout:
    config      typed model/runtime configs with NAB-preset defaults
    utils       deterministic hashing, RNG schedules, logging
    data        synthetic cluster generator, NAB-format corpus IO, stream sources
    nab         NAB scorer/sweeper/runner (public NAB scoring spec)
    models      CPU oracle (numpy, the semantic spec) + HTMModel/AnomalyDetector factory
    ops         TPU kernels: SP, TM, fused step (XLA-compiled JAX)
    parallel    mesh/sharding over the ("streams",) axis, host<->device feed
    service     stream registry, alerting, checkpointing
"""

__version__ = "0.1.0"

from rtap_tpu.config import (  # noqa: F401
    DateConfig,
    LikelihoodConfig,
    ModelConfig,
    RDSEConfig,
    SPConfig,
    TMConfig,
    cluster_preset,
    nab_preset,
)
