"""Host-side predictive-horizon tracking (ISSUE 16).

The fused step's on-device predict reducer (ops/predict_tpu.py) hands
the loop one compact per-stream leaf per (group, tick): horizon-old
predicted-column overlap vs the tick's actual input, the divergence
EWMA, predicted sparsity. This package turns those into LEAD TIME:

- :class:`~rtap_tpu.predict.horizon.PredictTracker` folds the leaves
  into per-stream divergence trajectories and emits edge-triggered
  ``precursor`` event lines (stable alert ids, flight-recorder dumps)
  BEFORE the anomaly score spikes;
- :class:`~rtap_tpu.predict.blast.BlastFuser` fuses precursors with the
  correlate/ topology so a cascading fault pages ONCE, at the first
  node, with the predicted blast radius — not once per stream as the
  fault rolls downstream.

See docs/PREDICT.md for the operator surface.
"""

from rtap_tpu.predict.blast import BlastFuser
from rtap_tpu.predict.horizon import PredictTracker

__all__ = ["PredictTracker", "BlastFuser"]
