"""Per-stream divergence tracking -> ``precursor`` events (ISSUE 16).

The on-device predict reducer (ops/predict_tpu.py) scores, every tick,
how well the TM's horizon-old forward model predicted the columns that
actually fired — and folds the miss rate into a per-stream EWMA. A
stream in a learned stable regime holds that EWMA low; a slow pre-fault
drift (resource-exhaustion ramps, degrading dependencies) erodes the
TM's forward model ticks before the anomaly score itself spikes.

:class:`PredictTracker` is the host side: it folds the per-(group,
tick) leaves (``StreamGroup.last_predict``) into per-stream divergence
trajectories and pages with the HealthTracker discipline —

- **warm-up gating**: a stream must accumulate ``warmup_ticks`` scored
  samples before it may alarm (the device already holds scoring back a
  full horizon after (re)init; this is the host-side settling window on
  top);
- **debounce**: the EWMA must sit at/above ``threshold`` for
  ``min_ticks`` CONSECUTIVE scored ticks (one noisy excursion is not a
  precursor);
- **edge-triggered hysteresis**: one ``precursor`` event on entry; the
  stream re-arms only after its EWMA falls below ``rearm_frac *
  threshold`` (an EWMA oscillating at the line must not storm the alert
  stream).

Each event carries a stable ``alert_id`` (``precursor:<stream>:<tick>``
— a journal replay reproduces it bit-for-bit, so resume suppression
works by construction), the predicted lead time in ticks, and requests
a flight-recorder postmortem dump (a precursor is a black-box moment —
the window that led here is exactly what the operator wants captured).

When a :class:`~rtap_tpu.predict.blast.BlastFuser` is attached, every
precursor is also offered to it; a returned ``predicted_incident``
event is emitted through the same sink/suppression path (the fuser
itself stays pure — it decides, the tracker emits).

Thread model: :meth:`fold` runs on the serve loop thread; the obs HTTP
server calls :meth:`snapshot`/:meth:`scorecard` concurrently. Unlike
the HealthTracker (torn reads by documented contract), both sides hold
one reentrant lock — a snapshot is a consistent cut, and the lock is
uncontended on the hot path (one fold per collected chunk per group).
"""

from __future__ import annotations

import threading
import time

import numpy as np

from rtap_tpu.obs.metrics import TelemetryRegistry, get_registry

__all__ = ["PredictTracker", "PREDICT_EVENTS"]

#: predictive event vocabulary (docs/TELEMETRY.md, docs/PREDICT.md)
PREDICT_EVENTS = ("precursor", "predicted_incident")


def _is_pad(stream_id) -> bool:
    """Pad slots never page (they are fed NaN so they never score, but a
    just-released slot's id must not leak into an in-flight event)."""
    return stream_id is None or str(stream_id).startswith("__pad")


class _GroupPredict:
    """One group's folded predictor state (bounded: a few [G] vectors)."""

    __slots__ = ("ticks", "run", "alarmed", "samples", "ewma", "overlap",
                 "col_frac", "last_tick", "ids")

    def __init__(self, G: int):
        self.ticks = 0                          # predict leaves folded
        self.run = np.zeros(G, np.int64)        # consecutive hot scored ticks
        self.alarmed = np.zeros(G, bool)        # edge-trigger latch
        self.samples = np.zeros(G, np.int64)    # scored ticks seen (warm-up)
        self.ewma = np.full(G, np.nan, np.float64)     # latest divergence
        self.overlap = np.full(G, np.nan, np.float64)  # latest overlap
        self.col_frac = np.full(G, np.nan, np.float64)
        self.last_tick = -1
        self.ids: list = [None] * G             # latest slot -> stream id


class PredictTracker:
    """Folds per-(group, tick) predict leaves into lead-time precursors.

    Construction registers the fleet gauges once; :meth:`fold` is the
    only hot-path call (one per collected chunk per group — a few numpy
    ops over [T, G] leaves, self-benchmarked by
    ``obs/selfbench.measure_predict`` and gated <= 1% of the tick
    budget by ``bench.py --obs-bench``).

    `sink` (callable taking one JSON-able event dict), `flight`
    (obs.FlightRecorder) and `blast`
    (:class:`~rtap_tpu.predict.blast.BlastFuser`) may be attached after
    construction — ``live_loop`` wires them exactly like the
    HealthTracker's.
    """

    def __init__(self, horizon: int, registry: TelemetryRegistry | None = None,
                 sink=None, flight=None, blast=None,
                 threshold: float = 0.35,
                 min_ticks: int = 12,
                 warmup_ticks: int = 32,
                 rearm_frac: float = 0.5):
        if horizon < 1:
            raise ValueError(f"horizon must be >= 1; got {horizon}")
        if not (0.0 < threshold <= 1.0):
            raise ValueError(
                f"threshold must be in (0, 1]; got {threshold}")
        if min_ticks < 1:
            raise ValueError(f"min_ticks must be >= 1; got {min_ticks}")
        if warmup_ticks < 0:
            raise ValueError(
                f"warmup_ticks must be >= 0; got {warmup_ticks}")
        if not (0.0 <= rearm_frac < 1.0):
            raise ValueError(
                f"rearm_frac must be in [0, 1); got {rearm_frac}")
        self.horizon = int(horizon)
        self.threshold = float(threshold)
        self.min_ticks = int(min_ticks)
        self.warmup_ticks = int(warmup_ticks)
        self.rearm_frac = float(rearm_frac)
        self.sink = sink
        self.flight = flight
        self.blast = blast
        # fold runs on the serve loop thread; snapshot/scorecard/stats on
        # the obs HTTP thread — one reentrant guard covers both sides
        # (stats -> snapshot -> scorecard nest under the same holder)
        self._lock = threading.RLock()
        self._groups: dict[int, _GroupPredict] = {}
        self.events_total = 0
        self.events_suppressed = 0
        self._events_by_kind: dict[str, int] = {}
        #: armed replay-suppression ids (service/alerts.scan_event_ids):
        #: a journal replay reproduces each event bit-for-bit; ids already
        #: on disk update state but skip the sink/flight re-emission
        self._suppress: set[str] = set()
        reg = registry or get_registry()
        self._obs_events = {
            kind: reg.counter(
                "rtap_obs_predict_events_total",
                "predictive events by kind (precursor / "
                "predicted_incident)", event=kind)
            for kind in PREDICT_EVENTS
        }
        self._obs_ewma_max = reg.gauge(
            "rtap_obs_predict_miss_ewma_max",
            "worst per-stream predicted->actual miss EWMA across the "
            "fleet (the divergence trajectory precursors page on)")
        self._obs_overlap = reg.gauge(
            "rtap_obs_predict_overlap_mean",
            "fleet mean horizon-old predicted-column overlap at the "
            "latest folded tick (scored streams only)")
        self._obs_alarmed = reg.gauge(
            "rtap_obs_predict_streams_alarmed",
            "streams currently inside a precursor alarm (edge-triggered; "
            "re-arm below rearm_frac * threshold)")
        self._obs_fold_seconds = reg.histogram(
            "rtap_obs_predict_fold_seconds",
            "wall seconds per PredictTracker.fold call (one per collected "
            "chunk per group; gated <= 1% of the tick budget by "
            "bench.py --obs-bench)")

    # ---------------------------------------------------------- resume --
    def arm_suppression(self, ids) -> None:
        """Arm replay suppression for already-on-disk event ids (the
        serve resume path scans the alert sink tail with
        service/alerts.scan_event_ids and hands the ids here): a
        replayed fold still updates tracker state — the latch positions
        must match the pre-crash process — but the duplicate event line
        is not re-emitted."""
        with self._lock:
            self._suppress.update(str(i) for i in ids)

    # ------------------------------------------------------------ fold --
    def fold(self, group: int, leaves: dict, tick: int = -1,
             ids=None) -> None:
        """Fold one collected chunk's predict leaves ([T, G] arrays from
        ``StreamGroup.last_predict``) into group `group`'s trajectories
        and run the per-stream edge triggers once per tick row.

        `tick` is the LAST tick of the chunk (row i happened at
        ``tick - (T - 1 - i)``); `ids` the slot -> stream-id mapping
        (length G; pads None or pad-prefixed — they never page)."""
        with self._lock:
            self._fold_locked(group, leaves, tick, ids)

    def _fold_locked(self, group: int, leaves: dict, tick: int,
                     ids) -> None:
        t0 = time.perf_counter()
        scored = np.atleast_2d(np.asarray(leaves["scored"], bool))
        ewma = np.atleast_2d(np.asarray(leaves["miss_ewma"], np.float64))
        overlap = np.atleast_2d(np.asarray(leaves["overlap"], np.float64))
        col_frac = np.atleast_2d(
            np.asarray(leaves["pred_col_frac"], np.float64))
        T, G = scored.shape
        g = self._groups.get(group)
        if g is None or len(g.ids) != G:
            g = self._groups[group] = _GroupPredict(G)
        if ids is not None:
            g.ids = list(ids)
        thr = self.threshold
        for i in range(T):
            g.ticks += 1
            row_tick = int(tick - (T - 1 - i)) if tick >= 0 else -1
            s = scored[i]
            e = ewma[i]
            hot = s & np.isfinite(e) & (e >= thr)
            # consecutive-hot run: a scored cool tick resets; an
            # UNSCORED tick (source gap) holds the run rather than
            # resetting — an outage must not silently disarm a ramp
            g.run = np.where(hot, g.run + 1, np.where(s, 0, g.run))
            g.samples += s
            fire = (~g.alarmed) & (g.run >= self.min_ticks) \
                & (g.samples >= self.warmup_ticks)
            rearm = g.alarmed & s & np.isfinite(e) \
                & (e < self.rearm_frac * thr)
            for slot in np.nonzero(fire)[0]:
                sid = g.ids[slot] if slot < len(g.ids) else None
                if _is_pad(sid):
                    continue
                g.alarmed[slot] = True
                self._precursor(group, int(slot), str(sid), row_tick,
                                float(e[slot]), float(overlap[i, slot]))
            g.alarmed[rearm] = False
            g.run[rearm] = 0
        # latest-scored adoption (the HealthTracker discipline): an
        # all-NaN outage row must not zero the scorecard
        live = np.nonzero(scored.any(-1))[0]
        g.last_tick = int(tick)
        if live.size:
            i = int(live[-1])
            s = scored[i]
            g.ewma = np.where(s, ewma[i], g.ewma)
            g.overlap = np.where(s, overlap[i], g.overlap)
            g.col_frac = np.where(s, col_frac[i], g.col_frac)
        self._set_fleet_gauges()
        self._obs_fold_seconds.observe(time.perf_counter() - t0)

    # ------------------------------------------------- event emission --
    def _precursor(self, group: int, slot: int, stream: str, tick: int,
                   ewma: float, overlap: float) -> None:
        ev = {
            "event": "precursor",
            "tick": int(tick),
            "group": int(group),
            "slot": int(slot),
            "stream": stream,
            "alert_id": f"precursor:{stream}:{tick}",
            "miss_ewma": round(ewma, 6),
            "overlap": None if not np.isfinite(overlap)
            else round(overlap, 6),
            "threshold": self.threshold,
            "horizon_ticks": self.horizon,
            # the divergence was measured against a prediction captured
            # a full horizon ago: the drift is at least that old, so the
            # page leads the score spike by up to k ticks
            "predicted_lead_ticks": self.horizon,
        }
        self._emit(ev)
        if self.blast is not None:
            inc = self.blast.precursor(stream, tick, ev)
            if inc is not None:
                self._emit(inc)

    def _emit(self, ev: dict) -> None:
        kind = ev["event"]
        aid = ev.get("alert_id")
        if aid is not None and aid in self._suppress:
            # replay of an already-delivered event: state latched above,
            # line already on disk — do not page twice
            self._suppress.discard(aid)
            self.events_suppressed += 1
            return
        self.events_total += 1
        self._events_by_kind[kind] = self._events_by_kind.get(kind, 0) + 1
        counter = self._obs_events.get(kind)
        if counter is not None:
            counter.inc()
        if self.flight is not None:
            # a precursor is a black-box moment like a health incident:
            # capture the window that led here
            self.flight.record_event(ev)
            self.flight.request_dump(kind, ev.get("tick", -1))
        if self.sink is not None:
            self.sink(ev)

    def _set_fleet_gauges(self) -> None:
        gs = list(self._groups.values())
        if not gs:
            return
        ewmas = np.concatenate([g.ewma for g in gs])
        overlaps = np.concatenate([g.overlap for g in gs])
        self._obs_ewma_max.set(
            float(np.nanmax(ewmas)) if np.isfinite(ewmas).any() else 0.0)
        self._obs_overlap.set(
            float(np.nanmean(overlaps))
            if np.isfinite(overlaps).any() else 0.0)
        self._obs_alarmed.set(int(sum(int(g.alarmed.sum()) for g in gs)))

    # -------------------------------------------------------- surface --
    def scorecard(self, gi: int) -> dict:
        """One group's JSON scorecard (the /predict per-group unit)."""
        with self._lock:
            return self._scorecard_locked(gi)

    def _scorecard_locked(self, gi: int) -> dict:
        g = self._groups[gi]
        fin = np.isfinite(g.ewma)
        alarmed = [
            {"slot": int(s), "stream": None if _is_pad(g.ids[s]) else
             str(g.ids[s]), "miss_ewma": round(float(g.ewma[s]), 6)
             if np.isfinite(g.ewma[s]) else None}
            for s in np.nonzero(g.alarmed)[0]
        ]
        return {
            "group": int(gi),
            "ticks": g.ticks,
            "last_tick": g.last_tick,
            "streams_scored": int(fin.sum()),
            "miss_ewma": {
                "max": round(float(np.nanmax(g.ewma)), 6)
                if fin.any() else None,
                "mean": round(float(np.nanmean(g.ewma)), 6)
                if fin.any() else None,
            },
            "overlap_mean": round(float(np.nanmean(g.overlap)), 6)
            if np.isfinite(g.overlap).any() else None,
            "pred_col_frac_mean": round(float(np.nanmean(g.col_frac)), 6)
            if np.isfinite(g.col_frac).any() else None,
            "alarmed": alarmed,
            "verdict": "ok" if not alarmed else "precursor",
        }

    def snapshot(self) -> dict:
        """The GET /predict body: fleet rollup + per-group scorecards.
        Also embedded in postmortem bundle summaries (obs/flight.py)."""
        with self._lock:
            return self._snapshot_locked()

    def _snapshot_locked(self) -> dict:
        gids = sorted(list(self._groups))
        groups = [self._scorecard_locked(gi) for gi in gids]
        attention = [g["group"] for g in groups if g["verdict"] != "ok"]
        maxes = [g["miss_ewma"]["max"] for g in groups
                 if g["miss_ewma"]["max"] is not None]
        out = {
            "fleet": {
                "groups": len(groups),
                "ticks_folded": sum(g["ticks"] for g in groups),
                "horizon_ticks": self.horizon,
                "threshold": self.threshold,
                "miss_ewma_max": max(maxes) if maxes else None,
                "streams_alarmed": sum(len(g["alarmed"]) for g in groups),
                "groups_attention": attention,
                "events_total": self.events_total,
                "events_by_kind": dict(sorted(self._events_by_kind.items())),
                "verdict": "ok" if not attention else "precursor",
            },
            "groups": groups,
        }
        if self.blast is not None:
            out["blast"] = self.blast.snapshot()
        return out

    def stats(self) -> dict:
        """End-of-run accounting for the loop's stats dict (compact)."""
        with self._lock:
            fleet = self._snapshot_locked()["fleet"] \
                if self._groups else {}
            return {
                "groups": len(self._groups),
                "ticks_folded": sum(
                    g.ticks for g in list(self._groups.values())),
                "horizon_ticks": self.horizon,
                "events": dict(sorted(self._events_by_kind.items())),
                "events_suppressed": self.events_suppressed,
                **({"verdict": fleet.get("verdict"),
                    "miss_ewma_max": fleet.get("miss_ewma_max"),
                    "streams_alarmed": fleet.get("streams_alarmed")}
                   if fleet else {}),
            }
