"""Precursor x topology fusion -> ``predicted_incident`` (ISSUE 16).

A cascading fault — database brown-out rolling into its web tier — is
N per-stream precursors spread over the lag between nodes. Paging N
times defeats the point of predicting; paging after the Nth defeats the
LEAD. :class:`BlastFuser` fuses precursors with the correlate/
:class:`~rtap_tpu.correlate.topology.TopologyMap`: the FIRST precursor
in a topology cluster emits ONE ``predicted_incident`` event carrying
the cluster's full node set as the *predicted* blast radius — the
operator is paged at the first node, told which nodes the fault will
reach, before the downstream nodes fall over (eval/fault_eval.py's
cascade scenario scores exactly this).

Later precursors inside the quiescence window attach to the open
incident silently (their per-stream ``precursor`` lines already tell
that story); the window closes after ``window_ticks`` without a new
member, re-arming the cluster. All decisions are pure functions of
(stream, tick) — a journal replay reproduces every incident id
bit-for-bit, which is what makes resume suppression work.

The fuser does not emit: :meth:`precursor` RETURNS the incident event
(or None) and the owning
:class:`~rtap_tpu.predict.horizon.PredictTracker` pushes it through its
own sink/flight/suppression path — one emission discipline, not two.

The predicted radius is every node DECLARED in the cluster (spec
topologies) plus every node actually seen streaming into it (covers
``--topology infer``, where nothing is declared up front); `seed_streams`
pre-registers the fleet's ids at construction so the radius is complete
from the first page, not grown as precursors arrive.
"""

from __future__ import annotations

__all__ = ["BlastFuser"]


class _Cluster:
    __slots__ = ("first_tick", "last_tick", "first_stream", "streams",
                 "precursors", "incident_id")

    def __init__(self, tick: int, stream: str):
        self.first_tick = int(tick)
        self.last_tick = int(tick)
        self.first_stream = stream
        self.streams: set[str] = {stream}
        self.precursors: list[str] = []
        self.incident_id = ""


class BlastFuser:
    """Fuse per-stream precursors into one page per topology cluster.

    `topology` is a correlate/ TopologyMap (spec or infer); `window_ticks`
    the quiescence horizon — a cluster with no new precursor for that
    many ticks closes its incident and may page again; `seed_streams`
    optionally pre-registers the fleet's stream ids so inferred
    clusters know their full node membership before the first page.
    """

    def __init__(self, topology, window_ticks: int = 256,
                 seed_streams=None):
        if window_ticks < 1:
            raise ValueError(
                f"window_ticks must be >= 1; got {window_ticks}")
        self.topology = topology
        self.window_ticks = int(window_ticks)
        #: cluster key -> known member nodes (declared + seen streaming)
        self._nodes: dict[str, set[str]] = {}
        for node in getattr(topology, "services", {}):
            self._nodes.setdefault(
                topology._component_of(topology.service_of(node)),
                set()).add(node)
        if seed_streams is not None:
            self.observe_streams(seed_streams)
        self._open: dict[str, _Cluster] = {}
        self.incidents_total = 0

    def observe_streams(self, stream_ids) -> None:
        """Register streams' nodes into their clusters' known radius
        (idempotent; live_loop calls this on registry version changes so
        claimed streams join the predicted radius too)."""
        for sid in stream_ids:
            sid = str(sid)
            if sid.startswith("__pad"):
                continue
            node = self.topology.node_of(sid)
            self._nodes.setdefault(
                self.topology.cluster_of(sid), set()).add(node)

    def precursor(self, stream: str, tick: int, ev: dict) -> dict | None:
        """Fold one precursor -> a ``predicted_incident`` event for the
        FIRST precursor of a (re)opened cluster window, else None."""
        cluster = self.topology.cluster_of(stream)
        self._nodes.setdefault(cluster, set()).add(
            self.topology.node_of(stream))
        w = self._open.get(cluster)
        if w is not None and tick - w.last_tick > self.window_ticks:
            del self._open[cluster]
            w = None
        if w is not None:
            # attach silently: the cluster already paged this window
            w.last_tick = max(w.last_tick, int(tick))
            w.streams.add(stream)
            w.precursors.append(str(ev.get("alert_id")))
            return None
        w = self._open[cluster] = _Cluster(tick, stream)
        w.precursors.append(str(ev.get("alert_id")))
        w.incident_id = f"predicted_incident:{cluster}:{int(tick)}"
        self.incidents_total += 1
        node = self.topology.node_of(stream)
        return {
            "event": "predicted_incident",
            "tick": int(tick),
            "cluster": cluster,
            "first_stream": stream,
            "first_node": node,
            "alert_id": w.incident_id,
            # the PREDICTED blast radius: every node this cluster can
            # reach, named at the first page — not grown after the fact
            "blast_radius": sorted(self._nodes.get(cluster, {node})),
            "precursors": list(w.precursors),
            "horizon_ticks": ev.get("horizon_ticks"),
            "predicted_lead_ticks": ev.get("predicted_lead_ticks"),
        }

    def snapshot(self) -> dict:
        """Embedded under ``blast`` in the /predict body."""
        open_windows = [
            {
                "cluster": c,
                "incident_id": w.incident_id,
                "first_tick": w.first_tick,
                "last_tick": w.last_tick,
                "first_stream": w.first_stream,
                "streams": len(w.streams),
                "blast_radius": sorted(self._nodes.get(c, set())),
            }
            for c, w in sorted(list(self._open.items()))
        ]
        return {
            "window_ticks": self.window_ticks,
            "clusters_known": len(self._nodes),
            "incidents_total": self.incidents_total,
            "open": open_windows,
        }
