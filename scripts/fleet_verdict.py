"""Shared fleet-plane verdict helpers for the soak/chaos harnesses.

failover_soak.py, crash_soak.py and fleet_chaos.py all close their runs
the same way: read the soak's story back THROUGH the fleet plane
(aggregator events/members/snaps) and judge it against the lease/journal
ground truth. The individual checks — the DOWN→role_changed takeover
anchor walk, the promotion-epoch truth comparison, the death-DOWN vs
stall-flap classifier, the budget-completion tick, the
emitted+suppressed alert reconciliation — were duplicated across the
harnesses (ISSUE 20 satellite); this module is the one copy. Each
helper appends human-readable messages to the caller's ``failures``
list and returns the machine-readable block for the report JSON, so a
harness's ``fleet_verdict`` is a thin composition.
"""

from __future__ import annotations

__all__ = [
    "member_counter",
    "takeover_sequence",
    "promotion_epoch_truth",
    "final_tick_check",
    "reconcile_alert_counters",
    "classify_downs",
]


def member_counter(snap: dict, name: str):
    """A counter's value out of one member's pushed registry snapshot
    (None = the member never pushed that counter)."""
    for row in (snap.get("metrics") or {}).get("metrics", []):
        if row.get("name") == name and row.get("type") == "counter":
            return row.get("value", 0)
    return None


def takeover_sequence(events: list[dict], anchors: list[tuple],
                      failures: list[str]) -> list[dict]:
    """Walk the fleet event log against the scheduled takeovers.

    ``anchors`` is ``[(gone, successor, kind), ...]`` in schedule order;
    each must appear on the plane as the old leader going DOWN
    (staleness — a SIGKILLed process sends no BYE) followed by a
    ``role_changed`` to leader on the successor, with a cursor so the
    sequence is ordered, not just present. Returns one check dict per
    anchor."""
    seq = [e for e in events
           if e["event"] == "down"
           or (e["event"] == "role_changed" and e.get("role") == "leader")]
    checks: list[dict] = []
    cursor = 0
    for gone, succ, kind in anchors:
        j = next((i for i in range(cursor, len(seq))
                  if seq[i]["event"] == "down"
                  and seq[i]["member"] == gone), None)
        if j is None:
            failures.append(f"fleet plane never marked the {kind}ed "
                            f"leader {gone} DOWN")
            checks.append({"kind": kind, "down": gone, "promoted": succ,
                           "ok": False, "why": "no DOWN event"})
            continue
        r = next((i for i in range(j + 1, len(seq))
                  if seq[i]["event"] == "role_changed"
                  and seq[i]["member"] == succ), None)
        if r is None:
            failures.append(
                f"fleet plane saw {gone} DOWN but no role_changed to "
                f"leader on {succ} after it ({kind} round)")
            checks.append({"kind": kind, "down": gone, "promoted": succ,
                           "ok": False, "why": "no role_changed after"})
            continue
        checks.append({
            "kind": kind, "down": gone, "promoted": succ, "ok": True,
            "down_t_unix": seq[j]["t_unix"],
            "promoted_t_unix": seq[r]["t_unix"],
            "lease_epoch": seq[r].get("lease_epoch"),
            "old_lease_epoch": seq[r].get("old_lease_epoch")})
        cursor = r + 1
    return checks


def promotion_epoch_truth(events: list[dict], promotions: list[dict],
                          failures: list[str]) -> list[int]:
    """Every promotion the alert stream (lease/journal truth) recorded
    must have been observed on the plane at the SAME lease epoch, and
    vice versa — the fleet sees unscheduled jitter promotions too.
    Returns the sorted fleet-observed epochs."""
    fleet_epochs = sorted(e.get("lease_epoch") or 0 for e in events
                          if e["event"] == "role_changed"
                          and e.get("role") == "leader")
    truth_epochs = sorted(p.get("epoch") or 0 for p in promotions)
    if fleet_epochs != truth_epochs:
        failures.append(
            f"fleet-observed promotion epochs {fleet_epochs} != "
            f"lease/journal truth {truth_epochs}")
    return fleet_epochs


def final_tick_check(members: list[dict], want_last_tick: int,
                     failures: list[str]) -> int:
    """Budget completion must be visible through the plane alone: the
    final-flush push of the completing leader carries the last GLOBAL
    tick. Returns the max fleet-observed tick."""
    final_tick = max((m.get("tick") if m.get("tick") is not None else -1)
                     for m in members) if members else -1
    if final_tick != want_last_tick:
        failures.append(
            f"fleet plane never observed the budget completing "
            f"(last member tick {final_tick}, want {want_last_tick})")
    return final_tick


def reconcile_alert_counters(snap: dict, stats_alerts, who: str,
                             failures: list[str]) -> dict:
    """Close the alert books through the plane: a stats line's
    ``alerts`` is every crossing the member SCORED; on the plane those
    split into emitted lines (rtap_obs_alerts_total) plus
    resume-suppressed already-delivered ids
    (rtap_obs_alerts_suppressed_total) — the sum must equal it (the
    per-child artifact is corroboration, not source)."""
    emitted = member_counter(snap, "rtap_obs_alerts_total")
    suppressed = member_counter(
        snap, "rtap_obs_alerts_suppressed_total") or 0
    out = {"fleet_emitted": emitted, "fleet_suppressed": suppressed,
           "stats": stats_alerts}
    if emitted is not None and emitted + suppressed != stats_alerts:
        failures.append(
            f"{who}: fleet-pushed emitted+suppressed "
            f"{emitted}+{suppressed} != its stats-line crossing "
            f"count {stats_alerts}")
    return out


def classify_downs(member_events: list[dict]) -> tuple[int, int]:
    """Classify one member's staleness DOWNs by what follows each: the
    next liveness event is ``rejoined`` for a real death (the
    supervisor's replacement re-HELLOs) but ``up`` for a stall flap —
    a checkpoint/compile stall that held the push thread past a tight
    soak-cadence staleness horizon. Flaps are honest evidence of
    stalls, not deaths. Returns ``(death_downs, stall_flaps)``."""
    death_downs = flaps = 0
    for i, e in enumerate(member_events):
        if e["event"] != "down":
            continue
        nxt = next((x["event"] for x in member_events[i + 1:]
                    if x["event"] in ("up", "rejoined", "left")), None)
        if nxt == "rejoined":
            death_downs += 1
        elif nxt == "up":
            flaps += 1
    return death_downs, flaps
