"""Multivariate node-model evaluation -> reports/multivariate_node.json.

Benchmark config 4's quality evidence (SURVEY.md §6: 'multivariate per-node
cpu/mem/net fused RDSE'): N nodes, each a fused 3-field model, node-level
faults either coupled (all metrics degrade together) or single-metric.
Reports per-shape detection rate at a fixed alert threshold plus the
response distribution — the committed artifact behind the documented
trade-off (coupled faults alert; single-field responses dilute ~1/F, see
tests/integration/test_multivariate_node.py).

    RTAP_FORCE_CPU=1 python scripts/node_eval.py --nodes 12
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from rtap_tpu.utils.platform import maybe_force_cpu  # noqa: E402

maybe_force_cpu()

import numpy as np  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--nodes", type=int, default=12)
    ap.add_argument("--length", type=int, default=1400)
    ap.add_argument("--magnitude", type=float, default=6.0)
    ap.add_argument("--threshold", type=float, default=0.15,
                    help="alert threshold on log-likelihood (the fault "
                         "eval's F1-optimal range starts ~0.2; fused "
                         "single-field responses sit slightly below)")
    ap.add_argument("--latency-ticks", type=int, default=15)
    ap.add_argument("--out", default=os.path.join(REPO, "reports", "multivariate_node.json"))
    args = ap.parse_args()

    from rtap_tpu.config import node_preset
    from rtap_tpu.data.synthetic import SyntheticStreamConfig, generate_node
    from rtap_tpu.service.registry import StreamGroup

    cfg = node_preset(3)
    scfg = SyntheticStreamConfig(
        length=args.length, cadence_s=1.0, n_anomalies=3,
        kinds=("spike", "level_shift", "dropout"), anomaly_magnitude=args.magnitude,
        noise_phi=0.97, noise_scale=0.5, inject_after_frac=0.5,
    )
    nodes = [generate_node(f"node{i:05d}", scfg, seed=100 + i) for i in range(args.nodes)]

    # all nodes through ONE vmapped group: values [T, G, 3]
    G, T = len(nodes), args.length
    vals = np.stack([n.values for n in nodes], axis=1)  # [T, G, 3]
    ts = np.stack([n.timestamps for n in nodes], axis=1).astype(np.int64)
    grp = StreamGroup(cfg, [n.node_id for n in nodes], backend="tpu")
    t0 = time.time()
    loglik = np.empty((T, G))
    step = 128
    for lo in range(0, T, step):
        hi = min(lo + step, T)
        _, ll, _ = grp.run_chunk(vals[lo:hi], ts[lo:hi])
        loglik[lo:hi] = ll
    wall = time.time() - t0

    shapes = {"coupled": {"events": 0, "detected": 0, "responses": []},
              "single": {"events": 0, "detected": 0, "responses": []}}
    for g, node in enumerate(nodes):
        for (a, b), touched in zip(node.windows, node.event_metrics):
            kind = "coupled" if len(touched) == len(node.metrics) else "single"
            # window bounds are unix seconds: convert the tick allowance via
            # the stream cadence (ADVICE.md r3 — a non-1s cadence would
            # silently shrink/shift the detection window otherwise)
            w = (node.timestamps >= a) & (
                node.timestamps <= b + args.latency_ticks * scfg.cadence_s
            )
            resp = float(loglik[w, g].max())
            shapes[kind]["events"] += 1
            shapes[kind]["responses"].append(round(resp, 3))
            shapes[kind]["detected"] += int(resp >= args.threshold)

    for v in shapes.values():
        v["recall_at_threshold"] = round(v["detected"] / v["events"], 3) if v["events"] else None
        v["median_response"] = round(float(np.median(v["responses"])), 3) if v["responses"] else None

    report = {
        "config": "node_preset(3) — fused cpu/mem/net per node (benchmark config 4)",
        "nodes": args.nodes, "length": args.length, "magnitude": args.magnitude,
        "threshold": args.threshold, "latency_ticks": args.latency_ticks,
        "wall_s": round(wall, 1),
        "shapes": {k: {kk: vv for kk, vv in v.items() if kk != "responses"}
                   for k, v in shapes.items()},
        "note": ("Coupled node faults perturb all F fields and alert strongly; "
                 "single-field faults show the ~1/F-diluted response (full "
                 "per-metric sensitivity = per-metric streams, generate_cluster)."),
    }
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(report, f, indent=2)
    print(json.dumps(report["shapes"]))


if __name__ == "__main__":
    main()
