"""Crash soak: SIGKILL serve at seeded ticks; prove exactly-once durability.

ISSUE 5 acceptance surface. A deterministic serve child (journal +
periodic checkpoints + dense alert stream) runs under the real
:class:`rtap_tpu.resilience.Supervisor` while a seeded killer SIGKILLs
it at K random ticks (progress observed through the journal itself —
the kill lands at a tick, not a wall time). The supervisor restarts the
child; each restart restores its newest checkpoint, replays the
journaled ticks past it through the normal scoring path, and suppresses
already-delivered alert ids. The run FAILS (exit 5) unless:

- the final model state (every group's checkpoint tree) is
  BIT-IDENTICAL to a fault-free run over the same seeded feed,
- the concatenated alert stream carries exactly the fault-free run's
  ``alert_id`` set — zero duplicated, zero lost — with per-id records
  equal,
- every scheduled kill actually landed (rc -9) and the supervised run
  still completed its total tick budget.

A torn journal tail from a kill mid-write is expected and must never
prevent startup (truncations are counted in the report).

In-tree smoke: K=2 kills at tiny config (tests/integration/
test_durability_soak.py). Silicon: K>=10 at 4096x1024 — the queued
``r8_crash_soak`` hw_session step, which also reports catch-up replay
latency.

Usage: python scripts/crash_soak.py --seed 0 --kills 2 [--streams 6]
       [--group-size 3] [--ticks 96] [--cadence 0.01]
       [--checkpoint-every 7] [--backend cpu] [--threshold -1e9]
       [--journal-fsync os] [--workdir DIR] [--out report.json]
"""

from __future__ import annotations

import argparse
import json
import os
import random
import subprocess
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from rtap_tpu.utils.platform import maybe_force_cpu  # noqa: E402
from scripts.fleet_verdict import (  # noqa: E402
    classify_downs,
    final_tick_check,
    reconcile_alert_counters,
)

VERIFY_FAILED_EXIT = 5
INFRA_FAILED_EXIT = 3


def log(msg: str) -> None:
    print(f"[crash] {msg}", file=sys.stderr, flush=True)


# ---------------------------------------------------------------- child
def run_child(args) -> int:
    """One serve-process lifetime: recover the journal, resume the
    checkpoints, replay, then run the REMAINING ticks of the total
    budget over the seeded deterministic feed. Killed children leave
    their journal/checkpoints/alerts behind; completing children append
    a stats line to --stats-out."""
    maybe_force_cpu()

    import numpy as np

    from rtap_tpu.config import cluster_preset
    from rtap_tpu.resilience import ChaosEngine, ChaosSpec, TickJournal
    from rtap_tpu.resilience.journal import parse_fsync
    from rtap_tpu.service.checkpoint import peek_resume_ticks
    from rtap_tpu.service.loop import live_loop
    from rtap_tpu.service.registry import StreamGroupRegistry

    w = args.workdir
    os.makedirs(w, exist_ok=True)
    policy, every_n = parse_fsync(args.journal_fsync)
    journal = TickJournal(os.path.join(w, "journal"), fsync=policy,
                          fsync_every=every_n)
    ckdir = os.path.join(w, "ck")
    base = max(journal.next_tick, peek_resume_ticks(ckdir))
    n_eff = max(0, args.ticks - base)

    ids = [f"n{i // 3}.m{i % 3}" for i in range(args.streams)]
    reg = StreamGroupRegistry(cluster_preset(), group_size=args.group_size,
                              backend=args.backend,
                              threshold=args.threshold, debounce=1)
    for sid in ids:
        reg.add_stream(sid)
    reg.finalize()

    chaos = None
    if args.spec:
        # the schedule is GLOBAL-tick-indexed; a restarted child shifts
        # it onto its local clock (fired faults drop out — in particular
        # the proc_exit that killed the previous incarnation)
        chaos = ChaosEngine(ChaosSpec.from_file(args.spec).shifted(base))

    fleet_pub = None
    if args.fleet_port:
        # fleet observability plane (ISSUE 19): every incarnation of the
        # supervised child is the SAME fleet member ("serve") — a SIGKILL
        # shows up as staleness DOWN (no BYE), the restart as a rejoin
        # carrying the new resume base as its run_epoch. The parent reads
        # restart evidence through the plane, not per-child artifacts.
        from rtap_tpu.fleet import FleetPublisher

        fleet_pub = FleetPublisher(
            ("127.0.0.1", args.fleet_port), "serve", role="leader",
            run_epoch=base,
            push_interval_s=max(0.02, args.cadence / 2)).start()
        fleet_pub.set_tick_base(base)

    def seeded_row(k: int):
        g = base + k  # the feed depends only on the GLOBAL tick
        rng = np.random.Generator(np.random.Philox(key=(args.seed, g)))
        v = (30 + 5 * rng.random(len(ids))).astype(np.float32)
        if args.spike_every and g % args.spike_every == 0:
            # deterministic anomaly spikes so realistic thresholds see
            # alert traffic too (the floor threshold alerts every tick)
            v[(g // args.spike_every) % len(ids)] += 30.0
        return v, 1_700_000_000 + g

    source = seeded_row
    if args.binary_ingest:
        # route the SAME deterministic rows through the binary ingest
        # path in-process (frames -> walker -> dispatch-table scatter),
        # so the journal takes the raw-FRAME write-ahead path and every
        # kill-9 restart replays THROUGH the frame decode — the ISSUE 7
        # durability soak. Loopback, not a socket: the soak's verdict
        # is bit-identity, which a paced network feeder cannot promise.
        from rtap_tpu.ingest import BinaryBatchSource
        from rtap_tpu.ingest.protocol import data_frame

        bsrc = BinaryBatchSource(reg.slot_map(), port=None)
        bcodes = bsrc._table.codes

        def source(k: int):
            v, ts = seeded_row(k)
            bsrc.feed_frames([data_frame(bcodes, v, ts)])
            return bsrc(k)

        # live_loop journals raw frames when the source exposes them
        source.take_tick_frames = bsrc.take_tick_frames

    # SLO verdict (ISSUE 11): the seeded feed runs on a synthetic epoch,
    # so the wall-clock-anchored detect SLO is meaningless here — the
    # crash soak contracts on per-tick HOST latency instead (docs/SLO.md
    # clock contract). Pure observation: the bit-identity verdict is
    # judged on alert RECORDS, which the tracker never touches.
    latency = slo = None
    if args.slo != "off":
        from rtap_tpu.obs.slo import tick_slo_pair

        latency, slo = tick_slo_pair(args.cadence, args.slo)
        if fleet_pub is not None:
            fleet_pub.attach(latency=latency, slo=slo)
    stats = live_loop(
        source, reg, n_ticks=n_eff, cadence_s=args.cadence,
        alert_path=os.path.join(w, "alerts.jsonl"),
        checkpoint_dir=ckdir, checkpoint_every=args.checkpoint_every,
        journal=journal, chaos=chaos, latency=latency, slo=slo,
        fleet=fleet_pub)
    journal.close()
    if fleet_pub is not None:
        fleet_pub.close()  # final-state flush + orderly BYE
    line = {"base": base, "ran": stats["ticks"],
            "alerts": stats["alerts"],
            "scored": stats["scored"],
            "journal": stats.get("journal", {}),
            "slo": stats.get("slo")}
    if args.stats_out:
        with open(args.stats_out, "a") as f:
            f.write(json.dumps(line) + "\n")
            f.flush()
    print(json.dumps(line))
    return 0


# --------------------------------------------------------------- parent
def child_cmd(args, workdir: str, spec: str | None,
              fleet_port: int = 0) -> list[str]:
    cmd = [sys.executable, os.path.abspath(__file__), "--child",
           "--workdir", workdir, "--seed", str(args.seed),
           "--ticks", str(args.ticks), "--streams", str(args.streams),
           "--group-size", str(args.group_size),
           "--cadence", str(args.cadence),
           "--checkpoint-every", str(args.checkpoint_every),
           "--backend", args.backend, "--threshold", str(args.threshold),
           "--journal-fsync", args.journal_fsync,
           "--spike-every", str(args.spike_every),
           "--stats-out", os.path.join(workdir, "stats.jsonl")]
    if args.slo is not None:
        cmd += ["--slo", args.slo]
    if args.binary_ingest:
        cmd.append("--binary-ingest")
    if spec:
        cmd += ["--spec", spec]
    if fleet_port:
        cmd += ["--fleet-port", str(fleet_port)]
    return cmd


def _killer(sup, journal_dir: str, targets: list[int], observed: list,
            failures: list[str]) -> None:
    """SIGKILL the supervised child each time the journal shows the next
    target tick has been ingested; record the tick actually observed.
    Progress is the journal's LAST TICK INDEX, not a record count — the
    count shrinks when checkpoint compaction drops segments, the index
    is monotonic across rotation and compaction."""
    from rtap_tpu.resilience import last_journal_tick

    for target in targets:
        deadline = time.monotonic() + 120.0
        killed = False
        while time.monotonic() < deadline:
            n = last_journal_tick(journal_dir)
            child = sup.child
            if n >= target and child is not None and child.poll() is None:
                deaths_before = sup.deaths
                try:
                    child.kill()  # SIGKILL: no cleanup, no flush
                except OSError:
                    break
                observed.append(n)
                # wait for the supervisor to register the death before
                # aiming at the next target
                death_deadline = time.monotonic() + 60.0
                while sup.deaths == deaths_before and \
                        time.monotonic() < death_deadline:
                    time.sleep(0.01)
                killed = True
                break
            time.sleep(0.02)
        if not killed:
            failures.append(
                f"killer missed target tick {target} (journal reached "
                f"{last_journal_tick(journal_dir)}; child finished "
                "first?)")
            return


def _load_checkpoints(ckdir: str) -> dict:
    import orbax.checkpoint as ocp

    out = {}
    for name in sorted(os.listdir(ckdir)):
        p = os.path.join(ckdir, name)
        if not name.startswith("group") or not os.path.isdir(p):
            continue
        with open(os.path.join(p, "meta.json")) as f:
            meta = json.load(f)
        with ocp.PyTreeCheckpointer() as ckptr:
            tree = ckptr.restore(os.path.join(p, "state"))
        out[name] = (meta, tree)
    return out


def _flat(tree, prefix=""):
    import numpy as np

    if isinstance(tree, dict):
        for k in sorted(tree):
            yield from _flat(tree[k], f"{prefix}/{k}")
    else:
        yield prefix, np.asarray(tree)


def compare_states(ref_ck: str, got_ck: str, failures: list[str]) -> int:
    """Bitwise comparison of two checkpoint dirs' full state trees;
    returns leaves compared."""
    import numpy as np

    ref, got = _load_checkpoints(ref_ck), _load_checkpoints(got_ck)
    if sorted(ref) != sorted(got):
        failures.append(f"checkpoint groups differ: {sorted(ref)} vs "
                        f"{sorted(got)}")
        return 0
    leaves = 0
    for name in sorted(ref):
        rmeta, rtree = ref[name]
        gmeta, gtree = got[name]
        if rmeta["ticks"] != gmeta["ticks"]:
            failures.append(f"{name}: final tick cursor {gmeta['ticks']} "
                            f"!= fault-free {rmeta['ticks']}")
        rl, gl = dict(_flat(rtree)), dict(_flat(gtree))
        if sorted(rl) != sorted(gl):
            failures.append(f"{name}: state tree keys differ")
            continue
        for key in sorted(rl):
            leaves += 1
            a, b = rl[key], gl[key]
            equal = (a.shape == b.shape) and (
                np.array_equal(a, b, equal_nan=True)
                if a.dtype.kind in "fc" else np.array_equal(a, b))
            if not equal:
                failures.append(
                    f"{name}{key}: state diverges from the fault-free run")
    return leaves


def parse_alert_stream(path: str) -> dict:
    """Split a JSONL incident stream into alert records by alert_id,
    plus events, duplicates, and unparseable fragments (torn lines).
    Line walking rides the ONE shared tolerant iterator
    (service/alerts.iter_alert_records) so torn-fragment and
    event-vs-alert semantics can never drift from the serve stack's own
    resume scans (ISSUE 9 satellite)."""
    from rtap_tpu.service.alerts import iter_alert_records

    alerts: dict = {}
    dup: list[str] = []
    events: list[dict] = []
    garbage = 0
    for kind, rec in iter_alert_records(path):
        if kind == "garbage":
            garbage += 1  # torn fragment from a kill mid-write
            continue
        if kind == "event":
            events.append(rec)
            continue
        aid = rec.get("alert_id")
        if aid is None:
            garbage += 1
            continue
        if aid in alerts:
            dup.append(aid)
        alerts[aid] = rec
    return {"alerts": alerts, "dup": dup, "events": events,
            "garbage": garbage}


def fleet_verdict(agg, args, stats_path: str,
                  failures: list[str]) -> dict:
    """Judge the FLEET-OBSERVED restart story (ISSUE 19): every SIGKILL
    must appear on the plane as the member going DOWN by staleness (a
    kill-9'd process sends no BYE) then REJOINING when the supervisor's
    replacement re-HELLOs under the same name; the budget's completion
    and the completing incarnation's alert accounting must be readable
    through the plane alone. The individual checks live in
    scripts/fleet_verdict.py, shared with failover_soak and
    fleet_chaos."""
    events = agg.events_view()
    members = agg.members_view()
    snap = agg.member_snaps().get("serve") or {}
    serve_ev = [e for e in events if e["member"] == "serve"]
    rejoins = [e for e in serve_ev if e["event"] == "rejoined"]
    death_downs, flaps = classify_downs(serve_ev)
    if len(rejoins) != args.kills:
        failures.append(
            f"fleet plane saw {len(rejoins)} rejoin(s), expected one "
            f"per restart ({args.kills})")
    if death_downs != args.kills:
        failures.append(
            f"fleet plane saw {death_downs} death DOWN(s) (staleness "
            f"DOWN answered by a rejoin), scheduled {args.kills} "
            f"kill(s)")
    # each restart resumes FORWARD: the rejoin HELLOs carry the new
    # incarnation's resume base as run_epoch, which must be monotonic
    bases = [e.get("run_epoch") or 0 for e in rejoins]
    if bases != sorted(bases):
        failures.append(
            f"fleet-observed restart resume bases went backwards: "
            f"{bases}")
    final_tick = final_tick_check(members, args.ticks - 1, failures)
    # the completing incarnation's stats line counts every crossing it
    # SCORED; on the plane those split into emitted lines plus
    # resume-suppressed already-delivered ids — the sum closes the books
    reconciled = None
    last_line = None
    if os.path.isfile(stats_path):
        with open(stats_path) as f:
            for line in f:
                last_line = json.loads(line)
    if last_line is not None and snap:
        reconciled = reconcile_alert_counters(
            snap, last_line.get("alerts"), "the completing child",
            failures)
    return {
        "members": [{k: m.get(k) for k in ("member", "state", "role",
                                           "run_epoch", "tick",
                                           "snapshots")}
                    for m in members],
        "death_downs": death_downs,
        "stall_flaps": flaps,
        "rejoins": len(rejoins),
        "restart_bases": bases,
        "final_tick": final_tick,
        "counters_reconciled": reconciled,
        "events_total": len(events),
    }


def verify(args, ref_dir: str, crash_dir: str, sup, observed_kills: list,
           failures: list[str]) -> dict:
    ref_alerts = parse_alert_stream(os.path.join(ref_dir, "alerts.jsonl"))
    got_alerts = parse_alert_stream(os.path.join(crash_dir, "alerts.jsonl"))

    # exactly-once: zero duplicated, zero lost, records equal per id
    if got_alerts["dup"]:
        failures.append(
            f"{len(got_alerts['dup'])} DUPLICATED alert_id(s): "
            f"{got_alerts['dup'][:5]}")
    ref_ids = set(ref_alerts["alerts"])
    got_ids = set(got_alerts["alerts"])
    lost = sorted(ref_ids - got_ids)
    extra = sorted(got_ids - ref_ids)
    if lost:
        failures.append(f"{len(lost)} LOST alert_id(s): {lost[:5]}")
    if extra:
        failures.append(f"{len(extra)} EXTRA alert_id(s): {extra[:5]}")
    mismatched = [aid for aid in (ref_ids & got_ids)
                  if ref_alerts["alerts"][aid] != got_alerts["alerts"][aid]]
    if mismatched:
        failures.append(
            f"{len(mismatched)} alert record(s) differ from the "
            f"fault-free run: {mismatched[:5]}")
    if not ref_ids:
        failures.append("fault-free run emitted zero alerts — the soak "
                        "proves nothing (lower --threshold)")

    # final state bit-identical
    leaves = compare_states(os.path.join(ref_dir, "ck"),
                            os.path.join(crash_dir, "ck"), failures)

    # every kill landed as SIGKILL and the budget completed
    if sup.deaths != args.kills:
        failures.append(f"supervisor saw {sup.deaths} death(s), "
                        f"scheduled {args.kills}")
    bad_sigs = [s for s in sup.kill_signals if s != 9]
    if bad_sigs:
        failures.append(f"non-SIGKILL deaths observed: {bad_sigs}")

    # catch-up accounting: EVERY restart's replay comes from the
    # incident stream's journal_replayed events (a killed child never
    # reaches its stats append — stats.jsonl sees only completing
    # children, which would under-report K-1 of K catch-ups)
    stats_path = os.path.join(crash_dir, "stats.jsonl")
    total_ran = 0
    slo_verdict = None
    if os.path.isfile(stats_path):
        with open(stats_path) as f:
            for line in f:
                s = json.loads(line)
                total_ran = max(total_ran, s["base"] + s["ran"])
                # the final completing child's verdict covers the run's
                # tail; per-restart verdicts ride each stats line
                slo_verdict = s.get("slo") or slo_verdict
    trunc_events = [e for e in got_alerts["events"]
                    if e.get("event") == "journal_tail_truncated"]
    replay_events = [e for e in got_alerts["events"]
                     if e.get("event") == "journal_replayed"]
    catch_up = [{"replayed_ticks": e.get("ticks"),
                 "from_tick": e.get("from_tick"),
                 "replay_seconds": e.get("seconds")}
                for e in replay_events]
    if args.kills and not replay_events:
        failures.append("no journal_replayed event on the incident "
                        "stream despite kills — recovery never ran?")
    return {
        "alert_ids": len(ref_ids),
        "alerts_crash_run": len(got_ids),
        "duplicated": len(got_alerts["dup"]),
        "lost": len(lost),
        "extra": len(extra),
        "garbage_lines": got_alerts["garbage"],
        "state_leaves_compared": leaves,
        "kills_observed_at_ticks": observed_kills,
        "deaths": sup.deaths,
        "kill_signals": sup.kill_signals,
        "total_ticks_completed": total_ran,
        "catch_up": catch_up,
        "journal_truncation_events": len(trunc_events),
        "journal_replay_events": len(replay_events),
        "slo_verdict": slo_verdict,
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seed", type=int, default=0,
                    help="seeds the feed, the spike schedule, and the "
                         "kill ticks; same seed = same soak")
    ap.add_argument("--kills", type=int, default=2,
                    help="SIGKILLs delivered at seeded ticks (K>=2 "
                         "in-tree smoke, K>=10 on silicon)")
    ap.add_argument("--streams", type=int, default=6)
    ap.add_argument("--group-size", type=int, default=3)
    ap.add_argument("--ticks", type=int, default=96,
                    help="TOTAL tick budget across restarts")
    ap.add_argument("--cadence", type=float, default=0.01)
    ap.add_argument("--checkpoint-every", type=int, default=7)
    ap.add_argument("--backend", default="cpu")
    ap.add_argument("--threshold", type=float, default=-1e9,
                    help="alert threshold; the floor default makes every "
                         "scored tick an alert line — the densest "
                         "exactly-once check. Silicon runs use a real "
                         "threshold + the seeded spikes")
    ap.add_argument("--journal-fsync", default="os")
    ap.add_argument("--binary-ingest", action="store_true",
                    help="feed every child through the RB1 binary ingest "
                         "path (in-process loopback): the journal write-"
                         "ahead becomes raw FRAME records and each "
                         "restart's catch-up replays through the frame "
                         "decode — same bit-identity + exactly-once "
                         "verdict, over the new path (docs/INGEST.md)")
    ap.add_argument("--spike-every", type=int, default=13)
    ap.add_argument("--slo", default=None, metavar="NAME=TARGET@pQ",
                    help="latency SLO the children defend and the report "
                         "records a verdict for (default: tick=<cadence>"
                         "s@p99 — per-tick host latency; the seeded feed "
                         "runs on a synthetic epoch so wall-anchored "
                         "detect SLOs don't apply here, docs/SLO.md). "
                         "'off' disables")
    ap.add_argument("--restart-backoff", type=float, default=0.05)
    ap.add_argument("--fleet", action=argparse.BooleanOptionalAction,
                    default=True,
                    help="run an in-process fleet aggregator and judge "
                         "the restart story through the fleet plane too "
                         "(staleness DOWN per kill, rejoin per restart, "
                         "merged counters reconcile — docs/FLEET.md)")
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--out", default=None, help="report JSON path")
    # child-mode flags
    ap.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--spec", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--stats-out", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--fleet-port", type=int, default=0,
                    help=argparse.SUPPRESS)
    args = ap.parse_args()
    if args.child:
        return run_child(args)

    from rtap_tpu.resilience import Supervisor

    workdir = args.workdir or tempfile.mkdtemp(prefix="crash_soak_")
    ref_dir = os.path.join(workdir, "ref")
    crash_dir = os.path.join(workdir, "crash")
    os.makedirs(ref_dir, exist_ok=True)
    os.makedirs(crash_dir, exist_ok=True)
    t_all = time.monotonic()

    # 1. fault-free reference over the identical seeded feed
    log(f"reference run ({args.ticks} ticks, {args.streams} streams, "
        f"backend {args.backend})")
    rc = subprocess.run(child_cmd(args, ref_dir, None)).returncode
    if rc != 0:
        log(f"FATAL: fault-free reference run failed rc={rc}")
        return INFRA_FAILED_EXIT

    # 2. seeded kill schedule: K ticks spread over the middle of the run
    rng = random.Random(args.seed)
    span = max(args.kills, args.ticks * 3 // 5)
    lo = max(1, args.ticks // 5)
    window = max(1, span // max(1, args.kills))
    targets = sorted(min(args.ticks - 2, lo + i * window
                         + rng.randrange(max(1, window // 2)))
                     for i in range(args.kills))
    log(f"kill schedule (ticks): {targets}")

    # 3. supervised crashy run (the parent's aggregator watches it
    # through the fleet plane: kills land as staleness DOWNs, restarts
    # as rejoins — the reference run stays off the plane so the fleet
    # story is the crash run's alone)
    agg = None
    if args.fleet:
        from rtap_tpu.fleet import FleetAggregator

        agg = FleetAggregator(
            port=0,
            sweep_interval_s=max(0.02, min(0.2, args.cadence))).start()
        log(f"fleet aggregator on :{agg.port}")
    sup = Supervisor(
        child_cmd(args, crash_dir, None,
                  fleet_port=agg.port if agg is not None else 0),
        restart_budget=args.kills + 2,
        backoff_base_s=args.restart_backoff,
        backoff_max_s=max(1.0, args.restart_backoff * 4),
        event_path=os.path.join(crash_dir, "alerts.jsonl"),
        log=log)
    failures: list[str] = []
    observed: list = []
    killer = threading.Thread(
        target=_killer,
        args=(sup, os.path.join(crash_dir, "journal"), targets, observed,
              failures),
        daemon=True)
    killer.start()
    rc = sup.run(install_signals=False)
    killer.join(timeout=10.0)
    if rc != 0:
        failures.append(f"supervised run ended rc={rc} "
                        f"(deaths={sup.deaths})")

    # 4. verdict
    report_body = verify(args, ref_dir, crash_dir, sup, observed, failures)
    if agg is not None:
        report_body["fleetobs"] = fleet_verdict(
            agg, args, os.path.join(crash_dir, "stats.jsonl"), failures)
        with open(os.path.join(crash_dir, "fleet_snapshot.json"),
                  "w") as f:
            json.dump(agg.snapshot(), f, indent=2)
        agg.close()
    report = {
        "seed": args.seed,
        "kills_scheduled": targets,
        "ticks": args.ticks,
        "streams": args.streams,
        "group_size": args.group_size,
        "backend": args.backend,
        "journal_fsync": args.journal_fsync,
        "wall_s": round(time.monotonic() - t_all, 1),
        **report_body,
        "verified": not failures,
        "failures": failures,
        "workdir": workdir,
    }
    if args.out:
        os.makedirs(os.path.dirname(os.path.abspath(args.out)),
                    exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2)
    print(json.dumps(report))
    if failures:
        for msg in failures:
            log(f"FAIL: {msg}")
        return VERIFY_FAILED_EXIT
    log(f"OK: {args.kills} kill(s) at ticks {observed}, "
        f"{report['alert_ids']} alert ids exactly-once, "
        f"{report['state_leaves_compared']} state leaves bit-identical")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
