"""Chaos soak: a seeded fault schedule against the REAL live loop.

ISSUE 2 acceptance surface: every resilience path — source faults, group
quarantine + checkpoint restore, alert-sink quarantine, checkpoint-save
breaker — exercised end-to-end by deterministic injection, with a
machine-checked verdict:

- ``--seed N`` fully determines the fault schedule
  (``ChaosSpec.generate`` uses a private ``random.Random(seed)``); the
  report carries the schedule digest so two runs are comparable by eye.
- The run FAILS (exit 5) if any group's streams silently stopped being
  scored while unquarantined: per-group scored counts from the loop's
  ``scored_by_group`` stats must exactly match the unquarantined tick
  intervals reconstructed from the ``group_quarantined`` /
  ``group_restored`` events on the alert stream. Quarantine is allowed
  (that is the mechanism working); silence is not.

Usage: python scripts/chaos_soak.py --seed 1 [--streams 12]
       [--group-size 4] [--ticks 120] [--cadence 0.05] [--rate 0.08]
       [--backend tpu] [--out reports/chaos_soak.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from rtap_tpu.utils.platform import maybe_force_cpu  # noqa: E402

VERIFY_FAILED_EXIT = 5


def log(msg: str) -> None:
    print(f"[chaos] {msg}", file=sys.stderr, flush=True)


def _unquarantined_intervals(events: list[dict], n_groups: int,
                             ticks: int) -> list[list[tuple[int, int]]]:
    """Per group, the [start, end) tick intervals it was being scored,
    reconstructed from the alert stream's quarantine/restore events."""
    start = [0] * n_groups
    active = [True] * n_groups
    intervals: list[list[tuple[int, int]]] = [[] for _ in range(n_groups)]
    for e in events:
        g = e.get("group")
        if g is None or not 0 <= g < n_groups:
            continue
        if e["event"] == "group_quarantined" and active[g]:
            intervals[g].append((start[g], e["tick"]))
            active[g] = False
        elif e["event"] == "group_restored" and not active[g]:
            start[g] = e["tick"]
            active[g] = True
    for g in range(n_groups):
        if active[g]:
            intervals[g].append((start[g], ticks))
    return intervals


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seed", type=int, default=0,
                    help="fault-schedule seed; same seed = same schedule")
    ap.add_argument("--streams", type=int, default=12)
    ap.add_argument("--group-size", type=int, default=4)
    ap.add_argument("--ticks", type=int, default=120)
    ap.add_argument("--cadence", type=float, default=0.05)
    ap.add_argument("--rate", type=float, default=0.08,
                    help="per-tick fault probability in the generated "
                         "schedule")
    ap.add_argument("--backend", default="tpu")
    ap.add_argument("--checkpoint-every", type=int, default=5)
    ap.add_argument("--restore-after", type=int, default=6,
                    help="quarantine cooldown before checkpoint restore")
    ap.add_argument("--workdir", default=None,
                    help="alerts + checkpoints land here (default: a "
                         "fresh temp dir)")
    ap.add_argument("--out", default=None, help="report JSON path")
    args = ap.parse_args()
    maybe_force_cpu()

    import numpy as np

    from rtap_tpu.config import cluster_preset
    from rtap_tpu.resilience import ChaosEngine, ChaosSpec
    from rtap_tpu.service.loop import live_loop
    from rtap_tpu.service.registry import StreamGroupRegistry

    ids = [f"n{i // 3}.m{i % 3}" for i in range(args.streams)]
    reg = StreamGroupRegistry(cluster_preset(), group_size=args.group_size,
                              backend=args.backend)
    for sid in ids:
        reg.add_stream(sid)
    reg.finalize()
    n_groups = len(reg.groups)

    spec = ChaosSpec.generate(seed=args.seed, n_ticks=args.ticks,
                              n_groups=n_groups, rate=args.rate)
    digest = spec.digest()
    # reproducibility is a hard contract, not an aspiration: regenerate
    # and compare before trusting the run
    if ChaosSpec.generate(seed=args.seed, n_ticks=args.ticks,
                          n_groups=n_groups, rate=args.rate
                          ).digest() != digest:
        log("FATAL: schedule generation is not deterministic")
        return 3
    # group-targeted source_timeout faults resolve to that group's slice
    # of the source vector inside live_loop (ChaosEngine.set_group_streams
    # from the loop's routing) — one exporter's worth of streams times
    # out, the rest of the fleet's inputs stay untouched
    engine = ChaosEngine(spec)
    log(f"schedule: {len(spec.faults)} faults over {args.ticks} ticks, "
        f"digest {digest}")

    def source(k: int):
        rng = np.random.Generator(np.random.Philox(key=(args.seed, k)))
        return (30 + 5 * rng.random(len(ids))).astype(np.float32), \
            1_700_000_000 + k

    workdir = args.workdir or tempfile.mkdtemp(prefix="chaos_soak_")
    os.makedirs(workdir, exist_ok=True)
    alerts_path = os.path.join(workdir, "alerts.jsonl")
    # black-box coverage (ISSUE 4): every chaos run flies with the span
    # recorder + flight recorder armed, and the verdict below asserts a
    # chaos-induced quarantine left a VALID postmortem bundle behind
    from rtap_tpu.obs import FlightRecorder, TraceRecorder, validate_bundle

    trace = TraceRecorder(capacity=32768)
    pm_dir = os.path.join(workdir, "postmortems")
    flight = FlightRecorder(
        trace=trace, n_ticks=min(args.ticks, 240), out_dir=pm_dir,
        info={"command": "chaos_soak", "seed": args.seed,
              "schedule_digest": digest, "streams": args.streams,
              "group_size": args.group_size})
    stats = live_loop(
        source, reg, n_ticks=args.ticks, cadence_s=args.cadence,
        alert_path=alerts_path,
        checkpoint_dir=os.path.join(workdir, "ck"),
        checkpoint_every=args.checkpoint_every,
        quarantine_restore_after=args.restore_after,
        chaos=engine, trace=trace, flight=flight)

    with open(alerts_path) as f:
        events = [json.loads(line) for line in f
                  if line.startswith('{"event"')]
    failures: list[str] = []
    if stats["ticks"] != args.ticks:
        failures.append(
            f"loop stopped at tick {stats['ticks']} of {args.ticks}")
    # intervals come from the loop's own quarantine log, NOT the alert
    # stream: the sink may have been the faulted component, and a dropped
    # event line must not fail an otherwise-correct run
    intervals = _unquarantined_intervals(
        stats.get("quarantine_log", []), n_groups, stats["ticks"])
    expected = [sum(b - a for a, b in intervals[g]) * reg.groups[g].n_live
                for g in range(n_groups)]
    got = stats["scored_by_group"]
    for g in range(n_groups):
        if got[g] != expected[g]:
            failures.append(
                f"group{g}: scored {got[g]} but its unquarantined "
                f"intervals {intervals[g]} require {expected[g]} — streams "
                "silently stopped being scored while unquarantined")
    if sum(got) != stats["scored"]:
        failures.append(
            f"per-group counts sum to {sum(got)} != scored "
            f"{stats['scored']}")

    # ---- postmortem-bundle verdict: a chaos-injected quarantine must
    # leave a loadable black box behind (trace spans + event lines > 0)
    quarantines = [e for e in stats.get("quarantine_log", [])
                   if e["event"] == "group_quarantined"]
    bundle_dirs = sorted(
        os.path.join(pm_dir, d) for d in os.listdir(pm_dir)
        if not d.startswith(".tmp")) if os.path.isdir(pm_dir) else []
    verdicts = [validate_bundle(b) for b in bundle_dirs]
    if quarantines and not bundle_dirs:
        failures.append(
            f"{len(quarantines)} quarantine(s) occurred but no postmortem "
            "bundle was dumped")
    for b, v in zip(bundle_dirs, verdicts):
        if not v["ok"]:
            failures.append(f"invalid postmortem bundle {b}: {v['problems']}")
        elif v["events"] == 0:
            failures.append(f"postmortem bundle {b} captured zero events")
    pm_report = {
        "dir": pm_dir,
        "bundles": [os.path.basename(b) for b in bundle_dirs],
        "valid": sum(1 for v in verdicts if v["ok"]),
        "spans": sum(v["spans"] for v in verdicts),
        "instants": sum(v["instants"] for v in verdicts),
        "events": sum(v["events"] for v in verdicts),
        "dumps_skipped": stats.get("postmortem", {}).get("dumps_skipped", 0),
        "trace_records": trace.total,
        "trace_dropped": trace.dropped,
    }

    report = {
        "seed": args.seed,
        "schedule_digest": digest,
        "faults_scheduled": len(spec.faults),
        "faults_injected": engine.injected,
        "events": sorted({e["event"] for e in events}),
        "intervals": {f"group{g}": intervals[g] for g in range(n_groups)},
        "expected_by_group": expected,
        "postmortem": pm_report,
        "stats": stats,
        "verified": not failures,
        "failures": failures,
        "workdir": workdir,
    }
    if args.out:
        os.makedirs(os.path.dirname(os.path.abspath(args.out)),
                    exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2)
    print(json.dumps(report))
    if failures:
        for msg in failures:
            log(f"FAIL: {msg}")
        return VERIFY_FAILED_EXIT
    log(f"OK: {stats['scored']} scored, "
        f"{len(engine.injected)} faults injected, "
        f"{len(quarantines)} quarantines, "
        f"{pm_report['valid']} valid postmortem bundle(s)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
