"""Chaos soak: a seeded fault schedule against the REAL live loop.

ISSUE 2 acceptance surface: every resilience path — source faults, group
quarantine + checkpoint restore, alert-sink quarantine, checkpoint-save
breaker — exercised end-to-end by deterministic injection, with a
machine-checked verdict:

- ``--seed N`` fully determines the fault schedule
  (``ChaosSpec.generate`` uses a private ``random.Random(seed)``); the
  report carries the schedule digest so two runs are comparable by eye.
- The run FAILS (exit 5) if any group's streams silently stopped being
  scored while unquarantined: per-group scored counts from the loop's
  ``scored_by_group`` stats must exactly match the unquarantined tick
  intervals reconstructed from the ``group_quarantined`` /
  ``group_restored`` events on the alert stream. Quarantine is allowed
  (that is the mechanism working); silence is not.

``--supervise`` (ISSUE 5) runs the soak OUT of process instead: the
seeded schedule gains ``proc_exit`` faults (abrupt ``os._exit`` at tick
boundaries) and the child — ``scripts/crash_soak.py --child``, the
journaled + checkpointed serve runner — flies under the real
:class:`rtap_tpu.resilience.Supervisor`. The verdict checks the
supervisor restarted the child once per scheduled kill, the run still
completed its total tick budget, journal recovery actually ran
(``journal_replayed`` events on the incident stream), and the alert
stream carries zero duplicated ``alert_id``s.

``--topology-burst`` (ISSUE 9) schedules one explicit ``topology_burst``
fault — the source floods two adjacent nodes' streams (spanning multiple
serve groups) with a correlated value burst — alongside seeded
``source_timeout`` background noise, with topology-aware incident
correlation armed (``TopologyMap.infer`` over the soak's node naming).
The verdict: exactly ONE cluster-level incident pages (not N per-stream
alerts), its blast-radius node set is exactly the flooded nodes, and
every member alert_id is a real alert line on the stream.

``--replication`` (ISSUE 8) runs the seeded schedule against a LIVE
leader/standby pair instead: a journaled leader loop ships every append
to an in-process :class:`~rtap_tpu.resilience.StandbyFollower` over a
real socket while the ISSUE 8 network fault kinds — ``conn_drop``,
``stall_socket``, ``corrupt_bytes`` — fire on the wire at seeded
record ticks (``ChaosEngine.on_wire``). The verdict: the standby's
final model state is BIT-IDENTICAL to the leader's (every checkpoint
leaf) despite the faults, the standby applied every tick, and each
scheduled wire fault actually injected.

Usage: python scripts/chaos_soak.py --seed 1 [--streams 12]
       [--group-size 4] [--ticks 120] [--cadence 0.05] [--rate 0.08]
       [--backend tpu] [--out reports/chaos_soak.json]
       [--supervise --kills 2] [--replication]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from rtap_tpu.utils.platform import maybe_force_cpu  # noqa: E402

VERIFY_FAILED_EXIT = 5


def log(msg: str) -> None:
    print(f"[chaos] {msg}", file=sys.stderr, flush=True)


def _unquarantined_intervals(events: list[dict], n_groups: int,
                             ticks: int) -> list[list[tuple[int, int]]]:
    """Per group, the [start, end) tick intervals it was being scored,
    reconstructed from the alert stream's quarantine/restore events."""
    start = [0] * n_groups
    active = [True] * n_groups
    intervals: list[list[tuple[int, int]]] = [[] for _ in range(n_groups)]
    for e in events:
        g = e.get("group")
        if g is None or not 0 <= g < n_groups:
            continue
        if e["event"] == "group_quarantined" and active[g]:
            intervals[g].append((start[g], e["tick"]))
            active[g] = False
        elif e["event"] == "group_restored" and not active[g]:
            start[g] = e["tick"]
            active[g] = True
    for g in range(n_groups):
        if active[g]:
            intervals[g].append((start[g], ticks))
    return intervals


def run_supervised(args) -> int:
    """`--supervise`: seeded proc_exit kills + source/sink faults against
    the journaled serve child under the real Supervisor."""
    import random

    from rtap_tpu.resilience import ChaosSpec, Fault, Supervisor

    workdir = args.workdir or tempfile.mkdtemp(prefix="chaos_supervise_")
    os.makedirs(workdir, exist_ok=True)
    n_groups = -(-args.streams // args.group_size)
    # in-process-safe kinds ride along at the normal rate; group-killing
    # kinds stay out (a quarantined group across a restart boundary is a
    # different study — the journal replays it back to health anyway)
    base = ChaosSpec.generate(
        seed=args.seed, n_ticks=args.ticks, n_groups=n_groups,
        rate=args.rate,
        kinds=("source_timeout", "source_malformed", "alert_sink_oserror"))
    rng = random.Random(args.seed ^ 0x5EED)
    lo, hi = max(1, args.ticks // 5), max(2, args.ticks * 4 // 5)
    if not 1 <= args.kills <= hi - lo:
        log(f"--kills {args.kills} does not fit the schedulable window "
            f"[{lo}, {hi}) of a {args.ticks}-tick run (1..{hi - lo})")
        return 2
    kill_ticks = sorted(rng.sample(range(lo, hi), args.kills))
    faults = sorted(
        base.faults + [Fault(kind="proc_exit", tick=t) for t in kill_ticks],
        key=lambda f: f.tick)
    spec = ChaosSpec(faults=faults, seed=args.seed)
    spec_path = os.path.join(workdir, "spec.json")
    with open(spec_path, "w") as f:
        json.dump(spec.to_dict(), f)
    log(f"supervised schedule: {len(base.faults)} in-process faults + "
        f"proc_exit at ticks {kill_ticks}, digest {spec.digest()}")

    alerts_path = os.path.join(workdir, "alerts.jsonl")
    child = [sys.executable, os.path.join(REPO, "scripts", "crash_soak.py"),
             "--child", "--workdir", workdir, "--seed", str(args.seed),
             "--ticks", str(args.ticks), "--streams", str(args.streams),
             "--group-size", str(args.group_size),
             "--cadence", str(args.cadence),
             "--checkpoint-every", str(args.checkpoint_every),
             "--backend", args.backend, "--threshold", str(-1e9),
             "--journal-fsync", "os", "--spec", spec_path,
             "--stats-out", os.path.join(workdir, "stats.jsonl")]
    sup = Supervisor(child, restart_budget=args.kills + 2,
                     backoff_base_s=0.05, backoff_max_s=1.0,
                     event_path=alerts_path, log=log)
    rc = sup.run(install_signals=False)

    failures: list[str] = []
    if rc != 0:
        failures.append(f"supervised run ended rc={rc} "
                        f"(deaths={sup.deaths})")
    from rtap_tpu.resilience.chaos import PROC_EXIT_CODE

    if sup.deaths != args.kills:
        failures.append(
            f"{sup.deaths} death(s) for {args.kills} scheduled proc_exit "
            "faults — each must fire exactly once across restarts")
    bad_rc = [r for r in sup.death_rcs if r != PROC_EXIT_CODE]
    if bad_rc:
        failures.append(
            f"death rc(s) {bad_rc} are not the injected proc_exit "
            f"(rc {PROC_EXIT_CODE}) — a real crash rode the schedule")
    total = 0
    stats_path = os.path.join(workdir, "stats.jsonl")
    if os.path.isfile(stats_path):
        with open(stats_path) as f:
            for line in f:
                s = json.loads(line)
                total = max(total, s["base"] + s["ran"])
    if total != args.ticks:
        failures.append(f"run completed {total} of {args.ticks} total "
                        "ticks across restarts")
    # one scanner for both soaks: crash_soak's parse_alert_stream owns
    # the event-vs-alert split and torn-fragment tolerance
    from scripts.crash_soak import parse_alert_stream

    parsed = parse_alert_stream(alerts_path)
    seen_ids = set(parsed["alerts"])
    dup = parsed["dup"]
    replay_events = sum(1 for e in parsed["events"]
                        if e.get("event") == "journal_replayed")
    if dup:
        failures.append(f"{len(dup)} duplicated alert_id(s) across "
                        f"restarts: {dup[:5]}")
    if args.kills and not replay_events:
        failures.append("no journal_replayed event despite kills — "
                        "recovery never ran")
    report = {
        "mode": "supervise",
        "seed": args.seed,
        "schedule_digest": spec.digest(),
        "proc_exit_ticks": kill_ticks,
        "deaths": sup.deaths,
        "ticks_completed": total,
        "alert_ids": len(seen_ids),
        "duplicated": len(dup),
        "journal_replay_events": replay_events,
        "verified": not failures,
        "failures": failures,
        "workdir": workdir,
    }
    if args.out:
        os.makedirs(os.path.dirname(os.path.abspath(args.out)),
                    exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2)
    print(json.dumps(report))
    if failures:
        for msg in failures:
            log(f"FAIL: {msg}")
        return VERIFY_FAILED_EXIT
    log(f"OK: {sup.deaths} proc_exit death(s), {total} ticks completed, "
        f"{len(seen_ids)} alert ids unique, {replay_events} journal "
        "replays")
    return 0


def run_topology_burst(args) -> int:
    """`--topology-burst`: a correlated multi-group value burst rides the
    seeded schedule; the verdict is ONE cluster-level incident, not N
    per-stream pages (ISSUE 9)."""
    import dataclasses

    import numpy as np

    from rtap_tpu.config import cluster_preset
    from rtap_tpu.correlate import IncidentCorrelator, TopologyMap
    from rtap_tpu.resilience import ChaosEngine, ChaosSpec, Fault
    from rtap_tpu.service.loop import live_loop
    from rtap_tpu.service.registry import StreamGroupRegistry

    if args.streams < 12:
        log("--topology-burst floods nodes n1+n2 (stream indices 3..8) "
            "and needs healthy bystanders; use --streams >= 12")
        return 2
    # short probation so the default 120-tick run has a mature burst
    # window: burst at 3/4 of the run, likelihood ready at tick 60
    probation = 40 + 20
    burst_tick = args.ticks * 3 // 4
    burst_dur = 8
    if burst_tick <= probation + 5:
        log(f"burst tick {burst_tick} inside the likelihood probation "
            f"{probation} — raise --ticks (>= 96)")
        return 2
    ids = [f"n{i // 3}.m{i % 3}" for i in range(args.streams)]
    cfg = cluster_preset()
    cfg = dataclasses.replace(cfg, likelihood=dataclasses.replace(
        cfg.likelihood, learning_period=40, estimation_samples=20))
    reg = StreamGroupRegistry(cfg, group_size=args.group_size,
                              backend=args.backend, threshold=0.1,
                              debounce=2)
    for sid in ids:
        reg.add_stream(sid)
    reg.finalize()

    # the blast radius: every metric of nodes n1 and n2 — six streams
    # whose indices straddle a group boundary at the default group size
    burst_idx = tuple(range(3, 9))
    burst_nodes = sorted({ids[i].split(".")[0] for i in burst_idx})
    burst_groups = sorted({i // args.group_size for i in burst_idx})
    if len(burst_groups) < 2:
        log(f"burst indices {burst_idx} land in one group at "
            f"--group-size {args.group_size}; use a size that splits "
            "them (the point is a MULTI-group burst)")
        return 2
    base = ChaosSpec.generate(seed=args.seed, n_ticks=args.ticks,
                              rate=args.rate, kinds=("source_timeout",))
    burst = Fault(kind="topology_burst", tick=burst_tick,
                  duration=burst_dur, streams=burst_idx)
    spec = ChaosSpec(faults=sorted(base.faults + [burst],
                                   key=lambda f: f.tick), seed=args.seed)
    engine = ChaosEngine(spec)
    log(f"schedule: burst on {burst_nodes} (groups {burst_groups}) at "
        f"tick {burst_tick} + {len(base.faults)} background fault(s), "
        f"digest {spec.digest()}")

    correlator = IncidentCorrelator(TopologyMap.infer(), window_s=6,
                                    min_streams=4)

    def source(k: int):
        rng = np.random.Generator(np.random.Philox(key=(args.seed, k)))
        return (30 + 5 * rng.random(len(ids))).astype(np.float32), \
            1_700_000_000 + k

    workdir = args.workdir or tempfile.mkdtemp(prefix="chaos_topo_")
    os.makedirs(workdir, exist_ok=True)
    alerts_path = os.path.join(workdir, "alerts.jsonl")
    stats = live_loop(
        source, reg, n_ticks=args.ticks, cadence_s=args.cadence,
        alert_path=alerts_path, chaos=engine, correlator=correlator)

    failures: list[str] = []
    if stats["ticks"] != args.ticks:
        failures.append(
            f"loop stopped at tick {stats['ticks']} of {args.ticks}")
    if "topology_burst" not in {e["kind"] for e in engine.injected}:
        failures.append("the scheduled topology_burst never injected")
    # the incident contract is THE shared checker (one copy — a schema
    # change cannot silently de-fang one of the two topology soaks)
    from scripts.crash_soak import parse_alert_stream
    from scripts.workload_soak import check_single_incident

    parsed = parse_alert_stream(alerts_path)
    incs = check_single_incident(alerts_path, burst_nodes,
                                 correlator.min_streams, failures,
                                 "topology-burst", parsed=parsed)

    report = {
        "mode": "topology_burst",
        "seed": args.seed,
        "schedule_digest": spec.digest(),
        "burst_tick": burst_tick,
        "burst_nodes": burst_nodes,
        "burst_groups": burst_groups,
        "faults_injected": engine.injected,
        "alert_ids": len(set(parsed["alerts"])),
        "incidents": len(incs),
        "incident": incs[0] if len(incs) == 1 else None,
        "correlator": correlator.stats(),
        "verified": not failures,
        "failures": failures,
        "workdir": workdir,
    }
    if args.out:
        os.makedirs(os.path.dirname(os.path.abspath(args.out)),
                    exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2)
    print(json.dumps(report))
    if failures:
        for msg in failures:
            log(f"FAIL: {msg}")
        return VERIFY_FAILED_EXIT
    log(f"OK: 1 incident across groups {burst_groups} "
        f"({incs[0]['members']} members, {len(incs[0]['nodes'])} nodes) "
        f"from {len(set(parsed['alerts']))} per-stream alert(s)")
    return 0


def run_replication(args) -> int:
    """`--replication`: seeded wire faults against a live leader/standby
    pair; the verdict is standby state bit-identical to the leader's."""
    import threading
    import time

    import numpy as np

    from rtap_tpu.config import cluster_preset
    from rtap_tpu.resilience import (
        ChaosEngine,
        ChaosSpec,
        Lease,
        ReplicationSender,
        StandbyFollower,
        TickJournal,
    )
    from rtap_tpu.service.loop import _save_all, live_loop
    from rtap_tpu.service.registry import StreamGroupRegistry
    from scripts.crash_soak import compare_states

    workdir = args.workdir or tempfile.mkdtemp(prefix="chaos_repl_")
    os.makedirs(workdir, exist_ok=True)

    def build_reg():
        reg = StreamGroupRegistry(cluster_preset(),
                                  group_size=args.group_size,
                                  backend=args.backend, threshold=-1e9,
                                  debounce=1)
        for i in range(args.streams):
            reg.add_stream(f"n{i // 3}.m{i % 3}")
        reg.finalize()
        return reg

    leader_reg, standby_reg = build_reg(), build_reg()
    spec = ChaosSpec.generate(
        seed=args.seed, n_ticks=args.ticks, rate=args.rate,
        kinds=("conn_drop", "stall_socket", "corrupt_bytes"))
    engine = ChaosEngine(spec)
    log(f"replication schedule: {len(spec.faults)} wire faults over "
        f"{args.ticks} ticks, digest {spec.digest()}")

    lease_path = os.path.join(workdir, "lease")
    # the pair must STAY a pair for this soak: the standby's lease view
    # uses an enormous timeout so it never promotes mid-run
    leader_lease = Lease(lease_path, "leader", timeout_s=30.0)
    standby_lease = Lease(lease_path, "standby", timeout_s=1e9)
    assert leader_lease.try_acquire()

    stop = threading.Event()
    standby_journal = TickJournal(os.path.join(workdir, "standby-journal"))
    follower = StandbyFollower(
        standby_reg, standby_journal, lease=standby_lease, port=0,
        alert_path=None, checkpoint_dir=os.path.join(workdir, "ck"),
        cadence_s=args.cadence, stop_event=stop)
    results: dict = {}

    def follow():
        results["follow"] = follower.run()

    t = threading.Thread(target=follow, daemon=True)
    t.start()
    deadline = time.monotonic() + 30.0
    while follower.address is None and time.monotonic() < deadline:
        time.sleep(0.01)
    if follower.address is None:
        log("FATAL: standby listener never came up")
        return 3

    leader_journal = TickJournal(os.path.join(workdir, "leader-journal"))
    sender = ReplicationSender(
        follower.address, leader_journal,
        checkpoint_dir=os.path.join(workdir, "ck"), chaos=engine).start()
    leader_journal.tee = sender.tee
    leader_journal.compact_floor = sender.compact_floor

    def source(k: int):
        rng = np.random.Generator(np.random.Philox(key=(args.seed, k)))
        return (30 + 5 * rng.random(
            len(leader_reg.dispatch_ids()))).astype(np.float32), \
            1_700_000_000 + k

    stats = live_loop(
        source, leader_reg, n_ticks=args.ticks, cadence_s=args.cadence,
        alert_path=os.path.join(workdir, "alerts.jsonl"),
        checkpoint_dir=os.path.join(workdir, "ck"),
        checkpoint_every=args.checkpoint_every,
        journal=leader_journal, lease=leader_lease)

    failures: list[str] = []
    # let the standby drain the tail (the wire is asynchronous)
    deadline = time.monotonic() + 60.0
    while follower.expected < args.ticks and time.monotonic() < deadline:
        time.sleep(0.02)
    if follower.expected < args.ticks:
        failures.append(
            f"standby applied only {follower.expected} of {args.ticks} "
            "ticks before the drain deadline")
    leader_journal.close()
    sender.close()
    stop.set()
    t.join(timeout=30.0)
    standby_journal.close()

    # the verdict: bit-identical model state, leader vs standby, via the
    # checkpoint comparison the crash soak already owns
    lck = os.path.join(workdir, "verify-leader")
    sck = os.path.join(workdir, "verify-standby")
    _save_all(leader_reg.groups, lck)
    _save_all(standby_reg.groups, sck)
    leaves = compare_states(lck, sck, failures)
    injected_kinds = {e["kind"] for e in engine.injected}
    scheduled_kinds = {f.kind for f in spec.faults}
    missing = sorted(scheduled_kinds - injected_kinds)
    if missing:
        failures.append(f"scheduled wire fault kind(s) never injected: "
                        f"{missing}")
    if stats["ticks"] != args.ticks:
        failures.append(f"leader ran {stats['ticks']} of {args.ticks}")

    report = {
        "mode": "replication",
        "seed": args.seed,
        "schedule_digest": spec.digest(),
        "faults_scheduled": len(spec.faults),
        "faults_injected": engine.injected,
        "standby": follower.stats(),
        "sender": sender.stats(),
        "state_leaves_compared": leaves,
        "verified": not failures,
        "failures": failures,
        "workdir": workdir,
    }
    if args.out:
        os.makedirs(os.path.dirname(os.path.abspath(args.out)),
                    exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2)
    print(json.dumps(report))
    if failures:
        for msg in failures:
            log(f"FAIL: {msg}")
        return VERIFY_FAILED_EXIT
    log(f"OK: {len(engine.injected)} wire fault(s) injected, standby "
        f"applied {follower.applied} ticks, {leaves} state leaves "
        "bit-identical")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seed", type=int, default=0,
                    help="fault-schedule seed; same seed = same schedule")
    ap.add_argument("--streams", type=int, default=12)
    ap.add_argument("--group-size", type=int, default=4)
    ap.add_argument("--ticks", type=int, default=120)
    ap.add_argument("--cadence", type=float, default=0.05)
    ap.add_argument("--rate", type=float, default=0.08,
                    help="per-tick fault probability in the generated "
                         "schedule")
    ap.add_argument("--backend", default="tpu")
    ap.add_argument("--checkpoint-every", type=int, default=5)
    ap.add_argument("--restore-after", type=int, default=6,
                    help="quarantine cooldown before checkpoint restore")
    ap.add_argument("--workdir", default=None,
                    help="alerts + checkpoints land here (default: a "
                         "fresh temp dir)")
    ap.add_argument("--out", default=None, help="report JSON path")
    ap.add_argument("--supervise", action="store_true",
                    help="out-of-process mode (ISSUE 5): add seeded "
                         "proc_exit kills and run the journaled serve "
                         "child under the Supervisor; verify restarts, "
                         "journal recovery, and zero duplicated alert ids")
    ap.add_argument("--kills", type=int, default=2,
                    help="proc_exit faults scheduled with --supervise")
    ap.add_argument("--replication", action="store_true",
                    help="leader/standby mode (ISSUE 8): seeded "
                         "conn_drop/stall_socket/corrupt_bytes faults on "
                         "the replication wire; verify the standby's "
                         "state stays bit-identical to the leader's")
    ap.add_argument("--topology-burst", action="store_true",
                    help="incident-correlation mode (ISSUE 9): inject a "
                         "correlated multi-group value burst with "
                         "correlation armed; verify exactly ONE cluster-"
                         "level incident pages, not N per-stream alerts")
    args = ap.parse_args()
    maybe_force_cpu()
    if sum((args.supervise, args.replication, args.topology_burst)) > 1:
        log("--supervise, --replication and --topology-burst are "
            "separate drills")
        return 2
    if args.topology_burst:
        return run_topology_burst(args)
    if args.replication:
        return run_replication(args)
    if args.supervise:
        return run_supervised(args)

    import numpy as np

    from rtap_tpu.config import cluster_preset
    from rtap_tpu.resilience import ChaosEngine, ChaosSpec
    from rtap_tpu.service.loop import live_loop
    from rtap_tpu.service.registry import StreamGroupRegistry

    ids = [f"n{i // 3}.m{i % 3}" for i in range(args.streams)]
    reg = StreamGroupRegistry(cluster_preset(), group_size=args.group_size,
                              backend=args.backend)
    for sid in ids:
        reg.add_stream(sid)
    reg.finalize()
    n_groups = len(reg.groups)

    spec = ChaosSpec.generate(seed=args.seed, n_ticks=args.ticks,
                              n_groups=n_groups, rate=args.rate)
    digest = spec.digest()
    # reproducibility is a hard contract, not an aspiration: regenerate
    # and compare before trusting the run
    if ChaosSpec.generate(seed=args.seed, n_ticks=args.ticks,
                          n_groups=n_groups, rate=args.rate
                          ).digest() != digest:
        log("FATAL: schedule generation is not deterministic")
        return 3
    # group-targeted source_timeout faults resolve to that group's slice
    # of the source vector inside live_loop (ChaosEngine.set_group_streams
    # from the loop's routing) — one exporter's worth of streams times
    # out, the rest of the fleet's inputs stay untouched
    engine = ChaosEngine(spec)
    log(f"schedule: {len(spec.faults)} faults over {args.ticks} ticks, "
        f"digest {digest}")

    def source(k: int):
        rng = np.random.Generator(np.random.Philox(key=(args.seed, k)))
        return (30 + 5 * rng.random(len(ids))).astype(np.float32), \
            1_700_000_000 + k

    workdir = args.workdir or tempfile.mkdtemp(prefix="chaos_soak_")
    os.makedirs(workdir, exist_ok=True)
    alerts_path = os.path.join(workdir, "alerts.jsonl")
    # black-box coverage (ISSUE 4): every chaos run flies with the span
    # recorder + flight recorder armed, and the verdict below asserts a
    # chaos-induced quarantine left a VALID postmortem bundle behind
    from rtap_tpu.obs import FlightRecorder, TraceRecorder, validate_bundle

    trace = TraceRecorder(capacity=32768)
    pm_dir = os.path.join(workdir, "postmortems")
    flight = FlightRecorder(
        trace=trace, n_ticks=min(args.ticks, 240), out_dir=pm_dir,
        info={"command": "chaos_soak", "seed": args.seed,
              "schedule_digest": digest, "streams": args.streams,
              "group_size": args.group_size})
    stats = live_loop(
        source, reg, n_ticks=args.ticks, cadence_s=args.cadence,
        alert_path=alerts_path,
        checkpoint_dir=os.path.join(workdir, "ck"),
        checkpoint_every=args.checkpoint_every,
        quarantine_restore_after=args.restore_after,
        chaos=engine, trace=trace, flight=flight)

    with open(alerts_path) as f:
        events = [json.loads(line) for line in f
                  if line.startswith('{"event"')]
    failures: list[str] = []
    if stats["ticks"] != args.ticks:
        failures.append(
            f"loop stopped at tick {stats['ticks']} of {args.ticks}")
    # intervals come from the loop's own quarantine log, NOT the alert
    # stream: the sink may have been the faulted component, and a dropped
    # event line must not fail an otherwise-correct run
    intervals = _unquarantined_intervals(
        stats.get("quarantine_log", []), n_groups, stats["ticks"])
    expected = [sum(b - a for a, b in intervals[g]) * reg.groups[g].n_live
                for g in range(n_groups)]
    got = stats["scored_by_group"]
    for g in range(n_groups):
        if got[g] != expected[g]:
            failures.append(
                f"group{g}: scored {got[g]} but its unquarantined "
                f"intervals {intervals[g]} require {expected[g]} — streams "
                "silently stopped being scored while unquarantined")
    if sum(got) != stats["scored"]:
        failures.append(
            f"per-group counts sum to {sum(got)} != scored "
            f"{stats['scored']}")

    # ---- postmortem-bundle verdict: a chaos-injected quarantine must
    # leave a loadable black box behind (trace spans + event lines > 0)
    quarantines = [e for e in stats.get("quarantine_log", [])
                   if e["event"] == "group_quarantined"]
    bundle_dirs = sorted(
        os.path.join(pm_dir, d) for d in os.listdir(pm_dir)
        if not d.startswith(".tmp")) if os.path.isdir(pm_dir) else []
    verdicts = [validate_bundle(b) for b in bundle_dirs]
    if quarantines and not bundle_dirs:
        failures.append(
            f"{len(quarantines)} quarantine(s) occurred but no postmortem "
            "bundle was dumped")
    for b, v in zip(bundle_dirs, verdicts):
        if not v["ok"]:
            failures.append(f"invalid postmortem bundle {b}: {v['problems']}")
        elif v["events"] == 0:
            failures.append(f"postmortem bundle {b} captured zero events")
    pm_report = {
        "dir": pm_dir,
        "bundles": [os.path.basename(b) for b in bundle_dirs],
        "valid": sum(1 for v in verdicts if v["ok"]),
        "spans": sum(v["spans"] for v in verdicts),
        "instants": sum(v["instants"] for v in verdicts),
        "events": sum(v["events"] for v in verdicts),
        "dumps_skipped": stats.get("postmortem", {}).get("dumps_skipped", 0),
        "trace_records": trace.total,
        "trace_dropped": trace.dropped,
    }

    report = {
        "seed": args.seed,
        "schedule_digest": digest,
        "faults_scheduled": len(spec.faults),
        "faults_injected": engine.injected,
        "events": sorted({e["event"] for e in events}),
        "intervals": {f"group{g}": intervals[g] for g in range(n_groups)},
        "expected_by_group": expected,
        "postmortem": pm_report,
        "stats": stats,
        "verified": not failures,
        "failures": failures,
        "workdir": workdir,
    }
    if args.out:
        os.makedirs(os.path.dirname(os.path.abspath(args.out)),
                    exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2)
    print(json.dumps(report))
    if failures:
        for msg in failures:
            log(f"FAIL: {msg}")
        return VERIFY_FAILED_EXIT
    log(f"OK: {stats['scored']} scored, "
        f"{len(engine.injected)} faults injected, "
        f"{len(quarantines)} quarantines, "
        f"{pm_report['valid']} valid postmortem bundle(s)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
