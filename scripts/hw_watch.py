"""Harvest oscillating-tunnel windows: retry the hw_session agenda to done.

The TPU tunnel oscillates (SCALING.md: reachable for minutes, then backend
init hangs for tens of minutes — observed again this round: a window opened,
served layout_probe + half a profile, and wedged 6 minutes in). A one-shot
`hw_session.py` run burns its most-valuable-first steps on a dead tunnel;
this watcher instead loops the SAME agenda with a completion ledger:

- steps that exit rc=0 are recorded in hw_results/done.json and never rerun;
- a step that dies on backend init (the 120s watchdog,
  rc=INIT_WATCHDOG_EXIT) means the tunnel is down: sleep, then retry the
  same step — the init attempt IS the cheapest possible probe. Down-tunnel
  deaths never count toward --max-attempts (a wedged tunnel must never park
  the agenda), which is exactly why the watchdog code is distinctive and
  not 2 (argparse usage errors would retry forever);
- a step that times out mid-run (tunnel dropped under it) is retried too,
  up to --max-attempts, then parked as "gave_up" so one cursed step can't
  starve the rest of the agenda;
- hw_results/status.json always holds the live view (current step, tunnel
  state, ledger) for anything coordinating CPU-heavy work around the
  1-core host.

Usage: python scripts/hw_watch.py [--wall-budget 36000] [--budget-per-step 900]
       [--retry-sleep 90] [--max-attempts 6] [--steps 1,2,5]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from hw_session import (  # noqa: E402
    OUT, REPO, STEPS, log_tail, pick_steps, run_step, step_budget,
)

sys.path.insert(0, REPO)
from rtap_tpu.utils.platform import INIT_WATCHDOG_EXIT as INIT_FAIL_RC  # noqa: E402

DONE = os.path.join(OUT, "done.json")
STATUS = os.path.join(OUT, "status.json")


def log(msg: str) -> None:
    print(f"[hw_watch] {time.strftime('%H:%M:%S')} {msg}", file=sys.stderr, flush=True)


def _load(path: str) -> dict:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def _save(path: str, obj: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=2)
    os.replace(tmp, path)


def _status(ledger: dict, current: str | None, tunnel_up: bool | None) -> None:
    _save(STATUS, {
        "at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "current": current, "tunnel_up": tunnel_up,
        "done": sorted(k for k, v in ledger.items() if v.get("rc") == 0),
        "gave_up": sorted(k for k, v in ledger.items() if v.get("gave_up")),
    })


def ledger_entry_for(step: tuple, ledger: dict) -> dict:
    """The ledger entry for a step, ONLY if it was recorded for the step's
    CURRENT cmd (argv sans interpreter).

    A step edited between runs (same name, new flags) must re-run —
    whether it previously succeeded (the old log would masquerade as
    evidence for the new config) or gave up (a parked old experiment must
    not park its replacement). Entries without a recorded cmd
    (pre-cmd-ledger runs) are likewise no evidence."""
    e = ledger.get(step[0], {})
    return e if e.get("cmd") == step[1][1:] else {}


def pending_steps(picked: list[tuple], ledger: dict) -> list[tuple]:
    """Steps still owed a run: not completed-for-this-cmd, not given-up-
    for-this-cmd. Unit-tested (tests/unit/test_hw_watch_logic.py) — this
    decision gates which hardware evidence the round presents."""
    return [
        s for s in picked
        if ledger_entry_for(s, ledger).get("rc") != 0
        and not ledger_entry_for(s, ledger).get("gave_up")
    ]


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--wall-budget", type=float, default=36000.0)
    ap.add_argument("--budget-per-step", type=float, default=900.0)
    ap.add_argument("--retry-sleep", type=float, default=90.0)
    ap.add_argument("--max-attempts", type=int, default=6)
    ap.add_argument("--steps", default=None,
                    help="comma-separated 1-based step numbers (default all)")
    args = ap.parse_args()
    picked = pick_steps(args.steps)

    os.makedirs(OUT, exist_ok=True)
    ledger = _load(DONE)
    t_start = time.monotonic()
    # attempt counts carry over ONLY for entries recorded under the step's
    # current cmd — a redefined step is a new experiment with a fresh budget
    attempts: dict[str, int] = {
        s[0]: ledger_entry_for(s, ledger).get("attempts", 0) for s in picked
    }
    tunnel_up: bool | None = None

    while time.monotonic() - t_start < args.wall_budget:
        pending = pending_steps(picked, ledger)
        if not pending:
            log("agenda complete")
            _status(ledger, None, tunnel_up)
            return 0
        step = pending[0]
        name, cmd = step[0], step[1]
        budget = max(step_budget(step, args.budget_per_step), args.budget_per_step)
        _status(ledger, name, tunnel_up)
        log(f"step {name} (attempt {attempts.get(name, 0) + 1}/{args.max_attempts}, "
            f"{len(pending)} pending, budget {budget:.0f}s)")
        t0 = time.monotonic()
        rc = run_step(name, cmd, budget)
        dt = time.monotonic() - t0
        if rc != INIT_FAIL_RC:
            # an init-watchdog death is the tunnel's fault, not the step's:
            # only attempts that actually reached the backend count toward
            # the give-up limit (a down-tunnel must never park the agenda)
            attempts[name] = attempts.get(name, 0) + 1
        log(f"step {name}: rc={rc} in {dt:.0f}s — {log_tail(name)}")
        entry = {"rc": rc, "wall_s": round(dt, 1), "attempts": attempts.get(name, 0),
                 "at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                 "cmd": cmd[1:]}  # argv sans interpreter: the is_done() key
        if rc == 0:
            tunnel_up = True
            ledger[name] = entry
        else:
            tunnel_up = False if rc == INIT_FAIL_RC else tunnel_up
            if attempts.get(name, 0) >= args.max_attempts:
                entry["gave_up"] = True
                log(f"step {name}: giving up after {attempts[name]} attempts")
            ledger[name] = entry
            if not entry.get("gave_up"):
                log(f"tunnel looks {'down' if rc == INIT_FAIL_RC else 'flaky'}; "
                    f"sleeping {args.retry_sleep:.0f}s")
                time.sleep(args.retry_sleep)
        _save(DONE, ledger)
        _status(ledger, None, tunnel_up)
    log("wall budget exhausted")
    return 1


if __name__ == "__main__":
    sys.exit(main())
