"""Generate the committed dense-layout checkpoint fixture (ISSUE 18).

tests/unit/test_checkpoint.py's migration test restores this checkpoint
with ``load_group(..., sparsify=True)`` and asserts the migrated sparse
group reproduces the DENSE continuation recorded here bit-for-bit. The
fixture is committed so the test exercises a real cross-build restore — a
checkpoint written by the dense layout, read by the sparse build — not a
same-process round-trip.

Run from the repo root (CPU-only; the group runs on the JAX backend so the
checkpoint carries the batched [G, ...] tree and the restore path also
exercises the fwd-index rebuild):

    JAX_PLATFORMS=cpu python scripts/make_migration_fixture.py

Outputs (committed):
    tests/fixtures/migration/dense_ckpt/   orbax group checkpoint (dense SP pool)
    tests/fixtures/migration/expected.npz  values + the dense run's scores
"""

from __future__ import annotations

import pathlib
import shutil
import sys

import numpy as np

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from rtap_tpu.config import DateConfig, ModelConfig, RDSEConfig, SPConfig, TMConfig  # noqa: E402

FIXTURE_DIR = pathlib.Path(__file__).resolve().parent.parent / "tests" / "fixtures" / "migration"
WARM_TICKS = 60   # ticks before the checkpoint is cut
TAIL_TICKS = 40   # dense-continuation ticks recorded for the migration test
G = 2


def fixture_config() -> ModelConfig:
    """Small dense-pool model (perm_bits=16) — the committed checkpoint's
    geometry, kept tiny so the binary fixture stays a few tens of KB."""
    return ModelConfig(
        rdse=RDSEConfig(size=64, active_bits=7, resolution=0.5),
        date=DateConfig(time_of_day_width=0, time_of_day_size=0, weekend_width=0),
        sp=SPConfig(columns=64, potential_pct=0.8, num_active_columns=6,
                    syn_perm_active_inc=0.01, syn_perm_inactive_dec=0.002,
                    perm_bits=16),
        tm=TMConfig(cells_per_column=4, activation_threshold=3, min_threshold=2,
                    max_segments_per_cell=2, max_synapses_per_segment=8,
                    new_synapse_count=6, learn_cap=32, col_cap=6, perm_bits=16),
    )


def fixture_values(n: int = WARM_TICKS + TAIL_TICKS) -> np.ndarray:
    """Deterministic [n, G] stream values: phase-shifted sines + noise, one
    spike in the recorded tail so the scores are not flat."""
    rng = np.random.Generator(np.random.Philox(key=(77, 0xD15E)))
    t = np.arange(n)[:, None]
    phase = np.array([0.0, 1.3])[None, :]
    v = 50 + 12 * np.sin(2 * np.pi * t / 24.0 + phase) + rng.normal(0, 1.5, (n, G))
    v[WARM_TICKS + 12, 0] += 40.0
    return v.astype(np.float32)


def main() -> None:
    from rtap_tpu.service.checkpoint import save_group
    from rtap_tpu.service.registry import StreamGroup

    cfg = fixture_config()
    assert not cfg.sp.sparse_pool, "the fixture must be a DENSE-layout checkpoint"
    vals = fixture_values()
    grp = StreamGroup(cfg, [f"m{i}" for i in range(G)], backend="tpu")
    for i in range(WARM_TICKS):
        grp.tick(vals[i], 1_700_000_000 + i)

    if FIXTURE_DIR.exists():
        shutil.rmtree(FIXTURE_DIR)
    FIXTURE_DIR.mkdir(parents=True)
    save_group(grp, FIXTURE_DIR / "dense_ckpt")

    raw, loglik = [], []
    for i in range(WARM_TICKS, WARM_TICKS + TAIL_TICKS):
        r = grp.tick(vals[i], 1_700_000_000 + i)
        raw.append(np.asarray(r.raw))
        loglik.append(np.asarray(r.log_likelihood))
    np.savez(FIXTURE_DIR / "expected.npz",
             vals=vals, raw=np.stack(raw), log_likelihood=np.stack(loglik),
             warm_ticks=WARM_TICKS)
    total = sum(p.stat().st_size for p in FIXTURE_DIR.rglob("*") if p.is_file())
    print(f"fixture written to {FIXTURE_DIR} ({total:,} bytes)", file=sys.stderr)


if __name__ == "__main__":
    main()
