"""Measure the double-buffered feed gain on hardware (SURVEY.md §7 hard
part 3; round-2 verdict task 6's "measured overlap gain").

Compares, at steady state on the same StreamGroup:

- synchronous replay: run_chunk per chunk (device compute, then host
  likelihood, strictly alternating);
- pipelined replay: dispatch_chunk/collect_chunk depth-2 (host likelihood of
  chunk t overlaps device compute of chunk t+1 — utils/measure.py).

Prints one JSON line: {"sync": m/s, "pipelined": m/s, "gain": x}. The gain
is bounded by min(host, device) / max(host, device) overlap; with the host
likelihood measured ~250x faster than the device step (r3), expect a few
percent at most — the point is to MEASURE it, not assume it.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from rtap_tpu.utils.platform import (  # noqa: E402
    enable_compile_cache, init_backend_or_die, maybe_force_cpu,
)

maybe_force_cpu()
init_backend_or_die()


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--G", type=int, default=2048)
    ap.add_argument("--T", type=int, default=64)
    ap.add_argument("--chunks", type=int, default=4)
    args = ap.parse_args()

    enable_compile_cache(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from rtap_tpu.config import cluster_preset
    from rtap_tpu.service.registry import StreamGroup
    from rtap_tpu.utils.measure import make_sine_feed, measure_pipelined

    G, T = args.G, args.T
    grp = StreamGroup(cluster_preset(), [f"p{i:05d}" for i in range(G)], backend="tpu")
    vals, ts, _ = make_sine_feed(G, T, key=(9, 9))
    grp.run_chunk(vals, ts)  # warmup/compile

    t0 = time.perf_counter()
    for i in range(args.chunks):
        grp.run_chunk(vals, ts + (i + 1) * T)
    sync = args.chunks * T * G / (time.perf_counter() - t0)

    pipelined, _ = measure_pipelined(grp, vals, ts + (args.chunks + 1) * T, args.chunks)

    print(json.dumps({
        "G": G, "T": T,
        "sync_metrics_per_s": round(sync, 1),
        "pipelined_metrics_per_s": round(pipelined, 1),
        "gain": round(pipelined / sync, 4),
    }), flush=True)


if __name__ == "__main__":
    main()
