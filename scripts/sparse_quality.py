"""Sparse-vs-dense quality A/B -> reports/sparse_quality.json (ISSUE 18).

Two pieces of committed evidence for the member-index pool flip:

1. Held-out detection quality A/B: the fault-injection eval (family=
   "heldout" — the heavy-tailed/bursty/regime-switching world no preset was
   tuned on) run on the shipping sparse ``cluster_preset`` and on
   ``dense_cluster_preset`` (the pre-flip geometry: potential_pct=0.8 dense
   pools, S=4 TM lanes). Acceptance (one-sided): f1_sparse >= f1_dense -
   0.01 at each config's swept-best operating point.

2. TM segment-occupancy evidence for the S=4 -> S=2 lane cut: replay
   single-metric streams through the DENSE (S=4) config and histogram
   segments-in-use per cell (``seg_last >= 0``). The knob change is honest
   only if lanes 3-4 are essentially empty at convergence.

Usage:
    RTAP_FORCE_CPU=1 python scripts/sparse_quality.py [--streams 40]
        [--length 1000] [--quick]

Writes reports/sparse_quality.json and prints one JSON line per measurement
to stderr as it goes (partial progress survives a kill).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from rtap_tpu.utils.platform import maybe_force_cpu  # noqa: E402

maybe_force_cpu()

import numpy as np  # noqa: E402

REPORT = os.path.join(REPO, "reports", "sparse_quality.json")


def _window_mode(cfg):
    return dataclasses.replace(
        cfg, likelihood=dataclasses.replace(cfg.likelihood, mode="window"))


def _progress(obj) -> None:
    print(json.dumps(obj), file=sys.stderr, flush=True)


def eval_config(label: str, cfg, n_streams: int, length: int, seed: int) -> dict:
    """Held-out fault-eval for one config; returns the committed summary."""
    from rtap_tpu.eval.fault_eval import run_fault_eval

    t0 = time.perf_counter()
    rep = run_fault_eval(n_streams=n_streams, length=length,
                         cfg=_window_mode(cfg), backend="tpu",
                         chunk_ticks=128, seed=seed, family="heldout")
    out = {
        "label": label,
        "at_best": rep.at_best,
        "best_threshold": rep.best_threshold,
        "best_debounce": rep.best_debounce,
        "at_default": rep.at_default,
        "per_kind": rep.per_kind,
        "wall_s": round(time.perf_counter() - t0, 1),
    }
    _progress({"eval": label, "f1": rep.at_best["f1"], "wall_s": out["wall_s"]})
    return out


def measure_occupancy(cfg, n_streams: int = 8, length: int = 900,
                      seed: int = 23) -> dict:
    """Replay single-metric streams on the dense S=4 config and histogram
    segments-in-use per cell at the end of learning."""
    from rtap_tpu.data.synthetic import SyntheticStreamConfig, generate_stream
    from rtap_tpu.models.htm_model import HTMModel

    metrics = ("cpu", "mem", "net", "disk_io", "latency_ms")
    S = cfg.tm.max_segments_per_cell
    counts = np.zeros(S + 1, np.int64)  # counts[k] = cells using exactly k segments
    for i in range(n_streams):
        s = generate_stream(
            f"occ{i:03d}.{metrics[i % len(metrics)]}",
            SyntheticStreamConfig(length=length, n_anomalies=1,
                                  kinds=("level_shift",), anomaly_magnitude=6.0,
                                  noise_phi=0.97, noise_scale=0.5,
                                  inject_after_frac=0.6,
                                  metric=metrics[i % len(metrics)]),
            seed=seed + i,
        )
        m = HTMModel(cfg, seed=seed + i, backend="cpu")
        for t in range(length):
            m.run(int(s.timestamps[t]), float(s.values[t]))
        used = (m.state["seg_last"] >= 0).sum(axis=-1).ravel()
        counts += np.bincount(used, minlength=S + 1)
    total = int(counts.sum())
    frac = (counts / total).round(6).tolist()
    over2 = float(counts[3:].sum() / total) if S >= 3 else 0.0
    out = {
        "config": "dense_cluster_preset (S=4)",
        "n_streams": n_streams, "ticks": length,
        "cells_total": total,
        "cells_by_segments_used": counts.tolist(),
        "frac_by_segments_used": frac,
        "frac_cells_needing_gt2_segments": round(over2, 6),
    }
    _progress({"occupancy": out["frac_by_segments_used"],
               "frac_gt2": out["frac_cells_needing_gt2_segments"]})
    return out


def main() -> None:
    from rtap_tpu.config import cluster_preset, dense_cluster_preset

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--streams", type=int, default=40)
    ap.add_argument("--length", type=int, default=1000)
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--quick", action="store_true",
                    help="8-stream smoke run (not for the committed report; "
                         "length stays >= probation + margin)")
    args = ap.parse_args()
    n, length = (8, args.length) if args.quick else (args.streams, args.length)

    sparse = eval_config("cluster_preset (sparse P=64, S=2)",
                         cluster_preset(), n, length, args.seed)
    dense = eval_config("dense_cluster_preset (dense pct=0.8, S=4)",
                        dense_cluster_preset(), n, length, args.seed)
    delta = round(sparse["at_best"]["f1"] - dense["at_best"]["f1"], 4)
    occ = measure_occupancy(dense_cluster_preset())

    report = {
        "issue": 18,
        "family": "heldout",
        "n_streams": n, "n_ticks": length, "seed": args.seed,
        "sparse": sparse,
        "dense_baseline": dense,
        "f1_delta_sparse_minus_dense": delta,
        # acceptance is one-sided: sparse may not be WORSE than dense by
        # more than 0.01 (being better is fine)
        "f1_no_worse_than_dense_minus_0.01": bool(delta >= -0.01 - 1e-9),
        "tm_segment_occupancy": occ,
    }
    os.makedirs(os.path.dirname(REPORT), exist_ok=True)
    with open(REPORT, "w") as f:
        json.dump(report, f, indent=2)
        f.write("\n")
    _progress({"wrote": os.path.relpath(REPORT, REPO), "f1_delta": delta})


if __name__ == "__main__":
    main()
