"""Splice per-process Chrome traces onto one fleet Perfetto timeline.

The CLI over ``rtap_tpu.fleet.stitch_traces`` (ISSUE 19): feed it the
trace JSONs the processes exported (``GET /trace`` bodies, soak
artifacts) and it rebases every one onto the earliest recorder epoch —
a killed leader's final ticks and its standby's promotion spans land in
causal order on ONE timeline, each on its own named process track.

``--members`` takes a fleet snapshot JSON (``ha/fleet_snapshot.json``,
``GET /fleet/snapshot``) or a bare ``/fleet/members`` roster; the
registration clock offsets in it correct wall-clock disagreement
between hosts (the HELLO clock-alignment handshake) — without it the
stitch trusts each process's own wall clock.

Usage:
  python scripts/fleet_trace.py leader.trace.json standby.trace.json \
      --members /tmp/soak/ha/fleet_snapshot.json -o fleet.trace.json
  # then load fleet.trace.json in https://ui.perfetto.dev
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from rtap_tpu.fleet import stitch_traces  # noqa: E402


def _load_members(path: str) -> list[dict]:
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, list):  # a bare /fleet/members body
        return doc
    return doc.get("members") or []


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("traces", nargs="+", metavar="TRACE_JSON",
                    help="per-process Chrome trace files (GET /trace "
                         "bodies; obs/trace.py chrome_trace() docs)")
    ap.add_argument("--members", default=None,
                    help="fleet snapshot or /fleet/members JSON whose "
                         "clock_offset_s corrects each trace (matched "
                         "by pid)")
    ap.add_argument("-o", "--out", default=None,
                    help="write the stitched trace here (default: "
                         "stdout)")
    args = ap.parse_args()

    docs = []
    for path in args.traces:
        with open(path) as f:
            doc = json.load(f)
        if "traceEvents" not in doc:
            raise SystemExit(f"{path} is not a Chrome trace "
                             "(no traceEvents)")
        docs.append(doc)
    members = _load_members(args.members) if args.members else None
    stitched = stitch_traces(docs, members=members)
    other = stitched["otherData"]
    print(f"[fleet-trace] stitched {other.get('stitched_from', 0)} "
          f"trace(s), {len(stitched['traceEvents'])} events",
          file=sys.stderr)
    for p in other.get("processes", []):
        print(f"[fleet-trace]   {p.get('process_name')} pid "
              f"{p.get('pid')} -> track {p.get('stitched_pid')} "
              f"(+{p.get('shift_us', 0)}us)", file=sys.stderr)
    if args.out:
        with open(args.out, "w") as f:
            f.write(json.dumps(stitched) + "\n")
    else:
        print(json.dumps(stitched))
    return 0


if __name__ == "__main__":
    sys.exit(main())
