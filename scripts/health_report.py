"""Pretty-print a fleet model-health scorecard (ISSUE 6).

Renders the one health schema everywhere it lands:

- live, from a serving process: ``--url http://127.0.0.1:PORT/health``
  (the obs server route — ``serve --health --obs-port``),
- from a JSON file holding a /health snapshot (e.g. ``curl`` output or
  a harness artifact),
- from a postmortem bundle dir (reads ``summary.json``'s embedded
  ``health`` block — triage gets model state, not just timing).

``--json`` emits the machine view (the snapshot itself). Exit code: 0
when a health block was found and rendered, 2 otherwise, so harnesses
can gate on it.

Usage: python scripts/health_report.py TARGET [--json] [--groups N]
       python scripts/health_report.py --url http://HOST:PORT/health
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

INVALID_EXIT = 2

#: occupancy-histogram bar glyphs (eighth blocks, ascending)
_BARS = " ▁▂▃▄▅▆▇█"


def err(msg: str) -> None:
    print(f"[health] {msg}", file=sys.stderr, flush=True)


def _sparkline(hist) -> str:
    hist = [float(x) for x in (hist or [])]
    top = max(hist) if hist else 0.0
    if top <= 0:
        return "·" * len(hist)
    return "".join(_BARS[min(8, int(round(v / top * 8)))] for v in hist)


def load_snapshot(target: str | None, url: str | None) -> dict | None:
    """Resolve TARGET/--url to a health snapshot dict, or None."""
    if url:
        import urllib.request

        try:
            with urllib.request.urlopen(url, timeout=10) as r:
                return json.load(r)
        except Exception as e:  # noqa: BLE001 — CLI surface, say why
            err(f"GET {url} failed: {e}")
            return None
    if target is None:
        return None
    path = target
    if os.path.isdir(path):
        path = os.path.join(path, "summary.json")
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        err(f"cannot read {path}: {e}")
        return None
    if isinstance(doc, dict) and "fleet" in doc and "groups" in doc:
        return doc  # a /health snapshot
    if isinstance(doc, dict) and isinstance(doc.get("health"), dict):
        return doc["health"]  # a postmortem summary.json
    err(f"{path} holds no health snapshot (need fleet+groups, or a "
        "postmortem summary.json with a health block — was the serve "
        "run started with --health?)")
    return None


def render(snap: dict, max_groups: int) -> str:
    fleet = snap.get("fleet", {})
    groups = snap.get("groups", [])
    lines = []
    lines.append(
        f"fleet health: {fleet.get('verdict', '?')} "
        f"({fleet.get('groups', 0)} groups, "
        f"{fleet.get('ticks_folded', 0)} ticks folded)")
    hr = fleet.get("hit_rate")
    lines.append(
        f"  pool occupancy max : {fleet.get('pool_occupancy_max')}"
        f"    hit rate : {'n/a' if hr is None else round(hr, 4)}"
        f"    active-col frac : {fleet.get('active_col_frac_mean')}")
    lines.append(
        f"  score drift max    : {fleet.get('score_drift_max')}"
        f"    incidents : {fleet.get('events_by_kind') or 'none'}")
    att = fleet.get("groups_attention") or []
    if att:
        lines.append(f"  needs attention    : groups {att}")
    show = groups[:max_groups]
    for g in show:
        occ, syn, sp, sc = (g.get("occupancy", {}), g.get("synapses", {}),
                            g.get("sparsity", {}), g.get("score", {}))
        q = sc.get("quantiles") or {}
        lines.append(
            f"  group {g.get('group'):>3} [{g.get('verdict', '?')}] "
            f"occ {occ.get('frac')} |{_sparkline(occ.get('hist'))}| "
            f"conn {syn.get('connected_frac')} "
            f"act {sp.get('active_col_frac')}"
            f"/{sp.get('expected_active_frac')} "
            f"hit {g.get('hit_rate')} "
            f"p50/p90/p99 {q.get('p50')}/{q.get('p90')}/{q.get('p99')} "
            f"drift {sc.get('drift_tvd')}"
            f"{' DRIFTING' if sc.get('drifting') else ''}")
    if len(groups) > len(show):
        lines.append(f"  ... {len(groups) - len(show)} more groups "
                     "(--groups N to widen)")
    return "\n".join(lines)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("target", nargs="?", default=None,
                    help="health snapshot JSON file, or a postmortem "
                         "bundle dir (reads its summary.json)")
    ap.add_argument("--url", default=None,
                    help="fetch the snapshot live from a serving "
                         "process's GET /health route")
    ap.add_argument("--json", action="store_true",
                    help="emit the machine view (the snapshot JSON)")
    ap.add_argument("--groups", type=int, default=16,
                    help="per-group rows to render (default 16)")
    args = ap.parse_args()
    if (args.target is None) == (args.url is None):
        err("pass exactly one of TARGET or --url")
        return INVALID_EXIT
    snap = load_snapshot(args.target, args.url)
    if snap is None:
        return INVALID_EXIT
    if args.json:
        print(json.dumps(snap, indent=2))
    else:
        print(render(snap, args.groups), file=sys.stdout)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
