"""Memory/throughput scaling-law experiment -> SCALING.md (SURVEY.md §7
hard part 4; round-2 verdict task 2: "run the memory scaling-law experiment
and fix the 9x lie").

Three measurement families:

1. analytic per-stream state bytes per permanence domain (models/state.
   state_nbytes — sums the real arrays, the number the config docstrings
   quote) and the implied max-streams-per-chip at the v5e HBM budget;
2. on-device G-sweep: metrics/s and HBM in use per group size up to the OOM
   frontier (requires the TPU; skipped with a note when the tunnel is down);
3. detection-quality-vs-domain: fault-injection eval f1 for perm_bits
   0/16/8 (CPU, slow — enable with --quality).

Usage:
    python scripts/scaling_law.py [--quality] [--gs 1024,4096,...]
    RTAP_FORCE_CPU=1 python scripts/scaling_law.py   # analytic only

Writes SCALING.md at the repo root and prints one JSON line per measurement
to stderr as it goes (partial progress survives a kill).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from rtap_tpu.utils.platform import init_backend_or_die, maybe_force_cpu  # noqa: E402

FORCED_CPU = maybe_force_cpu()

# Marker separating the generated tables from hand-written analysis below it
# (100k shard proof, likelihood-mode study, ...). write_scaling_md preserves
# everything from this line on, so re-running the sweep can never destroy
# committed measurements that were appended by other experiments.
MANUAL_MARKER = "<!-- MANUAL: everything below survives scaling_law.py re-runs -->"

HBM_BYTES = 16 * 1024**3  # v5e: 16 GiB HBM per chip
WORKSPACE_RESERVE = 1.5 * 1024**3  # headroom for XLA workspace + feed buffers


def log(obj) -> None:
    print(json.dumps(obj), file=sys.stderr, flush=True)


def analytic_rows():
    from rtap_tpu.config import cluster_preset
    from rtap_tpu.models.state import state_nbytes

    rows = []
    for bits in (0, 16, 8):
        n = state_nbytes(cluster_preset(perm_bits=bits))
        per = n["total"]
        fit = int((HBM_BYTES - WORKSPACE_RESERVE) // per)
        top = [(k, v) for k, v in n.items() if k != "total"][:4]
        rows.append({"perm_bits": bits, "bytes_per_stream": per,
                     "max_streams_per_chip": fit, "top_tensors": top})
        log({"analytic": rows[-1]})
    return rows


def sparse_frontier_rows():
    """Member-index pool ladder (u16 domain): bytes/stream and streams/chip
    as the per-column pool width P moves, plus the legacy dense layout —
    the r16 decision table for trading pool capacity against the memory
    frontier. Analytic (state_nbytes on real arrays), so it regenerates on
    every run. Row labels deliberately do NOT match the analyzer's checked
    per-domain rows (those stay the single source of truth for the preset)."""
    import dataclasses

    from rtap_tpu.config import cluster_preset, dense_cluster_preset
    from rtap_tpu.models.state import state_nbytes

    base = cluster_preset(perm_bits=16)
    preset_p = base.sp_members
    rows = []
    for P in (32, 48, 64, 96):
        cfg = dataclasses.replace(
            base, sp=dataclasses.replace(base.sp, pool_members=P))
        per = state_nbytes(cfg)["total"]
        label = f"sparse P={P}" + (" (preset)" if P == preset_p else "")
        rows.append({"label": label, "bytes_per_stream": per,
                     "max_streams_per_chip": int((HBM_BYTES - WORKSPACE_RESERVE) // per)})
    dense = state_nbytes(dense_cluster_preset(perm_bits=16))["total"]
    rows.append({"label": "dense legacy (potential_pct=0.8, S=4)",
                 "bytes_per_stream": dense,
                 "max_streams_per_chip": int((HBM_BYTES - WORKSPACE_RESERVE) // dense)})
    for r in rows:
        log({"frontier": r})
    return rows


def device_sweep(gs: list[int], chunk_ticks: int = 64, measure_chunks: int = 3):
    import jax

    from rtap_tpu.utils.platform import enable_compile_cache

    enable_compile_cache(REPO)
    backend = jax.default_backend()
    dev = jax.devices()[0]
    rows = []
    from rtap_tpu.config import cluster_preset
    from rtap_tpu.service.registry import StreamGroup
    from rtap_tpu.utils.measure import make_sine_feed, measure_pipelined

    for G in gs:
        try:
            cfg = cluster_preset()
            grp = StreamGroup(cfg, [f"s{i:06d}" for i in range(G)], backend="tpu")
            vals, ts, _ = make_sine_feed(G, chunk_ticks, key=(1, G))
            t0 = time.perf_counter()
            grp.run_chunk(vals, ts)
            compile_s = time.perf_counter() - t0
            mps, _ = measure_pipelined(grp, vals, ts, measure_chunks)
            stats = dev.memory_stats() or {}
            hbm = stats.get("bytes_in_use", stats.get("peak_bytes_in_use", 0))
            row = {"G": G, "metrics_per_s": round(mps, 1), "compile_s": round(compile_s, 1),
                   "hbm_bytes_in_use": int(hbm), "backend": backend}
            rows.append(row)
            log({"sweep": row})
            del grp
        except Exception as e:  # OOM frontier or tunnel flake: record and stop
            rows.append({"G": G, "error": f"{type(e).__name__}: {str(e)[:200]}"})
            log({"sweep": rows[-1]})
            break
    return rows, backend


def quality_rows(n_streams: int = 40, length: int = 1000):
    import dataclasses

    from rtap_tpu.config import cluster_preset
    from rtap_tpu.eval.fault_eval import run_fault_eval

    rows = []
    for bits in (0, 16, 8):
        base = cluster_preset(perm_bits=bits)
        cfg = dataclasses.replace(
            base, likelihood=dataclasses.replace(base.likelihood, mode="window")
        )
        rep = run_fault_eval(n_streams=n_streams, length=length, cfg=cfg,
                             backend="tpu", chunk_ticks=128)
        b = rep.at_best
        rows.append({"perm_bits": bits, "f1": b["f1"], "recall": b["recall"],
                     "precision_episodes": b["precision"],
                     "median_latency_s": b["median_latency_s"]})
        log({"quality": rows[-1]})
    return rows


def _carry_section(old_generated: str, heading_prefix: str) -> list[str] | None:
    """Lines of the old generated section starting with `heading_prefix`, up
    to the next '## ' heading — so a run without fresh data for a section
    re-emits the previous run's measurements instead of a placeholder."""
    lines = old_generated.splitlines()
    start = next((i for i, l in enumerate(lines) if l.startswith(heading_prefix)), None)
    if start is None:
        return None
    end = next(
        (j for j in range(start + 1, len(lines)) if lines[j].startswith("## ")), len(lines)
    )
    block = lines[start:end]
    while block and not block[-1].strip():  # normalize: exactly one trailing blank
        block.pop()
    return block + [""]


def write_scaling_md(analytic, sweep, sweep_backend, quality, frontier=None) -> None:
    path = os.path.join(REPO, "SCALING.md")
    old = open(path).read() if os.path.exists(path) else ""
    if MANUAL_MARKER in old:
        old_generated, manual = old[: old.index(MANUAL_MARKER)], old[old.index(MANUAL_MARKER):]
    else:
        old_generated, manual = old, ""
    lines = [
        "# SCALING — measured memory & throughput laws (cluster preset)",
        "",
        "Generated by `scripts/scaling_law.py`. The honest per-stream budget",
        "comes from `models/state.state_nbytes` (sums the actual arrays);",
        "round 2 shipped a hand-derived \"~112 KB/stream\" figure that was 9x",
        "off — these tables replace guesses with measurements.",
        "",
        "## Per-stream device state (analytic, exact)",
        "",
        "| perm domain | bytes/stream | max streams/chip (16 GiB − 1.5 GiB reserve) |",
        "|---|---|---|",
    ]
    for r in analytic:
        dom = {0: "f32", 16: "u16 quanta", 8: "u8 quanta"}[r["perm_bits"]]
        lines.append(f"| {dom} | {r['bytes_per_stream']:,} | {r['max_streams_per_chip']:,} |")
    a16 = next(r for r in analytic if r["perm_bits"] == 16)
    a8 = next(r for r in analytic if r["perm_bits"] == 8)
    # Prose quotes the EXACT derived byte figures (a //1024 "KB" rounding
    # here once drifted 10 KB from the table it sits next to — ISSUE 18
    # satellite 1; the scaling-math analyzer checks the table rows, and the
    # prose must cite the same numbers verbatim).
    lines += [
        "",
        f"Largest tensors (u16 domain): "
        + ", ".join(f"`{k}` {v:,} B" for k, v in a16["top_tensors"]) + ".",
        "",
        "**The 100k-streams-on-ONE-chip north star is NOT reached even at the",
        "sparse cluster preset** (needs ≤ ~155 KB/stream; u8 reaches "
        f"{a8['bytes_per_stream']:,} B/stream = {a8['max_streams_per_chip']:,} "
        "streams/chip). "
        "It IS achievable on a v5e-8 pod: 100k streams / 8 chips x "
        f"{a16['bytes_per_stream']:,} B ≈ "
        f"{100_000 // 8 * a16['bytes_per_stream'] / 1024**3:.1f} GiB per chip "
        "(u16 domain), well inside HBM — the sharded path `sharded_chunk_step`",
        "is collective-free, so scale-out is linear by construction.",
        "Single-chip beyond the frontier requires shrinking the pools further",
        "(quality trade measured in the fault eval) — not promised here.",
        "",
    ]
    if frontier:
        lines += [
            "## Sparse frontier (member-index pool ladder, u16 domain)",
            "",
            "Pool width P is the per-column member count (`SPConfig.pool_members`;",
            "0 derives P from `potential_pct`). The dense legacy row is",
            "`dense_cluster_preset` — the pre-sparse geometry kept for the frozen",
            "golden, checkpoint migration, and the quality A/B baseline.",
            "",
            "| layout | bytes/stream | streams/chip |",
            "|---|---|---|",
        ]
        for r in frontier:
            lines.append(f"| {r['label']} | {r['bytes_per_stream']:,} "
                         f"| {r['max_streams_per_chip']:,} |")
        lines.append("")
    elif carried := _carry_section(old_generated, "## Sparse frontier"):
        lines += carried
    if sweep:
        lines += [
            f"## Device G-sweep (backend: {sweep_backend}, chunked replay, "
            "depth-2 pipelined)",
            "",
            "| G (streams) | metrics/s | compile s | HBM in use |",
            "|---|---|---|---|",
        ]
        for r in sweep:
            if "error" in r:
                lines.append(f"| {r['G']:,} | — | — | {r['error']} |")
            else:
                lines.append(
                    f"| {r['G']:,} | {r['metrics_per_s']:,.0f} | {r['compile_s']} | "
                    f"{r['hbm_bytes_in_use'] / 1024**3:.2f} GiB |"
                )
        lines.append("")
    elif carried := _carry_section(old_generated, "## Device G-sweep"):
        lines += carried
    else:
        lines += [
            "## Device G-sweep",
            "",
            "_Not measured in this run (TPU tunnel unavailable); re-run",
            "`python scripts/scaling_law.py` on hardware to fill this table._",
            "",
        ]
    if quality:
        lines += [
            "## Detection quality vs permanence domain (fault-injection eval,",
            "40 streams x 1000 s, magnitude 6, F1-optimal threshold)",
            "",
            "| perm domain | f1 | recall | precision (episodes) | median latency |",
            "|---|---|---|---|---|",
        ]
        for r in quality:
            dom = {0: "f32", 16: "u16", 8: "u8"}[r["perm_bits"]]
            lines.append(
                f"| {dom} | {r['f1']:.3f} | {r['recall']:.3f} | "
                f"{r['precision_episodes']:.3f} | {r['median_latency_s']} s |"
            )
        lines.append("")
    elif carried := _carry_section(old_generated, "## Detection quality"):
        lines += carried
    # idempotent tail: exactly one blank line, the manual block (normalized),
    # one trailing newline — repeated runs must not accrete whitespace
    while lines and not lines[-1].strip():
        lines.pop()
    lines += ["", (manual.rstrip() if manual else MANUAL_MARKER), ""]
    with open(path, "w") as f:
        f.write("\n".join(lines))
    log({"wrote": "SCALING.md"})


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    # Default brackets the measured r3 frontier: throughput peaks at small G
    # (38,956 at 256) and OOM lands between 8k and 16k (SCALING.md G-sweep).
    ap.add_argument("--gs", default="256,512,1024,2048,4096,8192,12288,16384",
                    help="comma-separated group sizes for the device sweep")
    ap.add_argument("--quality", action="store_true",
                    help="run the (slow) per-domain fault-eval comparison")
    ap.add_argument("--no-sweep", action="store_true",
                    help="skip the device sweep (analytic/quality only)")
    args = ap.parse_args()

    analytic = analytic_rows()
    frontier = sparse_frontier_rows()
    sweep, backend = ([], "none")
    if not args.no_sweep and not FORCED_CPU:
        # persist the analytic tables BEFORE touching the backend: the init
        # watchdog hard-exits (os._exit) on a wedged tunnel, which would
        # otherwise lose this run's results entirely
        write_scaling_md(analytic, sweep, backend, [], frontier)
        init_backend_or_die()
        sweep, backend = device_sweep([int(g) for g in args.gs.split(",")])
    quality = quality_rows() if args.quality else []
    write_scaling_md(analytic, sweep, backend, quality, frontier)


if __name__ == "__main__":
    main()
