"""Host ingest-path benchmark: JSONL (native C / pure Python) vs the RB1
binary batch protocol (socket and shared-memory ring).

The chip can score ~245k metrics/s (BENCH_LKG.json headline); the host
core that feeds it must ingest at least that many records/s while ALSO
driving the device and computing likelihoods. Per-record JSONL tops out
near ~100k records/s end-to-end on this class of host — the binding
edge ROADMAP item 5 names. This measures every transport over the same
record stream on one host core and writes the comparison artifact
(reports/ingest_r07.json is the committed ISSUE 7 gate: binary >= 1M
rows/s parsed on the 1-core tier-1 host AND >= 5x the JSONL TCP path).

    python scripts/ingest_bench.py [--records 1000000] [--streams 4096]
        [--frame-rows 4096] [--out reports/ingest_bench.json]
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

from rtap_tpu.service.sources import TcpJsonlSource  # noqa: E402


def make_payload(n_records: int, ids: list[str]) -> bytes:
    G = len(ids)
    return "".join(
        json.dumps({"id": ids[i % G], "value": 1.0 + (i % 1000) * 0.5,
                    "ts": 1_700_000_000 + i}) + "\n"
        for i in range(n_records)
    ).encode()


SENTINEL = -987654.5  # distinctive final-record value; in-order delivery
# (TCP / ring FIFO) means seeing it implies every earlier record was parsed


def socket_drive(native: bool, payload: bytes, n_records: int,
                 ids: list[str]) -> dict:
    """Push the payload through the real listener; wall time until the
    in-order sentinel record (appended after the payload) is applied —
    identical completion detection for both paths, so the speedup compares
    full parse pipelines, not a full pipeline vs a sendall return."""
    src = TcpJsonlSource(ids, native=native)
    tail = (json.dumps({"id": ids[0], "value": SENTINEL}) + "\n").encode()
    with src:
        t0 = time.perf_counter()
        with socket.create_connection(src.address, timeout=5.0) as s:
            s.sendall(payload + tail)
        deadline = time.time() + 600
        done = False
        while time.time() < deadline:
            with src._lock:
                done = src._latest[0] == np.float32(SENTINEL)
            if done:
                break
            time.sleep(0.005)
        dt = time.perf_counter() - t0
    if not done:
        raise SystemExit("ingest bench: payload not fully consumed in budget")
    return {"records_per_sec": round(n_records / dt), "wall_s": round(dt, 3)}


def inproc_drive(payload: bytes, n_records: int, ids: list[str]) -> dict:
    """Parser cost alone (no socket): feed 64 KiB chunks like the handler."""
    from rtap_tpu.native import NativeJsonlState

    latest = np.full(len(ids), np.nan, np.float32)
    st = NativeJsonlState(ids, latest)
    conn = st.new_conn()
    t0 = time.perf_counter()
    for off in range(0, len(payload), 65536):
        conn.feed(payload[off:off + 65536])
    conn.flush()
    dt = time.perf_counter() - t0
    assert st.counters[0] == n_records, st.counters
    conn.close()
    return {"records_per_sec": round(n_records / dt), "wall_s": round(dt, 3)}


# ------------------------------------------------------------- binary ----


def make_frames(n_records: int, slot_map: dict, ids: list[str],
                frame_rows: int) -> list[bytes]:
    """The same record stream as make_payload, as RB1 DATA frames."""
    from rtap_tpu.ingest.protocol import data_frame, encode_slot

    G = len(ids)
    code_by_pos = np.array(
        [encode_slot(a.shard, a.group, a.slot)
         for a in (slot_map[s] for s in ids)], np.uint32)
    idx = np.arange(n_records, dtype=np.int64)
    codes = code_by_pos[idx % G]
    values = (1.0 + (idx % 1000) * 0.5).astype(np.float32)
    frames = []
    for off in range(0, n_records, frame_rows):
        sl = slice(off, min(off + frame_rows, n_records))
        frames.append(data_frame(codes[sl], values[sl],
                                 1_700_000_000 + off,
                                 # rtap: allow[dtype-domain] — RB1 ts_delta wire field is u16 by layout, not a permanence grid
                                 deltas=(idx[sl] - off).astype(np.uint16)))
    return frames


def binary_socket_drive(frames: list[bytes], n_records: int,
                        slot_map: dict, ids: list[str]) -> dict:
    """Full pipeline over a real socket: frame walk + CRC + decode +
    scatter, sentinel-terminated like the JSONL drives."""
    from rtap_tpu.ingest import BinaryBatchSource
    from rtap_tpu.ingest.protocol import data_frame

    src = BinaryBatchSource(slot_map).start()
    code0 = src._table.codes[:1]
    tail = data_frame(code0, np.array([SENTINEL], np.float32), 1_700_000_000)
    try:
        t0 = time.perf_counter()
        with socket.create_connection(src.address, timeout=5.0) as s:
            s.recv(1 << 20)  # MAP hello
            for fr in frames:
                s.sendall(fr)
            s.sendall(tail)
            deadline = time.time() + 600
            done = False
            while time.time() < deadline:
                with src._lock:
                    done = src._latest[0] == np.float32(SENTINEL)
                if done:
                    break
                time.sleep(0.005)
        dt = time.perf_counter() - t0
    finally:
        src.close()
    if not done:
        raise SystemExit("ingest bench: binary payload not consumed in budget")
    return {"records_per_sec": round(n_records / dt), "wall_s": round(dt, 3)}


def binary_inproc_drive(frames: list[bytes], n_records: int,
                        slot_map: dict) -> dict:
    """Decode + scatter cost alone (no socket): walker feed per frame."""
    from rtap_tpu.ingest import BinaryBatchSource

    src = BinaryBatchSource(slot_map, port=None)
    t0 = time.perf_counter()
    src.feed_frames(frames)
    dt = time.perf_counter() - t0
    assert src.records_parsed == n_records, src.records_parsed
    return {"records_per_sec": round(n_records / dt), "wall_s": round(dt, 3)}


def shm_drive(frames: list[bytes], n_records: int, slot_map: dict) -> dict:
    """Shared-memory ring end-to-end: producer push + per-tick drain."""
    from rtap_tpu.ingest import BinaryBatchSource, ShmRing

    name = f"rtap_ibench_{os.getpid()}"
    ring_bytes = 32 << 20
    if any(len(fr) > ring_bytes for fr in frames):
        raise SystemExit(
            "ingest bench: a frame exceeds the shm ring capacity "
            f"({ring_bytes} B) — lower --frame-rows")
    src = BinaryBatchSource(slot_map, port=None, shm=name,
                            shm_bytes=ring_bytes)
    w = ShmRing.attach(name)
    tick = 0
    deadline = time.time() + 600  # same budget discipline as the
    # socket lanes: a wedged ring must fail, not hang the bench
    try:
        t0 = time.perf_counter()
        for fr in frames:
            while not w.push(fr):
                src(tick)  # ring full: consumer drains (backpressure)
                tick += 1
                if time.time() > deadline:
                    raise SystemExit("ingest bench: shm ring wedged")
        while src.records_parsed < n_records:
            src(tick)
            tick += 1
            if time.time() > deadline:
                raise SystemExit(
                    "ingest bench: shm payload not consumed in budget")
        dt = time.perf_counter() - t0
    finally:
        w.close()
        src.close()
    return {"records_per_sec": round(n_records / dt), "wall_s": round(dt, 3)}


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--records", type=int, default=1_000_000)
    ap.add_argument("--jsonl-records", type=int, default=None,
                    help="records for the (slow) JSONL lanes; default: "
                         "min(records, 300k) — rates are per-second "
                         "either way")
    ap.add_argument("--streams", type=int, default=4096)
    ap.add_argument("--frame-rows", type=int, default=8192,
                    help="rows per RB1 DATA frame (8192 is the measured "
                         "sweet spot on the 1-core host: fewer Python "
                         "frame crossings per byte; producers feeding "
                         "100k streams at 1 s send ~12 such frames/tick)")
    ap.add_argument("--group-size", type=int, default=1024)
    ap.add_argument("--out", default=os.path.join(REPO, "reports", "ingest_bench.json"))
    args = ap.parse_args()

    from rtap_tpu.config import cluster_preset
    from rtap_tpu.service.registry import StreamGroupRegistry

    ids = [f"node{i // 4:04d}.m{i % 4}" for i in range(args.streams)]
    # the real registry's slot map (cpu backend: no device init; the
    # bench is host-only by design — ISSUE 7's provable-on-host gate)
    reg = StreamGroupRegistry(cluster_preset(),
                              group_size=min(args.group_size, args.streams),
                              backend="cpu")
    for sid in ids:
        reg.add_stream(sid)
    reg.finalize()
    slot_map = reg.slot_map()

    n_jsonl = args.jsonl_records or min(args.records, 300_000)
    payload = make_payload(n_jsonl, ids)
    frames = make_frames(args.records, slot_map, ids, args.frame_rows)

    native_inproc = inproc_drive(payload, n_jsonl, ids)
    native_sock = socket_drive(True, payload, n_jsonl, ids)
    python_sock = socket_drive(False, payload, n_jsonl, ids)
    bin_inproc = binary_inproc_drive(frames, args.records, slot_map)
    bin_sock = binary_socket_drive(frames, args.records, slot_map, ids)
    shm = shm_drive(frames, args.records, slot_map)

    from rtap_tpu.ingest.protocol import FrameWalker

    result = {
        "records": args.records,
        "jsonl_records": n_jsonl,
        "streams": args.streams,
        "frame_rows": args.frame_rows,
        "payload_mb_jsonl": round(len(payload) / 1e6, 1),
        "payload_mb_binary": round(sum(len(f) for f in frames) / 1e6, 1),
        "native_walker": FrameWalker().native_active,
        "native_parser_inproc": native_inproc,
        "native_socket_end_to_end": native_sock,
        "python_socket_end_to_end": python_sock,
        "binary_decode_inproc": bin_inproc,
        "binary_socket_end_to_end": bin_sock,
        "binary_shm_ring_end_to_end": shm,
        "speedup_jsonl_native_vs_python": round(
            native_sock["records_per_sec"]
            / python_sock["records_per_sec"], 1),
        "speedup_binary_vs_jsonl_socket": round(
            bin_sock["records_per_sec"]
            / native_sock["records_per_sec"], 1),
        "gate_binary_1m_rows_per_sec":
            bin_sock["records_per_sec"] >= 1_000_000,
        "gate_binary_5x_jsonl":
            bin_sock["records_per_sec"]
            >= 5 * native_sock["records_per_sec"],
        "note": ("records/s through the live_loop source transports on one "
                 "host core; the ISSUE 7 acceptance gate is binary >= 1M "
                 "rows/s AND >= 5x the (native) JSONL TCP path in the "
                 "same harness"),
    }
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result))
    return 0 if (result["gate_binary_1m_rows_per_sec"]
                 and result["gate_binary_5x_jsonl"]) else 1


if __name__ == "__main__":
    raise SystemExit(main())
