"""Host ingest-path benchmark: native C JSONL parser vs pure Python.

The chip can score ~100k metrics/s (BASELINE.json north star); the host
core that feeds it must parse at least that many JSONL records/s while
ALSO driving the device and computing likelihoods. This measures both
TcpJsonlSource parse paths over a real socket (the production transport,
including recv/locking) and in-process (parser cost alone), and writes
reports/ingest_bench.json.

    python scripts/ingest_bench.py [--records 300000] [--streams 4096]
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

from rtap_tpu.service.sources import TcpJsonlSource  # noqa: E402


def make_payload(n_records: int, ids: list[str]) -> bytes:
    G = len(ids)
    return "".join(
        json.dumps({"id": ids[i % G], "value": 1.0 + (i % 1000) * 0.5,
                    "ts": 1_700_000_000 + i}) + "\n"
        for i in range(n_records)
    ).encode()


SENTINEL = -987654.5  # distinctive final-record value; TCP ordering on the
# single connection means seeing it implies every earlier record was parsed


def socket_drive(native: bool, payload: bytes, n_records: int,
                 ids: list[str]) -> dict:
    """Push the payload through the real listener; wall time until the
    in-order sentinel record (appended after the payload) is applied —
    identical completion detection for both paths, so the speedup compares
    full parse pipelines, not a full pipeline vs a sendall return."""
    src = TcpJsonlSource(ids, native=native)
    tail = (json.dumps({"id": ids[0], "value": SENTINEL}) + "\n").encode()
    with src:
        t0 = time.perf_counter()
        with socket.create_connection(src.address, timeout=5.0) as s:
            s.sendall(payload + tail)
        deadline = time.time() + 600
        done = False
        while time.time() < deadline:
            with src._lock:
                done = src._latest[0] == np.float32(SENTINEL)
            if done:
                break
            time.sleep(0.005)
        dt = time.perf_counter() - t0
    if not done:
        raise SystemExit("ingest bench: payload not fully consumed in budget")
    return {"records_per_sec": round(n_records / dt), "wall_s": round(dt, 3)}


def inproc_drive(payload: bytes, n_records: int, ids: list[str]) -> dict:
    """Parser cost alone (no socket): feed 64 KiB chunks like the handler."""
    from rtap_tpu.native import NativeJsonlState

    latest = np.full(len(ids), np.nan, np.float32)
    st = NativeJsonlState(ids, latest)
    conn = st.new_conn()
    t0 = time.perf_counter()
    for off in range(0, len(payload), 65536):
        conn.feed(payload[off:off + 65536])
    conn.flush()
    dt = time.perf_counter() - t0
    assert st.counters[0] == n_records, st.counters
    conn.close()
    return {"records_per_sec": round(n_records / dt), "wall_s": round(dt, 3)}


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--records", type=int, default=300_000)
    ap.add_argument("--streams", type=int, default=4096)
    ap.add_argument("--out", default=os.path.join(REPO, "reports", "ingest_bench.json"))
    args = ap.parse_args()

    ids = [f"node{i // 4:04d}.m{i % 4}" for i in range(args.streams)]
    payload = make_payload(args.records, ids)

    native_inproc = inproc_drive(payload, args.records, ids)
    native_sock = socket_drive(True, payload, args.records, ids)
    python_sock = socket_drive(False, payload, args.records, ids)

    result = {
        "records": args.records,
        "streams": args.streams,
        "payload_mb": round(len(payload) / 1e6, 1),
        "native_parser_inproc": native_inproc,
        "native_socket_end_to_end": native_sock,
        "python_socket_end_to_end": python_sock,
        "speedup_socket": round(native_sock["records_per_sec"]
                                / python_sock["records_per_sec"], 1),
        "note": ("records/s through TcpJsonlSource on one host core; the "
                 "100k-streams/s north star needs >=100k records/s of "
                 "headroom left over for device driving + likelihood"),
    }
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
