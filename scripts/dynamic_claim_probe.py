"""On-silicon probe for dynamic slot claims (SURVEY.md C19 lazy creation).

The CPU test suite pins claim semantics bit-exactly; this validates the
DEVICE path on the real chip: `set_state_row`'s donated .at[slot].set
update against grouped TPU state, scoring continuity after a mid-run
claim, and the claimed slot's post-probation emergence. Runs in seconds;
queued as a harvest step so the feature is silicon-proven, not just
CPU-proven.

    python scripts/dynamic_claim_probe.py [--group-size 256] [--ticks 48]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from rtap_tpu.utils.platform import init_backend_or_die, maybe_force_cpu  # noqa: E402

maybe_force_cpu()


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--group-size", type=int, default=256)
    ap.add_argument("--ticks", type=int, default=48)
    args = ap.parse_args()

    init_backend_or_die()
    import jax

    from rtap_tpu.config import scaled_cluster_preset
    from rtap_tpu.service.registry import StreamGroupRegistry

    platform = jax.devices()[0].platform
    cfg = scaled_cluster_preset(32)
    n_live = args.group_size - 2  # leave claimable pads
    reg = StreamGroupRegistry(cfg, group_size=args.group_size, backend="tpu")
    for i in range(n_live):
        reg.add_stream(f"s{i}")
    reg.finalize()
    grp = reg.groups[0]

    rng = np.random.default_rng(3)

    def tick(k: int) -> np.ndarray:
        vals = (30 + 5 * rng.random(grp.G)).astype(np.float32)
        raw, _, _ = grp.run_chunk(
            vals[None, :], np.full((1, grp.G), 1_700_000_000 + k, np.int64))
        return raw[0]

    for k in range(args.ticks):
        tick(k)

    # snapshot a pad slot's state row, claim it, verify the row was reset
    pad_slot = grp.live_slots()[-1] + 1 if n_live else 0
    before = {k: np.asarray(v)[pad_slot].copy() for k, v in grp.state.items()}
    reg.add_stream("claimed")
    _, slot = reg.lookup("claimed")
    assert slot == pad_slot, (slot, pad_slot)
    after = {k: np.asarray(v)[slot] for k, v in grp.state.items()}
    from rtap_tpu.models.state import init_state

    fresh = init_state(cfg, grp.seed)
    reset_exact = all(
        np.array_equal(after[k], np.asarray(fresh[k]).astype(after[k].dtype))
        for k in after)
    changed = any(not np.array_equal(before[k], after[k]) for k in before)

    raws = [tick(args.ticks + j) for j in range(args.ticks)]
    finite = all(np.isfinite(r).all() for r in raws)

    out = {
        "platform": platform,
        "group_size": args.group_size,
        "claimed_slot": int(slot),
        "reset_matches_fresh_init": bool(reset_exact),
        "pad_state_was_mutated_by_claim": bool(changed),
        "post_claim_scores_finite": bool(finite),
        "ok": bool(reset_exact and finite),
    }
    print(json.dumps(out))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
