"""Roofline / MFU accounting for the fused step (r4 verdict item 3).

"Actually fast, or just correct?" — this script closes the loop between the
measured metrics/s numbers and what a v5e-1 can sustain. For each config it
compiles the real chunked step and reads XLA's own cost model
(`compiled.cost_analysis()`: FLOPs + bytes accessed for the optimized HLO),
then divides by the chip peaks:

    TPU v5e (1 chip): ~197 TFLOP/s bf16, ~49 TFLOP/s f32 (MXU),
                      ~819 GB/s HBM bandwidth, 16 GiB HBM.

Outputs reports/roofline.json: per config, FLOPs/tick, HBM bytes/tick,
arithmetic intensity, the bandwidth- and compute-bound time floors, the
MEASURED ms/tick (from the committed silicon profiles, provenance noted),
and the implied utilizations. The point is to NAME the binding resource:
if measured time >> max(bytes/BW, flops/peak), the kernel is neither
HBM- nor MXU-bound — it is latency/occupancy-bound (many small serialized
ops), and the next lever is fusion/batching, not arithmetic.

    python scripts/roofline.py                  # on the chip (cost model of
                                                #   the TPU-lowered HLO)
    RTAP_FORCE_CPU=1 python scripts/roofline.py # CPU-lowered HLO (flagged)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from rtap_tpu.utils.platform import (  # noqa: E402
    enable_compile_cache, init_backend_or_die, maybe_force_cpu,
)

# v5e-1 peaks (public spec: 394 TOPS int8 / 197 TFLOPs bf16 per chip,
# 819 GB/s HBM BW, 16 GiB HBM)
PEAK_BF16_FLOPS = 197e12
PEAK_F32_FLOPS = 49e12
PEAK_HBM_BPS = 819e9

# Committed silicon measurements (ms/tick, T=32 chunked, full learning
# unless noted) — the provenance strings name the artifact logs.
MEASURED = {
    "preset_256col_G1024": (31.95, "hw_results/profile_flat.log: G=1024 "
                                   "31.95 ms/tick (32,050 metrics/s)"),
    "eighth_32col_G1024": (14.65, "hw_results/profile_eighth.log: G=1024 "
                                  "14.65 ms/tick (69,876 metrics/s)"),
    "eighth_32col_k2_G1024": (7.85, "hw_results/profile_eighth_k2.log: "
                                    "G=1024 7.85 ms/tick (130,380 metrics/s)"),
    "eighth_32col_G65536": (1555.4, "hw_results/profile_32col_bigg.log: "
                                    "G=65536 1555.4 ms/tick (42,134 "
                                    "metrics/s) — the residency frontier"),
}


def log(msg: str) -> None:
    print(f"[roofline] {msg}", file=sys.stderr, flush=True)


def _config(name: str):
    from rtap_tpu.config import cluster_preset, scaled_cluster_preset

    if name.startswith("preset_256col"):
        cfg = cluster_preset()
    else:
        cfg = scaled_cluster_preset(32)
    if "_k2_" in name or name.endswith("_k2"):
        cfg = cfg.with_learn_every(2)
    return cfg


def cost_of(cfg, G: int, T: int) -> dict:
    """Compile chunk_step at (G, T) and pull XLA's cost analysis."""
    import jax
    import jax.numpy as jnp

    from rtap_tpu.models.state import init_state, state_nbytes
    from rtap_tpu.ops.step import chunk_step, replicate_state

    state = replicate_state(init_state(cfg, seed=0), G)
    vals = jnp.zeros((T, G, 1), jnp.float32)
    ts = jnp.zeros((T, G), jnp.int32)

    def _chunk_learn(s, v, t):
        return chunk_step(s, v, t, cfg, learn=True)

    fn = jax.jit(_chunk_learn, donate_argnums=(0,))
    compiled = fn.lower(state, vals, ts).compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):  # older jax returns [dict]
        ca = ca[0] if ca else {}
    flops = float(ca.get("flops", 0.0))
    byt = float(ca.get("bytes accessed", 0.0))
    mem = compiled.memory_analysis()
    out = {
        "flops_per_chunk": flops,
        "bytes_accessed_per_chunk": byt,
        "flops_per_tick": flops / T,
        "bytes_per_tick": byt / T,
        "state_bytes_per_stream": int(state_nbytes(cfg)["total"]),
    }
    if mem is not None:
        for k in ("temp_size_in_bytes", "argument_size_in_bytes",
                  "output_size_in_bytes", "generated_code_size_in_bytes"):
            v = getattr(mem, k, None)
            if v is not None:
                out[k] = int(v)
    return out


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=os.path.join(REPO, "reports",
                                                  "roofline.json"))
    ap.add_argument("--T", type=int, default=32)
    ap.add_argument("--configs", default=None,
                    help="comma-separated subset of the config names "
                         "(cheap CPU drives skip the G=65536 compile)")
    args = ap.parse_args()

    maybe_force_cpu()
    init_backend_or_die()
    import jax

    enable_compile_cache(REPO)
    platform = jax.devices()[0].platform

    configs = {
        "preset_256col_G1024": ("preset_256col", 1024),
        "eighth_32col_G1024": ("eighth_32col", 1024),
        "eighth_32col_k2_G1024": ("eighth_32col_k2", 1024),
        "eighth_32col_G65536": ("eighth_32col", 65536),
    }
    if args.configs:
        picked = args.configs.split(",")
        bad = set(picked) - set(configs)
        if bad:
            raise SystemExit(f"unknown configs {sorted(bad)}")
        configs = {k: v for k, v in configs.items() if k in picked}
    rows = {}
    for name, (cfg_name, G) in configs.items():
        t0 = time.time()
        try:
            c = cost_of(_config(cfg_name), G, args.T)
        except Exception as e:  # noqa: BLE001 — a too-big compile must not
            # kill the smaller configs' accounting
            log(f"{name}: FAILED {type(e).__name__}: {str(e)[:200]}")
            rows[name] = {"error": str(e)[:300]}
            continue
        log(f"{name}: compiled in {time.time() - t0:.0f}s")
        bw_floor_ms = c["bytes_per_tick"] / PEAK_HBM_BPS * 1e3
        # the kernels are predominantly f32 elementwise/compare with f32
        # one-hot matmuls — credit the F32 peak (bf16 would flatter us 4x)
        fl_floor_ms = c["flops_per_tick"] / PEAK_F32_FLOPS * 1e3
        row = {
            **c,
            "arithmetic_intensity_flops_per_byte": round(
                c["flops_per_tick"] / max(c["bytes_per_tick"], 1), 3),
            "hbm_floor_ms_per_tick": round(bw_floor_ms, 3),
            "f32_mxu_floor_ms_per_tick": round(fl_floor_ms, 4),
        }
        meas = MEASURED.get(name)
        if meas and platform == "tpu":
            ms, prov = meas
            row.update({
                "measured_ms_per_tick": ms,
                "measured_provenance": prov,
                "hbm_utilization_pct": round(100 * bw_floor_ms / ms, 2),
                "f32_mxu_utilization_pct": round(100 * fl_floor_ms / ms, 3),
                "latency_bound_factor": round(
                    ms / max(bw_floor_ms, fl_floor_ms), 1),
            })
        rows[name] = row

    out = {
        "platform": platform,
        "chip_peaks": {"bf16_flops": PEAK_BF16_FLOPS,
                       "f32_flops": PEAK_F32_FLOPS,
                       "hbm_bytes_per_s": PEAK_HBM_BPS,
                       "hbm_bytes": 16 * (1 << 30)},
        "T": args.T,
        "note": ("cost model = XLA cost_analysis of the optimized HLO on "
                 "this platform; measured times are the committed T=32 "
                 "chunked silicon profiles (full learning). Utilization = "
                 "resource floor / measured. A latency_bound_factor >> 1 "
                 "means the step is bound by op-dispatch/serialization, "
                 "not by HBM or MXU."),
        "configs": rows,
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps({k: {kk: v[kk] for kk in
                          ("hbm_utilization_pct", "latency_bound_factor")
                          if kk in v}
                      for k, v in rows.items() if "error" not in v}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
