#!/usr/bin/env bash
# Static gates for the WHOLE package + scripts (tier-1 rides this via
# tests/unit/test_static_checks.py):
#
#  1. compileall — every rtap_tpu module AND every scripts/ entry point
#     must at least parse/compile; an import-time SyntaxError must fail
#     CI even if no test imports the file.
#  2. rtap-lint (python -m rtap_tpu.analysis) — the AST invariant
#     analyzer (ISSUEs 12+13+14+15, docs/ANALYSIS.md): twenty passes —
#     the print gate and MUST_BE_STRICT coverage pin, the race, purity,
#     exception-discipline, and flag↔docs passes, the whole-program v2
#     passes (lock-order deadlock cycles, cross-object sharing, replay
#     determinism, resource lifecycle), the device-kernel v3 family
#     (twin-parity, trace-safety, donate-read, static-hash/jit-churn,
#     dtype-domain, wire-contract), and the mesh-readiness v4 family
#     (partition-contract, device-scope, collective-discipline,
#     shard-resource, scaling-math — the ROADMAP-1 rails). Exit 0 iff
#     zero unsuppressed findings against the committed
#     analysis_baseline.json. Untouched-tree reruns are served from the
#     pass-partitioned content-hash findings cache (finding-identical
#     by test).
#
# This script is deliberately a thin wrapper: the checking logic has ONE
# home (rtap_tpu/analysis/), testable as a library, with a --json
# artifact surface for soaks (`python -m rtap_tpu.analysis --json`).
set -euo pipefail
cd "$(dirname "$0")/.."

python -m compileall -q rtap_tpu scripts bench.py

python -m rtap_tpu.analysis

echo "check_static: OK"
