#!/usr/bin/env bash
# Static gates for the WHOLE package + scripts (tier-1 rides this via
# tests/unit/test_static_checks.py):
#
#  1. compileall — every rtap_tpu module AND every scripts/ entry point
#     (profiler harness included) must at least parse/compile; an
#     import-time SyntaxError must fail CI even if no test imports the file.
#  2. print-gate — AST-based (a line grep cannot see a multi-line call):
#     - rtap_tpu/service/, rtap_tpu/obs/, rtap_tpu/resilience/,
#       rtap_tpu/ingest/, rtap_tpu/correlate/: NO print()
#       at all. Telemetry and diagnostics go through rtap_tpu.obs (registry
#       instruments, watchdog events, snapshots) or logging, never ad-hoc
#       stdout lines the harness would have to scrape back out of logs.
#     - everywhere else in rtap_tpu/, scripts/, bench.py: print() must
#       either target an explicit stream (file=...) or be the sanctioned
#       one-JSON-line stdout emission (a single json.dumps(...)/.to_json()
#       argument — the bench/eval artifact contract). Anything else is a
#       bare print and fails.
set -euo pipefail
cd "$(dirname "$0")/.."

python -m compileall -q rtap_tpu scripts bench.py

python - <<'PYEOF'
import ast
import os
import sys

STRICT_DIRS = (
    os.path.join("rtap_tpu", "service"),
    os.path.join("rtap_tpu", "obs"),
    os.path.join("rtap_tpu", "resilience"),
    os.path.join("rtap_tpu", "ingest"),
    os.path.join("rtap_tpu", "correlate"),
)


def allowed_outside_strict(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "file":
            return True  # explicit stream: stderr diagnostics
    if len(call.args) == 1 and isinstance(call.args[0], ast.Call):
        f = call.args[0].func
        if isinstance(f, ast.Attribute) and f.attr in ("dumps", "to_json"):
            return True  # the one-JSON-line stdout artifact contract
    return False


targets = []
for root in ("rtap_tpu", "scripts"):
    for dp, _dirs, fns in os.walk(root):
        if "__pycache__" in dp:
            continue
        targets += [os.path.join(dp, f) for f in fns if f.endswith(".py")]
targets.append("bench.py")

# coverage pin (ISSUE 11 satellite): the serve-path instrumentation
# modules MUST sit under a strict dir — a rename/move that silently
# dropped them out of no-print coverage would let stdout lines creep
# back into the hot path. Extend this list with every new module.
MUST_BE_STRICT = (
    os.path.join("rtap_tpu", "obs", "latency.py"),
    os.path.join("rtap_tpu", "obs", "slo.py"),
    os.path.join("rtap_tpu", "obs", "metrics.py"),
    os.path.join("rtap_tpu", "service", "loop.py"),
)
for p in MUST_BE_STRICT:
    if not os.path.isfile(p):
        print(f"check_static: expected strict module missing: {p}",
              file=sys.stderr)
        sys.exit(1)
    if not any(p.startswith(d + os.sep) for d in STRICT_DIRS):
        print(f"check_static: {p} fell out of strict no-print coverage",
              file=sys.stderr)
        sys.exit(1)

bad = []
for path in sorted(targets):
    with open(path) as fh:
        tree = ast.parse(fh.read(), filename=path)
    strict = any(path.startswith(d + os.sep) for d in STRICT_DIRS)
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "print"):
            continue
        if strict:
            bad.append(f"{path}:{node.lineno}: print() in the serve stack — "
                       "emit through rtap_tpu.obs (or logging) instead")
        elif not allowed_outside_strict(node):
            bad.append(f"{path}:{node.lineno}: bare print() — route to "
                       "stderr (file=) or emit a JSON artifact line")

if bad:
    print("\n".join(bad), file=sys.stderr)
    sys.exit(1)
PYEOF

echo "check_static: OK"
