#!/usr/bin/env bash
# Static gates for the serve stack (tier-1 rides this via
# tests/unit/test_static_checks.py):
#
#  1. compileall — every rtap_tpu module must at least parse/compile; an
#     import-time SyntaxError must fail CI even if no test imports the file.
#  2. print-gate — no bare print( in rtap_tpu/service/, rtap_tpu/obs/, or
#     rtap_tpu/resilience/: telemetry and diagnostics go through
#     rtap_tpu.obs (registry instruments, watchdog events, snapshots) or
#     logging, never ad-hoc stdout lines the harness would have to scrape
#     back out of logs. The resilience layer doubly so — its whole point
#     is structured events a machine can act on.
set -euo pipefail
cd "$(dirname "$0")/.."

python -m compileall -q rtap_tpu

# match real calls (start-of-line or non-identifier char before "print("),
# not occurrences inside words/strings like "fingerprint(" or docs
if grep -rnE '(^|[^A-Za-z0-9_."'"'"'])print\(' \
     rtap_tpu/service rtap_tpu/obs rtap_tpu/resilience --include='*.py'; then
  echo "check_static: bare print( in rtap_tpu/{service,obs,resilience}/ —" \
       "emit through rtap_tpu.obs (or logging) instead" >&2
  exit 1
fi

echo "check_static: OK"
