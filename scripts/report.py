"""Visualization report (SURVEY.md C22): replay + eval -> PNG overlays.

The reference ships Grafana-style dashboards of metric + anomaly likelihood
(SURVEY.md C22); the v1 plan is a matplotlib report script. Two artifacts:

- ``overlay.png`` — per-stream small multiples: metric value with injected
  fault windows shaded and alert marks, and (own axis, stacked — never a
  dual axis) the anomaly log-likelihood with the alert threshold. Data comes
  from an in-process replay of the synthetic cluster (deterministic seed).
- ``fault_eval.png`` — per-kind recall bars + headline metrics from a
  committed eval report JSON (reports/fault_eval.json).

Usage:
    RTAP_FORCE_CPU=1 python scripts/report.py --out-dir reports \
        [--eval-report reports/fault_eval.json] [--streams 6] [--length 900]

Design notes: colorblind-safe Okabe-Ito hues in fixed roles (value = blue,
likelihood = orange); the status color (vermillion) is reserved for alert
marks; fault windows are neutral gray bands; thin marks, recessive grid,
no top/right spines.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from rtap_tpu.utils.platform import maybe_force_cpu  # noqa: E402

maybe_force_cpu()

import matplotlib  # noqa: E402

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402
import numpy as np  # noqa: E402

# Okabe-Ito (CVD-safe): fixed roles, never cycled
C_VALUE = "#0072B2"  # blue — the metric
C_LIK = "#E69F00"  # orange — the likelihood
C_ALERT = "#D55E00"  # vermillion — STATUS: alert marks only
C_WINDOW = "#999999"  # neutral — labeled fault windows
INK = "#333333"
MUTED = "#767676"


def _style(ax):
    ax.spines[["top", "right"]].set_visible(False)
    ax.spines[["left", "bottom"]].set_color(MUTED)
    ax.tick_params(colors=MUTED, labelsize=8)
    ax.grid(True, axis="y", color="#DDDDDD", linewidth=0.6, alpha=0.7)
    ax.set_axisbelow(True)


def overlay_figure(streams, res, threshold: float, max_streams: int = 4):
    """Small multiples: per stream, value panel + log-likelihood panel."""
    n = min(max_streams, len(streams))
    fig, axes = plt.subplots(
        2 * n, 1, figsize=(10, 2.2 * 2 * n), sharex=True,
        layout="constrained",
    )
    axes = np.atleast_1d(axes)
    t0 = res.timestamps[0]
    tmin = (res.timestamps - t0) / 60.0  # minutes
    for i in range(n):
        s = streams[i]
        ax_v, ax_l = axes[2 * i], axes[2 * i + 1]
        for lo, hi in s.windows:
            for ax in (ax_v, ax_l):
                ax.axvspan((lo - t0) / 60.0, (hi - t0) / 60.0,
                           color=C_WINDOW, alpha=0.25, linewidth=0)
        ax_v.plot(tmin, s.values, color=C_VALUE, linewidth=1.2)
        ax_v.set_ylabel("value", fontsize=8, color=INK)
        ax_v.set_title(f"{s.stream_id} — metric, fault windows (gray), alerts",
                       fontsize=9, color=INK, loc="left")
        alerts = res.alerts[:, i]
        if alerts.any():
            ax_v.plot(tmin[alerts], s.values[alerts], linestyle="none",
                      marker="v", markersize=5, color=C_ALERT, label="alert")
            ax_v.legend(frameon=False, fontsize=8, loc="upper right",
                        borderaxespad=0.1)
        ax_l.plot(tmin, res.log_likelihood[:, i], color=C_LIK, linewidth=1.2)
        ax_l.axhline(threshold, color=MUTED, linewidth=0.9, linestyle="--")
        ax_l.text(tmin[-1], threshold, f" thr {threshold}", fontsize=7,
                  color=MUTED, va="bottom", ha="right")
        ax_l.set_ylabel("log-lik", fontsize=8, color=INK)
        ax_l.set_ylim(-0.02, 1.02)
        _style(ax_v)
        _style(ax_l)
    axes[-1].set_xlabel("minutes", fontsize=8, color=INK)
    fig.suptitle("Synthetic cluster replay — anomaly detection overlay",
                 fontsize=11, color=INK, ha="center")
    return fig


def eval_figure(report: dict):
    """Per-kind recall bars (one measure across categories -> one hue) with
    headline metrics in the title."""
    kinds = sorted(report["per_kind"])
    recalls = [report["per_kind"][k]["recall"] for k in kinds]
    b = report["at_best"]
    fig, ax = plt.subplots(figsize=(7, 0.6 * len(kinds) + 1.6))
    y = np.arange(len(kinds))
    ax.barh(y, recalls, height=0.55, color=C_VALUE, edgecolor="none")
    for i, r in enumerate(recalls):
        ax.text(min(r + 0.02, 1.02), i, f"{r:.2f}", va="center",
                fontsize=8, color=INK)
    ax.set_yticks(y, kinds, fontsize=9, color=INK)
    ax.set_xlim(0, 1.12)
    ax.set_xlabel("recall at F1-optimal threshold", fontsize=8, color=INK)
    ax.set_title(
        f"Fault-injection eval — f1 {b['f1']:.2f}, recall {b['recall']:.2f}, "
        f"episode precision {b['precision']:.2f}, "
        f"median latency {b['median_latency_s']} s",
        fontsize=9, color=INK, loc="left",
    )
    _style(ax)
    ax.grid(True, axis="x", color="#DDDDDD", linewidth=0.6, alpha=0.7)
    ax.grid(False, axis="y")
    fig.tight_layout()
    return fig


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out-dir", default=os.path.join(REPO, "reports"))
    ap.add_argument("--streams", type=int, default=6)
    ap.add_argument("--length", type=int, default=900)
    ap.add_argument("--threshold", type=float, default=0.39)
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--eval-report", default=None,
                    help="path to a fault_eval JSON report to chart")
    args = ap.parse_args()
    os.makedirs(args.out_dir, exist_ok=True)

    import dataclasses

    from rtap_tpu.config import cluster_preset
    from rtap_tpu.data.synthetic import SyntheticStreamConfig, generate_stream
    from rtap_tpu.service.loop import replay_streams

    base = cluster_preset()
    cfg = dataclasses.replace(
        base, likelihood=dataclasses.replace(base.likelihood, mode="window")
    )
    frac = cfg.likelihood.safe_inject_frac(args.length)
    metrics = ("cpu", "mem", "net")
    streams = [
        generate_stream(
            f"node{i:03d}.{metrics[i % 3]}",
            SyntheticStreamConfig(
                length=args.length, metric=metrics[i % 3], n_anomalies=2,
                kinds=("spike", "level_shift", "dropout"), anomaly_magnitude=6.0,
                noise_phi=0.97, noise_scale=0.5, inject_after_frac=frac,
            ),
            seed=args.seed,
        )
        for i in range(args.streams)
    ]
    res = replay_streams(streams, cfg, backend="tpu",
                         threshold=args.threshold, chunk_ticks=128)
    fig = overlay_figure(streams, res, args.threshold)
    overlay_path = os.path.join(args.out_dir, "overlay.png")
    fig.savefig(overlay_path, dpi=110)
    plt.close(fig)
    print(f"wrote {overlay_path}", file=sys.stderr)

    if args.eval_report and os.path.exists(args.eval_report):
        rep = json.load(open(args.eval_report))
        fig = eval_figure(rep)
        eval_path = os.path.join(args.out_dir, "fault_eval.png")
        fig.savefig(eval_path, dpi=110)
        plt.close(fig)
        print(f"wrote {eval_path}", file=sys.stderr)


if __name__ == "__main__":
    main()
