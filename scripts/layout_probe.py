"""Microbenchmark: WHERE does the fused step's 50x bandwidth gap live?

The r3 G-sweep measured ~15 GB/s effective HBM bandwidth through the fused
step (2% of v5e peak). Two suspects, each probed in isolation here:

1. **Tile padding.** TPU tiles the last two dims (e.g. (8, 128) for f32).
   The TM pools are carried as [G, C, K=8, S=4, M=12] — trailing dims 4x12
   pad to 8x128 (~21x memory inflation) UNLESS XLA's layout assignment
   collapses them. Probe: identical elementwise+reduce work on [G, C, 8, 4,
   12] vs flat [G, C, 384]; if the flat form is many times faster, the
   kernels should carry flat pools (reshape adapters at the chunk boundary).

2. **Per-stream lookup ops.** The step leans on vmapped top_k / argmax /
   argsort / sort at small shapes; if these serialize on the scalar core,
   they dominate regardless of layout. Probe: each op isolated at the
   step's exact shapes, G-batched.

Prints one JSON line per probe to stdout ({"probe": ..., "us_per_stream_tick"
: ...}); run on hardware via hw_session step 2 (or standalone).
"""

from __future__ import annotations

import json
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from rtap_tpu.utils.platform import (  # noqa: E402
    enable_compile_cache, init_backend_or_die, maybe_force_cpu,
)

maybe_force_cpu()
init_backend_or_die()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

G, C, K, S, M = 1024, 256, 8, 4, 12
T = 16  # scan length: amortizes dispatch, matches the step's chunked shape
Ac, L = 10, 32


def bench(name: str, fn, *args) -> None:
    fn_j = jax.jit(fn)
    out = fn_j(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(3):
        out = fn_j(*args)
    jax.block_until_ready(out)
    us = (time.perf_counter() - t0) / 3 / (G * T) * 1e6
    print(json.dumps({"probe": name, "us_per_stream_tick": round(us, 3)}), flush=True)


def scanned(body):
    """Run `body(carry)` T times under lax.scan — the step's real shape."""
    def fn(x):
        def step(c, _):
            return body(c), 0.0
        return jax.lax.scan(step, x, jnp.arange(T))[0]
    return fn


def main() -> None:
    enable_compile_cache(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    print(json.dumps({"platform": jax.devices()[0].device_kind}), file=sys.stderr, flush=True)
    rng = np.random.Generator(np.random.Philox(key=(4, 4)))
    pool4 = jnp.asarray(rng.integers(-1, K * C, (G, C, K, S, M)), jnp.int32)
    perm4 = jnp.asarray(rng.random((G, C, K, S, M)), jnp.float32)
    pool2 = pool4.reshape(G, C, K * S * M)
    perm2 = perm4.reshape(G, C, K * S * M)
    ids = jnp.asarray(rng.integers(0, C, (G, Ac)), jnp.int32)
    masks = jnp.asarray(rng.integers(0, 255, (G, Ac)), jnp.int32)

    # --- probe 1: the punish/death/dendrite-shaped pass, 4-D vs flat ---
    def member(p, i, m):
        c_pre = p // K
        k_pre = p % K
        msk = jnp.where(c_pre[..., None] == i[:, None, None, None, None, :]
                        if p.ndim == 5 else c_pre[..., None] == i[:, None, None, :],
                        m[:, None, None, None, None, :] if p.ndim == 5
                        else m[:, None, None, :], 0).sum(-1)
        return (p >= 0) & (((msk >> k_pre) & 1) > 0)

    def pass4(carry):
        p, w = carry
        act = member(p, ids, masks)
        w = jnp.where(act, jnp.minimum(w + 0.01, 1.0), w)
        dead = (p >= 0) & (w <= 0.0)
        p = jnp.where(dead, -1, p)
        conn = (act & (w >= 0.5)).sum(-1)  # [G, C, K, S]
        return (p, w + 0.0 * conn[..., None])

    def pass2(carry):
        p, w = carry
        act = member(p, ids, masks)
        w = jnp.where(act, jnp.minimum(w + 0.01, 1.0), w)
        dead = (p >= 0) & (w <= 0.0)
        p = jnp.where(dead, -1, p)
        red = jnp.asarray(np.kron(np.eye(K * S, dtype=np.float32), np.ones((M, 1), np.float32)))
        conn = jax.lax.dot_general((act & (w >= 0.5)).astype(jnp.float32), red,
                                   (((2,), (0,)), ((), ())))  # [G, C, K*S]
        return (p, w + 0.0 * conn[..., None].reshape(G, C, -1)[:, :, :1])

    bench("pool_pass_4d", scanned(pass4), (pool4, perm4))
    bench("pool_pass_flat", scanned(pass2), (pool2, perm2))

    # same pass in u16 storage with f32 compute (the quantized domain cost)
    perm2_u16 = (perm2 * 65535).astype(jnp.uint16)  # rtap: domain[u16]

    def pass2_u16(carry):
        p, w16 = carry
        w = w16.astype(jnp.float32) / 65535.0
        act = member(p, ids, masks)
        w = jnp.where(act, jnp.minimum(w + 0.01, 1.0), w)
        dead = (p >= 0) & (w <= 0.0)
        p = jnp.where(dead, -1, p)
        return (p, (w * 65535).astype(jnp.uint16))  # rtap: domain[u16]

    bench("pool_pass_flat_u16", scanned(pass2_u16), (pool2, perm2_u16))

    # --- probe 2: the lookup ops at step shapes ---
    colvals = jnp.asarray(rng.random((G, C)), jnp.float32)
    bench("topk_C", scanned(
        lambda x: x + jax.lax.top_k(x, 10)[0].sum(-1, keepdims=True) * 0), colvals)

    segpot = jnp.asarray(rng.integers(0, M, (G, C, K * S)), jnp.int32)
    bench("argmax_KS", scanned(
        # rtap: allow[dtype-domain] — ×0 keeps the op in the graph, value dropped
        lambda x: x + jnp.argmax(x, axis=-1)[..., None].astype(jnp.int32) * 0), segpot)

    lperm = jnp.asarray(rng.random((G, L, M)), jnp.float32)

    def grow_sorts(x):
        ranks = jnp.argsort(jnp.argsort(x, axis=-1, stable=True), axis=-1, stable=True)
        return x + ranks * 0.0

    bench("argsort2_LM", scanned(grow_sorts), lperm)

    maskC = colvals > 0.9

    def compact(x):
        iota = jnp.arange(C, dtype=jnp.int32)
        top = jax.lax.top_k(jnp.where(x, C - iota, 0), Ac)[0]
        return x | (top.sum() > 0)

    bench("compact_ids", scanned(compact), maskC)


if __name__ == "__main__":
    main()
