"""Detection quality of a half-size model — the deferred density datum.

SCALING.md's HBM-frontier section ends with "single-chip beyond the
frontier requires shrinking the TM pools (quality trade measured in the
fault eval) — not promised here". This measures that trade: the cluster
preset with SP columns halved (256 -> 128, k-winners 10 -> 5 at equal
~3.9% sparsity; TM per-cell pools unchanged) halves the dominant state
tensors (~282 KB/stream u16 vs 564), roughly doubling both the
stream-density frontier and — on a bandwidth-bound kernel — the
throughput ceiling. The question is what detection quality it costs at
production scale (120 x 1500, same protocol as reports/fault_eval.json).

    RTAP_FORCE_CPU=1 python scripts/model_size_eval.py \
        [--out reports/model_size_quality.json]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from rtap_tpu.utils.platform import maybe_force_cpu  # noqa: E402

maybe_force_cpu()


def sized_preset(columns: int, perm_bits: int = 16, learn_every: int = 1,
                 learning_period: int | None = None):
    """See rtap_tpu.config.scaled_cluster_preset (promoted there once the
    quality datum landed; this wrapper adds the cadence + likelihood-
    probation compositions — learning_period=600 is the documented
    precision lever from the quality study)."""
    from rtap_tpu.config import scaled_cluster_preset

    cfg = scaled_cluster_preset(columns, perm_bits=perm_bits)
    if learning_period is not None:
        cfg = cfg.with_learning_period(learning_period)
    if learn_every > 1:
        cfg = cfg.with_learn_every(learn_every)
    return cfg


VARIANTS = {
    "half_128col": lambda: sized_preset(128),
    "quarter_64col": lambda: sized_preset(64),
    "half_128col_k2": lambda: sized_preset(128, learn_every=2),
    "quarter_64col_k2": lambda: sized_preset(64, learn_every=2),
    "eighth_32col": lambda: sized_preset(32),
    "sixteenth_16col": lambda: sized_preset(16),
    # the projected ~126k/s/chip rung (32col learning is ~91% of the tick,
    # profile_eighth.log): what does k=2 cost the best-f1 width?
    "eighth_32col_k2": lambda: sized_preset(32, learn_every=2),
    # the resident-capability domain (u8 perm halves 32col state again —
    # the ~quarter-million-streams/chip claim needs its quality number;
    # the 256col domain study measured u8 acceptable, width may interact)
    "eighth_32col_u8": lambda: sized_preset(32, perm_bits=8),
    "eighth_32col_u8_k2": lambda: sized_preset(32, perm_bits=8, learn_every=2),
    # width x probation composition: lp600 is the +3-point likelihood
    # lever on the preset (quality_study streaming 0.789 -> 0.819); does
    # it stack with the best-f1 width (0.813) and its k=2 point (0.762)?
    "eighth_32col_lp600": lambda: sized_preset(32, learning_period=600),
    # the 100k-live cadence ladder (r5 soaks): k=2 misses the 1 s cadence
    # at 100x1024 (p50 1.4 s); k=3/k=4 are the candidate operating points,
    # so their quality must be measured, not assumed
    "eighth_32col_k3": lambda: sized_preset(32, learn_every=3),
    "eighth_32col_k4": lambda: sized_preset(32, learn_every=4),
    "eighth_32col_k2_lp600": lambda: sized_preset(32, learn_every=2,
                                                  learning_period=600),
}


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--streams", type=int, default=120)
    ap.add_argument("--length", type=int, default=1500)
    ap.add_argument("--all-kinds", action="store_true",
                    help="include the hard gradual kinds (drift, stuck); "
                         "results go to reports/model_size_allkinds.json "
                         "unless --out is given (separate merge file — the "
                         "two protocols must never mix in one report)")
    ap.add_argument("--out", default=None,
                    help="report path (default: reports/"
                         "model_size_quality.json, or _allkinds variant)")
    ap.add_argument("--variants", default=None,
                    help=f"comma-separated subset of {sorted(VARIANTS)} "
                         "(default: all not already in the report)")
    args = ap.parse_args()
    if args.out is None:
        args.out = os.path.join(
            REPO, "reports",
            "model_size_allkinds.json" if args.all_kinds
            else "model_size_quality.json")

    from rtap_tpu.data.synthetic import ANOMALY_KINDS
    from rtap_tpu.eval.fault_eval import run_fault_eval
    from rtap_tpu.models.state import state_nbytes

    kinds = (ANOMALY_KINDS if args.all_kinds
             else ("spike", "level_shift", "dropout"))

    results = {}
    if os.path.exists(args.out):  # merge: re-runs only measure what's asked
        with open(args.out) as f:
            results = json.load(f).get("variants", {})
    if args.variants:
        picked = args.variants.split(",")
        bad = set(picked) - set(VARIANTS)
        if bad:
            raise SystemExit(f"unknown variants {sorted(bad)}; have {sorted(VARIANTS)}")
    else:
        picked = [n for n in VARIANTS if n not in results]
    for name in picked:
        cfg = VARIANTS[name]()
        nbytes = state_nbytes(cfg)["total"]
        rep = run_fault_eval(n_streams=args.streams, length=args.length,
                             kinds=kinds, cfg=cfg, backend="tpu")
        d = dataclasses.asdict(rep)
        results[name] = {
            "bytes_per_stream": int(nbytes),
            # per-variant: a merged re-run at another scale must not
            # relabel previously measured entries
            "protocol": f"{args.streams} x {args.length}, "
                        + ("all kinds" if args.all_kinds
                           else "fault_eval defaults"),
            "at_best": d["at_best"],
            "best_threshold": d.get("best_threshold"),
            "per_kind": d.get("per_kind"),
        }
        print(json.dumps({name: results[name]["at_best"]}), flush=True)

    out = {
        "baseline_full": {
            "note": "reports/fault_eval.json (256 cols, 564 KB/stream u16)",
        },
        "variants": results,
    }
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(out, f, indent=2)
    return 0


if __name__ == "__main__":
    sys.exit(main())
