"""Profile the fused stream-group step on the real chip.

Breaks the per-tick cost down by (a) group size scaling, (b) component
ablation (encode / SP / TM, learn on/off), so optimization effort lands on
the measured bottleneck (VERDICT r1 next-step 1). Run on hardware:

    PYTHONPATH=/root/repo:/root/.axon_site python scripts/profile_step.py [--trace DIR]

Prints a table to stderr; with --trace, wraps one measured chunk in a
jax.profiler trace for xprof.
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from rtap_tpu.utils.platform import init_backend_or_die, maybe_force_cpu  # noqa: E402

# must precede the jax / rtap_tpu.ops imports below — ops modules hold
# module-level jnp constants that initialize the backend at import time
maybe_force_cpu()
init_backend_or_die()  # the tunnel oscillates; die fast instead of hanging

import jax  # noqa: E402
import jax.numpy as jnp
import numpy as np

from rtap_tpu.config import ModelConfig, cluster_preset
from rtap_tpu.models.state import init_state
from rtap_tpu.ops.encoders_tpu import bind_offsets, encode_device
from rtap_tpu.ops.sp_tpu import sp_step
from rtap_tpu.ops.tm_tpu import tm_step
from rtap_tpu.ops.step import chunk_step, replicate_state_device


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def make_inputs(G, T, n_fields, seed=0):
    rng = np.random.Generator(np.random.Philox(key=(seed, 77)))
    vals = (35 + 20 * rng.random((T, G, n_fields))).astype(np.float32)
    ts = (1_700_000_000 + np.arange(T)[:, None] + np.zeros((1, G), np.int64)).astype(np.int32)
    return vals, ts


def time_fn(fn, state, iters=3, warmup=1):
    """fn(state) -> (state, aux); state buffers are donated, so thread them."""
    for _ in range(warmup):
        state, _ = fn(state)
    jax.block_until_ready(state)
    t0 = time.perf_counter()
    for _ in range(iters):
        state, _ = fn(state)
    jax.block_until_ready(state)
    return (time.perf_counter() - t0) / iters


# ---- ablation kernels: scan-over-T, vmap-over-G, one component only ----

def _scan_vmap(body, state, xs):
    def step(s, inp):
        return jax.vmap(body)(s, *inp)
    return jax.lax.scan(step, state, xs)


@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(0,))
def encode_only(state, vals, ts, cfg: ModelConfig):
    def body(s, v, t):
        off, bound = bind_offsets(v, s["enc_offset"], s["enc_bound"])
        s = {**s, "enc_offset": off, "enc_bound": bound}
        sdr = encode_device(cfg, v, t, off, s["enc_resolution"])
        return s, sdr.sum()
    return _scan_vmap(body, state, (vals, ts))


@partial(jax.jit, static_argnames=("cfg", "learn"), donate_argnums=(0,))
def sp_only(state, vals, ts, cfg: ModelConfig, learn=True):
    def body(s, v, t):
        sdr = encode_device(cfg, v, t, s["enc_offset"], s["enc_resolution"])
        s, active = sp_step(s, sdr, cfg.sp, learn)
        return s, active.sum()
    return _scan_vmap(body, state, (vals, ts))


@partial(jax.jit, static_argnames=("cfg", "learn"), donate_argnums=(0,))
def tm_only(state, actives, cfg: ModelConfig, learn=True):
    from rtap_tpu.ops.tm_tpu import from_kernel_layout, to_kernel_layout

    def body(s, a):
        s, raw = tm_step(s, a, cfg.tm, learn)
        return s, raw
    def step(s, a):
        return jax.vmap(body)(s, a)
    state, out = jax.lax.scan(step, to_kernel_layout(state), actives)
    return from_kernel_layout(state, cfg.tm), out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--trace", default=None)
    ap.add_argument("--T", type=int, default=32)
    ap.add_argument("--gs", type=int, nargs="*", default=[512, 2048, 4096, 8192])
    ap.add_argument("--pallas", action="store_true",
                    help="route the TM dendrite pass through the Pallas "
                         "kernel (ops/pallas_tm.py) — compare a run with "
                         "and without this flag on hardware")
    ap.add_argument("--scatter", choices=("matmul", "indexed"), default=None,
                    help="TM workspace-movement strategy (ops/tm_tpu.py "
                         "SCATTER_MODE): 'indexed' moves only touched rows, "
                         "'matmul' is the one-hot MXU formulation — A/B on "
                         "hardware")
    ap.add_argument("--layout", choices=("aos", "flat"), default=None,
                    help="TM kernel tensor layout (ops/tm_tpu.py LAYOUT_MODE):"
                         " 'flat' carries [C, K*S*M] pools through the scan "
                         "(no trailing-dim tile padding), 'aos' is the 4-D "
                         "original — A/B on hardware")
    ap.add_argument("--perm-bits", type=int, default=16, choices=(0, 8, 16),
                    help="permanence storage domain of the profiled cluster "
                         "preset: u16/u8 halve HBM per stream but add per-tick "
                         "storage<->compute conversions; f32 (0) skips them — "
                         "the faster choice may differ from the denser one")
    ap.add_argument("--sweep", choices=("dense", "compact"), default=None,
                    help="TM punish/death strategy (ops/tm_tpu.py SWEEP_MODE):"
                         " 'compact' touches only the <= punish_cap+learn_cap "
                         "affected segment rows, 'dense' sweeps the full "
                         "pools — A/B on hardware")
    ap.add_argument("--dendrite", choices=("scan", "forward"), default=None,
                    help="TM dendrite-activity strategy: 'forward' gathers "
                         "the active cells' forward-index rows (ops/"
                         "fwd_index.py; state grows by the index), 'scan' "
                         "sweeps the pools — A/B on hardware")
    ap.add_argument("--fwd-impl", choices=("scatter", "matmul"), default=None,
                    help="forward-index histogram accumulation: native "
                         "scatter-add vs factored one-hot MXU contraction")
    ap.add_argument("--learn-every", type=int, default=1,
                    help="learning cadence (ModelConfig.learn_every) with "
                         "learn_full_until=0: measures the cadenced steady "
                         "state (the lax.cond schedule in ops/step.py)")
    ap.add_argument("--columns", type=int, default=None,
                    help="rescale the preset to this SP width at equal "
                         "sparsity (config.scaled_cluster_preset; the "
                         "half-size 128-col model measured BETTER f1 than "
                         "the preset at half the state — "
                         "reports/model_size_quality.json)")
    ap.add_argument("--fanout-cap", type=int, default=None,
                    help="forward-index row width F (default: 384 under "
                         "--dendrite forward — the measured diurnal-workload "
                         "fanout tail; preset default otherwise). An "
                         "undersized F trips fwd_of and corrupts the "
                         "dendrite dynamics, invalidating the A/B")
    args = ap.parse_args()

    from rtap_tpu.utils.platform import enable_compile_cache

    enable_compile_cache(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    if args.pallas:
        from rtap_tpu.ops.pallas_tm import set_use_pallas

        set_use_pallas(True)
        log("Pallas dendrite kernel: ENABLED")
    if args.scatter:
        from rtap_tpu.ops.tm_tpu import set_scatter_mode

        set_scatter_mode(args.scatter)
        log(f"TM workspace movement: {args.scatter}")
    if args.layout:
        from rtap_tpu.ops.tm_tpu import set_layout_mode

        set_layout_mode(args.layout)
        log(f"TM kernel layout: {args.layout}")
    if args.sweep:
        from rtap_tpu.ops.tm_tpu import set_sweep_mode

        set_sweep_mode(args.sweep)
        log(f"TM punish/death sweep: {args.sweep}")
    if args.dendrite:
        from rtap_tpu.ops.tm_tpu import set_dendrite_mode

        set_dendrite_mode(args.dendrite)
        log(f"TM dendrite strategy: {args.dendrite}")
    if args.fwd_impl:
        from rtap_tpu.ops.tm_tpu import set_fwd_impl

        set_fwd_impl(args.fwd_impl)
        log(f"forward-index histogram impl: {args.fwd_impl}")

    if args.columns:
        from rtap_tpu.config import scaled_cluster_preset

        cfg = scaled_cluster_preset(args.columns, perm_bits=args.perm_bits)
        log(f"scaled preset: {args.columns} columns")
    else:
        cfg = cluster_preset(perm_bits=args.perm_bits)
    if args.fanout_cap or args.dendrite == "forward":
        import dataclasses

        F = args.fanout_cap or 384
        cfg = dataclasses.replace(cfg, tm=dataclasses.replace(cfg.tm, fanout_cap=F))
        log(f"forward-index fanout cap: {F}")
    if args.learn_every > 1:
        import dataclasses

        # learn_full_until=0: cadence applies from tick 0 so the measured
        # steady state is the cadenced one (quality study owns the maturity
        # window; this is a pure throughput probe)
        cfg = dataclasses.replace(cfg, learn_every=args.learn_every)
        log(f"learning cadence: every {args.learn_every} ticks")
    T = args.T
    log(f"platform: {jax.devices()[0].platform} {jax.devices()[0].device_kind} "
        f"(perm_bits={args.perm_bits})")

    log("\n== G scaling, full step (learn=True) ==")
    results = {}
    for G in args.gs:
        try:
            state = replicate_state_device(init_state(cfg, 0), G)
            vals, ts = make_inputs(G, T, cfg.n_fields)
            dt = time_fn(lambda s: chunk_step(s, vals, ts, cfg, True), state, iters=2)
            per_tick = dt / T
            rate = G * T / dt
            results[G] = rate
            log(f"G={G:6d}: {per_tick*1e3:8.2f} ms/tick  {rate:10.0f} metrics/s")
        except Exception as e:
            log(f"G={G:6d}: FAILED {type(e).__name__}: {str(e)[:120]}")

    if not results:
        # every probed G failed (OOM-frontier probes do this by design):
        # the FAILED lines above ARE the result — exit 0 so a watcher
        # step wrapping this run doesn't burn retries on a deterministic
        # outcome
        log("\nno G succeeded; skipping ablations")
        return 0
    # ablations at the LARGEST G that also leaves room for their extra
    # buffers: at the OOM frontier the main sweep fits but the ablation
    # temporaries (fresh state replicas, TM-only activation masks) do not
    # — each row is guarded so a frontier run still reports what fits,
    # and a row failure can never fail the step (watcher-attempt safety)
    G = max(g for g in results)
    log(f"\n== ablations at G={G}, T={T} ==")
    vals, ts = make_inputs(G, T, cfg.n_fields)

    def ablate(label, fn):
        try:
            st = replicate_state_device(init_state(cfg, 0), G)
            dt = time_fn(fn, st, iters=2)
            log(f"{label}: {dt/T*1e3:8.2f} ms/tick")
        except Exception as e:
            log(f"{label}: FAILED {type(e).__name__}: {str(e)[:100]}")

    ablate("full learn=True ", lambda s: chunk_step(s, vals, ts, cfg, True))
    ablate("full learn=False", lambda s: chunk_step(s, vals, ts, cfg, False))
    ablate("encode only     ", lambda s: encode_only(s, vals, ts, cfg))
    ablate("enc+SP learn    ", lambda s: sp_only(s, vals, ts, cfg, True))
    ablate("enc+SP infer    ", lambda s: sp_only(s, vals, ts, cfg, False))

    # TM alone: feed plausible active-column masks (k of C)
    try:
        rng = np.random.Generator(np.random.Philox(key=(1, 78)))
        C, k = cfg.sp.columns, cfg.sp.num_active_columns
        acts = np.zeros((T, G, C), bool)
        idx = rng.integers(0, C, (T, G, k))
        np.put_along_axis(acts, idx, True, axis=-1)
        acts_d = jnp.asarray(acts)
        ablate("TM only learn   ", lambda s: tm_only(s, acts_d, cfg, True))
        ablate("TM only infer   ", lambda s: tm_only(s, acts_d, cfg, False))
    except Exception as e:
        log(f"TM only         : FAILED {type(e).__name__}: {str(e)[:100]}")

    if args.trace:
        st = replicate_state_device(init_state(cfg, 0), G)
        chunk_step(st, vals, ts, cfg, True)  # compiled above; warm anyway
        st = replicate_state_device(init_state(cfg, 0), G)
        with jax.profiler.trace(args.trace):
            st, raw = chunk_step(st, vals, ts, cfg, True)
            jax.block_until_ready(raw)
        log(f"trace written to {args.trace}")


if __name__ == "__main__":
    main()
