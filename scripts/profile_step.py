"""Profile the fused stream-group step on the real chip.

Breaks the per-tick cost down by (a) group size scaling, (b) component
ablation (encode / SP / TM, learn on/off), and (c) — with --report — a
programmatic per-region cost extraction of the compiled program (entry-
computation region counts by opcode, XLA cost/memory analysis), so
optimization effort lands on the measured bottleneck (VERDICT r1
next-step 1) and the "where does the 10x latency-bound gap go" question
(reports/roofline.json) gets a committed, machine-readable answer. Run on
hardware:

    PYTHONPATH=/root/repo:/root/.axon_site python scripts/profile_step.py \
        [--trace DIR] [--report reports/profile_r06.json]

Prints a table to stderr; with --trace, wraps one measured chunk in a
jax.profiler trace for xprof; with --report, writes the full breakdown +
region analysis as one JSON artifact (platform-labeled — a CPU-drive run
is marked as such, never passed off as silicon).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from rtap_tpu.utils.platform import init_backend_or_die, maybe_force_cpu  # noqa: E402

# must precede the jax / rtap_tpu.ops imports below — ops modules hold
# module-level jnp constants that initialize the backend at import time
maybe_force_cpu()
init_backend_or_die()  # the tunnel oscillates; die fast instead of hanging

import jax  # noqa: E402
import jax.numpy as jnp
import numpy as np

from rtap_tpu.config import ModelConfig, cluster_preset
from rtap_tpu.models.state import init_state
from rtap_tpu.ops.encoders_tpu import bind_offsets, encode_device
from rtap_tpu.ops.sp_tpu import sp_step
from rtap_tpu.ops.tm_tpu import tm_step
from rtap_tpu.ops.step import chunk_step, replicate_state_device


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def make_inputs(G, T, n_fields, seed=0):
    rng = np.random.Generator(np.random.Philox(key=(seed, 77)))
    vals = (35 + 20 * rng.random((T, G, n_fields))).astype(np.float32)
    ts = (1_700_000_000 + np.arange(T)[:, None] + np.zeros((1, G), np.int64)).astype(np.int32)
    return vals, ts


def time_fn(fn, state, iters=3, warmup=1):
    """fn(state) -> (state, aux); state buffers are donated, so thread them."""
    for _ in range(warmup):
        state, _ = fn(state)
    jax.block_until_ready(state)
    t0 = time.perf_counter()
    for _ in range(iters):
        state, _ = fn(state)
    jax.block_until_ready(state)
    return (time.perf_counter() - t0) / iters


def region_analysis(cfg, G: int, T: int) -> dict:
    """Programmatic per-region cost extraction of the compiled fused step.

    Compiles the REAL chunk_step at (G, T) and reads, from the optimized
    HLO itself (no trace viewer in the loop): the entry-computation
    instruction count — each top-level instruction is one scheduled region
    / kernel launch, the currency the roofline's latency_bound_factor says
    we overspend — a histogram by opcode, the fusion-region count, and
    XLA's cost/memory analysis. Platform-dependent by construction: the
    committed artifact labels the platform, and the silicon number is the
    one that decides (hw_session step profile_r06)."""
    import re

    from rtap_tpu.models.state import init_state
    from rtap_tpu.ops.step import chunk_step

    state = jax.tree_util.tree_map(
        lambda x: jnp.broadcast_to(jnp.asarray(x)[None], (G, *np.shape(x))),
        init_state(cfg, seed=0))
    vals = jnp.zeros((T, G, cfg.n_fields), jnp.float32)
    ts = jnp.zeros((T, G), jnp.int32)
    def _chunk_learn(s, v, t):
        return chunk_step(s, v, t, cfg, learn=True)

    fn = jax.jit(_chunk_learn)
    compiled = fn.lower(state, vals, ts).compile()

    txt = compiled.as_text()

    def op_histogram(block: str) -> dict[str, int]:
        # one instruction per line: `%name = <shape> opcode(...)`; the
        # shape may be a spaced tuple, so the opcode is the FIRST
        # word-followed-by-( after the `=`
        ops: dict[str, int] = {}
        for line in block.splitlines():
            m = re.search(r"=\s+.*?\s([a-z][a-z0-9_-]*)\(", line)
            if m:
                ops[m.group(1)] = ops.get(m.group(1), 0) + 1
        return ops

    # entry computation: from "ENTRY %name" to its closing brace
    entry = txt[txt.index("ENTRY "):] if "ENTRY " in txt else txt
    entry = entry[:entry.index("\n}") + 2] if "\n}" in entry else entry
    ops = op_histogram(entry)
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    mem = compiled.memory_analysis()
    out = {
        "entry_instructions": sum(ops.values()),
        "fusion_regions": ops.get("fusion", 0),
        "while_loops": ops.get("while", 0),
        "opcode_histogram": dict(sorted(ops.items(), key=lambda kv: -kv[1])),
        "flops_per_chunk": float(ca.get("flops", 0.0)),
        "bytes_accessed_per_chunk": float(ca.get("bytes accessed", 0.0)),
    }
    if mem is not None:
        for k in ("temp_size_in_bytes", "argument_size_in_bytes",
                  "output_size_in_bytes", "generated_code_size_in_bytes"):
            v = getattr(mem, k, None)
            if v is not None:
                out[k] = int(v)
    # the scan body is where per-tick dispatch gaps live: resolve the
    # while instruction's body= computation and count ITS regions — each
    # is a per-tick dispatch boundary, paid T times per chunk
    wm = re.search(r"\swhile\(.*?body=%?([\w.\-]+)", entry)
    if wm:
        bm = re.search(r"\n%" + re.escape(wm.group(1)) + r"\s.*?\n}",
                       txt, re.S)
        if bm:
            bops = op_histogram(bm.group(0))
            out["scan_body_instructions"] = sum(bops.values())
            out["scan_body_fusions"] = bops.get("fusion", 0)
            out["scan_body_opcode_histogram"] = dict(
                sorted(bops.items(), key=lambda kv: -kv[1]))
    return out


# ---- ablation kernels: scan-over-T, vmap-over-G, one component only ----

def _scan_vmap(body, state, xs):
    def step(s, inp):
        return jax.vmap(body)(s, *inp)
    return jax.lax.scan(step, state, xs)


@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(0,))
def encode_only(state, vals, ts, cfg: ModelConfig):
    def body(s, v, t):
        off, bound = bind_offsets(v, s["enc_offset"], s["enc_bound"])
        s = {**s, "enc_offset": off, "enc_bound": bound}
        sdr = encode_device(cfg, v, t, off, s["enc_resolution"])
        return s, sdr.sum()
    return _scan_vmap(body, state, (vals, ts))


@partial(jax.jit, static_argnames=("cfg", "learn"), donate_argnums=(0,))
def sp_only(state, vals, ts, cfg: ModelConfig, learn=True):
    def body(s, v, t):
        sdr = encode_device(cfg, v, t, s["enc_offset"], s["enc_resolution"])
        s, active = sp_step(s, sdr, cfg.sp, learn)
        return s, active.sum()
    return _scan_vmap(body, state, (vals, ts))


@partial(jax.jit, static_argnames=("cfg", "learn"), donate_argnums=(0,))
def tm_only(state, actives, cfg: ModelConfig, learn=True):
    from rtap_tpu.ops.tm_tpu import from_kernel_layout, to_kernel_layout

    def body(s, a):
        s, raw = tm_step(s, a, cfg.tm, learn)
        return s, raw
    def step(s, a):
        return jax.vmap(body)(s, a)
    state, out = jax.lax.scan(step, to_kernel_layout(state), actives)
    return from_kernel_layout(state, cfg.tm), out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--trace", default=None)
    ap.add_argument("--T", type=int, default=32)
    ap.add_argument("--gs", type=int, nargs="*", default=[512, 2048, 4096, 8192])
    ap.add_argument("--report", default=None,
                    help="write the full profile (G sweep, ablations, "
                         "per-region cost extraction of the compiled "
                         "program) to this JSON path")
    ap.add_argument("--region-g", type=int, default=1024,
                    help="group size the --report region extraction "
                         "compiles at (compile-only — G=1024 is the "
                         "roofline's reference point and stays cheap even "
                         "where executing it would not be)")
    ap.add_argument("--scatter", choices=("matmul", "indexed", "pallas"),
                    default=None,
                    help="TM workspace-movement strategy (ops/tm_tpu.py "
                         "SCATTER_MODE): 'indexed' moves only touched rows, "
                         "'matmul' is the one-hot MXU formulation, 'pallas' "
                         "is the VMEM TM-learning megakernel "
                         "(ops/pallas_tm.py) — A/B on hardware")
    ap.add_argument("--layout", choices=("aos", "flat"), default=None,
                    help="TM kernel tensor layout (ops/tm_tpu.py LAYOUT_MODE):"
                         " 'flat' carries [C, K*S*M] pools through the scan "
                         "(no trailing-dim tile padding), 'aos' is the 4-D "
                         "original — A/B on hardware")
    ap.add_argument("--perm-bits", type=int, default=16, choices=(0, 8, 16),
                    help="permanence storage domain of the profiled cluster "
                         "preset: u16/u8 halve HBM per stream but add per-tick "
                         "storage<->compute conversions; f32 (0) skips them — "
                         "the faster choice may differ from the denser one")
    ap.add_argument("--sweep", choices=("dense", "compact"), default=None,
                    help="TM punish/death strategy (ops/tm_tpu.py SWEEP_MODE):"
                         " 'compact' touches only the <= punish_cap+learn_cap "
                         "affected segment rows, 'dense' sweeps the full "
                         "pools — A/B on hardware")
    ap.add_argument("--dendrite", choices=("scan", "forward"), default=None,
                    help="TM dendrite-activity strategy: 'forward' gathers "
                         "the active cells' forward-index rows (ops/"
                         "fwd_index.py; state grows by the index), 'scan' "
                         "sweeps the pools — A/B on hardware")
    ap.add_argument("--fwd-impl", choices=("scatter", "matmul"), default=None,
                    help="forward-index histogram accumulation: native "
                         "scatter-add vs factored one-hot MXU contraction")
    ap.add_argument("--learn-every", type=int, default=1,
                    help="learning cadence (ModelConfig.learn_every) with "
                         "learn_full_until=0: measures the cadenced steady "
                         "state (the lax.cond schedule in ops/step.py)")
    ap.add_argument("--columns", type=int, default=None,
                    help="rescale the preset to this SP width at equal "
                         "sparsity (config.scaled_cluster_preset; the "
                         "half-size 128-col model measured BETTER f1 than "
                         "the preset at half the state — "
                         "reports/model_size_quality.json)")
    ap.add_argument("--fanout-cap", type=int, default=None,
                    help="forward-index row width F (default: 384 under "
                         "--dendrite forward — the measured diurnal-workload "
                         "fanout tail; preset default otherwise). An "
                         "undersized F trips fwd_of and corrupts the "
                         "dendrite dynamics, invalidating the A/B")
    args = ap.parse_args()

    from rtap_tpu.utils.platform import enable_compile_cache

    enable_compile_cache(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    if args.scatter:
        from rtap_tpu.ops.tm_tpu import set_scatter_mode

        set_scatter_mode(args.scatter)
        log(f"TM workspace movement: {args.scatter}")
    if args.layout:
        from rtap_tpu.ops.tm_tpu import set_layout_mode

        set_layout_mode(args.layout)
        log(f"TM kernel layout: {args.layout}")
    if args.sweep:
        from rtap_tpu.ops.tm_tpu import set_sweep_mode

        set_sweep_mode(args.sweep)
        log(f"TM punish/death sweep: {args.sweep}")
    if args.dendrite:
        from rtap_tpu.ops.tm_tpu import set_dendrite_mode

        set_dendrite_mode(args.dendrite)
        log(f"TM dendrite strategy: {args.dendrite}")
    if args.fwd_impl:
        from rtap_tpu.ops.tm_tpu import set_fwd_impl

        set_fwd_impl(args.fwd_impl)
        log(f"forward-index histogram impl: {args.fwd_impl}")

    if args.columns:
        from rtap_tpu.config import scaled_cluster_preset

        cfg = scaled_cluster_preset(args.columns, perm_bits=args.perm_bits)
        log(f"scaled preset: {args.columns} columns")
    else:
        cfg = cluster_preset(perm_bits=args.perm_bits)
    if args.fanout_cap or args.dendrite == "forward":
        import dataclasses

        F = args.fanout_cap or 384
        cfg = dataclasses.replace(cfg, tm=dataclasses.replace(cfg.tm, fanout_cap=F))
        log(f"forward-index fanout cap: {F}")
    if args.learn_every > 1:
        import dataclasses

        # learn_full_until=0: cadence applies from tick 0 so the measured
        # steady state is the cadenced one (quality study owns the maturity
        # window; this is a pure throughput probe)
        cfg = dataclasses.replace(cfg, learn_every=args.learn_every)
        log(f"learning cadence: every {args.learn_every} ticks")
    T = args.T
    log(f"platform: {jax.devices()[0].platform} {jax.devices()[0].device_kind} "
        f"(perm_bits={args.perm_bits})")

    report = {
        "platform": jax.devices()[0].platform,
        "device_kind": jax.devices()[0].device_kind,
        "T": T,
        "perm_bits": args.perm_bits,
        "columns": args.columns,
        "learn_every": args.learn_every,
        "modes": None,  # filled below (import deferred until flags applied)
        "g_sweep": {},
        "ablations_ms_per_tick": {},
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    from rtap_tpu.ops.tm_tpu import (
        dendrite_mode, layout_mode, scatter_mode, sweep_mode,
    )

    report["modes"] = (f"{layout_mode()}/{scatter_mode()}/{sweep_mode()}"
                       f"/{dendrite_mode()}")

    log("\n== G scaling, full step (learn=True) ==")
    results = {}
    for G in args.gs:
        try:
            state = replicate_state_device(init_state(cfg, 0), G)
            vals, ts = make_inputs(G, T, cfg.n_fields)
            dt = time_fn(lambda s: chunk_step(s, vals, ts, cfg, True), state, iters=2)
            per_tick = dt / T
            rate = G * T / dt
            results[G] = rate
            report["g_sweep"][str(G)] = {
                "ms_per_tick": round(per_tick * 1e3, 3),
                "metrics_per_s": round(rate, 1),
            }
            log(f"G={G:6d}: {per_tick*1e3:8.2f} ms/tick  {rate:10.0f} metrics/s")
        except Exception as e:
            report["g_sweep"][str(G)] = {
                "failed": f"{type(e).__name__}: {str(e)[:160]}"}
            log(f"G={G:6d}: FAILED {type(e).__name__}: {str(e)[:120]}")

    if not results:
        # every probed G failed (OOM-frontier probes do this by design):
        # the FAILED lines above ARE the result — exit 0 so a watcher
        # step wrapping this run doesn't burn retries on a deterministic
        # outcome
        log("\nno G succeeded; skipping ablations")
        return 0
    # ablations at the LARGEST G that also leaves room for their extra
    # buffers: at the OOM frontier the main sweep fits but the ablation
    # temporaries (fresh state replicas, TM-only activation masks) do not
    # — each row is guarded so a frontier run still reports what fits,
    # and a row failure can never fail the step (watcher-attempt safety)
    G = max(g for g in results)
    log(f"\n== ablations at G={G}, T={T} ==")
    vals, ts = make_inputs(G, T, cfg.n_fields)

    report["ablation_G"] = G

    def ablate(label, fn):
        try:
            st = replicate_state_device(init_state(cfg, 0), G)
            dt = time_fn(fn, st, iters=2)
            report["ablations_ms_per_tick"][label.strip()] = round(dt / T * 1e3, 3)
            log(f"{label}: {dt/T*1e3:8.2f} ms/tick")
        except Exception as e:
            report["ablations_ms_per_tick"][label.strip()] = (
                f"FAILED {type(e).__name__}")
            log(f"{label}: FAILED {type(e).__name__}: {str(e)[:100]}")

    ablate("full learn=True ", lambda s: chunk_step(s, vals, ts, cfg, True))
    ablate("full learn=False", lambda s: chunk_step(s, vals, ts, cfg, False))
    ablate("encode only     ", lambda s: encode_only(s, vals, ts, cfg))
    ablate("enc+SP learn    ", lambda s: sp_only(s, vals, ts, cfg, True))
    ablate("enc+SP infer    ", lambda s: sp_only(s, vals, ts, cfg, False))

    # TM alone: feed plausible active-column masks (k of C)
    try:
        rng = np.random.Generator(np.random.Philox(key=(1, 78)))
        C, k = cfg.sp.columns, cfg.sp.num_active_columns
        acts = np.zeros((T, G, C), bool)
        idx = rng.integers(0, C, (T, G, k))
        np.put_along_axis(acts, idx, True, axis=-1)
        acts_d = jnp.asarray(acts)
        ablate("TM only learn   ", lambda s: tm_only(s, acts_d, cfg, True))
        ablate("TM only infer   ", lambda s: tm_only(s, acts_d, cfg, False))
    except Exception as e:
        log(f"TM only         : FAILED {type(e).__name__}: {str(e)[:100]}")

    if args.trace:
        st = replicate_state_device(init_state(cfg, 0), G)
        chunk_step(st, vals, ts, cfg, True)  # compiled above; warm anyway
        st = replicate_state_device(init_state(cfg, 0), G)
        with jax.profiler.trace(args.trace):
            st, raw = chunk_step(st, vals, ts, cfg, True)
            jax.block_until_ready(raw)
        log(f"trace written to {args.trace}")
        report["trace_dir"] = args.trace

    if args.report:
        # per-region cost extraction of the program the sweep measured:
        # region counts name where the latency-bound factor goes (dispatch
        # edges between regions), cost/memory analysis ties them to the
        # roofline floors
        try:
            log("\n== per-region cost extraction (compiled HLO) ==")
            ra = region_analysis(cfg, args.region_g, T)
            ra["G"] = args.region_g
            report["region_analysis"] = ra
            log(f"entry instructions: {ra['entry_instructions']} "
                f"(fusions {ra['fusion_regions']}); scan body: "
                f"{ra.get('scan_body_instructions', '?')} instructions / "
                f"{ra.get('scan_body_fusions', '?')} fusions")
        except Exception as e:  # keep the measured numbers even if HLO
            # introspection breaks on some backend
            report["region_analysis"] = {"failed": f"{type(e).__name__}: {e}"}
            log(f"region analysis FAILED: {type(e).__name__}: {str(e)[:120]}")
        os.makedirs(os.path.dirname(os.path.abspath(args.report)), exist_ok=True)
        with open(args.report, "w") as f:
            json.dump(report, f, indent=2)
        log(f"report written to {args.report}")


if __name__ == "__main__":
    main()
