"""Predictive-horizon cascade eval: page BEFORE the second node falls over.

ISSUE 16 acceptance gate. A seeded two-service cluster takes ONE
cascading fault whose origin node first degrades slowly — a linear
drift climbing over ``--precursor-ticks`` ticks before the origin's
step fault, with downstream nodes stepping ``--cascade-lag`` ticks
apart (data/synthetic.generate_topology_workload with a precursor
ramp). The full predict stack flies in-process: groups carry the fused
predictive-divergence reducer (``predict=k``), a PredictTracker turns
sustained divergence into ``precursor`` events, and a BlastFuser over
the declared topology collapses them into one ``predicted_incident``
at the FIRST node with the predicted blast radius.

The run FAILS (exit 5) unless eval/fault_eval.score_lead_time says

- ``win``: the first page lands strictly BEFORE the second node's fault
  onset (the cascade was still preventable when the operator was paged),
- the predicted blast radius covers every faulted cascade node, and
- zero false precursors fired on the healthy control service.

The committed artifact is reports/predict_r15.json (hw_session step
``r15_predict`` re-measures it on silicon; this script is cpu-safe).

Usage: python scripts/predict_eval.py [--ticks 400] [--seed 0]
       [--horizon 8] [--threshold 0.35] [--min-ticks 12]
       [--out reports/predict_r15.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from rtap_tpu.utils.platform import maybe_force_cpu  # noqa: E402

VERIFY_FAILED_EXIT = 5

#: short probation (workload_soak's discipline) so a few-hundred-tick
#: run has a mature window long before the ramp begins
EVAL_LEARNING_PERIOD = 60
EVAL_ESTIMATION = 30


def log(msg: str) -> None:
    print(f"[predict] {msg}", file=sys.stderr, flush=True)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ticks", type=int, default=400)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--services", type=int, default=2)
    ap.add_argument("--nodes-per-service", type=int, default=3)
    ap.add_argument("--burst-at-frac", type=float, default=0.75)
    ap.add_argument("--cascade-lag", type=int, default=8)
    ap.add_argument("--burst-dur", type=int, default=12)
    ap.add_argument("--precursor-ramp", type=float, default=8.0,
                    help="origin-node drift magnitude in noise sigmas "
                         "at the tick before its step fault")
    ap.add_argument("--precursor-ticks", type=int, default=80,
                    help="length of the origin node's pre-fault drift")
    ap.add_argument("--horizon", type=int, default=8)
    ap.add_argument("--threshold", type=float, default=0.35)
    ap.add_argument("--min-ticks", type=int, default=12)
    ap.add_argument("--backend", default="tpu")
    ap.add_argument("--out",
                    default=os.path.join(REPO, "reports", "predict_r15.json"))
    args = ap.parse_args()

    maybe_force_cpu()

    import dataclasses

    import numpy as np

    from rtap_tpu.config import cluster_preset
    from rtap_tpu.correlate import TopologyMap
    from rtap_tpu.data.synthetic import (
        SyntheticStreamConfig,
        generate_topology_workload,
    )
    from rtap_tpu.eval.fault_eval import score_lead_time
    from rtap_tpu.predict import BlastFuser, PredictTracker
    from rtap_tpu.service.loop import live_loop
    from rtap_tpu.service.registry import StreamGroupRegistry

    scfg = SyntheticStreamConfig(length=args.ticks, n_anomalies=0,
                                 noise_phi=0.9, noise_scale=0.3)
    wl = generate_topology_workload(
        n_services=args.services,
        nodes_per_service=args.nodes_per_service,
        cfg=scfg, seed=args.seed, burst_at_frac=args.burst_at_frac,
        cascade_lag=args.cascade_lag, burst_dur=args.burst_dur,
        precursor_ramp=args.precursor_ramp,
        precursor_ticks=args.precursor_ticks)
    log(f"cascade: origin {wl.precursor_node} ramps from tick "
        f"{wl.precursor_start}; onsets {wl.burst_onsets}")

    ids = [s.stream_id for s in wl.streams]
    values = np.stack([s.values for s in wl.streams], axis=1)  # [T, N]
    ts = wl.streams[0].timestamps
    base = cluster_preset()
    cfg = dataclasses.replace(base, likelihood=dataclasses.replace(
        base.likelihood, learning_period=EVAL_LEARNING_PERIOD,
        estimation_samples=EVAL_ESTIMATION))
    reg = StreamGroupRegistry(cfg, group_size=len(ids),
                              backend=args.backend, threshold=0.0,
                              debounce=1, predict=args.horizon)
    for sid in ids:
        reg.add_stream(sid)
    reg.finalize()

    events: list[dict] = []
    predictor = PredictTracker(
        horizon=args.horizon, threshold=args.threshold,
        min_ticks=args.min_ticks, sink=events.append,
        blast=BlastFuser(TopologyMap.from_spec(wl.spec),
                         seed_streams=ids))

    def feed(k: int):
        return values[k], int(ts[k])

    t0 = time.perf_counter()
    stats = live_loop(feed, reg, n_ticks=args.ticks, cadence_s=0.0,
                      predictor=predictor)
    elapsed = time.perf_counter() - t0
    score = score_lead_time(events, wl.burst_onsets, wl.burst_nodes)

    failures: list[str] = []
    if not score["paged"]:
        failures.append("no precursor/predicted_incident fired on the "
                        "cascade service")
    elif score["lead_ticks_vs_second"] is None \
            or score["lead_ticks_vs_second"] <= 0:
        failures.append(
            f"paged at tick {score['page_tick']}, AFTER the second "
            f"node's onset {score['second_onset']} — no lead")
    if not score["blast_covered"]:
        failures.append(
            "predicted blast radius does not cover the faulted nodes: "
            f"{score['predicted_incident']} vs {wl.burst_nodes}")
    if score["false_precursors"]:
        failures.append(f"{score['false_precursors']} false precursor(s) "
                        "on the healthy control service")

    result = {
        "verified": not failures,
        "failures": failures,
        "scenario": {
            "ticks": args.ticks, "seed": args.seed,
            "services": args.services,
            "nodes_per_service": args.nodes_per_service,
            "cascade_lag": args.cascade_lag,
            "burst_dur": args.burst_dur,
            "precursor_ramp": args.precursor_ramp,
            "precursor_ticks": args.precursor_ticks,
            "precursor_node": wl.precursor_node,
            "precursor_start": wl.precursor_start,
            "burst_onsets": wl.burst_onsets,
            "n_streams": len(ids),
        },
        "predictor": {
            "horizon_ticks": args.horizon,
            "threshold": args.threshold,
            "min_ticks": args.min_ticks,
        },
        "score": score,
        "predict_stats": stats.get("predict"),
        "backend": args.backend,
        "native_active": bool(stats.get("native_active")),
        "elapsed_s": round(elapsed, 3),
    }
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(result, f, indent=2, sort_keys=True)
        f.write("\n")
    log(f"report written to {args.out}")
    print(json.dumps(score, indent=2))
    if failures:
        for msg in failures:
            log(f"FAIL: {msg}")
        return VERIFY_FAILED_EXIT
    log(f"VERIFIED: paged {score['lead_ticks_vs_second']} ticks before "
        f"the second node's onset (origin lead "
        f"{score['lead_ticks_vs_origin']}), blast radius "
        f"{score['predicted_incident']['blast_radius']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
