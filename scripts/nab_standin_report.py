"""Score the full NAB stand-in corpus and commit the result as an artifact.

Runs the detector over every file of the stand-in corpus (8 files, 5 metric
profiles — data/nab_corpus.STANDIN_FILES) through the full NAB machinery
(per-file detection -> threshold sweep -> scaled-sigmoid window scoring ->
normalization) and writes reports/nab_standin.json with per-profile scores.

The stand-in is NOT the real NAB corpus (absent in this offline environment
— SURVEY.md §6 blocker); its absolute scores are not comparable to the
public scoreboard. What the artifact pins is (a) the full pipeline runs
corpus-scale end to end, and (b) a quality reference point that future
rounds must not regress (integration floors live in
tests/integration/test_nab_run.py).

    RTAP_FORCE_CPU=1 python scripts/nab_standin_report.py [--processes 1]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from rtap_tpu.utils.platform import maybe_force_cpu  # noqa: E402

maybe_force_cpu()


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--processes", type=int, default=1)
    ap.add_argument("--backend", default="tpu", choices=("tpu", "cpu"),
                    help="tpu = all files as ONE vmapped device group "
                         "(detect_files_batched; ~minutes on a real chip). "
                         "cpu = one oracle per file (hours at NAB-preset "
                         "size on a 1-core host — use --rows to shrink)")
    ap.add_argument("--rows", type=int, default=None,
                    help="truncate every file to this many rows (cheap drives)")
    ap.add_argument("--columns", type=int, default=None,
                    help="run the width-scaled NAB model "
                         "(config.scaled_nab_preset) instead of the full "
                         "2048-column preset — the model-width study's "
                         "generalization question, and the config that makes "
                         "the CPU corpus run feasible (~columns/2048 of the "
                         "full model's 10.5 s/tick)")
    ap.add_argument("--out", default=None,
                    help="default reports/nab_standin.json, or "
                         "nab_standin_cols<N>.json when --columns is set "
                         "(the full-size on-device artifact must not be "
                         "silently overwritten by a scaled run)")
    args = ap.parse_args()
    if args.out is None:
        name = (f"nab_standin_cols{args.columns}.json" if args.columns
                else "nab_standin.json")
        args.out = os.path.join(REPO, "reports", name)

    if args.backend == "tpu":
        from rtap_tpu.utils.platform import enable_compile_cache, init_backend_or_die

        init_backend_or_die()  # the tunnel oscillates; die fast
        # the NAB-preset programs are the repo's biggest compiles (65k-cell
        # TM); a tunnel window must not re-pay them on every attempt
        enable_compile_cache(REPO)

    from rtap_tpu.data.nab_corpus import NabFile, ensure_standin_corpus, load_corpus
    from rtap_tpu.nab.runner import run_corpus

    cfg = None
    if args.columns:
        from rtap_tpu.config import scaled_nab_preset

        # the runner rescales only the encoder resolution per file on top
        # of this base (nab/runner._file_range_config), same as full-size
        cfg = scaled_nab_preset(args.columns)

    with tempfile.TemporaryDirectory() as td:
        root = ensure_standin_corpus(td)
        files = load_corpus(root)
        if args.rows:
            files = [NabFile(f.name, f.timestamps[: args.rows], f.values[: args.rows],
                             f.windows) for f in files]
        t0 = time.time()
        res = run_corpus(files, cfg=cfg, backend=args.backend,
                         processes=args.processes)
        wall = time.time() - t0

    if args.backend == "tpu":
        # safe: init_backend_or_die already brought the backend up above
        import jax

        platform = jax.default_backend()
    else:
        # the oracle path is numpy-only; touching jax.default_backend()
        # here would lazily init the TPU runtime AFTER an hours-long CPU
        # run (crash risk if the chip is held; provenance mislabel if not)
        platform = "host-oracle"

    from rtap_tpu.config import nab_preset

    report = {
        "corpus": "stand-in (deterministic synthetic, NAB on-disk format)",
        "backend": args.backend,
        "platform": platform,
        "columns": (cfg if cfg is not None else nab_preset()).sp.columns,
        "files": [f.name for f in files],
        "records": int(sum(len(f.values) for f in files)),
        "wall_s": round(wall, 1),
        "scores": {
            prof: {"threshold": round(thr, 4), "score": round(score, 2)}
            for prof, (thr, score) in res.scores.items()
        },
        "note": (
            "Stand-in corpus scores are not comparable to the public NAB "
            "scoreboard; they pin the pipeline end-to-end and guard "
            "regressions. Real-corpus swap-in: set RTAP_NAB_CORPUS."
        ),
    }
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(report, f, indent=2)
    print(json.dumps(report["scores"]))


if __name__ == "__main__":
    main()
