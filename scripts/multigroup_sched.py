"""Multi-group-per-chip scheduling experiment (round-3 verdict, weak #3/#9).

The measured G-sweep says throughput per chip FALLS as one vmapped group
grows (38,956 metrics/s @ G=256 vs 29,725 @ G=8192 — SCALING.md): nothing
amortizes across streams, so a giant group only adds XLA workspace pressure.
The service story has therefore been "run many small groups" — asserted,
never measured. This script measures it: fixed TOTAL streams, split into k
equal groups, steady-state scored-metrics/s under two schedules:

- sequential: each group replays its whole span before the next starts
  (the current replay_streams shape), depth-2 pipelined within a group;
- interleaved: round-robin chunk dispatch across all k groups — every
  group keeps one chunk in flight, so the host's likelihood post-process
  for group A overlaps device compute for group B *and* the device queue
  never drains between groups.

All k groups share one compiled program (same shapes -> one jit cache
entry), so k only costs HBM state, not compile time. Output: one table +
reports/multigroup_sched.json for SCALING.md.

Usage: python scripts/multigroup_sched.py [--total 2048] [--splits 1,2,4,8]
       [--chunk-ticks 64] [--measure-chunks 4]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from rtap_tpu.utils.platform import (  # noqa: E402
    enable_compile_cache, init_backend_or_die, maybe_force_cpu,
)

maybe_force_cpu()
init_backend_or_die()
enable_compile_cache(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from rtap_tpu.config import cluster_preset  # noqa: E402
from rtap_tpu.service.registry import StreamGroup  # noqa: E402
from rtap_tpu.utils.measure import make_sine_feed  # noqa: E402


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _make_chunks(G: int, T: int, n_chunks: int, seed: int):
    """Pre-generate n_chunks of fresh (phase-continuing) values outside the
    timed window — novelty keeps the learning path honest (r3 weak #8)."""
    vals, ts, phase = make_sine_feed(G, T, key=(seed, 11))
    chunks = [(vals, ts)]
    for i in range(1, n_chunks):
        v, t, _ = make_sine_feed(G, T, key=(seed, 11 + i), t0=i * T, phase=phase)
        chunks.append((v, t))
    return chunks


def run_config(total: int, k: int, chunk_ticks: int, measure_chunks: int,
               backend: str) -> dict:
    G = total // k
    cfg = cluster_preset()
    log(f"-- {k} group(s) x G={G} (total {total}) --")
    t0 = time.perf_counter()
    groups = [
        StreamGroup(cfg, [f"s{g}_{i}" for i in range(G)], seed=g, backend=backend)
        for g in range(k)
    ]
    init_s = time.perf_counter() - t0
    # per-group chunk feeds: warmup chunk + measured chunks, distinct noise
    feeds = [_make_chunks(G, chunk_ticks, 1 + measure_chunks, seed=100 + g)
             for g in range(k)]

    # warmup: compile (shared across groups — same shapes) + 1st chunk each
    t0 = time.perf_counter()
    for g, grp in enumerate(groups):
        grp.collect_chunk(grp.dispatch_chunk(*feeds[g][0]))
    warm_s = time.perf_counter() - t0

    # sequential schedule: group-at-a-time, depth-2 within the group
    t0 = time.perf_counter()
    for g, grp in enumerate(groups):
        pending = grp.dispatch_chunk(*feeds[g][1])
        for i in range(2, 1 + measure_chunks):
            nxt = grp.dispatch_chunk(*feeds[g][i])
            grp.collect_chunk(pending)
            pending = nxt
        grp.collect_chunk(pending)
    seq_dt = time.perf_counter() - t0
    seq_rate = measure_chunks * chunk_ticks * total / seq_dt

    # fresh chunks for the interleaved pass (state has advanced; novelty again)
    feeds = [_make_chunks(G, chunk_ticks, measure_chunks, seed=500 + g)
             for g in range(k)]
    # interleaved schedule: round-robin dispatch, collect one round behind
    t0 = time.perf_counter()
    pending = [grp.dispatch_chunk(*feeds[g][0]) for g, grp in enumerate(groups)]
    for i in range(1, measure_chunks):
        nxt = [grp.dispatch_chunk(*feeds[g][i]) for g, grp in enumerate(groups)]
        for g, grp in enumerate(groups):
            grp.collect_chunk(pending[g])
        pending = nxt
    for g, grp in enumerate(groups):
        grp.collect_chunk(pending[g])
    inter_dt = time.perf_counter() - t0
    inter_rate = measure_chunks * chunk_ticks * total / inter_dt

    row = {
        "k_groups": k, "G": G, "total": total,
        "init_s": round(init_s, 2), "warmup_s": round(warm_s, 2),
        "sequential_metrics_per_s": round(seq_rate, 1),
        "interleaved_metrics_per_s": round(inter_rate, 1),
        "interleave_gain": round(inter_rate / seq_rate, 3),
    }
    log(json.dumps(row))
    del groups  # free HBM before the next configuration
    return row


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--total", type=int, default=2048)
    ap.add_argument("--splits", default="1,2,4,8")
    ap.add_argument("--chunk-ticks", type=int, default=64)
    ap.add_argument("--measure-chunks", type=int, default=4)
    ap.add_argument("--backend", default="tpu")
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "reports", "multigroup_sched.json"))
    args = ap.parse_args()

    splits = [int(s) for s in args.splits.split(",")]
    bad = [k for k in splits if args.total % k]
    if bad:
        raise SystemExit(f"--total {args.total} not divisible by splits {bad}")

    rows = [run_config(args.total, k, args.chunk_ticks, args.measure_chunks,
                       args.backend) for k in splits]
    import jax

    result = {
        "platform": jax.devices()[0].platform,
        "device": str(jax.devices()[0]),
        "total_streams": args.total,
        "chunk_ticks": args.chunk_ticks,
        "measure_chunks": args.measure_chunks,
        "rows": rows,
    }
    with open(args.out, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
