"""Live-serving soak at realistic scale (round-3 verdict, weak #7).

Round 3's live-path evidence was smoke-scale (a handful of streams, ~12
ticks at 0.1 s cadence). The round-2 ask was "zero missed deadlines at a
realistic G": this script runs the REAL operator surface —
``python -m rtap_tpu serve`` with G >= 1024 streams at 1 s cadence for >= 5
minutes, fed by an external TCP JSONL producer (the reference's
collector-push shape, SURVEY.md §3.3) — and commits the resulting stats
(missed deadlines, p50/p90/p99 tick latency, throughput, HBM occupancy) to
reports/live_soak.json.

The serve child binds an EPHEMERAL port (parsed from its own "listening"
line) so a previous attempt's orphan can never answer the readiness probe;
the feeder runs in THIS process as a real network producer, its pushed-tick
count and any death are recorded in the artifact, and a feeder that died
mid-soak fails the run (a "zero missed deadlines" line is only evidence if
data was actually flowing). Values follow the diurnal sine + noise profile
so the TM keeps learning novel input for the whole soak.

Usage: python scripts/live_soak.py [--streams 1024] [--ticks 330]
       [--cadence 1.0] [--backend tpu]
"""

from __future__ import annotations

import argparse
import json
import os
import re
import socket
import subprocess
import sys
import threading
import time


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# the feeder imports rtap_tpu in THIS process; running as `python
# scripts/live_soak.py` puts scripts/ (not the repo) at sys.path[0]
sys.path.insert(0, REPO)

from rtap_tpu.utils.platform import force_cpu_requested  # noqa: E402

FEEDER_DIED_EXIT = 5


def log(msg: str) -> None:
    print(f"[soak] {msg}", file=sys.stderr, flush=True)


class Feeder:
    """Push one record per stream per cadence over one persistent connection.

    Tracks `ticks_pushed` and records any fatal `error` instead of dying
    silently — the soak artifact must say whether data was actually flowing.
    """

    def __init__(self, port: int, ids: list[str], cadence_s: float,
                 churn_every: int = 0, binary: bool = False):
        self.port = port
        self.ids = list(ids)
        self.cadence_s = cadence_s
        # elastic churn (validates serve --auto-register/--auto-release-
        # after under deadline): every N pushed ticks, stop feeding one
        # original stream (it will be auto-released) and start feeding a
        # brand-new id (it will be auto-registered into freed capacity)
        self.churn_every = int(churn_every)
        self.churned = 0
        self.stop = threading.Event()
        self.ticks_pushed = 0
        self.error: str | None = None
        # binary: push RB1 batch frames over the persistent connection
        # (serve --ingest-port) instead of JSONL lines — one vectorized
        # frame per tick, no per-record formatting at all (the JSONL
        # feeder's ~350 ms/tick json cost at the 100k shape disappears)
        self.binary = bool(binary)
        self.thread = threading.Thread(
            target=self._run_binary if binary else self._run, daemon=True)

    def _run_binary(self) -> None:
        phase = None
        try:
            import numpy as np

            from rtap_tpu.ingest.emit import BinaryFeedConnection
            from rtap_tpu.ingest.protocol import data_frame
            from rtap_tpu.utils.measure import make_sine_feed

            conn = BinaryFeedConnection(("127.0.0.1", self.port),
                                        timeout_s=30.0)
            codes = None
            pending_names: set[str] = set()
            while not self.stop.is_set():
                t_start = time.perf_counter()
                ts = int(time.time())
                chunk, _, phase = make_sine_feed(
                    len(self.ids), 1, key=(7, 42 + self.ticks_pushed),
                    t0=self.ticks_pushed, phase=phase,
                )
                if conn.poll_map():
                    # serve pushed a fresh map (ANY membership change —
                    # e.g. an auto-release — bumps the epoch, and stale-
                    # epoch frames are refused whole): re-encode
                    codes = None
                if codes is None or len(codes) != len(self.ids):
                    codes = np.array(
                        [conn.code_of.get(s, -1) for s in self.ids],
                        np.int64)
                known = codes >= 0
                if known.any():
                    conn.send_frame(data_frame(
                        codes[known].astype(np.uint32),
                        chunk[0].astype(np.float32)[known], ts,
                        epoch=conn.epoch))
                self.ticks_pushed += 1
                if self.churn_every and \
                        self.ticks_pushed % self.churn_every == 0:
                    ci = self.churned % len(self.ids)
                    self.ids[ci] = f"churn{self.churned:04d}.m0"
                    self.churned += 1
                    pending_names.add(self.ids[ci])
                    conn.send_names(sorted(pending_names))
                    codes = None
                if pending_names:
                    # serve's membership block claims announced names at
                    # tick boundaries; refresh the map until they appear
                    # EVERY tick — each claim also bumps the map epoch,
                    # and frames stamped with the old epoch are refused
                    # (stale-code protection), so a lazy refresh here
                    # would go deaf for real streams too
                    conn.refresh_map()
                    pending_names -= set(conn.code_of)
                    codes = None
                budget = self.cadence_s - (time.perf_counter() - t_start)
                if budget > 0:
                    self.stop.wait(budget)
            conn.close()
        except (BrokenPipeError, ConnectionResetError):
            pass  # serve finished its tick budget and closed the listener
        except Exception as e:  # noqa: BLE001 — recorded, surfaced, fatal
            self.error = f"{type(e).__name__}: {e}"

    def _run(self) -> None:
        phase = None  # first chunk draws it; passed back for continuity
        prefixes = None  # per-id JSON prefixes, rebuilt when membership changes
        try:
            # inside the try: an import failure (the exact class of bug the
            # sys.path fix above addresses) must land in self.error, not
            # kill the thread silently and read as a connection drop
            import numpy as np

            from rtap_tpu.utils.measure import make_sine_feed

            sock = socket.create_connection(("127.0.0.1", self.port), timeout=5.0)
            # a paced producer should tolerate serve stalling a few ticks
            # (device hiccup) without dying; 30 s of backpressure = fatal
            sock.settimeout(30.0)
            f = sock.makefile("wb")
            while not self.stop.is_set():
                t_start = time.perf_counter()
                ts = int(time.time())
                # the same diurnal profile every other experiment feeds;
                # per-tick key = fresh noise (make_sine_feed reseeds per
                # call — the multigroup/measure chunk idiom), phase threads
                # stream continuity
                chunk, _, phase = make_sine_feed(
                    len(self.ids), 1, key=(7, 42 + self.ticks_pushed),
                    t0=self.ticks_pushed, phase=phase,
                )
                # hand-formatted JSON (parse-identical to json.dumps for
                # these plain floats/strings, spot-checked at init below):
                # at the 100k-stream soak shape json.dumps alone costs
                # ~350 ms of the 1 s cadence on the 1-core host; prefix
                # precompute + f-string is ~3.3x cheaper
                if prefixes is None or len(prefixes) != len(self.ids):
                    prefixes = [f'{{"id": "{sid}", "value": ' for sid in self.ids]
                suffix = f', "ts": {ts}}}\n'
                if np.isfinite(chunk).all():
                    lines = [p + repr(v) + suffix for p, v in
                             zip(prefixes, chunk[0].astype(float).tolist())]
                else:
                    # ADVICE r5: repr() on a non-finite float emits bare
                    # 'nan'/'inf', which json.loads rejects — the fast path
                    # is only parse-identical for finite values. json.dumps
                    # serializes the odd non-finite row as NaN/Infinity
                    # (accepted by the Python consumer path) instead of
                    # silently corrupting the record stream.
                    lines = [json.dumps({"id": sid, "value": v, "ts": ts}) + "\n"
                             for sid, v in
                             zip(self.ids, chunk[0].astype(float).tolist())]
                if self.ticks_pushed == 0:
                    rec = json.loads(lines[0])
                    assert rec == {"id": self.ids[0],
                                   "value": float(chunk[0][0]), "ts": ts}, rec
                f.write("".join(lines).encode())
                f.flush()
                self.ticks_pushed += 1
                if self.churn_every and \
                        self.ticks_pushed % self.churn_every == 0:
                    # rotate: drop the oldest still-original id, add a new
                    # one (values keep coming from the same feed column, so
                    # the signal stays realistic for the claimed model)
                    ci = self.churned % len(self.ids)
                    self.ids[ci] = f"churn{self.churned:04d}.m0"
                    prefixes[ci] = f'{{"id": "{self.ids[ci]}", "value": '
                    self.churned += 1
                budget = self.cadence_s - (time.perf_counter() - t_start)
                if budget > 0:
                    self.stop.wait(budget)
            f.close()
            sock.close()
        except (BrokenPipeError, ConnectionResetError):
            pass  # serve finished its tick budget and closed the listener
        except Exception as e:  # noqa: BLE001 — recorded, surfaced, fatal
            self.error = f"{type(e).__name__}: {e}"


def wait_for_listener(proc: subprocess.Popen, stderr_lines: list[str],
                      deadline_s: float) -> int:
    """Parse serve's own 'listening for JSONL records on host:port' stderr
    line -> bound port. Only THIS child's line is trusted (an orphan from a
    killed earlier attempt can answer a connect-probe; it cannot write to
    this process's pipe)."""
    pat = re.compile(r"listening for (?:JSONL records|binary batch frames) "
                     r"on \S+?:(\d+)")
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        for line in stderr_lines:
            m = pat.search(line)
            if m:
                return int(m.group(1))
        if proc.poll() is not None:
            sys.stderr.write("".join(stderr_lines))
            log(f"serve exited early rc={proc.returncode}")
            # propagate the child's code: the init watchdog's
            # INIT_WATCHDOG_EXIT must reach hw_watch as-is or a down
            # tunnel would be misread as a real step failure
            raise SystemExit(proc.returncode)
        time.sleep(0.25)
    raise SystemExit("serve never reported its TCP listener")


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--streams", type=int, default=1024)
    ap.add_argument("--ticks", type=int, default=330)
    ap.add_argument("--cadence", type=float, default=1.0)
    ap.add_argument("--backend", default="tpu")
    ap.add_argument("--group-size", type=int, default=1024,
                    help="passed through to serve: streams per device group "
                         "(multi-group interleaved serving when exceeded)")
    ap.add_argument("--pipeline-depth", type=int, default=1,
                    help="passed through to serve: 2 hides the per-group "
                         "device round trip behind the cadence sleep")
    ap.add_argument("--dispatch-threads", type=int, default=1,
                    help="passed through to serve: overlap the per-group "
                         "blocking dispatch RPCs (the tunnel's ~65 ms/group "
                         "serial floor that depth 2 alone cannot touch)")
    ap.add_argument("--columns", type=int, default=None,
                    help="passed through to serve: width-scaled cluster "
                         "preset (the density lever; SCALING.md)")
    ap.add_argument("--learn-every", type=int, default=1,
                    help="passed through to serve: learning cadence")
    ap.add_argument("--learn-full-until", type=int, default=None,
                    help="passed through to serve: 0 = mature-steady-state "
                         "capability semantics (the r5 soak forensics: the "
                         "default 300-tick full-rate window covered 91%% of "
                         "a 330-tick soak, masking the cadence entirely)")
    ap.add_argument("--micro-chunk", type=int, default=1,
                    help="passed through to serve: M ticks per device "
                         "dispatch (the per-program-floor amortizer)")
    ap.add_argument("--chunk-stagger", action="store_true",
                    help="passed through to serve: rotate micro-chunk "
                         "boundaries across groups (boundary-spike leveler)")
    ap.add_argument("--stagger-learn", action="store_true",
                    help="passed through to serve: stagger cadence phase "
                         "across groups (the 100k-serving load-spreading "
                         "shape)")
    ap.add_argument("--freeze", action="store_true",
                    help="passed through to serve: inference-only soak")
    ap.add_argument("--binary-ingest", action="store_true",
                    help="feed serve through the RB1 binary batch protocol "
                         "(serve --ingest-port) instead of per-record "
                         "JSONL: one vectorized frame per tick from the "
                         "feeder, zero per-record Python on either side "
                         "(ISSUE 7 wire-speed ingest; docs/INGEST.md)")
    ap.add_argument("--churn-every", type=int, default=0,
                    help="elastic-churn soak: every N feeder ticks, rotate "
                         "one stream id (old goes silent -> auto-released; "
                         "new appears -> auto-registered). Enables serve "
                         "--auto-register and --auto-release-after "
                         "(2x churn interval) automatically")
    ap.add_argument("--health", action="store_true",
                    help="arm the serve child's model-health reducers "
                         "(serve --health): fused on-device occupancy/"
                         "sparsity/score aggregates + scorecards; the "
                         "fleet gauges land in the obs snapshot this "
                         "soak reads back")
    ap.add_argument("--predict", action="store_true",
                    help="arm the serve child's predictive horizon "
                         "(serve --predict): fused predict reducer + "
                         "precursor paging; the predict fleet gauges "
                         "land in the obs snapshot this soak reads back "
                         "(docs/PREDICT.md)")
    ap.add_argument("--predict-horizon", type=int, default=None,
                    help="passed through to serve: score the forward "
                         "model k ticks ahead (implies --predict)")
    ap.add_argument("--threshold", type=float, default=None,
                    help="passed through to serve: alert threshold "
                         "(lower it to densify alert traffic when the "
                         "detect-latency sketch needs samples)")
    ap.add_argument("--latency", action="store_true",
                    help="arm the serve child's detection-latency "
                         "tracking (serve --latency): stage waterfalls, "
                         "windowed quantile sketches, lag gauges — the "
                         "latency/slo blocks land in this soak's report")
    ap.add_argument("--latency-window", type=int, default=None,
                    help="passed through to serve: sketch window ticks")
    ap.add_argument("--slo", action="append", default=None,
                    metavar="NAME=TARGET@pQ",
                    help="passed through to serve (repeatable): declare "
                         "a latency SLO, e.g. detect=2s@p99; the run's "
                         "SLO verdict is recorded in the report "
                         "(slo_verdict) and a burn dumps a postmortem "
                         "when --postmortem-dir is armed. Implies "
                         "--latency")
    ap.add_argument("--slo-fast-window", type=int, default=None,
                    help="passed through to serve: fast burn window ticks")
    ap.add_argument("--slo-slow-window", type=int, default=None,
                    help="passed through to serve: slow burn window ticks")
    ap.add_argument("--jax-trace", default=None,
                    help="passed through to serve: wrap the soak window in "
                         "jax.profiler.trace writing the XLA device trace "
                         "to this directory (the hw_session device-trace "
                         "step pairs it with the host span timeline)")
    ap.add_argument("--trace-out", default=None,
                    help="passed through to serve: write the host span "
                         "timeline as Perfetto-loadable Chrome trace JSON")
    ap.add_argument("--postmortem-dir", default=None,
                    help="passed through to serve: arm the flight "
                         "recorder (auto postmortem bundles on "
                         "quarantine/degradation/miss-burst/crash)")
    ap.add_argument("--startup-timeout", type=float, default=420.0,
                    help="budget for serve's backend init + first compile")
    ap.add_argument("--out", default=os.path.join(REPO, "reports", "live_soak.json"))
    ap.add_argument("--obs-snapshot", default=None,
                    help="telemetry snapshot JSONL the serve child writes "
                         "and this script reads back into the artifact "
                         "(default: $RTAP_OBS_SNAPSHOT, else <out>.obs.jsonl)")
    args = ap.parse_args()
    obs_snapshot = args.obs_snapshot \
        or os.environ.get("RTAP_OBS_SNAPSHOT") \
        or args.out + ".obs.jsonl"
    # fresh run, fresh telemetry: a stale snapshot line from an earlier
    # attempt must never be read back as this run's evidence
    try:
        os.remove(obs_snapshot)
    except OSError:
        pass

    ids = [f"node{i // 4:04d}.m{i % 4}" for i in range(args.streams)]
    alerts_path = os.path.join(REPO, "reports", "live_soak_alerts.jsonl")
    # @file form always: a 16k-stream comma list exceeds MAX_ARG_STRLEN
    # (observed: live_soak_16k step died "Argument list too long").
    # Per-run temp file: a fixed path would let concurrent soaks swap id
    # sets under each other mid-startup, and would leave junk in reports/
    import tempfile

    fd, ids_path = tempfile.mkstemp(prefix="live_soak_ids_", suffix=".txt")
    with os.fdopen(fd, "w") as f:
        f.write("\n".join(ids) + "\n")
    cmd = [
        sys.executable, "-m", "rtap_tpu", "serve",
        "--streams", "@" + ids_path,
        *(["--ingest-port", "0"] if args.binary_ingest
          else ["--port", "0"]),
        "--ticks", str(args.ticks),
        "--cadence", str(args.cadence),
        "--backend", args.backend,
        "--group-size", str(args.group_size),
        "--pipeline-depth", str(args.pipeline_depth),
        "--dispatch-threads", str(args.dispatch_threads),
        "--alerts", alerts_path,
        "--obs-snapshot", obs_snapshot,
    ]
    if args.columns is not None:
        cmd += ["--columns", str(args.columns)]
    if args.learn_every != 1:
        cmd += ["--learn-every", str(args.learn_every)]
    if args.stagger_learn:
        cmd += ["--stagger-learn"]
    if args.micro_chunk != 1:
        cmd += ["--micro-chunk", str(args.micro_chunk)]
    if args.learn_full_until is not None:
        cmd += ["--learn-full-until", str(args.learn_full_until)]
    if args.chunk_stagger:
        cmd += ["--chunk-stagger"]
    if args.freeze:
        cmd += ["--freeze"]
    if args.health:
        cmd += ["--health"]
    if args.predict or args.predict_horizon is not None:
        cmd += ["--predict"]
    if args.predict_horizon is not None:
        cmd += ["--predict-horizon", str(args.predict_horizon)]
    if args.threshold is not None:
        cmd += ["--threshold", str(args.threshold)]
    if args.latency or args.slo:
        cmd += ["--latency"]
    if args.latency_window is not None:
        cmd += ["--latency-window", str(args.latency_window)]
    for spec in args.slo or ():
        cmd += ["--slo", spec]
    if args.slo_fast_window is not None:
        cmd += ["--slo-fast-window", str(args.slo_fast_window)]
    if args.slo_slow_window is not None:
        cmd += ["--slo-slow-window", str(args.slo_slow_window)]
    if args.jax_trace:
        cmd += ["--jax-trace", args.jax_trace]
    if args.trace_out:
        cmd += ["--trace-out", args.trace_out]
    if args.postmortem_dir:
        cmd += ["--postmortem-dir", args.postmortem_dir]
    if args.churn_every:
        cmd += ["--auto-register",
                "--auto-release-after", str(2 * args.churn_every)]
    log(f"starting serve: G={args.streams} ticks={args.ticks} "
        f"cadence={args.cadence}s backend={args.backend}")
    proc = subprocess.Popen(cmd, cwd=REPO, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)
    stderr_lines: list[str] = []
    drain = threading.Thread(
        target=lambda: stderr_lines.extend(iter(proc.stderr.readline, "")),
        daemon=True)
    drain.start()

    feeder = None
    try:
        port = wait_for_listener(proc, stderr_lines, args.startup_timeout)
        feeder = Feeder(port, ids, args.cadence,
                        churn_every=args.churn_every,
                        binary=args.binary_ingest)
        feeder.thread.start()
        log(f"feeder attached on port {port}; soaking...")
        out = proc.stdout.read()  # EOF = serve exited; drain thread owns stderr
        proc.wait()
    finally:
        if feeder is not None:
            feeder.stop.set()
            feeder.thread.join(timeout=5)
        if proc.poll() is None:
            proc.kill()
            proc.wait()
        try:
            os.remove(ids_path)
        except OSError:
            pass
    if proc.returncode != 0:
        sys.stderr.write("".join(stderr_lines))
        log(f"serve failed rc={proc.returncode}")
        raise SystemExit(proc.returncode)  # keep INIT_WATCHDOG_EXIT intact

    stats = json.loads(out.strip().splitlines()[-1])
    # the serve child's telemetry registry, read from its snapshot file
    # rather than scraped out of stdout/stderr: the obs seam (rtap_tpu.obs)
    # is the structured surface for tick/phase/deadline accounting
    from rtap_tpu.obs import read_last_snapshot, summarize_snapshot

    snap = read_last_snapshot(obs_snapshot)
    obs_summary = summarize_snapshot(snap) if snap else None
    if obs_summary is None:
        log(f"warning: serve left no telemetry snapshot at {obs_snapshot}")
    n_alert_lines = 0
    n_event_lines = 0
    if os.path.exists(alerts_path):
        with open(alerts_path) as f:
            for line in f:
                # watchdog events share the alert stream; json.dumps puts
                # their discriminating "event" key first, so this split is
                # exact without parsing a potentially huge file
                if line.startswith('{"event"'):
                    n_event_lines += 1
                else:
                    n_alert_lines += 1
        os.remove(alerts_path)  # large; the count is the committed evidence
    result = {
        "streams": args.streams, "ticks": args.ticks, "cadence_s": args.cadence,
        "backend": args.backend, "group_size": args.group_size,
        # an honest artifact must say WHERE the group path actually ran:
        # backend="tpu" under RTAP_FORCE_CPU=1 is the JAX group kernels on
        # the CPU platform (the tunnel-down fallback), not the chip
        "forced_cpu": force_cpu_requested(),
        # model config the numbers were measured under — a width-scaled or
        # cadence-thinned soak must be distinguishable from a default one
        "columns": args.columns, "learn_every": args.learn_every,
        "stagger_learn": args.stagger_learn,
        "micro_chunk": args.micro_chunk,
        "learn_full_until": args.learn_full_until,
        "chunk_stagger": args.chunk_stagger,
        "binary_ingest": args.binary_ingest,
        "churn_every": args.churn_every, "ids_churned": feeder.churned,
        "alert_lines": n_alert_lines,
        "event_lines": n_event_lines,
        "feeder_ticks_pushed": feeder.ticks_pushed,
        "feeder_error": feeder.error, **stats,
        # the SLO verdict under a stable key (ISSUE 11): **stats already
        # carries "slo"/"latency" when armed, but harnesses key on this
        "slo_verdict": stats.get("slo"),
        "obs": obs_summary,
    }
    with open(args.out, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result))
    if feeder.error is not None:
        log(f"feeder died mid-soak: {feeder.error} — failing the run")
        return FEEDER_DIED_EXIT
    if feeder.ticks_pushed < args.ticks - 2:
        # BrokenPipe is normal at the END (serve closes after its tick
        # budget); a connection drop mid-soak leaves error=None but a tick
        # shortfall — a "zero missed deadlines" line without data flowing
        # is not evidence (2 ticks of slack: the final tick can race
        # serve's close)
        log(f"feeder pushed only {feeder.ticks_pushed}/{args.ticks} ticks "
            f"— connection dropped mid-soak; failing the run")
        return FEEDER_DIED_EXIT
    return 0


if __name__ == "__main__":
    sys.exit(main())
