"""Held-out external validation of the model-width quality claims.

Round-4's density headline (32-col >= preset quality at 1/8 state) was
measured entirely inside the world it was tuned in: one generator family
(diurnal sine + AR(1)), seed 11, magnitude 6-sigma, 3 detectable kinds
(r4 verdict, "what's weak" #1). This script evaluates the width ladder on
the HELD-OUT family (data/synthetic.py `family="heldout"`: Student-t
bursty noise, per-stream trend, unlabeled benign regime switches) across
multiple seeds, a 2-6-sigma magnitude sweep, and ALL FIVE fault kinds —
a world no config was tuned on.

Protocol per cell: run_fault_eval's 120 x 1500 sweep (threshold x
debounce, episode precision), production streaming likelihood, the same
machinery behind reports/fault_eval.json. Aggregation: mean best-f1 over
seeds per (variant, magnitude), then the verdict table preset-vs-32col.

    RTAP_FORCE_CPU=1 python scripts/heldout_eval.py --streams 40 \
        --seeds 11 --magnitudes 6          # cheap CPU drive
    python scripts/heldout_eval.py         # full study (device, ~45 min)
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from rtap_tpu.utils.platform import maybe_force_cpu  # noqa: E402

maybe_force_cpu()

VARIANTS = {
    "preset_256col": (256, 1),
    "preset_256col_k2": (256, 2),
    "half_128col": (128, 1),
    "quarter_64col": (64, 1),
    "eighth_32col": (32, 1),
    "eighth_32col_k2": (32, 2),  # the throughput-headline config
    "eighth_32col_k4": (32, 4),  # the 100k-live cadence candidate
    "eighth_32col_k3": (32, 3),  # the better-quality 100k operating point
}


def _cfg(columns: int, learn_every: int):
    from rtap_tpu.config import cluster_preset, scaled_cluster_preset

    cfg = cluster_preset() if columns == 256 else scaled_cluster_preset(columns)
    if learn_every > 1:
        cfg = cfg.with_learn_every(learn_every)
    return cfg


def log(msg: str) -> None:
    print(f"[heldout] {msg}", file=sys.stderr, flush=True)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--streams", type=int, default=120)
    ap.add_argument("--length", type=int, default=1500)
    ap.add_argument("--seeds", default="11,23,47")
    ap.add_argument("--magnitudes", default="2,4,6")
    ap.add_argument("--variants", default=None,
                    help=f"subset of {sorted(VARIANTS)} (default: all)")
    ap.add_argument("--backend", default="tpu")
    ap.add_argument("--out", default=os.path.join(REPO, "reports",
                                                  "heldout_eval.json"))
    args = ap.parse_args()

    from rtap_tpu.data.synthetic import ANOMALY_KINDS
    from rtap_tpu.eval.fault_eval import run_fault_eval

    seeds = [int(x) for x in args.seeds.split(",")]
    mags = [float(x) for x in args.magnitudes.split(",")]
    picked = args.variants.split(",") if args.variants else list(VARIANTS)
    bad = set(picked) - set(VARIANTS)
    if bad:
        raise SystemExit(f"unknown variants {sorted(bad)}; have {sorted(VARIANTS)}")

    cells: dict[str, dict] = {}
    if os.path.exists(args.out):  # merge: a re-run measures only what's missing
        with open(args.out) as f:
            cells = json.load(f).get("cells", {})

    t_start = time.time()
    for name in picked:
        cols, k = VARIANTS[name]
        for mag in mags:
            for seed in seeds:
                key = f"{name}|mag{mag:g}|seed{seed}"
                if key in cells:
                    continue
                t0 = time.time()
                rep = run_fault_eval(
                    n_streams=args.streams, length=args.length,
                    kinds=ANOMALY_KINDS, magnitude=mag, cfg=_cfg(cols, k),
                    backend=args.backend, seed=seed, family="heldout",
                )
                d = dataclasses.asdict(rep)
                cells[key] = {
                    "f1": d["at_best"]["f1"],
                    "recall": d["at_best"]["recall"],
                    "precision": d["at_best"]["precision"],
                    "best_threshold": d["best_threshold"],
                    "best_debounce": d["best_debounce"],
                    "per_kind_recall": {kk: v["recall"]
                                        for kk, v in d["per_kind"].items()},
                }
                log(f"{key}: f1={cells[key]['f1']:.3f} "
                    f"({time.time() - t0:.0f}s)")
                _write(args, cells, t_start)  # incremental: survive kills
    _write(args, cells, t_start, final=True)
    return 0


def _summarize(cells: dict) -> dict:
    """Aggregate mean f1 over seeds per (variant, magnitude) + the verdict."""
    agg: dict[str, dict[str, list[float]]] = {}
    for key, cell in cells.items():
        name, mag, _ = key.split("|")
        agg.setdefault(name, {}).setdefault(mag, []).append(cell["f1"])
    table = {
        name: {mag: round(sum(v) / len(v), 4) for mag, v in mags.items()}
        for name, mags in agg.items()
    }
    means = {
        name: round(sum(sum(v) / len(v) for v in mags.values()) / len(mags), 4)
        for name, mags in agg.items()
    }
    verdict = None
    if "preset_256col" in means and "eighth_32col" in means:
        verdict = {
            "preset_mean_f1": means["preset_256col"],
            "col32_mean_f1": means["eighth_32col"],
            "col32_holds": means["eighth_32col"] >= means["preset_256col"] - 0.01,
        }
    return {"mean_f1_by_magnitude": table, "mean_f1": means, "verdict": verdict}


def _write(args, cells: dict, t_start: float, final: bool = False) -> None:
    out = {
        "protocol": (f"{args.streams} x {args.length}, family=heldout, all 5 "
                     f"kinds, seeds={args.seeds}, magnitudes={args.magnitudes}, "
                     "streaming likelihood, threshold x debounce sweep"),
        "backend": args.backend,
        "cells": cells,
        **_summarize(cells),
        "wall_s": round(time.time() - t_start, 1),
    }
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    tmp = args.out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(out, f, indent=2)
    os.replace(tmp, args.out)
    if final:
        print(json.dumps({"mean_f1": out["mean_f1"], "verdict": out["verdict"]}))


if __name__ == "__main__":
    sys.exit(main())
