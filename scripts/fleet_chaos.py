"""Fleet-wide chaos drill: failover as a routine operation (ISSUE 20).

The fleet-scale generalization of failover_soak.py: TWO shards, each an
HA pair (leader + hot standby) holding its fencing epoch through a
shared CONTROL-PLANE process (``serve --control-only``,
rtap_tpu/fleet/control.py) instead of a lease file, all under one
fleet observability aggregator. A seeded schedule then drills every
failure class in one run:

- SIGKILL the CURRENT leader of each shard (>= 2 leader kills);
- SIGKILL a hot STANDBY (the plane must see DOWN -> rejoined; the
  leader's tick stream must not care);
- SIGKILL the CONTROL PLANE and restart it from its write-ahead epoch
  journal: during the outage every data plane keeps ticking on its
  cached lease (degraded ticks counted, ZERO stalled ticks), and the
  restarted plane recovers epochs exactly (never re-granting one);
- a SIGSTOP/SIGCONT zombie-fence round (the woken old leader must exit
  FENCED_RC, its in-flight alerts fence-dropped);
- one rolling-upgrade DRAIN: ``control_drain`` marks the shard, the
  leader exits orderly (releasing the lease, BYE reason=drain), the
  standby takes over immediately, the old leader rejoins as standby.

Verdict: per shard, the spliced alert stream and final model state must
be EXACTLY-ONCE and BIT-IDENTICAL to a fault-free reference over the
same seeded feed; every scheduled takeover must be visible through the
FLEET PLANE (old leader DOWN -> role_changed on the successor, judged
by scripts/fleet_verdict.py) at epochs equal to the control journal's
ground truth; control-journal grant epochs must be strictly monotonic
per shard across the control-plane kill; takeover detection must land
inside the tick budget. Exit 0 verified / 5 verification failed /
3 infra failed.

Usage:
  python scripts/fleet_chaos.py --seed 20 --out reports/fleetchaos_r20.json
"""

from __future__ import annotations

import argparse
import json
import os
import random
import signal
import socket
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from rtap_tpu.utils.platform import maybe_force_cpu  # noqa: E402
from scripts.fleet_verdict import (  # noqa: E402
    final_tick_check,
    member_counter,
    promotion_epoch_truth,
    takeover_sequence,
)

VERIFY_FAILED_EXIT = 5
INFRA_FAILED_EXIT = 3

SHARDS = 2  # one drill, two shards: enough to prove per-shard isolation


def log(msg: str) -> None:
    print(f"[fleetchaos] {msg}", file=sys.stderr, flush=True)


# ---------------------------------------------------------------- child
def run_child(args) -> int:
    """One data-plane process lifetime on one shard: join the control
    plane, decide role through its lease, follow until promoted or
    stopped, then serve the remaining budget — journaled, checkpointed,
    replicated to the shard peer, fenced by the CONTROL lease. A drain
    mark arriving over the heartbeat exits orderly (release + BYE
    reason=drain). ``--ref`` runs the plain single-process reference for
    the shard's feed instead (no lease, no control plane)."""
    maybe_force_cpu()

    import threading

    import numpy as np

    from rtap_tpu.config import cluster_preset
    from rtap_tpu.fleet.control import ControlLease
    from rtap_tpu.resilience import (
        FENCED_RC,
        ReplicationSender,
        StandbyFollower,
        TickJournal,
    )
    from rtap_tpu.service.checkpoint import peek_resume_ticks
    from rtap_tpu.service.loop import live_loop
    from rtap_tpu.service.registry import StreamGroupRegistry

    # warm orbax BEFORE the lease (see failover_soak.run_child): its
    # first import can hold the GIL long enough to starve a heartbeat
    import orbax.checkpoint  # noqa: F401

    w = args.workdir
    os.makedirs(w, exist_ok=True)
    alerts = os.path.join(w, "alerts.jsonl")
    ckdir = os.path.join(w, "ck")
    jdir = os.path.join(w, "journal" if args.ref
                        else f"journal-{args.name}")
    journal = TickJournal(jdir)

    ids = [f"n{i // 3}.m{i % 3}" for i in range(args.streams)]
    reg = StreamGroupRegistry(cluster_preset(), group_size=args.group_size,
                              backend=args.backend,
                              threshold=args.threshold, debounce=1)
    for sid in ids:
        reg.add_stream(sid)
    reg.finalize()

    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())

    lease = None
    resume_sup = None
    promote_info = None
    fleet_pub = None
    if not args.ref and args.fleet_port:
        from rtap_tpu.fleet import FleetPublisher

        fleet_pub = FleetPublisher(
            ("127.0.0.1", args.fleet_port), args.name, role="standby",
            shard=args.shard,
            push_interval_s=max(0.02, args.cadence / 2))
    if not args.ref:
        # the tentpole wiring: this shard's fencing epoch lives in the
        # control plane; the loop/follower/heartbeat cannot tell this
        # lease from the file one (FencingLease contract)
        lease = ControlLease(
            ("127.0.0.1", args.control_port), owner=args.name,
            shard=args.shard, timeout_s=args.lease_timeout,
            degraded_grace_s=args.control_grace)
        lease.on_drain = stop.set
        lease.hello("member")
        cur = lease.read()
        fresh_other = (cur is not None and cur.get("owner") != args.name
                       and not lease._stale(cur))
        if args.follow or fresh_other or not lease.try_acquire():
            if fleet_pub is not None:
                fleet_pub.start()
            follower = StandbyFollower(
                reg, journal, lease=lease, port=args.listen,
                alert_path=alerts, checkpoint_dir=ckdir,
                cadence_s=args.cadence, stop_event=stop)
            log(f"{args.name}: standby following shard {args.shard} "
                f"on :{args.listen}")
            outcome = follower.run()
            if outcome == "stopped":
                journal.close()
                if fleet_pub is not None:
                    fleet_pub.close()
                return 0
            resume_sup = follower.resume_suppression
            promote_info = {
                "detect_s": round(follower.promote_detect_s, 3),
                "epoch": lease.epoch,
                "re_emitted": follower.promote_re_emitted,
                "suppressed": follower.promote_suppressed,
            }
            log(f"{args.name}: PROMOTED shard {args.shard} at epoch "
                f"{lease.epoch} (detect {follower.promote_detect_s:.3f}s)")
        lease.start_heartbeat()
        if fleet_pub is not None:
            fleet_pub.set_role("leader", lease_epoch=lease.epoch)
            fleet_pub.start()

    base = max(journal.next_tick, peek_resume_ticks(ckdir))
    n_eff = max(0, args.ticks - base)
    if fleet_pub is not None:
        fleet_pub.set_tick_base(base)

    sender = None
    if not args.ref:
        sender = ReplicationSender(("127.0.0.1", args.peer), journal,
                                   checkpoint_dir=ckdir).start()
        journal.tee = sender.tee
        journal.compact_floor = sender.compact_floor

    def source(k: int):
        g = base + k  # the feed depends only on (shard, GLOBAL tick)
        rng = np.random.Generator(np.random.Philox(
            key=(args.seed + args.shard, g)))
        v = (30 + 5 * rng.random(len(ids))).astype(np.float32)
        if args.spike_every and g % args.spike_every == 0:
            v[(g // args.spike_every) % len(ids)] += 30.0
        return v, 1_700_000_000 + g

    stats = live_loop(
        source, reg, n_ticks=n_eff, cadence_s=args.cadence,
        alert_path=alerts, checkpoint_dir=ckdir,
        checkpoint_every=args.checkpoint_every, journal=journal,
        lease=lease, stop_event=stop, resume_suppression=resume_sup,
        fleet=fleet_pub)
    if sender is not None:
        sender.close()
        journal.tee = None
    drained = bool(lease is not None and lease.draining
                   and not stats.get("fenced"))
    if lease is not None:
        # order matters on the drain exit: stop the heartbeat FIRST so
        # it cannot observe its own release as a lost lease
        lease.stop_heartbeat()
        if drained:
            lease.release()
            log(f"{args.name}: shard {args.shard} drained — lease "
                "released, the standby takes over")
    journal.close()
    if fleet_pub is not None:
        fleet_pub.close(reason="drain" if drained else None)
    line = {"name": "ref" if args.ref else args.name,
            "shard": args.shard, "base": base,
            "ran": stats["ticks"], "alerts": stats["alerts"],
            "fenced": bool(stats.get("fenced")),
            "fenced_line_drops": stats.get("fenced_line_drops", 0),
            "drained": drained,
            "control_degraded_ticks":
                stats.get("control_degraded_ticks", 0),
            "promoted": promote_info}
    if args.stats_out:
        with open(args.stats_out, "a") as f:
            f.write(json.dumps(line) + "\n")
            f.flush()
    print(json.dumps(line))
    if stats.get("fenced"):
        return FENCED_RC
    return 0


# --------------------------------------------------------------- parent
def _free_ports(n: int) -> list[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _wait(cond, timeout_s: float, poll_s: float = 0.02) -> bool:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(poll_s)
    return False


def child_cmd(args, workdir: str, shard: int, name: str | None = None,
              listen: int = 0, peer: int = 0, control_port: int = 0,
              ref: bool = False, follow: bool = False) -> list[str]:
    cmd = [sys.executable, os.path.abspath(__file__), "--child",
           "--workdir", workdir, "--seed", str(args.seed),
           "--shard", str(shard),
           "--ticks", str(args.ticks), "--streams", str(args.streams),
           "--group-size", str(args.group_size),
           "--cadence", str(args.cadence),
           "--checkpoint-every", str(args.checkpoint_every),
           "--backend", args.backend, "--threshold", str(args.threshold),
           "--lease-timeout", str(args.lease_timeout),
           "--control-grace", str(args.control_grace),
           "--spike-every", str(args.spike_every),
           "--stats-out", os.path.join(workdir, "stats.jsonl")]
    if ref:
        cmd.append("--ref")
    else:
        cmd += ["--name", name, "--listen", str(listen),
                "--peer", str(peer), "--control-port", str(control_port)]
        if follow:
            cmd.append("--follow")
        if getattr(args, "fleet_port", 0):
            cmd += ["--fleet-port", str(args.fleet_port)]
    return cmd


def control_cmd(port: int, journal_dir: str, lease_timeout: float) \
        -> list[str]:
    """The control plane runs through the REAL serve CLI — the drill
    covers the operator surface, not just the library."""
    return [sys.executable, "-m", "rtap_tpu", "serve",
            "--control-listen", str(port),
            "--control-journal", journal_dir,
            "--lease-timeout", str(lease_timeout),
            "--control-only"]


def spawn_control(args, port: int, journal_dir: str) -> subprocess.Popen:
    p = subprocess.Popen(control_cmd(port, journal_dir,
                                     args.lease_timeout),
                         stdout=subprocess.DEVNULL,
                         stderr=subprocess.DEVNULL, cwd=REPO)
    return p


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--streams", type=int, default=6)
    ap.add_argument("--group-size", type=int, default=3)
    ap.add_argument("--ticks", type=int, default=160,
                    help="TOTAL tick budget PER SHARD across takeovers")
    ap.add_argument("--cadence", type=float, default=0.12)
    ap.add_argument("--checkpoint-every", type=int, default=7)
    ap.add_argument("--backend", default="cpu")
    ap.add_argument("--threshold", type=float, default=-1e9,
                    help="floor default = every scored tick is an alert "
                         "line, the densest exactly-once check")
    ap.add_argument("--lease-timeout", type=float, default=None,
                    help="default 4 * cadence (failover_soak's takeover "
                         "detection budget math)")
    ap.add_argument("--takeover-budget", type=int, default=10,
                    help="max takeover detection latency in ticks")
    ap.add_argument("--outage", type=float, default=None,
                    help="control-plane kill-to-restart window in "
                         "seconds (default 5 * lease timeout: several "
                         "staleness horizons of proven degraded "
                         "serving)")
    ap.add_argument("--control-grace", type=float, default=None,
                    help="data planes' bounded cached-lease window "
                         "(default: max(30s, 10 * outage) — the drill "
                         "outage must end well inside it)")
    ap.add_argument("--spike-every", type=int, default=13)
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--out", default=None, help="report JSON path")
    # child-mode flags
    ap.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--ref", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--follow", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--name", default="A", help=argparse.SUPPRESS)
    ap.add_argument("--shard", type=int, default=0, help=argparse.SUPPRESS)
    ap.add_argument("--listen", type=int, default=0, help=argparse.SUPPRESS)
    ap.add_argument("--peer", type=int, default=0, help=argparse.SUPPRESS)
    ap.add_argument("--control-port", type=int, default=0,
                    help=argparse.SUPPRESS)
    ap.add_argument("--fleet-port", type=int, default=0,
                    help=argparse.SUPPRESS)
    ap.add_argument("--stats-out", default=None, help=argparse.SUPPRESS)
    args = ap.parse_args()
    if args.lease_timeout is None:
        args.lease_timeout = 4 * args.cadence
    if args.outage is None:
        args.outage = 5 * args.lease_timeout
    if args.control_grace is None:
        args.control_grace = max(30.0, 10.0 * args.outage)
    if args.child:
        return run_child(args)

    from rtap_tpu.fleet import FleetAggregator
    from rtap_tpu.fleet.control import control_drain, control_read, \
        read_control_journal
    from rtap_tpu.resilience import FENCED_RC, last_journal_tick
    from scripts.crash_soak import compare_states, parse_alert_stream

    workdir = args.workdir or tempfile.mkdtemp(prefix="fleet_chaos_")
    control_dir = os.path.join(workdir, "control")
    os.makedirs(control_dir, exist_ok=True)
    shard_dirs = [os.path.join(workdir, f"shard{i}")
                  for i in range(SHARDS)]
    ref_dirs = [os.path.join(workdir, f"ref{i}") for i in range(SHARDS)]
    for d in shard_dirs + ref_dirs:
        os.makedirs(d, exist_ok=True)
    t_all = time.monotonic()
    failures: list[str] = []

    # 1. fault-free per-shard references over the identical feeds
    for i in range(SHARDS):
        log(f"reference run shard {i} ({args.ticks} ticks, "
            f"{args.streams} streams)")
        rc = subprocess.run(
            child_cmd(args, ref_dirs[i], shard=i, ref=True)).returncode
        if rc != 0:
            log(f"FATAL: reference run shard {i} failed rc={rc}")
            return INFRA_FAILED_EXIT

    # 2. the control plane (REAL serve CLI) + the fleet aggregator
    (control_port,) = _free_ports(1)
    caddr = ("127.0.0.1", control_port)
    control = spawn_control(args, control_port, control_dir)
    if not _wait(lambda: control_read(caddr, -1, timeout_s=0.5)
                 is not None, 120.0, poll_s=0.1):
        log("FATAL: control plane never answered")
        control.kill()
        return INFRA_FAILED_EXIT
    log(f"control plane on :{control_port} (journal {control_dir})")
    agg = FleetAggregator(
        port=0,
        sweep_interval_s=max(0.02, min(0.2, args.cadence))).start()
    args.fleet_port = agg.port
    log(f"fleet aggregator on :{agg.port}")

    # 3. two HA pairs: per shard, A first (acquires through the control
    # plane), then B (standby)
    ports = {(i, n): p
             for (i, n), p in zip([(i, n) for i in range(SHARDS)
                                   for n in "AB"],
                                  _free_ports(2 * SHARDS))}
    procs: dict[str, subprocess.Popen] = {}

    def member(shard: int, n: str) -> str:
        return f"s{shard}{n}"

    def spawn(shard: int, n: str, follow: bool = True) -> subprocess.Popen:
        other = "B" if n == "A" else "A"
        return subprocess.Popen(child_cmd(
            args, shard_dirs[shard], shard=shard, name=member(shard, n),
            listen=ports[(shard, n)], peer=ports[(shard, other)],
            control_port=control_port, follow=follow))

    def shard_owner(shard: int) -> str | None:
        p = control_read(caddr, shard, timeout_s=0.5)
        cur = (p or {}).get("cur")
        return cur.get("owner") if cur else None

    for i in range(SHARDS):
        procs[member(i, "A")] = spawn(i, "A", follow=False)
        if not _wait(lambda: shard_owner(i) == member(i, "A"), 120.0):
            log(f"FATAL: {member(i, 'A')} never acquired shard {i}")
            return INFRA_FAILED_EXIT
        procs[member(i, "B")] = spawn(i, "B")
    unscheduled_fences: list[str] = []

    def reap() -> str | None:
        """Unscheduled FENCED_RC exits are legitimate lease behavior
        under host jitter (see failover_soak.reap): respawn as standby
        and carry on. Any other unexpected death is fatal."""
        for nm, pp in list(procs.items()):
            rc = pp.poll()
            if rc is None or rc == 0:
                continue
            if rc == FENCED_RC:
                unscheduled_fences.append(nm)
                log(f"{nm} fenced by an unscheduled takeover — "
                    "respawning as standby")
                procs[nm] = spawn(int(nm[1]), nm[2])
            else:
                return f"child {nm} died unexpectedly rc={rc}"
        return None

    def shard_tick(shard: int, name: str) -> int:
        return last_journal_tick(
            os.path.join(shard_dirs[shard], f"journal-{name}"))

    def leader_reached(shard: int, target: int) -> str | None:
        name = shard_owner(shard)
        if name not in procs:
            return None
        if shard_tick(shard, name) >= target:
            return name
        return None

    def await_leader(shard: int, target: int, what: str) -> str | None:
        """Block until the shard's CURRENT leader has journaled tick
        >= target (the journal-observed kill discipline). Returns its
        member name, or None with a failure recorded."""
        hit: dict = {}

        def reached():
            err = reap()
            if err is not None:
                hit["dead"] = err
                return True
            name = leader_reached(shard, target)
            if name is not None:
                hit["name"] = name
            return name is not None

        if not _wait(reached, 240.0):
            failures.append(f"{what} missed target tick {target} on "
                            f"shard {shard} "
                            f"(owner={shard_owner(shard)})")
            return None
        if "dead" in hit:
            failures.append(hit["dead"])
            return None
        return hit["name"]

    def kill_leader(shard: int, target: int) -> dict | None:
        name = await_leader(shard, target, "leader kill")
        if name is None:
            return None
        p = procs[name]
        t_kill = time.monotonic()
        p.kill()
        p.wait()
        log(f"killed shard-{shard} leader {name} near tick {target}")
        if not _wait(lambda: shard_owner(shard) not in (None, name),
                     120.0):
            failures.append(
                f"standby never promoted on shard {shard} after "
                f"killing {name} at tick {target}")
            return None
        obs = {"shard": shard, "target": target, "killed": name,
               "new_leader": shard_owner(shard),
               "takeover_wall_s": round(time.monotonic() - t_kill, 3)}
        procs[name] = spawn(shard, name[2])  # rejoin as standby
        return obs

    # 4. the seeded drill schedule (targets on each shard's own journal
    # axis; jitter from a seeded rng so runs differ by seed, but every
    # phase keeps its order — the phases ARE the coverage)
    rng = random.Random(args.seed)

    def jitter(base_frac: float) -> int:
        t = int(args.ticks * base_frac) + rng.randrange(5)
        return min(args.ticks - 12, max(1, t))

    targets = {
        "kill0": jitter(0.12), "kill1": jitter(0.20),
        "standby_kill": jitter(0.30), "outage": jitter(0.40),
        "fence": jitter(0.62), "drain": jitter(0.80),
    }
    log(f"drill schedule (per-shard ticks): {targets}; outage "
        f"{args.outage:.2f}s; grace {args.control_grace:.1f}s")

    observed: list[dict] = []
    fence_report: dict | None = None
    drain_report: dict | None = None
    outage_report: dict | None = None

    # 4a. leader kills, one per shard
    obs = kill_leader(0, targets["kill0"])
    if obs:
        observed.append(obs)
    obs = None if failures else kill_leader(1, targets["kill1"])
    if obs:
        observed.append(obs)

    # 4b. standby kill on shard 0: the plane must see it; the leader
    # must not (its journal keeps advancing without a takeover)
    standby_kill: dict | None = None
    if not failures:
        name = await_leader(0, targets["standby_kill"], "standby kill")
        if name is not None:
            sb = member(0, "B" if name.endswith("A") else "A")
            before = shard_tick(0, name)
            epoch_before = (((control_read(caddr, 0) or {}).get("cur")
                             or {}).get("epoch"))
            procs[sb].kill()
            procs[sb].wait()
            log(f"killed shard-0 standby {sb} near tick "
                f"{targets['standby_kill']}")
            if not _wait(lambda: shard_tick(0, name) >= before + 4,
                         120.0):
                failures.append("shard-0 leader stalled after its "
                                "standby was killed")
            epoch_after = (((control_read(caddr, 0) or {}).get("cur")
                            or {}).get("epoch"))
            if epoch_after != epoch_before:
                failures.append(
                    f"standby kill moved shard-0 epoch "
                    f"{epoch_before} -> {epoch_after} (a takeover "
                    "happened; the leader should not have cared)")
            standby_kill = {"killed": sb, "leader": name,
                            "epoch": epoch_after}
            procs[sb] = spawn(0, sb[2])  # rejoin as standby

    # 4c. control-plane kill + journal-recovery restart: both shards
    # must keep ticking on cached leases (ZERO stalled ticks), the
    # restarted plane must recover every epoch, and no leader may fence
    if not failures:
        name0 = await_leader(0, targets["outage"], "control outage")
        name1 = shard_owner(1)
        if name0 is not None and name1 is not None:
            epochs_before = {
                i: ((control_read(caddr, i) or {}).get("cur")
                    or {}).get("epoch")
                for i in range(SHARDS)}
            control.kill()
            control.wait()
            t0 = time.monotonic()
            ticks_at_kill = {0: shard_tick(0, name0),
                             1: shard_tick(1, name1)}
            log(f"killed the CONTROL PLANE (outage {args.outage:.2f}s; "
                f"shard ticks at kill {ticks_at_kill})")
            time.sleep(args.outage)
            ticks_at_restart = {0: shard_tick(0, name0),
                                1: shard_tick(1, name1)}
            # the availability bar: a control-plane outage degrades,
            # never stalls — each shard's leader kept journaling
            min_advance = max(2, int(args.outage / args.cadence) // 4)
            for i in range(SHARDS):
                adv = ticks_at_restart[i] - ticks_at_kill[i]
                if adv < min_advance:
                    failures.append(
                        f"shard {i} STALLED during the control outage: "
                        f"advanced {adv} tick(s) in {args.outage:.2f}s "
                        f"(want >= {min_advance})")
            err = reap()
            if err is not None:
                failures.append(f"during control outage: {err}")
            control = spawn_control(args, control_port, control_dir)
            if not _wait(lambda: control_read(caddr, -1, timeout_s=0.5)
                         is not None, 120.0, poll_s=0.1):
                failures.append("restarted control plane never "
                                "answered")
            else:
                # recovery contract: same owners, same epochs — the
                # restart must not have fenced a healthy leader
                def _settled():
                    return all(shard_owner(i) == (name0, name1)[i]
                               for i in range(SHARDS))

                settled = _wait(_settled, 60.0, poll_s=0.1)
                epochs_after = {
                    i: ((control_read(caddr, i) or {}).get("cur")
                        or {}).get("epoch")
                    for i in range(SHARDS)}
                if not settled or epochs_after != epochs_before:
                    failures.append(
                        f"control restart changed lease state: owners "
                        f"settled={settled}, epochs {epochs_before} -> "
                        f"{epochs_after}")
                # sample the MERGED degraded counter NOW, while the
                # outage-era leaders still own their member rows: a
                # later same-name respawn overwrites the snap with a
                # fresh process's zeroed counters (latest-push-wins)
                degraded_fleet = sum(
                    member_counter(
                        s, "rtap_obs_control_degraded_ticks_total") or 0
                    for s in agg.member_snaps().values())
                outage_report = {
                    "outage_s": round(time.monotonic() - t0, 3),
                    "ticks_at_kill": ticks_at_kill,
                    "ticks_at_restart": ticks_at_restart,
                    "epochs": epochs_before,
                    "degraded_ticks_fleet": degraded_fleet,
                    "leaders_survived": settled}
                log(f"control plane restarted: {outage_report}")

    # 4d. zombie-fence round on shard 1: SIGSTOP the leader, let the
    # standby take over through the control plane, SIGCONT the zombie —
    # it must exit FENCED_RC
    if not failures:
        name = await_leader(1, targets["fence"], "fence round")
        if name is not None:
            p = procs[name]
            os.kill(p.pid, signal.SIGSTOP)
            log(f"SIGSTOPped shard-1 leader {name} near tick "
                f"{targets['fence']}")
            promoted = _wait(
                lambda: shard_owner(1) not in (None, name), 120.0)
            os.kill(p.pid, signal.SIGCONT)
            if not promoted:
                failures.append("standby never promoted during the "
                                "fence round")
            else:
                try:
                    rc = p.wait(timeout=120.0)
                except subprocess.TimeoutExpired:
                    p.kill()
                    rc = p.wait()
                    failures.append(
                        f"paused old leader {name} never exited after "
                        "SIGCONT (fence did not bite)")
                fence_report = {"paused": name, "rc": rc,
                                "new_leader": shard_owner(1)}
                if rc != FENCED_RC:
                    failures.append(
                        f"woken old leader {name} exited rc={rc}, "
                        f"expected FENCED_RC={FENCED_RC}")
                procs[name] = spawn(1, name[2])

    # 4e. rolling-upgrade drain on shard 0: mark it draining at the
    # control plane; the leader exits ORDERLY (rc 0, lease released,
    # BYE reason=drain), the standby takes over immediately, the old
    # leader rejoins as standby
    if not failures:
        name = await_leader(0, targets["drain"], "drain round")
        if name is not None:
            control_drain(caddr, 0)
            log(f"drain marked on shard 0 (leader {name})")
            p = procs[name]
            try:
                rc = p.wait(timeout=120.0)
            except subprocess.TimeoutExpired:
                p.kill()
                rc = p.wait()
            if rc != 0:
                failures.append(f"draining leader {name} exited "
                                f"rc={rc}, expected an orderly 0")
            if not _wait(lambda: shard_owner(0) not in (None, name),
                         120.0):
                failures.append("standby never took over the drained "
                                "shard")
            drain_report = {"drained": name, "rc": rc,
                            "new_leader": shard_owner(0)}
            procs[name] = spawn(0, name[2])  # rejoin as standby

    # 5. completion: each shard's leader finishes its budget (exit 0
    # with the journal at ticks-1); then stop the standbys
    done: dict[int, str] = {}

    def budget_done():
        err = reap()
        if err is not None:
            done["err"] = err
            return True
        for i in range(SHARDS):
            if i in done:
                continue
            for n in "AB":
                nm = member(i, n)
                if shard_tick(i, nm) >= args.ticks - 1 \
                        and procs[nm].poll() == 0:
                    done[i] = nm
        return all(i in done for i in range(SHARDS))

    if not _wait(budget_done, 600.0, poll_s=0.05):
        failures.append(f"shards never completed the budget "
                        f"(done={done})")
    if "err" in done:
        failures.append(str(done.pop("err")))
    for nm, p in procs.items():
        if p.poll() is None:
            p.terminate()
            try:
                p.wait(timeout=60.0)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
                failures.append(f"standby {nm} ignored SIGTERM")

    # 6. verdict — ground truth first: per-shard exactly-once alerts +
    # bit-identical state vs the fault-free references
    shards_verdict: list[dict] = []
    promotions_all: list[dict] = []
    degraded_events = 0
    for i in range(SHARDS):
        ref_alerts = parse_alert_stream(
            os.path.join(ref_dirs[i], "alerts.jsonl"))
        got_alerts = parse_alert_stream(
            os.path.join(shard_dirs[i], "alerts.jsonl"))
        if got_alerts["dup"]:
            failures.append(f"shard {i}: {len(got_alerts['dup'])} "
                            f"DUPLICATED alert_id(s): "
                            f"{got_alerts['dup'][:5]}")
        ref_ids = set(ref_alerts["alerts"])
        got_ids = set(got_alerts["alerts"])
        lost = sorted(ref_ids - got_ids)
        extra = sorted(got_ids - ref_ids)
        if lost:
            failures.append(f"shard {i}: {len(lost)} LOST alert_id(s): "
                            f"{lost[:5]}")
        if extra:
            failures.append(f"shard {i}: {len(extra)} EXTRA "
                            f"alert_id(s): {extra[:5]}")
        mismatched = [a for a in (ref_ids & got_ids)
                      if ref_alerts["alerts"][a] != got_alerts["alerts"][a]]
        if mismatched:
            failures.append(f"shard {i}: {len(mismatched)} alert "
                            f"record(s) differ: {mismatched[:5]}")
        if not ref_ids:
            failures.append(f"shard {i}: reference emitted zero alerts "
                            "— the drill proves nothing")
        leaves = compare_states(os.path.join(ref_dirs[i], "ck"),
                                os.path.join(shard_dirs[i], "ck"),
                                failures)
        promos = [e for e in got_alerts["events"]
                  if e.get("event") == "standby_promoted"]
        promotions_all.extend(promos)
        degraded_events += sum(
            1 for e in got_alerts["events"]
            if e.get("event") in ("control_plane_lost",
                                  "control_plane_regained"))
        shards_verdict.append({
            "shard": i, "alert_ids": len(ref_ids),
            "duplicated": len(got_alerts["dup"]), "lost": len(lost),
            "extra": len(extra), "garbage_lines": got_alerts["garbage"],
            "state_leaves_compared": leaves,
            "promotions": [
                {k: e.get(k) for k in ("tick", "epoch", "detect_s",
                                       "detect_ticks")}
                for e in promos]})

    # takeover budget, anchored to the SCHEDULED faults
    budget_anchors = [(k["target"], f"kill shard {k['shard']}")
                      for k in observed]
    if fence_report:
        budget_anchors.append((targets["fence"], "fence"))
    for target, kind in budget_anchors:
        cand = [p for p in promotions_all
                if p.get("detect_ticks") is not None
                and abs(p["tick"] - target) <= args.takeover_budget + 6]
        if not cand:
            failures.append(f"no standby_promoted event near the "
                            f"{kind} at tick {target}")
            continue
        p = min(cand, key=lambda q: abs(q["tick"] - target))
        if p["detect_ticks"] > args.takeover_budget:
            failures.append(
                f"takeover at tick {p['tick']} ({kind} at {target}) "
                f"detected in {p['detect_ticks']} ticks — over the "
                f"{args.takeover_budget}-tick budget")

    # 7. control-journal ground truth: grant epochs STRICTLY monotonic
    # per shard across the control-plane kill (the never-re-invert bar)
    journal_recs = read_control_journal(control_dir)
    grants: dict[int, list[int]] = {}
    for rec in journal_recs:
        if rec.get("kind") == "grant":
            grants.setdefault(int(rec["shard"]), []).append(
                int(rec["epoch"]))
    for i in range(SHARDS):
        eps = grants.get(i, [])
        if len(eps) < 3:
            failures.append(f"shard {i}: only {len(eps)} journaled "
                            "grant(s) — the drill's takeovers are not "
                            "in the epoch journal")
        if any(b <= a for a, b in zip(eps, eps[1:])):
            failures.append(f"shard {i}: journaled grant epochs not "
                            f"strictly monotonic: {eps} — the restart "
                            "re-inverted a fence")

    # 8. the fleet plane's story, judged with the shared helpers
    members = agg.members_view()
    events = agg.events_view()
    anchors = [(k["killed"], k["new_leader"], "kill") for k in observed]
    if fence_report:
        anchors.append((fence_report["paused"],
                        fence_report["new_leader"], "fence"))
    checks = takeover_sequence(events, anchors, failures)
    fleet_epochs = promotion_epoch_truth(events, promotions_all,
                                         failures)
    final_tick = final_tick_check(members, args.ticks - 1, failures)
    # the drain is an OPERATION on the plane: BYE reason=drain ("left",
    # never DOWN), then role_changed on the successor
    if drain_report:
        drained_nm = drain_report["drained"]
        left = next((e for e in events if e["event"] == "left"
                     and e["member"] == drained_nm
                     and e.get("reason") == "drain"), None)
        if left is None:
            failures.append(f"drained leader {drained_nm} never sent "
                            "BYE reason=drain to the fleet plane")
        if any(e["event"] == "down" and e["member"] == drained_nm
               and e["t_unix"] >= (left or {}).get("t_unix", 0)
               for e in events):
            failures.append(f"drained leader {drained_nm} was marked "
                            "DOWN — a drain must read as an operation")
    # the standby kill is VISIBLE: its member went down and rejoined
    if standby_kill:
        sb_ev = [e for e in events
                 if e["member"] == standby_kill["killed"]]
        if not any(e["event"] == "down" for e in sb_ev):
            failures.append(f"fleet plane never marked the killed "
                            f"standby {standby_kill['killed']} DOWN")
        if not any(e["event"] == "rejoined" for e in sb_ev):
            failures.append(f"killed standby {standby_kill['killed']} "
                            "never rejoined on the plane")
    # degraded serving is COUNTED: the merged fleet counter (sampled
    # while the outage-era leaders still owned their member rows) must
    # show the outage window, the per-process stats lines must agree,
    # and the lost/regained event pair must be on the incident stream
    degraded_total = (outage_report or {}).get("degraded_ticks_fleet", 0)
    stats_degraded = 0
    for i in range(SHARDS):
        try:
            with open(os.path.join(shard_dirs[i], "stats.jsonl")) as f:
                for ln in f:
                    try:
                        stats_degraded += int(json.loads(ln).get(
                            "control_degraded_ticks") or 0)
                    except (ValueError, TypeError):
                        pass
        except OSError:
            pass
    if outage_report and degraded_total <= 0:
        failures.append("control outage ran but the fleet plane never "
                        "showed a degraded tick "
                        "(rtap_obs_control_degraded_ticks_total)")
    if outage_report and stats_degraded <= 0:
        failures.append("control outage ran but no child's stats line "
                        "counted a degraded tick")
    if outage_report and degraded_events <= 0:
        failures.append("control outage ran but no "
                        "control_plane_lost/regained event reached an "
                        "incident stream")

    fleetobs = {
        "members": [{k: m.get(k) for k in ("member", "state", "role",
                                           "shard", "lease_epoch",
                                           "tick", "snapshots",
                                           "left_reason")}
                    for m in members],
        "sequence": checks,
        "promotion_epochs": fleet_epochs,
        "final_tick": final_tick,
        "degraded_ticks_total": degraded_total,
        "events_total": len(events),
    }
    with open(os.path.join(workdir, "fleet_snapshot.json"), "w") as f:
        json.dump(agg.snapshot(), f, indent=2)
    agg.close()
    control.terminate()
    try:
        control.wait(timeout=30.0)
    except subprocess.TimeoutExpired:
        control.kill()
        control.wait()

    report = {
        "seed": args.seed,
        "shards": SHARDS,
        "ticks_per_shard": args.ticks,
        "cadence_s": args.cadence,
        "lease_timeout_s": args.lease_timeout,
        "takeover_budget_ticks": args.takeover_budget,
        "schedule": targets,
        "leader_kills": observed,
        "standby_kill": standby_kill,
        "control_outage": outage_report,
        "fence_round": fence_report,
        "drain_round": drain_report,
        "completed_by": {str(k): v for k, v in done.items()},
        "unscheduled_fences": unscheduled_fences,
        "shards_verdict": shards_verdict,
        "control_journal": {
            "records": len(journal_recs),
            "grants_per_shard": {str(s): e
                                 for s, e in sorted(grants.items())}},
        "degraded_ticks_total": degraded_total,
        "degraded_ticks_stats": stats_degraded,
        "degraded_events": degraded_events,
        "fleetobs": fleetobs,
        "wall_s": round(time.monotonic() - t_all, 1),
        "verified": not failures,
        "failures": failures,
        "workdir": workdir,
    }
    if args.out:
        os.makedirs(os.path.dirname(os.path.abspath(args.out)),
                    exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2)
    print(json.dumps(report))
    if failures:
        log(f"VERIFY FAILED ({len(failures)}):")
        for msg in failures:
            log(f"  - {msg}")
        return VERIFY_FAILED_EXIT
    log(f"VERIFIED: {len(observed)} leader kill(s), 1 standby kill, "
        f"1 control-plane kill, 1 fence round, 1 drain; "
        f"{degraded_total} degraded tick(s), exactly-once on "
        f"{SHARDS} shard(s), epochs monotonic")
    return 0


if __name__ == "__main__":
    sys.exit(main())
