"""Render a fleet observability report: member roster, merged rollups.

One renderer for every surface the fleet plane exports (ISSUE 19):

- ``--url BASE``      — a live obs server with an aggregator attached:
  GET ``BASE/fleet/snapshot`` (serve --fleet-listen PORT --obs-port);
- ``--snapshot FILE`` — a fleet snapshot JSON as written by the soak
  harnesses (``ha/fleet_snapshot.json``, ``crash/fleet_snapshot.json``)
  or saved from ``GET /fleet/snapshot``;
- ``--report FILE``   — a soak report JSON whose ``fleetobs`` block
  (failover_soak / crash_soak) becomes the report body: the
  fleet-observed takeover/restart story next to its reconciliation.

Prints ONE JSON line to stdout (the artifact contract shared with the
benches) and a human-readable member table + fleet rollup to stderr.
``--out FILE`` also writes the report as indented JSON (the
committed-artifact form).

Usage:
  python scripts/fleet_report.py --url http://127.0.0.1:9100
  python scripts/fleet_report.py --snapshot /tmp/soak/ha/fleet_snapshot.json
  python scripts/fleet_report.py --report reports/fleetobs_r19.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _from_url(base: str) -> dict:
    import urllib.request

    with urllib.request.urlopen(
            base.rstrip("/") + "/fleet/snapshot", timeout=10) as r:
        snap = json.loads(r.read())
    return {"source": base, "fleet": snap}


def _from_snapshot(path: str) -> dict:
    with open(path) as f:
        snap = json.load(f)
    if "members" not in snap:
        raise SystemExit(f"{path} is not a fleet snapshot (no members "
                         "roster) — expected agg.snapshot() JSON")
    return {"source": os.path.abspath(path), "fleet": snap}


def _from_report(path: str) -> dict:
    with open(path) as f:
        rep = json.load(f)
    fo = rep.get("fleetobs")
    if not fo:
        raise SystemExit(
            f"{path} carries no fleetobs block — was the soak run with "
            "the fleet plane enabled (--fleet)?")
    return {"source": os.path.abspath(path), "fleetobs": fo,
            "verified": rep.get("verified"),
            "failures": rep.get("failures", [])}


def _fmt_s(v) -> str:
    if v is None:
        return "-"
    v = float(v)
    return f"{v * 1e3:.2f}ms" if v < 1.0 else f"{v:.3f}s"


def _member_rows(members: list[dict]) -> list[str]:
    lines = [f"Members ({len(members)}):",
             f"  {'member':<16} {'state':<6} {'role':<10} "
             f"{'epoch':>5} {'tick':>8} {'pushes':>7} {'age':>8}"]
    for m in members:
        age = m.get("last_push_age_s")
        state = str(m.get("state"))
        if m.get("left_reason"):
            # a reasoned departure (drain) is an operation, not an
            # outage — show it inline so the roster reads correctly
            state = f"{state}({m['left_reason']})"
        lines.append(
            f"  {str(m.get('member')):<16} {state:<6} "
            f"{str(m.get('role')):<10} "
            f"{m.get('lease_epoch') if m.get('lease_epoch') is not None else '-':>5} "
            f"{m.get('tick') if m.get('tick') is not None else '-':>8} "
            f"{m.get('snapshots', 0):>7} "
            f"{_fmt_s(age) if age is not None else '-':>8}")
    return lines


def _rollup_rows(snap: dict) -> list[str]:
    """The fleet rollup: summed counters, merged SLO, merged latency,
    worst-of health, incident totals (docs/FLEET.md merge semantics)."""
    lines: list[str] = []
    counters = (snap.get("metrics") or {}).get("counters") or []
    if counters:
        lines.append("Fleet counters (summed across members):")
        for c in counters:
            lbl = ",".join(f"{k}={v}"
                           for k, v in sorted((c.get("labels")
                                               or {}).items()))
            name = c["name"] + (f"{{{lbl}}}" if lbl else "")
            lines.append(f"  {name:<52} {c['value']:>12g} "
                         f"({c['members']} member(s))")
    slo = snap.get("slo")
    if slo and slo.get("slos"):
        lines.append(
            f"Fleet SLO verdict (merged sketches): "
            f"{'MET' if slo.get('met') else 'MISSED'}")
        for v in slo["slos"]:
            status = ("n/a" if v["met"] is None
                      else "met" if v["met"] else "MISS")
            lines.append(
                f"  {v['slo']:<22} {status:<4} "
                f"observed {_fmt_s(v.get('observed_quantile_s')):>10} "
                f"bad {v['bad']}/{v['samples']} "
                f"members {','.join(v.get('members', []))}")
    lat = snap.get("latency")
    if lat and lat.get("stages"):
        lines.append("Fleet stage quantiles (merged sketches):")
        for name, sk in sorted(lat["stages"].items()):
            q = sk.get("total") or {}
            lines.append(
                f"  {name:<10} p50 {_fmt_s(q.get('p50')):>10} "
                f"p95 {_fmt_s(q.get('p95')):>10} "
                f"p99 {_fmt_s(q.get('p99')):>10} n={q.get('count', 0)}")
    health = snap.get("health")
    if health and health.get("verdict") is not None:
        lines.append(f"Fleet health (worst-of): {health['verdict']} "
                     f"({health.get('groups_total', 0)} group(s) across "
                     f"{len(health.get('members') or {})} member(s))")
    inc = snap.get("incidents")
    if inc and inc.get("members"):
        lines.append(
            f"Incidents: {inc.get('open_windows_total', 0)} open "
            f"window(s), {inc.get('incidents_emitted_total', 0)} "
            f"emitted fleet-wide")
    events = snap.get("events") or []
    if events:
        lines.append(f"Last events ({len(events)} total):")
        for e in events[-8:]:
            extra = ""
            if e["event"] == "role_changed":
                extra = (f" {e.get('old_role')}->{e.get('role')} "
                         f"epoch {e.get('lease_epoch')}")
            elif e["event"] == "down":
                extra = f" after {_fmt_s(e.get('last_push_age_s'))}"
            elif e["event"] == "left" and e.get("reason"):
                extra = f" reason={e['reason']}"
            elif e["event"] == "rejoined" and "supervised" in e:
                extra = (" supervised-restart"
                         if e["supervised"] else " cold")
                if e.get("restarts_total") is not None:
                    extra += f" restarts={e['restarts_total']}"
            lines.append(f"  {e['event']:<12} {e['member']}{extra}")
    return lines


def _fleetobs_rows(rep: dict) -> list[str]:
    """A soak's fleet-observed story: the takeover/restart sequence the
    plane saw, judged against the lease/journal truth."""
    fo = rep["fleetobs"]
    lines = []
    if rep.get("verified") is not None:
        lines.append(f"Soak verdict: "
                     f"{'VERIFIED' if rep['verified'] else 'FAILED'}")
        for msg in rep.get("failures", []):
            lines.append(f"  FAIL: {msg}")
    lines.extend(_member_rows(fo.get("members") or []))
    for c in fo.get("sequence") or []:
        status = "ok" if c.get("ok") else f"FAIL ({c.get('why')})"
        lines.append(f"  {c['kind']:<6} {c['down']} DOWN -> "
                     f"{c['promoted']} promoted "
                     f"(epoch {c.get('lease_epoch')}): {status}")
    if "death_downs" in fo:
        lines.append(f"  restarts: {fo.get('rejoins')} rejoin(s), "
                     f"{fo['death_downs']} death DOWN(s), "
                     f"{fo.get('stall_flaps', 0)} stall flap(s), "
                     f"resume bases {fo.get('restart_bases')}")
    if fo.get("promotion_epochs"):
        lines.append(f"  promotion epochs (fleet-observed): "
                     f"{fo['promotion_epochs']}")
    lines.append(f"  final tick through the plane: {fo.get('final_tick')}")
    rec = fo.get("counters_reconciled")
    if rec:
        lines.append(f"  counters reconciled: {json.dumps(rec)}")
    return lines


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--url", help="live obs server base URL "
                                   "(GET /fleet/snapshot)")
    src.add_argument("--snapshot", help="fleet snapshot JSON (soak "
                                        "artifact or saved route body)")
    src.add_argument("--report", help="soak report JSON with a fleetobs "
                                      "block")
    ap.add_argument("--out", default=None,
                    help="also write the report as indented JSON "
                         "(the committed-artifact form)")
    ap.add_argument("--expect-down", type=int, default=0, metavar="N",
                    help="tolerate up to N members in state=down before "
                         "exiting 4 — for reports captured mid-drill "
                         "where a planned outage is still in flight "
                         "(default 0: any DOWN member is a failure)")
    args = ap.parse_args()
    if args.expect_down < 0:
        ap.error("--expect-down must be >= 0")

    if args.url:
        rep = _from_url(args.url)
    elif args.snapshot:
        rep = _from_snapshot(args.snapshot)
    else:
        rep = _from_report(args.report)

    if "fleet" in rep:
        lines = _member_rows(rep["fleet"].get("members") or [])
        lines += _rollup_rows(rep["fleet"])
    else:
        lines = _fleetobs_rows(rep)
    for line in lines:
        print(line, file=sys.stderr)
    if args.out:
        os.makedirs(os.path.dirname(os.path.abspath(args.out)),
                    exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(rep, f, indent=2)
    print(json.dumps(rep))
    fl = rep.get("fleet") or {}
    # exit contract: DOWN means an UNPLANNED outage. A member that left
    # with reason=drain (rolling upgrade) is an operation — never a
    # failure — and --expect-down N tolerates in-flight planned kills.
    down = [m for m in (fl.get("members") or [])
            if m.get("state") == "down"
            and m.get("left_reason") != "drain"]
    if rep.get("verified") is False or len(down) > args.expect_down:
        return 4
    return 0


if __name__ == "__main__":
    sys.exit(main())
