"""100k-stream sharded execution proof on a virtual 8-device mesh.

SURVEY.md config 5 / round-2 verdict task 2: demonstrate the NORTH-STAR
stream count actually executing through the production sharded path
(`sharded_chunk_step`, explicit shard_map SPMD, zero collectives) — on this
host via `--xla_force_host_platform_device_count`, since real multi-chip
hardware is not reachable from this environment. This validates shapes,
sharding layouts, HBM-scale state construction (~54 GiB at u16), and the
donation path at full scale; per-chip throughput comes from bench.py on
real silicon.

    python scripts/virtual_mesh_run.py [--streams 100000] [--devices 8]
                                       [--ticks 2] [--perm-bits 16]

Prints one JSON line with wall times and per-stream bytes.
"""

from __future__ import annotations

import argparse
import json
import os
import resource
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--streams", type=int, default=100_000)
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--ticks", type=int, default=2)
    ap.add_argument("--chunks", type=int, default=2)
    ap.add_argument("--perm-bits", type=int, default=16, choices=(0, 8, 16))
    args = ap.parse_args()

    from rtap_tpu.utils.platform import enable_compile_cache, force_virtual_devices

    force_virtual_devices(args.devices)
    enable_compile_cache(REPO)
    import jax

    import numpy as np

    from rtap_tpu.config import cluster_preset
    from rtap_tpu.models.state import init_state, state_nbytes
    from rtap_tpu.ops.step import sharded_chunk_step
    from rtap_tpu.parallel import make_stream_mesh
    from rtap_tpu.parallel.sharding import broadcast_group_state
    from rtap_tpu.utils.measure import make_sine_feed

    cfg = cluster_preset(perm_bits=args.perm_bits)
    G, T = args.streams, args.ticks
    per = state_nbytes(cfg)["total"]
    print(f"state: {per} B/stream x {G} = {per * G / 1024**3:.1f} GiB",
          file=sys.stderr, flush=True)

    mesh = make_stream_mesh(args.devices)
    t0 = time.perf_counter()
    state = broadcast_group_state(init_state(cfg, seed=0), G, mesh)
    jax.block_until_ready(state["syn_perm"])
    t_init = time.perf_counter() - t0
    print(f"state build+shard: {t_init:.1f}s", file=sys.stderr, flush=True)

    from jax.sharding import NamedSharding, PartitionSpec as P

    phase = None
    walls = []
    for c in range(args.chunks):
        vals, ts, phase = make_sine_feed(G, T, key=(13, 1), t0=c * T, phase=phase)
        vals_d = jax.device_put(vals[..., None], NamedSharding(mesh, P(None, "streams", None)))
        ts_d = jax.device_put(ts.astype(np.int32), NamedSharding(mesh, P(None, "streams")))
        t0 = time.perf_counter()
        state, raw = sharded_chunk_step(state, vals_d, ts_d, cfg, mesh)
        raw = np.asarray(jax.device_get(raw))
        walls.append(time.perf_counter() - t0)
        assert raw.shape == (T, G) and np.isfinite(raw).all()
        print(f"chunk {c}: {walls[-1]:.1f}s ({T * G / walls[-1]:.0f} metrics/s on "
              f"this CPU host)", file=sys.stderr, flush=True)

    peak_rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024**2
    print(json.dumps({
        "streams": G, "devices": args.devices, "ticks_per_chunk": T,
        "perm_bits": args.perm_bits, "bytes_per_stream": per,
        "state_gib": round(per * G / 1024**3, 2),
        "state_build_s": round(t_init, 1),
        "chunk_walls_s": [round(w, 1) for w in walls],
        "peak_rss_gib": round(peak_rss, 1),
        "note": "virtual CPU mesh: validates sharded execution at scale, "
                "not per-chip throughput (bench.py measures that)",
    }), flush=True)


if __name__ == "__main__":
    main()
