"""Render a detection-latency report: waterfall, quantiles, SLO verdict.

One renderer for every surface the latency layer exports (ISSUE 11):

- ``--report FILE``   — a serve/soak stats JSON whose ``latency`` /
  ``slo`` blocks (live_loop's ``stats["latency"]``/``stats["slo"]``,
  embedded verbatim by the soak harnesses) become the report body;
- ``--url BASE``      — a live obs server: GET ``BASE/latency`` and
  ``BASE/slo`` (404s tolerated — report what is armed);
- ``--snapshot FILE`` — an obs snapshot JSONL: the registry's
  ``rtap_obs_latency_*`` / ``rtap_obs_slo_*`` gauges, last line wins.

Prints ONE JSON line to stdout (the artifact contract shared with the
benches) and a human-readable waterfall/SLO table to stderr.
``--obs-bench-log FILE`` merges bench.py --obs-bench's gate lines into
the output's ``obs_bench`` block — how reports/latency_r11.json carries
its overhead evidence next to its quantiles. ``--out FILE`` also writes
the merged report as indented JSON (the committed-artifact form).

Usage:
  python scripts/latency_report.py --report reports/live_soak.json
  python scripts/latency_report.py --url http://127.0.0.1:9100
  python scripts/latency_report.py --snapshot soak.obs.jsonl
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _from_report(path: str) -> dict:
    with open(path) as f:
        rep = json.load(f)
    out = {"source": os.path.abspath(path)}
    for key in ("latency", "slo", "slo_verdict"):
        if key in rep and rep[key] is not None:
            out["slo" if key == "slo_verdict" else key] = rep[key]
    if "latency" not in out and "slo" not in out:
        raise SystemExit(
            f"{path} carries no latency/slo block — was the run armed "
            "with --latency/--slo?")
    return out


def _from_url(base: str) -> dict:
    import urllib.error
    import urllib.request

    out: dict = {"source": base}
    for route, key in (("/latency", "latency"), ("/slo", "slo")):
        try:
            with urllib.request.urlopen(base.rstrip("/") + route,
                                        timeout=10) as r:
                out[key] = json.loads(r.read())
        except urllib.error.HTTPError as e:
            if e.code != 404:  # 404 = not armed; anything else is real
                raise
    if "latency" not in out and "slo" not in out:
        raise SystemExit(f"{base}: neither /latency nor /slo is armed")
    return out


def _from_snapshot(path: str) -> dict:
    from rtap_tpu.obs import read_last_snapshot, summarize_snapshot

    snap = read_last_snapshot(path)
    if snap is None:
        raise SystemExit(f"no parseable snapshot line in {path}")
    summary = summarize_snapshot(snap)
    # prefixes built by concatenation so the metric-catalog drift gate
    # (which scans string literals) doesn't read them as registrations
    pfx = "rtap_obs_"
    wanted = (pfx + "latency", pfx + "slo", pfx + "last_tick_unixtime")
    picked = {k: v for k, v in summary.items() if k.startswith(wanted)}
    if not picked:
        raise SystemExit(
            f"{path} carries no rtap_obs_latency_*/rtap_obs_slo_* "
            "metrics — was the run armed with --latency/--slo?")
    return {"source": os.path.abspath(path), "registry": picked}


def _fmt_s(v) -> str:
    if v is None:
        return "-"
    v = float(v)
    return f"{v * 1e3:.2f}ms" if v < 1.0 else f"{v:.3f}s"


def render_human(rep: dict) -> list[str]:
    """The stderr triage table (docs/SLO.md triage order: verdict ->
    burn -> waterfall stage)."""
    lines = []
    slo = rep.get("slo")
    if slo:
        lines.append(f"SLO verdict: {'MET' if slo.get('met') else 'MISSED'}")
        for v in slo.get("slos", []):
            # met=None is NO DATA (zero observations) — render it as
            # such, never as a violation (the slo.py verdict contract)
            status = ("n/a" if v["met"] is None
                      else "met" if v["met"] else "MISS")
            lines.append(
                f"  {v['slo']:<22} {status:<4} "
                f"observed {_fmt_s(v.get('observed_quantile_s')):>10} "
                f"bad {v['bad']}/{v['samples']} "
                f"budget_left {v['budget_remaining']:+.2f} "
                f"burns {v['burn_events']}")
    lat = rep.get("latency")
    if lat:
        stages = dict(lat.get("stages") or {})
        det = lat.get("detect")
        if det is not None:
            stages = {**stages, "detect": det}
        lines.append(f"Stage quantiles ({lat.get('ticks', '?')} ticks, "
                     f"{lat.get('detect_samples', 0)} detect samples):")
        for name, sk in stages.items():
            q = sk.get("total", sk) if isinstance(sk, dict) else {}
            lines.append(
                f"  {name:<10} p50 {_fmt_s(q.get('p50')):>10} "
                f"p95 {_fmt_s(q.get('p95')):>10} "
                f"p99 {_fmt_s(q.get('p99')):>10} "
                f"p99.9 {_fmt_s(q.get('p99.9')):>10} "
                f"n={q.get('count', 0)}")
        wf = lat.get("waterfall")
        if wf:
            lines.append(f"Last waterfall (tick {wf.get('tick')}):")
            for k in ("arrival_lag_s", "backfill_hold_s", "ingest_lag_s",
                      "dispatch_s", "collect_s", "emit_s", "tick_s"):
                if wf.get(k) is not None:
                    lines.append(f"  {k:<16} {_fmt_s(wf[k])}")
            for k, v in (wf.get("lags") or {}).items():
                lines.append(f"  lag:{k:<12} {v}")
    return lines


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--report", help="serve/soak stats JSON with "
                                      "latency/slo blocks")
    src.add_argument("--url", help="live obs server base URL "
                                   "(GET /latency + /slo)")
    src.add_argument("--snapshot", help="obs snapshot JSONL (registry "
                                        "gauges; last line wins)")
    ap.add_argument("--obs-bench-log", default=None,
                    help="bench.py --obs-bench output to merge (one JSON "
                         "line per gate) — the overhead evidence block")
    ap.add_argument("--out", default=None,
                    help="also write the merged report as indented JSON "
                         "(the committed-artifact form)")
    args = ap.parse_args()

    if args.report:
        rep = _from_report(args.report)
    elif args.url:
        rep = _from_url(args.url)
    else:
        rep = _from_snapshot(args.snapshot)

    if args.obs_bench_log:
        gates = []
        with open(args.obs_bench_log) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    gates.append(json.loads(line))
                except ValueError:
                    continue
        rep["obs_bench"] = {
            "gates": gates,
            "all_pass": bool(gates) and all(
                g.get("pass_1pct_budget") for g in gates),
        }

    for line in render_human(rep):
        print(line, file=sys.stderr)
    if args.out:
        os.makedirs(os.path.dirname(os.path.abspath(args.out)),
                    exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(rep, f, indent=2)
    print(json.dumps(rep))
    slo = rep.get("slo")
    return 0 if slo is None or slo.get("met", True) else 4


if __name__ == "__main__":
    sys.exit(main())
