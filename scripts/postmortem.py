"""Pretty-print (and machine-check) a flight-recorder postmortem bundle.

A bundle is the atomic directory `serve --postmortem-dir` dumps on group
quarantine, degradation-level change, missed-tick burst, crash, or on
demand (rtap_tpu/obs/flight.py; docs/POSTMORTEM.md is the triage
runbook). This script renders the human view: what triggered the dump,
the timeline summary (window, per-phase cost, slowest spans), and the
event ledger in tick order. `--json` emits the machine view instead
(validate_bundle verdict + summary), and the exit code is the verdict
(0 valid, 2 invalid) either way, so harnesses can gate on it.

Usage: python scripts/postmortem.py BUNDLE_DIR [--json]
       [--slowest N] [--events N]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

INVALID_EXIT = 2


def err(msg: str) -> None:
    print(f"[postmortem] {msg}", file=sys.stderr, flush=True)


def _load_json(path: str):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _spans(bundle: str) -> list[dict]:
    tj = _load_json(os.path.join(bundle, "trace.json")) or {}
    return [e for e in tj.get("traceEvents", []) if e.get("ph") == "X"]


def _events(bundle: str) -> list[dict]:
    out = []
    try:
        with open(os.path.join(bundle, "events.jsonl")) as f:
            for line in f:
                if line.strip():
                    try:
                        out.append(json.loads(line))
                    except ValueError:
                        pass
    except OSError:
        pass
    return out


def render(bundle: str, summary: dict, slowest: int, n_events: int) -> str:
    lines = []
    t = summary.get("ticks", {})
    lines.append(f"postmortem bundle: {os.path.basename(bundle)}")
    lines.append(f"  reason   : {summary.get('reason')} at tick "
                 f"{summary.get('tick')}")
    lines.append(f"  window   : ticks {t.get('first')}..{t.get('last')} "
                 f"({t.get('count')} recorded, {t.get('missed')} missed "
                 "deadlines)")
    tm = summary.get("tick_ms")
    if tm:
        lines.append(f"  tick     : mean {tm['mean']} ms, max {tm['max']} ms")
    pm = summary.get("phase_ms") or {}
    if pm:
        lines.append("  phases   : " + ", ".join(
            f"{p} mean {v['mean']}/max {v['max']} ms"
            for p, v in sorted(pm.items(), key=lambda kv: -kv[1]["mean"])))
    tr = summary.get("trace")
    if tr:
        lines.append(f"  trace    : {tr['records']} records "
                     f"({tr['dropped']} dropped) — load trace.json in "
                     "ui.perfetto.dev")
    health = summary.get("health")
    if isinstance(health, dict) and health.get("fleet"):
        # the embedded model-health scorecard (ISSUE 6): triage gets the
        # model's state at the incident, not just the timing story
        fl = health["fleet"]
        lines.append(
            f"  health   : {fl.get('verdict', '?')} — pool occ max "
            f"{fl.get('pool_occupancy_max')}, hit rate "
            f"{fl.get('hit_rate')}, drift max "
            f"{fl.get('score_drift_max')}"
            + (f", attention: groups {fl['groups_attention']}"
               if fl.get("groups_attention") else ""))
        for g in health.get("groups", []):
            if g.get("verdict", "ok") == "ok":
                continue
            sc = g.get("score", {})
            lines.append(
                f"    group {g.get('group')}: {g['verdict']} "
                f"(occ {g.get('occupancy', {}).get('frac')}, act "
                f"{g.get('sparsity', {}).get('active_col_frac')}, "
                f"drift {sc.get('drift_tvd')})")
        lines.append("    full scorecards: scripts/health_report.py "
                     f"{os.path.basename(bundle)}")
    spans = _spans(bundle)
    if spans:
        top = sorted(spans, key=lambda e: -e.get("dur", 0))[:slowest]
        lines.append(f"  slowest {len(top)} spans:")
        for e in top:
            a = e.get("args", {})
            where = f"group{a['group']}" if "group" in a else "loop"
            lines.append(f"    {e.get('dur', 0) / 1e3:9.2f} ms  "
                         f"{e.get('name'):<14} tick {a.get('tick')} "
                         f"({where})")
    events = _events(bundle)
    by_kind = summary.get("events", {}).get("by_kind", {})
    if by_kind:
        lines.append("  events   : " + ", ".join(
            f"{k}x{v}" for k, v in by_kind.items()))
    if events:
        lines.append(f"  event ledger (last {min(n_events, len(events))}):")
        for e in events[-n_events:]:
            rest = {k: v for k, v in e.items() if k not in ("event", "tick")}
            line = (f"    tick {e.get('tick', '?')!s:>6}  "
                    f"{e.get('event'):<24}")
            if rest:
                line += " " + json.dumps(rest)
            lines.append(line)
    return "\n".join(lines)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("bundle", help="postmortem bundle directory")
    ap.add_argument("--json", action="store_true",
                    help="machine view: validation verdict + summary JSON")
    ap.add_argument("--slowest", type=int, default=8,
                    help="how many slowest spans to show")
    ap.add_argument("--events", type=int, default=20,
                    help="how many trailing event lines to show")
    args = ap.parse_args()

    from rtap_tpu.obs import validate_bundle

    verdict = validate_bundle(args.bundle)
    summary = _load_json(os.path.join(args.bundle, "summary.json")) or {}
    if args.json:
        print(json.dumps({"verdict": verdict, "summary": summary}))
    else:
        print(render(args.bundle, summary, args.slowest, args.events),
              file=sys.stdout if verdict["ok"] else sys.stderr)
        if not verdict["ok"]:
            err(f"INVALID bundle: {verdict['problems']}")
    return 0 if verdict["ok"] else INVALID_EXIT


if __name__ == "__main__":
    raise SystemExit(main())
