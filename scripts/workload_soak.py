"""Workload soak: seeded cascading fault -> exactly ONE incident, kill-9 safe.

ISSUE 9 acceptance surface (cpu — no silicon needed). A deterministic
multi-service cluster (data/synthetic.generate_topology_workload) takes
one seeded cascading burst: a chosen service's nodes spike one after
another (cascade lag ticks apart) across all their metrics while every
other service stays healthy. The serve child flies with the full
durability stack (journal + periodic checkpoints) AND topology-aware
incident correlation armed. The run FAILS (exit 5) unless:

- the fault-free reference run emits EXACTLY ONE cluster-level incident,
  covering >= --min-streams member streams, whose blast-radius node set
  is exactly the faulted service's nodes, and every member alert_id
  references an alert actually on the stream;
- the crash run (a seeded killer SIGKILLs the supervised child K times,
  at least once DURING the incident's open window — the hard case: the
  correlator's state dies mid-fold and must rebuild from the sink tail)
  produces an incident stream IDENTICAL to the reference's (same
  incident ids, same member sets, same blast radii — exactly-once
  across journal replay);
- the alert stream is exactly-once (crash_soak's machinery) and the
  final model state is bit-identical to the reference run's.

In-tree smoke: tests/integration/test_workloads_serve.py runs K=1 at a
tiny config. Usage:

    python scripts/workload_soak.py --seed 0 --kills 2 [--ticks 220]
        [--services 3] [--nodes-per-service 3] [--cadence 0.02]
        [--checkpoint-every 15] [--out reports/workload_soak.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from rtap_tpu.utils.platform import maybe_force_cpu  # noqa: E402

VERIFY_FAILED_EXIT = 5
INFRA_FAILED_EXIT = 3

#: likelihood shape every soak child shares: short probation so a
#: few-hundred-tick run has a mature post-probation burst window
SOAK_LEARNING_PERIOD = 60
SOAK_ESTIMATION = 30


def log(msg: str) -> None:
    print(f"[workload] {msg}", file=sys.stderr, flush=True)


def build_workload(args):
    from rtap_tpu.data.synthetic import (
        SyntheticStreamConfig,
        generate_topology_workload,
    )

    scfg = SyntheticStreamConfig(length=args.ticks, n_anomalies=0,
                                 noise_phi=0.9, noise_scale=0.3)
    return generate_topology_workload(
        n_services=args.services,
        nodes_per_service=args.nodes_per_service,
        cfg=scfg, seed=args.seed, burst_at_frac=args.burst_at_frac,
        cascade_lag=args.cascade_lag, burst_dur=args.burst_dur,
        burst_magnitude=args.burst_magnitude)


# ---------------------------------------------------------------- child
def run_child(args) -> int:
    """One serve-process lifetime over the seeded workload feed, with
    journal + checkpoints + incident correlation armed (crash_soak's
    child shape — killed children leave their trail behind)."""
    maybe_force_cpu()

    import dataclasses

    import numpy as np

    from rtap_tpu.config import cluster_preset, composite_preset
    from rtap_tpu.correlate import IncidentCorrelator, TopologyMap
    from rtap_tpu.resilience import TickJournal
    from rtap_tpu.service.checkpoint import peek_resume_ticks
    from rtap_tpu.service.loop import live_loop
    from rtap_tpu.service.registry import StreamGroupRegistry

    w = args.workdir
    os.makedirs(w, exist_ok=True)
    journal = TickJournal(os.path.join(w, "journal"))
    ckdir = os.path.join(w, "ck")
    base = max(journal.next_tick, peek_resume_ticks(ckdir))
    n_eff = max(0, args.ticks - base)

    wl = build_workload(args)
    ids = [s.stream_id for s in wl.streams]
    values = np.stack([s.values for s in wl.streams], axis=1)  # [T, N]
    ts = wl.streams[0].timestamps

    if args.preset == "composite":
        # the silicon shape (hw_session r12_workloads): the same seeded
        # cascade scored through the composite multi-field encoder —
        # value + delta both carry the wire value (the encoder
        # differentiates internally), the event-class column is quiet
        values = np.stack(
            [values, values, np.zeros_like(values)], axis=2)  # [T, N, 3]
        base_cfg = composite_preset()
    else:
        base_cfg = cluster_preset()
    cfg = dataclasses.replace(base_cfg, likelihood=dataclasses.replace(
        base_cfg.likelihood, learning_period=SOAK_LEARNING_PERIOD,
        estimation_samples=SOAK_ESTIMATION))
    reg = StreamGroupRegistry(cfg, group_size=args.group_size,
                              backend=args.backend,
                              threshold=args.threshold, debounce=2)
    for sid in ids:
        reg.add_stream(sid)
    reg.finalize()

    correlator = IncidentCorrelator(
        TopologyMap.from_spec(wl.spec),
        window_s=args.correlate_window, min_streams=args.min_streams)

    def source(k: int):
        g = base + k  # the feed depends only on the GLOBAL tick
        return values[g], int(ts[g])

    stats = live_loop(
        source, reg, n_ticks=n_eff, cadence_s=args.cadence,
        alert_path=os.path.join(w, "alerts.jsonl"),
        checkpoint_dir=ckdir, checkpoint_every=args.checkpoint_every,
        journal=journal, correlator=correlator)
    journal.close()
    line = {"base": base, "ran": stats["ticks"], "alerts": stats["alerts"],
            "incidents": stats.get("incidents", {})}
    with open(os.path.join(w, "stats.jsonl"), "a") as f:
        f.write(json.dumps(line) + "\n")
    print(json.dumps(line))
    return 0


# --------------------------------------------------------------- parent
def child_cmd(args, workdir: str) -> list[str]:
    return [sys.executable, os.path.abspath(__file__), "--child",
            "--workdir", workdir, "--seed", str(args.seed),
            "--ticks", str(args.ticks),
            "--services", str(args.services),
            "--nodes-per-service", str(args.nodes_per_service),
            "--group-size", str(args.group_size),
            "--cadence", str(args.cadence),
            "--checkpoint-every", str(args.checkpoint_every),
            "--backend", args.backend, "--preset", args.preset,
            "--threshold", str(args.threshold),
            "--correlate-window", str(args.correlate_window),
            "--min-streams", str(args.min_streams),
            "--burst-at-frac", str(args.burst_at_frac),
            "--cascade-lag", str(args.cascade_lag),
            "--burst-dur", str(args.burst_dur),
            "--burst-magnitude", str(args.burst_magnitude)]


def incident_records(path: str) -> list[dict]:
    from rtap_tpu.service.alerts import iter_alert_records

    return [rec for kind, rec in iter_alert_records(path)
            if kind == "event" and rec.get("event") == "incident"]


def check_single_incident(alerts_path: str, expected_nodes, min_streams: int,
                          failures: list[str], label: str,
                          parsed: dict | None = None) -> list[dict]:
    """THE shared topology-soak incident contract (this soak and
    chaos_soak --topology-burst verify the same promise — one checker,
    so a schema change cannot silently de-fang one of them): exactly ONE
    incident on the stream, blast radius == the expected node set, >=
    ``min_streams`` distinct member streams, and every member alert_id
    referencing an alert line actually on the stream
    (docs/WORKLOADS.md incident schema). ``parsed``: a pre-computed
    parse_alert_stream result to reuse instead of re-walking the file."""
    from scripts.crash_soak import parse_alert_stream

    incs = incident_records(alerts_path)
    if len(incs) != 1:
        failures.append(f"{label}: {len(incs)} incident(s) emitted, "
                        f"expected exactly 1 for the seeded burst")
        return incs
    inc = incs[0]
    if len(inc["streams"]) < min_streams:
        failures.append(f"{label}: incident groups {len(inc['streams'])} "
                        f"distinct stream(s), below min_streams "
                        f"{min_streams}")
    if sorted(inc["nodes"]) != sorted(expected_nodes):
        failures.append(f"{label}: blast radius {inc['nodes']} != faulted "
                        f"nodes {sorted(expected_nodes)}")
    ids_on_stream = set((parsed if parsed is not None
                         else parse_alert_stream(alerts_path))["alerts"])
    missing = [a for a in inc["alert_ids"] if a not in ids_on_stream]
    if missing:
        failures.append(f"{label}: {len(missing)} incident member "
                        f"alert_id(s) not on the alert stream: "
                        f"{missing[:5]}")
    return incs


def verify_incident_stream(args, wl, ref_alerts: str, failures: list[str],
                           label: str) -> list[dict]:
    """This soak's per-run checks: the shared contract against the
    seeded cascade's faulted nodes."""
    return check_single_incident(ref_alerts, wl.burst_nodes,
                                 args.min_streams, failures, label)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--kills", type=int, default=2)
    ap.add_argument("--ticks", type=int, default=220)
    ap.add_argument("--services", type=int, default=3)
    ap.add_argument("--nodes-per-service", type=int, default=3)
    ap.add_argument("--group-size", type=int, default=6)
    ap.add_argument("--cadence", type=float, default=0.02)
    ap.add_argument("--checkpoint-every", type=int, default=15)
    ap.add_argument("--backend", default="cpu")
    ap.add_argument("--preset", choices=("cluster", "composite"),
                    default="cluster",
                    help="model family for the soak children: cluster "
                         "(scalar RDSE — the acceptance default) or "
                         "composite (the ISSUE 9 multi-field encoder; "
                         "the hw_session r12_workloads silicon shape)")
    ap.add_argument("--threshold", type=float, default=0.1,
                    help="log-likelihood alert threshold: with the soak's "
                         "short probation the scalar burst peaks ~0.2 "
                         "while the healthy baseline sits ~0.02. The "
                         "composite preset's contrast profile is flatter "
                         "(burst ~0.07-0.09 vs healthy ~0.02 — the fused "
                         "SDR spreads novelty over three fields): pass "
                         "--threshold 0.04 with --preset composite")
    ap.add_argument("--correlate-window", type=int, default=10)
    ap.add_argument("--min-streams", type=int, default=3)
    ap.add_argument("--burst-at-frac", type=float, default=0.72)
    ap.add_argument("--cascade-lag", type=int, default=2)
    ap.add_argument("--burst-dur", type=int, default=10)
    ap.add_argument("--burst-magnitude", type=float, default=12.0)
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--out", default=None, help="report JSON path")
    ap.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    args = ap.parse_args()
    maybe_force_cpu()
    if args.child:
        return run_child(args)

    import random
    import subprocess

    from rtap_tpu.resilience import Supervisor, last_journal_tick
    from scripts.crash_soak import compare_states, parse_alert_stream

    wl = build_workload(args)
    onset0 = min(wl.burst_onsets.values())
    probation = SOAK_LEARNING_PERIOD + SOAK_ESTIMATION
    if onset0 <= probation + 10:
        log(f"FATAL: burst onset {onset0} inside the likelihood probation "
            f"{probation} — lengthen --ticks or raise --burst-at-frac")
        return 2
    workdir = args.workdir or tempfile.mkdtemp(prefix="workload_soak_")
    ref_dir = os.path.join(workdir, "ref")
    crash_dir = os.path.join(workdir, "crash")
    os.makedirs(ref_dir, exist_ok=True)
    os.makedirs(crash_dir, exist_ok=True)
    failures: list[str] = []
    t0 = time.monotonic()

    # 1. fault-free reference
    log(f"reference run: {args.ticks} ticks, {len(wl.streams)} streams, "
        f"burst service {wl.burst_service} at tick {onset0}")
    rc = subprocess.run(child_cmd(args, ref_dir)).returncode
    if rc != 0:
        log(f"FATAL: reference run failed rc={rc}")
        return INFRA_FAILED_EXIT
    ref_incs = verify_incident_stream(
        args, wl, os.path.join(ref_dir, "alerts.jsonl"), failures,
        "reference")

    # 2. the crash run: seeded kills, one pinned INSIDE the incident's
    # open window (the correlator state dies mid-fold)
    rng = random.Random(args.seed ^ 0xB1A57)
    lo = max(args.checkpoint_every + 2, args.ticks // 5)
    in_window = onset0 + args.burst_dur // 2
    pool = [t for t in range(lo, args.ticks * 4 // 5)
            if abs(t - in_window) > 3]
    targets = sorted([in_window] + rng.sample(pool, max(0, args.kills - 1)))
    log(f"crash run: SIGKILL at journal ticks ~{targets}")
    sup = Supervisor(child_cmd(args, crash_dir),
                     restart_budget=args.kills + 2,
                     backoff_base_s=0.05, backoff_max_s=1.0, log=log)
    observed: list[int] = []
    killer = threading.Thread(
        target=_killer, args=(sup, os.path.join(crash_dir, "journal"),
                              targets, observed, failures), daemon=True)
    killer.start()
    rc = sup.run(install_signals=False)
    killer.join(timeout=120.0)
    if rc != 0:
        failures.append(f"crash run ended rc={rc} (deaths={sup.deaths})")
    if sup.deaths != args.kills:
        failures.append(f"supervisor saw {sup.deaths} death(s), "
                        f"scheduled {args.kills}")
    bad_sigs = [s for s in sup.kill_signals if s != 9]
    if bad_sigs:
        failures.append(f"non-SIGKILL deaths observed: {bad_sigs}")

    # 3. verdicts
    crash_incs = verify_incident_stream(
        args, wl, os.path.join(crash_dir, "alerts.jsonl"), failures,
        "crash-run")
    # order-independent, content-exact comparison: the crash run's
    # incident records must be EXACTLY the reference's (a resume may
    # reorder the event line relative to later alerts, never change it)
    ref_sorted = sorted(json.dumps(i, sort_keys=True) for i in ref_incs)
    got_sorted = sorted(json.dumps(i, sort_keys=True) for i in crash_incs)
    if ref_sorted != got_sorted:
        failures.append("incident stream differs across kill-9 resume "
                        "(content compare by sorted record)")

    ref_alerts = parse_alert_stream(os.path.join(ref_dir, "alerts.jsonl"))
    got_alerts = parse_alert_stream(os.path.join(crash_dir, "alerts.jsonl"))
    if got_alerts["dup"]:
        failures.append(f"{len(got_alerts['dup'])} DUPLICATED alert_id(s)")
    lost = sorted(set(ref_alerts["alerts"]) - set(got_alerts["alerts"]))
    extra = sorted(set(got_alerts["alerts"]) - set(ref_alerts["alerts"]))
    if lost:
        failures.append(f"{len(lost)} LOST alert_id(s): {lost[:5]}")
    if extra:
        failures.append(f"{len(extra)} EXTRA alert_id(s): {extra[:5]}")
    if not ref_alerts["alerts"]:
        failures.append("reference run emitted zero alerts — the soak "
                        "proves nothing (lower --threshold)")
    leaves = compare_states(os.path.join(ref_dir, "ck"),
                            os.path.join(crash_dir, "ck"), failures)

    report = {
        "seed": args.seed,
        "streams": len(wl.streams),
        "burst_service": wl.burst_service,
        "burst_nodes": wl.burst_nodes,
        "burst_onset_tick": onset0,
        "kill_targets": targets,
        "kills_observed_at": observed,
        "deaths": sup.deaths,
        "alert_ids": len(ref_alerts["alerts"]),
        "incidents_reference": len(ref_incs),
        "incidents_crash_run": len(crash_incs),
        "incident": ref_incs[0] if len(ref_incs) == 1 else None,
        "state_leaves_compared": leaves,
        "wall_s": round(time.monotonic() - t0, 1),
        "verified": not failures,
        "failures": failures,
        "workdir": workdir,
    }
    if args.out:
        os.makedirs(os.path.dirname(os.path.abspath(args.out)),
                    exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2)
    print(json.dumps(report))
    if failures:
        for msg in failures:
            log(f"FAIL: {msg}")
        return VERIFY_FAILED_EXIT
    log(f"OK: 1 incident ({report['incident']['members']} members, "
        f"{len(report['incident']['nodes'])} nodes), "
        f"{len(ref_alerts['alerts'])} alert ids exactly-once, "
        f"{leaves} state leaves bit-identical across {sup.deaths} kill(s)")
    return 0


def _killer(sup, journal_dir: str, targets: list[int], observed: list,
            failures: list[str]) -> None:
    from scripts.crash_soak import _killer as crash_killer

    crash_killer(sup, journal_dir, targets, observed, failures)


if __name__ == "__main__":
    raise SystemExit(main())
