"""Full-rate trend rung: ONE pinned like-for-like config, every round.

Round-4 verdict weak #2: the full-rate throughput number moved 38,956 (r3)
-> 32,904 (r4) with no like-for-like rung separating the honest-feed fix
(r4's bench feeds NOVEL values per measured chunk; r3 re-dispatched the same
chunk, letting the TM fully learn a T-tick loop) from a genuine kernel
regression. This script measures the SAME config both ways:

  - full cluster preset (256 cols), G=256, T=64, full-rate learning,
    flat/matmul/dense kernel defaults;
  - `novel` feed (the honest r4 protocol) AND `repeated` feed (the r3
    protocol), back to back on the same warmed group state clone.

Output: reports/trend_rung.json with both numbers + their ratio. SCALING.md
tracks the novel number round-over-round; the repeated number exists to
translate historical results onto the honest scale.

Usage: python scripts/trend_rung.py [--out reports/trend_rung.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from rtap_tpu.utils.platform import (  # noqa: E402
    enable_compile_cache, init_backend_or_die, maybe_force_cpu,
)


def log(msg: str) -> None:
    print(f"[trend] {msg}", file=sys.stderr, flush=True)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=os.path.join(REPO, "reports", "trend_rung.json"))
    ap.add_argument("--G", type=int, default=256)
    ap.add_argument("--T", type=int, default=64)
    ap.add_argument("--measure-chunks", type=int, default=3)
    ap.add_argument("--repeats", type=int, default=3,
                    help="per-protocol repetitions; the artifact records the "
                         "best (least host-noise) and all raw values")
    args = ap.parse_args()

    maybe_force_cpu()
    init_backend_or_die()
    import jax

    enable_compile_cache(REPO)
    from rtap_tpu.config import cluster_preset
    from rtap_tpu.ops.tm_tpu import layout_mode, scatter_mode, sweep_mode
    from rtap_tpu.service.registry import StreamGroup
    from rtap_tpu.utils.measure import make_sine_feed, measure_pipelined

    cfg = cluster_preset()
    ids = [f"trend{i:04d}" for i in range(args.G)]
    platform = jax.devices()[0].platform
    log(f"platform={platform} G={args.G} T={args.T} "
        f"modes={layout_mode()}/{scatter_mode()}/{sweep_mode()}")

    results: dict[str, list[float]] = {"novel": [], "repeated": []}
    for protocol in ("novel", "repeated"):
        for rep in range(args.repeats):
            # fresh group per run: the repeated protocol's flattery depends
            # on the TM having learned THE measured loop, so the two
            # protocols must not share warmed state
            grp = StreamGroup(cfg, ids, backend="tpu")
            vals, ts, phase = make_sine_feed(args.G, args.T, key=(2026, 7))
            t0 = time.perf_counter()
            grp.run_chunk(vals, ts)  # warmup: compile + one real chunk
            warm_s = time.perf_counter() - t0
            novel = ((2026, 7), phase) if protocol == "novel" else None
            value, dt = measure_pipelined(grp, vals, ts, args.measure_chunks,
                                          novel=novel)
            results[protocol].append(round(value, 1))
            log(f"{protocol} rep {rep}: {value:.1f} metrics/s "
                f"(warmup {warm_s:.1f}s, measure {dt:.2f}s)")

    best_novel = max(results["novel"])
    best_rep = max(results["repeated"])
    out = {
        "config": "cluster_preset/flat/matmul/dense, full-rate learning",
        "G": args.G, "T": args.T, "measure_chunks": args.measure_chunks,
        "platform": platform,
        "novel_feed_metrics_per_s": best_novel,
        "repeated_feed_metrics_per_s": best_rep,
        "repeat_over_novel_ratio": round(best_rep / best_novel, 4),
        "raw": results,
        "history_note": (
            "r3 bench 38,956 used the repeated protocol; r4 full_rate_value "
            "32,904 used novel. The ratio above converts between the scales."
        ),
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    # bench.py appends its per-round {round, full_rate, headline} series
    # under "rounds" in this same artifact — a protocol-study rerun must
    # carry it forward, not wipe it
    try:
        with open(args.out) as f:
            prev_rounds = json.load(f).get("rounds")
    except (OSError, ValueError):
        prev_rounds = None
    if prev_rounds is not None:
        out["rounds"] = prev_rounds
    with open(args.out, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
