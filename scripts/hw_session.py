"""Run the full on-hardware measurement agenda in one tunnel-up window.

The TPU tunnel oscillates (SCALING.md): it can be reachable for minutes and
then hang backend init for an hour. When it IS up, this script spends the
window optimally — every step is a subprocess with its own wall budget (a
hang costs one step, not the session), ordered most-valuable-first. The
authoritative agenda and its ordering rationale live in the STEPS list
below (the r3 strategy matrix already measured sits first and is ledgered
done; bench + nab_corpus lead the remaining r4 agenda — see the comment
above them). --steps indices are positions in STEPS as printed by --help,
NOT a stable step id: always check the list after edits.

Logs land in hw_results/<step>.log; a one-line verdict per step prints to
stderr as it completes. Re-runs skip nothing here (fresh measurements
overwrite); the ledgered harvest loop is scripts/hw_watch.py.

Usage:  python scripts/hw_session.py [--budget-per-step 600] [--steps 1,2,5]
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "hw_results")
# obs_tail imports rtap_tpu.obs in THIS process; running as `python
# scripts/hw_session.py` puts scripts/ (not the repo) at sys.path[0]
sys.path.insert(0, REPO)


def log(msg: str) -> None:
    print(f"[hw_session] {msg}", file=sys.stderr, flush=True)


# entries are (name, cmd) or (name, cmd, budget_s)
STEPS: list[tuple[str, list[str]] | tuple[str, list[str], float]] = [
    ("layout_probe", [sys.executable, "scripts/layout_probe.py"]),
    # every step pins --layout: the process default flipped to flat with the
    # r4 A/B, so an omitted flag would silently re-measure (and on a rerun
    # OVERWRITE the committed evidence logs of) a different config than the
    # step's name claims
    ("profile_matmul", [sys.executable, "scripts/profile_step.py", "--T", "32",
                        "--gs", "1024", "--layout", "aos"]),
    ("profile_indexed", [sys.executable, "scripts/profile_step.py", "--T", "32",
                         "--gs", "1024", "--layout", "aos",
                         "--scatter", "indexed"]),
    ("profile_f32_indexed", [sys.executable, "scripts/profile_step.py", "--T", "32",
                             "--gs", "1024", "--layout", "aos",
                             "--perm-bits", "0", "--scatter", "indexed"]),
    ("profile_flat", [sys.executable, "scripts/profile_step.py", "--T", "32",
                      "--gs", "1024", "--layout", "flat"]),
    ("profile_flat_indexed", [sys.executable, "scripts/profile_step.py", "--T", "32",
                              "--gs", "1024", "--layout", "flat",
                              "--scatter", "indexed"]),
    # round-4 strategies: compact punish/death sweep; forward-index dendrite
    # (both fwd histogram impls). The first silicon batch (2026-07-31,
    # hw_results/profile_{matmul,indexed,flat,...}.log) measured the CPU
    # "indexed wins 2.4x" signal INVERTED on TPU (indexed 18.1k vs matmul
    # 28.1k vs flat/matmul 31.9k metrics/s at G=1024), so the r4 candidates
    # are raced on the silicon winner's base (matmul scatter, aos + flat)
    # rather than the CPU-guess base (--scatter indexed) they shipped with.
    # Most-valuable-first for a SHORT window (the tunnel has been wedged
    # for 7h as of this ordering; assume every window may be the last):
    # 1. bench — the headline artifact, and its ladder already races the
    #    main candidates (flat / aos / flat+compact / flat+compact+forward)
    #    at the measured-optimal rung, so it partially subsumes the
    #    individual profiles;
    # 2. nab_corpus — the committed-artifact verdict item (minutes on
    #    silicon; the CPU fallback measured 7 s/tick and was abandoned);
    # 3. cadence profiles — validate the 100k-projection (plain chunk_step
    #    compiles, low hang risk);
    # 4. the compact/fwd profile matrix (the indexed+compact variant hung
    #    compile for its full 900 s budget once — keep these behind the
    #    high-value steps);
    # 5. sweeps and service-shape experiments.
    # Layouts explicit everywhere: the process default flipped to flat with
    # the r4 A/B, and an omitted --layout would silently duplicate configs.
    ("bench", [sys.executable, "bench.py"], 1700.0),
    ("nab_corpus", [sys.executable, "scripts/nab_standin_report.py"]),
    ("profile_cadence4", [sys.executable, "scripts/profile_step.py", "--T", "32",
                          "--gs", "1024", "--layout", "flat",
                          "--learn-every", "4"]),
    ("profile_cadence8", [sys.executable, "scripts/profile_step.py", "--T", "32",
                          "--gs", "1024", "--layout", "flat",
                          "--learn-every", "8"]),
    ("profile_flat_compact", [sys.executable, "scripts/profile_step.py", "--T", "32",
                              "--gs", "1024", "--layout", "flat",
                              "--sweep", "compact"]),
    ("profile_compact", [sys.executable, "scripts/profile_step.py", "--T", "32",
                         "--gs", "1024", "--layout", "aos",
                         "--sweep", "compact"]),
    ("profile_fwd_matmul", [sys.executable, "scripts/profile_step.py", "--T", "32",
                            "--gs", "1024", "--layout", "flat",
                            "--dendrite", "forward", "--fwd-impl", "matmul"]),
    ("profile_fwd_scatter", [sys.executable, "scripts/profile_step.py", "--T", "32",
                             "--gs", "1024", "--layout", "flat",
                             "--dendrite", "forward", "--fwd-impl", "scatter"]),
    ("profile_fwd_aos", [sys.executable, "scripts/profile_step.py", "--T", "32",
                         "--gs", "1024", "--layout", "aos",
                         "--dendrite", "forward", "--fwd-impl", "matmul"]),
    ("scaling_sweep", [sys.executable, "scripts/scaling_law.py"]),
    ("pipeline_gain", [sys.executable, "scripts/pipeline_gain.py"]),
    # round-4 service-shape experiments (verdict weak #3 / #7); the soak is
    # startup (up to ~300 s compile) + a >= 5 min paced loop by design.
    # bench above subprocess-isolates its own attempts under
    # BENCH_BUDGET_S=1500; its step budget must exceed that or the runner
    # would SIGKILL it before its own SIGTERM-emit path can print the line.
    ("multigroup", [sys.executable, "scripts/multigroup_sched.py"], 1200.0),
    # the production serve shape landed this round: many small groups per
    # chip (live_loop over a registry, interleaved dispatch). Soak it at
    # that shape — 16 x 256 streams at the 1 s-cadence north star — rather
    # than the single giant group the G-sweep already showed is the wrong
    # operating point.
    # budget sized for the 4096-stream shape: startup (<=420 s init +
    # first-tick compile) + 330 ticks at up to ~4 s/tick of degradation —
    # the soak must be able to REPORT badly missed deadlines, not get
    # SIGKILLed by its own runner while measuring them
    ("live_soak", [sys.executable, "scripts/live_soak.py",
                   "--streams", "4096", "--group-size", "256"], 2100.0),
    # The 16x256 soak measured p50 1.07 s/tick — ALL deadlines missed at
    # the 1 s cadence, ~65 ms per group per tick of dispatch+collect round
    # trip over the remote-chip tunnel (the chunked multigroup throughput
    # was flat across decompositions, but live T=1 dispatches are latency-
    # bound, not bandwidth-bound). These shapes cut the round trips per
    # tick 4x/16x to isolate the per-dispatch cost from the device step.
    ("live_soak_g1024", [sys.executable, "scripts/live_soak.py",
                         "--streams", "4096", "--group-size", "1024",
                         "--out", "reports/live_soak_g1024.json"], 2100.0),
    ("live_soak_g4096", [sys.executable, "scripts/live_soak.py",
                         "--streams", "4096", "--group-size", "4096",
                         "--out", "reports/live_soak_g4096.json"], 2100.0),
    # depth-2 serve pipeline: collect tick k after dispatching k+1, hiding
    # the per-group round trip behind the cadence sleep at the production
    # 16x256 shape (alerts lag one cadence — the documented trade)
    ("live_soak_pipelined", [sys.executable, "scripts/live_soak.py",
                             "--streams", "4096", "--group-size", "256",
                             "--pipeline-depth", "2",
                             "--out", "reports/live_soak_pipelined.json"], 2100.0),
    # half-size model (scaled_cluster_preset 128 cols): measured BETTER f1
    # than the preset at half the state (reports/model_size_quality.json);
    # these measure the bandwidth-bound ~2x on silicon. The bench ladder
    # also carries the half rungs (BENCH_COLUMNS) for the headline path.
    ("profile_half", [sys.executable, "scripts/profile_step.py", "--T", "32",
                      "--gs", "1024", "--layout", "flat",
                      "--columns", "128"]),
    ("profile_half_k2", [sys.executable, "scripts/profile_step.py", "--T", "32",
                         "--gs", "1024", "--layout", "flat",
                         "--columns", "128", "--learn-every", "2"]),
    ("profile_eighth", [sys.executable, "scripts/profile_step.py", "--T", "32",
                        "--gs", "1024", "--layout", "flat",
                        "--columns", "32"]),
    # width-scaled NAB-family model over the stand-in corpus ON DEVICE
    # (minutes; the full-size run took 405 s): does the "preset is
    # oversized" finding generalize to the quality-model family on
    # diverse profiles? Scores land in reports/nab_standin_cols<N>.json,
    # never clobbering the full-size artifact.
    ("nab_cols256", [sys.executable, "scripts/nab_standin_report.py",
                     "--columns", "256"]),
    ("nab_cols512", [sys.executable, "scripts/nab_standin_report.py",
                     "--columns", "512"]),
    # first two points measured 2048 -> 8.25, 256 -> 27.69 (standard
    # profile): the width-quality curve on the corpus needs its middle and
    # lower ends before any preset recommendation is written down
    ("nab_cols128", [sys.executable, "scripts/nab_standin_report.py",
                     "--columns", "128"]),
    ("nab_cols1024", [sys.executable, "scripts/nab_standin_report.py",
                      "--columns", "1024"]),
    # small-model big-G: the full preset falls off past G=2048 (HBM-bound);
    # 64-col state is 1/4 — does the throughput curve stay flat to 8k?
    ("profile_64_g8192", [sys.executable, "scripts/profile_step.py", "--T", "32",
                          "--gs", "8192", "--layout", "flat",
                          "--columns", "64"]),
    # 32col+k2: projected ~126k/s (learning ~91% of the 32col tick) — the
    # first config past the north star whose BASE width beats the preset's
    # quality; the k=2 quality cost is measured by model_size_eval
    # (eighth_32col_k2 variant) on the CPU host
    ("profile_eighth_k2", [sys.executable, "scripts/profile_step.py", "--T", "32",
                           "--gs", "1024", "--layout", "flat",
                           "--columns", "32", "--learn-every", "2"]),
    # the 16x256 fix, round 3: depth 2 alone measured NO change (p50
    # 1.07 s — each dispatch is a blocking ~65 ms tunnel RPC, so 16
    # groups serialize ~1.04 s/tick regardless of when collection
    # happens); dispatch_threads=16 overlaps the RPCs. Success = the
    # production shape holds the 1 s cadence like 4x1024 does.
    ("live_soak_threads", [sys.executable, "scripts/live_soak.py",
                           "--streams", "4096", "--group-size", "256",
                           "--pipeline-depth", "2", "--dispatch-threads", "16",
                           "--out", "reports/live_soak_threads.json"], 2100.0),
    # the headline's missing quality number: what does k=2 cost the
    # best-f1 width (0.813 detectable / 0.758 all-kinds at k=1)? At
    # 64 col, k=2 cost 8.3 points. Runs the 120x1500 protocol on-device.
    ("eval_32col_k2", [sys.executable, "scripts/model_size_eval.py",
                       "--variants", "eighth_32col_k2"]),
    ("eval_32col_k2_allkinds", [sys.executable, "scripts/model_size_eval.py",
                                "--variants", "eighth_32col_k2",
                                "--all-kinds"]),
    # resident-capability frontier at the headline width: 256-col OOMs
    # between 8k and 16k streams/chip; 32-col state is 1/8, so the
    # frontier should land ~64k-128k — if >= 100k streams FIT and score
    # on ONE chip, the "100k-on-one-chip unreachable" r3 verdict flips
    # on the width axis. profile_step records FAILED per-G and exits 0,
    # so the OOM probe cannot burn watcher attempts.
    ("profile_32col_bigg", [sys.executable, "scripts/profile_step.py",
                            "--T", "32", "--gs", "16384", "32768", "65536",
                            "98304", "131072", "--layout", "flat",
                            "--columns", "32"], 1800.0),
    # absolute ceiling probe: u8 perm domain halves state again
    # (quality per domain measured in SCALING.md's domain table)
    ("profile_32col_bigg_u8", [sys.executable, "scripts/profile_step.py",
                               "--T", "32", "--gs", "131072", "196608",
                               "262144", "--layout", "flat", "--columns", "32",
                               "--perm-bits", "8"], 1800.0),
    # quality numbers for the u8-domain capability configs (16 s each on
    # device at the 120x1500 protocol)
    ("eval_32col_u8", [sys.executable, "scripts/model_size_eval.py",
                       "--variants", "eighth_32col_u8,eighth_32col_u8_k2"]),
    ("eval_32col_u8_allkinds", [sys.executable, "scripts/model_size_eval.py",
                                "--variants",
                                "eighth_32col_u8,eighth_32col_u8_k2",
                                "--all-kinds"]),
    # live capability at the measured resident frontier: 16k and 32k
    # streams at 1 s cadence WITH learning on one chip (32col learn
    # ticks profile 345/769 ms at G=16k/32k; k=2 + depth 2 + threads
    # hide the rest). Startup pays a big state transfer: raised budget.
    ("live_soak_16k", [sys.executable, "scripts/live_soak.py",
                       "--streams", "16384", "--group-size", "4096",
                       "--columns", "32", "--learn-every", "2",
                       "--pipeline-depth", "2", "--dispatch-threads", "4",
                       "--startup-timeout", "900",
                       "--out", "reports/live_soak_16k.json"], 2400.0),
    ("live_soak_32k", [sys.executable, "scripts/live_soak.py",
                       "--streams", "32768", "--group-size", "4096",
                       "--columns", "32", "--learn-every", "2",
                       "--pipeline-depth", "2", "--dispatch-threads", "8",
                       "--startup-timeout", "900",
                       "--out", "reports/live_soak_32k.json"], 2400.0),
    # frozen serving at the FULL resident frontier: inference-only ticks
    # profile ~1/5 of learning, so 65,536 frozen streams should hold 1 s
    # where learning cannot (1,555 ms/tick). Capability-envelope probe: a
    # fresh model served frozen measures the serving path, not detection.
    ("live_soak_64k_frozen", [sys.executable, "scripts/live_soak.py",
                              "--streams", "65536", "--group-size", "8192",
                              "--columns", "32", "--freeze",
                              "--pipeline-depth", "2",
                              "--dispatch-threads", "8",
                              "--startup-timeout", "1200",
                              "--out", "reports/live_soak_64k_frozen.json"],
     2700.0),
    # width x probation composition: does the lp600 likelihood lever
    # (+3 points on the preset) stack with the 32col width (0.813)?
    ("eval_32col_lp600", [sys.executable, "scripts/model_size_eval.py",
                          "--variants",
                          "eighth_32col_lp600,eighth_32col_k2_lp600"]),
    ("eval_32col_lp600_allkinds", [sys.executable,
                                   "scripts/model_size_eval.py",
                                   "--variants",
                                   "eighth_32col_lp600,eighth_32col_k2_lp600",
                                   "--all-kinds"]),
    # dynamic slot claim on the real chip: set_state_row's donated
    # .at[slot].set against grouped TPU state + scoring continuity
    ("dynamic_claim", [sys.executable, "scripts/dynamic_claim_probe.py"]),
    # elastic churn under deadline at production scale: does a mid-soak
    # claim/release (drain-first membership rule + on-device row reset)
    # cost missed ticks? ~16 rotations over the 330-tick soak.
    ("live_soak_churn", [sys.executable, "scripts/live_soak.py",
                         "--streams", "4096", "--group-size", "1024",
                         "--columns", "32", "--learn-every", "2",
                         "--pipeline-depth", "2", "--dispatch-threads", "4",
                         "--churn-every", "20", "--startup-timeout", "900",
                         "--out", "reports/live_soak_churn.json"], 2400.0),
    # sustained stability: 30 minutes of continuous churn at the
    # production shape — memory leaks, counter drift, or slow latency
    # creep would surface here, not in a 5-minute soak
    ("live_soak_30min", [sys.executable, "scripts/live_soak.py",
                         "--streams", "4096", "--group-size", "1024",
                         "--columns", "32", "--learn-every", "2",
                         "--pipeline-depth", "2", "--dispatch-threads", "4",
                         "--churn-every", "30", "--ticks", "1800",
                         "--startup-timeout", "900",
                         "--out", "reports/live_soak_30min.json"], 3300.0),
    # disambiguate the >65k resident wall: u16 fails at 98304; if u8 at
    # 81920/98304 also fails, the wall is purely G-structural in the
    # remote compiler (no state-size component)
    ("profile_32col_u8_mid", [sys.executable, "scripts/profile_step.py",
                              "--T", "32", "--gs", "81920", "98304",
                              "--layout", "flat", "--columns", "32",
                              "--perm-bits", "8"], 1800.0),
    # ---------------- round 5 ----------------
    # Pallas re-race at the HEADLINE width (verdict r4 item 8): the dendrite
    # kernel lost at 256-col/aos (24.3k vs 31.9k); arithmetic intensity at
    # 32-col/flat is different. A/B at the exact headline config (k=2) and
    # its full-rate base.
    # The >65k wall is per-program workspace, which scales with G AND the
    # scan chunk T (verdict r4 item 2: "smaller scan T at scale"). If T=8
    # compiles at 98304 where T=32 500s, the wall is the T-scaled feed/
    # workspace, and single-program residency extends toward 100k. T=8 at
    # 65536 calibrates the T-cost at a known-good G first.
    ("r5_T8_65k", [sys.executable, "scripts/profile_step.py",
                   "--T", "8", "--gs", "65536", "--layout", "flat",
                   "--columns", "32"], 1500.0),
    ("r5_T8_98k", [sys.executable, "scripts/profile_step.py",
                   "--T", "8", "--gs", "98304", "131072", "--layout", "flat",
                   "--columns", "32"], 1800.0),
    # THE round-5 flagship (verdict item 2): 100k streams LIVE LEARNING at
    # 1 s on ONE chip. The >65k single-program wall is G-structural
    # (r5_T8_98k: T=8 still compile-500s), so the route is many small
    # groups — the shape live serving already prefers (SCALING.md: compute
    # throughput PEAKS at G~1024; the 32k soak at 8x4096 held p50 67 ms
    # with 15x headroom). 100x1024 at 32col/k=2: average device compute
    # ~102400/136k = 0.75 s/tick, spread evenly by --stagger-learn so no
    # single tick carries the whole fleet's learning spike; 16 threads
    # overlap the ~65 ms/group dispatch RPCs.
    ("r5_soak_100k", [sys.executable, "scripts/live_soak.py",
                      "--streams", "102400", "--group-size", "1024",
                      "--columns", "32", "--learn-every", "2",
                      "--stagger-learn", "--pipeline-depth", "2",
                      "--dispatch-threads", "16",
                      "--startup-timeout", "1800",
                      "--out", "reports/live_soak_100k.json"], 4200.0),
    # 65,536 LEARNING live (r4 only demonstrated 65k frozen / 32k learning):
    # the intermediate capability rung, and the control for the 100-group
    # RPC-overhead question (16 groups here).
    ("r5_soak_64k_learn", [sys.executable, "scripts/live_soak.py",
                           "--streams", "65536", "--group-size", "4096",
                           "--columns", "32", "--learn-every", "2",
                           "--stagger-learn", "--pipeline-depth", "2",
                           "--dispatch-threads", "8",
                           "--startup-timeout", "1500",
                           "--out", "reports/live_soak_64k_learn.json"], 3600.0),
    # alternate 100k shape (25x4096): fewer, bigger dispatches — wins if
    # the 100-group RPC wall dominates, loses if the per-G compute falloff
    # (47k/s at G=16384 vs 74k at G=1024, k=1) dominates.
    ("r5_soak_100k_g4096", [sys.executable, "scripts/live_soak.py",
                            "--streams", "102400", "--group-size", "4096",
                            "--columns", "32", "--learn-every", "2",
                            "--stagger-learn", "--pipeline-depth", "2",
                            "--dispatch-threads", "8",
                            "--startup-timeout", "1800",
                            "--out", "reports/live_soak_100k_g4096.json"],
     4200.0),
    # pinned full-rate trend rung (verdict item 4): novel vs repeated feed
    # at the full preset, G=256/T=64 — explains r3 38,956 -> r4 32,904
    ("r5_trend_rung", [sys.executable, "scripts/trend_rung.py"], 1500.0),
    # roofline/MFU accounting (verdict item 3): XLA cost_analysis of the
    # TPU-lowered step vs chip peaks vs the committed measured times
    ("r5_roofline", [sys.executable, "scripts/roofline.py"], 1800.0),
    # held-out external validation of the width ladder (verdict item 1):
    # 7 variants x 3 seeds x 3 magnitudes, all 5 kinds, 120x1500 each.
    # Incremental-merge into reports/heldout_eval.json — a window drop
    # resumes where it left off.
    ("r5_heldout_eval", [sys.executable, "scripts/heldout_eval.py"], 5400.0),
    # 100k-soak forensics: the tick period pinned at ~1.4 s at BOTH
    # 100x1024/102k and 16x4096/64k (and 2.19 s at 25x4096/102k) — the
    # instrumented live_loop now reports phase_ms_per_tick
    # (source/dispatch/collect/emit); this rerun names the binding phase.
    ("r5_soak_64k_phase", [sys.executable, "scripts/live_soak.py",
                           "--streams", "65536", "--group-size", "4096",
                           "--columns", "32", "--learn-every", "2",
                           "--stagger-learn", "--pipeline-depth", "2",
                           "--dispatch-threads", "8",
                           "--startup-timeout", "1500",
                           "--out", "reports/live_soak_64k_phase.json"],
     3600.0),
    # the cadence ladder's hold candidate: k=4 halves the per-tick device
    # compute vs k=2 (learning is ~9x an inference tick at 32col) — at
    # 100x1024 the projection is ~0.8 s/tick. Quality cost measured by
    # r5_eval_k4/r5_heldout_eval, never assumed.
    ("r5_soak_100k_k4", [sys.executable, "scripts/live_soak.py",
                         "--streams", "102400", "--group-size", "1024",
                         "--columns", "32", "--learn-every", "4",
                         "--stagger-learn", "--pipeline-depth", "2",
                         "--dispatch-threads", "16",
                         "--startup-timeout", "1800",
                         "--out", "reports/live_soak_100k_k4.json"], 4200.0),
    # diurnal-family quality for the cadence ladder (same protocol as the
    # committed model_size artifacts; heldout covers the other family)
    ("r5_eval_k4", [sys.executable, "scripts/model_size_eval.py",
                    "--variants", "eighth_32col_k3,eighth_32col_k4"]),
    ("r5_eval_k4_allkinds", [sys.executable, "scripts/model_size_eval.py",
                             "--variants", "eighth_32col_k3,eighth_32col_k4",
                             "--all-kinds"]),
    # fresh headline for the round (stores BENCH_LKG; the driver also runs
    # bench.py itself at round end)
    ("r5_bench", [sys.executable, "bench.py"], 1700.0),
    # 100k cadence, round 3 of forensics: k=4 changed NOTHING (p50 1392 vs
    # 1398 ms) — at 100x1024 the binder is ~200 blocking ~70 ms RPCs/tick
    # 16-way overlapped (~0.9 s wall), not device compute. RPC waits
    # release the GIL; 48 threads project the RPC wall to ~0.3 s. k=2
    # first (the better-quality operating point).
    ("r5_soak_100k_t48", [sys.executable, "scripts/live_soak.py",
                          "--streams", "102400", "--group-size", "1024",
                          "--columns", "32", "--learn-every", "2",
                          "--stagger-learn", "--pipeline-depth", "2",
                          "--dispatch-threads", "48",
                          "--startup-timeout", "1800",
                          "--out", "reports/live_soak_100k_t48.json"],
     4200.0),
    ("r5_soak_100k_k4_t48", [sys.executable, "scripts/live_soak.py",
                             "--streams", "102400", "--group-size", "1024",
                             "--columns", "32", "--learn-every", "4",
                             "--stagger-learn", "--pipeline-depth", "2",
                             "--dispatch-threads", "48",
                             "--startup-timeout", "1800",
                             "--out",
                             "reports/live_soak_100k_k4_t48.json"], 4200.0),
    # Micro-chunk ladder: the per-program invocation floor (~6-12 ms,
    # thread- and cadence-invariant — r5 forensics) divides by M when M
    # ticks ride one dispatch (live_loop micro_chunk; bit-exact vs
    # per-tick by test). Price: <= (2M-1) ticks alert staleness at depth
    # 2. k=2 kept where possible (better quality: heldout 0.4002 vs k4
    # 0.3945, diurnal 0.762 vs 0.739).
    ("r5_soak_100k_m2", [sys.executable, "scripts/live_soak.py",
                         "--streams", "102400", "--group-size", "1024",
                         "--columns", "32", "--learn-every", "2",
                         "--stagger-learn", "--micro-chunk", "2",
                         "--pipeline-depth", "2", "--dispatch-threads", "16",
                         "--startup-timeout", "1800",
                         "--out", "reports/live_soak_100k_m2.json"], 4200.0),
    ("r5_soak_100k_m4", [sys.executable, "scripts/live_soak.py",
                         "--streams", "102400", "--group-size", "1024",
                         "--columns", "32", "--learn-every", "2",
                         "--stagger-learn", "--micro-chunk", "4",
                         "--pipeline-depth", "2", "--dispatch-threads", "16",
                         "--startup-timeout", "1800",
                         "--out", "reports/live_soak_100k_m4.json"], 4200.0),
    ("r5_soak_100k_k4_m4", [sys.executable, "scripts/live_soak.py",
                            "--streams", "102400", "--group-size", "1024",
                            "--columns", "32", "--learn-every", "4",
                            "--stagger-learn", "--micro-chunk", "4",
                            "--pipeline-depth", "2",
                            "--dispatch-threads", "16",
                            "--startup-timeout", "1800",
                            "--out",
                            "reports/live_soak_100k_k4_m4.json"], 4200.0),
    # THE steady-state capability soaks. Every soak above unknowingly ran
    # the 300-tick FULL-RATE maturity window over 91% of its 330 ticks
    # (serve's with_learn_every default) — which is why k/threads/m never
    # moved the needle. --learn-full-until 0 measures the mature fleet
    # (profile/bench semantics; production onboards gradually and never
    # pays the whole window at once). k4+m4 projects ~0.65 s/tick; k2+m4
    # ~1.0 s (marginal, better quality) — measure both.
    ("r5_soak_100k_steady_k4m4", [sys.executable, "scripts/live_soak.py",
                                  "--streams", "102400", "--group-size",
                                  "1024", "--columns", "32",
                                  "--learn-every", "4", "--learn-full-until",
                                  "0", "--stagger-learn", "--micro-chunk",
                                  "4", "--pipeline-depth", "2",
                                  "--dispatch-threads", "16",
                                  "--startup-timeout", "1800",
                                  "--out",
                                  "reports/live_soak_100k_steady_k4m4.json"],
     4200.0),
    ("r5_soak_100k_steady_k2m4", [sys.executable, "scripts/live_soak.py",
                                  "--streams", "102400", "--group-size",
                                  "1024", "--columns", "32",
                                  "--learn-every", "2", "--learn-full-until",
                                  "0", "--stagger-learn", "--micro-chunk",
                                  "4", "--pipeline-depth", "2",
                                  "--dispatch-threads", "16",
                                  "--startup-timeout", "1800",
                                  "--out",
                                  "reports/live_soak_100k_steady_k2m4.json"],
     4200.0),
    # THE capability soak: chunk_stagger levels the boundary spike (the
    # steady k4m4 run was sustainable at ~0.7 s/tick average but carried
    # 2.8 s of chunk work on every 4th tick = 83 guaranteed misses). With
    # rotated boundaries each tick carries ~25 groups' dispatch+collect —
    # projection ~0.7 s/tick EVERY tick. Bit-exact vs plain serving by
    # test (tests/unit/test_multigroup_serve.py).
    ("r5_soak_100k_final", [sys.executable, "scripts/live_soak.py",
                            "--streams", "102400", "--group-size", "1024",
                            "--columns", "32", "--learn-every", "4",
                            "--learn-full-until", "0", "--stagger-learn",
                            "--micro-chunk", "4", "--chunk-stagger",
                            "--pipeline-depth", "2",
                            "--dispatch-threads", "16",
                            "--startup-timeout", "1800",
                            "--out", "reports/live_soak_100k_final.json"],
     4200.0),
    # quality-better operating point at the same per-tick budget: k=3
    # (diurnal f1 0.7499 vs k4's 0.7389) with m=6 boundaries
    ("r5_soak_100k_final_k3m6", [sys.executable, "scripts/live_soak.py",
                                 "--streams", "102400", "--group-size",
                                 "1024", "--columns", "32",
                                 "--learn-every", "3", "--learn-full-until",
                                 "0", "--stagger-learn", "--micro-chunk",
                                 "6", "--chunk-stagger",
                                 "--pipeline-depth", "2",
                                 "--dispatch-threads", "16",
                                 "--startup-timeout", "1800",
                                 "--out",
                                 "reports/live_soak_100k_k3m6.json"],
     4200.0),
    # f32 permanence domain at the headline width (roofline follow-up):
    # the u16 storage the presets default to charges decode/encode
    # conversion passes over the largest pools EVERY tick; f32 skips them
    # at ~1.4x the state (still ~10 GB at 100k streams — fits). If it
    # wins, it is a free throughput bump at reference-faithful semantics.
    ("r5_f32_32col", [sys.executable, "scripts/profile_step.py",
                      "--T", "32", "--gs", "1024", "--layout", "flat",
                      "--columns", "32", "--perm-bits", "0"]),
    ("r5_f32_32col_k4", [sys.executable, "scripts/profile_step.py",
                         "--T", "32", "--gs", "1024", "--layout", "flat",
                         "--columns", "32", "--perm-bits", "0",
                         "--learn-every", "4"]),
    ("r5_f32_preset", [sys.executable, "scripts/profile_step.py",
                       "--T", "32", "--gs", "1024", "--layout", "flat",
                       "--perm-bits", "0"]),
    # complete the held-out ladder with the k=3 serving operating point
    # (merges into the existing artifact; ~1 min on device)
    ("r5_heldout_k3", [sys.executable, "scripts/heldout_eval.py",
                       "--variants", "eighth_32col_k3"]),
    # refresh the NAB stand-in artifact under the EXHAUSTIVE sweeper (the
    # committed scores were produced by the old ~200-quantile sweep; the
    # exhaustive optimum can only be >=, and the artifact must match the
    # shipped scorer)
    ("r5_nab_exhaustive", [sys.executable,
                           "scripts/nab_standin_report.py"], 1200.0),
    # width-curve points refreshed under the exhaustive sweeper (artifact
    # consistency with the shipped scorer; the full-size refresh moved
    # 8.25 -> 11.89 standard)
    ("r5_nab256", [sys.executable, "scripts/nab_standin_report.py",
                   "--columns", "256"]),
    ("r5_nab512", [sys.executable, "scripts/nab_standin_report.py",
                   "--columns", "512"]),
    # endurance at the flagship point: 30 MINUTES of 102,400 live
    # learning streams at the k3/m6 steady state — leaks, drift, or
    # latency creep would surface here, not in a 5.5-minute soak
    ("r5_soak_100k_30min", [sys.executable, "scripts/live_soak.py",
                            "--streams", "102400", "--group-size", "1024",
                            "--columns", "32", "--learn-every", "3",
                            "--learn-full-until", "0", "--stagger-learn",
                            "--micro-chunk", "6", "--chunk-stagger",
                            "--ticks", "1800", "--pipeline-depth", "2",
                            "--dispatch-threads", "16",
                            "--startup-timeout", "1800",
                            "--out",
                            "reports/live_soak_100k_30min.json"], 4500.0),
    # the quality-tier live point: 128col measured the BEST held-out f1
    # (0.4447 vs preset 0.4033); this soak backs docs/DEPLOYMENT.md's
    # 128col row with a live capability artifact at 16k streams
    ("r5_soak_16k_128col", [sys.executable, "scripts/live_soak.py",
                            "--streams", "16384", "--group-size", "1024",
                            "--columns", "128", "--learn-every", "2",
                            "--learn-full-until", "0", "--stagger-learn",
                            "--micro-chunk", "4", "--chunk-stagger",
                            "--pipeline-depth", "2",
                            "--dispatch-threads", "16",
                            "--startup-timeout", "1500",
                            "--out",
                            "reports/live_soak_16k_128col.json"], 3000.0),
    # capstone: elastic churn AT the flagship scale — 102,400 streams
    # with a stream rotating out (auto-released) and a new id in
    # (auto-registered) every 30 s, under the full serving stack
    # (k3/m6/chunk-stagger; membership forces warm boundary realignments)
    ("r5_soak_100k_churn", [sys.executable, "scripts/live_soak.py",
                            "--streams", "102400", "--group-size", "1024",
                            "--columns", "32", "--learn-every", "3",
                            "--learn-full-until", "0", "--stagger-learn",
                            "--micro-chunk", "6", "--chunk-stagger",
                            "--churn-every", "30", "--pipeline-depth", "2",
                            "--dispatch-threads", "16",
                            "--startup-timeout", "1800",
                            "--out",
                            "reports/live_soak_100k_churn.json"], 4200.0),
    # tighter error bars on the held-out verdict: two more seeds over the
    # full variant ladder (merge-incremental; ~5 s/cell on device)
    ("r5_heldout_seeds2", [sys.executable, "scripts/heldout_eval.py",
                           "--seeds", "59,71"], 2400.0),
    # the hour-long flagship run: 3600 ticks of 102,400 live learning
    # streams at the k3/m6 point — 368M+ metrics in one unbroken serve
    ("r5_soak_100k_1h", [sys.executable, "scripts/live_soak.py",
                         "--streams", "102400", "--group-size", "1024",
                         "--columns", "32", "--learn-every", "3",
                         "--learn-full-until", "0", "--stagger-learn",
                         "--micro-chunk", "6", "--chunk-stagger",
                         "--ticks", "3600", "--pipeline-depth", "2",
                         "--dispatch-threads", "16",
                         "--startup-timeout", "1800",
                         "--out",
                         "reports/live_soak_100k_1h.json"], 6600.0),
    # lifecycle honesty: 900 ticks under the DEFAULT maturity window —
    # the cold-start fleet pays ~300 full-rate ticks (misses expected),
    # then the cadenced steady state must hold; production onboards
    # gradually and never pays the whole window at once
    ("r5_soak_100k_lifecycle", [sys.executable, "scripts/live_soak.py",
                                "--streams", "102400", "--group-size",
                                "1024", "--columns", "32",
                                "--learn-every", "4", "--stagger-learn",
                                "--micro-chunk", "4", "--chunk-stagger",
                                "--ticks", "900", "--pipeline-depth", "2",
                                "--dispatch-threads", "16",
                                "--startup-timeout", "1800",
                                "--out",
                                "reports/live_soak_100k_lifecycle.json"],
     3600.0),
    # ---------------- round 6 (ISSUE 3: close the latency-bound gap) ----
    # Most-valuable-first: (1) the silicon profile_r06 — re-measures the
    # full-rate number UNDER the fused-region consolidation and commits
    # the per-region HLO extraction the round's analysis cites (this run
    # OVERWRITES reports/profile_r06.json, replacing the CPU-labeled
    # stand-in artifact with silicon — exactly the intended upgrade);
    # (2) the megakernel A/B at the preset width and the headline width
    # (RTAP_TM_SCATTER=pallas; a Mosaic compile failure or VMEM overrun is
    # a MEASURED negative result — the step log is the evidence either
    # way, same protocol as the r4 candidates); (3) a fresh bench, whose
    # ladder now carries the pallas rung and appends the full-rate trend
    # entry to reports/trend_rung.json.
    ("profile_r06", [sys.executable, "scripts/profile_step.py", "--T", "32",
                     "--gs", "1024", "--layout", "flat",
                     "--report", "reports/profile_r06.json"], 1500.0),
    ("profile_mega", [sys.executable, "scripts/profile_step.py", "--T", "32",
                      "--gs", "1024", "--layout", "flat",
                      "--scatter", "pallas"], 1500.0),
    ("profile_mega_32col", [sys.executable, "scripts/profile_step.py",
                            "--T", "32", "--gs", "1024", "--layout", "flat",
                            "--columns", "32", "--scatter", "pallas"],
     1500.0),
    ("r6_bench", [sys.executable, "bench.py"], 1700.0),
    ("r6_trend_rung", [sys.executable, "scripts/trend_rung.py"], 1500.0),
    # ---------------- round 7 (ISSUE 4: tracing + flight recorder) ----
    # Paired host+device timelines of the SAME 100-tick serve window at
    # the production multi-group shape: jax.profiler.trace captures the
    # XLA device trace (TensorBoard/Perfetto-loadable, under
    # hw_results/device_trace_r07/) while serve's span recorder writes
    # the host timeline (hw_results/host_trace_r07.json) — the first
    # artifact that can attribute a missed tick to device compute vs the
    # dispatch RPC wall vs host phases on silicon. The flight recorder
    # flies armed so any quarantine/miss-burst during the window leaves
    # a bundle next to the traces. 100 ticks keeps the device trace file
    # small enough to commit; budget covers init + warm-up + the window.
    ("r7_device_trace", [sys.executable, "scripts/live_soak.py",
                         "--streams", "4096", "--group-size", "1024",
                         "--columns", "32", "--learn-every", "2",
                         "--stagger-learn", "--ticks", "100",
                         "--pipeline-depth", "2", "--dispatch-threads", "4",
                         "--jax-trace", "hw_results/device_trace_r07",
                         "--trace-out", "hw_results/host_trace_r07.json",
                         "--postmortem-dir", "hw_results/postmortems_r07",
                         "--startup-timeout", "900",
                         "--out", "reports/live_soak_trace_r07.json"],
     2400.0),
    # ---------------- round 8 (ISSUE 5: crash-consistent durability) ----
    # Real-clock supervised kill-9 soak at the production shape: a
    # journaled + checkpointed serve child over the seeded feed is
    # SIGKILLed 10 times at journal-observed ticks and restarted by the
    # real Supervisor; the verdict (exit 5 on failure) is final model
    # state bit-identical to the fault-free run and the concatenated
    # alert stream exactly-once (zero duplicated / zero lost alert_ids).
    # The committed report carries the silicon catch-up numbers the docs
    # cite: per-restart journal replay ticks + wall seconds (how long a
    # crashed chip takes to be back at the live edge) and the torn-tail
    # truncation count. 600 ticks at 1 s cadence ~ 10 min fault-free;
    # the budget covers the reference run + 10 restart cycles, each
    # paying jax init + compile-cache-warm startup on top of replay.
    ("r8_crash_soak", [sys.executable, "scripts/crash_soak.py",
                       "--seed", "8", "--kills", "10",
                       "--streams", "4096", "--group-size", "1024",
                       "--ticks", "600", "--cadence", "1.0",
                       "--checkpoint-every", "60", "--backend", "tpu",
                       "--threshold", "0.5", "--journal-fsync", "every-64",
                       "--out", "reports/crash_soak_r08.json"],
     3600.0),
    # ---------------- round 9 (ISSUE 6: model-health observability) ----
    # The health-reducer silicon numbers the docs cite: the same
    # 4096x1024 production soak shape as r7, with the fused on-device
    # health reducers armed. Evidence harvested from the run's obs
    # snapshot + stats line: (1) OVERHEAD — tick latency percentiles and
    # missed-deadline count vs the r7 baseline quantify what the ~200 B/
    # group/tick reducer pass costs inside the compiled step (the CPU
    # path is proven bit-exact and <= 1%-host-fold in tier-1; the
    # device-side region cost only silicon can price); (2) OCCUPANCY —
    # the fleet's real segment-pool occupancy histogram at steady state,
    # the first measured input to ROADMAP-3 pool right-sizing. The
    # flight recorder flies armed so any pool_saturated/score_drift
    # incident during the window leaves a bundle with the scorecard
    # embedded.
    ("r9_health", [sys.executable, "scripts/live_soak.py",
                   "--streams", "4096", "--group-size", "1024",
                   "--columns", "32", "--learn-every", "2",
                   "--stagger-learn", "--ticks", "300",
                   "--pipeline-depth", "2", "--dispatch-threads", "4",
                   "--health",
                   "--postmortem-dir", "hw_results/postmortems_r09",
                   "--startup-timeout", "900",
                   "--out", "reports/live_soak_health_r09.json"],
     2400.0),
    # ---------------- round 10 (ISSUE 7: wire-speed binary ingest) -----
    # Silicon soak at the new ingest ceiling: the same 4096x1024
    # production shape as r9, fed through serve --ingest-port (RB1
    # binary batch frames, one vectorized frame per feeder tick — the
    # host-side ingest edge that bounded the 100k soak at ~102k
    # metrics/s is off the critical path; reports/ingest_r07.json holds
    # the host-only microbench: >=5x the JSONL TCP path, multi-M
    # rows/s). Health + flight armed like r9 so the run doubles as the
    # regression baseline for both; the artifact's ingest counters
    # (frames/rows/garbage/backpressure, snapshot rtap_obs_ingest_*)
    # say data flowed clean at cadence on silicon.
    ("r10_ingest", [sys.executable, "scripts/live_soak.py",
                    "--binary-ingest",
                    "--streams", "4096", "--group-size", "1024",
                    "--columns", "32", "--learn-every", "2",
                    "--stagger-learn", "--ticks", "300",
                    "--pipeline-depth", "2", "--dispatch-threads", "4",
                    "--health",
                    "--postmortem-dir", "hw_results/postmortems_r10",
                    "--startup-timeout", "900",
                    "--out", "reports/live_soak_ingest_r10.json"],
     2400.0),
    # ---------------- round 11 (ISSUE 8: hot-standby failover) --------
    # Real-clock failover soak at production cadence on the silicon
    # host (the PAIR is cpu-oracle here — two serve processes cannot
    # share the one chip; the device-mesh pair is ROADMAP-1's follow-
    # up): 2 SIGKILLs of the live leader + the SIGSTOP fence round at
    # 1 s cadence with a 5 s lease. The committed report carries the
    # real-host takeover numbers the runbook cites: per-takeover
    # detect_ticks (budget <= 10), promotion splice sizes
    # (re_emitted/suppressed), and the fenced zombie's refused-write
    # count. Budget covers the reference run + the HA run with three
    # restart cycles at 1 s ticks.
    ("r11_failover", [sys.executable, "scripts/failover_soak.py",
                      "--seed", "8", "--kills", "2",
                      "--streams", "96", "--group-size", "32",
                      "--ticks", "420", "--cadence", "1.0",
                      "--checkpoint-every", "30", "--backend", "cpu",
                      "--lease-timeout", "5.0",
                      "--out", "reports/failover_soak_r11.json"],
     3600.0),
    # ---------------- round 12 (ISSUE 9: workload breadth) ------------
    # The composite multi-field encoder on silicon with incident
    # correlation armed: the seeded cascading-fault soak (exactly ONE
    # cluster-level incident, kill-9 identical incident stream, bit-
    # identical state) at a 4-service topology, scored through the
    # {value, delta, event-class} fused-SDR device encoder. What only
    # silicon can price: the per-field encode kernels (three disjoint
    # layout segments vs one uniform RDSE) inside the compiled step at
    # real cadence, and the correlator fold riding the 1 s tick on the
    # hw host. --threshold 0.04 is the composite contrast point (the
    # fused SDR spreads novelty over three fields, flattening the
    # likelihood profile; see workload_soak --threshold help; cpu-
    # measured burst ~0.07-0.09 vs healthy ~0.02). Budget covers the
    # reference + crash runs at 1 s ticks plus compile.
    ("r12_workloads", [sys.executable, "scripts/workload_soak.py",
                       "--seed", "9", "--kills", "2",
                       "--preset", "composite", "--threshold", "0.04",
                       "--services", "4", "--nodes-per-service", "4",
                       "--group-size", "16", "--ticks", "420",
                       "--cadence", "1.0", "--checkpoint-every", "30",
                       "--backend", "tpu",
                       "--out", "reports/workload_soak_r12.json"],
     3600.0),
    # ---------------- round 13 (ISSUE 11: detection-latency SLOs) -----
    # The real-time headline on silicon: the r9/r10 production soak
    # shape with detection-latency tracking + declared SLOs armed. The
    # live feeder stamps rows with the host wall clock, so the e2e
    # detect sketch (source ts -> alert-sink flush) is the TRUE
    # detection latency of the served fleet at 1 s cadence — the first
    # measured number behind ROADMAP-2's "sub-second detection" premium
    # tier (the fault-eval's median 1-2 s is model latency; this is the
    # whole pipeline). detect=2s@p99 is the launch contract, tick=1s@p99
    # the cadence contract; a burn dumps a postmortem whose summary
    # embeds the waterfall, and the committed report carries the full
    # per-stage quantiles + the SLO verdict. --threshold 0.35 densifies
    # alert traffic enough to fill the detect sketch without drowning
    # the sink (cpu-measured alert rate at the sine feed).
    ("r13_latency", [sys.executable, "scripts/live_soak.py",
                     "--streams", "4096", "--group-size", "1024",
                     "--columns", "32", "--learn-every", "2",
                     "--stagger-learn", "--ticks", "300",
                     "--pipeline-depth", "2", "--dispatch-threads", "4",
                     "--threshold", "0.35",
                     "--latency", "--slo", "detect=2s@p99",
                     "--slo", "tick=1s@p99",
                     "--postmortem-dir", "hw_results/postmortems_r13",
                     "--startup-timeout", "900",
                     "--out", "reports/live_soak_latency_r13.json"],
     2400.0),
    # ---------------- round 15 (ISSUE 16: predictive horizon) ---------
    # The title claim on silicon: the committed cpu cascade gate
    # (reports/predict_r15.json — precursor ramp at the origin node,
    # lagged step faults downstream, win = page BEFORE the second
    # node's onset with the blast radius covered and zero false
    # precursors) re-measured with the predict reducer fused into the
    # compiled step on real HBM. Same seed/shape as the cpu artifact so
    # the two reports diff leaf-for-leaf; the eval exits 5 on any gate
    # failure, so a red step here is a real regression, not noise.
    # Budget covers compile + 400 ticks + the eval fold.
    ("r15_predict", [sys.executable, "scripts/predict_eval.py",
                     "--seed", "0", "--ticks", "400",
                     "--backend", "tpu",
                     "--out", "reports/predict_hw_r15.json"],
     1800.0),
    # ---------------- round 16 (ISSUE 18: sparse synapse pools) -------
    # First silicon numbers for the member-index SP layout: the profiler
    # at the bench's measured-optimal rung (G=1024, T=32), sweeping the
    # kernel strategies on the NEW default (sparse gather overlap +
    # S=2 TM lanes, 302,101 B/stream u16 vs 564,245 dense). The CPU
    # path is proven bit-exact against the oracle twins
    # (tests/parity/test_sparse_sp.py); this step answers the only open
    # question — whether the O(C*P) VPU gather beats the O(C*n_in) MXU
    # matmul on real HBM at the roofline (docs/KERNELS.md), and how far
    # the smaller state pushes the G-sweep OOM frontier.
    ("r16_sparse", [sys.executable, "scripts/profile_step.py",
                    "--T", "32", "--gs", "1024",
                    "--perm-bits", "16",
                    "--report", "hw_results/profile_sparse_r16.json"],
     1800.0),
]


def step_budget(step: tuple, default: float) -> float:
    """STEPS entries are (name, cmd) or (name, cmd, budget)."""
    return step[2] if len(step) > 2 else default


def pick_steps(spec: str | None) -> list[tuple]:
    """Resolve a --steps '1,5,7' spec (1-based) against STEPS, loudly."""
    if not spec:
        return STEPS
    picked = []
    for tok in spec.split(","):
        i = int(tok)
        if not 1 <= i <= len(STEPS):
            raise SystemExit(
                f"--steps: {i} out of range (steps are 1..{len(STEPS)})"
            )
        picked.append(STEPS[i - 1])
    return picked


def obs_snapshot_path(name: str) -> str:
    """Per-step telemetry snapshot sink (rtap_tpu.obs JSONL). Children
    inherit it via $RTAP_OBS_SNAPSHOT: serve writes its final registry
    snapshot there (directly, or through live_soak's pass-through), so the
    session ledger reads structured tick/deadline facts instead of
    scraping stdout lines out of the step log."""
    return os.path.join(OUT, f"{name}.obs.jsonl")


def run_step(name: str, cmd: list[str], budget: float) -> int:
    """One step attempt; stdout+stderr -> hw_results/<name>.log (overwrite).

    The step runs in its own session and a timeout kills the whole process
    GROUP: steps spawn grandchildren (`python -m rtap_tpu serve`, bench's
    attempt subprocesses) that must not outlive the timeout holding the TPU
    (and, historically, a fixed TCP port). Shared by hw_watch.py — kill
    semantics must not diverge between the one-shot and harvest runners."""
    import signal

    path = os.path.join(OUT, f"{name}.log")
    snap = obs_snapshot_path(name)
    try:
        os.remove(snap)  # fresh run, fresh telemetry (matches the log overwrite)
    except OSError:
        pass
    with open(path, "w") as f:
        proc = subprocess.Popen(cmd, cwd=REPO, stdout=f, stderr=subprocess.STDOUT,
                                start_new_session=True,
                                env={**os.environ, "RTAP_OBS_SNAPSHOT": snap})
        try:
            return proc.wait(timeout=budget)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                proc.kill()
            proc.wait()
            return -1


def log_tail(name: str, limit: int = 140) -> str:
    """Last nonempty line of a step's log, for one-line verdicts."""
    try:
        lines = [l.strip() for l in
                 open(os.path.join(OUT, f"{name}.log")).read().splitlines()
                 if l.strip()]
        return lines[-1][:limit] if lines else ""
    except OSError:
        return ""


def obs_tail(name: str) -> str:
    """Compact telemetry verdict from the step's obs snapshot (empty when
    the step emitted none — profiles and evals don't run the serve loop)."""
    from rtap_tpu.obs import read_last_snapshot, summarize_snapshot

    snap = read_last_snapshot(obs_snapshot_path(name))
    if snap is None:
        return ""
    s = summarize_snapshot(snap)
    parts = []
    for key, label in (("rtap_obs_ticks_total", "ticks"),
                       ("rtap_obs_missed_ticks_total", "missed"),
                       ("rtap_obs_scored_total", "scored"),
                       ("rtap_obs_alerts_total", "alerts"),
                       ("rtap_obs_routing_rebuilds_total", "rebuilds")):
        v = s.get(key)
        if v:
            parts.append(f"{label}={int(v)}")
    tick = s.get("rtap_obs_tick_seconds") or {}
    if tick.get("count"):
        parts.append(f"tick_mean={tick['mean'] * 1e3:.1f}ms"
                     f" tick_max={tick['max'] * 1e3:.0f}ms")
    return " ".join(parts)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--budget-per-step", type=float, default=600.0)
    ap.add_argument("--steps", default=None,
                    help="comma-separated 1-based step numbers (default all)")
    args = ap.parse_args()
    picked = pick_steps(args.steps)

    os.makedirs(OUT, exist_ok=True)
    for step in picked:
        name, cmd = step[0], step[1]
        budget = max(step_budget(step, args.budget_per_step), args.budget_per_step)
        log(f"step {name}: {' '.join(cmd[1:])} (budget {budget:.0f}s)")
        t0 = time.monotonic()
        rc = run_step(name, cmd, budget)
        dt = time.monotonic() - t0
        log(f"step {name}: rc={rc} in {dt:.0f}s — {log_tail(name)}")
        obs = obs_tail(name)
        if obs:
            log(f"step {name}: obs {obs}")


if __name__ == "__main__":
    main()
