"""Run the full on-hardware measurement agenda in one tunnel-up window.

The TPU tunnel oscillates (SCALING.md): it can be reachable for minutes and
then hang backend init for an hour. When it IS up, this script spends the
window optimally — every step is a subprocess with its own wall budget (a
hang costs one step, not the session), ordered most-valuable-first:

1. component ablation profile (where does the tick go?)         [matmul]
2. the same under --scatter indexed  (workspace-movement A/B)
3. the same under --pallas           (fused dendrite-kernel A/B)
4. scaling_law G-sweep               (fills SCALING.md's table)
5. bench.py                          (the headline number)

Logs land in hw_results/<step>.log; a one-line verdict per step prints to
stderr as it completes. Re-runs skip nothing (fresh measurements overwrite).

Usage:  python scripts/hw_session.py [--budget-per-step 600] [--steps 1,2,5]
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "hw_results")


def log(msg: str) -> None:
    print(f"[hw_session] {msg}", file=sys.stderr, flush=True)


STEPS: list[tuple[str, list[str]]] = [
    ("layout_probe", [sys.executable, "scripts/layout_probe.py"]),
    ("profile_matmul", [sys.executable, "scripts/profile_step.py", "--T", "32",
                        "--gs", "1024"]),
    ("profile_indexed", [sys.executable, "scripts/profile_step.py", "--T", "32",
                         "--gs", "1024", "--scatter", "indexed"]),
    ("profile_pallas", [sys.executable, "scripts/profile_step.py", "--T", "32",
                        "--gs", "1024", "--pallas"]),
    ("profile_f32_indexed", [sys.executable, "scripts/profile_step.py", "--T", "32",
                             "--gs", "1024", "--perm-bits", "0",
                             "--scatter", "indexed"]),
    ("profile_flat", [sys.executable, "scripts/profile_step.py", "--T", "32",
                      "--gs", "1024", "--layout", "flat"]),
    ("profile_flat_indexed", [sys.executable, "scripts/profile_step.py", "--T", "32",
                              "--gs", "1024", "--layout", "flat",
                              "--scatter", "indexed"]),
    # round-4 strategies: compact punish/death sweep; forward-index dendrite
    # (both fwd histogram impls); the stacked best-guess candidate
    ("profile_sweep_compact", [sys.executable, "scripts/profile_step.py", "--T", "32",
                               "--gs", "1024", "--scatter", "indexed",
                               "--sweep", "compact"]),
    ("profile_fwd_scatter", [sys.executable, "scripts/profile_step.py", "--T", "32",
                             "--gs", "1024", "--scatter", "indexed",
                             "--dendrite", "forward"]),
    ("profile_fwd_matmul", [sys.executable, "scripts/profile_step.py", "--T", "32",
                            "--gs", "1024", "--scatter", "indexed",
                            "--dendrite", "forward", "--fwd-impl", "matmul"]),
    ("profile_fwd_flat", [sys.executable, "scripts/profile_step.py", "--T", "32",
                          "--gs", "1024", "--layout", "flat",
                          "--scatter", "indexed", "--dendrite", "forward"]),
    ("pipeline_gain", [sys.executable, "scripts/pipeline_gain.py"]),
    ("nab_corpus", [sys.executable, "scripts/nab_standin_report.py"]),
    ("scaling_sweep", [sys.executable, "scripts/scaling_law.py"]),
    # bench subprocess-isolates its own attempts under BENCH_BUDGET_S=1500;
    # the step budget must exceed that or the runner would SIGKILL it before
    # its own SIGTERM-emit path can print the result line
    ("bench", [sys.executable, "bench.py"], 1700.0),
    # round-4 service-shape experiments (verdict weak #3 / #7); the soak is
    # startup (up to ~300 s compile) + a >= 5 min paced loop by design
    ("multigroup", [sys.executable, "scripts/multigroup_sched.py"], 1200.0),
    ("live_soak", [sys.executable, "scripts/live_soak.py"], 1500.0),
]


def step_budget(step: tuple, default: float) -> float:
    """STEPS entries are (name, cmd) or (name, cmd, budget)."""
    return step[2] if len(step) > 2 else default


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--budget-per-step", type=float, default=600.0)
    ap.add_argument("--steps", default=None,
                    help="comma-separated 1-based step numbers (default all)")
    args = ap.parse_args()
    picked = (
        [STEPS[int(i) - 1] for i in args.steps.split(",")] if args.steps else STEPS
    )

    os.makedirs(OUT, exist_ok=True)
    for step in picked:
        name, cmd = step[0], step[1]
        budget = max(step_budget(step, args.budget_per_step), args.budget_per_step)
        path = os.path.join(OUT, f"{name}.log")
        log(f"step {name}: {' '.join(cmd[1:])} (budget {budget:.0f}s)")
        t0 = time.monotonic()
        with open(path, "w") as f:
            # own session + group kill: steps spawn grandchildren (serve,
            # bench attempts) that must not outlive a timeout holding the TPU
            proc = subprocess.Popen(cmd, cwd=REPO, stdout=f,
                                    stderr=subprocess.STDOUT, start_new_session=True)
            try:
                rc = proc.wait(timeout=budget)
            except subprocess.TimeoutExpired:
                import signal

                try:
                    os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    proc.kill()
                proc.wait()
                rc = -1
        dt = time.monotonic() - t0
        tail = ""
        try:
            lines = [l.strip() for l in open(path).read().splitlines() if l.strip()]
            tail = lines[-1][:140] if lines else ""
        except OSError:
            pass
        log(f"step {name}: rc={rc} in {dt:.0f}s — {tail}")


if __name__ == "__main__":
    main()
