"""Failover soak: kill-9 the LEADER of a hot-standby pair; prove takeover.

ISSUE 8 acceptance surface. Two symmetric serve children share an alert
sink, a checkpoint dir, and a leadership lease; whichever holds the
lease runs the seeded deterministic feed as leader, journals every tick,
and ships the journal stream to the other (the standby), which applies
every tick through the normal scoring path and emits nothing. A seeded
killer SIGKILLs the CURRENT leader at journal-observed ticks; the
standby promotes on lease staleness (bumping the fencing epoch,
splicing the alert stream exactly-once, checkpointing its warm fleet)
and the killed process is restarted as the new standby — roles swap per
kill. One extra round SIGSTOPs the leader instead: the standby promotes
while the old leader is merely paused, and on SIGCONT the zombie must
discover the fence, append NOTHING to the alert sink, and exit
``FENCED_RC``. The run FAILS (exit 5) unless:

- the final checkpoint state (every orbax leaf of every group) is
  BIT-IDENTICAL to a fault-free single-process run over the same
  seeded feed,
- the spliced alert stream is exactly-once vs the fault-free run —
  zero duplicated, zero lost ``alert_id``s, per-id records equal,
- every takeover detected within the tick budget
  (``standby_promoted.detect_ticks`` <= ``--takeover-budget``, default
  10),
- the SIGSTOP round's zombie leader exited ``FENCED_RC`` with its
  fence-dropped line count recorded (it provably appended nothing).

In-tree smoke: K=2 kills + the fence round at tiny config
(tests/integration/test_failover.py, cpu backend). Silicon: the queued
``r11_failover`` hw_session step.

Usage: python scripts/failover_soak.py --seed 0 --kills 2 [--streams 6]
       [--group-size 3] [--ticks 96] [--cadence 0.05]
       [--checkpoint-every 7] [--backend cpu] [--lease-timeout 0.3]
       [--workdir DIR] [--out report.json] [--no-fence-round]
"""

from __future__ import annotations

import argparse
import json
import os
import random
import signal
import socket
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from rtap_tpu.utils.platform import maybe_force_cpu  # noqa: E402
from scripts.fleet_verdict import (  # noqa: E402
    final_tick_check,
    promotion_epoch_truth,
    reconcile_alert_counters,
    takeover_sequence,
)

VERIFY_FAILED_EXIT = 5
INFRA_FAILED_EXIT = 3


def log(msg: str) -> None:
    print(f"[failover] {msg}", file=sys.stderr, flush=True)


# ---------------------------------------------------------------- child
def run_child(args) -> int:
    """One HA serve-process lifetime: decide role from the lease, follow
    (standby) until promoted or stopped, then serve the remaining ticks
    of the total budget as leader — journaled, checkpointed, replicated
    to the peer, fenced by the lease. ``--ref`` runs the plain
    single-process reference instead (no lease, no replication)."""
    maybe_force_cpu()

    import threading

    import numpy as np

    from rtap_tpu.config import cluster_preset
    from rtap_tpu.resilience import (
        FENCED_RC,
        Lease,
        ReplicationSender,
        StandbyFollower,
        TickJournal,
    )
    from rtap_tpu.service.checkpoint import peek_resume_ticks
    from rtap_tpu.service.loop import live_loop
    from rtap_tpu.service.registry import StreamGroupRegistry

    # warm orbax BEFORE touching the lease: its first import (tensorstore
    # C init) holds the GIL for seconds on a 1-core host, and a lease
    # heartbeat starved through the first checkpoint round would read as
    # a dead leader to the peer (a false takeover)
    import orbax.checkpoint  # noqa: F401

    w = args.workdir
    os.makedirs(w, exist_ok=True)
    alerts = os.path.join(w, "alerts.jsonl")
    ckdir = os.path.join(w, "ck")
    jdir = os.path.join(w, "journal" if args.ref
                        else f"journal-{args.name}")
    journal = TickJournal(jdir)

    ids = [f"n{i // 3}.m{i % 3}" for i in range(args.streams)]
    reg = StreamGroupRegistry(cluster_preset(), group_size=args.group_size,
                              backend=args.backend,
                              threshold=args.threshold, debounce=1)
    for sid in ids:
        reg.add_stream(sid)
    reg.finalize()

    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())

    lease = None
    resume_sup = None
    promote_info = None
    fleet_pub = None
    if not args.ref and args.fleet_port:
        # fleet observability plane (ISSUE 19): this child is a member;
        # the parent's in-process aggregator reads the verdict evidence
        # (DOWN -> role_changed sequence, merged counters/SLO) through
        # the plane instead of scraping per-child artifacts. Push faster
        # than the takeover window so event ORDER is evidence.
        from rtap_tpu.fleet import FleetPublisher

        fleet_pub = FleetPublisher(
            ("127.0.0.1", args.fleet_port), args.name, role="standby",
            push_interval_s=max(0.02, args.cadence / 2))
    if not args.ref:
        lease = Lease(os.path.join(w, "lease"), owner=args.name,
                      timeout_s=args.lease_timeout)
        cur = lease.read()
        fresh_other = (cur is not None and cur.get("owner") != args.name
                       and not lease._stale(cur))
        # --follow pins the intended role: a child the harness spawned
        # as a standby must never sniff a momentarily-stale lease (the
        # live leader mid-GIL-stall under host load) and come up as a
        # second leader — it FOLLOWS, and earns leadership only through
        # the promotion path (which fences the other side properly)
        if args.follow or fresh_other or not lease.try_acquire():
            if fleet_pub is not None:
                fleet_pub.start()  # the standby phase is on the plane too
            follower = StandbyFollower(
                reg, journal, lease=lease, port=args.listen,
                alert_path=alerts, checkpoint_dir=ckdir,
                cadence_s=args.cadence, stop_event=stop)
            log(f"{args.name}: standby following on :{args.listen}")
            outcome = follower.run()
            if outcome == "stopped":
                journal.close()
                if fleet_pub is not None:
                    fleet_pub.close()  # orderly BYE: "left", not DOWN
                return 0
            resume_sup = follower.resume_suppression
            promote_info = {
                "detect_s": round(follower.promote_detect_s, 3),
                "epoch": lease.epoch,
                "re_emitted": follower.promote_re_emitted,
                "suppressed": follower.promote_suppressed,
            }
            log(f"{args.name}: PROMOTED at epoch {lease.epoch} "
                f"(detect {follower.promote_detect_s:.3f}s)")
        # leadership liveness = PROCESS alive: the heartbeat thread
        # keeps the lease fresh through multi-second checkpoint rounds
        lease.start_heartbeat()
        if fleet_pub is not None:
            # promotion (or immediate leadership): same member, new
            # role, the lease epoch the parent checks against truth.
            # start() is idempotent — the standby path already pushes.
            fleet_pub.set_role("leader", lease_epoch=lease.epoch)
            fleet_pub.start()

    base = max(journal.next_tick, peek_resume_ticks(ckdir))
    n_eff = max(0, args.ticks - base)
    if fleet_pub is not None:
        fleet_pub.set_tick_base(base)  # report journal-GLOBAL progress

    sender = None
    if not args.ref:
        sender = ReplicationSender(("127.0.0.1", args.peer), journal,
                                   checkpoint_dir=ckdir).start()
        journal.tee = sender.tee
        journal.compact_floor = sender.compact_floor

    def source(k: int):
        g = base + k  # the feed depends only on the GLOBAL tick
        rng = np.random.Generator(np.random.Philox(key=(args.seed, g)))
        v = (30 + 5 * rng.random(len(ids))).astype(np.float32)
        if args.spike_every and g % args.spike_every == 0:
            v[(g // args.spike_every) % len(ids)] += 30.0
        return v, 1_700_000_000 + g

    # SLO verdict (ISSUE 11): per-tick host latency — the seeded feed's
    # synthetic epoch rules out the wall-anchored detect SLO here
    # (docs/SLO.md clock contract). The replication-ack lag rides the
    # tracker as a first-class gauge while this child leads.
    latency = slo = None
    if args.slo != "off":
        from rtap_tpu.obs.slo import tick_slo_pair

        latency, slo = tick_slo_pair(args.cadence, args.slo)
        if sender is not None:
            latency.lag_providers["repl_ack_ticks"] = \
                lambda _t, _ts: sender.ack_lag_ticks()
        if fleet_pub is not None:
            fleet_pub.attach(latency=latency, slo=slo)
    stats = live_loop(
        source, reg, n_ticks=n_eff, cadence_s=args.cadence,
        alert_path=alerts, checkpoint_dir=ckdir,
        checkpoint_every=args.checkpoint_every, journal=journal,
        lease=lease, stop_event=stop, resume_suppression=resume_sup,
        latency=latency, slo=slo, fleet=fleet_pub)
    if sender is not None:
        sender.close()
        journal.tee = None
    if lease is not None:
        lease.stop_heartbeat()
    journal.close()
    if fleet_pub is not None:
        fleet_pub.close()  # final-state flush + orderly BYE
    line = {"name": "ref" if args.ref else args.name, "base": base,
            "ran": stats["ticks"], "alerts": stats["alerts"],
            "fenced": bool(stats.get("fenced")),
            "fenced_line_drops": stats.get("fenced_line_drops", 0),
            "promoted": promote_info,
            "slo": stats.get("slo"),
            "repl_ack_lag": (stats.get("latency") or {}).get("lags")}
    if args.stats_out:
        with open(args.stats_out, "a") as f:
            f.write(json.dumps(line) + "\n")
            f.flush()
    print(json.dumps(line))
    if stats.get("fenced"):
        return FENCED_RC
    return 0


# --------------------------------------------------------------- parent
def _free_ports(n: int) -> list[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def child_cmd(args, workdir: str, name: str | None = None,
              listen: int = 0, peer: int = 0, ref: bool = False,
              follow: bool = False) -> list[str]:
    cmd = [sys.executable, os.path.abspath(__file__), "--child",
           "--workdir", workdir, "--seed", str(args.seed),
           "--ticks", str(args.ticks), "--streams", str(args.streams),
           "--group-size", str(args.group_size),
           "--cadence", str(args.cadence),
           "--checkpoint-every", str(args.checkpoint_every),
           "--backend", args.backend, "--threshold", str(args.threshold),
           "--lease-timeout", str(args.lease_timeout),
           "--spike-every", str(args.spike_every),
           "--stats-out", os.path.join(workdir, "stats.jsonl")]
    if args.slo is not None:
        cmd += ["--slo", args.slo]
    if ref:
        cmd.append("--ref")
    else:
        cmd += ["--name", name, "--listen", str(listen),
                "--peer", str(peer)]
        if follow:
            cmd.append("--follow")
        if getattr(args, "fleet_port", 0):
            cmd += ["--fleet-port", str(args.fleet_port)]
    return cmd


def _lease_owner(path: str) -> str | None:
    try:
        with open(path) as f:
            return json.load(f).get("owner")
    except (OSError, ValueError):
        return None


def _wait(cond, timeout_s: float, poll_s: float = 0.02) -> bool:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(poll_s)
    return False


def fleet_verdict(agg, args, observed: list, fence_report,
                  promotions: list, stats_lines: list,
                  failures: list[str]) -> dict:
    """Judge the FLEET-OBSERVED story against the lease/journal truth
    (ISSUE 19): every takeover must appear on the plane as the old
    leader going DOWN (staleness — a SIGKILLed process sends no BYE)
    followed by a ``role_changed`` to leader on the successor; the
    fleet-observed promotion epochs must equal the alert stream's
    ``standby_promoted`` epochs; the budget's completion and the
    completing leader's alert count must be visible through merged
    fleet state alone. The individual checks live in
    scripts/fleet_verdict.py, shared with crash_soak and fleet_chaos."""
    members = agg.members_view()
    events = agg.events_view()
    snaps = agg.member_snaps()
    fl_slo = agg.fleet_slo()

    # the observed failover sequence, one anchor per scheduled takeover
    anchors = [(k["killed"], k["new_leader"], "kill") for k in observed]
    if fence_report:
        anchors.append((fence_report["paused"],
                        fence_report["new_leader"], "fence"))
    checks = takeover_sequence(events, anchors, failures)
    fleet_epochs = promotion_epoch_truth(events, promotions, failures)
    final_tick = final_tick_check(members, args.ticks - 1, failures)

    reconciled = {}
    for line in stats_lines:
        nm = line.get("name")
        if nm not in snaps or line.get("fenced"):
            continue  # a fenced zombie's counters are fence-dropped
        reconciled[nm] = reconcile_alert_counters(
            snaps[nm], line.get("alerts"), f"member {nm}", failures)

    # fleet SLO comes from MERGED sketches (never max-of-member-p99s)
    if args.slo != "off":
        slos = fl_slo.get("slos") or []
        if not slos:
            failures.append("fleet plane carries no merged SLO verdict "
                            "despite armed SLOs")
        elif any(v.get("observed_quantile_s") is None
                 for v in slos if v.get("samples")):
            failures.append("fleet SLO verdict lacks a merged-sketch "
                            "observed quantile")

    return {
        "members": [{k: m.get(k) for k in ("member", "state", "role",
                                           "lease_epoch", "tick",
                                           "snapshots")}
                    for m in members],
        "sequence": checks,
        "promotion_epochs": fleet_epochs,
        "final_tick": final_tick,
        "counters_reconciled": reconciled,
        "events_total": len(events),
        "slo": fl_slo,
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--kills", type=int, default=2,
                    help="SIGKILLs delivered to the CURRENT leader at "
                         "seeded journal-observed ticks (>= 2 for the "
                         "acceptance bar)")
    ap.add_argument("--streams", type=int, default=6)
    ap.add_argument("--group-size", type=int, default=3)
    ap.add_argument("--ticks", type=int, default=96,
                    help="TOTAL tick budget across takeovers")
    ap.add_argument("--cadence", type=float, default=0.25,
                    help="tick cadence; the takeover budget is in TICKS "
                         "of this cadence, so very small values make "
                         "host scheduling jitter dominate the budget")
    ap.add_argument("--checkpoint-every", type=int, default=7)
    ap.add_argument("--backend", default="cpu")
    ap.add_argument("--threshold", type=float, default=-1e9,
                    help="floor default = every scored tick is an alert "
                         "line, the densest exactly-once check")
    ap.add_argument("--lease-timeout", type=float, default=None,
                    help="lease staleness before the standby promotes "
                         "(default: 4 * cadence — detection = timeout "
                         "+ heartbeat age + poll, which must land "
                         "inside the 10-tick takeover budget)")
    ap.add_argument("--takeover-budget", type=int, default=10,
                    help="max takeover detection latency in ticks")
    ap.add_argument("--spike-every", type=int, default=13)
    ap.add_argument("--slo", default=None, metavar="NAME=TARGET@pQ",
                    help="latency SLO every serving child defends and "
                         "the report records a verdict for (default: "
                         "tick=<cadence>s@p99; 'off' disables — see "
                         "docs/SLO.md clock contract for why detect "
                         "SLOs don't apply to the seeded feed)")
    ap.add_argument("--fence-round",
                    action=argparse.BooleanOptionalAction, default=True,
                    help="add a SIGSTOP/SIGCONT round proving a paused "
                         "old leader is fenced out of the alert sink")
    ap.add_argument("--fleet",
                    action=argparse.BooleanOptionalAction, default=True,
                    help="host a fleet aggregator in the parent and make "
                         "every HA child a fleet member: the takeover "
                         "verdict (leader DOWN -> standby promoted at "
                         "the successor epoch), merged counters, and "
                         "the fleet SLO are then read through the fleet "
                         "plane and judged against the lease/journal "
                         "truth (docs/FLEET.md)")
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--out", default=None, help="report JSON path")
    # child-mode flags
    ap.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--ref", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--follow", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--name", default="A", help=argparse.SUPPRESS)
    ap.add_argument("--listen", type=int, default=0, help=argparse.SUPPRESS)
    ap.add_argument("--peer", type=int, default=0, help=argparse.SUPPRESS)
    ap.add_argument("--fleet-port", type=int, default=0,
                    help=argparse.SUPPRESS)
    ap.add_argument("--stats-out", default=None, help=argparse.SUPPRESS)
    args = ap.parse_args()
    if args.lease_timeout is None:
        # detection after a death = 1.5 * timeout (the follower's
        # staleness-persistence grace) + heartbeat age (timeout/3)
        # + staleness poll + host scheduling jitter; 4 * cadence lands
        # at ~8 ticks of the 10-tick budget with jitter headroom, and
        # the grace absorbs single starved-heartbeat reads
        args.lease_timeout = 4 * args.cadence
    if args.child:
        return run_child(args)

    from rtap_tpu.resilience import FENCED_RC, last_journal_tick
    from scripts.crash_soak import compare_states, parse_alert_stream

    workdir = args.workdir or tempfile.mkdtemp(prefix="failover_soak_")
    ref_dir = os.path.join(workdir, "ref")
    ha_dir = os.path.join(workdir, "ha")
    os.makedirs(ref_dir, exist_ok=True)
    os.makedirs(ha_dir, exist_ok=True)
    t_all = time.monotonic()
    failures: list[str] = []

    # 1. fault-free single-process reference over the identical feed
    log(f"reference run ({args.ticks} ticks, {args.streams} streams, "
        f"backend {args.backend})")
    rc = subprocess.run(child_cmd(args, ref_dir, ref=True)).returncode
    if rc != 0:
        log(f"FATAL: reference run failed rc={rc}")
        return INFRA_FAILED_EXIT

    # 2. the HA pair: A first (acquires the lease), then B (standby).
    # The parent hosts the fleet aggregator IN-PROCESS (Python API, no
    # HTTP hop): verdict evidence arrives through the plane.
    agg = None
    if args.fleet:
        from rtap_tpu.fleet import FleetAggregator

        agg = FleetAggregator(
            port=0,
            sweep_interval_s=max(0.02, min(0.2, args.cadence))).start()
        args.fleet_port = agg.port
        log(f"fleet aggregator on :{agg.port} (sweep "
            f"{agg.sweep_interval_s}s)")
    ports = dict(zip("AB", _free_ports(2)))
    lease_path = os.path.join(ha_dir, "lease")

    def spawn(name: str, follow: bool = True) -> subprocess.Popen:
        other = "B" if name == "A" else "A"
        return subprocess.Popen(child_cmd(
            args, ha_dir, name=name, listen=ports[name],
            peer=ports[other], follow=follow))

    procs = {"A": spawn("A", follow=False)}
    if not _wait(lambda: _lease_owner(lease_path) == "A", 120.0):
        log("FATAL: A never acquired the lease")
        return INFRA_FAILED_EXIT
    procs["B"] = spawn("B")
    unscheduled_fences: list[str] = []

    def reap() -> str | None:
        """An UNSCHEDULED fenced exit (rc FENCED_RC) is legitimate lease
        behavior under host scheduling jitter — a starved heartbeat read
        as a death, the standby promoted, the fence held, and the same
        exactly-once machinery governs the splice (it is verified by the
        final verdict either way). Respawn the fenced child as the new
        standby and carry on; any OTHER unexpected death is fatal."""
        from rtap_tpu.resilience import FENCED_RC as _F

        for nm, pp in list(procs.items()):
            rc = pp.poll()
            if rc is None or rc == 0:
                continue
            if rc == _F:
                unscheduled_fences.append(nm)
                log(f"{nm} fenced by an unscheduled takeover (host "
                    "jitter) — respawning as standby")
                procs[nm] = spawn(nm)
            else:
                return f"child {nm} died unexpectedly rc={rc}"
        return None

    # 3. seeded kill schedule over the middle of the run + fence round
    rng = random.Random(args.seed)
    lo, hi = max(1, args.ticks // 5), max(2, args.ticks * 3 // 5)
    window = max(1, (hi - lo) // max(1, args.kills))
    targets = sorted(min(args.ticks - 8, lo + i * window
                         + rng.randrange(max(1, window // 2)))
                     for i in range(args.kills))
    fence_target = min(args.ticks - 4, args.ticks * 3 // 4) \
        if args.fence_round else None
    log(f"kill schedule (ticks): {targets}; fence round at "
        f"{fence_target}")

    observed: list[dict] = []
    fence_report: dict | None = None

    def leader_name() -> str | None:
        return _lease_owner(lease_path)

    def leader_reached(target: int) -> str | None:
        name = leader_name()
        if name not in procs:
            return None
        if last_journal_tick(os.path.join(ha_dir,
                                          f"journal-{name}")) >= target:
            return name
        return None

    for target in targets:
        hit: dict = {}

        def reached():
            err = reap()
            if err is not None:
                hit["dead"] = err
                return True
            name = leader_reached(target)
            if name is not None:
                hit["name"] = name
            return name is not None

        if not _wait(reached, 180.0):
            failures.append(f"killer missed target tick {target} "
                            f"(leader={leader_name()})")
            break
        if "dead" in hit:
            failures.append(hit["dead"])
            break
        name = hit["name"]
        p = procs[name]
        t_kill = time.monotonic()
        try:
            p.kill()  # SIGKILL: no cleanup, no flush
        except OSError:
            failures.append(f"could not SIGKILL leader {name}")
            break
        p.wait()
        log(f"killed leader {name} near tick {target}")
        if not _wait(lambda: leader_name() not in (None, name), 120.0):
            failures.append(
                f"standby never promoted after killing {name} at "
                f"tick {target}")
            break
        takeover_s = time.monotonic() - t_kill
        observed.append({"target": target, "killed": name,
                         "new_leader": leader_name(),
                         "takeover_wall_s": round(takeover_s, 3)})
        # the killed process rejoins as the new standby
        procs[name] = spawn(name)

    # 4. fence round: pause the leader, let the standby promote, resume
    # the zombie — it must fence itself out and exit FENCED_RC
    if args.fence_round and not failures:
        hit = {}

        def reached_f():
            err = reap()
            if err is not None:
                hit["dead"] = err
                return True
            name = leader_reached(fence_target)
            if name is not None:
                hit["name"] = name
            return name is not None

        if not _wait(reached_f, 180.0):
            failures.append(f"fence round missed target tick "
                            f"{fence_target} (leader={leader_name()})")
        elif "dead" in hit:
            failures.append(hit["dead"])
        else:
            name = hit["name"]
            p = procs[name]
            os.kill(p.pid, signal.SIGSTOP)
            log(f"SIGSTOPped leader {name} near tick {fence_target}")
            promoted = _wait(lambda: leader_name() not in (None, name),
                             120.0)
            os.kill(p.pid, signal.SIGCONT)
            if not promoted:
                failures.append("standby never promoted during the "
                                "fence round")
            else:
                try:
                    rc = p.wait(timeout=120.0)
                except subprocess.TimeoutExpired:
                    p.kill()
                    rc = p.wait()
                    failures.append(
                        f"paused old leader {name} never exited after "
                        "SIGCONT (fence did not bite)")
                fence_report = {"paused": name, "rc": rc,
                                "new_leader": leader_name()}
                if rc != FENCED_RC:
                    failures.append(
                        f"woken old leader {name} exited rc={rc}, "
                        f"expected FENCED_RC={FENCED_RC}")
                procs[name] = spawn(name)

    # 5. completion: the leader finishing the budget exits 0; stop the
    # remaining standby (SIGTERM -> orderly "stopped")
    done: dict = {}

    def budget_done():
        err = reap()
        if err is not None:
            done["err"] = err
            return True
        for name, p in procs.items():
            if p.poll() == 0:
                done["name"] = name
                return True
        return False

    if not _wait(budget_done, 300.0, poll_s=0.05):
        failures.append("no child completed the total tick budget")
    elif "err" in done:
        failures.append(done["err"])
    for name, p in procs.items():
        if p.poll() is None:
            p.terminate()
            try:
                p.wait(timeout=60.0)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
                failures.append(f"standby {name} ignored SIGTERM")

    # 6. verdict
    ref_alerts = parse_alert_stream(os.path.join(ref_dir, "alerts.jsonl"))
    got_alerts = parse_alert_stream(os.path.join(ha_dir, "alerts.jsonl"))
    if got_alerts["dup"]:
        failures.append(f"{len(got_alerts['dup'])} DUPLICATED "
                        f"alert_id(s): {got_alerts['dup'][:5]}")
    ref_ids = set(ref_alerts["alerts"])
    got_ids = set(got_alerts["alerts"])
    lost = sorted(ref_ids - got_ids)
    extra = sorted(got_ids - ref_ids)
    if lost:
        failures.append(f"{len(lost)} LOST alert_id(s): {lost[:5]}")
    if extra:
        failures.append(f"{len(extra)} EXTRA alert_id(s): {extra[:5]}")
    mismatched = [aid for aid in (ref_ids & got_ids)
                  if ref_alerts["alerts"][aid] != got_alerts["alerts"][aid]]
    if mismatched:
        failures.append(f"{len(mismatched)} alert record(s) differ: "
                        f"{mismatched[:5]}")
    if not ref_ids:
        failures.append("reference run emitted zero alerts — the soak "
                        "proves nothing (lower --threshold)")
    leaves = compare_states(os.path.join(ref_dir, "ck"),
                            os.path.join(ha_dir, "ck"), failures)
    promotions = [e for e in got_alerts["events"]
                  if e.get("event") == "standby_promoted"]
    # budget check anchored to the SCHEDULED takeovers: each kill and
    # the fence round must have a promotion near its target tick,
    # detected within budget. Unscheduled jitter-driven promotions (see
    # reap()) are reported but not budget-judged — the exactly-once and
    # state verdicts above govern them.
    anchors = [(k["target"], "kill") for k in observed]
    if fence_report:
        anchors.append((fence_target, "fence"))
    for target, kind in anchors:
        cand = [p for p in promotions
                if p.get("detect_ticks") is not None
                and abs(p["tick"] - target) <= args.takeover_budget + 6]
        if not cand:
            failures.append(f"no standby_promoted event near the {kind} "
                            f"at tick {target}")
            continue
        p = min(cand, key=lambda q: abs(q["tick"] - target))
        if p["detect_ticks"] > args.takeover_budget:
            failures.append(
                f"takeover at tick {p['tick']} ({kind} at {target}) "
                f"detected in {p['detect_ticks']} ticks — over the "
                f"{args.takeover_budget}-tick budget")
    fenced_lines = []
    stats_path = os.path.join(ha_dir, "stats.jsonl")
    if os.path.isfile(stats_path):
        with open(stats_path) as f:
            fenced_lines = [json.loads(ln) for ln in f if ln.strip()]
    fenced_stats = [s for s in fenced_lines if s.get("fenced")]
    if fence_report and not fenced_stats:
        failures.append("fence round ran but no child reported a fenced "
                        "exit in stats.jsonl")
    # the SLO verdict (ISSUE 11): the completing leader's verdict covers
    # the run's tail; every serving child's rides its own stats line
    slo_verdict = next(
        (s.get("slo") for s in reversed(fenced_lines) if s.get("slo")),
        None)

    # the fleet plane's verdict (ISSUE 19): the aggregator's observed
    # story judged against the lease/journal truth above, and the whole
    # merged state preserved as an artifact (scripts/fleet_report.py
    # pretty-prints it; tests replay assertions against it)
    fleetobs = None
    if agg is not None:
        fleetobs = fleet_verdict(agg, args, observed, fence_report,
                                 promotions, fenced_lines, failures)
        with open(os.path.join(ha_dir, "fleet_snapshot.json"), "w") as f:
            json.dump(agg.snapshot(), f, indent=2)
        agg.close()

    report = {
        "seed": args.seed,
        "kills_scheduled": targets,
        "kills": observed,
        "fence_round": fence_report,
        "ticks": args.ticks,
        "cadence_s": args.cadence,
        "lease_timeout_s": args.lease_timeout,
        "takeover_budget_ticks": args.takeover_budget,
        "promotions": [
            {k: e.get(k) for k in ("tick", "epoch", "detect_s",
                                   "detect_ticks", "re_emitted",
                                   "suppressed")}
            for e in promotions],
        "alert_ids": len(ref_ids),
        "duplicated": len(got_alerts["dup"]),
        "lost": len(lost),
        "extra": len(extra),
        "garbage_lines": got_alerts["garbage"],
        "state_leaves_compared": leaves,
        "completed_by": done.get("name"),
        "unscheduled_fences": unscheduled_fences,
        "fenced_exits": fenced_stats,
        "slo_verdict": slo_verdict,
        "fleetobs": fleetobs,
        "wall_s": round(time.monotonic() - t_all, 1),
        "verified": not failures,
        "failures": failures,
        "workdir": workdir,
    }
    if args.out:
        os.makedirs(os.path.dirname(os.path.abspath(args.out)),
                    exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2)
    print(json.dumps(report))
    if failures:
        for msg in failures:
            log(f"FAIL: {msg}")
        return VERIFY_FAILED_EXIT
    log(f"OK: {len(observed)} kill(s) + "
        f"{'1 fence round' if fence_report else 'no fence round'}, "
        f"{len(promotions)} promotion(s), {report['alert_ids']} alert "
        f"ids exactly-once, {leaves} state leaves bit-identical")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
